"""Benchmark: GPT-2 125M training step on one chip -> tokens/sec + MFU.

BASELINE.md milestone 1 (GPT-2 125M fwd+bwd) measured as a full jitted
train step (fwd + bwd + Adam), bf16 compute. Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline is measured MFU / the BASELINE.json north-star 40% MFU target.

TPU access rides a fragile tunnel (a killed init can wedge it for hours), so
the device is probed in a THROWAWAY SUBPROCESS first: if init + one matmul
don't complete within BENCH_PROBE_TIMEOUT the child is abandoned (never
killed mid-init) and the bench falls back to a CPU smoke run with an explicit
"tpu_unavailable" error field — rc stays 0 and the JSON line always appears.

Env knobs: BENCH_PLATFORM=cpu forces the virtual-CPU path (smoke testing);
BENCH_BSZ / BENCH_SEQ / BENCH_ITERS override shapes; BENCH_SWEEP=0 disables
the batch-size sweep; BENCH_AB=0 skips the flash-vs-XLA A/B leg.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

# chip -> peak bf16 FLOP/s (public TPU specs)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e (Trillium)
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, smoke only
}


def _arm_watchdog(seconds: float) -> None:
    """Belt over the probe's braces: if anything after a successful probe
    still wedges (compile hang), emit one JSON line and exit instead of
    hanging the driver."""
    import threading

    def fire():
        print(json.dumps({
            "metric": "gpt2_125m_train_mfu", "value": 0.0, "unit": "% MFU",
            "vs_baseline": 0.0,
            "error": f"bench watchdog fired after {seconds:.0f}s "
                     "(device init or compile hang)",
        }), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    global _WATCHDOG
    _WATCHDOG = t


_WATCHDOG = None


def probe_tpu() -> dict:
    """Probe TPU init in a subprocess; never block the bench on a wedged
    tunnel. Returns {"alive": bool, "reason": str, ...probe fields}.

    The child is NOT killed on timeout — killing a process inside the
    tunnel's make_c_api_client wedges the remote side for hours; an
    abandoned blocked child costs one idle process instead."""
    probe = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "tpu_probe.py")
    timeouts = [float(os.environ.get("BENCH_PROBE_TIMEOUT", 150)), 45.0]
    for attempt, limit in enumerate(timeouts):
        out_path = os.path.join(
            tempfile.mkdtemp(prefix="tpu_probe_"), "probe.json")
        child = subprocess.Popen(
            [sys.executable, probe, out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        deadline = time.time() + limit
        while time.time() < deadline:
            if child.poll() is not None:
                break
            time.sleep(1.0)
        if child.poll() is not None and os.path.exists(out_path):
            with open(out_path) as f:
                info = json.load(f)
            info["reason"] = f"probe ok (attempt {attempt + 1})"
            return info
        if child.poll() is not None:
            reason = f"probe exited rc={child.returncode} without a result"
        else:
            reason = (f"probe timed out after {limit:.0f}s "
                      "(tunnel wedged in device init); child abandoned")
        print(f"warning: tpu probe attempt {attempt + 1}: {reason}",
              file=sys.stderr)
    return {"alive": False, "reason": reason}


def main():
    _arm_watchdog(float(os.environ.get("BENCH_TIMEOUT", 900)))

    tpu_error = None
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        platform = "cpu"
    else:
        info = probe_tpu()
        # the probe reports alive=true even when JAX silently fell back to
        # its CPU backend — only a tpu platform counts as tunnel-alive
        if info.get("alive") and info.get("platform") == "tpu":
            platform = "tpu"
        elif info.get("alive"):
            platform = "cpu"
            tpu_error = ("tpu_unavailable: probe initialized platform "
                         f"{info.get('platform')!r} (kind "
                         f"{info.get('device_kind')!r}), not a TPU")
        else:
            platform = "cpu"
            tpu_error = f"tpu_unavailable: {info.get('reason', 'unknown')}"

    import jax

    if platform == "cpu":
        # pin AFTER import: the tunnel plugin's sitecustomize rewrites
        # jax_platforms at import time, overriding the env var
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
    from hetu_galvatron_tpu.models.builder import (
        init_causal_lm,
        model_flops_per_token,
        param_count,
    )
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

    dev = jax.devices()[0]
    kind = dev.device_kind
    peak = next((v for k, v in PEAK_FLOPS.items() if kind.startswith(k)), None)
    if dev.platform == "cpu":
        peak = PEAK_FLOPS["cpu"]
    peak_assumed = peak is None
    if peak_assumed:
        print(f"warning: unknown device kind {kind!r}; assuming v5e peak "
              "(197 TFLOP/s) — MFU may be wrong", file=sys.stderr)
        peak = 197e12

    on_tpu = dev.platform != "cpu"
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    iters = int(os.environ.get("BENCH_ITERS", 20 if on_tpu else 5))
    cfg = ModelArgs(model_name="gpt2-small", seq_length=seq,
                    max_position_embeddings=max(seq, 1024))
    flops_tok = model_flops_per_token(cfg, seq)
    tx = make_optimizer(TrainArgs(lr=1e-4, lr_decay_style="constant"))

    def build_step(use_flash: bool, cfg_local=None):
        cfg_local = cfg_local or cfg
        overrides = None
        if use_flash:
            from hetu_galvatron_tpu.ops.pallas.flash_attention import flash_sdpa

            overrides = {i: {"sdpa_fn": flash_sdpa}
                         for i in range(cfg_local.num_hidden_layers)}
        loss_fn = make_loss_fn(cfg_local, compute_dtype=jnp.bfloat16,
                               layer_overrides=overrides)
        return jax.jit(make_train_step(loss_fn, tx), donate_argnums=(0, 1))

    def measure(use_flash: bool, bsz: int, cfg_local=None):
        """Compile + warm + time one (attention impl, bsz) config.
        Returns tokens/sec, or raises (OOM / Mosaic failure)."""
        cfg_local = cfg_local or cfg
        step = build_step(use_flash, cfg_local)
        params, _ = init_causal_lm(jax.random.key(0), cfg_local)
        params = jax.device_put(params, dev)
        opt = jax.jit(tx.init)(params)
        data = np.random.RandomState(0).randint(
            0, cfg_local.padded_vocab_size, (bsz, seq + 1))
        batch = jax.device_put(
            jax.tree.map(jnp.asarray, make_batch(data)), dev)
        for _ in range(3):  # warmup + compile
            params, opt, metrics = step(params, opt, batch)
        float(metrics["loss"])  # host round-trip: full pipeline drained
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt, metrics = step(params, opt, batch)
        # sync on a host transfer of the last step's loss, NOT just
        # block_until_ready: through the axon tunnel block_until_ready has
        # been observed returning before the queued steps actually ran,
        # yielding physically impossible throughputs
        loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
        return bsz * seq * iters / dt, loss

    # plausibility bound for EVERY measurement (primary, fallback retry, and
    # A/B leg): >100% MFU means the tunnel's async dispatch lied about
    # timing, not that the chip is fast. When the peak itself is a guess
    # (unknown device kind) a genuinely faster chip must not be rejected, so
    # the bound is loosened to 10x the guessed peak.
    bound = peak * (10.0 if peak_assumed else 1.0)

    def measure_checked(use_flash: bool, bsz: int, cfg_local=None):
        tps, loss = measure(use_flash, bsz, cfg_local)
        if tps * flops_tok > bound:
            print(f"warning: bsz {bsz} measured {tps:,.0f} tok/s "
                  "(implausible; async-timing glitch); remeasuring",
                  file=sys.stderr)
            tps, loss = measure(use_flash, bsz, cfg_local)
            if tps * flops_tok > bound:
                raise RuntimeError(
                    f"bsz {bsz}: repeated implausible timing "
                    f"({tps:,.0f} tok/s)")
        return tps, loss

    # batch-size candidates: sweep on TPU (HBM allows far more than the old
    # fixed 8 for a 125M model), single size on CPU smoke
    if os.environ.get("BENCH_BSZ"):
        bszs = [int(os.environ["BENCH_BSZ"])]
    elif on_tpu and os.environ.get("BENCH_SWEEP", "1") != "0":
        bszs = [64, 32, 16, 8]
    else:
        bszs = [8]

    want_flash = (on_tpu and cfg.use_flash_attn
                  and os.environ.get("BENCH_FLASH", "1") != "0")
    used_flash = want_flash
    flash_error = None
    best = None  # (tokens_per_sec, bsz, loss, flash_used_for_this_run)
    for bsz in bszs:
        try:
            tps, loss = measure_checked(used_flash, bsz)
        except Exception as e:
            msg = str(e).lower()
            oom = ("resource_exhausted" in msg or "out of memory" in msg
                   or "allocation" in msg and "hbm" in msg)
            if oom:
                print(f"warning: bsz {bsz} OOM; trying smaller",
                      file=sys.stderr)
                continue
            if "implausible timing" in msg:
                print(f"warning: bsz {bsz} skipped: {e}", file=sys.stderr)
                continue
            if used_flash:
                # Mosaic/pallas failure: fall back to the XLA core once,
                # retrying the same bsz
                flash_error = f"{type(e).__name__}: {e}"
                print(f"warning: flash attention failed ({flash_error}); "
                      "falling back to XLA attention", file=sys.stderr)
                used_flash = False
                try:
                    tps, loss = measure_checked(False, bsz)
                except Exception as e2:
                    print(f"warning: bsz {bsz} failed: {e2}", file=sys.stderr)
                    continue
            else:
                print(f"warning: bsz {bsz} failed ({type(e).__name__}); "
                      "trying smaller", file=sys.stderr)
                continue
        mfu = tps * flops_tok / peak * 100.0
        print(f"bench: bsz {bsz} flash={used_flash} "
              f"{tps:,.0f} tok/s ({mfu:.1f}% MFU)", file=sys.stderr)
        if best is None or tps > best[0]:
            best = (tps, bsz, loss, used_flash)
        if best[1] != bsz:
            break  # throughput stopped improving as bsz shrinks

    if best is None:
        print(json.dumps({
            "metric": "gpt2_125m_train_mfu", "value": 0.0, "unit": "% MFU",
            "vs_baseline": 0.0,
            "error": tpu_error or "no batch size ran to completion",
        }), flush=True)
        return 0

    # attribute the result to the impl that produced the WINNING run, not
    # the loop's final state (a mid-sweep flash fallback must not relabel
    # an earlier flash-measured winner)
    tokens_per_sec, bsz, loss, best_flash = best

    # A/B the attention impls at the winning bsz FIRST, both legs with the
    # plain CE, so flash_speedup isolates the attention kernel (the fused-CE
    # leg below may later replace the headline throughput)
    ab = None
    if best_flash and os.environ.get("BENCH_AB", "1") != "0":
        try:
            xla_tps, _ = measure_checked(False, bsz)
            ab = {"xla_tokens_per_sec": round(xla_tps, 1),
                  "flash_speedup": round(tokens_per_sec / xla_tps, 3)}
            print(f"bench A/B: flash {tokens_per_sec:,.0f} vs XLA "
                  f"{xla_tps:,.0f} tok/s ({ab['flash_speedup']}x)",
                  file=sys.stderr)
        except Exception as e:
            print(f"warning: XLA A/B leg failed: {e}", file=sys.stderr)

    # fused Pallas cross-entropy leg at the winning config: adopt it for the
    # headline if it wins (it is a first-class config of the framework)
    fused_ce = False
    ce_ab = None
    if on_tpu and os.environ.get("BENCH_CE", "1") != "0":
        try:
            cfg_ce = cfg.model_copy(update={"use_fused_ce": True})
            ce_tps, ce_loss = measure_checked(best_flash, bsz, cfg_ce)
            ce_ab = {"fused_ce_tokens_per_sec": round(ce_tps, 1),
                     "fused_ce_speedup": round(ce_tps / tokens_per_sec, 3)}
            print(f"bench CE A/B: fused {ce_tps:,.0f} vs plain "
                  f"{tokens_per_sec:,.0f} tok/s "
                  f"({ce_ab['fused_ce_speedup']}x)", file=sys.stderr)
            if ce_tps > tokens_per_sec:
                tokens_per_sec, loss, fused_ce = ce_tps, ce_loss, True
        except Exception as e:
            print(f"warning: fused-CE leg failed: {e}", file=sys.stderr)

    mfu = tokens_per_sec * flops_tok / peak * 100.0

    # count from abstract shapes — no need to re-materialize 125M weights
    params_n = param_count(jax.eval_shape(
        lambda k: init_causal_lm(k, cfg)[0], jax.random.key(0)))
    out = {
        "metric": "gpt2_125m_train_mfu",
        "value": round(mfu, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / 40.0, 4) if on_tpu else 0.0,
        "tokens_per_sec": round(tokens_per_sec, 1),
        "params": params_n,
        "device": kind,
        "peak_flops": peak,
        "peak_assumed": peak_assumed,
        "flash_attention": best_flash,
        "fused_ce": fused_ce,
        "bsz": bsz,
        "seq": seq,
        "loss": round(loss, 4),
    }
    if tpu_error:
        out["error"] = tpu_error
    if flash_error:
        out["flash_error"] = flash_error
    if ab:
        out.update(ab)
    if ce_ab:
        out.update(ce_ab)
    if _WATCHDOG is not None:
        _WATCHDOG.cancel()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
