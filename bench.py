"""Benchmark: GPT-2 125M training step on one chip -> tokens/sec + MFU.

BASELINE.md milestone 1 (GPT-2 125M fwd+bwd) measured as a full jitted
train step (fwd + bwd + Adam), bf16 compute. Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline is measured MFU / the BASELINE.json north-star 40% MFU target.

Round-4 redesign (the driver bench must ALWAYS land a parseable result):
  * The PARENT process never imports jax and never opens the device. It
    orchestrates throwaway children (``bench.py --leg '<json>'``), each of
    which measures ONE (attention impl, bsz, fused-ce) config and appends
    progress + result lines to a journal file as numbers arrive. Partial
    results survive a wedged tunnel.
  * A child that stops making journal progress is ABANDONED, never killed:
    SIGTERM-ing a process inside the tunnel's make_c_api_client wedges the
    remote side for hours (tools/tpu_probe.py docstring; round-3 incident
    log in PERF.md). Abandoned children self-terminate server-side.
  * The CPU fallback leg never touches the TPU plugin: JAX_PLATFORMS=cpu in
    the child env before any jax import, plus
    jax.config.update("jax_platforms", "cpu") immediately after import to
    undo the axon sitecustomize rewrite (same recipe as tests/conftest.py).
  * The parent exits rc=0 with one JSON line in every failure mode; its
    last-resort watchdog runs in a process that holds no device, so firing
    it cannot wedge anything.

Env knobs: BENCH_PLATFORM=cpu forces the CPU path (smoke testing);
BENCH_BSZ / BENCH_SEQ / BENCH_ITERS override shapes; BENCH_SWEEP=0 disables
the batch-size sweep; BENCH_AB=0 skips the flash-vs-XLA A/B leg; BENCH_CE=0
skips the fused-CE leg; BENCH_SERVE_PREFIX=0 / BENCH_SPEC_DECODE=0 skip the
serving A/B legs (prefix-cache TTFT ratio, speculative-decode tokens/sec);
BENCH_HIER_DP=0 / BENCH_SYNTH_COLLECTIVES=0 skip the hierarchical-dp and
synthesized-collective A/B legs;
BENCH_TIMEOUT caps total wall clock (default 900s); BENCH_JOURNAL pins the
journal path (default: a fresh temp file).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

METRIC = "gpt2_125m_train_mfu"
NORTH_STAR_MFU = 40.0  # BASELINE.json

# chip -> peak bf16 FLOP/s (public TPU specs)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e (Trillium)
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, smoke only
}


# ---------------------------------------------------------------------------
# child: one measurement leg
# ---------------------------------------------------------------------------

def _journal_append(path: str, line: dict) -> None:
    line = dict(line, t=round(time.time(), 2))
    with open(path, "a") as f:
        f.write(json.dumps(line) + "\n")
        f.flush()
        os.fsync(f.fileno())


def run_leg(spec: dict, journal: str) -> int:
    """Measure one config and journal the result. Runs in a throwaway
    subprocess; exceptions become an 'error' journal line, never a traceback
    the parent has to parse. Exit code is irrelevant to the parent (it reads
    the journal), but 0 keeps logs clean."""
    leg_id = spec["id"]

    def emit(status, **kw):
        _journal_append(journal, {"id": leg_id, "status": status, **kw})

    try:
        emit("start")
        if spec.get("kind") == "tp_overlap":
            # A/B leg: overlapped ring TP collectives vs GSPMD on the same
            # tp x dp plans (tools/tp_overlap_bench.py). The CPU variant
            # needs the 8-device virtual mesh, not the single-device pin.
            if spec["platform"] == "cpu":
                os.environ["JAX_PLATFORMS"] = "cpu"
                flag = "--xla_force_host_platform_device_count=8"
                if "xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import tp_overlap_bench

            out = tp_overlap_bench.run(on_tpu=spec["platform"] == "tpu")
            if "skipped" in out:
                emit("error", error=out["skipped"])
            else:
                emit("ok", tp_overlap_vs_gspmd=out["overlap_vs_gspmd"],
                     tp_overlap_recompiles=out["overlap_recompiles"],
                     tp_overlap_legs=out["legs"], platform=out["platform"])
            return 0
        if spec.get("kind") == "compiled_overlap":
            # unified-path A/B leg: host vs compiled 1F1B with the
            # shard_map kernels (ring tp matmuls + flash) live on both
            # engines (tools/pipeline_dispatch_bench.py --kernels). Needs
            # the 8-device virtual mesh on CPU, like the tp_overlap leg.
            if spec["platform"] == "cpu":
                os.environ["JAX_PLATFORMS"] = "cpu"
                flag = "--xla_force_host_platform_device_count=8"
                if "xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import pipeline_dispatch_bench

            out = pipeline_dispatch_bench.run_kernels(
                on_tpu=spec["platform"] == "tpu")
            if "skipped" in out:
                emit("error", error=out["skipped"])
            else:
                emit("ok",
                     compiled_overlap_vs_host=out["compiled_overlap_vs_host"],
                     compiled_overlap_recompiles=out["compiled_recompiles"],
                     platform=out["platform"])
            return 0
        if spec.get("kind") == "hier_dp":
            # hierarchical-vs-flat dp gradient reduction A/B
            # (tools/hier_dp_bench.py): lane-accumulated rs/ar/ag once per
            # step vs GSPMD's in-scan flat all-reduce, same plans. Needs
            # the 8-device virtual mesh on CPU, like the tp_overlap leg.
            if spec["platform"] == "cpu":
                os.environ["JAX_PLATFORMS"] = "cpu"
                flag = "--xla_force_host_platform_device_count=8"
                if "xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import hier_dp_bench

            out = hier_dp_bench.run(on_tpu=spec["platform"] == "tpu")
            if "skipped" in out:
                emit("error", error=out["skipped"])
            else:
                emit("ok", hier_dp_vs_flat=out["hier_dp_vs_flat"],
                     hier_dp_recompiles=out["hier_dp_recompiles"],
                     hier_dp_bucketed_vs_mono=out.get(
                         "hier_dp_bucketed_vs_mono"),
                     hier_dp_legs=out["legs"], platform=out["platform"])
            return 0
        if spec.get("kind") == "synth_collectives":
            # synthesized-vs-hand-built collective A/B
            # (tools/synth_collectives_bench.py): the emitted ring /
            # halving-doubling schedule programs vs the canonical
            # reference bodies, bit-parity asserted before timing. Needs
            # the 8-device virtual mesh on CPU, like the tp_overlap leg.
            if spec["platform"] == "cpu":
                os.environ["JAX_PLATFORMS"] = "cpu"
                flag = "--xla_force_host_platform_device_count=8"
                if "xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import synth_collectives_bench

            out = synth_collectives_bench.run(
                on_tpu=spec["platform"] == "tpu")
            if "skipped" in out:
                emit("error", error=out["skipped"])
            else:
                emit("ok",
                     synth_collectives_vs_handbuilt=out[
                         "synth_collectives_vs_handbuilt"],
                     synth_collectives_recompiles=out[
                         "synth_collectives_recompiles"],
                     synth_collectives_legs=out["legs"],
                     platform=out["platform"])
            return 0
        if spec.get("kind") in ("serve_prefix", "spec_decode"):
            # serving A/B legs (tools/serve_bench.py): single-device tiny
            # engines — radix prefix cache hit-vs-cold TTFT ratio, and
            # speculative-decode vs plain tokens/sec
            if spec["platform"] == "cpu":
                os.environ["JAX_PLATFORMS"] = "cpu"
                os.environ.setdefault(
                    "XLA_FLAGS", "--xla_force_host_platform_device_count=1")
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import serve_bench

            on_tpu = spec["platform"] == "tpu"
            if spec["kind"] == "serve_prefix":
                out = serve_bench.run_prefix(on_tpu=on_tpu)
            else:
                out = serve_bench.run_spec(on_tpu=on_tpu)
            if "skipped" in out:
                emit("error", error=out["skipped"])
            else:
                emit("ok", **out)
            return 0
        if spec["platform"] == "cpu":
            # tunnel-safe: pin the platform BEFORE jax loads any backend...
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ.setdefault(
                "XLA_FLAGS", "--xla_force_host_platform_device_count=1")

        import jax

        if spec["platform"] == "cpu":
            # ...and again AFTER import: the axon sitecustomize rewrites
            # jax_platforms to "axon,cpu" at import time (tests/conftest.py)
            jax.config.update("jax_platforms", "cpu")

        import jax.numpy as jnp
        import numpy as np

        from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
        from hetu_galvatron_tpu.models.builder import (
            init_causal_lm,
            model_flops_per_token,
            param_count,
        )
        from hetu_galvatron_tpu.runtime.dataloader import make_batch
        from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
        from hetu_galvatron_tpu.runtime.trainer import (
            make_loss_fn,
            make_train_step,
        )

        dev = jax.devices()[0]
        kind = dev.device_kind
        emit("device", platform=dev.platform, device_kind=kind)

        peak = next(
            (v for k, v in PEAK_FLOPS.items() if kind.startswith(k)), None)
        if dev.platform == "cpu":
            peak = PEAK_FLOPS["cpu"]
        peak_assumed = peak is None
        if peak_assumed:
            peak = 197e12

        if os.environ.get("BENCH_FAKE_WEDGE"):  # test hook: simulate a hang
            time.sleep(float(os.environ.get("BENCH_FAKE_WEDGE_SECS", 120)))
            return 0

        seq, bsz, iters = spec["seq"], spec["bsz"], spec["iters"]
        cfg = ModelArgs(model_name="gpt2-small", seq_length=seq,
                        max_position_embeddings=max(seq, 1024))
        if os.environ.get("BENCH_TINY"):  # smoke-test shapes
            cfg = cfg.model_copy(update={
                "hidden_size": 128, "num_hidden_layers": 2,
                "num_attention_heads": 4, "vocab_size": 1024})
        if spec["fused_ce"]:
            cfg = cfg.model_copy(update={"use_fused_ce": True})
        flops_tok = model_flops_per_token(cfg, seq)
        tx = make_optimizer(TrainArgs(lr=1e-4, lr_decay_style="constant"))

        overrides = None
        if spec["flash"]:
            from hetu_galvatron_tpu.ops.pallas.flash_attention import (
                flash_sdpa,
            )

            overrides = {i: {"sdpa_fn": flash_sdpa}
                         for i in range(cfg.num_hidden_layers)}
        loss_fn = make_loss_fn(cfg, compute_dtype=jnp.bfloat16,
                               layer_overrides=overrides)
        step = jax.jit(make_train_step(loss_fn, tx), donate_argnums=(0, 1))

        params, _ = init_causal_lm(jax.random.key(0), cfg)
        params = jax.device_put(params, dev)
        opt = jax.jit(tx.init)(params)
        data = np.random.RandomState(0).randint(
            0, cfg.padded_vocab_size, (bsz, seq + 1))
        batch = jax.device_put(
            jax.tree.map(jnp.asarray, make_batch(data)), dev)

        def timed_run():
            nonlocal params, opt
            t0 = time.perf_counter()
            metrics = None
            for _ in range(iters):
                params, opt, metrics = step(params, opt, batch)
            # sync on a host transfer of the last step's loss, NOT just
            # block_until_ready: through the axon tunnel block_until_ready
            # has been observed returning before the queued steps actually
            # ran, yielding physically impossible throughputs
            loss = float(metrics["loss"])
            dt = time.perf_counter() - t0
            return bsz * seq * iters / dt, loss

        params, opt, metrics = step(params, opt, batch)  # compile
        float(metrics["loss"])
        emit("compiled")
        for _ in range(2):  # warmup
            params, opt, metrics = step(params, opt, batch)
        float(metrics["loss"])
        emit("warm")

        # plausibility bound: >100% MFU means the tunnel's async dispatch
        # lied about timing, not that the chip is fast. When the peak itself
        # is a guess, a genuinely faster chip must not be rejected (10x).
        bound = peak * (10.0 if peak_assumed else 1.0)
        tps, loss = timed_run()
        if tps * flops_tok > bound:
            emit("remeasure", tokens_per_sec=round(tps, 1))
            tps, loss = timed_run()
            if tps * flops_tok > bound:
                emit("error", error=(f"repeated implausible timing "
                                     f"({tps:,.0f} tok/s)"),
                     implausible=True)
                return 0

        params_n = param_count(jax.eval_shape(
            lambda k: init_causal_lm(k, cfg)[0], jax.random.key(0)))
        emit("ok",
             tokens_per_sec=round(tps, 1),
             loss=round(loss, 4),
             mfu=round(tps * flops_tok / peak * 100.0, 2),
             flops_per_token=flops_tok,
             peak_flops=peak,
             peak_assumed=peak_assumed,
             params=params_n,
             platform=dev.platform,
             device_kind=kind)
        return 0
    except Exception as e:  # noqa: BLE001 — journal every failure
        msg = f"{type(e).__name__}: {e}"
        low = msg.lower()
        oom = ("resource_exhausted" in low or "out of memory" in low
               or ("allocation" in low and "hbm" in low))
        try:
            emit("error", error=msg[:2000], oom=oom)
        except OSError:
            pass
        return 0


# ---------------------------------------------------------------------------
# parent: orchestration (NEVER imports jax)
# ---------------------------------------------------------------------------

class Orchestrator:
    """Runs legs as children, reads their journal lines, abandons (never
    kills) children that stop making progress."""

    def __init__(self, journal: str, deadline: float,
                 progress_timeout: float = 180.0):
        self.journal = journal
        self.deadline = deadline
        self.progress_timeout = float(
            os.environ.get("BENCH_PROGRESS_TIMEOUT", progress_timeout))
        self._next_id = 0
        self._offset = 0
        self._lines: list[dict] = []
        self.wedged = False
        self.abandoned: list[int] = []

    def _poll_journal(self) -> None:
        try:
            with open(self.journal) as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except FileNotFoundError:
            return
        for raw in chunk.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                self._lines.append(json.loads(raw))
            except json.JSONDecodeError:
                pass  # torn write from an abandoned child; ignore

    def lines_for(self, leg_id: int) -> list[dict]:
        return [ln for ln in self._lines if ln.get("id") == leg_id]

    def run(self, spec: dict, leg_budget: float,
            hard_deadline: float | None = None) -> dict:
        """Run one leg to completion / error / abandonment. Returns the
        final journal line for the leg, or a synthesized one on wedge.
        ``hard_deadline`` overrides the orchestrator deadline (the CPU
        fallback leg runs in the time reserved past it)."""
        spec = dict(spec, id=self._next_id)
        self._next_id += 1
        argv = [sys.executable, os.path.abspath(__file__),
                "--leg", json.dumps(spec), self.journal]
        log = os.path.splitext(self.journal)[0] + f".leg{spec['id']}.log"
        with open(log, "w") as lf:
            child = subprocess.Popen(argv, stdout=lf, stderr=lf,
                                     cwd=os.path.dirname(
                                         os.path.abspath(__file__)))
        leg_deadline = min(time.time() + leg_budget,
                           hard_deadline or self.deadline)
        last_progress = time.time()
        n_seen = 0
        while True:
            self._poll_journal()
            mine = self.lines_for(spec["id"])
            if len(mine) > n_seen:
                n_seen = len(mine)
                last_progress = time.time()
            if mine and mine[-1]["status"] in ("ok", "error"):
                return mine[-1]
            if child.poll() is not None:
                # exited without a terminal line: re-read once then give up
                self._poll_journal()
                mine = self.lines_for(spec["id"])
                if mine and mine[-1]["status"] in ("ok", "error"):
                    return mine[-1]
                return {"id": spec["id"], "status": "error",
                        "error": f"leg exited rc={child.returncode} "
                                 "without a result"}
            now = time.time()
            if (now - last_progress > self.progress_timeout
                    or now > leg_deadline):
                stage = mine[-1]["status"] if mine else "spawn"
                self.abandoned.append(child.pid)
                if spec["platform"] == "tpu":
                    # ABANDON: never SIGTERM a process that may hold the
                    # device — it wedges the remote side of the tunnel
                    self.wedged = True
                    fate = f"pid {child.pid} left running"
                elif os.environ.get("BENCH_NEVER_KILL", "0") != "0":
                    fate = f"pid {child.pid} left running"
                else:
                    # a CPU child cannot hold the tunnel: safe to reap, with
                    # SIGKILL escalation so a SIGTERM-ignoring child cannot
                    # outlive the message claiming it was terminated
                    child.terminate()
                    try:
                        child.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        child.kill()
                        child.wait()
                    fate = f"pid {child.pid} terminated"
                print(f"warning: leg {spec['id']} ({spec['platform']} "
                      f"flash={spec['flash']} bsz={spec['bsz']}) abandoned "
                      f"after no progress past stage {stage!r} "
                      f"({fate})", file=sys.stderr)
                return {"id": spec["id"], "status": "wedged", "stage": stage}
            time.sleep(1.0)


def probe_tpu() -> dict:
    """Probe TPU init in a throwaway subprocess; never block the bench on a
    wedged tunnel. The child is NOT killed on timeout — abandoned."""
    probe = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "tpu_probe.py")
    timeouts = [float(os.environ.get("BENCH_PROBE_TIMEOUT", 150)), 45.0]
    reason = "probe not run"
    for attempt, limit in enumerate(timeouts):
        out_path = os.path.join(
            tempfile.mkdtemp(prefix="tpu_probe_"), "probe.json")
        child = subprocess.Popen(
            [sys.executable, probe, out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        deadline = time.time() + limit
        while time.time() < deadline:
            if child.poll() is not None:
                break
            time.sleep(1.0)
        if child.poll() is not None and os.path.exists(out_path):
            with open(out_path) as f:
                info = json.load(f)
            info["reason"] = f"probe ok (attempt {attempt + 1})"
            return info
        if child.poll() is not None:
            reason = f"probe exited rc={child.returncode} without a result"
        else:
            reason = (f"probe timed out after {limit:.0f}s "
                      "(tunnel wedged in device init); child abandoned")
        print(f"warning: tpu probe attempt {attempt + 1}: {reason}",
              file=sys.stderr)
    return {"alive": False, "reason": reason}


def _zero_result(error: str) -> dict:
    return {"metric": METRIC, "value": 0.0, "unit": "% MFU",
            "vs_baseline": 0.0, "error": error}


_WATCHDOG = None
_RESULT_EMITTED = False


def _emit_result(out: dict) -> None:
    global _RESULT_EMITTED
    if _RESULT_EMITTED:
        return
    _RESULT_EMITTED = True
    if _WATCHDOG is not None:
        _WATCHDOG.cancel()
    print(json.dumps(out), flush=True)


def _arm_watchdog(seconds: float, state: dict) -> None:
    """Last resort: the parent holds no device, so exiting here is safe.
    Emits best-so-far (or zero) and exits rc=0 — the result always lands."""
    import threading

    def fire():
        out = state.get("best_out") or _zero_result(
            f"bench watchdog fired after {seconds:.0f}s; "
            f"last stage: {state.get('stage', 'unknown')}")
        out.setdefault("watchdog_fired", True)
        _emit_result(out)
        os._exit(0)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    global _WATCHDOG
    _WATCHDOG = t


def main() -> int:
    total = float(os.environ.get("BENCH_TIMEOUT", 900))
    t_start = time.time()
    state = {"stage": "probe"}
    _arm_watchdog(total - 5.0, state)

    journal = os.environ.get("BENCH_JOURNAL") or os.path.join(
        tempfile.mkdtemp(prefix="bench_"), "journal.jsonl")
    os.makedirs(os.path.dirname(os.path.abspath(journal)), exist_ok=True)
    print(f"bench: journal at {journal}", file=sys.stderr)

    tpu_error = None
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        platform = "cpu"
    else:
        info = probe_tpu()
        # the probe reports alive=true even when JAX silently fell back to
        # its CPU backend — only a tpu platform counts as tunnel-alive
        if info.get("alive") and info.get("platform") == "tpu":
            platform = "tpu"
        elif info.get("alive"):
            platform = "cpu"
            tpu_error = ("tpu_unavailable: probe initialized platform "
                         f"{info.get('platform')!r} (kind "
                         f"{info.get('device_kind')!r}), not a TPU")
        else:
            platform = "cpu"
            tpu_error = f"tpu_unavailable: {info.get('reason', 'unknown')}"

    on_tpu = platform == "tpu"
    # Reserve tail time for the tunnel-safe CPU fallback leg (~5 min on this
    # host) — only meaningful when TPU legs might wedge. The deadline must
    # stay in the future even for small BENCH_TIMEOUT values: otherwise every
    # leg is insta-abandoned at stage 'spawn' (round-4 advisor finding).
    fallback_reserve = 340.0 if on_tpu else 0.0
    deadline = t_start + max(total - fallback_reserve, total * 0.5)
    orch = Orchestrator(journal, deadline=deadline)

    seq = int(os.environ.get("BENCH_SEQ", 1024 if on_tpu else 512))
    iters = int(os.environ.get("BENCH_ITERS", 20 if on_tpu else 2))
    base = {"platform": platform, "seq": seq, "iters": iters,
            "flash": False, "fused_ce": False}

    if os.environ.get("BENCH_BSZ"):
        bszs = [int(os.environ["BENCH_BSZ"])]
    elif on_tpu and os.environ.get("BENCH_SWEEP", "1") != "0":
        bszs = [64, 32, 16, 8]
    else:
        bszs = [2]

    want_flash = (on_tpu
                  and os.environ.get("BENCH_FLASH", "1") != "0")
    leg_budget = 300.0 if on_tpu else 600.0

    state["stage"] = "sweep"
    flash_error = None
    best = None  # journal 'ok' line of the winning run, + bsz/flash tags
    used_flash = want_flash
    for bsz in bszs:
        if orch.wedged:
            break
        res = orch.run(dict(base, flash=used_flash, bsz=bsz), leg_budget)
        if res["status"] == "error":
            if res.get("oom"):
                print(f"warning: bsz {bsz} OOM; trying smaller",
                      file=sys.stderr)
                continue
            if res.get("implausible"):
                print(f"warning: bsz {bsz} skipped: {res['error']}",
                      file=sys.stderr)
                continue
            if used_flash:
                # Mosaic/pallas failure: fall back to the XLA core once,
                # retrying the same bsz
                flash_error = res["error"]
                print(f"warning: flash attention failed ({flash_error}); "
                      "falling back to XLA attention", file=sys.stderr)
                used_flash = False
                res = orch.run(dict(base, flash=False, bsz=bsz), leg_budget)
                if res["status"] != "ok":
                    continue
            else:
                print(f"warning: bsz {bsz} failed: {res.get('error')}",
                      file=sys.stderr)
                continue
        if res["status"] != "ok":
            break  # wedged
        res = dict(res, bsz=bsz, flash=used_flash, seq=seq)
        print(f"bench: bsz {bsz} flash={used_flash} "
              f"{res['tokens_per_sec']:,.0f} tok/s ({res['mfu']:.1f}% MFU)",
              file=sys.stderr)
        if best is None or res["tokens_per_sec"] > best["tokens_per_sec"]:
            best = res
            state["best_out"] = _assemble(best, tpu_error, flash_error,
                                          on_tpu, partial=True)
            # a result landed: the CPU fallback is moot, spend its reserve
            orch.deadline = t_start + total - 30.0
        if best["bsz"] != bsz:
            break  # throughput stopped improving as bsz shrinks

    if orch.wedged:
        tpu_error = tpu_error or (
            "tpu_wedged: a measurement leg stopped making progress and was "
            "abandoned (tunnel wedge); partial results only")

    if best is None and on_tpu:
        # nothing landed on TPU: tunnel-safe CPU smoke so value > 0
        state["stage"] = "cpu-fallback"
        tpu_error = tpu_error or "tpu_unavailable: no TPU leg completed"
        res = orch.run({"platform": "cpu", "seq": 256, "iters": 2,
                        "flash": False, "fused_ce": False, "bsz": 2}, 600.0,
                       hard_deadline=t_start + total - 30.0)
        if res["status"] == "ok":
            best = dict(res, bsz=2, flash=False, seq=256)
            on_tpu = False

    if best is None:
        _emit_result(_zero_result(
            tpu_error or "no batch size ran to completion"))
        return 0

    # A/B the attention impls at the winning bsz, both legs with the plain
    # CE, so flash_speedup isolates the attention kernel
    ab = None
    if (best["flash"] and not orch.wedged
            and os.environ.get("BENCH_AB", "1") != "0"):
        state["stage"] = "ab"
        res = orch.run(dict(base, flash=False, bsz=best["bsz"]), leg_budget)
        if res["status"] == "ok":
            ab = {"xla_tokens_per_sec": res["tokens_per_sec"],
                  "flash_speedup": round(
                      best["tokens_per_sec"] / res["tokens_per_sec"], 3)}
            print(f"bench A/B: flash {best['tokens_per_sec']:,.0f} vs XLA "
                  f"{res['tokens_per_sec']:,.0f} tok/s "
                  f"({ab['flash_speedup']}x)", file=sys.stderr)
        else:
            print(f"warning: XLA A/B leg failed: {res.get('error')}",
                  file=sys.stderr)

    # fused Pallas cross-entropy leg at the winning config: adopt it for the
    # headline if it wins (it is a first-class config of the framework)
    ce_ab = None
    fused_ce = False
    if (on_tpu and not orch.wedged
            and os.environ.get("BENCH_CE", "1") != "0"):
        state["stage"] = "fused-ce"
        res = orch.run(dict(base, flash=best["flash"], bsz=best["bsz"],
                            fused_ce=True), leg_budget)
        if res["status"] == "ok":
            ce_ab = {"fused_ce_tokens_per_sec": res["tokens_per_sec"],
                     "fused_ce_speedup": round(
                         res["tokens_per_sec"] / best["tokens_per_sec"], 3)}
            print(f"bench CE A/B: fused {res['tokens_per_sec']:,.0f} vs "
                  f"plain {best['tokens_per_sec']:,.0f} tok/s "
                  f"({ce_ab['fused_ce_speedup']}x)", file=sys.stderr)
            if res["tokens_per_sec"] > best["tokens_per_sec"]:
                best = dict(res, bsz=best["bsz"], flash=best["flash"],
                            seq=seq)
                fused_ce = True
        else:
            print(f"warning: fused-CE leg failed: {res.get('error')}",
                  file=sys.stderr)

    # overlapped-TP A/B (tools/tp_overlap_bench.py): on-chip by default
    # (where the ring hops can actually hide under compute); opt-in on CPU
    # via BENCH_TP_OVERLAP=1 (the virtual-mesh ratio only bounds overhead)
    tp_ab = None
    if (not orch.wedged and os.environ.get(
            "BENCH_TP_OVERLAP", "1" if on_tpu else "0") != "0"):
        state["stage"] = "tp-overlap"
        res = orch.run({"kind": "tp_overlap", "platform": platform,
                        "seq": seq, "bsz": best["bsz"], "iters": iters,
                        "flash": False, "fused_ce": False}, leg_budget)
        if res["status"] == "ok":
            tp_ab = {"tp_overlap_vs_gspmd": res["tp_overlap_vs_gspmd"],
                     "tp_overlap_recompiles": res["tp_overlap_recompiles"]}
            print(f"bench TP-overlap A/B: overlap_vs_gspmd "
                  f"{res['tp_overlap_vs_gspmd']} (recompiles "
                  f"{res['tp_overlap_recompiles']})", file=sys.stderr)
        else:
            print(f"warning: tp-overlap A/B leg failed: {res.get('error')}",
                  file=sys.stderr)

    # unified-path A/B (pipeline_dispatch_bench --kernels): compiled 1F1B
    # with the shard_map kernels inside vs the host engine with the same
    # kernels. On by default on BOTH platforms (unlike tp_overlap, the
    # CPU-mesh ratio here is meaningful — it is the committed
    # bench_baseline.json compiled_overlap leg); BENCH_COMPILED_OVERLAP=0
    # opts out. The leg runs the bench tool's own pinned reference
    # workload (tp2 x dp2 x pp2 at its documented shapes/iters); the
    # seq/bsz/flash fields below only label the journal + abandon log,
    # same as the tp_overlap leg's spec.
    co_ab = None
    if (not orch.wedged
            and os.environ.get("BENCH_COMPILED_OVERLAP", "1") != "0"):
        state["stage"] = "compiled-overlap"
        res = orch.run({"kind": "compiled_overlap", "platform": platform,
                        "seq": seq, "bsz": best["bsz"], "iters": iters,
                        "flash": False, "fused_ce": False}, leg_budget)
        if res["status"] == "ok":
            co_ab = {"compiled_overlap_vs_host":
                     res["compiled_overlap_vs_host"],
                     "compiled_overlap_recompiles":
                     res["compiled_overlap_recompiles"]}
            print(f"bench compiled-overlap A/B: compiled_overlap_vs_host "
                  f"{res['compiled_overlap_vs_host']} (recompiles "
                  f"{res['compiled_overlap_recompiles']})", file=sys.stderr)
        else:
            print(f"warning: compiled-overlap A/B leg failed: "
                  f"{res.get('error')}", file=sys.stderr)

    # hierarchical dp reduction A/B (tools/hier_dp_bench.py): on by default
    # on both platforms — the CPU ratio (once-per-step vs per-microbatch
    # reduction schedule) is the committed bench_baseline.json entry.
    # BENCH_HIER_DP=0 opts out.
    hier_ab = None
    if (not orch.wedged
            and os.environ.get("BENCH_HIER_DP", "1") != "0"):
        state["stage"] = "hier-dp"
        res = orch.run({"kind": "hier_dp", "platform": platform,
                        "seq": seq, "bsz": best["bsz"], "iters": iters,
                        "flash": False, "fused_ce": False}, leg_budget)
        if res["status"] == "ok":
            hier_ab = {"hier_dp_vs_flat": res["hier_dp_vs_flat"],
                       "hier_dp_recompiles": res["hier_dp_recompiles"]}
            if isinstance(res.get("hier_dp_bucketed_vs_mono"),
                          (int, float)):
                # bucketed software-pipelined schedule vs the monolithic
                # hier program (tools/hier_dp_bench.py bucketed leg)
                hier_ab["hier_dp_bucketed_vs_mono"] = \
                    res["hier_dp_bucketed_vs_mono"]
            print(f"bench hier-dp A/B: hier_dp_vs_flat "
                  f"{res['hier_dp_vs_flat']} (recompiles "
                  f"{res['hier_dp_recompiles']}; bucketed-vs-mono "
                  f"{res.get('hier_dp_bucketed_vs_mono')})",
                  file=sys.stderr)
        else:
            print(f"warning: hier-dp A/B leg failed: {res.get('error')}",
                  file=sys.stderr)

    # synthesized-vs-hand-built collective A/B
    # (tools/synth_collectives_bench.py): on by default on both platforms
    # — the CPU ratio (emitted schedule program overhead over the
    # reference body, bit-parity asserted) is the committed
    # bench_baseline.json entry. BENCH_SYNTH_COLLECTIVES=0 opts out.
    synth_ab = None
    if (not orch.wedged
            and os.environ.get("BENCH_SYNTH_COLLECTIVES", "1") != "0"):
        state["stage"] = "synth-collectives"
        res = orch.run({"kind": "synth_collectives", "platform": platform,
                        "seq": seq, "bsz": best["bsz"], "iters": iters,
                        "flash": False, "fused_ce": False}, leg_budget)
        if res["status"] == "ok":
            synth_ab = {"synth_collectives_vs_handbuilt":
                        res["synth_collectives_vs_handbuilt"],
                        "synth_collectives_recompiles":
                        res["synth_collectives_recompiles"]}
            print(f"bench synth-collectives A/B: "
                  f"synth_collectives_vs_handbuilt "
                  f"{res['synth_collectives_vs_handbuilt']} (recompiles "
                  f"{res['synth_collectives_recompiles']})",
                  file=sys.stderr)
        else:
            print(f"warning: synth-collectives A/B leg failed: "
                  f"{res.get('error')}", file=sys.stderr)

    # serving A/B legs (tools/serve_bench.py run_prefix / run_spec): on by
    # default on both platforms — the CPU ratios are real (TTFT measures
    # actual prefill compute skipped; tokens/sec the actual verify cost)
    # and are the committed bench_baseline.json entries.
    # BENCH_SERVE_PREFIX=0 / BENCH_SPEC_DECODE=0 opt out.
    serve_ab = {}
    for kind, env, keys in (
            ("serve_prefix", "BENCH_SERVE_PREFIX",
             ("serve_prefix_ttft_ratio", "ttft_cold_ms", "ttft_hit_ms",
              "prefix_hit_rate", "serve_prefix_recompiles")),
            ("spec_decode", "BENCH_SPEC_DECODE",
             ("spec_decode_tokens_ratio", "spec_accept_rate",
              "spec_decode_recompiles"))):
        if orch.wedged or os.environ.get(env, "1") == "0":
            continue
        state["stage"] = kind.replace("_", "-")
        res = orch.run({"kind": kind, "platform": platform, "seq": seq,
                        "bsz": best["bsz"], "iters": iters, "flash": False,
                        "fused_ce": False}, leg_budget)
        if res["status"] == "ok":
            for k in keys:
                if k in res:
                    serve_ab[k] = res[k]
            print(f"bench {kind} A/B: " + " ".join(
                f"{k}={res[k]}" for k in keys[:1] if k in res),
                file=sys.stderr)
        else:
            print(f"warning: {kind} A/B leg failed: {res.get('error')}",
                  file=sys.stderr)

    out = _assemble(best, tpu_error, flash_error, on_tpu)
    out["fused_ce"] = fused_ce
    if ab:
        out.update(ab)
    if ce_ab:
        out.update(ce_ab)
    if tp_ab:
        out.update(tp_ab)
    if co_ab:
        out.update(co_ab)
    if hier_ab:
        out.update(hier_ab)
    if synth_ab:
        out.update(synth_ab)
    if serve_ab:
        out.update(serve_ab)
    if orch.abandoned:
        out["abandoned_children"] = orch.abandoned
    _emit_result(out)
    return 0


def _assemble(best: dict, tpu_error, flash_error, on_tpu: bool,
              partial: bool = False) -> dict:
    mfu = best["mfu"]
    out = {
        "metric": METRIC,
        "value": mfu,
        "unit": "% MFU",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4) if on_tpu else 0.0,
        "tokens_per_sec": best["tokens_per_sec"],
        "params": best["params"],
        "device": best["device_kind"],
        "peak_flops": best["peak_flops"],
        "peak_assumed": best["peak_assumed"],
        "flash_attention": best["flash"],
        "bsz": best["bsz"],
        "seq": best["seq"],
        "loss": best["loss"],
    }
    if tpu_error:
        out["error"] = tpu_error
    if flash_error:
        out["flash_error"] = flash_error
    if partial:
        out["partial"] = True
    return out


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--leg":
        sys.exit(run_leg(json.loads(sys.argv[2]), sys.argv[3]))
    sys.exit(main())
