"""Benchmark: GPT-2 125M training step on one chip -> tokens/sec + MFU.

BASELINE.md milestone 1 (GPT-2 125M fwd+bwd) measured as a full jitted
train step (fwd + bwd + Adam), bf16 compute. Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline is measured MFU / the BASELINE.json north-star 40% MFU target.

Env knobs: BENCH_PLATFORM=cpu forces the virtual-CPU path (smoke testing);
BENCH_BSZ / BENCH_SEQ / BENCH_ITERS override shapes.
"""

import json
import os
import sys
import time

import numpy as np

# chip -> peak bf16 FLOP/s (public TPU specs)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e (Trillium)
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, smoke only
}


def _arm_watchdog(seconds: float) -> None:
    """If TPU init or compile wedges (the axon tunnel can hang indefinitely
    in make_c_api_client), still emit one JSON line and exit instead of
    hanging the driver."""
    import threading

    def fire():
        print(json.dumps({
            "metric": "gpt2_125m_train_mfu", "value": 0.0, "unit": "% MFU",
            "vs_baseline": 0.0,
            "error": f"bench watchdog fired after {seconds:.0f}s "
                     "(device init or compile hang)",
        }), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    global _WATCHDOG
    _WATCHDOG = t


_WATCHDOG = None


def main():
    _arm_watchdog(float(os.environ.get("BENCH_TIMEOUT", 900)))
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
    from hetu_galvatron_tpu.models.builder import (
        init_causal_lm,
        model_flops_per_token,
        param_count,
    )
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

    dev = jax.devices()[0]
    kind = dev.device_kind
    peak = next((v for k, v in PEAK_FLOPS.items() if kind.startswith(k)), None)
    if dev.platform == "cpu":
        peak = PEAK_FLOPS["cpu"]
    peak_assumed = peak is None
    if peak_assumed:
        print(f"warning: unknown device kind {kind!r}; assuming v5e peak "
              "(197 TFLOP/s) — MFU may be wrong", file=sys.stderr)
        peak = 197e12

    seq = int(os.environ.get("BENCH_SEQ", 1024))
    bsz = int(os.environ.get("BENCH_BSZ", 8))
    iters = int(os.environ.get("BENCH_ITERS", 10))
    cfg = ModelArgs(model_name="gpt2-small", seq_length=seq,
                    max_position_embeddings=max(seq, 1024))

    params, _ = init_causal_lm(jax.random.key(0), cfg)
    tx = make_optimizer(TrainArgs(lr=1e-4, lr_decay_style="constant"))

    def build_step(use_flash: bool):
        overrides = None
        if use_flash:
            from hetu_galvatron_tpu.ops.pallas.flash_attention import flash_sdpa

            overrides = {i: {"sdpa_fn": flash_sdpa}
                         for i in range(cfg.num_hidden_layers)}
        loss_fn = make_loss_fn(cfg, compute_dtype=jnp.bfloat16,
                               layer_overrides=overrides)
        return jax.jit(make_train_step(loss_fn, tx), donate_argnums=(0, 1))

    want_flash = (dev.platform != "cpu" and cfg.use_flash_attn
                  and os.environ.get("BENCH_FLASH", "1") != "0")
    step = build_step(want_flash)

    params = jax.device_put(params, dev)
    opt = jax.jit(tx.init)(params)
    data = np.random.RandomState(0).randint(0, cfg.padded_vocab_size,
                                            (bsz, seq + 1))
    batch = jax.device_put(jax.tree.map(jnp.asarray, make_batch(data)), dev)

    used_flash = want_flash
    try:
        for _ in range(3):  # warmup + compile
            params, opt, metrics = step(params, opt, batch)
        jax.block_until_ready(metrics["loss"])
    except Exception as e:  # Mosaic/pallas failure: fall back to XLA core
        if not want_flash:
            raise
        print(f"warning: flash attention failed ({type(e).__name__}: {e}); "
              "falling back to XLA attention", file=sys.stderr)
        used_flash = False
        step = build_step(False)
        # the failed step may have executed with donated buffers: rebuild
        params, _ = init_causal_lm(jax.random.key(0), cfg)
        params = jax.device_put(params, dev)
        opt = jax.jit(tx.init)(params)
        for _ in range(3):
            params, opt, metrics = step(params, opt, batch)
        jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt, metrics = step(params, opt, batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = bsz * seq * iters / dt
    flops_tok = model_flops_per_token(cfg, seq)
    mfu = tokens_per_sec * flops_tok / peak * 100.0
    out = {
        "metric": "gpt2_125m_train_mfu",
        "value": round(mfu, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / 40.0, 4),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "step_ms": round(dt / iters * 1000, 2),
        "params": param_count(params),
        "device": kind,
        "peak_flops": peak,
        "peak_assumed": peak_assumed,
        "flash_attention": used_flash,
        "bsz": bsz,
        "seq": seq,
        "loss": round(float(metrics["loss"]), 4),
    }
    if _WATCHDOG is not None:
        _WATCHDOG.cancel()
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
