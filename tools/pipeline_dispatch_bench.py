"""Host-dispatch overhead microbench for the pipeline engines (A/B).

The host PipelineEngine sequences its schedule from the host: every
microbatch costs one jitted-call dispatch per stage (fwd) plus one per stage
(bwd), relying on JAX async dispatch to overlap device work (VERDICT r4 weak
#5: whether that approximates 1F1B on hardware needs at least a
dispatch-cost bound). The compiled engine (runtime/compiled_pipeline.py)
fuses the whole 1F1B step into ONE program. This tool measures both sides:

* ``dispatch_us`` — wall time of ONE already-compiled stage-jit call with
  near-zero compute (tiny shapes), i.e. the pure Python/jit-call overhead
  the host pays per (stage, microbatch) leg. The schedule stays ahead of
  the devices iff per-microbatch device compute >> dispatch_us * stages.
  This is also the number ``search.dispatch_us`` feeds to the cost model.
* ``step_overhead_ratio`` — full host ``PipelineEngine.train_step`` wall
  time over the serial sum of its stage compute (same jits timed
  standalone), on the virtual CPU mesh. On CPU every "device" shares the
  host, so this ratio is an UPPER bound on scheduling overhead (no real
  overlap is possible); values near 1.0 mean the host sequencing adds
  little beyond compute.
* ``compiled_vs_host`` — the A/B leg: the SAME pp2 x chunks4 workload
  through the compiled single-program schedule, reported as
  compiled-step-wall / host-step-wall (<= 1.0 means the fused program at
  minimum recovers the dispatch overhead it eliminates), plus
  ``compiled_recompiles`` — the jit-cache growth across the timed
  steady-state loop, which must be 0.
* ``--kernels`` — the UNIFIED-path leg (round 12): the same A/B on a
  tp2 x dp2 x pp2 plan with the shard_map kernels live on BOTH sides —
  overlapped-TP ring ag/rs matmuls (``tp_overlap=True``) plus the Pallas
  flash kernel (interpret mode on CPU, real Mosaic on ``--tpu``). Since the
  compiled engine de-vmapped its stage axis, the kernels run INSIDE the
  fused program; ``compiled_overlap_vs_host`` <= 1.0 is the proof that the
  dispatch saving survives with kernels enabled (the composition the
  tools/bench_gate.py ``compiled_overlap`` leg gates).

Prints one JSON line. Run (virtual CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/pipeline_dispatch_bench.py [--kernels]
On a real chip (tools/tpu_measure_all.py step): add ``--tpu`` to keep the
default platform and let the pp2 plan land on 8 real devices.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_FLAG = "--xla_force_host_platform_device_count=8"
if "--tpu" not in sys.argv:
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        # APPEND to any pre-set flags: setdefault would silently leave one
        # virtual device while the bench builds an 8-device plan
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + _FLAG).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


def run(pp: int = 2, chunks: int = 4, iters: int = 30,
        on_tpu: bool = False) -> dict:
    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    devices = jax.devices()[:8] if on_tpu else jax.devices("cpu")[:8]
    if len(devices) < 8:
        # single-chip tunnel: the pp2 plan needs 8 devices — report instead
        # of crashing so tpu_measure_all's log shows why the leg is absent
        return {"metric": "pipeline_dispatch_overhead", "skipped":
                f"need 8 devices for the pp{pp} plan, have {len(devices)}"}
    args = CoreArgs.model_validate({
        "model": {
            "hidden_size": 32, "num_hidden_layers": 2 * pp,
            "num_attention_heads": 2, "vocab_size": 64,
            "seq_length": 8, "max_position_embeddings": 16,
            "hidden_act": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope", "tie_word_embeddings": False,
            "add_bias_linear": False, "add_qkv_bias": False,
            "make_vocab_size_divisible_by": 1, "ffn_hidden_size": 64,
            "use_flash_attn": False,
        },
        "parallel": {"pp_deg": pp, "chunks": chunks,
                     "pipeline_type": "pipedream_flush",
                     "global_train_batch_size": 4 * chunks},
    })
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(args.model, hpc, args.train, devices=devices,
                         compute_dtype=jnp.float32)
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    data = np.random.RandomState(0).randint(
        0, args.model.padded_vocab_size,
        (hpc.global_bsz, args.model.seq_length + 1))
    batch = make_batch(data)

    # warm every jit (compile outside the timed region)
    sp2, so2, _ = eng.train_step(sp, so, batch)

    # (1) pure dispatch cost: repeated calls of one compiled stage fwd with
    # the same tiny input; block each call so the number is call->result
    # latency, not queue depth
    x = eng._put_stage0({k: v[: hpc.global_bsz // chunks]
                         for k, v in batch.items()})
    rng = jax.random.key(0)
    fwd0 = eng._fwd_jits[0]
    y = fwd0(sp[0], x, rng, None, None)
    jax.block_until_ready(y)
    t0 = time.perf_counter()
    n = 200
    for _ in range(n):
        y = fwd0(sp[0], x, rng, None, None)
        jax.block_until_ready(y)
    dispatch_us = (time.perf_counter() - t0) / n * 1e6

    # (2)+(3) end-to-end host step wall vs the compiled single-program
    # schedule, INTERLEAVED per iteration so transient machine load hits
    # both legs alike, summarized by medians (robust to spikes — the CI
    # hosts running the virtual mesh are shared)
    ceng = CompiledPipelineEngine(args.model, hpc, args.train,
                                  devices=devices,
                                  compute_dtype=jnp.float32)
    csp = ceng.split_params(params, axes)
    cso = ceng.init_opt(csp, axes)
    csp, cso, cm = ceng.train_step(csp, cso, batch)  # compile
    jax.block_until_ready(cm["loss"])
    n_compiles = ceng.compile_count()
    host_times, comp_times = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        sp, so, m = eng.train_step(sp, so, batch)
        host_times.append(time.perf_counter() - t0)
        # feed symmetry: the compiled leg pays its per-step microbatch
        # staging (put_batch) inside the timed window exactly like the
        # host engine pays its internal device_put feed — the ratio prices
        # what cli/train_dist.py actually runs
        t0 = time.perf_counter()
        csp, cso, cm = ceng.train_step(csp, cso, batch)
        jax.block_until_ready(cm["loss"])
        comp_times.append(time.perf_counter() - t0)
    step_ms = float(np.median(host_times)) * 1e3
    compiled_ms = float(np.median(comp_times)) * 1e3
    compiled_recompiles = ceng.compile_count() - n_compiles

    # serial stage compute: fwd+bwd of every (stage, microbatch) leg timed
    # back-to-back through the same jits (approximates the device work the
    # schedule must cover)
    mbs, weights = eng._microbatches(dict(batch))
    serial_times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        ctx = {"inputs": [], "extras": [], "labels": [], "losses": [],
               "aux": [[] for _ in mbs], "rng": rng}
        grad_acc = [None] * len(eng.stages)
        for mi, mb in enumerate(mbs):
            eng._fwd_microbatch(sp, mb, ctx, mi)
        for mi in range(len(mbs)):
            eng._bwd_microbatch(sp, mi, weights[mi], ctx, grad_acc)
        jax.block_until_ready(grad_acc)
        serial_times.append(time.perf_counter() - t0)
    serial_ms = float(np.median(serial_times)) * 1e3

    out = {
        "metric": "pipeline_dispatch_overhead",
        "platform": "tpu" if on_tpu else "cpu",
        "pp": pp, "chunks": chunks,
        "dispatch_us": round(dispatch_us, 1),
        "step_ms": round(step_ms, 2),
        "serial_fwd_bwd_ms": round(serial_ms, 2),
        "step_overhead_ratio": round(step_ms / max(serial_ms, 1e-9), 3),
        "compiled_step_ms": round(compiled_ms, 2),
        "compiled_vs_host": round(compiled_ms / max(step_ms, 1e-9), 3),
        "compiled_recompiles": int(compiled_recompiles),
        "note": ("CPU mesh: devices share the host, so step_overhead_ratio "
                 "upper-bounds host-sequencing cost; on TPU the host "
                 "schedule stays ahead iff per-microbatch stage compute >> "
                 "dispatch_us * pp. compiled_vs_host <= 1.0 means the fused "
                 "single-program 1F1B at minimum recovers the dispatch "
                 "overhead it eliminates."),
    }
    return out


def run_kernels(pp: int = 2, chunks: int = 0, iters: int = 20,
                on_tpu: bool = False) -> dict:
    """The unified-path A/B: host vs compiled 1F1B on a tp2 x dp2 x pp2
    plan with the overlapped-TP ring matmuls AND the flash kernel active on
    both engines (interpret mode on the CPU mesh — same arithmetic, real
    Mosaic on TPU). This is the composition the de-vmapped stage axis
    exists for: the kernels run inside the fused single program, so the
    ratio prices dispatch elimination WITH the kernels, not instead of
    them."""
    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    devices = jax.devices()[:8] if on_tpu else jax.devices("cpu")[:8]
    if len(devices) < 8:
        return {"metric": "pipeline_kernels_ab", "skipped":
                f"need 8 devices for the tp2xdp2xpp{pp} plan, have "
                f"{len(devices)}"}
    # wide enough that the ring chunks and flash blocks are non-degenerate
    # on TPU; on the CPU mesh the same shapes keep interpret mode tractable.
    # chunks: on the SHARED-HOST cpu mesh every lockstep bubble tick costs
    # real compute (no idle device to hide it on), so the ratio is bounded
    # below by ~T/m = 1 + 2(pp-1)/m — m=16 amortizes the bubble enough
    # that the dispatch saving shows through (measured 0.86 vs 1.24 at
    # m=4); on TPU lanes are physically parallel and m=8 suffices
    hidden, seq = (256, 256) if on_tpu else (32, 8)
    if not chunks:
        chunks = 8 if on_tpu else 16
    args = CoreArgs.model_validate({
        "model": {
            "hidden_size": hidden, "num_hidden_layers": 2 * pp,
            "num_attention_heads": max(hidden // 16, 2), "vocab_size": 64,
            "seq_length": seq, "max_position_embeddings": 2 * seq,
            "hidden_act": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope", "tie_word_embeddings": False,
            "add_bias_linear": False, "add_qkv_bias": False,
            "make_vocab_size_divisible_by": 1, "ffn_hidden_size": 2 * hidden,
            "use_flash_attn": True,
        },
        "parallel": {"pp_deg": pp, "chunks": chunks, "global_tp_deg": 2,
                     "pipeline_type": "pipedream_flush",
                     "global_train_batch_size": 4 * chunks},
    })
    hpc = get_hybrid_parallel_config(args, 8)
    kern = dict(tp_overlap=True, use_flash=True,
                flash_interpret=not on_tpu)
    eng = PipelineEngine(args.model, hpc, args.train, devices=devices,
                         compute_dtype=jnp.float32, **kern)
    ceng = CompiledPipelineEngine(args.model, hpc, args.train,
                                  devices=devices,
                                  compute_dtype=jnp.float32, **kern)
    if not ceng.tp_overlap:
        return {"metric": "pipeline_kernels_ab", "skipped":
                f"tp_overlap ineligible: {ceng.overlap_reason}"}
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    csp = ceng.split_params(params, axes)
    cso = ceng.init_opt(csp, axes)
    data = np.random.RandomState(0).randint(
        0, args.model.padded_vocab_size,
        (hpc.global_bsz, args.model.seq_length + 1))
    batch = make_batch(data)

    # compile + warm both legs outside the timed window; the losses must
    # agree (the kernels are exact, not approximations)
    sp, so, hm = eng.train_step(sp, so, batch)
    csp, cso, cm = ceng.train_step(csp, cso, batch)
    if abs(float(cm["loss"]) - float(hm["loss"])) > 1e-4:
        raise AssertionError(
            f"kernel legs diverged: compiled {float(cm['loss'])} vs host "
            f"{float(hm['loss'])}")
    n_compiles = ceng.compile_count()
    host_times, comp_times = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        sp, so, hm = eng.train_step(sp, so, batch)
        host_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        csp, cso, cm = ceng.train_step(csp, cso, batch)
        jax.block_until_ready(cm["loss"])
        comp_times.append(time.perf_counter() - t0)
    host_ms = float(np.median(host_times)) * 1e3
    comp_ms = float(np.median(comp_times)) * 1e3
    ratio = round(comp_ms / max(host_ms, 1e-9), 3)
    return {
        "metric": "pipeline_kernels_ab",
        "platform": "tpu" if on_tpu else "cpu",
        "pp": pp, "chunks": chunks, "tp": 2, "dp": 2,
        "hidden": hidden, "seq": seq, "iters": iters,
        "host_step_ms": round(host_ms, 2),
        "compiled_step_ms": round(comp_ms, 2),
        "compiled_vs_host": ratio,
        "compiled_overlap_vs_host": ratio,  # the bench_gate leg key
        "compiled_recompiles": int(ceng.compile_count() - n_compiles),
        "flash_interpret": not on_tpu,
        "note": ("tp2 x dp2 x pp2 with ring ag/rs matmuls + flash on BOTH "
                 "engines; <= 1.0 means the fused program keeps its "
                 "dispatch win with the shard_map kernels running inside "
                 "it (the de-vmapped stage axis)."),
    }


if __name__ == "__main__":
    _kern = "--kernels" in sys.argv
    _tpu = "--tpu" in sys.argv
    print(json.dumps(run_kernels(on_tpu=_tpu) if _kern else run(on_tpu=_tpu)))
