"""Overlapped-TP A/B microbench: decomposed ring collective matmuls
(ops/overlap.py, ``tp_overlap.enable``) vs the GSPMD auto-partitioned
collectives, on the SAME tp x dp plan.

Two legs per tp degree (tp2 x dp4 and tp4 x dp2 on the 8-device mesh),
INTERLEAVED per iteration so transient machine load hits both alike,
summarized by medians:

* ``overlap_vs_gspmd`` — overlap-step wall / gspmd-step wall: per-leg
  median ratios plus the headline median of the POOLED per-iteration
  ratios across all tp legs. On the virtual CPU mesh every "device"
  shares the host, so no real transfer/compute overlap exists and the
  ratio only bounds the decomposition's bookkeeping overhead (chunked
  matmuls + ppermutes vs one gathered matmul); the on-chip ratio (--tpu)
  is where the ring hops hide under the MXU and the ratio must drop below
  1. The companion cost-model term (cost_model/cost.py tp_overlap
  discount) prices that hardware effect for the search.
* ``overlap_recompiles`` — jit-cache growth of the overlap step across the
  timed steady state, which must be 0 (the ring path must not retrace).
* ``--schedule-impl compiled`` (round 12) — the same rings-vs-GSPMD A/B
  measured INSIDE the compiled single-program 1F1B engine on pp2 x tp x dp
  plans (tp2 x dp2 and tp4 x dp1): the de-vmapped stage axis lets the ring
  kernels run as stage-stacked shard_maps in the fused program, and this
  leg prices exactly that composition. Default ``--schedule-impl spmd`` is
  the original pp=1 GSPMD-step A/B.

Prints one JSON line. Run (virtual CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/tp_overlap_bench.py [--schedule-impl compiled]
On a real chip (tools/tpu_measure_all.py step): add ``--tpu``.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_FLAG = "--xla_force_host_platform_device_count=8"
# The CPU pin must only fire on DIRECT invocation: importers (bench.py's
# tp_overlap leg, the tests) set their own platform env, and a leg that
# wants the real chip would otherwise be silently forced onto 8 virtual
# CPU devices by this module-level guard (its argv never carries --tpu)
if __name__ == "__main__" and "--tpu" not in sys.argv:
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        # APPEND to any pre-set flags: setdefault would silently leave one
        # virtual device while the bench builds an 8-device plan
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + _FLAG).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


def _build_step(args, devices, tp_overlap):
    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=devices)
    tx = make_optimizer(args.train)
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        args.model, hpc, mesh, axes, tx, params, compute_dtype=jnp.bfloat16,
        donate=False, tp_overlap=tp_overlap)
    sp = shard_params(params, pspecs, mesh)
    so = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    return step, sp, so, batch_shd


def _build_compiled_step(args, devices, tp_overlap):
    """One CompiledPipelineEngine train-step closure for the compiled-mode
    A/B: the rings (or GSPMD collectives) run INSIDE the fused 1F1B
    program. Returns (step, recompile_probe) where step(batch) runs one
    full optimizer step."""
    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    hpc = get_hybrid_parallel_config(args, 8)
    eng = CompiledPipelineEngine(args.model, hpc, args.train,
                                 devices=devices,
                                 compute_dtype=jnp.bfloat16,
                                 tp_overlap=tp_overlap)
    if tp_overlap and not eng.tp_overlap:
        raise RuntimeError(f"overlap ineligible: {eng.overlap_reason}")
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    state = {"sp": eng.split_params(params, axes)}
    state["so"] = eng.init_opt(state["sp"], axes)

    def step(batch):
        state["sp"], state["so"], m = eng.train_step(
            state["sp"], state["so"], batch)
        return m

    return step, eng.compile_count


def run(iters: int = 12, on_tpu: bool = False, tps=(2, 4),
        hidden: int = 256, seq: int = 256,
        schedule_impl: str = "spmd") -> dict:
    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.runtime.dataloader import make_batch

    compiled = schedule_impl == "compiled"
    devices = jax.devices()[:8] if on_tpu else jax.devices("cpu")[:8]
    if len(devices) < 8:
        return {"metric": "tp_overlap_ab", "skipped":
                f"need 8 devices for the tp x dp plans, have {len(devices)}"}

    legs = {}
    pooled_ratios = []
    total_recompiles = 0
    for tp in tps:
        # shapes big enough that the per-chunk matmuls amortize dispatch
        # (at toy widths the ring's extra op count dominates on CPU and the
        # ratio says nothing about the decomposition itself)
        parallel = {"global_tp_deg": tp, "global_train_batch_size": 8}
        if compiled:
            # the fused 1F1B program hosts the rings as stage-stacked
            # shard_maps: pp2 with the remaining degree as dp
            parallel.update(pp_deg=2, chunks=2,
                            pipeline_type="pipedream_flush")
        args = CoreArgs.model_validate({
            "model": {
                "hidden_size": hidden, "num_hidden_layers": 2,
                "num_attention_heads": max(hidden // 32, 1),
                "vocab_size": 128,
                "seq_length": seq, "max_position_embeddings": seq,
                "hidden_act": "swiglu", "normalization": "rmsnorm",
                "position_embedding_type": "rope",
                "tie_word_embeddings": False, "add_bias_linear": False,
                "make_vocab_size_divisible_by": 1,
                "ffn_hidden_size": 4 * hidden,
                "use_flash_attn": False,
            },
            "parallel": parallel,
        })
        data = np.random.RandomState(0).randint(
            0, args.model.padded_vocab_size, (8, seq + 1))
        if compiled:
            host_batch = make_batch(data)
            g_run, g_probe = _build_compiled_step(args, devices, False)
            o_run, o_probe = _build_compiled_step(args, devices, True)
            g_step = lambda: g_run(host_batch)
            o_step = lambda: o_run(host_batch)
        else:
            batch = jax.tree.map(jnp.asarray, make_batch(data))
            g_fn, g_sp, g_so, g_shd = _build_step(args, devices, False)
            o_fn, o_sp, o_so, o_shd = _build_step(args, devices, True)
            gb = jax.device_put(batch, g_shd)
            ob = jax.device_put(batch, o_shd)

            def g_step(_s=[g_sp, g_so]):
                _s[0], _s[1], m = g_fn(_s[0], _s[1], gb)
                return m

            def o_step(_s=[o_sp, o_so]):
                _s[0], _s[1], m = o_fn(_s[0], _s[1], ob)
                return m

            g_probe = g_fn._cache_size
            o_probe = o_fn._cache_size
        # compile + warm both legs outside the timed window
        for _ in range(2):
            gm = g_step()
            om = o_step()
        if abs(float(gm["loss"]) - float(om["loss"])) > 1e-2:
            raise AssertionError(
                f"overlap leg diverged from gspmd: {float(om['loss'])} vs "
                f"{float(gm['loss'])}")
        n_compiles = o_probe()

        g_times, o_times = [], []
        for _ in range(iters):
            t0 = time.perf_counter()
            gm = g_step()
            jax.block_until_ready(gm["loss"])
            g_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            om = o_step()
            jax.block_until_ready(om["loss"])
            o_times.append(time.perf_counter() - t0)
        g_ms = float(np.median(g_times)) * 1e3
        o_ms = float(np.median(o_times)) * 1e3
        recompiles = o_probe() - n_compiles
        total_recompiles += recompiles
        pooled_ratios += [o / g for o, g in zip(o_times, g_times)]
        legs[f"tp{tp}"] = {
            "gspmd_step_ms": round(g_ms, 2),
            "overlap_step_ms": round(o_ms, 2),
            "overlap_vs_gspmd": round(o_ms / max(g_ms, 1e-9), 3),
            "overlap_recompiles": int(recompiles),
        }

    return {
        "metric": "tp_overlap_ab",
        "platform": "tpu" if on_tpu else "cpu",
        "schedule_impl": schedule_impl,
        "iters": iters,
        "legs": legs,
        # headline: median of the POOLED per-iteration interleaved ratios
        # across all tp legs (each iteration's pair ran back-to-back, so
        # transient load cancels inside a ratio)
        "overlap_vs_gspmd": round(float(np.median(pooled_ratios)), 3),
        "overlap_recompiles": int(total_recompiles),
        "note": ("interleaved per-iteration medians. CPU mesh: no real "
                 "overlap exists (devices share the host), so the ratio "
                 "bounds the ring decomposition's bookkeeping overhead; "
                 "the on-chip ratio (--tpu) is where the ppermute hops "
                 "hide under the MXU."),
    }


if __name__ == "__main__":
    impl = "spmd"
    if "--schedule-impl" in sys.argv:
        impl = sys.argv[sys.argv.index("--schedule-impl") + 1]
    if impl not in ("spmd", "compiled"):
        sys.exit(f"unknown --schedule-impl {impl!r} (spmd | compiled)")
    print(json.dumps(run(on_tpu="--tpu" in sys.argv, schedule_impl=impl)))
