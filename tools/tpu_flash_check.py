"""Compile + parity check the Pallas flash kernels on the real TPU.

Run standalone (NOT under the CPU-pinning test conftest). Compares the
Mosaic-compiled fwd+bwd against the dense XLA core on small shapes, then
times both on a GPT-2-shaped workload. Writes one JSON line to stdout.
"""
import json
import os
import sys
import time

# repo root on sys.path WITHOUT PYTHONPATH (which breaks the tunnel
# plugin's sitecustomize registration of the 'axon' backend)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(json.dumps({"ok": False, "error": f"not a tpu: {dev.platform}"}))
        return 1

    from hetu_galvatron_tpu.models.modules import xla_sdpa
    from hetu_galvatron_tpu.ops.pallas.flash_attention import flash_sdpa

    rng = np.random.RandomState(0)
    out = {"ok": True, "device": dev.device_kind}

    # -- parity: MHA causal, GQA causal, non-causal -------------------------
    for name, (N, K, causal) in {
        "mha_causal": (4, 4, True),
        "gqa_causal": (4, 2, True),
        "mha_noncausal": (4, 4, False),
    }.items():
        B, S, D = 2, 512, 64
        q = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
        k = jnp.asarray(rng.randn(B, S, K, D), jnp.bfloat16)
        v = jnp.asarray(rng.randn(B, S, K, D), jnp.bfloat16)

        def loss_f(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) ** 2)

        t0 = time.time()
        o_flash = flash_sdpa(q, k, v, causal=causal)
        o_ref = xla_sdpa(q, k, v, causal=causal)
        err = float(jnp.max(jnp.abs(o_flash.astype(jnp.float32)
                                    - o_ref.astype(jnp.float32))))
        g_flash = jax.grad(loss_f(flash_sdpa), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_f(xla_sdpa), argnums=(0, 1, 2))(q, k, v)
        gerr = max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b.astype(jnp.float32))))
            for a, b in zip(g_flash, g_ref))
        out[name] = {"fwd_maxerr": err, "bwd_maxerr": gerr,
                     "secs": round(time.time() - t0, 1)}
        print(f"{name}: fwd {err:.4f} bwd {gerr:.4f}", file=sys.stderr)

    # -- fused cross-entropy: Mosaic parity + timing ------------------------
    if os.environ.get("FLASH_CE", "1") != "0":
        from hetu_galvatron_tpu.ops.pallas.cross_entropy import fused_ce_nll

        T, V = 4096, 50304
        logits = jnp.asarray(rng.randn(T, V), jnp.bfloat16)
        labels = jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)

        def ref_nll(x):
            x = x.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(x, axis=-1)
            gold = jnp.take_along_axis(x, labels[:, None], axis=-1)[:, 0]
            return lse - gold

        def ce_bench(fn, iters=30):
            f = jax.jit(jax.grad(lambda x: jnp.mean(fn(x))))
            r = f(logits)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(iters):
                r = f(logits)
            jax.block_until_ready(r)
            float(jnp.sum(r).astype(jnp.float32))
            return (time.perf_counter() - t0) / iters * 1e3

        try:
            nll = fused_ce_nll(logits, labels)
            err = float(jnp.max(jnp.abs(nll - ref_nll(logits))))
            ms_f, ms_x = ce_bench(lambda x: fused_ce_nll(x, labels)), \
                ce_bench(ref_nll)
            out["fused_ce"] = {"maxerr": err, "flash_ms": round(ms_f, 3),
                               "xla_ms": round(ms_x, 3),
                               "speedup": round(ms_x / ms_f, 3)}
        except Exception as e:
            out["fused_ce"] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(f"fused_ce: {out['fused_ce']}", file=sys.stderr)

    # -- timing sweep -------------------------------------------------------
    shape = os.environ.get("FLASH_SHAPE", "8,1024,12,64")
    B, S, N, D = (int(x) for x in shape.split(","))
    q = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)

    def bench(fn, grad, iters=50):
        if grad:
            f = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v, causal=True) ** 2),
                argnums=(0, 1, 2)))
        else:
            f = jax.jit(lambda q, k, v: fn(q, k, v, causal=True))
        r = f(q, k, v)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(q, k, v)
        jax.block_until_ready(r)
        # host round-trip: belt over block_until_ready through the tunnel
        leaf = r[0] if isinstance(r, tuple) else r
        float(jnp.sum(leaf).astype(jnp.float32))
        return (time.perf_counter() - t0) / iters * 1e3

    out["shape"] = [B, S, N, D]
    out["xla_ms"] = {"fwd": round(bench(xla_sdpa, False), 3),
                     "fwdbwd": round(bench(xla_sdpa, True), 3)}
    print(f"xla: {out['xla_ms']}", file=sys.stderr)
    import functools
    blocks = [(256, 256), (256, 512), (512, 512), (512, 1024), (1024, 512),
              (256, 1024), (1024, 1024)]
    sweep = {}
    for bq, bk in blocks:
        if S % min(bq, S) or S % min(bk, S):
            continue
        fn = functools.partial(flash_sdpa, block_q=bq, block_k=bk)
        try:
            r = {"fwd": round(bench(fn, False), 3),
                 "fwdbwd": round(bench(fn, True), 3)}
        except Exception as e:
            r = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        sweep[f"{bq}x{bk}"] = r
        print(f"flash {bq}x{bk}: {r}", file=sys.stderr)
    out["flash_sweep_ms"] = sweep
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
