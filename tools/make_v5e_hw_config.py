"""Generate spec-derived TPU v5e hardware tables for the search engine.

The hardware profiler (core/profiler/hardware_profiler.py) measures these
tables on a live multi-chip mesh; this environment exposes exactly ONE chip
through the axon tunnel, so multi-chip ICI bandwidth cannot be measured
in-process. This tool fills the gap with tables DERIVED FROM PUBLIC v5e
SPECS so the search engine can plan for a v5e pod slice instead of the
reference's A100/NCCL fixtures (tests/fixtures/*). Every value is estimated
from first principles and labeled as such in the JSON (`"source"` key);
whenever a real multi-chip mesh is available, run
``python -m hetu_galvatron_tpu.cli.profiler <cfg> mode=profile_hardware``
and the measured tables take the same schema and path layout.

Model (documented assumptions, not measurements):
- v5e ICI: 2D torus, per-link one-way bandwidth ~45 GB/s (= 45 MB/ms); each
  torus axis has two directed links per chip (one per direction).
- Ring all-reduce over one axis of n chips: each directed link carries
  (n-1)/n of the buffer, both directions used in parallel =>
  t = M * (n-1)/n / B_uni; effective "bandwidth" M/t = B_uni * n/(n-1).
- Consecutive vs non-consecutive groups: wormhole routing keeps per-link
  bandwidth flat within a slice; the non-consec value is derated 10% for
  the longer average path (the A100 fixture's consec/non-consec distinction
  is an NVLink-vs-PCIe artifact with no v5e equivalent).
- P2P (pipeline stage boundary, one neighbor): one directed link => 45 MB/ms
  regardless of pp degree (the reference's degradation with pp is an NVLink
  topology artifact).
- All-to-all over a bidirectional ring of n chips: per-chip shard M, average
  hop distance n/4, two directed links => t ~= M * n / (8 * B_uni).
- Overlap slowdown: TPUs run collectives on a dedicated async fabric, but
  HBM contention still slows concurrent compute; 1.1 is a conservative
  placeholder between "no slowdown" (1.0) and the A100-measured 1.1256.
"""

from __future__ import annotations

import json
import os
import sys

B_UNI = 45.0  # MB/ms one-way per ICI link (public v5e spec, ~45 GB/s)


def allreduce_bandwidth(n: int) -> float:
    return round(B_UNI * n / (n - 1), 3)


def allreduce_time_ms(mb: float, n: int) -> float:
    return mb * (n - 1) / n / B_UNI


def all2all_time_ms(mb: float, n: int) -> float:
    return mb * n / (8.0 * B_UNI)


def make_tables(world: int = 8):
    source = ("spec-derived estimate (tools/make_v5e_hw_config.py); "
              "not measured — single-chip environment")
    ar = {"source": source}
    n = world
    while n >= 2:
        ar[f"allreduce_size_{n}_consec_1"] = allreduce_bandwidth(n)
        ar[f"allreduce_size_{n}_consec_0"] = round(
            allreduce_bandwidth(n) * 0.9, 3)
        n //= 2
    p2p = {"source": source}
    pp = 2
    while pp <= world:
        p2p[f"pp_size_{pp}"] = B_UNI
        pp *= 2
    sp = {"source": source}
    size = 2
    while size <= world:
        mb = 1
        while mb <= 512:
            sp[f"allreduce_size_{size}_{mb}MB_time"] = round(
                allreduce_time_ms(mb, size), 4)
            sp[f"all2all_size_{size}_{mb}MB_time"] = round(
                all2all_time_ms(mb, size), 4)
            mb *= 2
        size *= 2
    overlap = {"overlap_coe": 1.1, "source": source}
    return ar, p2p, sp, overlap


def main(out_dir: str, world: int = 8) -> int:
    ar, p2p, sp, overlap = make_tables(world)
    os.makedirs(out_dir, exist_ok=True)
    tag = f"1nodes_{world}gpus_per_node"
    for name, payload in [
        (f"allreduce_bandwidth_{tag}.json", ar),
        (f"p2p_bandwidth_{tag}.json", p2p),
        (f"sp_time_{tag}.json", sp),
        ("overlap_coefficient.json", overlap),
    ]:
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            json.dump(payload, f, indent=4)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else (
        "hetu_galvatron_tpu/profiles/tpu_v5e/hardware")
    world = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    sys.exit(main(out, world))
