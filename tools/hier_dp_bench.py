"""Hierarchical dp gradient-reduction A/B: the explicit lane-accumulated
reduce-scatter/all-reduce/all-gather path (ops/hier_reduce.py,
``parallel.hier_dp``) vs the flat GSPMD dp all-reduce, on the SAME plans.

Two legs on the 8-device mesh (dp8 pure-dp and tp2 x dp4), chunks=8 so the
structural difference shows: the flat path's GSPMD all-reduce runs INSIDE
the microbatch scan (once per microbatch), while the hierarchical path
accumulates per-lane grads reduction-free and pays the three-collective
schedule ONCE per step. Iterations are INTERLEAVED so transient machine
load hits both alike, summarized by medians:

* ``hier_dp_vs_flat`` — hier-step wall / flat-step wall per leg, plus the
  headline median of the POOLED per-iteration ratios. On the virtual CPU
  mesh the links are all the same host memory, so the per-LEVEL win (the
  cross-slice hop carrying only the 1/intra shard over DCN) does not
  show — what the CPU ratio measures is the once-per-step vs
  once-per-microbatch schedule difference plus the lane-vmap overhead;
  the cost model's per-level curves price the topology effect for the
  search (cost_model.cost.hier_dp_reduce_ms).
* ``hier_dp_recompiles`` — jit-cache growth of the hier step across the
  timed steady state; must be 0 (the lane path must not retrace).
* ``hier_dp_bucketed_vs_mono`` — the BUCKETED software-pipelined
  schedule (``parallel.hier_bucket_mb``, ops/hier_reduce.py wavefront
  emission) vs the monolithic three-collective program, hier-vs-hier on
  the pure-dp plan. On the CPU mesh there is no DCN/ICI split to
  overlap, so the ratio mostly prices the bucketing overhead (slice /
  concat / extra collective dispatch) — the gate pins it at <= ~1.0 so
  the bucketed program never costs more than it hides; the overlap WIN
  itself needs a real multi-slice fleet (tools/tpu_measure_all.py).

Prints one JSON line. Run (virtual CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/hier_dp_bench.py
On a real slice (tools/tpu_measure_all.py step): add ``--tpu``.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_FLAG = "--xla_force_host_platform_device_count=8"
if __name__ == "__main__" and "--tpu" not in sys.argv:
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + _FLAG).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


def _bench_args(tp: int, hidden: int, seq: int, chunks: int,
                dcn_slices: int):
    """The one bench model/plan config (every leg measures the SAME
    model): tiny untied swiglu/rmsnorm/rope stack; the batch keeps
    B/chunks >= dp so every microbatch still splits into the dp lanes."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs

    return CoreArgs.model_validate({
        "model": {
            "hidden_size": hidden, "num_hidden_layers": 2,
            "num_attention_heads": max(hidden // 32, 1),
            "vocab_size": 128,
            "seq_length": seq, "max_position_embeddings": seq,
            "hidden_act": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope",
            "tie_word_embeddings": False, "add_bias_linear": False,
            "make_vocab_size_divisible_by": 1,
            "ffn_hidden_size": 4 * hidden,
            "use_flash_attn": False,
        },
        "parallel": {"global_tp_deg": tp,
                     "global_train_batch_size": 8 * chunks,
                     "chunks": chunks,
                     "dcn_slices": dcn_slices},
    })


def _build_step(args, devices, hier_dp, dcn_slices, hier_bucket_mb=0.0):
    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=devices, dcn_slices=dcn_slices)
    tx = make_optimizer(args.train)
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        args.model, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.bfloat16, donate=False, hier_dp=hier_dp,
        dcn_slices=dcn_slices, hier_bucket_mb=hier_bucket_mb)
    sp = shard_params(params, pspecs, mesh)
    so = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    return step, sp, so, batch_shd


def run(iters: int = 8, on_tpu: bool = False,
        plans=((1, 8), (2, 4)), hidden: int = 320, seq: int = 128,
        chunks: int = 8, dcn_slices: int = 2,
        bucket_mb: float = 8.0) -> dict:
    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from hetu_galvatron_tpu.runtime.dataloader import make_batch

    devices = jax.devices()[:8] if on_tpu else jax.devices("cpu")[:8]
    if len(devices) < 8:
        return {"metric": "hier_dp_ab", "skipped":
                f"need 8 devices for the dp plans, have {len(devices)}"}

    legs = {}
    pooled = []
    total_recompiles = 0
    for tp, dp in plans:
        args = _bench_args(tp, hidden, seq, chunks, dcn_slices)
        data = np.random.RandomState(0).randint(
            0, args.model.padded_vocab_size,
            (args.parallel.global_train_batch_size, seq + 1))
        batch = jax.tree.map(jnp.asarray, make_batch(data))
        f_fn, f_sp, f_so, f_shd = _build_step(args, devices, False,
                                              dcn_slices)
        h_fn, h_sp, h_so, h_shd = _build_step(args, devices, True,
                                              dcn_slices)
        fb = jax.device_put(batch, f_shd)
        hb = jax.device_put(batch, h_shd)

        def f_step(_s=[f_sp, f_so]):
            _s[0], _s[1], m = f_fn(_s[0], _s[1], fb)
            return m

        def h_step(_s=[h_sp, h_so]):
            _s[0], _s[1], m = h_fn(_s[0], _s[1], hb)
            return m

        for _ in range(2):
            fm = f_step()
            hm = h_step()
        if abs(float(fm["loss"]) - float(hm["loss"])) > 1e-2:
            raise AssertionError(
                f"hier leg diverged from flat: {float(hm['loss'])} vs "
                f"{float(fm['loss'])}")
        n_compiles = h_fn._cache_size()

        f_times, h_times = [], []
        for _ in range(iters):
            t0 = time.perf_counter()
            fm = f_step()
            jax.block_until_ready(fm["loss"])
            f_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            hm = h_step()
            jax.block_until_ready(hm["loss"])
            h_times.append(time.perf_counter() - t0)
        f_ms = float(np.median(f_times)) * 1e3
        h_ms = float(np.median(h_times)) * 1e3
        recompiles = h_fn._cache_size() - n_compiles
        total_recompiles += recompiles
        pooled += [h / f for h, f in zip(h_times, f_times)]
        legs[f"tp{tp}dp{dp}"] = {
            "flat_step_ms": round(f_ms, 2),
            "hier_step_ms": round(h_ms, 2),
            "hier_dp_vs_flat": round(h_ms / max(f_ms, 1e-9), 3),
            "hier_dp_recompiles": int(recompiles),
        }

    # bucketed-vs-monolithic leg (hier vs hier, pure-dp plan — the
    # largest payload): the monolithic step is REBUILT and re-timed here
    # on purpose — interleaving mono/bucketed iterations back to back is
    # what keeps the ratio fair under machine-load drift (reusing the
    # earlier hier leg's times would pair measurements minutes apart)
    tp, dp = plans[0]
    args = _bench_args(tp, hidden, seq, chunks, dcn_slices)
    data = np.random.RandomState(0).randint(
        0, args.model.padded_vocab_size,
        (args.parallel.global_train_batch_size, seq + 1))
    batch = jax.tree.map(jnp.asarray, make_batch(data))
    m_fn, m_sp, m_so, m_shd = _build_step(args, devices, True, dcn_slices)
    b_fn, b_sp, b_so, b_shd = _build_step(args, devices, True, dcn_slices,
                                          hier_bucket_mb=bucket_mb)
    mb_ = jax.device_put(batch, m_shd)
    bb_ = jax.device_put(batch, b_shd)

    def m_step(_s=[m_sp, m_so]):
        _s[0], _s[1], m = m_fn(_s[0], _s[1], mb_)
        return m

    def b_step(_s=[b_sp, b_so]):
        _s[0], _s[1], m = b_fn(_s[0], _s[1], bb_)
        return m

    for _ in range(2):
        mm = m_step()
        bm = b_step()
    if abs(float(mm["loss"]) - float(bm["loss"])) > 1e-2:
        raise AssertionError(
            f"bucketed hier diverged from monolithic: {float(bm['loss'])} "
            f"vs {float(mm['loss'])}")
    n_compiles = b_fn._cache_size()
    m_times, b_times = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        mm = m_step()
        jax.block_until_ready(mm["loss"])
        m_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        bm = b_step()
        jax.block_until_ready(bm["loss"])
        b_times.append(time.perf_counter() - t0)
    # ratio of medians (not median of ratios): the reduce is a small
    # slice of the step, so per-iteration pairing mostly pairs noise
    bucketed_ratio = round(float(np.median(b_times))
                           / max(float(np.median(m_times)), 1e-9), 3)
    bucket_recompiles = int(b_fn._cache_size() - n_compiles)
    total_recompiles += bucket_recompiles

    return {
        "metric": "hier_dp_ab",
        "platform": "tpu" if on_tpu else "cpu",
        "iters": iters,
        "chunks": chunks,
        "dcn_slices": dcn_slices,
        "legs": legs,
        "hier_dp_vs_flat": round(float(np.median(pooled)), 3),
        "hier_dp_recompiles": int(total_recompiles),
        "hier_bucket_mb": bucket_mb,
        "bucketed": {
            "mono_step_ms": round(float(np.median(m_times)) * 1e3, 2),
            "bucketed_step_ms": round(float(np.median(b_times)) * 1e3, 2),
            "hier_dp_bucketed_vs_mono": bucketed_ratio,
            "bucket_recompiles": bucket_recompiles,
        },
        "hier_dp_bucketed_vs_mono": bucketed_ratio,
    }


if __name__ == "__main__":
    print(json.dumps(run(on_tpu="--tpu" in sys.argv)))
