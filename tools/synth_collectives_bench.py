"""Synthesized-vs-hand-built collective A/B: the emitter's lowering of
the verified ring / halving-doubling all-reduce schedules
(collectives/synthesize.py -> verify.py -> emit.py) against the
canonical hand-built bodies (collectives/reference.py) the profiler has
timed since PR 13 — same 8-rank group, same payload, full-manual
shard_map on both sides.

The contract under test is twofold:

* **bit-parity** — the emitted program must produce the hand-built
  body's output bit-for-bit (same hop order, same add association);
  the bench ASSERTS it before timing — a wall-clock win on a wrong
  answer is not a win,
* **zero abstraction tax** — the emitted program is a table-driven
  take/ppermute/where unrolling of the same data movement, so its
  wall-clock must track the hand-built loop. ``synth_collectives_vs_
  handbuilt`` is the pooled median of per-iteration emitted/hand-built
  ratios across both algorithms; tools/bench_gate.py pins it (a ratio,
  regresses UP — the pad/index bookkeeping starting to cost real time).

On the CPU mesh the links are host memory, so the ratio prices pure
program overhead — exactly the quantity the gate should watch; the
schedule CHOICE itself is priced offline (collectives/pricing.py,
``check --schedules``), not here.

Prints one JSON line. Run (virtual CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/synth_collectives_bench.py
On a real slice: add ``--tpu``.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_FLAG = "--xla_force_host_platform_device_count=8"
if __name__ == "__main__" and "--tpu" not in sys.argv:
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + _FLAG).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

# emitted schedule family -> the hand-built reference body it must match
PAIRS = (("ring", "ring"), ("tree_hd", "tree"))


def run(iters: int = 16, on_tpu: bool = False, n: int = 8,
        payload_mb: float = 4.0) -> dict:
    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from hetu_galvatron_tpu.collectives.emit import emit_allreduce_body
    from hetu_galvatron_tpu.collectives.reference import (
        handbuilt_allreduce_body,
    )
    from hetu_galvatron_tpu.collectives.synthesize import (
        synthesize_dp_schedule,
    )
    from hetu_galvatron_tpu.collectives.verify import verify

    devices = jax.devices()[:n] if on_tpu else jax.devices("cpu")[:n]
    if len(devices) < n:
        return {"metric": "synth_collectives", "skipped":
                f"need {n} devices for the group, have {len(devices)}"}
    mesh = Mesh(np.asarray(devices), ("dp",))

    def jit_body(body):
        return jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp"), check_rep=False))

    # per-device f32 vector sized to the payload; divisible by n so the
    # ring chunks and the tree halvings both split it evenly
    local = int(payload_mb * (1 << 20) // 4) // n * n
    x = jnp.asarray(np.random.RandomState(0)
                    .standard_normal(n * local), jnp.float32)

    legs = {}
    pooled = []
    recompiles = 0
    for fam, ref in PAIRS:
        sched = verify(synthesize_dp_schedule(fam, n, 1))
        e_fn = jit_body(emit_allreduce_body(sched, "dp",
                                            verify_first=False))
        h_fn = jit_body(handbuilt_allreduce_body(ref, n, "dp"))
        e_out = jax.block_until_ready(e_fn(x))
        h_out = jax.block_until_ready(h_fn(x))
        bitexact = bool(jnp.array_equal(e_out, h_out))
        if not bitexact:
            raise AssertionError(
                f"emitted {fam} diverged from the hand-built {ref} body "
                f"(max |diff| "
                f"{float(jnp.max(jnp.abs(e_out - h_out)))})")
        n_compiles = e_fn._cache_size() + h_fn._cache_size()
        e_times, h_times = [], []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(e_fn(x))
            e_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            jax.block_until_ready(h_fn(x))
            h_times.append(time.perf_counter() - t0)
        leg_recompiles = (e_fn._cache_size() + h_fn._cache_size()
                          - n_compiles)
        recompiles += leg_recompiles
        pooled += [e / h for e, h in zip(e_times, h_times)]
        e_ms = float(np.median(e_times)) * 1e3
        h_ms = float(np.median(h_times)) * 1e3
        legs[fam] = {
            "handbuilt_ms": round(h_ms, 3),
            "emitted_ms": round(e_ms, 3),
            "emitted_vs_handbuilt": round(e_ms / max(h_ms, 1e-9), 3),
            "bitexact": bitexact,
            "recompiles": int(leg_recompiles),
        }

    return {
        "metric": "synth_collectives",
        "platform": "tpu" if on_tpu else "cpu",
        "iters": iters,
        "payload_mb": payload_mb,
        "group": n,
        "legs": legs,
        "synth_collectives_vs_handbuilt":
            round(float(np.median(pooled)), 3),
        "synth_collectives_recompiles": int(recompiles),
    }


if __name__ == "__main__":
    print(json.dumps(run(on_tpu="--tpu" in sys.argv)))
