#!/usr/bin/env python
"""Chaos drill matrix: fault-inject a real supervised training run and
assert the recovery invariants.

::

    python tools/chaos_drill.py                # full matrix (slow, CPU)
    python tools/chaos_drill.py --case crash   # one case
    python tools/chaos_drill.py --smoke        # harness self-check (fast)

Each matrix case runs the REAL stack: a baseline ``cli.train_dist``
child establishes the uninterrupted loss trajectory, then
``cli.supervise`` drives fault-injected children
(``runtime/chaos.py``) through the cross-process supervisor, and the
case asserts by name on exit codes, restart counts, bit-exact resumed
trajectories (per-step ``train/loss`` gauges from the metrics JSONL —
full float precision, unlike the 4-decimal stdout log), bit-exact
final parameters (the final committed checkpoint's arrays), bounded
RPO, torn-staging-dir cleanup, and parseable flight-recorder dumps.

``--smoke`` validates the harness itself with synthetic (jax-free)
children in a few seconds — the leg ``__graft_entry__.dryrun_multichip``
runs on every dryrun. The pytest wrappers live in
``tests/core/test_chaos.py`` (slow tier).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ZOO = os.path.join(REPO, "hetu_galvatron_tpu", "models", "configs")

TINY = [
    "model.hidden_size=32", "model.num_hidden_layers=2",
    "model.num_attention_heads=2", "model.vocab_size=64",
    "model.seq_length=8", "model.max_position_embeddings=16",
    "model.make_vocab_size_divisible_by=1",
    "train.train_iters=6", "train.seed=1234",
    "parallel.mixed_precision=fp32",
    "parallel.global_train_batch_size=8",
    "logging.log_interval=1",
    "observability.enabled=true", "observability.flush_interval=1",
]

CASES = ("crash", "preempt", "kill_mid_save", "corrupt_meta",
         "transient_io", "hung_save", "budget")


def _child_env() -> Dict[str, str]:
    """Children run on exactly ONE virtual CPU device: drills measure
    the recovery protocol, not the mesh, and a single device keeps the
    trajectories deterministic and the compiles cheap."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.setdefault("JAX_ENABLE_X64", "0")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run(module: str, overrides: List[str], *,
         timeout_s: float = 420.0) -> subprocess.CompletedProcess:
    cmd = [sys.executable, "-m", module,
           os.path.join(ZOO, "gpt2-small.yaml")] + TINY + overrides
    return subprocess.run(cmd, env=_child_env(), cwd=REPO,
                          capture_output=True, text=True,
                          timeout=timeout_s)


def _trajectory(metrics_path: str) -> Dict[int, float]:
    """step -> loss from the metrics JSONL's ``train/loss`` gauge
    records. Last write per step wins: a resumed attempt re-flushing a
    step supersedes the dead attempt's value (they must be bit-equal
    anyway — asserted by the caller)."""
    traj: Dict[int, float] = {}
    with open(metrics_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn tail line from a killed writer
            if rec.get("kind") == "gauge" and \
                    rec.get("name") == "train/loss" and \
                    rec.get("step") is not None:
                traj[int(rec["step"])] = float(rec["value"])
    return traj


def _final_params(ckpt_root: str):
    """Arrays of the NEWEST committed checkpoint (flat path -> np)."""
    import numpy as np

    from hetu_galvatron_tpu.runtime import ckpt_paths

    latest = ckpt_paths.latest_committed_step(ckpt_root)
    assert latest is not None, f"no committed checkpoint under {ckpt_root}"
    step, ckdir = latest
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(os.path.join(ckdir, "params"))
    import jax

    flat = {
        jax.tree_util.keystr(path): np.asarray(leaf)  # off-device compare
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }
    return step, flat


def _assert_bit_equal_params(root_a: str, root_b: str) -> int:
    import numpy as np

    step_a, a = _final_params(root_a)
    step_b, b = _final_params(root_b)
    assert step_a == step_b, \
        f"final committed steps differ: {step_a} vs {step_b}"
    assert a.keys() == b.keys()
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), \
            f"params leaf {k} differs at step {step_a}"
    return step_a


def _assert_traj_matches(base: Dict[int, float], got: Dict[int, float],
                         *, require_last: bool = True) -> None:
    """Every step both runs logged must agree BIT-EXACTLY, and the
    chaos run must reach the baseline's final step. (A killed writer
    may lose its last un-flushed record, so strict superset is not
    required of intermediate steps.)"""
    common = sorted(set(base) & set(got))
    assert common, f"no common steps: baseline {sorted(base)}, " \
                   f"chaos {sorted(got)}"
    for s in common:
        assert base[s] == got[s], \
            f"step {s}: loss {got[s]!r} != baseline {base[s]!r}"
    if require_last:
        last = max(base)
        assert last in got, \
            f"chaos run never reached final step {last} (got {sorted(got)})"


def _flight_dumps(d: str, prefix: str = "flight") -> List[Dict[str, Any]]:
    out = []
    for p in sorted(glob.glob(os.path.join(d, f"{prefix}*.json"))):
        with open(p) as f:
            out.append(json.load(f))  # parseable or the case fails
    return out


def _supervisor_events(metrics_path: str) -> List[Dict[str, Any]]:
    evs = []
    with open(metrics_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "event" and rec.get("name") == "supervisor":
                evs.append(rec.get("data") or {})
    return evs


def run_baseline(workdir: str) -> Dict[str, Any]:
    """The uninterrupted reference run every case compares against."""
    d = os.path.join(workdir, "baseline")
    shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d)
    metrics = os.path.join(d, "metrics.jsonl")
    proc = _run("hetu_galvatron_tpu.cli.train_dist", [
        f"ckpt.save={d}/ck", "ckpt.save_interval=2",
        f"observability.metrics_path={metrics}",
    ])
    assert proc.returncode == 0, \
        f"baseline failed ({proc.returncode}):\n{proc.stdout}\n{proc.stderr}"
    traj = _trajectory(metrics)
    assert len(traj) >= 5, f"baseline logged too few steps: {sorted(traj)}"
    return {"traj": traj, "ckpt": f"{d}/ck"}


# ---------------------------------------------------------------------------
# matrix cases — each returns a short human-readable result line
# ---------------------------------------------------------------------------


def _supervised(workdir: str, name: str, extra: List[str],
                *, max_restarts: int = 3) -> Tuple[int, str, str, subprocess.CompletedProcess]:
    d = os.path.join(workdir, name)
    shutil.rmtree(d, ignore_errors=True)  # a stale dir would replay old
    os.makedirs(d)                        # receipts into the assertions
    metrics = os.path.join(d, "metrics.jsonl")
    proc = _run("hetu_galvatron_tpu.cli.supervise", [
        f"ckpt.save={d}/ck", "ckpt.save_interval=2",
        f"observability.metrics_path={metrics}",
        f"observability.flight_dir={d}/flight",
        "chaos.enable=true",
        "supervisor.auto_restart=true", "supervisor.mode=process",
        f"supervisor.max_restarts={max_restarts}",
        "supervisor.backoff_base_s=0.1", "supervisor.backoff_max_s=0.2",
        "supervisor.poll_interval_s=0.1",
    ] + extra)
    return proc.returncode, f"{d}/ck", metrics, proc


def case_crash(workdir: str, baseline: Dict[str, Any]) -> str:
    """Unhandled host exception at step 3: child exits 1, supervisor
    relaunches, resume from step_2 replays bit-exactly."""
    rc, ck, metrics, proc = _supervised(workdir, "crash",
                                        ["chaos.kind=crash",
                                         "chaos.at_iter=3"])
    assert rc == 0, f"supervised run failed ({rc}):\n{proc.stdout[-2000:]}" \
                    f"\n{proc.stderr[-2000:]}"
    evs = _supervisor_events(metrics)
    exits = [e for e in evs if e.get("event") == "child_exit"]
    assert [e["code"] for e in exits] == [1, 0], \
        f"expected exits [1, 0], got {[e['code'] for e in exits]}"
    _assert_traj_matches(baseline["traj"], _trajectory(metrics))
    step = _assert_bit_equal_params(baseline["ckpt"], ck)
    dumps = _flight_dumps(os.path.join(workdir, "crash", "flight"))
    assert any(d.get("reason") == "crash" for d in dumps), \
        "no child crash flight dump"
    assert any(d.get("reason", "").startswith("child_exit")
               for d in dumps), "no supervisor flight dump"
    return f"crash: exit 1 -> restart -> bit-equal at step {step}"


def case_preempt(workdir: str, baseline: Dict[str, Any]) -> str:
    """SIGTERM mid-run: the guard checkpoints at the boundary, exits 18,
    the relaunch finishes the run step-for-step."""
    rc, ck, metrics, proc = _supervised(workdir, "preempt",
                                        ["chaos.kind=sigterm",
                                         "chaos.at_iter=3"])
    assert rc == 0, f"supervised run failed ({rc}):\n{proc.stdout[-2000:]}" \
                    f"\n{proc.stderr[-2000:]}"
    evs = _supervisor_events(metrics)
    exits = [e["code"] for e in evs if e.get("event") == "child_exit"]
    assert exits == [18, 0], f"expected exits [18, 0], got {exits}"
    _assert_traj_matches(baseline["traj"], _trajectory(metrics))
    step = _assert_bit_equal_params(baseline["ckpt"], ck)
    return f"preempt: exit 18 -> restart -> bit-equal at step {step}"


def case_kill_mid_save(workdir: str, baseline: Dict[str, Any]) -> str:
    """SIGKILL inside the commit window of the step_4 save: the payload
    is staged but no COMMITTED marker lands. The supervisor sees a
    signal death, the relaunch resumes from step_2 (the torn step_4.tmp
    is invisible to selection), replays bit-exactly, and the re-save
    sweeps the torn staging dir."""
    rc, ck, metrics, proc = _supervised(
        workdir, "kill_mid_save",
        ["chaos.kind=kill_mid_save", "chaos.at_iter=4"])
    assert rc == 0, f"supervised run failed ({rc}):\n{proc.stdout[-2000:]}" \
                    f"\n{proc.stderr[-2000:]}"
    evs = _supervisor_events(metrics)
    exits = [e["code"] for e in evs if e.get("event") == "child_exit"]
    assert exits == [-9, 0], f"expected exits [-9, 0], got {exits}"
    # RPO: the dead attempt lost at most the steps since its last commit
    # (save_interval=2 -> bounded at 2 steps); the receipt the
    # supervisor observed at death proves a commit existed
    death = [e for e in evs if e.get("event") == "child_exit"][0]
    assert death.get("commit_step") is not None and \
        death["commit_step"] >= 2, f"no commit receipt at death: {death}"
    _assert_traj_matches(baseline["traj"], _trajectory(metrics))
    step = _assert_bit_equal_params(baseline["ckpt"], ck)
    torn = glob.glob(os.path.join(ck, "*.tmp"))
    assert not torn, f"torn staging dirs survived: {torn}"
    dumps = _flight_dumps(os.path.join(workdir, "kill_mid_save", "flight"),
                          prefix="flight_supervisor")
    assert dumps, "supervisor wrote no flight dump for the signal death"
    return (f"kill_mid_save: exit -9 (SIGKILL in commit window) -> "
            f"torn dir swept, bit-equal at step {step}")


def case_corrupt_meta(workdir: str, baseline: Dict[str, Any]) -> str:
    """A corrupted newest checkpoint + a crash: resume must FALL BACK to
    the previous committed step with a warning (never traceback) and
    still replay bit-exactly."""
    rc, ck, metrics, proc = _supervised(
        workdir, "corrupt_meta",
        ["chaos.plan=corrupt_meta@4,crash@5"])
    assert rc == 0, f"supervised run failed ({rc}):\n{proc.stdout[-2000:]}" \
                    f"\n{proc.stderr[-2000:]}"
    out = proc.stdout + proc.stderr
    assert "falling back" in out, \
        "resume never logged the corrupt-checkpoint fallback"
    # the injected ChaosCrash legitimately tracebacks; the RESUME must not
    blocks = re.findall(r"Traceback \(most recent call last\):(?:\n.+)+",
                        out)
    stray = [b for b in blocks if "ChaosCrash" not in b]
    assert not stray, \
        f"resume tracebacked on corruption:\n{stray[0][:2000]}"
    _assert_traj_matches(baseline["traj"], _trajectory(metrics))
    step = _assert_bit_equal_params(baseline["ckpt"], ck)
    return f"corrupt_meta: fallback resume -> bit-equal at step {step}"


def case_transient_io(workdir: str, baseline: Dict[str, Any]) -> str:
    """Crash, then transient I/O errors on the resume's checkpoint
    reads: the retry seam absorbs them (one attempt, no extra restart),
    and the trajectory still replays bit-exactly."""
    rc, ck, metrics, proc = _supervised(
        workdir, "transient_io",
        ["chaos.plan=crash@3,io_error", "chaos.io_error_count=2",
         "chaos.io_error_op=checkpoint"])
    assert rc == 0, f"supervised run failed ({rc}):\n{proc.stdout[-2000:]}" \
                    f"\n{proc.stderr[-2000:]}"
    evs = _supervisor_events(metrics)
    exits = [e["code"] for e in evs if e.get("event") == "child_exit"]
    assert exits == [1, 0], \
        f"transient I/O must not cost an attempt: exits {exits}"
    retried = any(
        "injecting transient I/O error" in (proc.stdout + proc.stderr)
        for _ in (0,))
    assert retried, "the injector never fired through the retry seam"
    _assert_traj_matches(baseline["traj"], _trajectory(metrics))
    step = _assert_bit_equal_params(baseline["ckpt"], ck)
    return f"transient_io: retries absorbed -> bit-equal at step {step}"


def case_hung_save(workdir: str, baseline: Dict[str, Any]) -> str:
    """A background checkpoint write hangs past ckpt.save_timeout_s:
    the watchdog counts it, the exit drain abandons it instead of
    wedging shutdown, and training itself completes."""
    rc, ck, metrics, proc = _supervised(
        workdir, "hung_save",
        ["chaos.kind=hung_save", "chaos.at_iter=4", "chaos.hang_s=30",
         "ckpt.snapshot_async=true", "ckpt.save_timeout_s=2"])
    assert rc == 0, f"supervised run failed ({rc}):\n{proc.stdout[-2000:]}" \
                    f"\n{proc.stderr[-2000:]}"
    out = proc.stdout + proc.stderr
    assert "abandoning a hung checkpoint write" in out or \
        "hung" in out, "the hung-save watchdog never reported"
    _assert_traj_matches(baseline["traj"], _trajectory(metrics))
    # the hung write never committed: the newest commit predates it
    from hetu_galvatron_tpu.runtime import ckpt_paths

    latest = ckpt_paths.latest_committed_step(ck)
    assert latest is not None and latest[0] <= 4, \
        f"hung save should not have committed: {latest}"
    return (f"hung_save: watchdog fired, drain abandoned the write, "
            f"run completed (last commit step_{latest[0]})")


def case_budget(workdir: str, baseline: Dict[str, Any]) -> str:
    """A crash loop with NO progress (no checkpointing): the restart
    budget exhausts and the supervisor surfaces the child's exit code
    terminally."""
    d = os.path.join(workdir, "budget")
    shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d)
    metrics = os.path.join(d, "metrics.jsonl")
    # no ckpt.save: no commits (no progress), AND no chaos marker dir
    # (chaos.state_dir unset) so the crash re-fires every attempt
    proc = _run("hetu_galvatron_tpu.cli.supervise", [
        "chaos.enable=true", "chaos.kind=crash", "chaos.at_iter=1",
        f"observability.metrics_path={metrics}",
        "supervisor.max_restarts=2",
        f"supervisor.state_file={d}/state.json",
        "supervisor.backoff_base_s=0.05", "supervisor.backoff_max_s=0.1",
        "supervisor.poll_interval_s=0.1",
    ])
    assert proc.returncode == 1, \
        f"budget exhaustion must surface exit 1, got {proc.returncode}"
    st = json.load(open(os.path.join(d, "state.json")))
    assert st["attempt"] == 3 and st["restarts"] == 2, st
    evs = _supervisor_events(metrics)
    assert any(e.get("event") == "giveup" for e in evs), \
        "no giveup event in the supervisor timeline"
    return "budget: 3 attempts, budget exhausted, surfaced exit 1"


CASE_FNS = {
    "crash": case_crash,
    "preempt": case_preempt,
    "kill_mid_save": case_kill_mid_save,
    "corrupt_meta": case_corrupt_meta,
    "transient_io": case_transient_io,
    "hung_save": case_hung_save,
    "budget": case_budget,
}


def run_case(name: str, workdir: str,
             baseline: Optional[Dict[str, Any]] = None) -> str:
    """One matrix case end to end (pytest entry point). ``baseline``
    (from :func:`run_baseline`) may be shared across cases — same
    config, same seed."""
    if baseline is None:
        baseline = run_baseline(workdir)
    return CASE_FNS[name](workdir, baseline)


# ---------------------------------------------------------------------------
# --smoke: harness self-check with synthetic children (no jax)
# ---------------------------------------------------------------------------


def smoke(workdir: str) -> None:
    """Validates the drill harness itself — supervisor loop, exit-code
    handling, commit receipts, pin lifecycle, flight dump parsing —
    with ``python -c`` children in a few seconds. Run by
    ``__graft_entry__.dryrun_multichip`` on every dryrun."""
    sys.path.insert(0, REPO)
    from hetu_galvatron_tpu.observability.recorder import FlightRecorder
    from hetu_galvatron_tpu.runtime import ckpt_paths
    from hetu_galvatron_tpu.runtime.supervisor import ProcessSupervisor

    root = os.path.join(workdir, "ck")
    os.makedirs(root, exist_ok=True)
    # attempt 1: commit step_2, exit 18 (preempted) — progress resets the
    # budget. attempt 2: SIGKILL itself once (marker-one-shot, like a
    # real transient). attempt 3: clean exit.
    child = r"""
import json, os, sys
root, marker = sys.argv[1], sys.argv[2]
steps = sorted(int(d[5:]) for d in os.listdir(root)
               if d.startswith("step_") and d[5:].isdigit())
if not steps:
    d = os.path.join(root, "step_2")
    os.makedirs(d)
    json.dump({"iteration": 2,
               "hybrid_parallel_config": {"world_size": 1}},
              open(os.path.join(d, "meta.json"), "w"))
    open(os.path.join(d, "COMMITTED"), "w").write("ok")
    sys.exit(18)   # preempted after committing step 2
if not os.path.exists(marker):
    open(marker, "w").write("x")
    os.kill(os.getpid(), 9)  # die abruptly before any new commit
sys.exit(0)
"""
    flight = os.path.join(workdir, "flight")
    rec = FlightRecorder(out_dir=flight, prefix="flight_supervisor")
    marker = os.path.join(workdir, "killed_once")
    sup = ProcessSupervisor(
        lambda st: [sys.executable, "-c", child, root, marker],
        save_dir=root, max_restarts=2, base_delay=0.0,
        poll_interval=0.05, sleep=lambda s: None, recorder=rec,
        log=lambda m: None)
    rc = sup.run()
    assert rc == 0, f"smoke supervision failed: rc {rc}"
    assert sup.state.attempt == 3, sup.state
    assert sup.state.last_commit_step == 2
    assert ckpt_paths.read_resume_pin(root) is None, \
        "pin must be cleared on success"
    st = json.load(open(os.path.join(root, "SUPERVISOR_STATE.json")))
    assert st["attempt"] == 3, st
    dumps = _flight_dumps(flight, prefix="flight_supervisor")
    assert dumps and all("reason" in d and "events" in d for d in dumps), \
        "supervisor flight dumps missing or unparseable"
    # health payload is json-serializable (what /healthz would serve)
    health = json.loads(json.dumps(sup.health()))
    assert health["supervisor_attempt"] == 3
    assert health["last_commit_step"] == 2
    print("chaos_drill --smoke: supervisor loop, receipts, pin, "
          "flight dumps, health OK")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="fast harness self-check (synthetic children)")
    ap.add_argument("--case", choices=CASES, default=None,
                    help="run one matrix case instead of all")
    ap.add_argument("--workdir", default=None,
                    help="working directory (default: a fresh tempdir)")
    ns = ap.parse_args(argv)
    workdir = ns.workdir or tempfile.mkdtemp(prefix="chaos_drill_")
    if ns.smoke:
        smoke(workdir)
        return 0
    sys.path.insert(0, REPO)
    names = [ns.case] if ns.case else list(CASES)
    print(f"chaos drill: baseline run (workdir {workdir})", flush=True)
    baseline = run_baseline(workdir)
    failures = []
    for name in names:
        print(f"chaos drill: case {name} ...", flush=True)
        try:
            print(f"  {run_case(name, workdir, baseline)}", flush=True)
        except (AssertionError, subprocess.TimeoutExpired) as e:
            failures.append((name, e))
            print(f"  FAILED: {e}", flush=True)
    if failures:
        print(f"chaos drill: {len(failures)}/{len(names)} case(s) FAILED")
        return 1
    print(f"chaos drill: all {len(names)} case(s) green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
