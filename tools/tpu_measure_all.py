"""Opportunistic TPU measurement suite: wait for the axon tunnel, then run
every pending hardware measurement back-to-back in one tunnel-up window.

The tunnel can wedge for hours (see PERF.md incident log), so measurements
are batched: the suite polls with throwaway probe subprocesses (abandoned on
timeout, NEVER killed — killing a process inside device init wedges the
remote side), and once the chip answers it runs each step as its own
subprocess so a crash or hang in one step cannot take down the rest. A step
that exceeds its deadline is abandoned and the suite STOPS (the abandoned
child still holds the chip).

Steps:
  1. gpt2-small per-layer forward time, batch mode (bsz 1..8, seq 1024)
  2. gpt2-small per-layer forward time, sequence mode (seq 512..4096)
     — merged into the same computation JSON (disjoint keys)
  3. gpt2-small memory profile (tp=1; single chip)
  4. llama2-7b(2-layer) forward time at bsz1/seq2048 — the BASELINE.md
     anchor point (reference A100: 15.08 ms for 2 layers)
  5. flash-attention block sweep + fused-CE timing (tools/tpu_flash_check.py)
  6. full bench.py (MFU headline + A/B legs)

Run detached:  python tools/tpu_measure_all.py > tpu_measure.log 2>&1 &
Outputs land in hetu_galvatron_tpu/profiles/tpu_v5e/ (+ bench JSON on
stdout of step 6, captured in the log dir).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROF_DIR = os.path.join(ROOT, "hetu_galvatron_tpu", "profiles", "tpu_v5e")
LOG_DIR = os.path.join(ROOT, "tpu_measure_logs")
COMP_JSON = os.path.join(
    PROF_DIR, "computation_profiling_bf16_gpt2-small_all.json")
GPT2_YAML = os.path.join(
    ROOT, "hetu_galvatron_tpu", "models", "configs", "gpt2-small.yaml")
LLAMA_YAML = os.path.join(
    ROOT, "hetu_galvatron_tpu", "models", "configs", "llama2-7b.yaml")


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def wait_for_tunnel(max_hours: float) -> bool:
    probe = os.path.join(ROOT, "tools", "tpu_probe.py")
    deadline = time.time() + max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        out_path = os.path.join(LOG_DIR, f"probe_{attempt}.json")
        child = subprocess.Popen([sys.executable, probe, out_path],
                                 stdout=subprocess.DEVNULL,
                                 stderr=subprocess.DEVNULL, cwd=ROOT)
        limit = time.time() + 120
        while time.time() < limit and child.poll() is None:
            time.sleep(2)
        if child.poll() is not None and os.path.exists(out_path):
            info = json.load(open(out_path))
            if info.get("alive") and info.get("platform") == "tpu":
                log(f"tunnel alive (attempt {attempt}): "
                    f"{info.get('device_kind')}")
                return True
            log(f"probe attempt {attempt}: up but not tpu: {info}")
        else:
            log(f"probe attempt {attempt}: "
                + ("hung; child abandoned" if child.poll() is None
                   else f"exited rc={child.returncode} without result"))
        time.sleep(180)
    return False


def run_step(name: str, argv: list, deadline_s: float,
             env_extra: dict = None) -> bool:
    """Run one measurement subprocess; True = completed (any rc). False =
    hung past the deadline (child abandoned; caller must stop the suite)."""
    log(f"step {name}: {' '.join(argv[:4])} ...")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # steps run on the real chip
    if env_extra:
        env.update(env_extra)
    out = open(os.path.join(LOG_DIR, f"{name}.log"), "w")
    child = subprocess.Popen(argv, stdout=out, stderr=subprocess.STDOUT,
                             cwd=ROOT, env=env)
    limit = time.time() + deadline_s
    while time.time() < limit and child.poll() is None:
        time.sleep(5)
    if child.poll() is None:
        log(f"step {name}: exceeded {deadline_s:.0f}s; child abandoned — "
            "stopping the suite (the chip is still held)")
        return False
    log(f"step {name}: rc={child.returncode}")
    return True


def _last_json_line(log_path: str):
    """Last stdout line of a step log that parses as a JSON dict (every
    bench prints exactly one such result line), or None."""
    try:
        with open(log_path) as f:
            lines = f.readlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def build_bench_candidate():
    """Merge the fresh step outputs into one bench_gate candidate: bench.py's
    result line is the base, the pipeline A/B contributes compiled_vs_host,
    and the TP-overlap A/B contributes tp_overlap_vs_gspmd when bench.py's
    own tp_overlap leg did not run. Returns the candidate path, or None when
    no bench result line exists (bench never completed)."""
    base = _last_json_line(os.path.join(LOG_DIR, "bench.log"))
    if base is None:
        return None
    ab = _last_json_line(os.path.join(LOG_DIR, "pipeline_ab.log"))
    if ab and isinstance(ab.get("compiled_vs_host"), (int, float)):
        base.setdefault("compiled_vs_host", ab["compiled_vs_host"])
    tp = _last_json_line(os.path.join(LOG_DIR, "tp_overlap.log"))
    if tp and isinstance(tp.get("overlap_vs_gspmd"), (int, float)):
        base.setdefault("tp_overlap_vs_gspmd", tp["overlap_vs_gspmd"])
    co = _last_json_line(os.path.join(LOG_DIR, "compiled_overlap.log"))
    if co and isinstance(co.get("compiled_overlap_vs_host"), (int, float)):
        base.setdefault("compiled_overlap_vs_host",
                        co["compiled_overlap_vs_host"])
    hd = _last_json_line(os.path.join(LOG_DIR, "hier_dp.log"))
    if hd and isinstance(hd.get("hier_dp_vs_flat"), (int, float)):
        base.setdefault("hier_dp_vs_flat", hd["hier_dp_vs_flat"])
    if hd and isinstance(hd.get("hier_dp_bucketed_vs_mono"), (int, float)):
        base.setdefault("hier_dp_bucketed_vs_mono",
                        hd["hier_dp_bucketed_vs_mono"])
    path = os.path.join(LOG_DIR, "bench_candidate.json")
    with open(path, "w") as f:
        json.dump({"parsed": base}, f, indent=2)
    return path


def merge_comp_json(extra_path: str) -> None:
    """Merge a sequence-mode computation JSON into the batch-mode one
    (disjoint keys: bsz{b}_seq1024 vs bsz1_seq{S})."""
    if not (os.path.exists(COMP_JSON) and os.path.exists(extra_path)):
        return
    base = json.load(open(COMP_JSON))
    base.update(json.load(open(extra_path)))
    with open(COMP_JSON, "w") as f:
        json.dump(base, f, indent=4)
    os.remove(extra_path)
    log(f"merged sequence-mode keys into {COMP_JSON}")


def main() -> int:
    os.makedirs(LOG_DIR, exist_ok=True)
    os.makedirs(PROF_DIR, exist_ok=True)
    max_hours = float(os.environ.get("TPU_WAIT_HOURS", 6))
    if not wait_for_tunnel(max_hours):
        log(f"tunnel never came up within {max_hours}h; giving up")
        return 1

    py = sys.executable
    prof = [py, "-m", "hetu_galvatron_tpu.cli.profiler", GPT2_YAML,
            "mode=model_profiler",
            "model_profiler.output_dir=" + PROF_DIR]
    seq_dir = os.path.join(LOG_DIR, "seq_mode")
    steps = [
        ("comp_batch", prof + [
            "model_profiler.profile_type=computation",
            "model_profiler.profile_mode=batch",
            "model_profiler.profile_max_batch_size=8"], 2400, None),
        ("comp_sequence", [py, "-m", "hetu_galvatron_tpu.cli.profiler",
                           GPT2_YAML, "mode=model_profiler",
                           "model_profiler.output_dir=" + seq_dir,
                           "model_profiler.profile_type=computation",
                           "model_profiler.profile_mode=sequence",
                           "model_profiler.profile_min_seq_length=512",
                           "model_profiler.profile_max_seq_length=4096",
                           "model_profiler.profile_seq_length_step=512"],
         2400, None),
        ("memory", prof + [
            "model_profiler.profile_type=memory",
            "model_profiler.profile_batch_size=8",
            "model_profiler.max_tp_deg=1"], 2400, None),
        ("llama_anchor", [py, "-m", "hetu_galvatron_tpu.cli.profiler",
                          LLAMA_YAML, "mode=model_profiler",
                          "model_profiler.output_dir=" + PROF_DIR,
                          "model_profiler.profile_type=computation",
                          "model_profiler.layernum_min=1",
                          "model_profiler.layernum_max=2",
                          "model_profiler.profile_batch_size=1",
                          "model_profiler.profile_seq_length_list=[2048]"],
         2400, None),
        ("flash_check", [py, os.path.join(ROOT, "tools",
                                          "tpu_flash_check.py")], 2400, None),
        # compiled-vs-host pipeline schedule A/B on the chip (the CPU-mesh
        # numbers in PERF.md only bound dispatch; the on-chip ratio also
        # sees real overlap + collective-permute transfers)
        ("pipeline_ab", [py, os.path.join(ROOT, "tools",
                                          "pipeline_dispatch_bench.py"),
                         "--tpu"], 1800, None),
        # overlapped-TP vs GSPMD collectives on the chip (the CPU-mesh
        # ratio only bounds the ring decomposition's overhead; on ICI the
        # ppermute hops hide under the MXU and the ratio is the real win)
        ("tp_overlap", [py, os.path.join(ROOT, "tools",
                                         "tp_overlap_bench.py"),
                        "--tpu"], 1800, None),
        # unified path: host vs compiled 1F1B with the shard_map kernels
        # (ring tp matmuls + flash) live on BOTH engines — the product of
        # the dispatch saving and the overlap hiding, in one ratio
        ("compiled_overlap", [py, os.path.join(ROOT, "tools",
                                               "pipeline_dispatch_bench.py"),
                              "--kernels", "--tpu"], 1800, None),
        # hierarchical-vs-flat dp gradient reduction: on multi-slice
        # topologies this is where the per-level schedule shows (the
        # cross-slice hop carries only the 1/intra shard over DCN)
        ("hier_dp", [py, os.path.join(ROOT, "tools", "hier_dp_bench.py"),
                     "--tpu"], 1800, None),
        ("bench", [py, os.path.join(ROOT, "bench.py")], 1100, None),
    ]
    for name, argv, deadline, env_extra in steps:
        if not run_step(name, argv, deadline, env_extra):
            return 2
        if name == "comp_sequence":
            merge_comp_json(os.path.join(
                seq_dir, "computation_profiling_bf16_gpt2-small_all.json"))

    # perf regression sentinel over the measurements THIS run just took
    # (the driver archives BENCH_r*.json only after the suite exits, so
    # gating "newest history" here would judge last round's numbers): merge
    # the fresh step outputs into one candidate and gate it against the
    # committed baseline. rc=1 on a regressed leg is logged like any step
    # rc — the suite continues (measurement must finish), but the per-leg
    # delta report lands in bench_gate.log.
    candidate = build_bench_candidate()
    gate = [py, os.path.join(ROOT, "tools", "bench_gate.py")]
    if candidate:
        gate += ["--candidate", candidate]
    else:
        log("bench_gate: no fresh bench output parsed; gating newest "
            "archived history instead")
    if not run_step("bench_gate", gate, 300, {"JAX_PLATFORMS": "cpu"}):
        return 2
    log("suite complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
