"""Perf regression sentinel over the bench history (BENCH_r*.json).

The driver runs ``bench.py`` every round and archives the result as
``BENCH_r<NN>.json`` (``{"n": ..., "cmd": ..., "rc": ..., "tail": ...,
"parsed": {...}}`` — ``parsed`` is bench.py's single JSON result line).
Nothing ever read those files back; a regression was only caught by a
human rereading them. This gate closes that loop:

* a **committed baseline** (``tools/bench_baseline.json``) records the
  accepted per-leg numbers and the device they were measured on,
* each run, the **newest** history entry is compared leg-by-leg against
  the baseline with a relative threshold (default 10%), honoring each
  leg's direction (``tokens_per_sec`` up is good; ``compiled_vs_host``
  down is good),
* a leg past the threshold fails the gate (rc 1) with a readable per-leg
  delta report; legs measured on a different device than the baseline are
  skipped with a warning (a CPU-fallback bench must not "regress" a TPU
  baseline, nor green-light it); a baseline leg the candidate lacks fails
  only when same-device history shows it was measured before (vanished),
  and renders as "pending" when it is simply newer than the history (a
  freshly committed entry),
* the history's per-leg min/max rides along as a noise-context column.

Wiring: ``tools/tpu_measure_all.py`` runs the gate after its bench step;
``__graft_entry__.dryrun_multichip`` runs ``--smoke`` (a synthetic
self-check: an unchanged run must pass, an artificially regressed leg
must fail) so the gate itself is exercised on every CI dryrun with no
bench data needed.

Usage:
  python tools/bench_gate.py                 # newest BENCH_r*.json vs baseline
  python tools/bench_gate.py --threshold 0.05
  python tools/bench_gate.py --candidate path.json
  python tools/bench_gate.py --update-baseline   # accept the candidate
  python tools/bench_gate.py --smoke             # self-check, no data needed
"""

from __future__ import annotations

import argparse
import glob
import io
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(ROOT, "tools", "bench_baseline.json")
DEFAULT_HISTORY = os.path.join(ROOT, "BENCH_r*.json")

# leg name -> (source key in the parsed bench result, higher_is_better)
LEGS: Tuple[Tuple[str, str, bool], ...] = (
    ("mfu_pct", "value", True),
    ("tokens_per_sec", "tokens_per_sec", True),
    ("flash_speedup", "flash_speedup", True),
    ("fused_ce_speedup", "fused_ce_speedup", True),
    ("tp_overlap_vs_gspmd", "tp_overlap_vs_gspmd", False),
    ("compiled_vs_host", "compiled_vs_host", False),
    # the unified path: compiled 1F1B with the shard_map kernels (ring tp
    # matmuls + flash) inside, vs the host engine with the same kernels
    # (tools/pipeline_dispatch_bench.py --kernels). A ratio, regresses UP.
    ("compiled_overlap", "compiled_overlap_vs_host", False),
    # serving legs (tools/serve_bench.py run_prefix / run_spec):
    # hit-vs-cold TTFT ratio under the radix prefix cache (below 1.0 =
    # cached prefill really skipped; regresses UP) and speculative-decode
    # vs plain tokens/sec (above 1.0 = accepted drafts beat the wider
    # verify program; regresses DOWN)
    ("serve_prefix", "serve_prefix_ttft_ratio", False),
    ("spec_decode", "spec_decode_tokens_ratio", True),
    # hierarchical-vs-flat dp gradient reduction (tools/hier_dp_bench.py):
    # lane-accumulated rs/ar/ag once per step vs the flat GSPMD in-scan
    # all-reduce. A ratio, regresses UP.
    ("hier_dp", "hier_dp_vs_flat", False),
    # bucketed software-pipelined hier schedule vs the monolithic
    # three-collective program (hier vs hier, same plan): on the CPU mesh
    # the ratio prices the bucketing overhead (<= ~1.0 — the pipelined
    # program must not cost more than it hides); regresses UP.
    ("hier_dp_bucketed", "hier_dp_bucketed_vs_mono", False),
    # synthesized-schedule emitter vs the hand-built reference bodies
    # (tools/synth_collectives_bench.py): emitted ring/halving-doubling
    # program wall-clock over the canonical bodies, bit-parity asserted
    # before timing. A ratio pricing the emitter's table-driven
    # bookkeeping; regresses UP.
    ("synth_collectives", "synth_collectives_vs_handbuilt", False),
)


def extract_legs(parsed: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """Numeric per-leg values from one bench ``parsed`` dict."""
    out: Dict[str, float] = {}
    if not isinstance(parsed, dict):
        return out
    for leg, key, _ in LEGS:
        v = parsed.get(key)
        if isinstance(v, (int, float)) and v > 0:
            out[leg] = float(v)
    return out


def load_history(pattern: str = DEFAULT_HISTORY
                 ) -> List[Tuple[int, str, Dict[str, Any]]]:
    """(round, path, parsed) for every readable history file with a parsed
    result, ordered by round number."""
    out = []
    for path in glob.glob(pattern):
        m = re.search(r"r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = obj.get("parsed") if isinstance(obj, dict) else None
        if isinstance(parsed, dict):
            out.append((int(m.group(1)), path, parsed))
    return sorted(out)


def compare(baseline: Dict[str, Any], candidate: Dict[str, Any],
            *, threshold: float,
            history: Optional[List[Dict[str, Any]]] = None
            ) -> Tuple[List[Dict[str, Any]], bool]:
    """Per-leg delta rows + overall pass. ``baseline``/``candidate`` are
    {"device": ..., "legs": {...}} dicts; ``history`` is a list of older
    parsed bench results for the noise-context column."""
    base_dev = str(baseline.get("device", ""))
    cand_dev = str(candidate.get("device", ""))
    dev_ok = (not base_dev) or (base_dev == cand_dev)
    hist_legs: Dict[str, List[float]] = {}
    for parsed in history or []:
        if str(parsed.get("device", "")) != base_dev:
            continue
        for leg, v in extract_legs(parsed).items():
            hist_legs.setdefault(leg, []).append(v)

    rows: List[Dict[str, Any]] = []
    ok = True
    directions = {leg: hib for leg, _, hib in LEGS}
    for leg in [l for l, _, _ in LEGS]:
        b = baseline.get("legs", {}).get(leg)
        c = candidate.get("legs", {}).get(leg)
        if b is None and c is None:
            continue
        row: Dict[str, Any] = {"leg": leg, "baseline": b, "candidate": c}
        hist = hist_legs.get(leg)
        if hist:
            row["history"] = (min(hist), max(hist))
        if not dev_ok:
            row["status"] = (f"skipped (device mismatch: "
                             f"{cand_dev or '?'} vs baseline "
                             f"{base_dev or '?'})")
        elif b is None:
            row["status"] = "new (no baseline; run --update-baseline)"
        elif c is None:
            if hist:
                # a leg silently VANISHING is a regression signal: the
                # bench measured it before (same-device history) and
                # stopped — something the baseline promises went dark
                row["status"] = "MISSING from candidate"
                ok = False
            else:
                # a baseline leg NO same-device run ever produced is
                # merely newer than the history (a freshly committed
                # entry, e.g. compiled_overlap): render it pending, not
                # failed, or the gate is permanently red from the commit
                # that introduces a leg until the next bench round
                row["status"] = "pending (no measured history yet)"
        else:
            delta = (c - b) / b
            row["delta"] = delta
            worse = -delta if directions[leg] else delta
            if worse > threshold:
                row["status"] = f"REGRESSED (>{threshold:.0%})"
                ok = False
            elif worse < -threshold:
                row["status"] = "improved"
            else:
                row["status"] = "ok"
        rows.append(row)
    return rows, ok


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    return f"{v:.3f}".rstrip("0").rstrip(".")


def render_report(rows: List[Dict[str, Any]], ok: bool, *,
                  candidate_name: str, baseline_name: str, out=None) -> None:
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    w(f"== bench gate: {candidate_name} vs {baseline_name} ==")
    w(f"{'leg':<22}{'baseline':>10}{'candidate':>11}{'delta':>9}"
      f"{'history':>17}  status")
    for r in rows:
        hist = (f"[{_fmt(r['history'][0])}, {_fmt(r['history'][1])}]"
                if "history" in r else "-")
        delta = f"{r['delta']:+.1%}" if "delta" in r else "-"
        w(f"{r['leg']:<22}{_fmt(r['baseline']):>10}"
          f"{_fmt(r['candidate']):>11}{delta:>9}{hist:>17}  {r['status']}")
    n_bad = sum(1 for r in rows
                if r["status"].startswith(("REGRESSED", "MISSING")))
    if not ok:
        w(f"bench gate: FAIL ({n_bad} leg(s) regressed)")
    elif rows and all(r["status"].startswith("skipped") for r in rows):
        # every leg was device-skipped: nothing was actually gated, and
        # "PASS" would green-light an ungated run (e.g. a TPU candidate
        # against the committed CPU baseline)
        w("bench gate: NO VERDICT (every leg skipped — run "
          "--update-baseline on this device to start gating it)")
    else:
        w("bench gate: PASS")


def smoke() -> int:
    """Self-check with synthetic data: an unchanged run must pass and an
    artificially regressed leg must fail — exercising extract/compare/
    render end-to-end without any bench history."""
    base = {"device": "TPU v5 lite",
            "legs": {"mfu_pct": 40.0, "tokens_per_sec": 100000.0,
                     "compiled_vs_host": 0.7, "compiled_overlap": 0.75,
                     "serve_prefix": 0.3, "spec_decode": 1.4,
                     "hier_dp": 0.85, "hier_dp_bucketed": 0.95}}
    same = {"device": "TPU v5 lite",
            "legs": {"mfu_pct": 39.2, "tokens_per_sec": 98000.0,
                     "compiled_vs_host": 0.72, "compiled_overlap": 0.77,
                     "serve_prefix": 0.31, "spec_decode": 1.37,
                     # hier_dp_bucketed IMPROVING (dropping — the
                     # pipelined schedule hiding more) must pass too:
                     # both directions of the new leg ride the smoke
                     "hier_dp": 0.87, "hier_dp_bucketed": 0.82}}
    bad = {"device": "TPU v5 lite",
           "legs": {"mfu_pct": 40.1, "tokens_per_sec": 80000.0,
                    "compiled_vs_host": 0.95, "compiled_overlap": 1.2,
                    # serve_prefix regresses UP (hits stop skipping
                    # prefill), spec_decode DOWN (drafts stop paying)
                    "serve_prefix": 0.9, "spec_decode": 0.8,
                    # hier_dp regresses UP (the hierarchical schedule
                    # stops beating the flat all-reduce); the bucketed
                    # leg regresses UP too (bucketing overhead outgrew
                    # the overlap win)
                    "hier_dp": 1.3, "hier_dp_bucketed": 1.25}}
    other_dev = {"device": "cpu", "legs": {"mfu_pct": 5.0}}

    rows, ok_same = compare(base, same, threshold=0.10)
    render_report(rows, ok_same, candidate_name="<unchanged run>",
                  baseline_name="<synthetic baseline>")
    rows, ok_bad = compare(base, bad, threshold=0.10)
    render_report(rows, ok_bad, candidate_name="<regressed run>",
                  baseline_name="<synthetic baseline>")
    regressed = {r["leg"] for r in rows
                 if r["status"].startswith("REGRESSED")}
    rows, ok_dev = compare(base, other_dev, threshold=0.10)
    buf = io.StringIO()
    render_report(rows, ok_dev, candidate_name="<other device>",
                  baseline_name="<synthetic baseline>", out=buf)
    healthy = (ok_same and not ok_bad
               and regressed == {"tokens_per_sec", "compiled_vs_host",
                                 "compiled_overlap", "serve_prefix",
                                 "spec_decode", "hier_dp",
                                 "hier_dp_bucketed"}
               and ok_dev
               and all(r["status"].startswith("skipped") for r in rows)
               and "NO VERDICT" in buf.getvalue()
               and _regret_smoke())
    print(f"bench gate --smoke: "
          f"{'self-check OK' if healthy else 'SELF-CHECK FAILED'}")
    return 0 if healthy else 1


def _regret_smoke() -> bool:
    """Synthetic plan-regret detection case (observability/calibration):
    calibrated curves that halve the collective cost must flip a
    comm-heavy runner-up past the incumbent (triggered), while calibrated
    == prior must not. Keeps the regret sentinel's arithmetic under the
    same no-bench-data self-check the perf legs get."""
    try:
        try:
            from hetu_galvatron_tpu.observability.calibration import (
                evaluate_plan_regret,
            )
        except ImportError:
            # run as a bare script (python tools/bench_gate.py): the repo
            # root is not on sys.path — add it and retry
            sys.path.insert(0, os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            from hetu_galvatron_tpu.observability.calibration import (
                evaluate_plan_regret,
            )
    except ImportError as e:
        print(f"regret smoke: calibration module unavailable ({e})")
        return False
    prior = {"2_1": (0.1, 100.0), "4_1": (0.1, 100.0)}
    calib = {"2_1": (0.05, 200.0), "4_1": (0.05, 200.0)}
    incumbent = {"time_cost_ms": 100.0, "pp": 1, "bsz": 8, "chunks": 2,
                 "layers": [{"tp": 1, "dp": 2}] * 2}
    hungry = {"time_cost_ms": 101.0, "pp": 1, "bsz": 8, "chunks": 2,
              "layers": [{"tp": 4, "dp": 2}] * 2}
    kw = dict(seq_len=4096, hidden_size=4096, param_mb=8.0,
              mixed_precision=True, threshold=0.001)
    hit = evaluate_plan_regret(incumbent, [hungry], prior=(prior, None),
                               calibrated=(calib, None), **kw)
    quiet = evaluate_plan_regret(incumbent, [hungry], prior=(prior, None),
                                 calibrated=(prior, None), **kw)
    ok = (bool(hit["triggered"]) and hit["regret_ms"] > 0
          and not quiet["triggered"] and quiet["regret_ms"] == 0.0)
    print(f"regret smoke: {'ok' if ok else 'FAILED'} "
          f"(triggered {hit['regret_ms']:.3f} ms; quiet "
          f"{quiet['regret_ms']:.3f} ms)")
    return ok


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=DEFAULT_HISTORY,
                    help="glob of BENCH_r*.json files")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--candidate", default=None,
                    help="a bench result JSON (BENCH_r*.json shape or a "
                         "bare parsed dict); default: newest history entry")
    ap.add_argument("--threshold", type=float, default=None,
                    help="relative regression threshold (default: the "
                         "baseline's recorded threshold, else 0.10)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept the candidate as the new baseline")
    ap.add_argument("--smoke", action="store_true",
                    help="synthetic self-check (CI; needs no bench data)")
    args = ap.parse_args(argv)

    if args.smoke:
        return smoke()

    history = load_history(args.history)
    if args.candidate:
        try:
            with open(args.candidate) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench gate: cannot read candidate "
                  f"{args.candidate}: {e}", file=sys.stderr)
            return 2
        parsed = obj.get("parsed", obj) if isinstance(obj, dict) else None
        cand_name = args.candidate
        prior = [p for _, path, p in history
                 if os.path.abspath(path) != os.path.abspath(args.candidate)]
    elif history:
        _, cand_name, parsed = history[-1]
        prior = [p for _, _, p in history[:-1]]
    else:
        print(f"bench gate: no parseable history at {args.history} and no "
              "--candidate given", file=sys.stderr)
        return 2
    legs = extract_legs(parsed)
    if not legs:
        print(f"bench gate: candidate {cand_name} carries no per-leg "
              "numbers (bench never completed?); nothing to gate",
              file=sys.stderr)
        return 0
    candidate = {"device": (parsed or {}).get("device", ""), "legs": legs}

    if args.update_baseline:
        baseline = {"created_from": os.path.basename(str(cand_name)),
                    "device": candidate["device"],
                    "threshold": (args.threshold if args.threshold is not None
                                  else 0.10),
                    "legs": candidate["legs"]}
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"bench gate: baseline updated from {cand_name} "
              f"({len(legs)} legs) -> {args.baseline}")
        return 0

    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench gate: no baseline at {args.baseline} ({e}); run "
              "with --update-baseline to create one", file=sys.stderr)
        return 2

    threshold = args.threshold
    if threshold is None:
        rec = baseline.get("threshold")
        threshold = float(rec) if isinstance(rec, (int, float)) else 0.10
    rows, ok = compare(baseline, candidate, threshold=threshold,
                       history=prior)
    render_report(rows, ok, candidate_name=str(cand_name),
                  baseline_name=args.baseline)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
