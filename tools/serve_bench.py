#!/usr/bin/env python
"""Closed-loop serving load generator: TTFT / inter-token / throughput.

Drives the in-process serving engine (``hetu_galvatron_tpu/serving/``) with
a fixed-concurrency closed loop — every completed request is immediately
replaced until the request budget is spent — the standard way to find a
serving stack's latency/throughput operating point (open-loop arrival
replays live in ``cli/serve.py`` via ``arrival_offset_s``).

CPU-runnable smoke mode (like ``bench.py``'s probe path)::

    JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke

Real shapes::

    python tools/serve_bench.py --hidden 1024 --layers 8 --heads 16 \
        --kv-heads 4 --vocab 32000 --requests 256 --concurrency 32 \
        --max-batch 16 --max-new 64

Weights are random (the bench measures the serving machinery, not the
model); pass ``--json out.json`` for a machine-readable report and
``--metrics m.jsonl`` to keep the engine's own telemetry stream.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def build_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + small load (CI / laptop)")
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="0 = MHA (== --heads)")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--max-positions", type=int, default=1024)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--prompt-len", default="8:64",
                    help="min:max prompt length (uniform)")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", default=None, help="write the report here")
    ap.add_argument("--metrics", default=None,
                    help="engine telemetry JSONL path")
    return ap.parse_args(argv)


def pct(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def main(argv=None) -> int:
    ns = build_args(argv)
    if ns.smoke:
        ns.hidden, ns.layers, ns.heads, ns.vocab = 64, 2, 4, 256
        ns.max_positions = 128
        ns.requests = min(ns.requests, 24)
        ns.concurrency = min(ns.concurrency, 6)
        ns.max_batch = min(ns.max_batch, 4)
        ns.max_new = min(ns.max_new, 8)
        ns.prompt_len = "4:24"
        ns.block_size = 8

    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.observability.registry import MetricsRegistry
    from hetu_galvatron_tpu.observability.sinks import JsonlSink
    from hetu_galvatron_tpu.serving.engine import ServingEngine

    lo, hi = (int(x) for x in ns.prompt_len.split(":"))
    cfg = ModelArgs(
        hidden_size=ns.hidden, num_hidden_layers=ns.layers,
        num_attention_heads=ns.heads,
        num_key_value_heads=ns.kv_heads or None,
        vocab_size=ns.vocab, max_position_embeddings=ns.max_positions,
        seq_length=min(ns.max_positions, hi + ns.max_new),
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1)
    params, _ = init_causal_lm(jax.random.key(ns.seed), cfg)
    serving = ServingArgs(
        max_batch_size=ns.max_batch, kv_block_size=ns.block_size,
        max_seq_len=min(ns.max_positions, hi + ns.max_new),
        max_new_tokens=ns.max_new, temperature=ns.temperature)
    registry = MetricsRegistry(
        [JsonlSink(ns.metrics)] if ns.metrics else [])
    # bf16 on accelerators, f32 on CPU (smoke numerics)
    dtype = (jnp.float32 if jax.devices()[0].platform == "cpu"
             else jnp.bfloat16)
    engine = ServingEngine(params, cfg, serving, registry=registry,
                           compute_dtype=dtype)

    print(f"warmup: compiling decode + prefill buckets ...", file=sys.stderr)
    t0 = time.monotonic()
    engine.warmup()
    warm_s = time.monotonic() - t0
    compiles_warm = engine.compile_count()

    counter = {"left": ns.requests}
    lock = threading.Lock()
    ttfts, itls, lats, toks_out = [], [], [], [0]
    not_done = {}  # status -> count: rejected/timeout/cancelled/error

    def worker(wid: int):
        # per-worker stream: RandomState is not thread-safe and a shared
        # one would make --seed runs depend on thread interleaving
        rng = np.random.RandomState(ns.seed + wid)
        while True:
            with lock:
                if counter["left"] <= 0:
                    return
                counter["left"] -= 1
            n = rng.randint(lo, hi + 1)
            prompt = rng.randint(0, cfg.vocab_size, (n,)).tolist()
            t_sub = time.monotonic()
            h = engine.submit(prompt, seed=wid)
            prev = None
            for _ in h.tokens():
                now = time.monotonic()
                if prev is not None:
                    itls.append((now - prev) * 1000.0)
                prev = now
            if h.status != "done":
                # a benchmark must not silently shrink its own load:
                # non-completions are reported, not dropped
                with lock:
                    not_done[h.status] = not_done.get(h.status, 0) + 1
                continue
            ttfts.append(h.ttft_s() * 1000.0)
            lats.append((h.finished_t - t_sub) * 1000.0)
            with lock:
                toks_out[0] += len(h.output)

    engine.start()
    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(ns.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    engine.close()
    registry.close()

    report = {
        "model": {"hidden": ns.hidden, "layers": ns.layers,
                  "heads": ns.heads, "vocab": ns.vocab},
        "load": {"requests": ns.requests, "concurrency": ns.concurrency,
                 "max_batch": ns.max_batch, "prompt_len": ns.prompt_len,
                 "max_new": ns.max_new},
        "warmup_s": round(warm_s, 3),
        "wall_s": round(wall, 3),
        "completed": len(lats),
        "not_completed": not_done,  # rejected/timeout/cancelled/error
        "tokens_out": toks_out[0],
        "tokens_per_sec": round(toks_out[0] / wall, 2) if wall else 0.0,
        "requests_per_sec": round(len(lats) / wall, 2) if wall else 0.0,
        "ttft_ms": {"p50": round(pct(ttfts, 50), 3),
                    "p90": round(pct(ttfts, 90), 3),
                    "p99": round(pct(ttfts, 99), 3)},
        "itl_ms": {"p50": round(pct(itls, 50), 3),
                   "p99": round(pct(itls, 99), 3)},
        "latency_ms": {"p50": round(pct(lats, 50), 3),
                       "p99": round(pct(lats, 99), 3)},
        "jit_programs_after_warmup": compiles_warm,
        "jit_programs_final": engine.compile_count(),
        "steady_state_recompiles":
            engine.compile_count() - compiles_warm,
    }
    print(json.dumps(report, indent=2))
    if ns.json:
        with open(ns.json, "w") as f:
            json.dump(report, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
