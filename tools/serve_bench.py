#!/usr/bin/env python
"""Closed-loop serving load generator: TTFT / inter-token / throughput.

Drives the in-process serving engine (``hetu_galvatron_tpu/serving/``) with
a fixed-concurrency closed loop — every completed request is immediately
replaced until the request budget is spent — the standard way to find a
serving stack's latency/throughput operating point (open-loop arrival
replays live in ``cli/serve.py`` via ``arrival_offset_s``).

CPU-runnable smoke mode (like ``bench.py``'s probe path)::

    JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke

Real shapes::

    python tools/serve_bench.py --hidden 1024 --layers 8 --heads 16 \
        --kv-heads 4 --vocab 32000 --requests 256 --concurrency 32 \
        --max-batch 16 --max-new 64

Shared-prefix trace (``--shared-prefixes N``): requests open with one of N
generated system prompts (``--prefix-len`` tokens) plus a random suffix —
the production shape the radix prefix cache serves. With
``--prefix-cache`` the report splits TTFT percentiles by hit/miss and
carries ``prefix_hit_rate``; ``--spec-decode``/``--spec-k`` turn on
speculative decoding and report the accept rate.

Weights are random (the bench measures the serving machinery, not the
model); pass ``--json out.json`` for a machine-readable report and
``--metrics m.jsonl`` to keep the engine's own telemetry stream.

``run_prefix()`` / ``run_spec()`` are the importable A/B legs ``bench.py``
and ``tools/bench_gate.py`` consume (committed CPU baselines in
``tools/bench_baseline.json``): hit-vs-cold TTFT ratio and
spec-vs-plain tokens/sec ratio, both at zero steady-state recompiles.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def build_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + small load (CI / laptop)")
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="0 = MHA (== --heads)")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--max-positions", type=int, default=1024)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--prompt-len", default="8:64",
                    help="min:max prompt length (uniform)")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shared-prefixes", type=int, default=0,
                    help="N shared system prompts prepended to prompts "
                         "(0 = fully random trace)")
    ap.add_argument("--prefix-len", type=int, default=64,
                    help="shared system-prompt length in tokens")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable the radix prefix cache")
    ap.add_argument("--spec-decode", action="store_true",
                    help="enable speculative decoding (n-gram draft)")
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--json", default=None, help="write the report here")
    ap.add_argument("--metrics", default=None,
                    help="engine telemetry JSONL path")
    return ap.parse_args(argv)


def pct(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def main(argv=None) -> int:
    ns = build_args(argv)
    if ns.smoke:
        ns.hidden, ns.layers, ns.heads, ns.vocab = 64, 2, 4, 256
        ns.max_positions = 128
        ns.requests = min(ns.requests, 24)
        ns.concurrency = min(ns.concurrency, 6)
        ns.max_batch = min(ns.max_batch, 4)
        ns.max_new = min(ns.max_new, 8)
        ns.prompt_len = "4:24"
        ns.block_size = 8
        ns.prefix_len = min(ns.prefix_len, 32)

    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.observability.registry import MetricsRegistry
    from hetu_galvatron_tpu.observability.sinks import JsonlSink
    from hetu_galvatron_tpu.serving.engine import ServingEngine

    lo, hi = (int(x) for x in ns.prompt_len.split(":"))
    base_len = ns.prefix_len if ns.shared_prefixes else 0
    max_total = min(ns.max_positions, base_len + hi + ns.max_new)
    cfg = ModelArgs(
        hidden_size=ns.hidden, num_hidden_layers=ns.layers,
        num_attention_heads=ns.heads,
        num_key_value_heads=ns.kv_heads or None,
        vocab_size=ns.vocab, max_position_embeddings=ns.max_positions,
        seq_length=max_total,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1)
    params, _ = init_causal_lm(jax.random.key(ns.seed), cfg)
    serving = ServingArgs(
        max_batch_size=ns.max_batch, kv_block_size=ns.block_size,
        max_seq_len=max_total,
        max_new_tokens=ns.max_new, temperature=ns.temperature,
        prefix_cache=ns.prefix_cache,
        spec_decode=ns.spec_decode, spec_k=ns.spec_k)
    registry = MetricsRegistry(
        [JsonlSink(ns.metrics)] if ns.metrics else [])
    # bf16 on accelerators, f32 on CPU (smoke numerics)
    dtype = (jnp.float32 if jax.devices()[0].platform == "cpu"
             else jnp.bfloat16)
    engine = ServingEngine(params, cfg, serving, registry=registry,
                           compute_dtype=dtype)
    # the shared-prefix trace: N fixed system prompts; each request opens
    # with one of them (uniform), then a random suffix
    sys_rng = np.random.RandomState(ns.seed + 100003)
    sys_prompts = [sys_rng.randint(0, cfg.vocab_size,
                                   (ns.prefix_len,)).tolist()
                   for _ in range(ns.shared_prefixes)]

    print(f"warmup: compiling decode + prefill buckets ...", file=sys.stderr)
    t0 = time.monotonic()
    engine.warmup()
    warm_s = time.monotonic() - t0
    compiles_warm = engine.compile_count()

    counter = {"left": ns.requests}
    lock = threading.Lock()
    ttfts, itls, lats, toks_out = [], [], [], [0]
    ttft_hit, ttft_miss = [], []
    not_done = {}  # status -> count: rejected/timeout/cancelled/error

    def worker(wid: int):
        # per-worker stream: RandomState is not thread-safe and a shared
        # one would make --seed runs depend on thread interleaving
        rng = np.random.RandomState(ns.seed + wid)
        while True:
            with lock:
                if counter["left"] <= 0:
                    return
                counter["left"] -= 1
            n = rng.randint(lo, hi + 1)
            prompt = rng.randint(0, cfg.vocab_size, (n,)).tolist()
            if sys_prompts:
                prompt = sys_prompts[rng.randint(len(sys_prompts))] + prompt
            t_sub = time.monotonic()
            h = engine.submit(prompt, seed=wid)
            prev = None
            for _ in h.tokens():
                now = time.monotonic()
                if prev is not None:
                    itls.append((now - prev) * 1000.0)
                prev = now
            if h.status != "done":
                # a benchmark must not silently shrink its own load:
                # non-completions are reported, not dropped
                with lock:
                    not_done[h.status] = not_done.get(h.status, 0) + 1
                continue
            ttfts.append(h.ttft_s() * 1000.0)
            (ttft_hit if h.cached_tokens else ttft_miss).append(
                h.ttft_s() * 1000.0)
            lats.append((h.finished_t - t_sub) * 1000.0)
            with lock:
                toks_out[0] += len(h.output)

    engine.start()
    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(ns.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    engine.close()
    registry.close()

    report = {
        "model": {"hidden": ns.hidden, "layers": ns.layers,
                  "heads": ns.heads, "vocab": ns.vocab},
        "load": {"requests": ns.requests, "concurrency": ns.concurrency,
                 "max_batch": ns.max_batch, "prompt_len": ns.prompt_len,
                 "max_new": ns.max_new},
        "warmup_s": round(warm_s, 3),
        "wall_s": round(wall, 3),
        "completed": len(lats),
        "not_completed": not_done,  # rejected/timeout/cancelled/error
        "tokens_out": toks_out[0],
        "tokens_per_sec": round(toks_out[0] / wall, 2) if wall else 0.0,
        "requests_per_sec": round(len(lats) / wall, 2) if wall else 0.0,
        "ttft_ms": {"p50": round(pct(ttfts, 50), 3),
                    "p90": round(pct(ttfts, 90), 3),
                    "p99": round(pct(ttfts, 99), 3)},
        "itl_ms": {"p50": round(pct(itls, 50), 3),
                   "p99": round(pct(itls, 99), 3)},
        "latency_ms": {"p50": round(pct(lats, 50), 3),
                       "p99": round(pct(lats, 99), 3)},
        "jit_programs_after_warmup": compiles_warm,
        "jit_programs_final": engine.compile_count(),
        "steady_state_recompiles":
            engine.compile_count() - compiles_warm,
    }
    if ns.prefix_cache:
        report["prefix_hit_rate"] = round(
            engine.prefix.hit_rate if engine.prefix else 0.0, 4)
        report["ttft_ms_hit"] = {"p50": round(pct(ttft_hit, 50), 3),
                                 "p90": round(pct(ttft_hit, 90), 3),
                                 "n": len(ttft_hit)}
        report["ttft_ms_miss"] = {"p50": round(pct(ttft_miss, 50), 3),
                                  "p90": round(pct(ttft_miss, 90), 3),
                                  "n": len(ttft_miss)}
    if ns.spec_decode:
        report["spec_accept_rate"] = round(engine.spec_accept_rate(), 4)
    print(json.dumps(report, indent=2))
    if ns.json:
        with open(ns.json, "w") as f:
            json.dump(report, f, indent=2)
    return 0


# ---------------------------------------------------------------------------
# importable A/B legs (bench.py / tools/bench_gate.py)
# ---------------------------------------------------------------------------


def _leg_engine(prefix_cache, spec_decode, *, seed=0, max_new=24,
                hidden=128, layers=2, max_pos=256, max_seq=192,
                warm_buckets=None):
    """One small single-device engine for the A/B legs (CPU-runnable; on
    TPU the same shapes measure the real dispatch path)."""
    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.serving.engine import ServingEngine

    cfg = ModelArgs(
        hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=4, vocab_size=512,
        max_position_embeddings=max_pos, seq_length=128,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1)
    params, _ = init_causal_lm(jax.random.key(seed), cfg)
    sv = ServingArgs(max_batch_size=4, kv_block_size=8,
                     max_seq_len=max_seq,
                     max_new_tokens=max_new, prefix_cache=prefix_cache,
                     spec_decode=spec_decode, spec_k=4)
    dtype = (jnp.float32 if jax.devices()[0].platform == "cpu"
             else jnp.bfloat16)
    eng = ServingEngine(params, cfg, sv, compute_dtype=dtype)
    eng.warmup(buckets=warm_buckets)
    return eng, cfg


def run_prefix(on_tpu: bool = False, reps: int = 12):
    """The ``serve_prefix`` bench leg: hit-vs-cold TTFT on a shared-prefix
    trace. Reports ``serve_prefix_ttft_ratio`` = median(hit TTFT) /
    median(cold TTFT) — below 1.0 means the radix cache really skips
    prefill work; regresses UP. The model/prefix are sized so a cold
    prefill is tens of ms on CPU (OS scheduling noise amortizes) and the
    pairs interleave so load spikes land on both sides."""
    import numpy as np

    # only the two buckets the leg exercises get warmed (cold prompts
    # bucket to 512, hit suffixes to 8) — warmup stays seconds, not the
    # full ladder
    eng, cfg = _leg_engine(True, False, max_new=2, hidden=256, layers=4,
                           max_pos=640, max_seq=520,
                           warm_buckets=[8, 512])
    rng = np.random.RandomState(0)
    cold_ms, hit_ms = [], []
    recompiles0 = eng.compile_count()
    try:
        for rep in range(reps):
            sys_p = rng.randint(0, cfg.vocab_size, (496,)).tolist()
            hc = eng.submit(sys_p + [1])
            eng.run_until_idle()
            if hc.status != "done":
                return {"skipped": f"cold request {hc.status}"}
            hh = eng.submit(sys_p + [2])
            eng.run_until_idle()
            if hh.status != "done" or not hh.cached_tokens:
                return {"skipped": "hit request missed the cache"}
            if rep == 0:
                continue  # first pair warms allocator paths; drop it
            cold_ms.append(hc.ttft_s() * 1000.0)
            hit_ms.append(hh.ttft_s() * 1000.0)
        ratio = float(np.median(hit_ms) / np.median(cold_ms))
        return {
            "serve_prefix_ttft_ratio": round(ratio, 4),
            "ttft_cold_ms": round(float(np.median(cold_ms)), 3),
            "ttft_hit_ms": round(float(np.median(hit_ms)), 3),
            "prefix_hit_rate": round(eng.prefix.hit_rate, 4),
            "serve_prefix_recompiles": eng.compile_count() - recompiles0,
            "platform": "tpu" if on_tpu else "cpu",
        }
    finally:
        eng.close()


def run_spec(on_tpu: bool = False, requests: int = 6, iters: int = 5):
    """The ``spec_decode`` bench leg: tokens/sec with speculative decoding
    vs plain decode on the same greedy workload (long continuations, so
    the n-gram draft has cycles to predict). Reports
    ``spec_decode_tokens_ratio`` = spec/plain — above 1.0 means accepted
    drafts outpace the wider verify program; regresses DOWN.

    A/B runs INTERLEAVE (plain, spec, plain, spec, ...) and the ratio is
    taken between per-iteration medians, so a load spike on a shared CPU
    host lands on both sides instead of poisoning one (the
    tp_overlap_bench recipe). Both sides emit the identical greedy
    streams, so the tokens/sec ratio reduces to a wall-time ratio."""
    import time as _time

    import numpy as np

    eng_plain, cfg = _leg_engine(False, False, max_new=64)
    eng_spec, _ = _leg_engine(False, True, max_new=64)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (12,)).tolist()
               for _ in range(requests)]
    recompiles0 = eng_spec.compile_count()
    walls = {False: [], True: []}
    toks = {False: 0, True: 0}
    try:
        for it in range(iters + 1):
            for spec, eng in ((False, eng_plain), (True, eng_spec)):
                t0 = _time.monotonic()
                handles = [eng.submit(p) for p in prompts]
                eng.run_until_idle()
                wall = _time.monotonic() - t0
                if not all(h.status == "done" for h in handles):
                    return {"skipped": "a bench request did not complete"}
                if it == 0:
                    continue  # warm allocator/telemetry paths; drop it
                walls[spec].append(wall)
                toks[spec] = sum(len(h.output) for h in handles)
        if toks[False] != toks[True]:
            return {"skipped": "spec stream diverged from plain (token "
                               "counts differ) — losslessness bug"}
        ratio = float(np.median(walls[False]) / np.median(walls[True]))
        return {
            "spec_decode_tokens_ratio": round(ratio, 4),
            "tokens_per_sec_plain": round(
                toks[False] / float(np.median(walls[False])), 2),
            "tokens_per_sec_spec": round(
                toks[True] / float(np.median(walls[True])), 2),
            "spec_accept_rate": round(eng_spec.spec_accept_rate(), 4),
            "spec_decode_recompiles":
                eng_spec.compile_count() - recompiles0,
            "platform": "tpu" if on_tpu else "cpu",
        }
    finally:
        eng_plain.close()
        eng_spec.close()


if __name__ == "__main__":
    sys.exit(main())
