"""Probe whether the axon TPU tunnel is alive, without wedging it.

Writes progress lines to /tmp/tpu_probe.log so the parent can observe how far
init got. NEVER kill this process while it is between 'init:start' and
'init:done' — killing a process inside make_c_api_client wedges the remote
tunnel for hours (see memory: axon-tpu-tunnel-fragility).
"""
import json
import sys
import time

LOG = "/tmp/tpu_probe.log"
OUT = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_probe.json"


def log(msg):
    with open(LOG, "a") as f:
        f.write(f"{time.time():.1f} {msg}\n")
        f.flush()


def main():
    log("probe:start")
    import jax  # noqa: E402  (sitecustomize rewrites jax_platforms to axon,cpu)

    log("import:done")
    log("init:start")
    devs = jax.devices()
    log(f"init:done devices={[str(d) for d in devs]}")
    kinds = [getattr(d, "device_kind", "?") for d in devs]
    log(f"kinds={kinds}")
    # Run one real op end-to-end to prove the data path, not just init.
    x = jax.numpy.ones((256, 256), dtype=jax.numpy.bfloat16)
    y = jax.jit(lambda a: a @ a)(x)
    y.block_until_ready()
    log("matmul:done")
    out = {
        "alive": True,
        "platform": devs[0].platform,
        "device_kind": kinds[0],
        "n_devices": len(devs),
    }
    with open(OUT, "w") as f:
        json.dump(out, f)
    log("probe:done " + json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
