"""Sensitivity sweep over the spec-derived TPU v5e hardware tables.

The v5e tables (profiles/tpu_v5e/hardware/*.json) are estimates from public
specs, not measurements (tools/make_v5e_hw_config.py). Before trusting a
searched plan "for v5e-8", this tool answers: *which of those invented
coefficients does the chosen plan actually depend on?* Each coefficient
family — allreduce bandwidth, p2p bandwidth, overlap coefficient, sp
collective latency — is scaled by 0.5x and 2x (bandwidths scale down when
times scale up and vice versa) while everything else stays at baseline; the
search engine (core/search_engine/engine.py) runs on each variant and the
chosen plan + throughput are recorded.

Output: ``hetu_galvatron_tpu/profiles/tpu_v5e/sensitivity.json`` and a
human-readable ``SENSITIVITY.md`` next to the tables. The committed JSON is
kept in sync by ``tests/search_engine/test_hw_sensitivity.py``, which
re-runs a subset of the sweep and compares.

Reference anchor: the measured-tables workflow this substitutes for is
``galvatron/profile_hardware/hardware_configs/*.json`` (the reference
measures on its 8xA100 node; a single tunneled v5e chip cannot measure ICI).

Run: ``python tools/hw_sensitivity.py`` (CPU-only; ~1-2 min).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
HW = os.path.join(REPO, "hetu_galvatron_tpu", "profiles", "tpu_v5e",
                  "hardware")
FIXTURES = os.path.join(REPO, "tests", "fixtures")

# coefficient family -> (filename, how a "2x better hardware" scale applies)
FAMILIES = {
    # bandwidths: scale values directly (2x = faster links)
    "allreduce_bandwidth": ("allreduce_bandwidth_1nodes_8gpus_per_node.json",
                            "bandwidth"),
    "p2p_bandwidth": ("p2p_bandwidth_1nodes_8gpus_per_node.json",
                      "bandwidth"),
    # times: scale values INVERSELY (2x hardware = half the time)
    "sp_time": ("sp_time_1nodes_8gpus_per_node.json", "time"),
    # dimensionless slowdown of overlapped compute (>= 1.0)
    "overlap_coe": ("overlap_coefficient.json", "overlap"),
}

FACTORS = (0.5, 2.0)


def _scaled_table(path: str, kind: str, factor: float) -> dict:
    with open(path) as f:
        table = json.load(f)
    out = {}
    for k, v in table.items():
        if not isinstance(v, (int, float)):
            out[k] = v
            continue
        if kind == "bandwidth":
            out[k] = v * factor
        elif kind == "time":
            out[k] = v / factor
        else:  # overlap: scale the slowdown margin above 1.0
            out[k] = 1.0 + (v - 1.0) * factor
    return out


def _run_search(tables: dict, out_dir: str):
    """One search-engine run over the given hardware table paths. Model
    time/memory profiles stay pinned to the repo fixtures (llama2-7b) —
    the sweep isolates the HARDWARE coefficients."""
    from hetu_galvatron_tpu.core.args_schema import SearchArgs
    from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine

    sargs = SearchArgs(
        num_nodes=1, num_devices_per_node=8, memory_constraint=36,
        settle_bsz=64, settle_chunks=32, default_dp_type="zero2",
        pipeline_type="pipedream_flush", sequence_parallel=True,
        async_grad_reduce=False, time_profile_mode="sequence",
        memory_profile_mode="sequence", max_tp_deg=8, max_pp_deg=4,
        time_profiling_path=os.path.join(
            FIXTURES, "computation_profiling_bf16_llama2-7b_all.json"),
        memory_profiling_path=os.path.join(
            FIXTURES, "memory_profiling_bf16_llama2-7b_all.json"),
        allreduce_bandwidth_config_path=tables["allreduce_bandwidth"],
        p2p_bandwidth_config_path=tables["p2p_bandwidth"],
        overlap_coe_path=tables["overlap_coe"],
        sp_time_path=tables["sp_time"],
        output_config_path=out_dir)
    eng = SearchEngine(sargs)
    eng.set_model_info(
        [{"hidden_size": 4096, "seq_len": 8192, "layer_num": 28}],
        "llama2-7b")
    eng.initialize()
    throughput = eng.optimize()
    plan_file = [f for f in os.listdir(out_dir)
                 if f.startswith("galvatron_config_")][0]
    with open(os.path.join(out_dir, plan_file)) as f:
        plan = json.load(f)
    return throughput, plan


def plan_signature(plan: dict) -> str:
    """Compact strategy signature for flip detection: pp + the per-layer
    (tp, cp, sdp, ckpt) vectors collapsed to runs + vtp."""
    pp = plan.get("pp_deg")
    vtp = plan.get("vtp", plan.get("embed_sdp"))
    keys = ["tp_sizes_enc", "use_sp", "checkpoint", "fsdp_type"]
    parts = [f"pp{pp}", f"vtp{vtp}"]
    for k in keys:
        v = plan.get(k)
        if isinstance(v, str):
            toks = v.split(",")
            runs = []
            for t in toks:
                if runs and runs[-1][0] == t:
                    runs[-1][1] += 1
                else:
                    runs.append([t, 1])
            parts.append(k + "=" + ",".join(f"{t}x{n}" for t, n in runs))
    return " ".join(parts)


def run_sweep(factors=FACTORS, families=None) -> dict:
    baseline_paths = {name: os.path.join(HW, fn)
                      for name, (fn, _) in FAMILIES.items()}
    results = {"model": "llama2-7b fixtures over v5e-8 hw tables",
               "factors": list(factors), "runs": []}

    def one(label, tables):
        with tempfile.TemporaryDirectory() as out:
            thr, plan = _run_search(tables, out)
        sig = plan_signature(plan)
        results["runs"].append({"label": label, "throughput": round(thr, 4),
                                "signature": sig})
        print(f"  {label:34s} throughput {thr:8.4f}  {sig}",
              file=sys.stderr)
        return sig

    print("hw sensitivity sweep (baseline + ±2x per family):",
          file=sys.stderr)
    base_sig = one("baseline", baseline_paths)
    for name, (fn, kind) in (families or FAMILIES).items():
        for factor in factors:
            with tempfile.TemporaryDirectory() as tdir:
                scaled = _scaled_table(os.path.join(HW, fn), kind, factor)
                spath = os.path.join(tdir, fn)
                with open(spath, "w") as f:
                    json.dump(scaled, f)
                tables = dict(baseline_paths, **{name: spath})
                one(f"{name} x{factor}", tables)
    flips = [r["label"] for r in results["runs"]
             if r["signature"] != base_sig]
    results["baseline_signature"] = base_sig
    results["flipped"] = flips
    return results


def write_docs(results: dict) -> None:
    out_json = os.path.join(HW, os.pardir, "sensitivity.json")
    with open(out_json, "w") as f:
        json.dump(results, f, indent=2)
    lines = [
        "# Hardware-table sensitivity (spec-derived v5e-8 coefficients)",
        "",
        "The hardware tables in `hardware/` are estimates from public specs",
        "(`tools/make_v5e_hw_config.py`), not measurements. This sweep re-runs",
        "the search engine (llama2-7b profile fixtures, bsz 64, 36 GB HBM",
        "budget) with each coefficient family scaled to 0.5x and 2x of its",
        "estimated value, and records whether the chosen plan changes.",
        "",
        "Regenerate with `python tools/hw_sensitivity.py`;",
        "`tests/search_engine/test_hw_sensitivity.py` keeps this file in",
        "sync with the search engine.",
        "",
        f"Baseline plan: `{results['baseline_signature']}`",
        "",
        "| run | throughput | plan |",
        "|---|---|---|",
    ]
    base = results["baseline_signature"]
    for r in results["runs"]:
        mark = "**flips**" if r["signature"] != base else "same plan"
        lines.append(f"| {r['label']} | {r['throughput']} | {mark}: "
                     f"`{r['signature']}` |")
    lines += [
        "",
        "Reading: coefficient families whose ±2x runs keep the same plan do",
        "not gate the current searched plan, so their estimation error is",
        "harmless for plan CHOICE (throughput predictions still shift).",
        "Families listed under `flipped` in `sensitivity.json` are the ones",
        "worth measuring on real multi-chip hardware first",
        "(`cli/profiler mode=profile_hardware`).",
        "",
    ]
    out_md = os.path.join(HW, os.pardir, "SENSITIVITY.md")
    with open(out_md, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {os.path.normpath(out_json)} and "
          f"{os.path.normpath(out_md)}", file=sys.stderr)


if __name__ == "__main__":
    write_docs(run_sweep())
