"""Config spine tests (parity with reference tests/test_arguments.py:
YAML + override round-trips through the validated schema)."""

import pytest

from hetu_galvatron_tpu.core.arguments import load_config, parse_overrides, args_from_cli

pytestmark = pytest.mark.utils


def test_defaults():
    args = load_config()
    assert args.model.hidden_size == 768
    assert args.parallel.mixed_precision == "bf16"
    assert args.mode == "train_dist"


def test_yaml_and_overrides(tmp_path):
    cfg = tmp_path / "m.yaml"
    cfg.write_text(
        "model:\n  hidden_size: 1024\n  num_hidden_layers: 4\n"
        "parallel:\n  global_tp_deg: 2\n"
    )
    args = load_config(str(cfg), ["model.hidden_size=2048", "++parallel.pp_deg=2"])
    assert args.model.hidden_size == 2048  # override wins over yaml
    assert args.model.num_hidden_layers == 4
    assert args.parallel.global_tp_deg == 2
    assert args.parallel.pp_deg == 2


def test_include_composition(tmp_path):
    (tmp_path / "base.yaml").write_text("model:\n  vocab_size: 32000\n  hidden_size: 64\n")
    child = tmp_path / "child.yaml"
    child.write_text("include: base.yaml\nmodel:\n  hidden_size: 128\n")
    args = load_config(str(child))
    assert args.model.vocab_size == 32000
    assert args.model.hidden_size == 128


def test_override_types():
    t = parse_overrides(["a.b=8", "a.c=true", "a.d=1e-4", "a.e=hello"])
    assert t == {"a": {"b": 8, "c": True, "d": 1e-4, "e": "hello"}}


def test_invalid_value_rejected():
    with pytest.raises(Exception):
        load_config({"parallel": {"mixed_precision": "fp64"}})


def test_cli_convention(tmp_path):
    cfg = tmp_path / "c.yaml"
    cfg.write_text("model:\n  hidden_size: 256\n")
    args = args_from_cli([str(cfg), "train.lr=0.5"], mode="train_dist")
    assert args.model.hidden_size == 256 and args.train.lr == 0.5


def test_derived_model_fields():
    args = load_config({"model": {"hidden_size": 512, "num_attention_heads": 8,
                                  "vocab_size": 50257}})
    assert args.model.head_dim == 64
    assert args.model.padded_vocab_size % 128 == 0
    assert args.model.kv_heads == 8


def test_negative_hier_bucket_mb_rejected():
    """parallel.hier_bucket_mb < 0 is a config error: the auto-sweep
    convention is search-side only (search.hier_bucket_mb < 0) — a truthy
    negative runtime value would silently override a plan's recorded
    bucket size into the monolithic schedule."""
    with pytest.raises(Exception, match="hier_bucket_mb"):
        load_config({"parallel": {"hier_bucket_mb": -1.0}})
    # the search-side auto mode stays accepted
    assert load_config(
        {"search": {"hier_bucket_mb": -1.0}}).search.hier_bucket_mb == -1.0
