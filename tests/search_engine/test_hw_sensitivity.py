"""Hardware-table sensitivity (VERDICT r3/r4: the v5e tables are
spec-derived estimates — the search's plan choice must be characterized
against their error). tools/hw_sensitivity.py sweeps each coefficient
family ±2x; this test re-runs a subset and keeps the committed
profiles/tpu_v5e/sensitivity.json in sync with the live search engine."""

import json
import os

import pytest

pytestmark = pytest.mark.search_engine

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
SENS = os.path.join(REPO, "hetu_galvatron_tpu", "profiles", "tpu_v5e",
                    "sensitivity.json")


def _recorded():
    with open(SENS) as f:
        return json.load(f)


def test_sensitivity_doc_exists_and_covers_all_families():
    rec = _recorded()
    labels = {r["label"] for r in rec["runs"]}
    assert "baseline" in labels
    for fam in ("allreduce_bandwidth", "p2p_bandwidth", "sp_time",
                "overlap_coe"):
        for f in rec["factors"]:
            assert f"{fam} x{f}" in labels, f"{fam} x{f} missing from sweep"
    # the sweep must have found at least one coefficient the plan depends
    # on — a sweep reporting total insensitivity would mean the signature
    # is too coarse to detect flips
    assert rec["flipped"], "no perturbation flips the plan; check signature"


@pytest.mark.slow
def test_sweep_matches_committed_doc():
    """Re-run the baseline plus one flipping and one non-flipping
    perturbation; signatures must match the committed sensitivity.json
    (stale doc after a search-engine or table change fails here)."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import hw_sensitivity as hs

    rec = _recorded()
    by_label = {r["label"]: r for r in rec["runs"]}
    fresh = hs.run_sweep(
        factors=(0.5,),
        families={"allreduce_bandwidth": hs.FAMILIES["allreduce_bandwidth"],
                  "p2p_bandwidth": hs.FAMILIES["p2p_bandwidth"]})
    fresh_by = {r["label"]: r for r in fresh["runs"]}
    for label in ("baseline", "allreduce_bandwidth x0.5",
                  "p2p_bandwidth x0.5"):
        assert fresh_by[label]["signature"] == by_label[label]["signature"], (
            f"{label}: sensitivity.json is stale — regenerate with "
            "python tools/hw_sensitivity.py")
    # the recorded flip structure still holds on the fresh run
    assert (fresh_by["allreduce_bandwidth x0.5"]["signature"]
            != fresh_by["baseline"]["signature"])
    assert (fresh_by["p2p_bandwidth x0.5"]["signature"]
            == fresh_by["baseline"]["signature"])
