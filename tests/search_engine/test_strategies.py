"""Strategy enumeration/filtering units (reference test_generate_strategies)."""

import pytest

from hetu_galvatron_tpu.core.search_engine.strategies import (
    SearchSpaceLimits,
    SearchStrategy,
    enumerate_strategies,
    pp_division_even,
)
from hetu_galvatron_tpu.utils.strategy import DPType

pytestmark = pytest.mark.search_engine


def test_enumeration_power_of_two_and_world():
    layer, vocab = enumerate_strategies(8, 28, SearchSpaceLimits())
    assert layer and vocab
    for s in layer:
        assert s.world == 8
        assert s.cp == 1  # disabled by default
        assert not (s.tp > 1 and s.sp > 1)
        if s.dp == 1:
            assert s.dp_type == DPType.DDP
    # sorted + deduped
    keys = [s.sort_key() for s in layer]
    assert keys == sorted(keys)
    assert len(keys) == len(set(keys))
    # vocab variants have no checkpoint dim
    assert all(not v.checkpoint for v in vocab)


def test_default_dp_type_changes_candidates():
    layer_ddp, _ = enumerate_strategies(8, 28, SearchSpaceLimits(), "ddp")
    layer_z2, _ = enumerate_strategies(8, 28, SearchSpaceLimits(), "zero2")
    assert any(s.dp_type == DPType.DDP and s.dp > 1 for s in layer_ddp)
    assert not any(s.dp_type == DPType.DDP and s.dp > 1 for s in layer_z2)
    assert any(s.dp_type == DPType.ZERO2 for s in layer_z2)


def test_filters():
    lim = SearchSpaceLimits(disable_pp=1, disable_ckpt=1, disable_fsdp=1)
    layer, _ = enumerate_strategies(8, 28, lim)
    assert all(s.pp == 1 and not s.checkpoint and s.dp_type != DPType.ZERO3
               for s in layer)


def test_simple_string():
    s = SearchStrategy(pp=1, tp=4, dp=2, dp_type=DPType.ZERO3, checkpoint=True)
    assert s.simple_string() == "1-4*-2f-c"
    u = SearchStrategy(pp=2, sp=4, dp=1)
    assert u.simple_string() == "2-4*-1-sp"


def test_pp_division_even():
    assert pp_division_even([28], 4) == [7, 7, 7, 7]
    assert pp_division_even([30], 4) == [7, 7, 7, 9]


def test_to_runtime_roundtrip():
    s = SearchStrategy(pp=2, sp=4, dp=1, checkpoint=True)
    r = s.to_runtime()
    assert r.tp_size == 4 and r.sp and r.pp_deg == 2 and r.checkpoint
