"""Decomposed per-layer cost components (cost_model.layer_time_components):
the audit's predicted side must stay glued to the same primitives the
search prices with (_tp_terms, the dp/cp/pp message arithmetic), so the
calibration table can never audit a different model than the one that
picked the plan."""

import numpy as np
import pytest

from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    _tp_terms,
    layer_time_components,
)
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy
from hetu_galvatron_tpu.utils.strategy import DPType

pytestmark = pytest.mark.search_engine


def _latency_table(per_mb=0.01):
    table = {mb: per_mb * mb for mb in (1, 2, 4, 8, 16, 32, 64, 128)}
    table["popt"] = np.array([per_mb, 0.0])
    return table


def _ctx(**kw):
    base = dict(
        parameter_size=48.0, seq_length=128, hidden_size=256, layer_num=4,
        mixed_precision=True, forward_computation_time=0.05,
        comm_coe_dict={"8_1": 0.01, "8_0": 0.01, "4_1": 0.01, "4_0": 0.01,
                       "2_1": 0.01, "2_0": 0.01, "1": 0.0, "1_1": 0.0},
        allgather_latency={2: _latency_table(), 4: _latency_table(),
                           8: _latency_table()},
        all2all_latency={2: _latency_table(), 4: _latency_table(),
                         8: _latency_table()},
        p2p_comm_coe_dict={2: 0.02, 4: 0.02},
    )
    base.update(kw)
    return CostContext(**base)


def test_components_track_tp_terms_and_sum():
    ctx = _ctx()
    s = SearchStrategy(pp=1, tp=2, dp=4)
    comp = layer_time_components(s, ctx, 64, 1)
    fct, bct, tp_time = _tp_terms(s, ctx, 64, 1)
    scale = ctx.costmodel_coe / ctx.layer_num
    assert comp["fct_ms"] == pytest.approx(fct * scale)
    assert comp["bct_ms"] == pytest.approx(bct * scale)
    assert comp["tp_ms"] == pytest.approx(tp_time * scale)
    assert comp["dp_ms"] > 0       # sdp=4 gradient sync
    assert comp["cp_ms"] == 0.0 and comp["pp_ms"] == 0.0
    assert comp["total_ms"] == pytest.approx(
        sum(v for k, v in comp.items() if k != "total_ms"))


def test_dp_component_zero_without_replicas_and_zero3_premium():
    ctx = _ctx()
    assert layer_time_components(
        SearchStrategy(pp=1, tp=8, dp=1), ctx, 64, 1)["dp_ms"] == 0.0
    ddp = layer_time_components(
        SearchStrategy(pp=1, tp=2, dp=4), ctx, 64, 1)["dp_ms"]
    z3 = layer_time_components(
        SearchStrategy(pp=1, tp=2, dp=4, dp_type=DPType.ZERO3),
        ctx, 64, 1)["dp_ms"]
    # ZeRO-3 re-gathers params in the backward: +50% on the same message
    assert z3 == pytest.approx(1.5 * ddp)
    # full precision doubles the gradient payload
    full = layer_time_components(
        SearchStrategy(pp=1, tp=2, dp=4), _ctx(mixed_precision=False),
        64, 1)["dp_ms"]
    assert full == pytest.approx(2 * ddp)


def test_dp_ring_not_charged_for_dp1_replica_groups():
    """A dp==1 plan whose sdp > 1 via cp replicas pays no gradient ring in
    layer_time_cost's folded branches (both overlap() calls gate on dp>1)
    — the decomposition must not invent one; under ZeRO-3 only the
    all-gather premium survives."""
    ctx = _ctx()
    s = SearchStrategy(pp=1, tp=2, cp=2, dp=1)
    assert s.sdp == 2
    assert layer_time_components(s, ctx, 64, 1)["dp_ms"] == 0.0
    z3 = layer_time_components(
        SearchStrategy(pp=1, tp=2, cp=2, dp=1, dp_type=DPType.ZERO3),
        ctx, 64, 1)["dp_ms"]
    param_mb = ctx.parameter_size / s.tp
    msg = 2 * (s.sdp - 1) * (param_mb / s.sdp) * ctx.layer_num / 2  # bf16
    scale = ctx.costmodel_coe / ctx.layer_num
    assert z3 == pytest.approx(0.5 * msg * ctx.comm_coe_dict["2_0"] * scale)


def test_pp_and_checkpoint_components():
    ctx = _ctx()
    pp = layer_time_components(
        SearchStrategy(pp=2, tp=2, dp=2), ctx, 64, 2)
    assert pp["pp_ms"] > 0
    # without a p2p profile the pp term is unpriceable, not invented
    no_p2p = layer_time_components(
        SearchStrategy(pp=2, tp=2, dp=2), _ctx(p2p_comm_coe_dict=None),
        64, 2)
    assert no_p2p["pp_ms"] == 0.0
    # remat: backward recomputes the forward (bct += fct) and replays its
    # collectives (1.5x tp messages)
    base = layer_time_components(SearchStrategy(pp=1, tp=2, dp=4),
                                 ctx, 64, 1)
    ck = layer_time_components(
        SearchStrategy(pp=1, tp=2, dp=4, checkpoint=True), ctx, 64, 1)
    assert ck["bct_ms"] == pytest.approx(base["bct_ms"] + base["fct_ms"])
    assert ck["tp_ms"] == pytest.approx(1.5 * base["tp_ms"])


def test_alpha_beta_prices_tp_component():
    """With fitted pairs the tp component is priced on the α-β curve —
    the same number predicted_comm_per_step audits against."""
    s = SearchStrategy(pp=1, tp=2, dp=4)
    legacy = layer_time_components(s, _ctx(), 64, 1)["tp_ms"]
    ab = layer_time_components(
        s, _ctx(tp_alpha_beta={"2_1": (0.5, 100.0)}), 64, 1)["tp_ms"]
    # a fat α must make the fitted price exceed the pure-bandwidth table
    assert ab > legacy
