"""Golden-value regression of the full search flow, mirroring the reference's
tests/search_engine/test_parallelsim_optimization.py:10-50: same synthetic
profiling fixtures (tests/fixtures/*.json), same expected throughput and plan
for the llama-search task (seq 8192, settle_bsz 64, 36 GB, zero2 default,
pipedream_flush) in fine-grained and coarse modes."""

import glob
import json
import os

import pytest

from hetu_galvatron_tpu.core.args_schema import SearchArgs
from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
from hetu_galvatron_tpu.utils.strategy import DPType, config2strategy

pytestmark = pytest.mark.search_engine

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")

GOLDEN_FINE = 2.6485091403918064
GOLDEN_COARSE = 2.5246283459057333


def _make_engine(tmp_path, *, settle_chunks, fine_grained):
    args = SearchArgs(
        num_nodes=1, num_devices_per_node=8, memory_constraint=36,
        settle_bsz=64, settle_chunks=settle_chunks,
        default_dp_type="zero2", pipeline_type="pipedream_flush",
        fine_grained_mode=fine_grained, sequence_parallel=True,
        async_grad_reduce=False, mixed_precision="bf16",
        time_profile_mode="sequence", memory_profile_mode="sequence",
        time_profiling_path=os.path.join(
            FIXTURES, "computation_profiling_bf16_llama2-7b_all.json"),
        memory_profiling_path=os.path.join(
            FIXTURES, "memory_profiling_bf16_llama2-7b_all.json"),
        allreduce_bandwidth_config_path=os.path.join(
            FIXTURES, "allreduce_bandwidth_1nodes_8gpus_per_node.json"),
        p2p_bandwidth_config_path=os.path.join(
            FIXTURES, "p2p_bandwidth_1nodes_8gpus_per_node.json"),
        overlap_coe_path=os.path.join(FIXTURES, "overlap_coefficient.json"),
        sp_time_path=os.path.join(
            FIXTURES, "sp_time_1nodes_8gpus_per_node.json"),
        output_config_path=str(tmp_path),
    )
    eng = SearchEngine(args)
    eng.set_model_info(
        [{"hidden_size": 4096, "seq_len": 8192, "layer_num": 28}],
        "llama2-7b")
    eng.initialize()
    return eng


def _simple_strings(cfg):
    """Render the plan the way the reference golden test does
    (to_simple_string: pp-tpsp[*]-dp[f][-c])."""
    layers, _, _ = config2strategy(cfg, world_size=8)
    out = []
    for s in layers:
        txt = f"{s.pp_deg}-"
        txt += f"{s.tp_size}*-" if s.tp_size != 1 else f"{s.tp_size}-"
        txt += f"{s.dp_size}f" if s.dp_type == DPType.ZERO3 else f"{s.dp_size}"
        if s.checkpoint:
            txt += "-c"
        if s.sp:
            txt += "-sp"
        out.append(txt)
    return out


def test_fine_grained_golden(tmp_path):
    eng = _make_engine(tmp_path, settle_chunks=32, fine_grained=1)
    throughput = eng.optimize()
    assert abs(throughput - GOLDEN_FINE) < 1e-6, throughput

    files = glob.glob(os.path.join(str(tmp_path), "*.json"))
    assert len(files) == 1
    assert os.path.basename(files[0]).startswith("galvatron_config_")
    cfg = json.load(open(files[0]))
    for key in ["pp_deg", "tp_sizes_enc", "tp_consecutive_flags",
                "dp_types_enc", "use_sp", "checkpoint", "global_bsz",
                "chunks", "pp_division", "pipeline_type", "default_dp_type",
                "vtp", "vsp"]:
        assert key in cfg, key
    assert cfg["pp_deg"] == 1
    assert cfg["global_bsz"] == 64
    assert cfg["chunks"] == 32
    assert cfg["pp_division"] == "28"
    assert cfg["pipeline_type"] == "pipedream_flush"
    assert cfg["default_dp_type"] == "zero2"
    assert cfg["vtp"] == 8
    assert cfg["vsp"] == 0
    assert cfg["embed_sdp"] == 0

    got = _simple_strings(cfg)
    expect = (["1-4*-2f-c"] * 14) + (["1-4*-2f"] * 12) + (["1-4*-2"] * 2)
    assert got == expect, got

    # the plan-regret sentinel's inputs ride along in the same file: the
    # winner's priced step time and the deduped top-k runner-ups, each in
    # the stored-strategy shape cost_model.reprice_stored_plan_ms prices.
    # config2strategy ignores both keys, so config_mode=json loads are
    # unaffected (the golden layer strings above already proved that)
    assert cfg["predicted_time_cost_ms"] == pytest.approx(24164.538105)
    rups = cfg["runner_ups"]
    assert len(rups) == 3  # search.runner_up_k default
    for r in rups:
        assert r["throughput"] < GOLDEN_FINE
        assert r["time_cost_ms"] > cfg["predicted_time_cost_ms"]
        assert r["bsz"] == 64
        assert r["pp"] >= 1
        assert r["strategies"]
        assert all(set(lay) == {"tp", "dp", "cp", "sp", "ckpt", "consec"}
                   for lay in r["layers"])
    # ranked best-first, distinct plans
    assert [r["throughput"] for r in rups] == sorted(
        (r["throughput"] for r in rups), reverse=True)
    assert len({json.dumps(r["layers"]) + str(r["pp"]) for r in rups}) == 3


def test_coarse_golden(tmp_path):
    eng = _make_engine(tmp_path, settle_chunks=8, fine_grained=0)
    throughput = eng.optimize()
    assert abs(throughput - GOLDEN_COARSE) < 1e-6, throughput

    files = glob.glob(os.path.join(str(tmp_path), "*.json"))
    assert len(files) == 1
    cfg = json.load(open(files[0]))
    assert cfg["pp_deg"] == 1
    assert cfg["chunks"] == 8
    assert cfg["vtp"] == 1
    assert cfg["vsp"] == 0
    assert cfg["embed_sdp"] == 1
    got = _simple_strings(cfg)
    assert got == ["1-1-8f-c"] * 28, got


@pytest.mark.slow
def test_numpy_fallback_matches_cpp(tmp_path):
    """The pure-python DP must agree with the C++ core exactly."""
    eng = _make_engine(tmp_path, settle_chunks=32, fine_grained=1)
    eng.args.use_cpp_core = False
    throughput = eng.optimize()
    assert abs(throughput - GOLDEN_FINE) < 1e-6, throughput


def test_parallel_search_matches_sequential(tmp_path):
    """parallel_search=True explores the same task grid and returns the
    identical optimum (reference's thread-pool mode)."""
    eng = _make_engine(tmp_path, settle_chunks=32, fine_grained=1)
    eng.args.parallel_search = True
    throughput = eng.optimize()
    assert abs(throughput - GOLDEN_FINE) < 1e-6, throughput
