"""The collective compiler's search hook (cost_model.cost.
dp_schedule_rankings / dp_schedule_choice): the schedule space is priced
from the profiled per-algorithm ring fits, the winner flips with the
gradient payload (trees ONLY at small sizes), legacy profiles price
nothing (the golden-search byte-identity hinges on it), and the chosen
name round-trips through the plan JSON into the runtime config."""

import pytest

from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    dp_schedule_choice,
    dp_schedule_rankings,
)
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy
from hetu_galvatron_tpu.utils.strategy import (
    DPType,
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    config2strategy,
    strategy_list2config,
)

pytestmark = [pytest.mark.search_engine, pytest.mark.collectives]

ALGOS = {"8_1": {"ring_ici": (0.05, 10.0)},
         "4_1": {"ring_ici": (0.04, 8.0)},
         "2_0": {"ring_dcn": (0.5, 1.0)}}
DP8 = SearchStrategy(pp=1, tp=1, dp=8)


def _ctx(**kw):
    base = dict(parameter_size=48.0, layer_num=4, mixed_precision=True,
                hier_dp=True, dcn_slices=1, alpha_beta_algos=ALGOS)
    base.update(kw)
    return CostContext(**base)


def test_rankings_price_the_whole_space():
    ranks = dp_schedule_rankings(DP8, _ctx(), 8.0)
    assert set(ranks) >= {"ring", "tree_hd", "tree_bcast", "torus2d"}
    assert all(v > 0 for v in ranks.values())


def test_choice_flips_with_gradient_size():
    """The pinned plan flip: ONLY at small gradient payloads does a
    latency-optimal tree win; at bulk the bandwidth-optimal ring/torus
    must take it back."""
    ctx = _ctx()
    small, _ = dp_schedule_choice(DP8, ctx, 0.0005)
    bulk, ranks = dp_schedule_choice(DP8, ctx, 64.0)
    assert small in ("tree_hd", "tree_bcast")
    assert bulk in ("ring", "torus2d")
    # the rankings carry every priced family for the plan record
    assert set(ranks) >= {"ring", "tree_hd", "tree_bcast", "torus2d"}


def test_legacy_profile_prices_nothing():
    """No per-algorithm curves (legacy profile) -> no rankings, no
    choice, no plan-JSON key — the golden searches stay byte-identical."""
    assert dp_schedule_rankings(DP8, _ctx(alpha_beta_algos={}), 8.0) == {}
    assert dp_schedule_choice(DP8, _ctx(alpha_beta_algos={}), 8.0) is None


def test_ineligible_strategies_price_nothing():
    ctx = _ctx()
    assert dp_schedule_rankings(
        SearchStrategy(pp=1, tp=8, dp=1), ctx, 8.0) == {}
    assert dp_schedule_rankings(
        DP8, _ctx(hier_dp=False), 8.0) == {}


def test_hier_split_uses_dcn_curves():
    """With a 2-slice seam the space includes hier_rings and prices over
    both link classes (the dcn ring fit at the cross size)."""
    ranks = dp_schedule_rankings(DP8, _ctx(dcn_slices=2), 8.0)
    assert "hier_rings" in ranks and "ring" in ranks


# ---------------------------------------------------------------------------
# plan JSON round-trip
# ---------------------------------------------------------------------------


def _layers(dp=8, n=2):
    return [LayerStrategy(pp_deg=1, tp_size=1, dp_size=dp, cp_size=1,
                          dp_type=DPType.from_name("ddp"))
            for _ in range(n)]


def test_dp_schedule_round_trips_through_plan_json():
    cfg = strategy_list2config(
        _layers(), global_bsz=16, chunks=2,
        vocab=EmbeddingLMHeadStrategy(vtp=1), pp_division=[2],
        hier_dp=True, dp_schedule="tree_hd")
    assert cfg["dp_schedule"] == "tree_hd"
    _, _, extras = config2strategy(cfg, world_size=8)
    assert extras["dp_schedule"] == "tree_hd"


def test_dp_schedule_absent_without_hier_dp():
    """A schedule name without the hierarchical path is meaningless —
    the serializer must not write one."""
    cfg = strategy_list2config(
        _layers(), global_bsz=16, chunks=2,
        vocab=EmbeddingLMHeadStrategy(vtp=1), pp_division=[2],
        dp_schedule="tree_hd")
    assert "dp_schedule" not in cfg
    _, _, extras = config2strategy(cfg, world_size=8)
    assert extras.get("dp_schedule") is None
