"""Latency-aware (α-β) TP collective pricing + overlapped-TP discount in
the cost model: the α term must be able to flip a tp choice the pure
bandwidth model gets wrong, the overlap discount must flip a choice toward
tp, and the 0-α / 0-discount defaults must leave every existing cost
byte-identical (the golden search regressions pin the full-plan version of
that property against the legacy fixtures)."""

import os

import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import SearchArgs
from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    layer_time_cost,
    tp_overlap_expressible,
    tp_overlap_hidden_frac,
)
from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy

pytestmark = [pytest.mark.search_engine, pytest.mark.tp_overlap]

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")


def _latency_table(per_mb=0.01):
    """Pure-bandwidth measured table: time strictly proportional to size
    (what the legacy sp_time fixtures encode for fat messages)."""
    table = {mb: per_mb * mb for mb in (1, 2, 4, 8, 16, 32, 64, 128)}
    table["popt"] = np.array([per_mb, 0.0])
    return table


def _ctx(**kw):
    base = dict(
        parameter_size=48.0, seq_length=128, hidden_size=256, layer_num=4,
        mixed_precision=True,
        forward_computation_time=0.05,
        comm_coe_dict={"8_1": 0.01, "8_0": 0.01, "4_1": 0.01, "4_0": 0.01,
                       "2_1": 0.01, "2_0": 0.01, "1": 0.0, "1_1": 0.0},
        dp_overlap_coe=1.1, bct_overlap_coe=1.1,
        allgather_latency={2: _latency_table(), 4: _latency_table(),
                           8: _latency_table()},
        all2all_latency={2: _latency_table(), 4: _latency_table(),
                         8: _latency_table()},
    )
    base.update(kw)
    return CostContext(**base)


def _cost(s, ctx, gbsz=64, chunks=1):
    return layer_time_cost(s, ctx, gbsz, chunks)[0]


TP2 = SearchStrategy(pp=1, tp=2, dp=4)
TP4 = SearchStrategy(pp=1, tp=4, dp=2)


def test_alpha_term_flips_tp_choice():
    """With expensive dp grad sync, bandwidth-only pricing favours tp4
    (its dp=2 sync is cheap and its messages ride the same ms/MB slope);
    the fitted α GROWS with the ring size (more hops per collective —
    exactly what the per-group-size pairs capture), so the latency term
    punishes tp4's 6 collectives harder and flips the choice to tp2."""
    coe = {"8_1": 0.1, "8_0": 0.1, "4_1": 0.1, "4_0": 0.1,
           "2_1": 0.1, "2_0": 0.1, "1": 0.0, "1_1": 0.0}
    ctx = _ctx(comm_coe_dict=coe)
    assert _cost(TP4, ctx) < _cost(TP2, ctx)

    # β matches the tables' slope (allreduce = 2x the ag-equivalent rate),
    # α grows with group size
    ab = {"2_1": (0.2, 50.0), "4_1": (2.0, 50.0), "8_1": (4.0, 50.0)}
    ctx_a = _ctx(comm_coe_dict=coe, tp_alpha_beta=ab)
    assert _cost(TP2, ctx_a) < _cost(TP4, ctx_a)


def test_alpha_beta_zero_alpha_matches_tables():
    """α = 0 with β matching the measured slope reproduces the legacy
    table lookup exactly (the ag-equivalent is half the allreduce curve:
    0.5 * mb / 50 == 0.01 * mb)."""
    ctx = _ctx()
    ctx_ab = _ctx(tp_alpha_beta={"2_1": (0.0, 50.0), "4_1": (0.0, 50.0)})
    for s in (TP2, TP4):
        assert _cost(s, ctx_ab) == pytest.approx(_cost(s, ctx), rel=1e-12)


def test_overlap_discount_flips_choice_toward_tp():
    """A dp-only plan beats tp2 when TP comm is priced serial; with the
    overlap discount (the decomposed matmuls hide the collectives under
    compute) the tp2 plan wins."""
    dp8 = SearchStrategy(pp=1, tp=1, dp=8)
    ctx = _ctx(comm_coe_dict={"8_1": 0.003, "8_0": 0.003, "4_1": 0.003,
                              "4_0": 0.003, "2_1": 0.003, "2_0": 0.003,
                              "1": 0.0, "1_1": 0.0},
               allgather_latency={2: _latency_table(0.005),
                                  4: _latency_table(0.005),
                                  8: _latency_table(0.005)})
    assert _cost(dp8, ctx) < _cost(TP2, ctx)

    ctx_ov = _ctx(comm_coe_dict=ctx.comm_coe_dict,
                  allgather_latency=ctx.allgather_latency, tp_overlap=True)
    assert _cost(TP2, ctx_ov) < _cost(dp8, ctx_ov)
    # the discount never touches tp=1 plans
    assert _cost(dp8, ctx_ov) == _cost(dp8, ctx)


def test_defaults_leave_costs_identical():
    """tp_overlap=False + empty alpha-beta (the defaults) change nothing —
    the invariant that keeps every existing golden plan byte-identical."""
    ctx = _ctx()
    ctx_default = _ctx(tp_alpha_beta={}, tp_overlap=False)
    for s in (TP2, TP4, SearchStrategy(pp=1, tp=1, dp=8),
              SearchStrategy(pp=1, tp=2, dp=4, checkpoint=True),
              SearchStrategy(pp=1, sp=2, tp=1, dp=4)):
        w0, n0 = layer_time_cost(s, ctx, 64, 2)
        w1, n1 = layer_time_cost(s, ctx_default, 64, 2)
        assert (w0, n0) == (w1, n1)


def test_overlap_expressibility_gate():
    ctx = _ctx(tp_overlap=True)
    assert tp_overlap_expressible(TP2, ctx)
    assert not tp_overlap_expressible(SearchStrategy(pp=1, tp=1, dp=8), ctx)
    assert not tp_overlap_expressible(
        SearchStrategy(pp=1, tp=2, cp=2, dp=2), ctx)
    # the compiled pipeline engine hosts the rings too (de-vmapped stage
    # axis): pp > 1 under schedule_impl=compiled keeps the discount, so
    # the overlap hiding and the dispatch waiver compose
    ctx_c = _ctx(tp_overlap=True, schedule_impl="compiled")
    assert tp_overlap_expressible(SearchStrategy(pp=1, tp=2, dp=4), ctx_c)
    assert tp_overlap_expressible(SearchStrategy(pp=2, tp=2, dp=2), ctx_c)
    off = _ctx(tp_overlap=False)
    assert not tp_overlap_expressible(TP2, off)


def test_hidden_frac_bounds_and_regimes():
    ctx = _ctx(tp_overlap=True)
    f = tp_overlap_hidden_frac(TP2, ctx, 64, 1)
    assert 0.0 < f <= 1.0
    # compute-bound regime: hidden fraction approaches 2 - overlap_coe
    big_compute = _ctx(tp_overlap=True, forward_computation_time=100.0)
    assert tp_overlap_hidden_frac(TP2, big_compute, 64, 1) == pytest.approx(
        2.0 - 1.1, rel=1e-6)
    # inexpressible -> 0
    assert tp_overlap_hidden_frac(
        SearchStrategy(pp=1, tp=1, dp=8), ctx, 64, 1) == 0.0


def test_engine_threads_alpha_beta_and_overlap(tmp_path):
    """SearchArgs.tp_overlap + the profile's fitted α-β keys flow into
    every layertype's CostContext; the legacy fixture (no α keys) yields
    an empty table."""
    import json
    import shutil

    bw_src = os.path.join(FIXTURES,
                          "allreduce_bandwidth_1nodes_8gpus_per_node.json")
    bw = json.load(open(bw_src))
    bw["allreduce_size_8_consec_1_alpha_ms"] = 0.25
    bw["allreduce_size_8_consec_1_beta_mb_per_ms"] = 320.0
    bw_path = tmp_path / "allreduce_bandwidth.json"
    bw_path.write_text(json.dumps(bw))

    def make(bw_file, tp_overlap):
        args = SearchArgs(
            num_nodes=1, num_devices_per_node=8, memory_constraint=36,
            settle_bsz=64, settle_chunks=8,
            default_dp_type="zero2", pipeline_type="pipedream_flush",
            fine_grained_mode=0, sequence_parallel=True,
            async_grad_reduce=False, mixed_precision="bf16",
            time_profile_mode="sequence", memory_profile_mode="sequence",
            tp_overlap=tp_overlap,
            time_profiling_path=os.path.join(
                FIXTURES, "computation_profiling_bf16_llama2-7b_all.json"),
            memory_profiling_path=os.path.join(
                FIXTURES, "memory_profiling_bf16_llama2-7b_all.json"),
            allreduce_bandwidth_config_path=str(bw_file),
            p2p_bandwidth_config_path=os.path.join(
                FIXTURES, "p2p_bandwidth_1nodes_8gpus_per_node.json"),
            overlap_coe_path=os.path.join(FIXTURES,
                                          "overlap_coefficient.json"),
            sp_time_path=os.path.join(
                FIXTURES, "sp_time_1nodes_8gpus_per_node.json"),
            output_config_path=str(tmp_path),
        )
        eng = SearchEngine(args)
        eng.set_model_info(
            [{"hidden_size": 4096, "seq_len": 8192, "layer_num": 28}],
            "llama2-7b")
        eng.initialize()
        return eng

    eng = make(bw_path, tp_overlap=1)
    for ctx in eng.contexts:
        assert ctx.tp_overlap is True
        assert ctx.tp_alpha_beta == {"8_1": (0.25, 320.0)}

    legacy = make(bw_src, tp_overlap=0)
    for ctx in legacy.contexts:
        assert ctx.tp_overlap is False
        assert ctx.tp_alpha_beta == {}
