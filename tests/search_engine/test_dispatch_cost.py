"""Host-dispatch overhead term in the pipeline cost model: the search
engine's pp choice must price the two pipeline.schedule_impl flavours
differently (the host-sequenced engine pays ~dispatch_us per stage-jit call,
the compiled single-program schedule pays none)."""

import os

import pytest

from hetu_galvatron_tpu.core.args_schema import SearchArgs
from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    pipeline_time_cost,
)
from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy

pytestmark = pytest.mark.search_engine

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")


def _ctx(dispatch_us=0.0, schedule_impl="host"):
    return CostContext(
        parameter_size=48.0, seq_length=1024, hidden_size=4096, layer_num=4,
        mixed_precision=True, pipeline_type="pipedream_flush",
        forward_computation_time=1.0,
        comm_coe_dict={"8_1": 0.2, "4_1": 0.05, "2_1": 0.05, "1_1": 0.0},
        p2p_comm_coe_dict={2: 0.0001},
        dispatch_us=dispatch_us, schedule_impl=schedule_impl,
    )


def _plan_cost(pp, dispatch_us=0.0, schedule_impl="host", chunks=4, gbsz=16):
    s = SearchStrategy(pp=pp, tp=1, dp=8 // pp)
    ctx = _ctx(dispatch_us, schedule_impl)
    partition = [4 // pp] * pp
    return pipeline_time_cost(
        [4], [ctx], [s] * 4, partition, chunks, gbsz, pp, [0.0] * pp)


def test_dispatch_term_is_linear_in_pp_and_chunks():
    base = _plan_cost(pp=2, dispatch_us=0.0)
    loaded = _plan_cost(pp=2, dispatch_us=500.0)
    # 2 (fwd + bwd) dispatches per (stage, microbatch) leg
    assert loaded - base == pytest.approx(500.0 * 1e-6 * 2 * 2 * 4)
    # pp=1 has no pipeline engine, hence no dispatch term
    assert _plan_cost(pp=1, dispatch_us=500.0) == _plan_cost(pp=1)


def test_compiled_schedule_pays_no_dispatch():
    assert _plan_cost(pp=2, dispatch_us=500.0, schedule_impl="compiled") == \
        _plan_cost(pp=2, dispatch_us=0.0, schedule_impl="compiled") == \
        _plan_cost(pp=2, dispatch_us=0.0, schedule_impl="host")


def test_compiled_waiver_only_for_expressible_plans():
    """Plans the compiled engine rejects at runtime (gpipe, uneven stage
    partition, heterogeneous strategies) fall back to the host engine and
    must keep paying dispatch even under schedule_impl=compiled."""
    d = 500.0
    term = d * 1e-6 * 2 * 2 * 4

    def cost(partition, strategies, pipeline_type="pipedream_flush",
             dispatch_us=0.0):
        ctx = _ctx(dispatch_us, "compiled")
        ctx.pipeline_type = pipeline_type
        return pipeline_time_cost([4], [ctx], strategies, partition, 4, 16,
                                  2, [0.0, 0.0])

    uniform = [SearchStrategy(pp=2, tp=1, dp=4)] * 4
    # gpipe cannot compile -> dispatch applies
    assert cost([2, 2], uniform, "gpipe", d) == \
        pytest.approx(cost([2, 2], uniform, "gpipe") + term)
    # uneven stage partition -> dispatch applies
    assert cost([1, 3], uniform, dispatch_us=d) == \
        pytest.approx(cost([1, 3], uniform) + term)
    # heterogeneous per-layer strategies -> dispatch applies
    mixed = uniform[:2] + [SearchStrategy(pp=2, tp=1, dp=4,
                                          checkpoint=True)] * 2
    assert cost([2, 2], mixed, dispatch_us=d) == \
        pytest.approx(cost([2, 2], mixed) + term)
    # the expressible shape keeps the waiver
    assert cost([2, 2], uniform, dispatch_us=d) == cost([2, 2], uniform)
    # cp plans are expressible since the engine de-vmapped its stage axis
    # (the ring-attention kernel runs inside the fused program): no dispatch
    def cp_cost(dispatch_us):
        ctx = _ctx(dispatch_us, "compiled")
        ctx.comm_coe_dict = dict(ctx.comm_coe_dict, **{"2_0": 0.05})
        cp_plan = [SearchStrategy(pp=2, tp=1, cp=2, dp=2)] * 4
        return pipeline_time_cost([4], [ctx], cp_plan, [2, 2], 4, 16, 2,
                                  [0.0, 0.0])

    assert cp_cost(d) == pytest.approx(cp_cost(0.0))


def test_plan_flip_needs_product_of_waiver_and_overlap():
    """The composition pin: a deep-pp tp plan beats the pp=1 alternative
    ONLY when the dispatch waiver (compiled schedule) and the tp_overlap
    discount apply TOGETHER — either effect alone leaves it losing. This is
    the search-side contract of running the ring kernels inside the
    compiled 1F1B program."""
    layers, chunks, gbsz = 4, 4, 16

    def cost(s, pp, *, schedule_impl, overlap, dispatch_us):
        ctx = CostContext(
            parameter_size=48.0, seq_length=1024, hidden_size=4096,
            layer_num=layers, mixed_precision=True,
            pipeline_type="pipedream_flush",
            forward_computation_time=3.0,
            # dp=8 gradient all-reduce priced expensive (the pressure that
            # makes deep pp attractive at all); tp pair fitted mid-range
            comm_coe_dict={"8_1": 1.05, "8_0": 1.05, "4_1": 0.1,
                           "4_0": 0.1, "2_1": 0.05, "2_0": 0.05,
                           "1_1": 0.0},
            p2p_comm_coe_dict={2: 0.0001},
            tp_alpha_beta={"2_1": (0.3, 5.0), "2_0": (0.3, 5.0)},
            tp_overlap=overlap, schedule_impl=schedule_impl,
            dispatch_us=dispatch_us)
        partition = [layers // pp] * pp
        return pipeline_time_cost([layers], [ctx], [s] * layers, partition,
                                  chunks, gbsz, pp, [0.0] * pp)

    deep = SearchStrategy(pp=2, tp=2, dp=2)
    flat = SearchStrategy(pp=1, tp=1, dp=8)
    d = 650.0  # us per stage-jit call

    def delta(schedule_impl, overlap):
        return (cost(deep, 2, schedule_impl=schedule_impl, overlap=overlap,
                     dispatch_us=d)
                - cost(flat, 1, schedule_impl=schedule_impl,
                       overlap=overlap, dispatch_us=d))

    assert delta("host", False) > 0        # baseline: deep pp loses
    assert delta("host", True) > 0         # overlap alone: still loses
    assert delta("compiled", False) > 0    # waiver alone: still loses
    assert delta("compiled", True) < 0     # the product flips the plan


def test_pp_choice_flips_when_dispatch_is_cranked():
    """With cheap intra-stage dp comm at dp=4 vs expensive at dp=8, pp=2
    wins on pure compute/comm — until the host-dispatch overhead term makes
    deep pp pay for its 2 * pp * chunks stage-jit calls."""
    assert _plan_cost(pp=2) < _plan_cost(pp=1)  # pipeline wins undispatched
    crank = 5000.0  # us per call — a slow-dispatch host
    assert _plan_cost(pp=2, dispatch_us=crank) > \
        _plan_cost(pp=1, dispatch_us=crank)  # choice flips to pp=1
    # ...but the compiled schedule keeps the pipeline win at any dispatch
    assert _plan_cost(pp=2, dispatch_us=crank,
                      schedule_impl="compiled") < _plan_cost(pp=1)


def test_search_engine_threads_dispatch_into_contexts(tmp_path):
    """SearchArgs.dispatch_us / pipeline_schedule_impl flow into every
    layertype's CostContext (the values pipeline_time_cost reads)."""
    args = SearchArgs(
        num_nodes=1, num_devices_per_node=8, memory_constraint=36,
        settle_bsz=64, settle_chunks=8,
        default_dp_type="zero2", pipeline_type="pipedream_flush",
        fine_grained_mode=0, sequence_parallel=True,
        async_grad_reduce=False, mixed_precision="bf16",
        time_profile_mode="sequence", memory_profile_mode="sequence",
        dispatch_us=375.0, pipeline_schedule_impl="compiled",
        time_profiling_path=os.path.join(
            FIXTURES, "computation_profiling_bf16_llama2-7b_all.json"),
        memory_profiling_path=os.path.join(
            FIXTURES, "memory_profiling_bf16_llama2-7b_all.json"),
        allreduce_bandwidth_config_path=os.path.join(
            FIXTURES, "allreduce_bandwidth_1nodes_8gpus_per_node.json"),
        p2p_bandwidth_config_path=os.path.join(
            FIXTURES, "p2p_bandwidth_1nodes_8gpus_per_node.json"),
        overlap_coe_path=os.path.join(FIXTURES, "overlap_coefficient.json"),
        sp_time_path=os.path.join(
            FIXTURES, "sp_time_1nodes_8gpus_per_node.json"),
        output_config_path=str(tmp_path),
    )
    eng = SearchEngine(args)
    eng.set_model_info(
        [{"hidden_size": 4096, "seq_len": 8192, "layer_num": 28}],
        "llama2-7b")
    eng.initialize()
    for ctx in eng.contexts:
        assert ctx.dispatch_us == 375.0
        assert ctx.schedule_impl == "compiled"
