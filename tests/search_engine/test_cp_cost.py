"""CP (ring attention) in the search: cost-model terms + planner reachability.

Beyond the reference, which ships context parallelism disabled in the search
(search_engine/args_schema.py:29 disable_cp=1 with no cp term in
layer_cost.py): here cp>1 strategies are priced — compute and activations
shard over the ring, K/V block exchanges are charged per hop — so the
planner can actually choose the runtime's ring attention
(ops/ring_attention.py) for long sequences.
"""

import glob
import json

import numpy as np
import os

import pytest

from hetu_galvatron_tpu.core.args_schema import SearchArgs
from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    layer_memory_cost,
    layer_time_cost,
)
from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy
from hetu_galvatron_tpu.utils.strategy import config2strategy

pytestmark = pytest.mark.search_engine

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")


def _ctx():
    return CostContext(
        parameter_size=48.0, seq_length=32768, hidden_size=4096, layer_num=8,
        forward_computation_time=4.0,
        tp_activation_per_bsz_dict={1: 512.0, 2: 260.0, 4: 132.0, 8: 68.0,
                                    "checkpoint": 28.0},
        comm_coe_dict={"1": 0.0, "1_0": 0.0, "1_1": 0.0,
                       "2_0": 0.0072, "2_1": 0.0065,
                       "4_0": 0.0072, "4_1": 0.0065,
                       "8_0": 0.0072, "8_1": 0.0065, "8": 0.0065},
        dp_overlap_coe=1.1256, bct_overlap_coe=1.1256,
    )


def test_cp_divides_compute_and_activation():
    ctx = _ctx()
    base = SearchStrategy(pp=1, tp=1, sp=1, cp=1, dp=1)
    cp4 = SearchStrategy(pp=1, tp=1, sp=1, cp=4, dp=1)
    t1, _ = layer_time_cost(base, ctx, gbsz=8, chunks=8)
    t4, _ = layer_time_cost(cp4, ctx, gbsz=8, chunks=8)
    # compute shards 4x; ring comm gives some back but must not erase it
    assert t4 < t1
    assert t4 > t1 / 4
    m1 = layer_memory_cost(base, ctx, gbsz=8, chunks=8)
    m4 = layer_memory_cost(cp4, ctx, gbsz=8, chunks=8)
    # activation divides by cp; model states shard over sdp=cp (ZeRO default
    # off here -> states unchanged)
    act1, act4 = m1 - 4 * 48.0, m4 - 4 * 48.0
    assert abs(act4 - act1 / 4) < 1e-6


def test_cp_ring_cost_scales_with_seq():
    short = _ctx()
    short.seq_length = 1024
    long = _ctx()
    cp8 = SearchStrategy(pp=1, tp=1, sp=1, cp=8, dp=1)
    t_short = layer_time_cost(cp8, short, gbsz=8, chunks=8)[0]
    t_long = layer_time_cost(cp8, long, gbsz=8, chunks=8)[0]
    assert t_long > t_short  # ring message grows with the sequence


def test_search_picks_cp_for_long_sequences(tmp_path):
    """Single-sample microbatches (max_dp=1) at long sequence with tp and
    Ulysses disabled: the planner must reach for cp>1 — and the plan must
    load into the runtime config stack."""
    args = SearchArgs(
        num_nodes=1, num_devices_per_node=8, memory_constraint=36,
        settle_bsz=8, settle_chunks=8, default_dp_type="zero2",
        pipeline_type="pipedream_flush", fine_grained_mode=True,
        sequence_parallel=True, async_grad_reduce=False,
        mixed_precision="bf16",
        disable_cp=0, disable_ulysses=1, disable_tp=1, disable_pp=1,
        time_profile_mode="sequence", memory_profile_mode="sequence",
        time_profiling_path=os.path.join(
            FIXTURES, "computation_profiling_bf16_llama2-7b_all.json"),
        memory_profiling_path=os.path.join(
            FIXTURES, "memory_profiling_bf16_llama2-7b_all.json"),
        allreduce_bandwidth_config_path=os.path.join(
            FIXTURES, "allreduce_bandwidth_1nodes_8gpus_per_node.json"),
        p2p_bandwidth_config_path=os.path.join(
            FIXTURES, "p2p_bandwidth_1nodes_8gpus_per_node.json"),
        overlap_coe_path=os.path.join(FIXTURES, "overlap_coefficient.json"),
        sp_time_path=os.path.join(
            FIXTURES, "sp_time_1nodes_8gpus_per_node.json"),
        output_config_path=str(tmp_path),
    )
    eng = SearchEngine(args)
    eng.set_model_info(
        [{"hidden_size": 4096, "seq_len": 32768, "layer_num": 8}],
        "llama-long")
    eng.initialize()
    assert any(s.cp > 1 for s in eng.layer_strategies), \
        "cp strategies must survive enumeration with disable_cp=0"
    throughput = eng.optimize()
    assert throughput > 0
    plan_path = glob.glob(os.path.join(str(tmp_path),
                                       "galvatron_config_*.json"))[0]
    cfg = json.load(open(plan_path))
    layers, _, _ = config2strategy(cfg, world_size=8)
    assert any(s.cp_size > 1 for s in layers), \
        f"expected cp in the plan, got {cfg['cp_sizes_enc']}"


def _tiny_engine(tmp_path, seq=8192):
    args = SearchArgs(
        num_nodes=1, num_devices_per_node=8, memory_constraint=36,
        settle_bsz=16, settle_chunks=4, default_dp_type="zero2",
        pipeline_type="pipedream_flush", fine_grained_mode=True,
        sequence_parallel=True, async_grad_reduce=False,
        mixed_precision="bf16", max_pp_deg=2,
        time_profile_mode="sequence", memory_profile_mode="sequence",
        time_profiling_path=os.path.join(
            FIXTURES, "computation_profiling_bf16_llama2-7b_all.json"),
        memory_profiling_path=os.path.join(
            FIXTURES, "memory_profiling_bf16_llama2-7b_all.json"),
        allreduce_bandwidth_config_path=os.path.join(
            FIXTURES, "allreduce_bandwidth_1nodes_8gpus_per_node.json"),
        p2p_bandwidth_config_path=os.path.join(
            FIXTURES, "p2p_bandwidth_1nodes_8gpus_per_node.json"),
        overlap_coe_path=os.path.join(FIXTURES, "overlap_coefficient.json"),
        sp_time_path=os.path.join(
            FIXTURES, "sp_time_1nodes_8gpus_per_node.json"),
        output_config_path=str(tmp_path))
    eng = SearchEngine(args)
    eng.set_model_info(
        [{"hidden_size": 4096, "seq_len": seq, "layer_num": 8}],
        "llama-tiny")
    eng.initialize()
    return eng


def test_pp_division_balanced_sums_and_covers(tmp_path):
    from hetu_galvatron_tpu.core.cost_model.cost import (
        embed_memory_cost,
        layer_memory_cost,
    )
    from hetu_galvatron_tpu.utils.strategy import DPType

    eng = _tiny_engine(tmp_path)
    div = eng.pp_division_balanced(gbsz=16, chunks=4, pp=2)
    assert sum(div) == 8 and all(d >= 1 for d in div)

    # balanced division's stage-memory imbalance is no worse than even's
    base = SearchStrategy(pp=2, tp=1, sp=1, cp=1, dp=4,
                          dp_type=DPType.ZERO2)
    lmem = layer_memory_cost(base, eng.contexts[0], 16, 4, 0, "gpipe")
    other = embed_memory_cost(base.vocab_variant(), eng.contexts[0], 16, 4,
                              pipeline_type="gpipe")

    def imbalance(d):
        stages = [d[0] * lmem + other[0], d[1] * lmem + other[1]]
        return max(stages) - min(stages)

    assert imbalance(div) <= imbalance([4, 4]) + 1e-6


def test_check_cost_model_rows(tmp_path, capsys):
    eng = _tiny_engine(tmp_path)
    rows = eng.check_cost_model(gbsz=16, chunks=4)
    assert rows, "at least one strategy should evaluate"
    out = capsys.readouterr().out
    assert "check_cost_model[" in out
    for r in rows:
        assert r["time"] > 0 and np.isfinite(r["time"])
        assert all(np.isfinite(m) for m in r["layer_memory"])
