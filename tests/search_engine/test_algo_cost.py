"""Per-algorithm / per-level collective pricing + the hierarchical dp
term: the min-over-curves choice must pick the right algorithm per
message size, the hierarchical term must be able to flip the chosen plan,
and EMPTY per-algorithm data must leave every cost byte-identical (the
golden search regressions pin the full-plan version against the legacy
fixtures)."""

import numpy as np
import pytest

from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    _algo_min_ms,
    _tp_message_ms,
    hier_dp_reduce_ms,
    hier_dp_wins,
    layer_time_cost,
    layer_time_components,
)
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy

pytestmark = [pytest.mark.search_engine]


def _latency_table(per_mb=0.01):
    table = {mb: per_mb * mb for mb in (1, 2, 4, 8, 16, 32, 64, 128)}
    table["popt"] = np.array([per_mb, 0.0])
    return table


def _ctx(**kw):
    base = dict(
        parameter_size=48.0, seq_length=128, hidden_size=256, layer_num=4,
        mixed_precision=True,
        forward_computation_time=0.05,
        comm_coe_dict={"8_1": 0.01, "8_0": 0.01, "4_1": 0.01, "4_0": 0.01,
                       "2_1": 0.01, "2_0": 0.01, "1": 0.0, "1_1": 0.0},
        dp_overlap_coe=1.1, bct_overlap_coe=1.1,
        allgather_latency={2: _latency_table(), 4: _latency_table(),
                           8: _latency_table()},
        all2all_latency={2: _latency_table(), 4: _latency_table(),
                         8: _latency_table()},
    )
    base.update(kw)
    return CostContext(**base)


def _cost(s, ctx, gbsz=64, chunks=1):
    return layer_time_cost(s, ctx, gbsz, chunks)[0]


TP2 = SearchStrategy(pp=1, tp=2, dp=4)
TP4 = SearchStrategy(pp=1, tp=4, dp=2)
DP8 = SearchStrategy(pp=1, tp=1, dp=8)


# ---------------------------------------------------------------------------
# min-over-algorithm-curves
# ---------------------------------------------------------------------------


def test_algo_min_picks_per_message_size():
    """Ring: low α, low β⁻¹ slope advantage at bulk; tree: high bandwidth
    cost but tiny α. The min must switch algorithms with the message
    size — the whole point of fitting per-algorithm curves."""
    algos = {"4_1": {"ring_ici": (1.0, 100.0),   # 1ms + size/100
                     "tree_ici": (0.05, 20.0)}}  # 0.05ms + size/20
    ctx = _ctx(alpha_beta_algos=algos)
    small = _algo_min_ms(ctx, 4, 1, "ici", 0.1)
    big = _algo_min_ms(ctx, 4, 1, "ici", 64.0)
    assert small == pytest.approx(0.05 + 0.1 / 20.0)   # tree wins small
    assert big == pytest.approx(1.0 + 64.0 / 100.0)    # ring wins big
    # level filter: no dcn curves fitted -> None
    assert _algo_min_ms(ctx, 4, 1, "dcn", 1.0) is None
    assert _algo_min_ms(ctx, 2, 1, "ici", 1.0) is None


def test_tp_message_prices_min_of_flat_and_algo_curves():
    ab = {"2_1": (1.0, 50.0)}
    algos = {"2_1": {"tree_ici": (0.1, 50.0)}}
    ctx = _ctx(tp_alpha_beta=ab, alpha_beta_algos=algos)
    # algo curve cheaper at every size here
    assert _tp_message_ms(TP2, ctx, 4.0) == pytest.approx(
        0.5 * (0.1 + 4.0 / 50.0))
    # without algo data, the flat pair prices it (legacy behavior)
    ctx2 = _ctx(tp_alpha_beta=ab)
    assert _tp_message_ms(TP2, ctx2, 4.0) == pytest.approx(
        0.5 * (1.0 + 4.0 / 50.0))


def test_empty_algo_data_costs_byte_identical():
    """The golden-cost discipline: alpha_beta_algos={} and hier_dp=False
    (the defaults) reproduce today's costs bit-for-bit."""
    for s in (TP2, TP4, DP8):
        assert _cost(s, _ctx()) == _cost(
            s, _ctx(alpha_beta_algos={}, hier_dp=False))


def test_algo_pairs_flip_the_chosen_plan():
    """PINNED plan flip: with slow flat measured tables, tp4 wins (cheap
    dp sync); fitted per-algorithm ICI curves that are much faster at
    size 2 than size 4 flip the winner to tp2 — the choice the
    single-curve model cannot express."""
    coe = {"8_1": 0.1, "8_0": 0.1, "4_1": 0.1, "4_0": 0.1,
           "2_1": 0.1, "2_0": 0.1, "1": 0.0, "1_1": 0.0}
    ctx = _ctx(comm_coe_dict=coe)
    assert _cost(TP4, ctx) < _cost(TP2, ctx)
    algos = {"2_1": {"ring_ici": (0.01, 500.0), "tree_ici": (0.005, 80.0)},
             "4_1": {"ring_ici": (2.0, 60.0), "tree_ici": (1.5, 30.0)}}
    ctx_a = _ctx(comm_coe_dict=coe, alpha_beta_algos=algos)
    assert _cost(TP2, ctx_a) < _cost(TP4, ctx_a)


# ---------------------------------------------------------------------------
# hierarchical dp term
# ---------------------------------------------------------------------------


def _hier_algos():
    return {
        # intra-host level: size 4 ICI ring/tree curves
        "4_1": {"ring_ici": (0.1, 200.0), "tree_ici": (0.2, 100.0)},
        # cross-slice level: size 2 DCN curves (slow links)
        "2_0": {"ring_dcn": (1.0, 10.0)},
        "2_1": {"ring_ici": (0.05, 300.0)},
        "8_1": {"ring_ici": (0.2, 150.0)},
    }


def test_hier_dp_reduce_ms_hand_math():
    """dp8 over 2 slices: intra=4, cross=2. Time = allreduce_ici(4, V)
    (the rs+ag halves) + allreduce_dcn(2, V/4)."""
    ctx = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=_hier_algos())
    V = 12.0
    want = (0.1 + V / 200.0) + (1.0 + (V / 4) / 10.0)
    assert hier_dp_reduce_ms(DP8, ctx, V) == pytest.approx(want)
    # missing dcn curve -> None (flat pricing stays)
    algos = {"4_1": _hier_algos()["4_1"]}
    ctx2 = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=algos)
    assert hier_dp_reduce_ms(DP8, ctx2, V) is None
    # disabled -> None regardless of curves
    ctx3 = _ctx(hier_dp=False, alpha_beta_algos=_hier_algos())
    assert hier_dp_reduce_ms(DP8, ctx3, V) is None


def test_hier_dp_cp_sp_layers_priced_on_spmd_path():
    """cp/Ulysses-bearing sdp groups are now eligible at pp=1: the hier
    term splits the DP group (not sdp) and adds the in-lane cp/sp
    residual (one ICI allreduce-curve hit at full grad volume). pp>1
    cp/sp plans stay inexpressible — the pp engines keep their ring/a2a
    kernels (search==runtime parity)."""
    ctx = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=_hier_algos())
    V = 12.0
    s_cp = SearchStrategy(pp=1, tp=1, cp=2, dp=4)
    # dp=4 over 2 slices: cross=2, intra=2 -> "2_1" ici curve at V, "2_0"
    # dcn curve at V/2; residual csp=2 -> "2_1" at V
    want = ((0.05 + V / 300.0) + (1.0 + (V / 2) / 10.0)
            + (0.05 + V / 300.0))
    assert hier_dp_reduce_ms(s_cp, ctx, V) == pytest.approx(want)
    s_sp = SearchStrategy(pp=1, tp=1, sp=2, dp=4)
    assert hier_dp_reduce_ms(s_sp, ctx, V) == pytest.approx(want)
    # pp>1 cp plans: the engines would raise HIER_KERNEL_REASON, so the
    # search must not price them
    assert hier_dp_reduce_ms(
        SearchStrategy(pp=2, tp=1, cp=2, dp=4), ctx, V) is None
    # missing residual curve -> None (flat pricing stays)
    algos = {k: v for k, v in _hier_algos().items() if k != "2_1"}
    ctx2 = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=algos)
    assert hier_dp_reduce_ms(s_cp, ctx2, V) is None


# pipelining-friendly curves for the bucketed tests: β-bound ICI and DCN
# stages of comparable size, tiny α — the regime where hiding the slow
# link behind the fast ones pays
_PIPE_ALGOS = {"4_1": {"ring_ici": (0.01, 5.0)},
               "2_0": {"ring_dcn": (0.01, 1.0)},
               "2_1": {"ring_ici": (0.05, 300.0)},
               "8_1": {"ring_ici": (0.2, 150.0)}}


def test_hier_dp_bucketed_hand_math():
    """Fill-drain pipeline price: V=96 at 8-MB buckets -> B=12, per-bucket
    msg 8 MB; T = t_ici + t_dcn + 11 * max(t_ici, t_dcn). The 0-default
    reproduces the monolithic sum exactly (golden discipline)."""
    V = 96.0
    mono = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=_PIPE_ALGOS)
    want_mono = (0.01 + V / 5.0) + (0.01 + (V / 4) / 1.0)
    assert hier_dp_reduce_ms(DP8, mono, V) == pytest.approx(want_mono)
    bkt = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=_PIPE_ALGOS,
               hier_bucket_mb=8.0)
    t_ici = 0.01 + 8.0 / 5.0
    t_dcn = 0.01 + 2.0 / 1.0
    want_bkt = t_ici + t_dcn + 11 * max(t_ici, t_dcn)
    assert hier_dp_reduce_ms(DP8, bkt, V) == pytest.approx(want_bkt)
    assert want_bkt < want_mono  # the pipelined schedule hides the ICI time


def test_hier_dp_bucket_auto_sweep_picks_argmin():
    """hier_bucket_mb < 0 (auto): the price is the candidate sweep's min
    and hier_dp_best_bucket reports the chosen granularity for the plan
    record ("hier_bucket_mb" in the plan JSON)."""
    from hetu_galvatron_tpu.core.cost_model.cost import (
        _BUCKET_SWEEP_MB,
        hier_dp_best_bucket,
    )

    V = 96.0
    auto = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=_PIPE_ALGOS,
                hier_bucket_mb=-1.0)
    ms, bucket = hier_dp_best_bucket(DP8, auto, V)
    per_cand = {c: hier_dp_reduce_ms(
        DP8, _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=_PIPE_ALGOS,
                  hier_bucket_mb=c), V) for c in _BUCKET_SWEEP_MB}
    assert ms == pytest.approx(min(per_cand.values()))
    assert per_cand[bucket] == pytest.approx(ms)
    assert bucket > 0  # at these curves, bucketing beats monolithic
    # and the plain reduce-ms entry returns the same auto price
    assert hier_dp_reduce_ms(DP8, auto, V) == pytest.approx(ms)


def test_hier_bucketing_flips_the_plan_record():
    """THE pinned bucketing flip: at a flat dp coefficient where the
    MONOLITHIC hier schedule loses to the flat overlapped ring
    (hier_dp_wins False -> the plan records no "hier_dp"), pricing the
    bucketed pipelined schedule wins (hier_dp_wins True -> the plan
    records hier_dp + hier_bucket_mb and the runtime flips paths)."""
    coe = {"8_1": 0.25, "8_0": 0.25, "4_1": 0.25, "4_0": 0.25,
           "2_1": 0.25, "2_0": 0.25, "1": 0.0, "1_1": 0.0}
    mono = _ctx(comm_coe_dict=coe, hier_dp=True, dcn_slices=2,
                alpha_beta_algos=_PIPE_ALGOS)
    bkt = _ctx(comm_coe_dict=coe, hier_dp=True, dcn_slices=2,
               alpha_beta_algos=_PIPE_ALGOS, hier_bucket_mb=12.0)
    assert not hier_dp_wins(DP8, mono, 64, 1)
    assert hier_dp_wins(DP8, bkt, 64, 1)
    assert (layer_time_cost(DP8, bkt, 64, 1)[0]
            < layer_time_cost(DP8, mono, 64, 1)[0])


def test_hier_dp_term_flips_the_chosen_plan():
    """PINNED hier flip: with a slow flat dp coefficient, tp4xdp2 beats
    tp1xdp8 (less dp traffic); the hierarchical curves make the big dp
    group cheap (fast intra-host level + tiny cross shard), flipping the
    winner to dp8 — and hier_dp_wins records the choice for the plan."""
    coe = {"8_1": 0.4, "8_0": 0.4, "4_1": 0.4, "4_0": 0.4,
           "2_1": 0.4, "2_0": 0.4, "1": 0.0, "1_1": 0.0}
    ctx = _ctx(comm_coe_dict=coe)
    assert _cost(TP4, ctx) < _cost(DP8, ctx)
    ctx_h = _ctx(comm_coe_dict=coe, hier_dp=True, dcn_slices=2,
                 alpha_beta_algos=_hier_algos())
    assert _cost(DP8, ctx_h) < _cost(TP4, ctx_h)
    assert hier_dp_wins(DP8, ctx_h, 64, 1)
    assert not hier_dp_wins(DP8, ctx, 64, 1)


def test_hier_enabled_never_raises_cost():
    """min(flat, hier): at FIXED curves, turning the hier pricing on can
    only lower a cost (the algo curves themselves may reprice tp either
    way — that's the min-over-curves tests' subject, not this one)."""
    for s in (TP2, TP4, DP8):
        flat = _cost(s, _ctx(alpha_beta_algos=_hier_algos()))
        hier = _cost(s, _ctx(hier_dp=True, dcn_slices=2,
                             alpha_beta_algos=_hier_algos()))
        assert hier <= flat + 1e-15


def test_components_reflect_hier_choice():
    """When the hierarchical term priced the layer, the audit-facing
    decomposition reports the hierarchical dp time."""
    coe = {"8_1": 0.4, "8_0": 0.4, "4_1": 0.4, "4_0": 0.4,
           "2_1": 0.4, "2_0": 0.4, "1": 0.0, "1_1": 0.0}
    ctx_h = _ctx(comm_coe_dict=coe, hier_dp=True, dcn_slices=2,
                 alpha_beta_algos=_hier_algos())
    comp = layer_time_components(DP8, ctx_h, 64, 1)
    V = 48.0 / 1 * 4 * 0.5  # param_mb * n * mixed
    want = hier_dp_reduce_ms(DP8, ctx_h, V)
    assert comp["dp_ms"] * 4 == pytest.approx(want)  # scale = coe/n


def test_hier_split_absorbs_pp_first():
    """dcn_slices absorb pp before dp (mesh.dcn_factor_shape parity):
    pp2 x dp4 under 2 slices has NO cross-slice dp level — the hier term
    needs only the intra curves."""
    s = SearchStrategy(pp=2, tp=1, dp=4)
    algos = {"4_1": {"ring_ici": (0.1, 200.0)}}
    ctx = _ctx(hier_dp=True, dcn_slices=2, alpha_beta_algos=algos)
    V = 10.0
    assert hier_dp_reduce_ms(s, ctx, V) == pytest.approx(0.1 + V / 200.0)
