"""Search-trace JSONL: every explored task + the winner is auditable
(observability/sinks.py record schema)."""

import json
import os

import pytest

from hetu_galvatron_tpu.core.args_schema import SearchArgs
from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine, TaskResult
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy
from hetu_galvatron_tpu.utils.strategy import DPType

pytestmark = pytest.mark.search_engine


def _engine(trace_path):
    args = SearchArgs(search_trace_path=trace_path)
    return SearchEngine(args)


def test_write_search_trace_schema(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    eng = _engine(path)
    tasks = [(64, 8, 1, "tp_only", 4), (64, 8, 2, "tp_with_sp", 8)]
    s = SearchStrategy(pp=2, tp=2, dp=2, dp_type=DPType.ZERO2)
    results = [
        TaskResult(),  # infeasible
        TaskResult(throughput=2.5, time_cost=25.6, strategy_list=[s, s],
                   pp_size=2, pp_stage_list=[1, 1], memory_cost=[10.0, 9.0],
                   vocab_tp_sp=2, bsz=64, chunks=8),
    ]
    eng._write_search_trace(tasks, results, results[1])
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 3
    assert [r["name"] for r in recs] == ["search_task", "search_task",
                                         "search_best"]
    assert all(r["kind"] == "event" for r in recs)
    t0, t1, best = (r["data"] for r in recs)
    assert t0 == {"bsz": 64, "chunks": 8, "pp": 1, "mode": "tp_only",
                  "max_tp": 4, "throughput": -1.0, "time_cost": None,
                  "feasible": False}
    assert t1["feasible"] and t1["throughput"] == 2.5
    assert t1["pp_division"] == [1, 1]
    assert t1["vocab"] == {"vtp": 2, "vsp": 0, "embed_sdp": 0}
    assert best["throughput"] == 2.5
    assert len(best["strategies"]) == 2
    assert "tp2" in best["strategies"][0].replace(" ", "") or \
        "2" in best["strategies"][0]  # human-readable form_strategy string


def test_no_trace_path_writes_nothing(tmp_path):
    eng = _engine(None)
    eng._write_search_trace([], [], TaskResult())
    assert not os.listdir(tmp_path)
