"""Speculative decoding: draft providers, the greedy acceptance rule, and
engine-level losslessness (spec streams bit-identical to plain decode and
offline generate, accept rate > 0 on cyclic continuations, sampled lanes
unchanged, zero steady-state recompiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.models.generate import generate
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.serving.engine import ServingEngine
from hetu_galvatron_tpu.serving.spec_decode import (
    ModelDraft,
    NgramDraft,
    accept_length,
    make_draft,
)

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = dict(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=128, seq_length=32,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def _offline(params, cfg, prompt, n_new, cache={}):
    key = (id(params), len(prompt), n_new)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(lambda p, t: generate(
            p, t, cfg, n_new, pad_id=0, compute_dtype=jnp.float32))
        cache[key] = fn
    out = np.asarray(fn(params, jnp.asarray([prompt], jnp.int32)))
    return out[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# drafts + acceptance rule (host-side)
# ---------------------------------------------------------------------------


def test_ngram_draft_prompt_lookup():
    d = NgramDraft(max_n=3, min_n=1)
    # trailing 3-gram [1,2,3] occurred at the start; propose what followed
    assert d.propose([1, 2, 3, 4, 5, 1, 2, 3], 2) == [4, 5]
    # most RECENT earlier occurrence wins
    assert d.propose([7, 9, 7, 8, 7], 2) == [8, 7]
    # falls back to shorter n-grams before giving up
    assert d.propose([5, 6, 1, 9, 4, 6], 1) == [1]
    assert d.propose([1, 2, 3], 2) == []  # nothing repeats
    assert d.propose([], 2) == []
    with pytest.raises(ValueError):
        NgramDraft(max_n=0)


def test_accept_length_rule():
    # targets[j] = model's choice after drafted[0..j-1]
    assert accept_length([5, 6, 7], [5, 6, 7, 8], k_eff=3) == 3
    assert accept_length([5, 9, 7], [5, 6, 7, 8], k_eff=3) == 1
    assert accept_length([9, 6, 7], [5, 6, 7, 8], k_eff=3) == 0
    assert accept_length([5, 6, 7], [5, 6, 7, 8], k_eff=1) == 1  # budget
    assert accept_length([], [5], k_eff=3) == 0


def test_model_draft_matches_offline_greedy():
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    draft = ModelDraft(params, cfg, window=32)
    ctx = np.random.RandomState(0).randint(0, 128, (11,)).tolist()
    assert draft.propose(ctx, 4) == _offline(params, cfg, ctx, 4)
    # jits once per (bucket, k): a second same-bucket call reuses it
    n = draft.compile_count()
    draft.propose(ctx + [1], 4)
    assert draft.compile_count() == n


def test_make_draft_dispatch():
    sv = ServingArgs(spec_decode=True, spec_draft="ngram")
    assert isinstance(make_draft(sv), NgramDraft)
    assert make_draft(ServingArgs()) is None
    with pytest.raises(ValueError, match="draft_params"):
        make_draft(ServingArgs(spec_decode=True, spec_draft="model"))


# ---------------------------------------------------------------------------
# engine-level losslessness
# ---------------------------------------------------------------------------


def test_spec_streams_bit_identical_with_accepts():
    """Greedy spec streams == plain engine streams == offline generate,
    with a strictly positive accept rate (long continuations cycle, and
    prompt-lookup predicts the cycle), at zero steady-state recompiles."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    reg = MetricsRegistry()
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, 128, (n,)).tolist(), 24)
            for n in (10, 7, 13, 10)]

    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=64,
                     max_new_tokens=24, spec_decode=True, spec_k=3)
    eng = ServingEngine(params, cfg, sv, registry=reg,
                        compute_dtype=jnp.float32)
    eng.warmup(buckets=[8, 16])  # every bucket this workload reaches
    warm = eng.compile_count()
    handles = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
    steps = 0
    while eng.scheduler.has_work():
        eng.step()
        steps += 1
        assert steps < 500
    assert eng.compile_count() == warm
    for (p, m), h in zip(reqs, handles):
        assert h.status == "done"
        assert h.result(0) == _offline(params, cfg, p, m)
    assert eng.spec_accept_rate() > 0.0
    assert reg.counter("serve/drafted_tokens").value > 0
    assert reg.counter("serve/spec_accepted_tokens").value > 0
    # accepted tokens shrink the step count below one-token-per-step
    total_emitted = sum(len(h.output) for h in handles)
    decode_steps = total_emitted - len(reqs)  # prefill emits the first
    assert steps < decode_steps + len(reqs) + 4  # strictly fewer steps


def test_spec_sampled_lanes_match_plain_engine():
    """temperature > 0 lanes do not speculate but still emit the same
    per-request fold_in stream the plain engine produces."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    prompt = np.random.RandomState(1).randint(0, 128, (9,)).tolist()
    outs = []
    for spec in (False, True):
        sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=48,
                         max_new_tokens=10, spec_decode=spec, spec_k=3)
        eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
        h = eng.submit(prompt, temperature=0.8, seed=13)
        eng.run_until_idle()
        assert h.status == "done"
        outs.append(h.result(0))
    assert outs[0] == outs[1]
    assert len(set(outs[0])) > 1  # genuinely sampling


def test_spec_eos_and_budget_mid_window():
    """EOS inside an accepted window retires the stream exactly at the
    offline truncation point; a 1-token budget emits exactly one."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(2), cfg)
    prompt = np.random.RandomState(2).randint(0, 128, (6,)).tolist()
    free_run = _offline(params, cfg, prompt, 16)
    eos = free_run[7]  # deep enough that spec windows cross it
    want = free_run[: free_run.index(eos) + 1]
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=48,
                     max_new_tokens=16, spec_decode=True, spec_k=4,
                     eos_id=eos)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
    h = eng.submit(prompt)
    eng.run_until_idle()
    assert h.status == "done" and h.finish_reason == "eos"
    assert h.result(0) == want
    assert eng.kv.allocator.used == 0
    h1 = eng.submit(prompt, max_new_tokens=1)
    eng.run_until_idle()
    assert h1.result(0) == _offline(params, cfg, prompt, 1)
