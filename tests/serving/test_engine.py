"""Serving engine drills: continuous-batching parity vs offline generate,
zero steady-state recompiles, EOS retirement, cancellation/timeouts,
telemetry stream + summarize rendering."""

import io
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import (
    CoreArgs,
    ModelArgs,
    ServingArgs,
)
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.models.generate import generate
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.observability.sinks import JsonlSink
from hetu_galvatron_tpu.serving.engine import ServingEngine

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = dict(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=128, seq_length=32,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def _offline(params, cfg, prompt, n_new, eos_id=None, cache={}):
    """Offline reference stream: generate() on the single unpadded row,
    trimmed at the first EOS (inclusive) — the retirement contract the
    pad_id masking pins. jitted per (len, n_new) shape."""
    key = (id(params), len(prompt), n_new, eos_id)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(lambda p, t: generate(
            p, t, cfg, n_new, eos_id=eos_id, pad_id=0,
            compute_dtype=jnp.float32))
        cache[key] = fn
    out = np.asarray(fn(params, jnp.asarray([prompt], jnp.int32)))
    row = out[0, len(prompt):].tolist()
    if eos_id is not None and eos_id in row:
        row = row[: row.index(eos_id) + 1]
    return row


# ---------------------------------------------------------------------------
# single device
# ---------------------------------------------------------------------------


def test_single_device_parity_ragged_and_zero_recompiles():
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=48,
                     max_new_tokens=8)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
    eng.warmup(buckets=[8, 16])
    warm = eng.compile_count()
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, 128, (n,)).tolist(), m)
            for n, m in [(3, 4), (9, 8), (13, 6), (1, 8), (16, 5), (7, 8)]]
    handles = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
    eng.run_until_idle()
    assert eng.compile_count() == warm  # steady state: no recompiles
    for (p, m), h in zip(reqs, handles):
        assert h.status == "done"
        assert h.result(0) == _offline(params, cfg, p, m)


def test_eos_retirement_matches_offline_and_recycles():
    """Force EOS mid-stream: pick eos_id from an offline run's interior,
    then check the engine retires exactly there and the freed slot serves
    a follow-up request."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    prompt = np.random.RandomState(1).randint(0, 128, (5,)).tolist()
    free_run = _offline(params, cfg, prompt, 8)
    eos = free_run[2]  # third generated token becomes the stop token
    want = _offline(params, cfg, prompt, 8, eos_id=eos)
    assert want[-1] == eos and len(want) < 8

    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=8, eos_id=eos)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
    h1 = eng.submit(prompt)
    eng.run_until_idle()
    assert h1.status == "done" and h1.finish_reason == "eos"
    assert h1.result(0) == want
    assert eng.kv.allocator.used == 0  # blocks freed on retirement
    # recycled lane serves the next request
    h2 = eng.submit(prompt, eos_id=None)
    eng.run_until_idle()
    assert h2.status == "done" and h2.finish_reason == "length"


def test_sampling_is_batch_composition_invariant():
    """A sampled request's stream depends on its own (seed, temperature),
    not on which neighbors share the batch — the per-request fold_in
    contract continuous batching depends on."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(2), cfg)
    rng = np.random.RandomState(2)
    probe = rng.randint(0, 128, (6,)).tolist()
    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=6)
    runs = []
    for neighbors in ([], [rng.randint(0, 128, (4,)).tolist(),
                           rng.randint(0, 128, (11,)).tolist()]):
        eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
        hs = [eng.submit(n, temperature=0.9, seed=100 + i)
              for i, n in enumerate(neighbors)]
        h = eng.submit(probe, temperature=0.7, seed=7)
        eng.run_until_idle()
        assert h.status == "done"
        runs.append(h.result(0))
        del hs
    assert runs[0] == runs[1]
    assert len(set(runs[0])) > 1  # actually sampling, not degenerate


def test_cancellation_timeout_and_rejection():
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(3), cfg)
    reg = MetricsRegistry()
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=8)
    eng = ServingEngine(params, cfg, sv, registry=reg,
                        compute_dtype=jnp.float32)
    prompt = [1, 2, 3]
    # rejection: can never fit
    h_rej = eng.submit([5] * 40, max_new_tokens=8)
    assert h_rej.status == "rejected"
    # cancellation mid-decode
    h_c = eng.submit(prompt, max_new_tokens=8)
    eng.step()  # prefill + first decode
    h_c.cancel()
    eng.step()
    assert h_c.status == "cancelled"
    assert 0 < len(h_c.output) < 8
    # timeout: immediate deadline trips at the next sweep
    h_t = eng.submit(prompt, max_new_tokens=8, timeout_s=1e-9)
    eng.step()
    time.sleep(0.005)
    eng.step()
    assert h_t.status == "timeout"
    assert eng.kv.allocator.used == 0
    assert reg.counter("serve/requests_rejected").value == 1
    assert reg.counter("serve/requests_cancelled").value == 1
    assert reg.counter("serve/requests_timeout").value == 1
    # cancelled/expired while still QUEUED must count too (and never be
    # admitted): saturate both lanes, queue two more, resolve them
    blockers = [eng.submit(prompt, max_new_tokens=8) for _ in range(2)]
    eng.step()
    h_qc = eng.submit(prompt, max_new_tokens=8)
    h_qt = eng.submit(prompt, max_new_tokens=8, timeout_s=1e-9)
    h_qc.cancel()
    time.sleep(0.005)
    eng.step()
    assert h_qc.status == "cancelled" and h_qc.output == []
    assert h_qt.status == "timeout" and h_qt.output == []
    assert reg.counter("serve/requests_cancelled").value == 2
    assert reg.counter("serve/requests_timeout").value == 2
    eng.run_until_idle()
    assert all(b.status == "done" for b in blockers)


def test_background_thread_streams_tokens():
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(4), cfg)
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=5)
    with ServingEngine(params, cfg, sv, compute_dtype=jnp.float32) as eng:
        eng.start()
        prompt = [3, 1, 4, 1, 5]
        h = eng.submit(prompt)
        got = list(h.tokens())  # blocking caller-side stream
        assert h.status == "done" and got == h.result(0)
        assert got == _offline(params, cfg, prompt, 5)
        eng.stop()


def test_default_warmup_buckets_cover_the_cap():
    """bucket_length caps at the (possibly non-power-of-two) per-sequence
    capacity; warmup's default ladder must include that cap or the first
    long prompt recompiles mid-serving."""
    from hetu_galvatron_tpu.serving.engine import default_buckets

    assert default_buckets(8, 32) == [8, 16, 32]
    assert default_buckets(16, 112) == [16, 32, 64, 112]
    assert default_buckets(16, 16) == [16]


def test_engine_thread_error_resolves_handles():
    """A fatal error inside the background loop must abort every pending
    handle (status 'error'), never leave callers blocked forever."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(5), cfg)
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=4)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)

    def boom(slot, bucket):
        raise RuntimeError("injected prefill failure")

    eng._prefill_slot = boom
    eng.start()
    h = eng.submit([1, 2, 3])
    out = h.result(timeout=30)  # resolves instead of hanging
    assert h.status == "error" and "injected" in h.finish_reason
    assert out == []
    assert isinstance(eng.error, RuntimeError)
    # a submit AFTER the abort resolves immediately too (nothing will
    # ever step the queue again)
    h_late = eng.submit([4, 5])
    assert h_late.status == "error" and h_late.done()
    eng.close()


def test_rejects_unsupported_families():
    cfg = _cfg(model_type="bert", position_embedding_type="learned",
               normalization="layernorm", hidden_act="gelu",
               norm_position="post", add_bias_linear=True)
    params, _ = init_causal_lm(jax.random.key(0), _cfg())
    with pytest.raises(NotImplementedError):
        ServingEngine(params, cfg)


# ---------------------------------------------------------------------------
# the continuous-batching drill (8-device CPU mesh, plan-aware SPMD)
# ---------------------------------------------------------------------------


@pytest.mark.distributed
def test_continuous_batching_drill_mesh8(tmp_path):
    """>= 32 concurrent requests with staggered arrival and ragged
    prompt/output lengths on the 8-device mesh under a tp2 plan: every
    stream matches offline generate() exactly, steady-state decode
    triggers zero recompiles, and the serving metrics land in the JSONL
    sink and render through cli/summarize.py."""
    cfg = _cfg()
    args = CoreArgs(model=cfg.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.vocab_tp = 2
    args.parallel.global_train_batch_size = 8
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=jax.devices("cpu")[:8])
    params, axes = init_causal_lm(jax.random.key(0), cfg)

    metrics_path = str(tmp_path / "serve_metrics.jsonl")
    reg = MetricsRegistry([JsonlSink(metrics_path)])
    sv = ServingArgs(max_batch_size=8, kv_block_size=8, max_seq_len=48,
                     max_new_tokens=8, flush_interval=8)
    eng = ServingEngine(params, cfg, sv, mesh=mesh, hpc=hpc,
                        axes_tree=axes, registry=reg,
                        compute_dtype=jnp.float32)
    # kv pool sharding follows the plan's attention tp axes
    assert any(s != (None,) * 4 and list(s) != [None] * 4
               for s in eng.kv.pspecs), eng.kv.pspecs

    rng = np.random.RandomState(0)
    lens = [3, 7, 12, 20, 1, 9, 15, 5] * 4  # 32 requests, ragged prompts
    news = [4, 8, 6, 8, 8, 5, 7, 8] * 4  # ragged output budgets
    reqs = [(rng.randint(0, 128, (n,)).tolist(), m)
            for n, m in zip(lens, news)]

    eng.warmup(buckets=[8, 16, 32])
    warm_compiles = eng.compile_count()

    # staggered arrival: requests land in four waves with decode steps
    # (and some idle steps) in between — continuous batching must fill
    # freed lanes from the queue while older sequences keep decoding
    handles = []
    for wave in range(4):
        for p, m in reqs[wave * 8:(wave + 1) * 8]:
            handles.append(eng.submit(p, max_new_tokens=m))
        for _ in range(3):
            eng.step()
    eng.run_until_idle(max_steps=2000)
    eng.close()
    reg.close()

    # zero recompiles after warmup (the jit cache-miss pin)
    assert eng.compile_count() == warm_compiles

    # every stream matches the offline decode exactly
    assert all(h.status == "done" for h in handles)
    for (p, m), h in zip(reqs, handles):
        assert h.result(0) == _offline(params, cfg, p, m), (len(p), m)

    # telemetry: TTFT / inter-token / queue / KV occupancy in the sink
    records = [json.loads(line) for line in open(metrics_path)]
    names = {(r.get("kind"), r.get("name")) for r in records}
    for expect in [("histogram", "serve/ttft_ms"),
                   ("histogram", "serve/itl_ms"),
                   ("gauge", "serve/queue_depth"),
                   ("gauge", "serve/kv_occupancy"),
                   ("gauge", "serve/tokens_per_sec"),
                   ("counter", "serve/requests_completed")]:
        assert expect in names, expect
    done = [r for r in records
            if r.get("name") == "serve/requests_completed"]
    assert done[-1]["value"] == 32
    ttft = [r for r in records if r.get("name") == "serve/ttft_ms"]
    assert ttft[-1]["count"] == 32

    # ... and cli/summarize.py renders them
    from hetu_galvatron_tpu.cli.summarize import summarize

    buf = io.StringIO()
    headline = summarize(metrics_path, out=buf)
    text = buf.getvalue()
    assert "-- serving --" in text
    assert "TTFT ms" in text and "inter-token ms" in text
    assert headline["serve/requests_completed"] == 32
    assert headline["ttft_p50_ms"] > 0
