"""tools/serve_bench.py smoke: the closed-loop load generator must run on
CPU (--smoke), complete its request budget, and report a parseable JSON
with zero steady-state recompiles."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BENCH = os.path.join(REPO, "tools", "serve_bench.py")


def test_serve_bench_smoke(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single device is enough (and faster)
    out_json = tmp_path / "report.json"
    metrics = tmp_path / "m.jsonl"
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--requests", "10",
         "--concurrency", "4", "--json", str(out_json),
         "--metrics", str(metrics)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(out_json.read_text())
    assert report["completed"] == 10
    assert report["tokens_out"] > 0 and report["tokens_per_sec"] > 0
    assert report["ttft_ms"]["p50"] > 0
    assert report["steady_state_recompiles"] == 0
    # the engine's own telemetry stream landed too
    names = {json.loads(line).get("name") for line in open(metrics)}
    assert "serve/ttft_ms" in names and "serve/tokens_per_sec" in names
