"""tools/serve_bench.py smoke: the closed-loop load generator must run on
CPU (--smoke), complete its request budget, and report a parseable JSON
with zero steady-state recompiles — plus the shared-prefix trace mode
(hit/miss TTFT split) and the importable serve_prefix / spec_decode A/B
legs bench.py and bench_gate.py consume."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BENCH = os.path.join(REPO, "tools", "serve_bench.py")


def test_serve_bench_smoke(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single device is enough (and faster)
    out_json = tmp_path / "report.json"
    metrics = tmp_path / "m.jsonl"
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--requests", "10",
         "--concurrency", "4", "--json", str(out_json),
         "--metrics", str(metrics)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(out_json.read_text())
    assert report["completed"] == 10
    assert report["tokens_out"] > 0 and report["tokens_per_sec"] > 0
    assert report["ttft_ms"]["p50"] > 0
    assert report["steady_state_recompiles"] == 0
    # the engine's own telemetry stream landed too
    names = {json.loads(line).get("name") for line in open(metrics)}
    assert "serve/ttft_ms" in names and "serve/tokens_per_sec" in names


def test_serve_bench_shared_prefix_trace(tmp_path):
    """--shared-prefixes + --prefix-cache + --spec-decode: the report
    splits TTFT by hit/miss, carries the hit and accept rates, and the
    trace really produces hits."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    out_json = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--requests", "10",
         "--concurrency", "2", "--shared-prefixes", "2",
         "--prefix-len", "24", "--prefix-cache", "--spec-decode",
         "--spec-k", "2", "--json", str(out_json)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(out_json.read_text())
    assert report["completed"] == 10
    assert report["steady_state_recompiles"] == 0
    assert report["prefix_hit_rate"] > 0
    assert report["ttft_ms_hit"]["n"] + report["ttft_ms_miss"]["n"] == 10
    assert report["ttft_ms_hit"]["n"] >= 5  # 2 prefixes, 10 requests
    assert "spec_accept_rate" in report


def test_serve_bench_ab_legs_importable():
    """run_prefix / run_spec (the bench.py legs): sane ratios, zero
    steady-state recompiles, lossless spec. Shrunk shapes — this is a
    wiring test, not a measurement."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serve_bench

    out = serve_bench.run_prefix(reps=2)
    assert "skipped" not in out, out
    assert 0 < out["serve_prefix_ttft_ratio"] < 1.0
    assert out["serve_prefix_recompiles"] == 0
    assert out["prefix_hit_rate"] > 0
    out = serve_bench.run_spec(requests=2, iters=1)
    assert "skipped" not in out, out
    assert out["spec_decode_tokens_ratio"] > 0
    assert out["spec_decode_recompiles"] == 0
    assert out["spec_accept_rate"] > 0
