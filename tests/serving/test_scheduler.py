"""Continuous-batching scheduler: admission control, retirement, slot
recycling, fixed-shape decode state."""

import jax.numpy as jnp
import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.serving.kv_cache import SCRATCH_BLOCK, PagedKVCache
from hetu_galvatron_tpu.serving.scheduler import (
    Request,
    Scheduler,
    bucket_length,
)

pytestmark = pytest.mark.serving


def _sched(num_blocks=17, block_size=4, max_seq_len=16, max_slots=2,
           **kw):
    cfg = ModelArgs(hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, vocab_size=64,
                    max_position_embeddings=64,
                    make_vocab_size_divisible_by=1)
    kv = PagedKVCache(cfg, num_blocks=num_blocks, block_size=block_size,
                      max_seq_len=max_seq_len, dtype=jnp.float32)
    return Scheduler(kv, max_slots=max_slots,
                     max_position_embeddings=64, **kw), kv


def test_bucket_lengths():
    assert bucket_length(1, 4, 32) == 4
    assert bucket_length(4, 4, 32) == 4
    assert bucket_length(5, 4, 32) == 8
    assert bucket_length(9, 4, 32) == 16
    assert bucket_length(30, 4, 32) == 32
    # cap wins even when the pow2 ladder would overshoot
    assert bucket_length(10, 4, 12) == 12


def test_admission_and_recycling():
    s, kv = _sched()
    h1 = s.submit(Request(tokens=[1, 2, 3], max_new_tokens=4))
    h2 = s.submit(Request(tokens=[1] * 6, max_new_tokens=4))
    h3 = s.submit(Request(tokens=[2, 2], max_new_tokens=2))
    assert s.queue_depth == 3
    admitted = s.admit()
    assert len(admitted) == 2  # two slots
    assert s.queue_depth == 1
    slots = {sl.index for sl, _ in admitted}
    assert slots == {0, 1}
    assert h1.status == "running" and h2.status == "running"
    # retire one -> slot + blocks recycle, next request admitted
    sl0 = admitted[0][0]
    blocks0 = list(sl0.blocks)
    s.retire(sl0, "done", "eos")
    assert h1.status == "done" and h1.finish_reason == "eos"
    admitted2 = s.admit()
    assert len(admitted2) == 1
    assert admitted2[0][0].index == sl0.index  # recycled lane
    assert set(admitted2[0][0].blocks) <= set(blocks0)  # recycled blocks
    assert h3.status == "running"


def test_rejects_oversized_requests_immediately():
    s, kv = _sched(max_seq_len=16)
    # 20 total tokens can never fit the 16-token per-sequence capacity
    h = s.submit(Request(tokens=[1] * 10, max_new_tokens=10))
    assert h.status == "rejected" and h.done()
    assert s.queue_depth == 0 and s.rejected == 1
    # empty prompts and empty generation budgets are rejected too
    assert s.submit(Request(tokens=[], max_new_tokens=2)).status == "rejected"
    assert s.submit(Request(tokens=[1], max_new_tokens=0)).status == "rejected"
    # a request whose block need exceeds the WHOLE pool can never run:
    # reject at submit instead of queueing forever
    s2, _ = _sched(num_blocks=3, max_seq_len=16)  # 2 allocatable blocks
    h2 = s2.submit(Request(tokens=[1] * 8, max_new_tokens=4))  # needs 3
    assert h2.status == "rejected"


def test_pool_exhaustion_preserves_fifo():
    # 5 allocatable blocks; each request needs 3 (8 prompt + 4 new @ bs 4)
    s, kv = _sched(num_blocks=6, max_slots=4)
    h1 = s.submit(Request(tokens=[1] * 8, max_new_tokens=4))
    h2 = s.submit(Request(tokens=[2] * 8, max_new_tokens=4))
    admitted = s.admit()
    assert len(admitted) == 1  # second doesn't fit the pool
    assert h2.status == "queued"
    s.retire(admitted[0][0], "done", "eos")
    assert len(s.admit()) == 1
    assert h2.status == "running"
    del h1


def test_prefill_token_budget_caps_admissions_but_never_deadlocks():
    s, kv = _sched(num_blocks=33, max_slots=4, max_prefill_tokens=8)
    for _ in range(3):
        s.submit(Request(tokens=[1] * 8, max_new_tokens=2))  # bucket 8 each
    admitted = s.admit()
    assert len(admitted) == 1  # 8-token budget = one bucket per step
    # a budget smaller than the smallest bucket still admits one
    s2, _ = _sched(num_blocks=33, max_slots=4, max_prefill_tokens=2)
    s2.submit(Request(tokens=[1] * 8, max_new_tokens=2))
    assert len(s2.admit()) == 1


def test_flops_budget_derives_token_cap():
    cfg = ModelArgs(hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, vocab_size=64,
                    max_position_embeddings=64,
                    make_vocab_size_divisible_by=1)
    kv = PagedKVCache(cfg, num_blocks=17, block_size=4, max_seq_len=16,
                      dtype=jnp.float32)
    s = Scheduler(kv, max_slots=2, max_position_embeddings=64,
                  prefill_flops_budget=1000.0, flops_per_token=100.0)
    assert s.prefill_token_cap == 10
    # explicit token cap tightens further
    s = Scheduler(kv, max_slots=2, max_position_embeddings=64,
                  prefill_flops_budget=1000.0, flops_per_token=100.0,
                  max_prefill_tokens=4)
    assert s.prefill_token_cap == 4


def test_sweep_timeout_and_cancel():
    import time

    s, kv = _sched()
    h1 = s.submit(Request(tokens=[1, 2], max_new_tokens=4, timeout_s=0.5))
    h2 = s.submit(Request(tokens=[3, 4], max_new_tokens=4))
    s.admit()
    assert h1.status == "running"  # unexpired deadline admits normally
    h1.request.timeout_s = 1e-9  # now let it lapse mid-run
    h2.cancel()
    time.sleep(0.01)
    assert s.sweep() == (1, 1)
    assert h1.status == "timeout"
    assert h2.status == "cancelled"
    assert kv.allocator.used == 0  # blocks returned
    # cancelled while still queued resolves at the next admit
    h3 = s.submit(Request(tokens=[5], max_new_tokens=2))
    h3.cancel()
    s.admit()
    assert h3.status == "cancelled"
    # a deadline that expires while QUEUED is dropped before admission
    # (no prefill work for a request nobody is waiting on)
    h4 = s.submit(Request(tokens=[6], max_new_tokens=2, timeout_s=1e-9))
    time.sleep(0.005)
    assert s.sweep_waiting() == (0, 1)
    assert h4.status == "timeout" and s.queue_depth == 0


def test_decode_state_is_fixed_shape():
    s, kv = _sched(max_slots=3)
    s.submit(Request(tokens=[7, 8, 9], max_new_tokens=4, temperature=0.5,
                     seed=11))
    s.admit()
    st = s.decode_state()
    assert len(st["tokens"]) == 3 and len(st["tables"]) == 3
    assert all(len(t) == kv.max_blocks_per_seq for t in st["tables"])
    assert st["active"] == [True, False, False]
    assert st["tokens"][0] == 9 and st["pos"][0] == 3
    assert st["temps"][0] == 0.5 and st["seeds"][0] == 11
    # inactive lanes park on the scratch block at pos 0
    assert st["tables"][1] == [SCRATCH_BLOCK] * kv.max_blocks_per_seq
    assert st["pos"][1] == 0


def test_handle_stream_and_result():
    s, _ = _sched()
    h = s.submit(Request(tokens=[1], max_new_tokens=3))
    s.admit()
    slot = s.active[0]
    for t in (5, 6):
        h._emit(t)
    s.retire(slot, "done", "length")
    assert list(h.tokens()) == [5, 6]
    assert list(h.tokens()) == []  # re-iteration terminates, never hangs
    assert h.result(timeout=1) == [5, 6]
    assert h.ttft_s() is not None and h.ttft_s() >= 0
