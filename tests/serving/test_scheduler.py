"""Continuous-batching scheduler: admission control, retirement, slot
recycling, fixed-shape decode state."""

import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.serving.kv_cache import SCRATCH_BLOCK, PagedKVCache
from hetu_galvatron_tpu.serving.scheduler import (
    Request,
    Scheduler,
    bucket_length,
)

pytestmark = pytest.mark.serving


def _sched(num_blocks=17, block_size=4, max_seq_len=16, max_slots=2,
           **kw):
    cfg = ModelArgs(hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, vocab_size=64,
                    max_position_embeddings=64,
                    make_vocab_size_divisible_by=1)
    kv = PagedKVCache(cfg, num_blocks=num_blocks, block_size=block_size,
                      max_seq_len=max_seq_len, dtype=jnp.float32)
    return Scheduler(kv, max_slots=max_slots,
                     max_position_embeddings=64, **kw), kv


def test_bucket_lengths():
    assert bucket_length(1, 4, 32) == 4
    assert bucket_length(4, 4, 32) == 4
    assert bucket_length(5, 4, 32) == 8
    assert bucket_length(9, 4, 32) == 16
    assert bucket_length(30, 4, 32) == 32
    # cap wins even when the pow2 ladder would overshoot
    assert bucket_length(10, 4, 12) == 12


def test_admission_and_recycling():
    s, kv = _sched()
    h1 = s.submit(Request(tokens=[1, 2, 3], max_new_tokens=4))
    h2 = s.submit(Request(tokens=[1] * 6, max_new_tokens=4))
    h3 = s.submit(Request(tokens=[2, 2], max_new_tokens=2))
    assert s.queue_depth == 3
    admitted = s.admit()
    assert len(admitted) == 2  # two slots
    assert s.queue_depth == 1
    slots = {sl.index for sl, _ in admitted}
    assert slots == {0, 1}
    assert h1.status == "running" and h2.status == "running"
    # retire one -> slot + blocks recycle, next request admitted
    sl0 = admitted[0][0]
    blocks0 = list(sl0.blocks)
    s.retire(sl0, "done", "eos")
    assert h1.status == "done" and h1.finish_reason == "eos"
    admitted2 = s.admit()
    assert len(admitted2) == 1
    assert admitted2[0][0].index == sl0.index  # recycled lane
    assert set(admitted2[0][0].blocks) <= set(blocks0)  # recycled blocks
    assert h3.status == "running"


def test_rejects_oversized_requests_immediately():
    s, kv = _sched(max_seq_len=16)
    # 20 total tokens can never fit the 16-token per-sequence capacity
    h = s.submit(Request(tokens=[1] * 10, max_new_tokens=10))
    assert h.status == "rejected" and h.done()
    assert s.queue_depth == 0 and s.rejected == 1
    # empty prompts and empty generation budgets are rejected too
    assert s.submit(Request(tokens=[], max_new_tokens=2)).status == "rejected"
    assert s.submit(Request(tokens=[1], max_new_tokens=0)).status == "rejected"
    # a request whose block need exceeds the WHOLE pool can never run:
    # reject at submit instead of queueing forever
    s2, _ = _sched(num_blocks=3, max_seq_len=16)  # 2 allocatable blocks
    h2 = s2.submit(Request(tokens=[1] * 8, max_new_tokens=4))  # needs 3
    assert h2.status == "rejected"


def test_pool_exhaustion_preserves_fifo():
    # 5 allocatable blocks; each request needs 3 (8 prompt + 4 new @ bs 4)
    s, kv = _sched(num_blocks=6, max_slots=4)
    h1 = s.submit(Request(tokens=[1] * 8, max_new_tokens=4))
    h2 = s.submit(Request(tokens=[2] * 8, max_new_tokens=4))
    admitted = s.admit()
    assert len(admitted) == 1  # second doesn't fit the pool
    assert h2.status == "queued"
    s.retire(admitted[0][0], "done", "eos")
    assert len(s.admit()) == 1
    assert h2.status == "running"
    del h1


def test_prefill_token_budget_caps_admissions_but_never_deadlocks():
    s, kv = _sched(num_blocks=33, max_slots=4, max_prefill_tokens=8)
    for _ in range(3):
        s.submit(Request(tokens=[1] * 8, max_new_tokens=2))  # bucket 8 each
    admitted = s.admit()
    assert len(admitted) == 1  # 8-token budget = one bucket per step
    # a budget smaller than the smallest bucket still admits one
    s2, _ = _sched(num_blocks=33, max_slots=4, max_prefill_tokens=2)
    s2.submit(Request(tokens=[1] * 8, max_new_tokens=2))
    assert len(s2.admit()) == 1


def test_flops_budget_derives_token_cap():
    cfg = ModelArgs(hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, vocab_size=64,
                    max_position_embeddings=64,
                    make_vocab_size_divisible_by=1)
    kv = PagedKVCache(cfg, num_blocks=17, block_size=4, max_seq_len=16,
                      dtype=jnp.float32)
    s = Scheduler(kv, max_slots=2, max_position_embeddings=64,
                  prefill_flops_budget=1000.0, flops_per_token=100.0)
    assert s.prefill_token_cap == 10
    # explicit token cap tightens further
    s = Scheduler(kv, max_slots=2, max_position_embeddings=64,
                  prefill_flops_budget=1000.0, flops_per_token=100.0,
                  max_prefill_tokens=4)
    assert s.prefill_token_cap == 4


def test_sweep_timeout_and_cancel():
    import time

    s, kv = _sched()
    h1 = s.submit(Request(tokens=[1, 2], max_new_tokens=4, timeout_s=0.5))
    h2 = s.submit(Request(tokens=[3, 4], max_new_tokens=4))
    s.admit()
    assert h1.status == "running"  # unexpired deadline admits normally
    h1.request.timeout_s = 1e-9  # now let it lapse mid-run
    h2.cancel()
    time.sleep(0.01)
    assert s.sweep() == (1, 1)
    assert h1.status == "timeout"
    assert h2.status == "cancelled"
    assert kv.allocator.used == 0  # blocks returned
    # cancelled while still queued resolves at the next admit
    h3 = s.submit(Request(tokens=[5], max_new_tokens=2))
    h3.cancel()
    s.admit()
    assert h3.status == "cancelled"
    # a deadline that expires while QUEUED is dropped before admission
    # (no prefill work for a request nobody is waiting on)
    h4 = s.submit(Request(tokens=[6], max_new_tokens=2, timeout_s=1e-9))
    time.sleep(0.005)
    assert s.sweep_waiting() == (0, 1)
    assert h4.status == "timeout" and s.queue_depth == 0


def test_decode_state_is_fixed_shape():
    s, kv = _sched(max_slots=3)
    s.submit(Request(tokens=[7, 8, 9], max_new_tokens=4, temperature=0.5,
                     seed=11))
    s.admit()
    st = s.decode_state()
    assert len(st["tokens"]) == 3 and len(st["tables"]) == 3
    assert all(len(t) == kv.max_blocks_per_seq for t in st["tables"])
    assert st["active"] == [True, False, False]
    assert st["tokens"][0] == 9 and st["pos"][0] == 3
    assert st["temps"][0] == 0.5 and st["seeds"][0] == 11
    # inactive lanes park on the scratch block at pos 0
    assert st["tables"][1] == [SCRATCH_BLOCK] * kv.max_blocks_per_seq
    assert st["pos"][1] == 0


def _prefix_sched(num_blocks=33, block_size=4, max_seq_len=32,
                  max_slots=4, **kw):
    from hetu_galvatron_tpu.serving.prefix_cache import PrefixCache

    cfg = ModelArgs(hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, vocab_size=64,
                    max_position_embeddings=64,
                    make_vocab_size_divisible_by=1)
    kv = PagedKVCache(cfg, num_blocks=num_blocks, block_size=block_size,
                      max_seq_len=max_seq_len, dtype=jnp.float32)
    pc = PrefixCache(kv.allocator, block_size)
    return Scheduler(kv, max_slots=max_slots, max_position_embeddings=64,
                     prefix_cache=pc, **kw), kv, pc


def _seed_cache(s, tokens, max_new=4):
    """Run one request through admit -> note_prefilled -> retire so its
    prompt's full blocks live in the radix tree."""
    h = s.submit(Request(tokens=list(tokens), max_new_tokens=max_new))
    (slot, bucket), = s.admit()
    s.note_prefilled(slot)
    s.retire(slot, "done", "length")
    return h


def test_prefix_admission_charges_only_uncached_suffix():
    """A hit's admission cost is the SUFFIX bucket, not the prompt: with
    an 8-token prefill budget, two hit requests (suffix bucket 4 each)
    ride one step where cold twins (bucket 16) would be serialized."""
    sys_toks = [7] * 12  # 3 full blocks
    s, kv, pc = _prefix_sched(max_prefill_tokens=8)
    _seed_cache(s, sys_toks)
    for i in range(2):
        s.submit(Request(tokens=sys_toks + [20 + i, 30 + i],
                         max_new_tokens=2))
    admitted = s.admit()
    assert len(admitted) == 2  # 2 x bucket-4 suffixes fit the 8 budget
    assert all(sl.cached_len == 12 for sl, _ in admitted)
    assert all(b == 4 for _, b in admitted)
    assert all(sl.handle.cached_tokens == 12 for sl, _ in admitted)
    # cold twins of the same total length (14) bucket to 16 > 8: one per
    # step (the never-deadlock clause), proving the charge really is the
    # suffix, not the prompt
    s2, _, _ = _prefix_sched(max_prefill_tokens=8)
    for i in range(2):
        s2.submit(Request(tokens=[50 + i] * 14, max_new_tokens=2))
    assert len(s2.admit()) == 1


def test_fully_cached_prompt_admits_cleanly():
    """Zero uncached prefill tokens: no prefill dispatch (bucket 0), the
    slot enters at pos = len-1 with a copy-on-write of the last block,
    zero prefill-budget charge, and a FLOPs-derived budget divides
    nothing by zero."""
    sys_toks = [3] * 16  # exactly 4 blocks: a full-hit candidate
    s, kv, pc = _prefix_sched(prefill_flops_budget=400.0,
                              flops_per_token=100.0)  # cap = 4 tokens
    _seed_cache(s, sys_toks)
    h = s.submit(Request(tokens=list(sys_toks), max_new_tokens=4))
    admitted = s.admit()
    assert len(admitted) == 1
    slot, bucket = admitted[0]
    assert bucket == 0  # nothing to prefill
    assert slot.cached_len == 16 and h.cached_tokens == 16
    assert slot.pos == 15 and slot.last_token == sys_toks[-1]
    assert slot.cow is not None
    src, dst = slot.cow
    assert src not in slot.blocks and dst in slot.blocks
    assert dst in slot.owned_blocks  # the COW copy is private
    # table covers the whole budget: 20 tokens / bs 4 = 5 blocks
    assert len(slot.blocks) == 5
    # a second full-hit rides the same admit even under the 4-token cap
    # (charge is zero), while a cold 16-token twin would exceed it
    h2 = s.submit(Request(tokens=list(sys_toks), max_new_tokens=4))
    admitted2 = s.admit()
    assert len(admitted2) == 1 and admitted2[0][1] == 0
    del h2


def test_retirement_decrefs_shared_blocks_stay_cached():
    sys_toks = [9] * 8  # 2 blocks
    s, kv, pc = _prefix_sched()
    _seed_cache(s, sys_toks)
    held = kv.allocator.used
    assert pc.blocks_held == 2 and held == 2  # tree keeps the prefix
    h = s.submit(Request(tokens=sys_toks + [1, 2], max_new_tokens=2))
    (slot, bucket), = s.admit()
    assert bucket == 4 and slot.cached_len == 8
    shared = list(slot.blocks[:2])
    # tree ref + the running request's own ref: a stray strict free()
    # while a live sequence reads the blocks raises instead of corrupting
    assert all(kv.allocator.refcount(b) == 2 for b in shared)
    from hetu_galvatron_tpu.serving.kv_cache import BlockAccountingError
    with pytest.raises(BlockAccountingError, match="shared"):
        kv.allocator.free(shared)
    s.note_prefilled(slot)  # tree adopts the new full block too? (10//4=2
    # full blocks are exactly the cached ones -> nothing new)
    s.retire(slot, "done", "length")
    assert h.status == "done"
    # shared prefix survives retirement; the request's privates are gone
    assert pc.blocks_held == 2
    assert all(kv.allocator.refcount(b) == 1 for b in shared)
    assert kv.allocator.used == 2


def test_pool_pressure_evicts_cold_radix_nodes():
    """When the free list cannot satisfy an admission, unpinned radix
    nodes are evicted LRU-first instead of stalling the queue."""
    s, kv, pc = _prefix_sched(num_blocks=9, max_seq_len=32)  # 8 usable
    _seed_cache(s, [5] * 16, max_new=4)  # tree holds 4 blocks
    assert kv.allocator.available == 4
    # needs 6 blocks (16 prompt + 8 new @ bs 4): must evict the tree
    h = s.submit(Request(tokens=[6] * 16, max_new_tokens=8))
    (slot, bucket), = s.admit()
    assert h.status == "running"
    assert pc.blocks_held < 4  # cache gave blocks back
    del slot, bucket


def test_self_pinned_prefix_cannot_livelock_admission():
    """A request whose own match() pins the only evictable radix path
    must not stall forever when the pool cannot also satisfy its private
    need: admission drops the pins and retries COLD (evicting the now
    unpinned path) before concluding the pool is full."""
    s, kv, pc = _prefix_sched(num_blocks=8, block_size=4, max_seq_len=28,
                              max_slots=2)
    _seed_cache(s, [9] * 24, max_new=4)  # tree holds 6 of the 7 blocks
    assert kv.allocator.available == 1
    h = s.submit(Request(tokens=[9] * 24, max_new_tokens=4))
    admitted = s.admit()  # full hit needs 2 blocks; only 1 free
    assert len(admitted) == 1 and h.status == "running"
    slot, bucket = admitted[0]
    assert slot.cached_len == 0 and bucket > 0  # admitted cold
    assert pc.blocks_held == 0  # its own prefix was sacrificed
    s.retire(slot, "done", "length")
    assert kv.allocator.used == pc.blocks_held  # accounting coherent


def test_scheduler_defrag_rewrites_slots_and_radix():
    s, kv, pc = _prefix_sched()
    _seed_cache(s, [4] * 8)
    h = s.submit(Request(tokens=[4] * 8 + [9, 9], max_new_tokens=2))
    (slot, _), = s.admit()
    old_content_block = slot.blocks[0]
    kv.pools[0]["k"] = kv.pools[0]["k"].at[old_content_block].set(42.0)
    s.defrag()
    # every view renamed consistently: the tree's tables still name
    # exactly the slot's shared-prefix blocks (under the NEW ids)
    _, node_tables = pc.export_tables()
    assert sorted(set(b for t in node_tables for b in t)
                  ) == sorted(set(slot.blocks[:2]))
    assert set(slot.owned_blocks) <= set(slot.blocks)
    got = np.asarray(kv.pools[0]["k"][slot.blocks[0]])
    np.testing.assert_array_equal(got, np.full_like(got, 42.0))
    # allocator still coherent: retiring cleans up under the new names
    s.note_prefilled(slot)
    s.retire(slot, "done", "length")
    assert h.status == "done"
    assert kv.allocator.used == pc.blocks_held


def test_handle_stream_and_result():
    s, _ = _sched()
    h = s.submit(Request(tokens=[1], max_new_tokens=3))
    s.admit()
    slot = s.active[0]
    for t in (5, 6):
        h._emit(t)
    s.retire(slot, "done", "length")
    assert list(h.tokens()) == [5, 6]
    assert list(h.tokens()) == []  # re-iteration terminates, never hangs
    assert h.result(timeout=1) == [5, 6]
    assert h.ttft_s() is not None and h.ttft_s() >= 0
