"""Zero-downtime serving weight swap (ServingEngine.swap_weights): the
hot-swap contract — zero failed requests, zero recompiles, post-swap
outputs bit-matching a cold engine on the new checkpoint — plus the
prefix-cache invalidation and the typed rejection of shape drift."""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.serving.engine import ServingEngine, WeightSwapError

pytestmark = [pytest.mark.serving, pytest.mark.elastic]

CFG = ModelArgs(
    hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
    vocab_size=64, seq_length=16, max_position_embeddings=64,
    make_vocab_size_divisible_by=1, tie_word_embeddings=False)


def _engine(params, **over):
    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=64,
                     max_new_tokens=6, **over)
    return ServingEngine(params, CFG, sv, compute_dtype=jnp.float32)


def _params(seed):
    return init_causal_lm(jax.random.key(seed), CFG)[0]


def test_swap_weights_flips_to_new_checkpoint_without_recompiles():
    """The core contract on a quiet engine: after swap_weights the very
    next request streams exactly what a COLD engine on the new checkpoint
    streams, the jit caches never grow, and the telemetry counts the
    swap."""
    prompt = list(range(1, 12))
    eng = _engine(_params(1))
    eng.warmup()
    n0 = eng.compile_count()
    h_old = eng.submit(prompt)
    eng.run_until_idle()
    out_old = h_old.result()

    stall_ms = eng.swap_weights(_params(2))
    assert stall_ms >= 0.0
    h_new = eng.submit(prompt)
    eng.run_until_idle()
    out_new = h_new.result()
    assert eng.compile_count() == n0  # zero recompiles, ever

    cold = _engine(_params(2))
    hc = cold.submit(prompt)
    cold.run_until_idle()
    assert out_new == hc.result()  # bit-match the new checkpoint
    assert out_new != out_old      # ... and the weights really changed

    assert eng.registry.counter("serve/weight_swaps").value == 1
    assert eng.registry.histogram("serve/swap_stall_ms").count == 1
    eng.close()
    cold.close()


def test_swap_weights_rejects_shape_drift():
    """A hot swap may only replace VALUES: a different architecture must
    be rejected with the typed error, leaving the engine serving the old
    weights."""
    eng = _engine(_params(1))
    eng.warmup()
    bigger = CFG.model_copy(update={"hidden_size": 64,
                                    "ffn_hidden_size": 256})
    p_big = init_causal_lm(jax.random.key(3), bigger)[0]
    with pytest.raises(WeightSwapError):
        eng.swap_weights(p_big)
    # structure drift (extra/missing leaves) is typed too
    p_missing = jax.tree.map(lambda x: x, eng.params)
    p_missing["layers"][0]["attn"].pop("wqkv")
    with pytest.raises(WeightSwapError):
        eng.swap_weights(p_missing)
    h = eng.submit([1, 2, 3])
    eng.run_until_idle()
    assert h.status == "done"  # still serving
    eng.close()


def test_swap_invalidates_prefix_cache():
    """Pooled k/v was computed under the OLD weights: a post-swap request
    sharing a cached prefix must prefill COLD (no stale splice) and still
    bit-match a cold engine on the new checkpoint."""
    shared = list(range(1, 17))  # two full 8-token blocks
    eng = _engine(_params(1), prefix_cache=True)
    eng.warmup()
    h1 = eng.submit(shared + [20, 21])
    eng.run_until_idle()
    h2 = eng.submit(shared + [30, 31])  # warm-cache hit pre-swap
    eng.run_until_idle()
    assert eng.prefix.hits >= 1 and eng.prefix.blocks_held > 0

    eng.swap_weights(_params(2))
    assert eng.prefix.blocks_held == 0  # tree dropped at the flip

    h3 = eng.submit(shared + [30, 31])
    eng.run_until_idle()
    cold = _engine(_params(2), prefix_cache=True)
    hc = cold.submit(shared + [30, 31])
    cold.run_until_idle()
    assert h3.result() == hc.result()
    eng.close()
    cold.close()


def test_prefix_invalidate_zombie_pins():
    """Tree mechanics without an engine: invalidate() frees unpinned
    nodes immediately; a node pinned by a live request detaches as a
    zombie whose blocks free at its last release — and the fresh tree
    never matches stale content."""
    from hetu_galvatron_tpu.serving.kv_cache import BlockAllocator
    from hetu_galvatron_tpu.serving.prefix_cache import PrefixCache

    alloc = BlockAllocator(32)
    cache = PrefixCache(alloc, block_size=4)
    toks_a = tuple(range(8))
    blocks_a = alloc.alloc(2)
    cache.insert(toks_a, blocks_a)
    toks_b = tuple(range(100, 108))
    blocks_b = alloc.alloc(2)
    cache.insert(toks_b, blocks_b)
    used0 = alloc.used

    # a live request pins path A
    n, blocks, path = cache.match(toks_a)
    assert n == 8 and path

    dropped = cache.invalidate()
    assert dropped == 2  # B freed now; A is pinned -> zombie
    assert cache.blocks_held == 2
    # stale content no longer matches
    n2, _, path2 = cache.match(toks_a)
    assert n2 == 0 and not path2

    # the pinned request retires: zombie blocks drop with its release
    cache.release(path)
    assert cache.blocks_held == 0
    # tree refs are gone; only the requests' own allocator refs remain
    alloc.decref(blocks_a)
    alloc.decref(blocks_b)
    assert alloc.used == used0 - 4


def test_serve_cli_watch_requires_ckpt(capsys):
    """watch=<s> without a checkpoint root to poll is a usage error, not
    a crash mid-serving."""
    from hetu_galvatron_tpu.cli.serve import main as serve_main

    zoo = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    rc = serve_main([os.path.join(zoo, "gpt2-small.yaml"),
                     "prompt=hi", "watch=1"])
    assert rc == 2
    assert "ckpt=" in capsys.readouterr().err


def test_weight_swap_load_drill(tmp_path):
    """THE serving acceptance drill: closed-loop load across a hot swap
    between two REAL trained checkpoints — every request completes (zero
    failed/dropped), the jit caches stay flat after the swap warms, and
    post-swap streams bit-match a cold engine on the new checkpoint."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.runtime.checkpoint import load_checkpoint

    zoo = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    save = str(tmp_path / "ckpt")
    args = args_from_cli([
        os.path.join(zoo, "gpt2-small.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_attention_heads=2", "model.vocab_size=64",
        "model.seq_length=16", "model.max_position_embeddings=64",
        "model.make_vocab_size_divisible_by=1",
        "model.tie_word_embeddings=false",
        "parallel.mixed_precision=fp32",
        "parallel.global_train_batch_size=8", "train.train_iters=2",
        f"ckpt.save={save}", "ckpt.save_interval=1",
    ], mode="train_dist")
    out = train(args)
    assert out["exit_code"] is None
    cfg = args.model
    target = jax.eval_shape(lambda k: init_causal_lm(k, cfg)[0],
                            jax.random.key(0))
    p1, _, _ = load_checkpoint(os.path.join(save, "step_1"), target)
    p2, _, _ = load_checkpoint(os.path.join(save, "step_2"), target)

    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=64,
                     max_new_tokens=6, prefix_cache=True)
    eng = ServingEngine(p1, cfg, sv, compute_dtype=jnp.float32)
    eng.warmup()
    n0 = eng.compile_count()
    eng.start()

    shared = list(range(1, 17))
    rng = np.random.RandomState(0)
    pre = [eng.submit(shared + rng.randint(1, 60, 3).tolist())
           for _ in range(8)]
    time.sleep(0.05)  # the load is mid-flight when the roll begins
    stall_ms = eng.swap_weights(p2)
    post_prompts = [shared + rng.randint(1, 60, 3).tolist()
                    for _ in range(8)]
    post = [eng.submit(p) for p in post_prompts]
    for h in pre + post:
        h.result(timeout=120)
    eng.stop()

    # zero failed/dropped requests across the roll
    assert all(h.status == "done" for h in pre + post)
    assert eng.registry.counter("serve/requests_rejected").value == 0
    assert eng.error is None
    # zero steady-state recompiles after the swap warms (no new programs
    # at all: same shapes, same shardings)
    assert eng.compile_count() == n0
    assert stall_ms < 5000.0  # the blip is bounded; the flip is host-only

    # post-swap outputs bit-match a cold engine on the new checkpoint
    cold = ServingEngine(p2, cfg, sv, compute_dtype=jnp.float32)
    for h, prompt in zip(post, post_prompts):
        hc = cold.submit(prompt)
        cold.run_until_idle()
        assert h.result() == hc.result()
    eng.close()
    cold.close()
