"""Request-lifecycle tracing through the serving engine: complete
timelines, additive TTFT component split, queue-wait histogram, SLO
attainment gauges, zero steady-state recompiles with tracing ON, the
serve/errors counter (exception class label) on an injected failing
step, and the flight dump on engine abort."""

import io
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.cli.summarize import (
    request_timelines,
    summarize,
    timeline_complete,
    ttft_components,
)
from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.observability.sinks import JsonlSink
from hetu_galvatron_tpu.serving.engine import ServingEngine

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = dict(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=128, seq_length=32,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def _traced_engine(tmp_path, params, cfg, **sv_kw):
    metrics_path = str(tmp_path / "serve_metrics.jsonl")
    reg = MetricsRegistry([JsonlSink(metrics_path)])
    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=48,
                     max_new_tokens=8, trace_requests=True,
                     slo_ttft_ms=60_000.0, slo_itl_ms=60_000.0,
                     flush_interval=4, **sv_kw)
    eng = ServingEngine(params, cfg, sv, registry=reg,
                        compute_dtype=jnp.float32)
    return eng, reg, metrics_path


def test_complete_timelines_and_additive_ttft_split(tmp_path):
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    eng, reg, metrics_path = _traced_engine(tmp_path, params, cfg)
    eng.warmup(buckets=[8, 16])
    warm = eng.compile_count()
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, 128, (n,)).tolist(), m)
            for n, m in [(3, 4), (9, 6), (13, 5), (1, 8), (7, 3)]]
    handles = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
    eng.run_until_idle()
    eng.close()
    reg.close()

    # tracing adds zero steady-state recompiles (host-side events only)
    assert eng.compile_count() == warm
    assert all(h.status == "done" for h in handles)

    records = [json.loads(line) for line in open(metrics_path)]
    timelines, bad = request_timelines(records)
    assert bad == 0
    rids = {h.request.rid for h in handles}
    assert set(timelines) == rids  # no orphaned or missing requests
    for rid, evs in timelines.items():
        assert timeline_complete(evs), (rid, [e["ev"] for e in evs])
        names = [e["ev"] for e in evs]
        assert names[0] == "submit" and names[-1] == "retire"
        assert "admit" in names and "first_token" in names
        # one decode/verify window event per generated token after the
        # first (prefill produced token 1), retire reason length-bound
        ret = evs[-1]
        n_windows = sum(1 for e in evs if e["ev"] in ("decode", "verify"))
        assert n_windows == ret["generated"] - 1

    # the component split sums to measured TTFT (additive by design)
    comp = ttft_components(timelines)
    assert len(comp["ttft"]) == len(rids)
    for q, p, d, t in zip(comp["queue"], comp["prefill"],
                          comp["first_decode"], comp["ttft"]):
        assert q + p + d == pytest.approx(t, abs=1e-6)
        assert p > 0  # cold requests really paid a prefill

    # ... and the handle-side TTFT agrees with the event's within jitter
    by_rid = {h.request.rid: h for h in handles}
    for rid, evs in timelines.items():
        ft = next(e for e in evs if e["ev"] == "first_token")
        assert ft["ttft_ms"] == pytest.approx(
            by_rid[rid].ttft_s() * 1000.0, rel=0.05, abs=0.5)

    # queue-wait histogram (satellite): one observation per admission
    qw = [r for r in records if r.get("name") == "serve/queue_wait_ms"]
    assert qw and qw[-1]["count"] == len(rids)

    # SLO attainment gauges exported (generous targets -> 1.0)
    names = {(r.get("kind"), r.get("name")) for r in records}
    assert ("gauge", "serve/slo_ttft_attainment") in names
    assert ("gauge", "serve/slo_itl_attainment") in names
    slo = [r for r in records
           if r.get("name") == "serve/slo_ttft_attainment"]
    assert slo[-1]["value"] == 1.0

    # summarize renders the breakdown and timelines
    buf = io.StringIO()
    headline = summarize(metrics_path, out=buf, timeline="all")
    text = buf.getvalue()
    assert headline["requests_traced"] == len(rids)
    assert headline["timelines_complete"] == len(rids)
    assert "TTFT breakdown" in text and "first_decode" in text
    assert "SLO" in text and "request timelines" in text
    assert headline["ttft_queue_p50_ms"] >= 0


def test_rejected_request_has_complete_timeline(tmp_path):
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    eng, reg, metrics_path = _traced_engine(tmp_path, params, cfg)
    h = eng.submit([5] * 100, max_new_tokens=8)  # can never fit
    assert h.status == "rejected"
    eng.close()
    reg.close()
    records = [json.loads(line) for line in open(metrics_path)]
    timelines, _ = request_timelines(records)
    evs = timelines[h.request.rid]
    assert [e["ev"] for e in evs] == ["submit", "retire"]
    assert evs[-1]["status"] == "rejected"
    assert timeline_complete(evs)


def test_tight_slo_reports_partial_attainment(tmp_path):
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(2), cfg)
    eng, reg, metrics_path = _traced_engine(
        tmp_path, params, cfg)
    eng.serving = eng.serving.model_copy(update={"slo_ttft_ms": 1e-6})
    h = eng.submit([1, 2, 3], max_new_tokens=3)
    eng.run_until_idle()
    eng.close()
    reg.close()
    assert h.status == "done"
    assert reg.gauge("serve/slo_ttft_attainment").value == 0.0
    assert reg.gauge("serve/slo_ttft_ms").value == pytest.approx(1e-6)


def test_engine_error_counter_labeled_and_flight_dump(tmp_path):
    """Satellite: an engine-error retirement must leave a labeled
    serve/errors counter, retire events, and a flight dump."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(3), cfg)
    eng, reg, metrics_path = _traced_engine(
        tmp_path, params, cfg, flight_dir=str(tmp_path / "flight"))

    def boom(slot, bucket):
        raise RuntimeError("injected step failure")

    eng._prefill_slot = boom
    eng.start()
    h = eng.submit([1, 2, 3])
    assert h.result(timeout=30) == []
    assert h.status == "error"
    eng.close()
    reg.close()

    # the counter carries the exception class as a label
    assert reg.counter("serve/errors", error="RuntimeError").value == 1
    assert reg.counter("serve/engine_errors").value == 1

    # flight dump: parseable, carries the traceback and the event ring
    dumps = os.listdir(tmp_path / "flight")
    assert len(dumps) == 1 and dumps[0].startswith("flight_")
    assert eng.recorder.dumped
    with open(tmp_path / "flight" / dumps[0]) as f:
        flight = json.load(f)
    assert flight["reason"] == "engine_error"
    assert flight["exception"]["type"] == "RuntimeError"
    assert "injected step failure" in flight["exception"]["traceback"]
    assert any(e["data"].get("ev") == "submit" for e in flight["events"])

    # the timeline in the metrics stream still terminates (retire/error)
    records = [json.loads(line) for line in open(metrics_path)]
    timelines, _ = request_timelines(records)
    evs = timelines[h.request.rid]
    assert evs[-1]["ev"] == "retire" and evs[-1]["status"] == "error"


def test_tracing_off_emits_no_request_events(tmp_path):
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(4), cfg)
    metrics_path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(metrics_path)])
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=4)
    eng = ServingEngine(params, cfg, sv, registry=reg,
                        compute_dtype=jnp.float32)
    h = eng.submit([1, 2, 3])
    eng.run_until_idle()
    eng.close()
    reg.close()
    assert h.status == "done"
    records = [json.loads(line) for line in open(metrics_path)]
    timelines, _ = request_timelines(records)
    assert timelines == {}
    # with tracing off AND no flight_dir the recorder tap is not even
    # attached — the default serving path pays nothing per token
    assert eng.recorder.events() == []


def test_flight_dir_alone_keeps_ring_context(tmp_path):
    """flight_dir without trace_requests: no JSONL stream, but the
    recorder ring still captures the lifecycle for crash dumps."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(6), cfg)
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=4, flight_dir=str(tmp_path / "fl"))
    eng = ServingEngine(params, cfg, sv, registry=MetricsRegistry(),
                        compute_dtype=jnp.float32)
    h = eng.submit([1, 2, 3])
    eng.run_until_idle()
    eng.close()
    assert h.status == "done"
    assert any(e["data"].get("ev") == "retire"
               for e in eng.recorder.events())
