"""Paged KV cache: allocator lifecycle, pool scatter/gather, paged
attention parity with the dense-cache decode math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.serving.kv_cache import (
    SCRATCH_BLOCK,
    BlockAllocator,
    PagedKVCache,
    gather_pages,
    paged_sdpa,
    pool_pspecs,
    scatter_prefill,
    scatter_token,
)

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = dict(hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                vocab_size=64, max_position_embeddings=64, seq_length=32,
                make_vocab_size_divisible_by=1)
    base.update(kw)
    return ModelArgs(**base)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_cycle():
    a = BlockAllocator(8)
    assert a.available == 7  # block 0 is scratch
    x = a.alloc(3)
    y = a.alloc(4)
    assert a.available == 0 and a.used == 7
    assert SCRATCH_BLOCK not in x + y
    assert len(set(x + y)) == 7  # no block handed out twice
    assert a.alloc(1) is None  # pool exhausted -> no partial grant
    a.free(x)
    assert a.available == 3
    z = a.alloc(2)
    assert set(z) <= set(x)  # LIFO recycling


def test_allocator_rejects_bad_frees():
    a = BlockAllocator(4)
    x = a.alloc(1)
    with pytest.raises(ValueError):
        a.free([SCRATCH_BLOCK])
    with pytest.raises(ValueError):
        a.free([99])
    a.free(x)
    with pytest.raises(ValueError):
        a.free(x)  # double free


def test_defrag_compacts_live_blocks():
    cfg = _cfg()
    kv = PagedKVCache(cfg, num_blocks=9, block_size=4, max_seq_len=16,
                      dtype=jnp.float32)
    t1 = kv.allocator.alloc(2)
    t2 = kv.allocator.alloc(2)
    t3 = kv.allocator.alloc(2)
    kv.allocator.free(t2)  # leave a hole
    # write a recognizable value through each live block
    for j, b in enumerate(t1 + t3):
        for L in range(cfg.num_hidden_layers):
            kv.pools[L]["k"] = kv.pools[L]["k"].at[b].set(float(j + 1))
    before = [np.asarray(gather_pages(kv.pools[0]["k"],
                                      jnp.asarray([t], jnp.int32)[None]))
              for t in t1 + t3]
    new_tables = kv.defrag([t1, t3])
    # live ids now occupy 1..4, free list is the tail
    assert sorted(b for t in new_tables for b in t) == [1, 2, 3, 4]
    assert kv.allocator.available == 4
    after = [np.asarray(gather_pages(kv.pools[0]["k"],
                                     jnp.asarray([b], jnp.int32)[None]))
             for t in new_tables for b in t]
    for b4, a4 in zip(before, after):
        np.testing.assert_array_equal(b4, a4)


def test_defrag_rejects_inconsistent_tables():
    cfg = _cfg()
    kv = PagedKVCache(cfg, num_blocks=6, block_size=4, max_seq_len=8,
                      dtype=jnp.float32)
    t = kv.allocator.alloc(2)
    with pytest.raises(ValueError):
        kv.defrag([t[:1]])  # one outstanding block unaccounted for


# ---------------------------------------------------------------------------
# pool ops
# ---------------------------------------------------------------------------


def test_scatter_gather_roundtrip():
    P, bs, K, D = 6, 4, 2, 8
    pool = jnp.zeros((P, bs, K, D), jnp.float32)
    kv = jnp.arange(8 * K * D, dtype=jnp.float32).reshape(8, K, D)
    table = jnp.asarray([3, 1], jnp.int32)  # deliberately out of order
    pool = scatter_prefill(pool, kv, table)
    got = gather_pages(pool, jnp.asarray([[3, 1]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(kv))
    # single-token scatter at position 9 (block 1 of the table, offset 1)
    tok = jnp.full((1, K, D), -7.0)
    pool = scatter_token(pool, tok, jnp.asarray([1], jnp.int32),
                         jnp.asarray([1], jnp.int32))
    got = gather_pages(pool, jnp.asarray([[3, 1]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(got)[0, 5], np.asarray(tok)[0])


def test_paged_sdpa_matches_dense_cached_sdpa():
    """paged_sdpa == models/generate._cached_sdpa row-for-row at the
    row's own position (GQA geometry)."""
    from hetu_galvatron_tpu.models.generate import _cached_sdpa

    rng = np.random.RandomState(0)
    S, T, nq, nkv, D = 3, 16, 4, 2, 8
    q = jnp.asarray(rng.randn(S, 1, nq, D), jnp.float32)
    ck = jnp.asarray(rng.randn(S, T, nkv, D), jnp.float32)
    cv = jnp.asarray(rng.randn(S, T, nkv, D), jnp.float32)
    pos = jnp.asarray([2, 9, 15], jnp.int32)
    got = np.asarray(paged_sdpa(q, ck, cv, pos))
    for b in range(S):
        want = _cached_sdpa(q[b:b + 1], ck[b:b + 1], cv[b:b + 1],
                            int(pos[b]))
        np.testing.assert_allclose(got[b], np.asarray(want)[0], rtol=1e-6)


def test_pool_sizing_and_occupancy():
    cfg = _cfg(num_key_value_heads=2)  # GQA: pool stores kv heads only
    kv = PagedKVCache(cfg, num_blocks=5, block_size=4, max_seq_len=10,
                      dtype=jnp.float32)
    assert kv.pools[0]["k"].shape == (5, 4, 2, cfg.head_dim)
    assert kv.max_blocks_per_seq == 3  # ceil(10/4)
    assert kv.blocks_for(9) == 3 and kv.blocks_for(4) == 1
    assert kv.fits(10) and not kv.fits(13)
    assert kv.occupancy == 0.0
    kv.allocator.alloc(2)
    assert kv.occupancy == pytest.approx(0.5)


def test_pool_pspecs_follow_tp_axes():
    from jax.sharding import PartitionSpec as P

    class Sh:
        def __init__(self, tp_axes, ulysses=False):
            self.tp_axes = tp_axes
            self.ulysses = ulysses

    specs = pool_pspecs([Sh(("d1",)), Sh(("d0", "d1")),
                         Sh(("d1",), ulysses=True)], 3, kv_heads=2)
    assert specs[0] == P(None, None, ("d1",), None)
    # tp=4 does not divide kv_heads=2 -> replicate
    assert specs[1] == P(None, None, None, None)
    # ulysses tp axes carry sequence, not heads -> replicate
    assert specs[2] == P(None, None, None, None)
    assert pool_pspecs(None, 2, 2) == [P(None, None, None, None)] * 2
