"""Paged KV cache: allocator lifecycle, pool scatter/gather, paged
attention parity with the dense-cache decode math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.serving.kv_cache import (
    SCRATCH_BLOCK,
    BlockAccountingError,
    BlockAllocator,
    PagedKVCache,
    gather_pages,
    paged_sdpa,
    paged_sdpa_window,
    pool_pspecs,
    scatter_prefill,
    scatter_token,
)

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = dict(hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                vocab_size=64, max_position_embeddings=64, seq_length=32,
                make_vocab_size_divisible_by=1)
    base.update(kw)
    return ModelArgs(**base)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_cycle():
    a = BlockAllocator(8)
    assert a.available == 7  # block 0 is scratch
    x = a.alloc(3)
    y = a.alloc(4)
    assert a.available == 0 and a.used == 7
    assert SCRATCH_BLOCK not in x + y
    assert len(set(x + y)) == 7  # no block handed out twice
    assert a.alloc(1) is None  # pool exhausted -> no partial grant
    a.free(x)
    assert a.available == 3
    z = a.alloc(2)
    assert set(z) <= set(x)  # LIFO recycling


def test_allocator_rejects_bad_frees():
    a = BlockAllocator(4)
    x = a.alloc(1)
    with pytest.raises(ValueError):
        a.free([SCRATCH_BLOCK])
    with pytest.raises(ValueError):
        a.free([99])
    a.free(x)
    with pytest.raises(ValueError):
        a.free(x)  # double free


def test_refcount_share_decref_lifecycle():
    """Sharing semantics: incref adds an owner, decref drops one, the
    block returns to the free list only when the LAST owner leaves."""
    a = BlockAllocator(8)
    x = a.alloc(2)
    assert all(a.refcount(b) == 1 for b in x)
    a.incref(x)  # second owner (e.g. the radix tree adopting the blocks)
    assert all(a.refcount(b) == 2 for b in x)
    assert a.decref(x) == []  # still co-owned: nothing freed
    assert a.used == 2
    assert sorted(a.decref(x)) == sorted(x)  # last owner out -> recycled
    assert a.used == 0
    with pytest.raises(BlockAccountingError):
        a.decref(x)  # double free, typed
    with pytest.raises(BlockAccountingError):
        a.incref([x[0]])  # can't adopt an unallocated block


def test_free_while_shared_raises_typed_error():
    """Strict free() of a co-owned block must raise (a silent free would
    yank a block out from under the other owner's table) — and the error
    is typed so callers can tell bookkeeping bugs from other
    ValueErrors."""
    a = BlockAllocator(8)
    x = a.alloc(1)
    a.incref(x)
    with pytest.raises(BlockAccountingError, match="shared"):
        a.free(x)
    assert a.refcount(x[0]) == 2  # nothing changed
    a.decref(x)
    a.free(x)  # sole owner again: strict free is fine
    with pytest.raises(BlockAccountingError, match="double free"):
        a.free(x)
    with pytest.raises(BlockAccountingError):
        a.free([0])  # scratch is never freeable
    # a duplicated id within ONE call must raise, not double-release
    # (validate-then-mutate would otherwise hand the block out twice)
    y = a.alloc(1)
    with pytest.raises(BlockAccountingError, match="duplicate"):
        a.free(y + y)
    with pytest.raises(BlockAccountingError, match="duplicate"):
        a.decref(y + y)
    assert a.refcount(y[0]) == 1  # untouched by the rejected calls


def test_defrag_rewrites_every_referencing_table_and_keeps_refcounts():
    """Compaction with refcount>1 blocks: the same block appears in
    several tables (a sequence's view + the radix tree's view); defrag
    must rename it consistently EVERYWHERE, preserve contents, and carry
    the refcounts through the permutation."""
    cfg = _cfg()
    kv = PagedKVCache(cfg, num_blocks=9, block_size=4, max_seq_len=16,
                      dtype=jnp.float32)
    shared = kv.allocator.alloc(2)  # a cached prefix: seq + tree own it
    kv.allocator.incref(shared)
    hole = kv.allocator.alloc(1)
    private = kv.allocator.alloc(1)
    kv.allocator.decref(hole)  # leave a hole so compaction moves things
    for j, b in enumerate(shared + private):
        kv.pools[0]["k"] = kv.pools[0]["k"].at[b].set(float(j + 1))
    seq_table = shared + private
    tree_table = list(shared)
    new_seq, new_tree = kv.defrag([seq_table, tree_table])
    assert new_seq[:2] == new_tree  # shared ids renamed consistently
    assert sorted(new_seq) == [1, 2, 3]  # compacted to the low indices
    assert kv.allocator.refcount(new_tree[0]) == 2  # rc survived the move
    assert kv.allocator.refcount(new_seq[2]) == 1
    got = np.asarray(gather_pages(
        kv.pools[0]["k"], jnp.asarray([new_seq], jnp.int32)))[0]
    want = np.concatenate([np.full((4, cfg.kv_heads, cfg.head_dim), v)
                           for v in (1.0, 2.0, 3.0)])
    np.testing.assert_array_equal(got, want)
    # decref to zero -> everything recycles cleanly under the new names
    assert sorted(kv.allocator.decref(new_seq[2:])) == [new_seq[2]]
    kv.allocator.decref(new_tree)
    assert sorted(kv.allocator.decref(new_tree)) == sorted(new_tree)
    assert kv.allocator.used == 0


def test_defrag_rejects_table_referencing_free_block():
    cfg = _cfg()
    kv = PagedKVCache(cfg, num_blocks=6, block_size=4, max_seq_len=8,
                      dtype=jnp.float32)
    t = kv.allocator.alloc(2)
    kv.allocator.decref(t[1:])
    with pytest.raises(BlockAccountingError):
        kv.defrag([t])  # t[1] is free — a stale table must be loud


def test_paged_sdpa_window_matches_sequential_rows():
    """Row j of a W-wide window == paged_sdpa at position start+j with
    the same cache (the bit-parity the verify program and the
    prefix-suffix prefill both ride on)."""
    rng = np.random.RandomState(0)
    S, W, T, nq, nkv, D = 2, 3, 16, 4, 2, 8
    q = jnp.asarray(rng.randn(S, W, nq, D), jnp.float32)
    ck = jnp.asarray(rng.randn(S, T, nkv, D), jnp.float32)
    cv = jnp.asarray(rng.randn(S, T, nkv, D), jnp.float32)
    start = jnp.asarray([2, 9], jnp.int32)
    got = np.asarray(paged_sdpa_window(q, ck, cv, start))
    for j in range(W):
        want = np.asarray(paged_sdpa(q[:, j:j + 1], ck, cv, start + j))
        np.testing.assert_array_equal(got[:, j:j + 1], want)


def test_defrag_compacts_live_blocks():
    cfg = _cfg()
    kv = PagedKVCache(cfg, num_blocks=9, block_size=4, max_seq_len=16,
                      dtype=jnp.float32)
    t1 = kv.allocator.alloc(2)
    t2 = kv.allocator.alloc(2)
    t3 = kv.allocator.alloc(2)
    kv.allocator.free(t2)  # leave a hole
    # write a recognizable value through each live block
    for j, b in enumerate(t1 + t3):
        for L in range(cfg.num_hidden_layers):
            kv.pools[L]["k"] = kv.pools[L]["k"].at[b].set(float(j + 1))
    before = [np.asarray(gather_pages(kv.pools[0]["k"],
                                      jnp.asarray([t], jnp.int32)[None]))
              for t in t1 + t3]
    new_tables = kv.defrag([t1, t3])
    # live ids now occupy 1..4, free list is the tail
    assert sorted(b for t in new_tables for b in t) == [1, 2, 3, 4]
    assert kv.allocator.available == 4
    after = [np.asarray(gather_pages(kv.pools[0]["k"],
                                     jnp.asarray([b], jnp.int32)[None]))
             for t in new_tables for b in t]
    for b4, a4 in zip(before, after):
        np.testing.assert_array_equal(b4, a4)


def test_defrag_rejects_inconsistent_tables():
    cfg = _cfg()
    kv = PagedKVCache(cfg, num_blocks=6, block_size=4, max_seq_len=8,
                      dtype=jnp.float32)
    t = kv.allocator.alloc(2)
    with pytest.raises(ValueError):
        kv.defrag([t[:1]])  # one outstanding block unaccounted for


# ---------------------------------------------------------------------------
# pool ops
# ---------------------------------------------------------------------------


def test_scatter_gather_roundtrip():
    P, bs, K, D = 6, 4, 2, 8
    pool = jnp.zeros((P, bs, K, D), jnp.float32)
    kv = jnp.arange(8 * K * D, dtype=jnp.float32).reshape(8, K, D)
    table = jnp.asarray([3, 1], jnp.int32)  # deliberately out of order
    pool = scatter_prefill(pool, kv, table)
    got = gather_pages(pool, jnp.asarray([[3, 1]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(kv))
    # single-token scatter at position 9 (block 1 of the table, offset 1)
    tok = jnp.full((1, K, D), -7.0)
    pool = scatter_token(pool, tok, jnp.asarray([1], jnp.int32),
                         jnp.asarray([1], jnp.int32))
    got = gather_pages(pool, jnp.asarray([[3, 1]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(got)[0, 5], np.asarray(tok)[0])


def test_paged_sdpa_matches_dense_cached_sdpa():
    """paged_sdpa == models/generate._cached_sdpa row-for-row at the
    row's own position (GQA geometry)."""
    from hetu_galvatron_tpu.models.generate import _cached_sdpa

    rng = np.random.RandomState(0)
    S, T, nq, nkv, D = 3, 16, 4, 2, 8
    q = jnp.asarray(rng.randn(S, 1, nq, D), jnp.float32)
    ck = jnp.asarray(rng.randn(S, T, nkv, D), jnp.float32)
    cv = jnp.asarray(rng.randn(S, T, nkv, D), jnp.float32)
    pos = jnp.asarray([2, 9, 15], jnp.int32)
    got = np.asarray(paged_sdpa(q, ck, cv, pos))
    for b in range(S):
        want = _cached_sdpa(q[b:b + 1], ck[b:b + 1], cv[b:b + 1],
                            int(pos[b]))
        np.testing.assert_allclose(got[b], np.asarray(want)[0], rtol=1e-6)


def test_pool_sizing_and_occupancy():
    cfg = _cfg(num_key_value_heads=2)  # GQA: pool stores kv heads only
    kv = PagedKVCache(cfg, num_blocks=5, block_size=4, max_seq_len=10,
                      dtype=jnp.float32)
    assert kv.pools[0]["k"].shape == (5, 4, 2, cfg.head_dim)
    assert kv.max_blocks_per_seq == 3  # ceil(10/4)
    assert kv.blocks_for(9) == 3 and kv.blocks_for(4) == 1
    assert kv.fits(10) and not kv.fits(13)
    assert kv.occupancy == 0.0
    kv.allocator.alloc(2)
    assert kv.occupancy == pytest.approx(0.5)


def test_pool_pspecs_follow_tp_axes():
    from jax.sharding import PartitionSpec as P

    class Sh:
        def __init__(self, tp_axes, ulysses=False):
            self.tp_axes = tp_axes
            self.ulysses = ulysses

    specs = pool_pspecs([Sh(("d1",)), Sh(("d0", "d1")),
                         Sh(("d1",), ulysses=True)], 3, kv_heads=2)
    assert specs[0] == P(None, None, ("d1",), None)
    # tp=4 does not divide kv_heads=2 -> replicate
    assert specs[1] == P(None, None, None, None)
    # ulysses tp axes carry sequence, not heads -> replicate
    assert specs[2] == P(None, None, None, None)
    assert pool_pspecs(None, 2, 2) == [P(None, None, None, None)] * 2
