"""Radix prefix cache: tree mechanics (match/insert/split/evict/pins),
engine integration (hit streams bit-identical to offline generate while
skipping cached-prefix prefill), and the shared-prefix acceptance drill
on the 8-device tp2 mesh."""

import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import (
    CoreArgs,
    ModelArgs,
    ServingArgs,
)
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.models.generate import generate
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.observability.sinks import JsonlSink
from hetu_galvatron_tpu.serving.engine import ServingEngine
from hetu_galvatron_tpu.serving.kv_cache import BlockAllocator
from hetu_galvatron_tpu.serving.prefix_cache import PrefixCache

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = dict(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=256, seq_length=32,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def _offline(params, cfg, prompt, n_new, cache={}):
    key = (id(params), len(prompt), n_new)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(lambda p, t: generate(
            p, t, cfg, n_new, pad_id=0, compute_dtype=jnp.float32))
        cache[key] = fn
    out = np.asarray(fn(params, jnp.asarray([prompt], jnp.int32)))
    return out[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# tree mechanics (host-side, no model)
# ---------------------------------------------------------------------------


def test_match_insert_roundtrip_block_aligned():
    a = BlockAllocator(32)
    pc = PrefixCache(a, 4)
    blocks = a.alloc(3)
    toks = list(range(12))
    assert pc.insert(toks, blocks) == blocks  # tree adopts (incref)
    assert all(a.refcount(b) == 2 for b in blocks)
    assert pc.blocks_held == 3
    # full match + overhang: only whole blocks of the PROMPT are usable
    cached, got, path = pc.match(toks + [99, 98])
    assert cached == 12 and got == blocks
    assert all(n.ref == 1 for n in path)  # pinned until release
    pc.release(path)
    # partial match: first 8 tokens shared, then divergence mid-edge
    cached, got, path = pc.match(toks[:8] + [77] * 8)
    assert cached == 8 and got == blocks[:2]
    pc.release(path)
    # sub-block prefixes are never claimed
    cached, got, path = pc.match(toks[:3])
    assert cached == 0 and got == [] and path == ()


def test_insert_splits_edges_and_dedupes():
    a = BlockAllocator(32)
    pc = PrefixCache(a, 4)
    b1 = a.alloc(3)
    toks1 = [1] * 4 + [2] * 4 + [3] * 4
    pc.insert(toks1, b1)
    # diverges after 2 blocks -> edge split, only the new tail adopted
    b2 = a.alloc(3)
    toks2 = [1] * 4 + [2] * 4 + [9] * 4
    assert pc.insert(toks2, b2) == b2[2:]
    assert pc.blocks_held == 4
    cached, got, p = pc.match(toks2)
    assert cached == 12 and got == b1[:2] + [b2[2]]
    pc.release(p)
    # an identical re-insert adopts nothing (first writer wins)
    b3 = a.alloc(3)
    assert pc.insert(toks1, b3) == []
    assert pc.blocks_held == 4


def test_lru_eviction_respects_pins():
    a = BlockAllocator(32)
    pc = PrefixCache(a, 4)
    ba = a.alloc(2)
    bb = a.alloc(2)
    pc.insert([1] * 8, ba)
    pc.insert([2] * 8, bb)
    # touch A so B is the LRU leaf, then pin B via a match
    _, _, pa = pc.match([1] * 8)
    pc.release(pa)
    _, _, pb = pc.match([2] * 8)
    held = pc.blocks_held
    # B (true LRU by stamp? A was touched later... both touched by match;
    # B most recently) -> LRU is A, but A is unpinned: evict takes A
    freed = pc.evict(1)
    assert freed == 2 and pc.blocks_held == held - 2
    # only B remains and it is pinned: nothing more can go
    assert pc.evict(10) == 0
    pc.release(pb)
    assert pc.evict(10) == 2
    assert pc.blocks_held == 0
    assert a.used == 4  # the requests' own references survive eviction
    a.decref(ba)
    a.decref(bb)
    assert a.used == 0


def test_max_blocks_cap_evicts_on_insert():
    a = BlockAllocator(64)
    pc = PrefixCache(a, 4, max_blocks=4)
    b1 = a.alloc(3)
    pc.insert([1] * 12, b1)
    b2 = a.alloc(3)
    pc.insert([2] * 12, b2)
    assert pc.blocks_held <= 4


# ---------------------------------------------------------------------------
# engine integration (single device)
# ---------------------------------------------------------------------------


def test_prefix_hits_bit_identical_and_skip_prefill():
    """Cold / partial-hit / full-hit requests all produce exactly the
    offline stream; hits skip the cached prefill tokens (prefill_tokens
    counts only suffixes) and steady state never recompiles."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    reg = MetricsRegistry()
    sv = ServingArgs(max_batch_size=4, kv_block_size=8, max_seq_len=64,
                     max_new_tokens=8, prefix_cache=True)
    eng = ServingEngine(params, cfg, sv, registry=reg,
                        compute_dtype=jnp.float32)
    eng.warmup(buckets=[8, 16, 32])  # every bucket this workload reaches
    warm = eng.compile_count()
    rng = np.random.RandomState(0)
    sys_toks = rng.randint(0, 128, (24,)).tolist()  # 3 full blocks
    cold = sys_toks + rng.randint(0, 128, (5,)).tolist()
    hit = sys_toks + rng.randint(0, 128, (9,)).tolist()
    full = list(sys_toks)  # 24 % 8 == 0: a fully-cached prompt

    h1 = eng.submit(cold)
    eng.run_until_idle()
    assert h1.cached_tokens == 0
    pre_cold = reg.counter("serve/prefill_tokens").value
    assert pre_cold == 29

    h2 = eng.submit(hit)
    h3 = eng.submit(full)
    eng.run_until_idle()
    assert h2.cached_tokens == 24 and h3.cached_tokens == 24
    # only the 9-token suffix hit the prefill program; the full hit none
    assert reg.counter("serve/prefill_tokens").value == pre_cold + 9
    for p, h in ((cold, h1), (hit, h2), (full, h3)):
        assert h.status == "done"
        assert h.result(0) == _offline(params, cfg, p, 8), len(p)
    # full hit recorded a TTFT (satellite: histogram still records)
    assert reg.histogram("serve/ttft_ms").count == 3
    assert eng.compile_count() == warm
    assert reg.counter("serve/prefix_hits").value == 2
    assert reg.counter("serve/prefix_cached_tokens").value == 48
    assert eng.prefix.hit_rate == pytest.approx(2 / 3)


def test_suffix_bucket_overshoot_at_table_capacity():
    """A pow-of-two suffix bucket can overshoot the per-sequence table
    capacity a deep cached prefix leaves (cached 8 + bucket 16 > 5-block
    table): the prefix-prefill program routes the overflow lanes' writes
    to scratch and the stream stays bit-exact."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(7), cfg)
    sv = ServingArgs(max_batch_size=2, kv_block_size=4, max_seq_len=20,
                     max_new_tokens=3, prefix_cache=True)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
    rng = np.random.RandomState(7)
    pre = rng.randint(0, 128, (8,)).tolist()
    h1 = eng.submit(pre + rng.randint(0, 128, (4,)).tolist(),
                    max_new_tokens=3)
    eng.run_until_idle()
    p2 = pre + rng.randint(0, 128, (9,)).tolist()  # 17 + 3 = capacity
    h2 = eng.submit(p2, max_new_tokens=3)
    eng.run_until_idle()
    assert h2.cached_tokens == 8
    assert len(eng.scheduler.padded_table(
        [])) == 5  # the capacity this test is about
    assert h1.status == "done" and h2.status == "done"
    assert h2.result(0) == _offline(params, cfg, p2, 3)


def test_prefix_engine_defrag_mid_serving():
    """defrag() between requests renames every table; later hits still
    reproduce the offline stream from the compacted pool."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=48,
                     max_new_tokens=6, prefix_cache=True)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
    rng = np.random.RandomState(1)
    sys_toks = rng.randint(0, 128, (16,)).tolist()
    p1 = sys_toks + rng.randint(0, 128, (3,)).tolist()
    p2 = sys_toks + rng.randint(0, 128, (6,)).tolist()
    h1 = eng.submit(p1)
    eng.run_until_idle()
    eng.defrag()
    h2 = eng.submit(p2)
    eng.run_until_idle()
    assert h2.cached_tokens == 16
    assert h1.result(0) == _offline(params, cfg, p1, 6)
    assert h2.result(0) == _offline(params, cfg, p2, 6)


def test_eviction_under_pool_pressure_stays_correct():
    """A pool too small to keep the tree warm evicts cold prefixes to
    admit new work — streams stay exact either way."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(2), cfg)
    # 8 usable blocks, bs 8: one 24+8-token request needs 4, and each
    # retired request leaves 3 in the tree — the third admission must
    # evict the coldest prefix to proceed
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=8, num_kv_blocks=9, prefix_cache=True)
    eng = ServingEngine(params, cfg, sv, compute_dtype=jnp.float32)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 128, (24,)).tolist() for _ in range(4)]
    for p in prompts:
        h = eng.submit(p)
        eng.run_until_idle()
        assert h.status == "done"
        assert h.result(0) == _offline(params, cfg, p, 8)
    assert eng.prefix.evicted_blocks > 0  # pressure really evicted


# ---------------------------------------------------------------------------
# the shared-prefix acceptance drill (8-device CPU mesh, tp2 plan)
# ---------------------------------------------------------------------------


@pytest.mark.distributed
@pytest.mark.parametrize("spec", [False, True], ids=["plain", "spec"])
def test_shared_prefix_drill_mesh8(tmp_path, spec):
    """32 staggered requests sharing 3 system prompts under a tp2 plan on
    the 8-device mesh, rerun with request tracing ON: every stream
    bit-identical to offline generate() (with and without speculative
    decoding), zero steady-state recompiles (event emission is host-side
    only), cache-hit TTFT strictly below cold TTFT, every request
    yielding a complete ordered timeline whose TTFT components sum to
    the measured TTFT, SLO attainment gauges exported, and the serving
    gauges landing in the JSONL sink."""
    cfg = _cfg()
    args = CoreArgs(model=cfg.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.vocab_tp = 2
    args.parallel.global_train_batch_size = 8
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=jax.devices("cpu")[:8])
    params, axes = init_causal_lm(jax.random.key(0), cfg)

    metrics_path = str(tmp_path / "serve_metrics.jsonl")
    reg = MetricsRegistry([JsonlSink(metrics_path)])
    sv = ServingArgs(max_batch_size=8, kv_block_size=8, max_seq_len=128,
                     max_new_tokens=24, flush_interval=8,
                     prefix_cache=True, spec_decode=spec, spec_k=3,
                     trace_requests=True, slo_ttft_ms=120_000.0,
                     slo_itl_ms=120_000.0)
    eng = ServingEngine(params, cfg, sv, mesh=mesh, hpc=hpc,
                        axes_tree=axes, registry=reg,
                        compute_dtype=jnp.float32)
    rng = np.random.RandomState(0)
    sys_prompts = [rng.randint(0, 128, (88,)).tolist() for _ in range(3)]
    reqs = []
    for i in range(32):
        p = list(sys_prompts[i % 3]) + rng.randint(
            0, 128, (1 + i % 7,)).tolist()
        reqs.append((p, 4 + (i % 3) * 10))  # ragged budgets: 4/14/24

    eng.warmup(buckets=[8, 16, 32, 64, 128])
    warm = eng.compile_count()

    handles = []
    for wave in range(4):
        for p, m in reqs[wave * 8:(wave + 1) * 8]:
            handles.append(eng.submit(p, max_new_tokens=m))
        for _ in range(3):
            eng.step()
    eng.run_until_idle(max_steps=4000)

    # controlled TTFT A/B (idle engine, one request at a time — the
    # staggered waves above conflate TTFT with queueing): a cold request
    # pays the full 128-bucket prefill, a hit only its 8-token suffix
    cold_ttfts, hit_ttfts = [], []
    for rep in range(3):
        cold_p = rng.randint(0, 128, (88,)).tolist() + [rep]
        hc = eng.submit(cold_p, max_new_tokens=2)
        eng.run_until_idle()
        hit_p = list(sys_prompts[rep]) + [rep]
        hh = eng.submit(hit_p, max_new_tokens=2)
        eng.run_until_idle()
        assert hc.cached_tokens == 0 and hh.cached_tokens == 88
        cold_ttfts.append(hc.ttft_s())
        hit_ttfts.append(hh.ttft_s())
    assert float(np.median(hit_ttfts)) < float(np.median(cold_ttfts))

    eng.close()
    reg.close()

    assert eng.compile_count() == warm  # zero steady-state recompiles
    assert all(h.status == "done" for h in handles)
    for (p, m), h in zip(reqs, handles):
        assert h.result(0) == _offline(params, cfg, p, m), (len(p), m)
    n_hits = sum(1 for h in handles if h.cached_tokens >= 80)
    assert n_hits >= 20  # the trace really was shared-prefix dominated

    if spec:
        assert eng.spec_accept_rate() > 0.0

    records = [json.loads(line) for line in open(metrics_path)]
    names = {(r.get("kind"), r.get("name")) for r in records}
    assert ("gauge", "serve/prefix_hit_rate") in names
    assert ("gauge", "serve/slo_ttft_attainment") in names
    assert ("gauge", "serve/slo_itl_attainment") in names
    assert ("histogram", "serve/queue_wait_ms") in names
    if spec:
        assert ("gauge", "serve/spec_accept_rate") in names
        assert ("counter", "serve/drafted_tokens") in names

    # acceptance: every request (the 32 staggered + the 6 A/B probes)
    # yields a complete, ordered timeline, and the TTFT component split
    # is additive to the measured TTFT
    from hetu_galvatron_tpu.cli.summarize import (
        request_timelines,
        summarize,
        timeline_complete,
        ttft_components,
    )

    timelines, bad = request_timelines(records)
    assert bad == 0
    want_rids = {h.request.rid for h in handles} | {
        h.request.rid for h in (hc, hh)}
    assert want_rids <= set(timelines)
    for rid, evs in timelines.items():
        assert timeline_complete(evs), (rid, [e["ev"] for e in evs])
    comp = ttft_components(timelines)
    assert len(comp["ttft"]) == len(timelines)
    for q, p, d, t in zip(comp["queue"], comp["prefill"],
                          comp["first_decode"], comp["ttft"]):
        assert q + p + d == pytest.approx(t, abs=1e-6)
    # shared-prefix hits really skipped the cached prefill: the A/B hit
    # probe's admit shows the 11 matched blocks and its prefill dispatch
    # covered only the 1-token uncached suffix (the cold probe paid the
    # full 88-token prompt)
    hit_evs = timelines[hh.request.rid]
    admit = next(e for e in hit_evs if e["ev"] == "admit")
    assert admit["cached_len"] == 88 and admit["hit_blocks"] == 11
    hit_pf = next(e for e in hit_evs if e["ev"] == "prefill")
    assert hit_pf["cached"] == 88 and hit_pf["suffix"] == 1
    cold_evs = timelines[hc.request.rid]
    cold_pf = next(e for e in cold_evs if e["ev"] == "prefill")
    assert cold_pf["cached"] == 0 and cold_pf["suffix"] == 89

    buf = io.StringIO()
    headline = summarize(metrics_path, out=buf)
    text = buf.getvalue()
    assert "prefix hit rate" in text
    assert headline["prefix_hit_rate"] > 0.5
    assert headline["timelines_complete"] == headline["requests_traced"]
    assert "TTFT breakdown" in text and "SLO" in text
    assert headline["serve/slo_ttft_attainment"] == 1.0
    if spec:
        assert "spec accept rate" in text
