"""Flight recorder (observability/recorder.py): bounded ring, atomic
dump with exception context, the never-raises contract, and summarize's
handling of whole and torn dumps."""

import json
import os

import pytest

from hetu_galvatron_tpu.cli.summarize import summarize
from hetu_galvatron_tpu.observability.events import EventStream
from hetu_galvatron_tpu.observability.recorder import FlightRecorder
from hetu_galvatron_tpu.observability.registry import MetricsRegistry

pytestmark = pytest.mark.observability


def test_ring_is_bounded():
    rec = FlightRecorder(capacity=8, registry=MetricsRegistry())
    for i in range(50):
        rec.note("tick", i=i)
    evs = rec.events()
    assert len(evs) == 8
    assert evs[0]["data"]["i"] == 42 and evs[-1]["data"]["i"] == 49


def test_dump_atomic_parseable_with_exception(tmp_path):
    reg = MetricsRegistry()
    reg.counter("serve/steps").inc(5)
    ev = EventStream(reg)
    rec = FlightRecorder(registry=reg, out_dir=str(tmp_path)).attach(ev)
    ev.emit("submit", 1, prompt_len=3)
    try:
        raise ValueError("synthetic fault")
    except ValueError as e:
        path = rec.dump("crash", exc=e)
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path).startswith("flight_")
    # atomic: no .tmp residue
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    with open(path) as f:
        obj = json.load(f)
    assert obj["kind"] == "flight_recorder" and obj["reason"] == "crash"
    assert obj["exception"]["type"] == "ValueError"
    assert "synthetic fault" in obj["exception"]["traceback"]
    assert any(e["data"].get("ev") == "submit" for e in obj["events"])
    assert any(m["name"] == "serve/steps" and m["value"] == 5.0
               for m in obj["metrics"])
    assert rec.dumped == [path]


def test_dump_without_out_dir_is_noop():
    rec = FlightRecorder(registry=MetricsRegistry())
    rec.note("tick")
    assert rec.dump("whatever") is None
    assert rec.dumped == []


def test_dump_never_raises(tmp_path, monkeypatch):
    """The PR-6 contract extended: a failing dump must never mask the
    fault that triggered it."""
    rec = FlightRecorder(registry=MetricsRegistry(),
                         out_dir=str(tmp_path / "nope"))
    import hetu_galvatron_tpu.observability.recorder as R

    monkeypatch.setattr(R.json, "dump",
                        lambda *a, **k: (_ for _ in ()).throw(
                            OSError("disk full")))
    assert rec.dump("crash") is None  # swallowed, not raised
    assert isinstance(rec.last_error, OSError)


def test_summarize_renders_dump_and_survives_torn_dump(tmp_path, capsys):
    import io

    reg = MetricsRegistry()
    ev = EventStream(reg)
    rec = FlightRecorder(registry=reg, out_dir=str(tmp_path)).attach(ev)
    ev.emit("submit", 4, prompt_len=2)
    ev.emit("retire", 4, status="done", reason="eos", generated=1)
    path = rec.dump("signal:SIGTERM")
    buf = io.StringIO()
    head = summarize(path, out=buf)
    text = buf.getvalue()
    assert head["flight_reason"] == "signal:SIGTERM"
    assert "flight recorder dump" in text and "submit" in text

    # torn dump (crash mid-write of a pre-atomic copy): truncated JSON
    # must degrade to a warning + empty summary, never a traceback
    torn = tmp_path / "flight_torn.json"
    torn.write_text(open(path).read()[: 40])
    buf2 = io.StringIO()
    head2 = summarize(str(torn), out=buf2)
    err = capsys.readouterr().err
    assert "warning" in err and "skipped" in err
    assert head2.get("flight_reason") is None


def test_summarize_skips_corrupt_request_events(tmp_path, capsys):
    """Satellite hardening: torn event records in the metrics JSONL are
    warned about and skipped; intact timelines still render."""
    path = tmp_path / "m.jsonl"
    lines = [
        json.dumps({"t": 1.0, "kind": "event", "name": "request",
                    "data": {"ev": "submit", "rid": 1, "seq": 0,
                             "tm": 10.0, "prompt_len": 4, "max_new": 2}}),
        # corrupt: data is not a dict
        json.dumps({"t": 1.0, "kind": "event", "name": "request",
                    "data": [1, 2]}),
        # corrupt: missing rid/seq
        json.dumps({"t": 1.0, "kind": "event", "name": "request",
                    "data": {"ev": "admit"}}),
        # corrupt: seq is a string (must not TypeError the sort)
        json.dumps({"t": 1.0, "kind": "event", "name": "request",
                    "data": {"ev": "decode", "rid": 1, "seq": "x",
                             "tm": 11.0}}),
        # stream-level (no rid): NOT corrupt, surfaced as ENGINE ERROR
        json.dumps({"t": 1.0, "kind": "event", "name": "request",
                    "data": {"ev": "engine_error", "seq": 2, "tm": 11.5,
                             "error": "RuntimeError", "message": "boom"}}),
        json.dumps({"t": 1.0, "kind": "event", "name": "request",
                    "data": {"ev": "retire", "rid": 1, "seq": 1,
                             "tm": 12.0, "status": "done",
                             "reason": "eos", "generated": 2}}),
        '{"half a reco',  # torn line
    ]
    path.write_text("\n".join(lines) + "\n")
    import io

    buf = io.StringIO()
    head = summarize(str(path), out=buf)
    err = capsys.readouterr().err
    assert "corrupt request event" in err
    assert head["requests_traced"] == 1
    assert head["timelines_complete"] == 1
    # the rid-less engine_error record is not "corrupt" — it renders
    assert head["engine_error_events"] == 1
    assert "ENGINE ERROR: RuntimeError: boom" in buf.getvalue()


def test_summarize_cli_timeline_flag_parsing(tmp_path, capsys):
    """--timeline must not eat the file path (flag-first invocation) and
    a bare flag with no path prints usage instead of crashing."""
    from hetu_galvatron_tpu.cli.summarize import main

    path = tmp_path / "m.jsonl"
    path.write_text(json.dumps(
        {"t": 1.0, "kind": "counter", "name": "train/steps",
         "value": 2.0}) + "\n")
    assert main(["--timeline", str(path)]) == 0  # path not consumed
    assert "run summary" in capsys.readouterr().out
    assert main([str(path), "--timeline", "all"]) == 0
    capsys.readouterr()
    assert main(["--timeline"]) == 2  # usage, not IndexError
    assert "usage:" in capsys.readouterr().out
