"""Prometheus text-format exposition + the stdlib /metrics endpoint:
rendering rules (sanitized names, counter _total, histogram summary
convention, label escaping), a live scrape smoke over an ephemeral port,
and the serving.metrics_port engine wiring."""

import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from hetu_galvatron_tpu.observability.prometheus import (
    MetricsHTTPServer,
    prometheus_text,
    sanitize_name,
)
from hetu_galvatron_tpu.observability.registry import MetricsRegistry

pytestmark = pytest.mark.observability


def test_sanitize_name():
    assert sanitize_name("serve/ttft_ms") == "serve_ttft_ms"
    assert sanitize_name("audit/time_ratio") == "audit_time_ratio"
    assert sanitize_name("9lives") == "_9lives"


def test_prometheus_text_rendering():
    reg = MetricsRegistry()
    reg.counter("serve/requests", outcome="completed").inc(3)
    reg.gauge("serve/kv_occupancy").set(0.25)
    h = reg.histogram("serve/ttft_ms")
    for v in (10.0, 20.0, 30.0):
        h.observe(v)
    reg.gauge("audit/time_ratio", component="tp").set(1.2)
    text = prometheus_text(reg)
    lines = text.strip().splitlines()
    assert "# TYPE serve_requests_total counter" in lines
    assert 'serve_requests_total{outcome="completed"} 3.0' in lines
    assert "serve_kv_occupancy 0.25" in lines
    assert "# TYPE serve_ttft_ms summary" in lines
    assert 'serve_ttft_ms{quantile="0.5"} 20.0' in lines
    assert "serve_ttft_ms_sum 60.0" in lines
    assert "serve_ttft_ms_count 3" in lines
    assert 'audit_time_ratio{component="tp"} 1.2' in lines
    assert text.endswith("\n")


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.gauge("g", reason='quo"te\nnl').set(1.0)
    text = prometheus_text(reg)
    assert 'reason="quo\\"te\\nnl"' in text


def test_http_server_scrape_smoke():
    reg = MetricsRegistry()
    reg.counter("serve/submitted").inc(7)
    with MetricsHTTPServer(reg, port=0, host="127.0.0.1") as srv:
        assert srv.port > 0  # ephemeral port was bound and reported
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert "serve_submitted_total 7.0" in body
        # scrapes see live values, not a bind-time snapshot
        reg.counter("serve/submitted").inc()
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert "serve_submitted_total 8.0" in resp.read().decode()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5)
    srv.stop()  # idempotent after the context exit


def test_healthz_endpoint():
    """/healthz: 200 + JSON liveness payload (uptime, last-step age,
    health_fn merge) without paying for the text exposition; /metrics
    stays intact alongside."""
    import json
    import time

    reg = MetricsRegistry()
    reg.counter("serve/submitted").inc(1)
    srv = MetricsHTTPServer(reg, port=0, host="127.0.0.1",
                            health_fn=lambda: {"queue_depth": 3})
    with srv:
        url = f"http://127.0.0.1:{srv.port}/healthz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/json"
            payload = json.loads(resp.read().decode())
        assert payload["status"] == "ok"
        assert payload["uptime_s"] >= 0.0
        assert payload["last_step_age_s"] is None  # no step yet
        assert payload["queue_depth"] == 3  # health_fn merged
        srv.note_step()
        time.sleep(0.01)
        with urllib.request.urlopen(url, timeout=5) as resp:
            payload = json.loads(resp.read().decode())
        assert payload["last_step_age_s"] is not None
        assert 0.0 < payload["last_step_age_s"] < 5.0
        # both routes coexist
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as resp:
            assert "serve_submitted_total 1.0" in resp.read().decode()


def test_healthz_last_audit_age_and_calibration_scrape():
    """The calibration loop's freshness is visible on BOTH routes: the
    /healthz liveness payload carries last_audit_age_s (null until the
    first audit — "never audited" is distinguishable from "stale"), and
    the calibration/* gauges render under sanitized names on /metrics."""
    import json
    import time

    reg = MetricsRegistry()
    reg.gauge("calibration/plan_regret_ms").set(3.41)
    reg.gauge("calibration/drift_score").set(0.25)
    with MetricsHTTPServer(reg, port=0, host="127.0.0.1") as srv:
        hurl = f"http://127.0.0.1:{srv.port}/healthz"
        with urllib.request.urlopen(hurl, timeout=5) as resp:
            assert json.loads(resp.read())["last_audit_age_s"] is None
        srv.note_audit()
        time.sleep(0.01)
        with urllib.request.urlopen(hurl, timeout=5) as resp:
            payload = json.loads(resp.read())
        assert 0.0 < payload["last_audit_age_s"] < 5.0
        # a step does not refresh the audit age (they age independently)
        srv.note_step()
        with urllib.request.urlopen(hurl, timeout=5) as resp:
            payload2 = json.loads(resp.read())
        assert payload2["last_audit_age_s"] >= payload["last_audit_age_s"]
        murl = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(murl, timeout=5) as resp:
            body = resp.read().decode()
        assert "# TYPE calibration_plan_regret_ms gauge" in body
        assert "calibration_plan_regret_ms 3.41" in body
        assert "calibration_drift_score 0.25" in body


def test_healthz_health_fn_failure_keeps_probe_alive():
    import json

    def broken():
        raise RuntimeError("stats backend down")

    reg = MetricsRegistry()
    with MetricsHTTPServer(reg, port=0, health_fn=broken) as srv:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as resp:
            assert resp.status == 200  # the probe must not 500
            payload = json.loads(resp.read().decode())
        assert payload["status"] == "ok"
        assert "RuntimeError" in payload["health_fn_error"]


def test_serving_engine_healthz_wiring():
    """The engine marks each step for /healthz (last-step age reflects
    real engine progress)."""
    import json

    from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.serving.engine import ServingEngine

    cfg = ModelArgs(
        hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=64, seq_length=16,
        make_vocab_size_divisible_by=1, ffn_hidden_size=64,
        tie_word_embeddings=False)
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.float32), params)
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=4, metrics_port=0)
    eng = ServingEngine(params, cfg, sv, registry=MetricsRegistry(),
                        compute_dtype=jnp.float32)
    try:
        url = f"http://127.0.0.1:{eng.metrics_port}/healthz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert json.loads(resp.read())["last_step_age_s"] is None
        h = eng.submit([1, 2, 3])
        eng.run_until_idle()
        assert h.status == "done"
        with urllib.request.urlopen(url, timeout=5) as resp:
            payload = json.loads(resp.read())
        assert payload["last_step_age_s"] is not None
    finally:
        eng.close()


def test_serving_engine_metrics_port_wiring():
    """serving.metrics_port=0 binds an ephemeral endpoint for the engine's
    registry; close() tears it down. Off (None) by default."""
    from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.serving.engine import ServingEngine

    cfg = ModelArgs(
        hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=64, seq_length=16,
        make_vocab_size_divisible_by=1, ffn_hidden_size=64,
        tie_word_embeddings=False)
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.float32), params)
    sv = ServingArgs(max_batch_size=2, kv_block_size=8, max_seq_len=32,
                     max_new_tokens=4, metrics_port=0)
    reg = MetricsRegistry()
    eng = ServingEngine(params, cfg, sv, registry=reg)
    try:
        assert eng.metrics_port and eng.metrics_port > 0
        reg.gauge("serve/queue_depth").set(0.0)
        url = f"http://127.0.0.1:{eng.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert "serve_queue_depth 0.0" in resp.read().decode()
    finally:
        eng.close()
    assert eng.metrics_server is None
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(url, timeout=2)

    # default: no server
    off = ServingEngine(params, cfg, ServingArgs(
        max_batch_size=2, kv_block_size=8, max_seq_len=32,
        max_new_tokens=4), registry=MetricsRegistry())
    try:
        assert off.metrics_port is None and off.metrics_server is None
    finally:
        off.close()
