"""Unified telemetry layer: registry semantics, sink round-trips, span
nesting, MFU math against a hand-computed GPT-2-small example, and the
train_loop CPU smoke contract (JSONL emitted; no device sync in the hot
loop; <5% hook overhead)."""

import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs
from hetu_galvatron_tpu.observability import (
    JsonlSink,
    MetricsRegistry,
    TraceCapture,
    TrainingTelemetry,
    make_tensorboard_sink,
    peak_device_tflops,
    plan_comm_volume,
    span,
)
from hetu_galvatron_tpu.observability.tracing import current_span_path

pytestmark = pytest.mark.observability


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counters_gauges_and_label_identity():
    reg = MetricsRegistry()
    reg.counter("steps").inc()
    reg.counter("steps").inc(2)
    assert reg.counter("steps").value == 3
    # distinct labels are distinct instruments; same labels dedup
    reg.counter("bytes", collective="dp").inc(10)
    reg.counter("bytes", collective="tp").inc(20)
    assert reg.counter("bytes", collective="dp").value == 10
    assert reg.counter("bytes", collective="tp").value == 20
    reg.gauge("mem", stat="peak").set(5.0)
    reg.gauge("mem", stat="peak").set(7.0)  # last write wins
    assert reg.gauge("mem", stat="peak").value == 7.0
    # counters/gauges/histograms with the same NAME are separate metrics
    reg.histogram("steps").observe(1.0)
    assert reg.counter("steps").value == 3


def test_histogram_percentiles_and_cap():
    reg = MetricsRegistry()
    h = reg.histogram("lat")
    for v in range(1, 101):
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["min"] == 1.0 and snap["max"] == 100.0
    assert abs(snap["mean"] - 50.5) < 1e-9
    assert abs(snap["p50"] - 50.5) < 1.0
    assert 89 <= snap["p90"] <= 92 and 98 <= snap["p99"] <= 100
    # bounded memory: >cap observations decimate but count/sum stay exact
    h2 = reg.histogram("big")
    for v in range(10000):
        h2.observe(float(v))
    assert h2.count == 10000
    assert len(h2._samples) < 4096
    assert abs(h2.snapshot()["p50"] - 5000) / 5000 < 0.05


def test_jsonl_sink_roundtrip_and_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    reg.counter("train/steps").inc(4)
    reg.gauge("train/mfu").set(0.41)
    reg.histogram("train/step_time_ms", phase="train").observe(12.0)
    reg.event("plan", {"pp_deg": 2}, step=0)
    reg.flush(step=7)
    recs = [json.loads(l) for l in open(path)]
    kinds = {r["kind"] for r in recs}
    assert kinds == {"counter", "gauge", "histogram", "event"}
    by_name = {r["name"]: r for r in recs}
    assert by_name["train/steps"]["value"] == 4
    assert by_name["train/steps"]["step"] == 7
    assert by_name["train/mfu"]["value"] == 0.41
    h = by_name["train/step_time_ms"]
    assert h["labels"] == {"phase": "train"}
    for k in ("count", "mean", "min", "max", "p50", "p90", "p99"):
        assert k in h
    assert by_name["plan"]["data"] == {"pp_deg": 2}
    assert all("t" in r for r in recs)
    # counters carry CURRENT values: a second flush appends, last wins
    reg.counter("train/steps").inc()
    reg.close(step=8)
    recs = [json.loads(l) for l in open(path)]
    steps = [r for r in recs if r["name"] == "train/steps"]
    assert steps[-1]["value"] == 5


def test_tensorboard_sink_noop_path(tmp_path, monkeypatch):
    """The no-tensorboard path (what CI exercises): the factory degrades
    to None and configure() attaches only the JSONL sink."""
    from hetu_galvatron_tpu.observability.registry import (
        configure,
        get_registry,
        set_registry,
    )

    monkeypatch.setenv("HGTPU_NO_TENSORBOARD", "1")
    assert make_tensorboard_sink(str(tmp_path / "tb")) is None
    old = get_registry()
    try:
        reg = configure(jsonl_path=str(tmp_path / "m.jsonl"),
                        tensorboard_dir=str(tmp_path / "tb"))
        assert get_registry() is reg
        assert len(reg.sinks) == 1
        assert isinstance(reg.sinks[0], JsonlSink)
    finally:
        set_registry(old)


# ---------------------------------------------------------------------------
# spans + trace capture
# ---------------------------------------------------------------------------


def test_span_nesting_paths():
    reg = MetricsRegistry()
    with span("train", registry=reg):
        with span("fwd", registry=reg):
            assert current_span_path() == "train/fwd"
        with span("bwd", registry=reg):
            time.sleep(0.002)
    assert current_span_path() == ""
    paths = {m.labels["path"] for m in reg.metrics() if m.name == "span_ms"}
    assert paths == {"train", "train/fwd", "train/bwd"}
    bwd = reg.histogram("span_ms", path="train/bwd")
    assert bwd.count == 1 and bwd.snapshot()["max"] >= 1.0
    # the outer span covers its children
    outer = reg.histogram("span_ms", path="train")
    assert outer.snapshot()["max"] >= bwd.snapshot()["max"]


def test_span_survives_exceptions():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        with span("boom", registry=reg):
            raise ValueError("x")
    assert current_span_path() == ""
    assert reg.histogram("span_ms", path="boom").count == 1


def test_trace_capture_window(monkeypatch):
    calls = []
    import hetu_galvatron_tpu.observability.tracing as T

    class FakeProfiler:
        @staticmethod
        def start_trace(d):
            calls.append(("start", d))

        @staticmethod
        def stop_trace():
            calls.append(("stop", None))

    monkeypatch.setattr(jax, "profiler", FakeProfiler)
    tc = TraceCapture("/tmp/tr", start_iter=2, num_iters=2)
    traced = [tc.step(it) for it in range(6)]
    tc.stop()
    tc.stop()  # idempotent
    assert traced == [False, False, True, True, False, False]
    assert calls == [("start", "/tmp/tr"), ("stop", None)]
    # one capture per lifetime: the window does not re-arm
    assert tc.step(10) is False
    # disabled when no dir / enabled=False
    assert TraceCapture("", start_iter=0).step(0) is False
    assert TraceCapture("/tmp/x", enabled=False).step(0) is False


# ---------------------------------------------------------------------------
# MFU / FLOPs math
# ---------------------------------------------------------------------------


def test_model_flops_per_token_gpt2_small_hand_computed():
    from hetu_galvatron_tpu.core.cost_model.cost import model_flops_per_token

    cfg = ModelArgs()  # gpt2-small defaults: h=768 L=12 N=12 s=1024
    # hand computation (dense-score MFU convention, bwd = 2x fwd):
    h, s, ffn = 768, 1024, 4 * 768
    qkv_out = 4 * 2 * h * h              # q, k, v, out projections
    scores = 2 * 2 * s * h               # QK^T + PV, N*D == h
    mlp = 2 * 2 * h * ffn                # two ungated matrices
    per_layer = qkv_out + scores + mlp
    head = 2 * h * 50304                 # padded vocab (50257 -> %128)
    expect = 3 * (12 * per_layer + head)
    assert model_flops_per_token(cfg) == pytest.approx(expect, rel=1e-12)
    assert expect == 854_654_976  # the number a reviewer can re-derive


def test_model_flops_gqa_swiglu_and_moe():
    from hetu_galvatron_tpu.core.cost_model.cost import model_flops_per_token

    gqa = ModelArgs(hidden_size=64, num_hidden_layers=1,
                    num_attention_heads=8, num_key_value_heads=2,
                    seq_length=16, vocab_size=128, hidden_act="swiglu",
                    ffn_hidden_size=160, make_vocab_size_divisible_by=1)
    h, s, nd, kd = 64, 16, 64, 16
    per_layer = (2 * h * nd + 2 * 2 * h * kd + 2 * nd * h
                 + 2 * 2 * s * nd + 3 * 2 * h * 160)
    assert model_flops_per_token(gqa) == pytest.approx(
        3 * (per_layer + 2 * h * 128))
    # MoE: only active experts count; every freq-th layer is MoE
    moe = gqa.model_copy(update={
        "num_experts": 8, "moe_topk": 2, "num_shared_experts": 1,
        "num_hidden_layers": 2, "moe_layer_freq": 2,
        "moe_ffn_hidden_size": 96})
    moe_layer = (2 * h * nd + 2 * 2 * h * kd + 2 * nd * h + 2 * 2 * s * nd
                 + 2 * h * 8 + 3 * 3 * 2 * h * 96)
    assert model_flops_per_token(moe) == pytest.approx(
        3 * (per_layer + moe_layer + 2 * h * 128))


def test_mfu_gauge_math():
    reg = MetricsRegistry()
    cfg = ModelArgs()
    tel = TrainingTelemetry(reg, model=cfg, global_batch_size=8,
                            seq_length=1024, world_size=4,
                            peak_tflops_per_device=100.0, flush_interval=100)
    # synthesize a perfectly regular 100ms step cadence
    tel._times = [i * 0.1 for i in range(11)]
    tps = tel.tokens_per_sec()
    assert tps == pytest.approx(8 * 1024 / 0.1, rel=1e-6)
    tel.flush()
    mfu = reg.gauge("train/mfu").value
    expect = tps * tel.flops_per_token / (100.0e12 * 4)
    assert mfu == pytest.approx(expect, rel=1e-9)


@pytest.mark.robustness
def test_telemetry_resume_from_continues_counters():
    """A checkpoint-resumed run's cumulative step/token counters continue
    from the stored totals instead of restarting at zero (full-state
    resume carries the telemetry step)."""
    reg = MetricsRegistry()
    tel = TrainingTelemetry(reg, global_batch_size=4, seq_length=8,
                            flush_interval=100)
    tel.resume_from(10)
    assert reg.counter("train/steps").value == 10
    assert reg.counter("train/tokens").value == 10 * 4 * 8
    tel(10, {"loss": 1.0})
    assert reg.counter("train/steps").value == 11
    # resume_from(0) on a fresh run is a no-op
    reg2 = MetricsRegistry()
    TrainingTelemetry(reg2, global_batch_size=4, seq_length=8).resume_from(0)
    assert reg2.counter("train/steps").value == 0


def test_peak_tflops_table():
    assert peak_device_tflops("TPU v5 lite") == 197.0
    assert peak_device_tflops("TPU v4") == 275.0
    assert peak_device_tflops("TPU v5p") == 459.0
    assert peak_device_tflops("cpu") is None
    assert peak_device_tflops("") is None


# ---------------------------------------------------------------------------
# predicted plan comm volume
# ---------------------------------------------------------------------------


def test_plan_comm_volume_formulas():
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb
    from hetu_galvatron_tpu.utils.strategy import DPType, LayerStrategy

    cfg = ModelArgs(hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, seq_length=32, vocab_size=128,
                    make_vocab_size_divisible_by=1)
    layers = [
        LayerStrategy(tp_size=2, dp_size=2),                       # tp x dp
        LayerStrategy(tp_size=2, dp_size=2, sp=True,               # ulysses
                      dp_type=DPType.ZERO3),
    ]
    vols = plan_comm_volume(layers, cfg, global_bsz=8, chunks=2)
    pmb = layer_param_mb(cfg)
    # layer 0: tp=2 dp=2 -> sdp=2, grads bf16 over tp shards
    grad_mb = pmb / 2 * 0.5
    assert vols[0]["dp_allreduce_mb"] == pytest.approx(2 * 0.5 * grad_mb)
    lbsz = 8 // 2 // 2
    act_mb = lbsz * 32 * 64 * 2 / 2**20
    assert vols[0]["tp_collective_mb"] == pytest.approx(act_mb * 6 * 2)
    assert vols[0]["cp_ring_mb"] == 0.0 and vols[0]["pp_p2p_mb"] == 0.0
    # layer 1: Ulysses sp=2 -> 4 all-to-alls, full-size grads, sdp=dp*sp=4
    grad1 = pmb * 0.5
    assert vols[1]["dp_allreduce_mb"] == pytest.approx(2 * 3 / 4 * grad1)
    assert vols[1]["tp_collective_mb"] == pytest.approx(act_mb * 4 * 2)
    assert vols[1]["total_mb"] == pytest.approx(
        vols[1]["dp_allreduce_mb"] + vols[1]["tp_collective_mb"])


def test_emit_plan_telemetry_is_one_shot_event_not_gauges(tmp_path):
    """The plan's per-layer comm constants ride the ONE-SHOT ``plan``
    event; no plan/* gauges may be registered — gauges re-snapshot into
    the sink on every flush, duplicating constant data ~4*layers records
    per flush for the whole run (ROADMAP open item)."""
    import json
    from types import SimpleNamespace

    from hetu_galvatron_tpu.observability.telemetry import (
        emit_plan_telemetry,
    )
    from hetu_galvatron_tpu.utils.strategy import LayerStrategy

    cfg = ModelArgs(hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, seq_length=32, vocab_size=128,
                    make_vocab_size_divisible_by=1)
    hpc = SimpleNamespace(
        layers=[LayerStrategy(tp_size=2, dp_size=2)] * 2,
        global_bsz=8, chunks=2, pp_deg=1)
    path = tmp_path / "m.jsonl"
    reg = MetricsRegistry([JsonlSink(str(path))])
    emit_plan_telemetry(reg, hpc, cfg)
    assert not any(m.name.startswith("plan/") for m in reg.metrics())
    # the event carries the totals AND the per-layer breakdown
    reg.flush(step=0)
    reg.flush(step=1)
    reg.close()
    records = [json.loads(line) for line in open(path)]
    plans = [r for r in records if r.get("name") == "plan"]
    assert len(plans) == 1  # one-shot: repeated flushes add nothing
    data = plans[0]["data"]
    assert data["predicted_comm_mb_per_step"] > 0
    assert len(data["layers"]) == 2
    assert data["layers"][0]["layer"] == 0
    assert data["layers"][0]["tp_collective_mb"] > 0
    vols = plan_comm_volume(hpc.layers, cfg, global_bsz=8, chunks=2)
    assert data["predicted_comm_mb_per_step"] == pytest.approx(
        sum(v["total_mb"] for v in vols))
    # summarize still renders the predicted total from the event
    import io

    from hetu_galvatron_tpu.cli.summarize import summarize

    buf = io.StringIO()
    summarize(str(path), out=buf)
    assert "plan comm MB/step (predicted)" in buf.getvalue()


# ---------------------------------------------------------------------------
# no-sync + overhead contracts
# ---------------------------------------------------------------------------


class _SyncSentinel:
    """Models an async device scalar: float() on the step that is still
    'in flight' (the newest submitted step) is a blocking sync — flag it.
    Older steps have long completed; converting them is free."""

    def __init__(self, step, clock):
        self.step = step
        self.clock = clock  # dict holding the newest submitted step
        self.conversions = 0

    def __float__(self):
        if self.step >= self.clock["newest"] and not self.clock["closed"]:
            raise AssertionError(
                f"float() on the in-flight step {self.step} inside the "
                "hot loop — this blocks async dispatch")
        self.conversions += 1
        return 1.25


def test_telemetry_never_syncs_inflight_values(tmp_path):
    reg = MetricsRegistry([JsonlSink(str(tmp_path / "m.jsonl"))])
    tel = TrainingTelemetry(reg, global_batch_size=4, seq_length=8,
                            flush_interval=4)
    clock = {"newest": -1, "closed": False}
    sentinels = []
    for it in range(10):
        clock["newest"] = it
        s = _SyncSentinel(it, clock)
        sentinels.append(s)
        # flushes fire inside the loop at it=3 and it=7; they may drain
        # COMPLETED steps but never the newest (potentially in-flight) one
        tel(it, {"loss": s})
    clock["closed"] = True  # loop over: close() may drain everything
    tel.close()
    assert sum(s.conversions for s in sentinels) == 10
    assert reg.gauge("train/loss").value == 1.25
    assert reg.counter("train/steps").value == 10
    assert reg.counter("train/tokens").value == 10 * 4 * 8


def test_telemetry_hook_overhead_under_5_percent(tmp_path):
    """The acceptance bound: the per-step cost of the telemetry hook
    (including its amortized flushes, which snapshot histograms and write
    JSONL) stays under 5% of a ~2ms CPU-smoke step. Measured as per-call
    hook time rather than loop wall-clock so sleep jitter cannot flake the
    bound."""
    tel = TrainingTelemetry(
        MetricsRegistry([JsonlSink(str(tmp_path / "m.jsonl"))]),
        global_batch_size=8, seq_length=128, flush_interval=16)
    loss = np.float32(1.0)
    with tel:
        for it in range(64):  # warm caches / lazy file open
            tel(it, {"loss": loss})
        # best-of-5 windows: the bound is on the hook's intrinsic cost, so
        # one GC pause / scheduler hiccup must not flake the suite
        best = float("inf")
        it = 64
        for _ in range(5):
            n = 320  # multiple of flush_interval: flush cost is amortized in
            t0 = time.perf_counter()
            for _ in range(n):
                tel(it, {"loss": loss})
                it += 1
            best = min(best, (time.perf_counter() - t0) / n)
    step_s = 0.002  # the CPU smoke benchmark's step scale
    assert best < 0.05 * step_s, f"hook costs {best * 1e6:.0f}us/step"


# ---------------------------------------------------------------------------
# train_loop CPU smoke: JSONL out, summarize renders it
# ---------------------------------------------------------------------------


def test_train_loop_telemetry_smoke_and_summarize(tmp_path, capsys):
    from hetu_galvatron_tpu.cli import summarize as S
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.dataloader import synthetic_batches
    from hetu_galvatron_tpu.runtime.trainer import train_loop

    path = str(tmp_path / "metrics.jsonl")
    args = CoreArgs.model_validate({
        "model": {"hidden_size": 32, "num_hidden_layers": 2,
                  "num_attention_heads": 2, "vocab_size": 64,
                  "seq_length": 8, "max_position_embeddings": 16,
                  "make_vocab_size_divisible_by": 1},
        "parallel": {"global_train_batch_size": 4},
        "train": {"train_iters": 6},
        "observability": {"enabled": True, "metrics_path": path,
                          "flush_interval": 2, "peak_tflops": 0.001},
    })
    params, _ = init_causal_lm(jax.random.key(0), args.model)
    _, _, losses = train_loop(args, params,
                              synthetic_batches(args.model, 4))
    assert len(losses) == 6 and np.isfinite(losses).all()
    recs = [json.loads(l) for l in open(path)]
    names = {r["name"] for r in recs}
    # the acceptance triple: step-time, tokens/sec, and MFU entries
    assert "train/step_time_ms" in names
    assert "train/tokens_per_sec" in names
    assert "train/mfu" in names
    assert "train/loss" in names
    last = {r["name"]: r for r in recs}
    assert last["train/steps"]["value"] == 6
    assert last["train/tokens"]["value"] == 6 * 4 * 8
    assert last["train/step_time_ms"]["count"] == 5
    assert last["train/mfu"]["value"] > 0
    # span aggregation rode along through the same registry
    span_paths = {r["labels"]["path"] for r in recs if r["name"] == "span_ms"}
    assert {"train/fetch", "train/step"} <= span_paths

    headline = S.summarize(path)
    out = capsys.readouterr().out
    assert "MFU" in out and "tokens/sec" in out and "step time ms" in out
    assert headline["steps"] == 6
    assert headline["tokens_per_sec"] > 0
    assert S.main([path]) == 0


def test_summarize_usage_error(capsys):
    from hetu_galvatron_tpu.cli import summarize as S

    assert S.main([]) == 2
    assert "usage" in capsys.readouterr().out


def test_telemetry_reusable_across_loops(tmp_path):
    """One instance may serve consecutive train loops: close() re-arms on
    the next call, so the second loop's tail is not silently dropped."""
    reg = MetricsRegistry([JsonlSink(str(tmp_path / "m.jsonl"))])
    tel = TrainingTelemetry(reg, global_batch_size=2, seq_length=4,
                            flush_interval=100)
    for it in range(3):
        tel(it, {"loss": np.float32(1.0)})
    tel.close()
    assert reg.counter("train/steps").value == 3
    for it in range(3, 5):
        tel(it, {"loss": np.float32(2.0)})
    tel.close()
    assert reg.counter("train/steps").value == 5
    assert reg.gauge("train/loss").value == 2.0  # second phase drained


def test_summarize_tolerates_truncated_tail(tmp_path, capsys):
    """A run killed mid-flush leaves a partial final JSONL line; the
    post-mortem tool must summarize the intact records, not crash."""
    from hetu_galvatron_tpu.cli import summarize as S

    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    reg.counter("train/steps").inc(9)
    reg.close(step=9)
    with open(path, "a") as f:
        f.write('{"t": 1.0, "kind": "gauge", "name": "train/mf')  # torn
    headline = S.summarize(path)
    assert headline["steps"] == 9
    assert "skipped 1 unparseable" in capsys.readouterr().err


def test_tensorboard_sink_stepless_records_extend_last_step():
    """telemetry.close() flushes with step=None; the TB sink must emit at
    the last seen step, not reset the chart to x=0."""
    from hetu_galvatron_tpu.observability.sinks import TensorBoardSink

    scalars = []

    class W:
        def add_scalar(self, name, v, step):
            scalars.append((name, v, step))

        def flush(self):
            pass

    s = TensorBoardSink(W())
    s.write({"kind": "gauge", "name": "loss", "value": 2.0, "step": 5})
    s.write({"kind": "gauge", "name": "loss", "value": 1.0, "step": None})
    assert scalars == [("loss", 2.0, 5), ("loss", 1.0, 5)]


def test_summarize_hardware_alpha_beta_table(tmp_path, capsys):
    """Pointing summarize at a hardware bandwidth JSON renders the per-
    group bandwidth + fitted α-β table; a legacy (bandwidth-only) JSON
    renders with dashes and says the cost model falls back."""
    import json

    from hetu_galvatron_tpu.cli import summarize as S

    cfg = {"allreduce_size_8_consec_1": 160.4,
           "allreduce_size_4_consec_1": 164.2,
           "allreduce_size_4_consec_0": 165.5,
           "allreduce_size_8_consec_1_alpha_ms": 0.12,
           "allreduce_size_8_consec_1_beta_mb_per_ms": 320.0}
    path = tmp_path / "allreduce_bandwidth.json"
    path.write_text(json.dumps(cfg))
    head = S.summarize(str(path))
    out = capsys.readouterr().out
    assert head["groups"] == 3
    assert head["alpha_beta_groups"] == 1
    assert "hardware profile" in out and "alpha ms" in out
    assert "0.12" in out and "320" in out

    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps(
        {"allreduce_size_2_consec_1": 150.0}))
    head = S.summarize(str(legacy))
    assert head["alpha_beta_groups"] == 0
    assert "legacy bandwidth-only" in capsys.readouterr().out


def test_plan_tp_overlap_hidden_frac_volume_weighted():
    """The runtime gauge value: volume-weighted share of TP collective
    traffic on overlapped layers (1.0 when every tp layer overlaps, 0 with
    none, partial when only some layers are eligible)."""
    from types import SimpleNamespace

    from hetu_galvatron_tpu.observability.telemetry import (
        plan_tp_overlap_hidden_frac,
    )
    from hetu_galvatron_tpu.utils.strategy import LayerStrategy

    model = SimpleNamespace(seq_length=16, hidden_size=64,
                            num_attention_heads=4, kv_heads=4,
                            head_dim=16, ffn_dim=128, vocab_size=128,
                            hidden_act="gelu",
                            tie_word_embeddings=False)
    tp2 = LayerStrategy(pp_deg=1, tp_size=2, dp_size=4)
    hpc = SimpleNamespace(layers=[tp2, tp2], global_bsz=8, chunks=1)
    assert plan_tp_overlap_hidden_frac(hpc, model, [0, 1]) == 1.0
    assert plan_tp_overlap_hidden_frac(hpc, model, []) == 0.0
    assert plan_tp_overlap_hidden_frac(hpc, model, [0]) == 0.5
    # no tp traffic at all -> 0
    dp8 = LayerStrategy(pp_deg=1, tp_size=1, dp_size=8)
    hpc0 = SimpleNamespace(layers=[dp8, dp8], global_bsz=8, chunks=1)
    assert plan_tp_overlap_hidden_frac(hpc0, model, []) == 0.0
