"""Residual decay/windowing (observability.calibration.window_points)
and the closed calibration loop (cli.search_dist.feed_calibrated_profile):
aged and untimestamped residuals fall out of the posterior, floods are
bounded per curve, and the next search prices with the runtime-calibrated
profile ONLY when its hardware fingerprint matches."""

import os

import pytest

from hetu_galvatron_tpu.cli.search_dist import feed_calibrated_profile
from hetu_galvatron_tpu.core.args_schema import CoreArgs
from hetu_galvatron_tpu.observability.calibration import (
    PROFILE_NAME,
    hardware_fingerprint,
    window_points,
    write_calibrated_profile,
)

pytestmark = [pytest.mark.observability]

NOW = 1_700_000_000.0
DAY = 86400.0


def _pt(t=None, group="allreduce_size_8_consec_1", alg="ring", mb=1.0):
    p = {"group": group, "alg": alg, "mb": mb, "ms": 0.5}
    if t is not None:
        p["t"] = t
    return p


# ---------------------------------------------------------------------------
# window_points
# ---------------------------------------------------------------------------


def test_window_drops_old_and_untimestamped():
    """An active window ages out stale points AND legacy lines with no
    timestamp — unknown-age residuals must not anchor the posterior."""
    pts = [_pt(t=NOW), _pt(t=NOW - 10 * DAY), _pt(t=None)]
    kept = window_points(pts, window_days=5.0, now=NOW)
    assert kept == [pts[0]]


def test_window_boundary_is_inclusive():
    pts = [_pt(t=NOW - 5 * DAY)]
    assert window_points(pts, window_days=5.0, now=NOW) == pts


def test_max_points_keeps_newest_per_curve():
    """The per-(group, alg) cap keeps the NEWEST points, so one flood of
    appends cannot crowd out fresher measurements on another curve."""
    ring = [_pt(t=NOW + i) for i in range(5)]
    tree = [_pt(t=NOW + i, alg="tree") for i in range(3)]
    kept = window_points(ring + tree, max_points_per_curve=2)
    assert kept == [ring[3], ring[4], tree[1], tree[2]]


def test_flat_points_bucket_separately_from_algo_points():
    flat = [_pt(t=NOW + i, alg=None) for i in range(3)]
    ring = [_pt(t=NOW + i) for i in range(3)]
    kept = window_points(flat + ring, max_points_per_curve=1)
    assert kept == [flat[2], ring[2]]


def test_zero_limits_keep_everything():
    """0/0 is the historical keep-everything behaviour (non-dict garbage
    is still discarded)."""
    pts = [_pt(t=None), _pt(t=NOW - 1000 * DAY)]
    assert window_points(pts + ["junk"]) == pts


# ---------------------------------------------------------------------------
# feed_calibrated_profile
# ---------------------------------------------------------------------------


def _args(td, use_calibrated=1):
    a = CoreArgs()
    a.search.use_calibrated = use_calibrated
    a.observability.calibration_dir = str(td)
    return a


def _write_profile(td, world=8, device=None):
    fp = hardware_fingerprint(None, world=world, device_kind=device)
    cfg = {
        "allreduce_size_8_consec_1_ring_alpha_ms": 0.05,
        "allreduce_size_8_consec_1_ring_beta_mb_per_ms": 10.0,
        "calibration_meta": {
            "source": "runtime-calibrated",
            "fingerprint": fp,
            "curves": {"allreduce_size_8_consec_1/ring":
                       {"points": 6, "method": "irls"}},
        },
    }
    return write_calibrated_profile(os.path.join(str(td), PROFILE_NAME),
                                    cfg)


def test_matching_fingerprint_installs_profile(tmp_path):
    """Device + world match: the search's bandwidth config path is
    swapped to the calibrated posterior, with provenance in the log."""
    a = _args(tmp_path)
    path = _write_profile(tmp_path, world=8)
    lines = []
    assert feed_calibrated_profile(a, 8, log=lines.append) is True
    assert a.search.allreduce_bandwidth_config_path == path
    assert any("runtime-calibrated" in ln for ln in lines)


def test_world_mismatch_is_ignored_with_reason(tmp_path):
    a = _args(tmp_path)
    _write_profile(tmp_path, world=16)
    lines = []
    assert feed_calibrated_profile(a, 8, log=lines.append) is False
    assert a.search.allreduce_bandwidth_config_path is None
    assert any("does not match" in ln for ln in lines)


def test_device_mismatch_is_ignored(tmp_path):
    a = _args(tmp_path)
    _write_profile(tmp_path, world=8, device="TPU v9000")
    assert feed_calibrated_profile(a, 8, log=lambda _m: None) is False
    assert a.search.allreduce_bandwidth_config_path is None


def test_opt_out_and_missing_pieces_feed_nothing(tmp_path):
    # explicit opt-out wins even with a matching profile on disk
    a = _args(tmp_path, use_calibrated=0)
    _write_profile(tmp_path, world=8)
    assert feed_calibrated_profile(a, 8, log=lambda _m: None) is False
    # no calibration dir configured
    b = CoreArgs()
    assert feed_calibrated_profile(b, 8, log=lambda _m: None) is False
    # dir configured but no profile written yet
    c = _args(tmp_path / "empty")
    os.makedirs(str(tmp_path / "empty"), exist_ok=True)
    assert feed_calibrated_profile(c, 8, log=lambda _m: None) is False
