"""Request-lifecycle event stream (observability/events.py): record
schema through the JSONL sink, sequence ordering, the disabled fast path,
tap fan-out, and tap-failure isolation."""

import json

import pytest

from hetu_galvatron_tpu.observability.events import EventStream
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.observability.sinks import JsonlSink

pytestmark = pytest.mark.observability


def test_emit_schema_lands_in_sink(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    ev = EventStream(reg)
    ev.emit("submit", 7, prompt_len=12, max_new=8)
    ev.emit("retire", 7, status="done", reason="eos", generated=3)
    reg.close()
    recs = [json.loads(line) for line in open(path)]
    assert len(recs) == 2
    for r in recs:
        assert r["kind"] == "event" and r["name"] == "request"
        d = r["data"]
        assert d["rid"] == 7 and "seq" in d and "tm" in d
    assert recs[0]["data"]["ev"] == "submit"
    assert recs[0]["data"]["prompt_len"] == 12
    assert recs[1]["data"]["ev"] == "retire"
    assert recs[1]["data"]["status"] == "done"


def test_seq_strictly_increasing_and_tm_monotonic():
    ev = EventStream(MetricsRegistry())
    datas = [ev.emit("decode", 1, n=1) for _ in range(32)]
    seqs = [d["seq"] for d in datas]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    tms = [d["tm"] for d in datas]
    assert all(a <= b for a, b in zip(tms, tms[1:]))


def test_disabled_without_taps_is_noop(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    ev = EventStream(reg, enabled=False)
    assert ev.emit("submit", 1) is None
    reg.close()
    import os

    # lazy-open sink with nothing written leaves no artifact at all
    assert not os.path.exists(path)


def test_taps_receive_even_when_sink_stream_disabled(tmp_path):
    """The flight-recorder contract: a crash dump has event context even
    for runs that never turned the JSONL stream on."""
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    ev = EventStream(reg, enabled=False)
    got = []
    ev.add_tap(lambda name, data: got.append((name, data)))
    ev.emit("submit", 3, prompt_len=2)
    assert len(got) == 1 and got[0][0] == "request"
    assert got[0][1]["rid"] == 3
    reg.close()
    import os

    assert not os.path.exists(path)  # sink stream stayed off


def test_broken_tap_is_counted_not_fatal():
    ev = EventStream(MetricsRegistry())

    def boom(name, data):
        raise RuntimeError("tap exploded")

    good = []
    ev.add_tap(boom)
    ev.add_tap(lambda n, d: good.append(d))
    d = ev.emit("submit", 1)
    assert d is not None and ev.tap_errors == 1
    assert len(good) == 1  # later taps still ran
