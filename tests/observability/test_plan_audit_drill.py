"""Acceptance drill: on the virtual 8-device mesh, train under a searched
tp2 x dp2 x pp2 plan with a trace window + fitted α-β pairs, and the closed
loop must report per-component predicted-vs-actual ratios in the plan_audit
event, with cli/summarize.py rendering the calibration table."""

import io
import json
import os

import pytest

from hetu_galvatron_tpu.utils.strategy import (
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    strategy_list2config,
)

pytestmark = [pytest.mark.observability, pytest.mark.distributed]

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")


def _searched_plan(tmp_path):
    """tp2 x dp2 x pp2 in the searched-config interchange format (what the
    search engine's save_results writes and config_mode=json loads)."""
    layers = [LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)
              for _ in range(2)]
    cfg = strategy_list2config(
        layers, global_bsz=8, chunks=2, pipeline_type="pipedream_flush",
        default_dp_type="ddp", vocab=EmbeddingLMHeadStrategy(vtp=1),
        pp_division=[1, 1],
        # save_results embeds the cost model's per-layer compute prediction
        # (fct+bct ms); the audit's compute row must pick it up
        predicted_layer_compute_ms=[0.5, 0.5])
    path = tmp_path / "galvatron_config_audit_drill.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def test_plan_audit_drill_mesh8(tmp_path):
    from hetu_galvatron_tpu.cli.summarize import summarize
    from hetu_galvatron_tpu.cli.train_dist import main

    # fitted α-β pairs for the plan's group size (2, both layouts): the
    # keys hardware_profiler.profile_alpha_beta writes
    hw = {"allreduce_size_2_consec_1_alpha_ms": 0.02,
          "allreduce_size_2_consec_1_beta_mb_per_ms": 400.0,
          "allreduce_size_2_consec_0_alpha_ms": 0.03,
          "allreduce_size_2_consec_0_beta_mb_per_ms": 300.0}
    hw_path = tmp_path / "hw_alpha_beta.json"
    hw_path.write_text(json.dumps(hw))
    metrics = str(tmp_path / "metrics.jsonl")

    rc = main([
        os.path.join(ZOO, "llama2-7b.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_attention_heads=2", "model.num_key_value_heads=2",
        "model.vocab_size=64", "model.seq_length=8",
        "model.max_position_embeddings=16", "model.ffn_hidden_size=64",
        "model.make_vocab_size_divisible_by=1",
        "train.train_iters=3", "parallel.mixed_precision=fp32",
        "parallel.config_mode=json",
        f"parallel.galvatron_config_path={_searched_plan(tmp_path)}",
        "observability.enabled=true",
        f"observability.metrics_path={metrics}",
        f"observability.audit_hardware_config={hw_path}",
        f"profile.trace_dir={tmp_path / 'trace'}",
        "profile.profile_warmup=1", "profile.trace_iters=2",
    ])
    assert rc == 0

    records = [json.loads(l) for l in open(metrics)]
    audits = [r for r in records if r.get("kind") == "event"
              and r.get("name") == "plan_audit"]
    assert len(audits) == 1
    table = audits[-1]["data"]
    assert table["steps"] == 2  # the traced window
    rows = {r["component"]: r for r in table["rows"]}

    # per-component predicted-vs-actual: the pipelined plan communicates
    # on tp (ag/rs), dp (grad all-reduce), and pp (stage transfers); the
    # α-β pairs price tp and dp, so those rows carry RATIOS
    for comp in ("tp", "dp"):
        row = rows[comp]
        assert row["measured_ms"] > 0
        assert row["predicted_ms"] > 0
        assert row["ratio"] == pytest.approx(
            row["measured_ms"] / row["predicted_ms"], rel=1e-2)
        assert row["residual_ms"] == pytest.approx(
            row["measured_ms"] - row["predicted_ms"], abs=1e-3)
    # the compute row diffs against the plan-embedded per-layer prediction
    comp = rows["compute"]
    assert comp["measured_ms"] > 0
    assert comp["predicted_ms"] == pytest.approx(1.0)  # 2 x 0.5 ms
    assert comp["ratio"] == pytest.approx(comp["measured_ms"] / 1.0, rel=1e-2)
    assert comp["residual_ms"] == pytest.approx(
        comp["measured_ms"] - 1.0, abs=1e-3)
    # 1F1B analytical bubble for pp2, m=2 chunks: 2(pp-1)/(m+2(pp-1))
    assert rows["bubble"]["predicted_frac"] == pytest.approx(0.5)
    assert 0.0 <= rows["bubble"]["measured_frac"] <= 1.0

    # audit gauges landed in the stream too
    gauges = {(r["name"], tuple(sorted((r.get("labels") or {}).items())))
              for r in records if r.get("kind") == "gauge"}
    assert ("audit/time_ratio", (("component", "tp"),)) in gauges

    # the program cost accounting fired for the pipeline stage programs
    progs = {r["data"]["program"] for r in records
             if r.get("kind") == "event" and r.get("name") == "program_cost"}
    assert any(p.startswith("pp/") for p in progs)

    # summarize renders the calibration table with the ratio column
    buf = io.StringIO()
    headline = summarize(metrics, out=buf)
    text = buf.getvalue()
    assert "plan audit: predicted vs actual" in text
    assert "ratio" in text and "residual" in text
    assert headline["audit_ratio_tp"] == rows["tp"]["ratio"]
    assert headline["audit_ratio_dp"] == rows["dp"]["ratio"]
    assert "program costs (XLA cost_analysis)" in text


def test_calibration_drill_mesh8(tmp_path):
    """The self-calibration acceptance drill: run the traced tp2 x dp2 x
    pp2 plan twice against a deliberately mispredicting prior (huge α),
    with the residual store + re-fitter + regret sentinel enabled. The
    store must accumulate across runs, the calibrated curves must land
    closer to the measured residuals than the prior did, the re-fit
    profile must round-trip through both α-β parsers with provenance, and
    a seeded runner-up overtaking the incumbent must raise exactly one
    plan_regret event + a nonzero calibration/plan_regret_ms gauge that
    cli/summarize.py renders."""
    from hetu_galvatron_tpu.cli.summarize import summarize
    from hetu_galvatron_tpu.cli.train_dist import main
    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_alpha_beta,
        read_alpha_beta_algos,
        read_profile_provenance,
    )

    # grossly overpredicting prior (α orders of magnitude above any real
    # CPU-mesh collective): the audit residuals must pull the calibrated
    # curves sharply down. The per-algorithm ring_ici curve undercuts the
    # flat pair so the cost model CHOOSES it — the re-fit must then land
    # in the read_alpha_beta_algos namespace, not the flat one.
    hw = {"allreduce_size_2_consec_1_alpha_ms": 2.0,
          "allreduce_size_2_consec_1_beta_mb_per_ms": 50.0,
          "allreduce_size_2_consec_1_alg_ring_lvl_ici_alpha_ms": 1.5,
          "allreduce_size_2_consec_1_alg_ring_lvl_ici_beta_mb_per_ms": 60.0,
          "allreduce_size_2_consec_0_alpha_ms": 3.0,
          "allreduce_size_2_consec_0_beta_mb_per_ms": 40.0}
    hw_path = tmp_path / "hw_prior.json"
    hw_path.write_text(json.dumps(hw))

    # seed the plan with the sentinel's inputs: the winner's priced total
    # and two runner-ups bracketing it in comm intensity. Whichever way
    # calibration moves the collective curves (here: sharply down), one
    # of them overtakes the incumbent's near-identical total
    plan_path = _searched_plan(tmp_path)
    cfg = json.loads(open(plan_path).read())
    layers = [{"tp": 2, "dp": 2, "cp": 1, "sp": 0, "ckpt": 0, "consec": 1}
              for _ in range(2)]
    cfg["predicted_time_cost_ms"] = 50.0
    cfg["runner_ups"] = [
        # comm-heavy: same layers, no pipeline split -> every collective
        # prices at 2x the incumbent's per-device share
        {"throughput": 1.0, "time_cost_ms": 50.01, "bsz": 8, "chunks": 2,
         "pp": 1, "strategies": ["pp1-tp2-dp2"], "layers": layers},
        # comm-light: deeper pipeline -> half the incumbent's share
        {"throughput": 1.0, "time_cost_ms": 50.01, "bsz": 8, "chunks": 2,
         "pp": 4, "strategies": ["pp4-tp2-dp2"], "layers": layers},
    ]
    with open(plan_path, "w") as f:
        json.dump(cfg, f)

    cal_dir = tmp_path / "calib"
    store_path = cal_dir / "residuals.jsonl"
    profile_path = cal_dir / "calibrated_profile.json"

    def run(i):
        metrics = str(tmp_path / f"metrics_{i}.jsonl")
        rc = main([
            os.path.join(ZOO, "llama2-7b.yaml"),
            "model.hidden_size=32", "model.num_hidden_layers=2",
            "model.num_attention_heads=2", "model.num_key_value_heads=2",
            "model.vocab_size=64", "model.seq_length=8",
            "model.max_position_embeddings=16", "model.ffn_hidden_size=64",
            "model.make_vocab_size_divisible_by=1",
            "train.train_iters=3", "parallel.mixed_precision=fp32",
            "parallel.config_mode=json",
            f"parallel.galvatron_config_path={plan_path}",
            "observability.enabled=true",
            f"observability.metrics_path={metrics}",
            f"observability.audit_hardware_config={hw_path}",
            f"observability.calibration_dir={cal_dir}",
            "observability.regret_threshold=0.000000001",
            f"profile.trace_dir={tmp_path / ('trace_' + str(i))}",
            "profile.profile_warmup=1", "profile.trace_iters=2",
        ])
        assert rc == 0
        return [json.loads(l) for l in open(metrics)]

    run(0)
    assert store_path.exists()
    n_after_first = len([l for l in open(store_path) if l.strip()])
    assert n_after_first > 0
    records = run(1)

    # persistent accumulation across runs/restarts
    points = [json.loads(l) for l in open(store_path) if l.strip()]
    assert len(points) == 2 * n_after_first
    assert all(p["fp"]["world"] == 8 for p in points)

    # calibrated curves converge toward the measured residuals: at every
    # stored point, the re-fit curve's prediction beats the prior's
    calibrated = json.loads(open(profile_path).read())
    cal_flat = read_alpha_beta(calibrated)
    cal_algos = read_alpha_beta_algos(calibrated)
    prior_flat = read_alpha_beta(hw)
    prior_algos = read_alpha_beta_algos(hw)
    assert cal_algos.get("2_1", {}).get("ring_ici") is not None
    assert "2_0" in cal_flat
    checked = 0
    for p in points:
        pr = (prior_flat.get(p["group"]) if p["alg"] == "flat"
              else prior_algos.get(p["group"], {}).get(p["alg"]))
        ca = (cal_flat.get(p["group"]) if p["alg"] == "flat"
              else cal_algos.get(p["group"], {}).get(p["alg"]))
        if pr is None or ca is None:
            continue
        prior_err = abs(pr[0] + p["mb"] / pr[1] - p["ms"])
        cal_err = abs(ca[0] + p["mb"] / ca[1] - p["ms"])
        assert cal_err < prior_err, (p, pr, ca)
        checked += 1
    assert checked == len(points)  # every point's curve was re-fit

    # provenance survives the file round-trip
    meta = read_profile_provenance(calibrated)
    assert meta["source"] == "runtime-calibrated"
    assert meta["curves"]["2_1/ring_ici"]["points"] >= 1
    assert meta["fingerprint"]["world"] == 8

    # exactly one plan_regret event in the run's stream + nonzero gauge
    regrets = [r for r in records if r.get("kind") == "event"
               and r.get("name") == "plan_regret"]
    assert len(regrets) == 1
    assert regrets[0]["data"]["regret_ms"] > 0
    gauges = {r["name"]: r["value"] for r in records
              if r.get("kind") == "gauge"}
    assert gauges["calibration/plan_regret_ms"] > 0
    assert gauges["calibration/points_total"] == len(points)
    assert gauges["calibration/curves_fitted"] >= 2
    assert gauges["calibration/drift_score"] > 0

    # summarize renders the calibration section + the regret alert
    buf = io.StringIO()
    headline = summarize(str(tmp_path / "metrics_1.jsonl"), out=buf)
    text = buf.getvalue()
    assert "-- calibration --" in text
    assert "PLAN REGRET" in text
    assert headline["plan_regret_ms"] > 0
    assert headline["plan_regret_events"] == 1

    # ...and the calibrated profile itself renders with provenance columns
    buf = io.StringIO()
    hw_headline = summarize(str(profile_path), out=buf)
    text = buf.getvalue()
    assert "runtime-calibrated" in text
    assert hw_headline["calibrated_curves"] >= 2
