"""Acceptance drill: on the virtual 8-device mesh, train under a searched
tp2 x dp2 x pp2 plan with a trace window + fitted α-β pairs, and the closed
loop must report per-component predicted-vs-actual ratios in the plan_audit
event, with cli/summarize.py rendering the calibration table."""

import io
import json
import os

import pytest

from hetu_galvatron_tpu.utils.strategy import (
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    strategy_list2config,
)

pytestmark = [pytest.mark.observability, pytest.mark.distributed]

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")


def _searched_plan(tmp_path):
    """tp2 x dp2 x pp2 in the searched-config interchange format (what the
    search engine's save_results writes and config_mode=json loads)."""
    layers = [LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)
              for _ in range(2)]
    cfg = strategy_list2config(
        layers, global_bsz=8, chunks=2, pipeline_type="pipedream_flush",
        default_dp_type="ddp", vocab=EmbeddingLMHeadStrategy(vtp=1),
        pp_division=[1, 1],
        # save_results embeds the cost model's per-layer compute prediction
        # (fct+bct ms); the audit's compute row must pick it up
        predicted_layer_compute_ms=[0.5, 0.5])
    path = tmp_path / "galvatron_config_audit_drill.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def test_plan_audit_drill_mesh8(tmp_path):
    from hetu_galvatron_tpu.cli.summarize import summarize
    from hetu_galvatron_tpu.cli.train_dist import main

    # fitted α-β pairs for the plan's group size (2, both layouts): the
    # keys hardware_profiler.profile_alpha_beta writes
    hw = {"allreduce_size_2_consec_1_alpha_ms": 0.02,
          "allreduce_size_2_consec_1_beta_mb_per_ms": 400.0,
          "allreduce_size_2_consec_0_alpha_ms": 0.03,
          "allreduce_size_2_consec_0_beta_mb_per_ms": 300.0}
    hw_path = tmp_path / "hw_alpha_beta.json"
    hw_path.write_text(json.dumps(hw))
    metrics = str(tmp_path / "metrics.jsonl")

    rc = main([
        os.path.join(ZOO, "llama2-7b.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_attention_heads=2", "model.num_key_value_heads=2",
        "model.vocab_size=64", "model.seq_length=8",
        "model.max_position_embeddings=16", "model.ffn_hidden_size=64",
        "model.make_vocab_size_divisible_by=1",
        "train.train_iters=3", "parallel.mixed_precision=fp32",
        "parallel.config_mode=json",
        f"parallel.galvatron_config_path={_searched_plan(tmp_path)}",
        "observability.enabled=true",
        f"observability.metrics_path={metrics}",
        f"observability.audit_hardware_config={hw_path}",
        f"profile.trace_dir={tmp_path / 'trace'}",
        "profile.profile_warmup=1", "profile.trace_iters=2",
    ])
    assert rc == 0

    records = [json.loads(l) for l in open(metrics)]
    audits = [r for r in records if r.get("kind") == "event"
              and r.get("name") == "plan_audit"]
    assert len(audits) == 1
    table = audits[-1]["data"]
    assert table["steps"] == 2  # the traced window
    rows = {r["component"]: r for r in table["rows"]}

    # per-component predicted-vs-actual: the pipelined plan communicates
    # on tp (ag/rs), dp (grad all-reduce), and pp (stage transfers); the
    # α-β pairs price tp and dp, so those rows carry RATIOS
    for comp in ("tp", "dp"):
        row = rows[comp]
        assert row["measured_ms"] > 0
        assert row["predicted_ms"] > 0
        assert row["ratio"] == pytest.approx(
            row["measured_ms"] / row["predicted_ms"], rel=1e-2)
        assert row["residual_ms"] == pytest.approx(
            row["measured_ms"] - row["predicted_ms"], abs=1e-3)
    # the compute row diffs against the plan-embedded per-layer prediction
    comp = rows["compute"]
    assert comp["measured_ms"] > 0
    assert comp["predicted_ms"] == pytest.approx(1.0)  # 2 x 0.5 ms
    assert comp["ratio"] == pytest.approx(comp["measured_ms"] / 1.0, rel=1e-2)
    assert comp["residual_ms"] == pytest.approx(
        comp["measured_ms"] - 1.0, abs=1e-3)
    # 1F1B analytical bubble for pp2, m=2 chunks: 2(pp-1)/(m+2(pp-1))
    assert rows["bubble"]["predicted_frac"] == pytest.approx(0.5)
    assert 0.0 <= rows["bubble"]["measured_frac"] <= 1.0

    # audit gauges landed in the stream too
    gauges = {(r["name"], tuple(sorted((r.get("labels") or {}).items())))
              for r in records if r.get("kind") == "gauge"}
    assert ("audit/time_ratio", (("component", "tp"),)) in gauges

    # the program cost accounting fired for the pipeline stage programs
    progs = {r["data"]["program"] for r in records
             if r.get("kind") == "event" and r.get("name") == "program_cost"}
    assert any(p.startswith("pp/") for p in progs)

    # summarize renders the calibration table with the ratio column
    buf = io.StringIO()
    headline = summarize(metrics, out=buf)
    text = buf.getvalue()
    assert "plan audit: predicted vs actual" in text
    assert "ratio" in text and "residual" in text
    assert headline["audit_ratio_tp"] == rows["tp"]["ratio"]
    assert headline["audit_ratio_dp"] == rows["dp"]["ratio"]
    assert "program costs (XLA cost_analysis)" in text
