"""Goodput tracker (observability/goodput.py): category accumulation,
checkpoint-payload persistence with restart-lost wall-gap accounting,
gauge export, and the supervisor's backoff-wait receipt."""

import pytest

from hetu_galvatron_tpu.observability.goodput import (
    CATEGORIES,
    GoodputTracker,
)
from hetu_galvatron_tpu.observability.registry import MetricsRegistry

pytestmark = pytest.mark.observability


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_measure_and_goodput_fraction():
    clk = FakeClock()
    gp = GoodputTracker(clock=clk, wall=clk)
    with gp.measure("productive_step"):
        clk.t += 8.0
    with gp.measure("checkpoint_save"):
        clk.t += 2.0
    assert gp.totals["productive_step"] == pytest.approx(8.0)
    assert gp.totals["checkpoint_save"] == pytest.approx(2.0)
    assert gp.goodput() == pytest.approx(0.8)
    assert gp.total() == pytest.approx(10.0)


def test_empty_tracker_reports_goodput_one():
    assert GoodputTracker().goodput() == 1.0


def test_state_roundtrip_books_wall_gap_as_restart_lost():
    """The persistence contract: totals survive through the checkpoint
    payload, and the commit-to-resume wall gap (dead attempt's discarded
    work + downtime + backoff) lands in restart_lost."""
    wall = FakeClock(1000.0)
    a = GoodputTracker(wall=wall)
    a.add("productive_step", 30.0)
    a.add("recompile", 5.0)
    snap = a.state_dict()  # committed at wall 1000

    wall.t = 1012.5  # 12.5 s later another process resumes
    b = GoodputTracker(wall=wall)
    b.load_state_dict(snap)
    assert b.totals["productive_step"] == pytest.approx(30.0)
    assert b.totals["recompile"] == pytest.approx(5.0)
    assert b.totals["restart_lost"] == pytest.approx(12.5)
    assert b.restarts_survived == 1
    assert 0.0 < b.goodput() < 1.0

    # a second preemption chains: survived count and lost time accumulate
    snap2 = b.state_dict()
    wall.t += 3.0
    c = GoodputTracker(wall=wall)
    c.load_state_dict(snap2)
    assert c.totals["restart_lost"] == pytest.approx(15.5)
    assert c.restarts_survived == 2


def test_flush_exports_gauges():
    reg = MetricsRegistry()
    gp = GoodputTracker()
    gp.add("productive_step", 9.0)
    gp.add("restart_lost", 1.0)
    gp.flush(reg)
    for c in CATEGORIES:
        assert reg.gauge(f"goodput/{c}_s").value >= 0.0
    assert reg.gauge("goodput/productive_step_s").value == 9.0
    assert reg.gauge("goodput/goodput_frac").value == pytest.approx(0.9)


def test_supervisor_counts_backoff_wait():
    from hetu_galvatron_tpu.runtime.supervisor import (
        EXIT_CODE_CHECKPOINT_AND_EXIT,
        run_with_restarts,
    )

    reg = MetricsRegistry()
    codes = [EXIT_CODE_CHECKPOINT_AND_EXIT, 0]

    rc = run_with_restarts(lambda: codes.pop(0), max_restarts=2,
                           base_delay=0.5, sleep=lambda s: None,
                           rng=__import__("random").Random(0),
                           registry=reg, log=lambda m: None)
    assert rc == 0
    # one restart happened and its (jittered, positive) backoff was
    # receipted for the goodput dashboards
    assert reg.counter("supervisor/restarts",
                       code=EXIT_CODE_CHECKPOINT_AND_EXIT).value == 1
    assert reg.counter("supervisor/backoff_wait_s").value > 0.0
