"""Unit coverage for the self-calibrating cost model
(observability/calibration.py): the persistent residual store (atomic
O_APPEND batches, torn-line tolerance, fingerprint isolation), the α-β
re-fitter's degenerate inputs (single point, zero size variance, negative
slope) and robust regression, profile round-trips through the
read_alpha_beta parsers with calibration_meta provenance, the stored-plan
re-pricer's hand-checked arithmetic, and the plan-regret sentinel."""

import io
import json
import os
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import pytest

from hetu_galvatron_tpu.core.cost_model.cost import reprice_stored_plan_ms
from hetu_galvatron_tpu.core.search_engine.profiles import (
    merge_calibrated_profile,
    read_alpha_beta,
    read_alpha_beta_algos,
    read_profile_provenance,
)
from hetu_galvatron_tpu.observability.calibration import (
    META_KEY,
    ResidualStore,
    calibration_points,
    drift_score,
    evaluate_plan_regret,
    fingerprint_key,
    hardware_fingerprint,
    plan_spec_from_hpc,
    refit_profile,
    run_calibration,
    write_calibrated_profile,
)
from hetu_galvatron_tpu.observability.recorder import FlightRecorder
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.observability.sinks import JsonlSink

pytestmark = pytest.mark.observability

FP_A = {"device": "cpu", "world": 8, "mesh": [2, 2, 2]}
FP_B = {"device": "TPU v4", "world": 8, "mesh": [2, 2, 2]}


def _pt(group="2_1", alg="flat", mb=4.0, ms=1.0, **kw):
    return {"collective": "allreduce", "group": group, "alg": alg,
            "mb": mb, "ms": ms, **kw}


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------


def test_hardware_fingerprint_and_key():
    fp = hardware_fingerprint(None, world=8, device_kind="TPU v4")
    assert fp == {"device": "TPU v4", "world": 8, "mesh": []}
    assert fingerprint_key(fp) == "TPU-v4_w8_nomesh"
    layers = [SimpleNamespace(tp_size=2, dp_size=2)]
    hpc = SimpleNamespace(layers=layers, pp_deg=2, world_size=8)
    fp = hardware_fingerprint(hpc, device_kind="cpu")
    assert fp == {"device": "cpu", "world": 8, "mesh": [2, 2, 2]}
    assert fingerprint_key(fp) == "cpu_w8_2x2x2"


# ---------------------------------------------------------------------------
# persistent residual store
# ---------------------------------------------------------------------------


def test_store_roundtrip_and_fingerprint_isolation(tmp_path):
    store = ResidualStore(str(tmp_path / "residuals.jsonl"))
    assert store.load() == []  # missing file is empty, not an error
    assert store.append([_pt(ms=1.0), _pt(ms=2.0)], fingerprint=FP_A,
                        run_id="r0") == 2
    assert store.append([_pt(ms=9.0)], fingerprint=FP_B) == 1
    everything = store.load()
    assert len(everything) == 3
    assert all("t" in p and "fp" in p for p in everything)
    assert everything[0]["run"] == "r0"
    # a v4 curve must never be refit from cpu residuals (and vice versa)
    mine = store.load(fingerprint=FP_A)
    assert [p["ms"] for p in mine] == [1.0, 2.0]
    assert store.skipped == 0
    assert [p["ms"] for p in store.load(fingerprint=FP_B)] == [9.0]


def test_store_skips_torn_and_corrupt_lines(tmp_path, capsys):
    path = tmp_path / "residuals.jsonl"
    store = ResidualStore(str(path))
    store.append([_pt(ms=1.0)], fingerprint=FP_A)
    with open(path, "a") as f:
        f.write("[1, 2, 3]\n")              # parseable but not a record
        f.write('{"collective": "allredu')  # torn mid-write crash line
    pts = store.load(fingerprint=FP_A)
    assert [p["ms"] for p in pts] == [1.0]
    assert store.skipped == 2
    assert "skipped 2" in capsys.readouterr().err
    # the next batch's leading newline terminates the torn tail, so only
    # the torn line itself stays lost — not the new batch's first record
    store.append([_pt(ms=3.0)], fingerprint=FP_A)
    assert [p["ms"] for p in store.load(fingerprint=FP_A)] == [1.0, 3.0]
    assert store.skipped == 2


def test_store_concurrent_appends_interleave_whole_lines(tmp_path):
    path = str(tmp_path / "residuals.jsonl")

    def worker(i):
        # each call opens its own O_APPEND descriptor, like concurrent
        # supervisor restarts sharing one store
        ResidualStore(path).append(
            [_pt(ms=float(i), run=i) for _ in range(5)], fingerprint=FP_A)

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(worker, range(40)))
    store = ResidualStore(path)
    pts = store.load(fingerprint=FP_A)
    assert store.skipped == 0  # no torn interior lines
    assert len(pts) == 200


def test_jsonl_sink_concurrent_flushes_stay_parseable(tmp_path):
    """The event-stream JSONL gets the same one-write O_APPEND discipline
    (a calibration sidecar and a training process may share a stream)."""
    path = str(tmp_path / "metrics.jsonl")

    def worker(i):
        sink = JsonlSink(path)
        for j in range(20):
            sink.write({"kind": "event", "name": "e", "data": {"i": i,
                                                               "j": j}})
            sink.flush()
        sink.close()

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(worker, range(8)))
    records = [json.loads(l) for l in open(path)]
    assert len(records) == 160
    assert all(r["kind"] == "event" for r in records)


def test_jsonl_sink_lazy_creation(tmp_path):
    path = tmp_path / "sub" / "metrics.jsonl"
    sink = JsonlSink(str(path))
    sink.flush()
    sink.close()
    assert not path.exists()  # nothing emitted -> no artifact


# ---------------------------------------------------------------------------
# residual extraction from an audit table
# ---------------------------------------------------------------------------


def _model():
    return SimpleNamespace(seq_length=8, hidden_size=32,
                           num_attention_heads=2, head_dim=16, kv_heads=2,
                           hidden_act="silu", ffn_dim=64)


def _hpc(pp=2, tp=2, dp=2, sp=False, ckpt=False, layers=2):
    mk = lambda: SimpleNamespace(  # noqa: E731 — local fixture factory
        tp_size=tp, dp_size=dp, cp_size=1, sp=sp, checkpoint=ckpt,
        tp_consecutive=True)
    return SimpleNamespace(layers=[mk() for _ in range(layers)],
                           pp_deg=pp, chunks=2, global_bsz=8, world_size=8)


def test_calibration_points_tp_dp_arithmetic():
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb

    table = {"rows": [
        {"component": "tp", "measured_ms": 6.0, "predicted_ms": 3.0},
        {"component": "tp[ring_ici]", "predicted_ms": 3.0, "chosen": True},
        {"component": "tp[flat]", "predicted_ms": 4.0},
        {"component": "dp", "measured_ms": 2.0, "predicted_ms": 1.0},
    ]}
    pts = calibration_points(table, _hpc(), _model(),
                             mixed_precision=False)
    by = {(p["group"], p["alg"]): p for p in pts}
    assert set(by) == {("2_1", "ring_ici"), ("2_0", "flat")}
    # tp: lbsz=8//2//2=2, act = 2*8*32*4B = 0.001953125 MB; per-layer
    # weight 6*chunks*0.5/pp = 3 messages, two identical layers -> one
    # group of weight 6, so per-message ms = 6.0/6
    tp = by[("2_1", "ring_ici")]
    assert tp["mb"] == pytest.approx(2 * 8 * 32 * 4 / 2**20)
    assert tp["w"] == pytest.approx(6.0)
    assert tp["ms"] == pytest.approx(1.0)
    # dp: sdp=2, consec=0 (tp>1), grad = param_mb/2 at fp32; weight
    # 1/pp per layer -> 1.0 total, per-ring ms = 2.0/1.0
    dp = by[("2_0", "flat")]
    assert dp["mb"] == pytest.approx(layer_param_mb(_model()) / 2)
    assert dp["w"] == pytest.approx(1.0)
    assert dp["ms"] == pytest.approx(2.0)


def test_calibration_points_hier_dp_contributes_nothing():
    table = {"rows": [
        {"component": "dp", "measured_ms": 2.0, "predicted_ms": 1.0},
        {"component": "dp[hier]", "measured_ms": 2.0},
    ]}
    pts = calibration_points(table, _hpc(tp=1), _model(),
                             mixed_precision=False)
    # hier-measured dp is one concatenated schedule, not per-layer flat
    # rings: no dp point may be attributed to the flat curve
    assert pts == []


def test_drift_score_excludes_decomposition_rows():
    table = {"rows": [
        {"component": "tp", "measured_ms": 3.0, "predicted_ms": 2.0},
        {"component": "dp", "measured_ms": 1.0, "predicted_ms": 1.0},
        {"component": "tp[ring_ici]", "measured_ms": 99.0,
         "predicted_ms": 1.0},
        {"component": "bubble", "predicted_frac": 0.5},  # no time pred
    ]}
    assert drift_score(table) == pytest.approx(1.0 / 3.0)
    assert drift_score({"rows": []}) is None


# ---------------------------------------------------------------------------
# α-β re-fitter
# ---------------------------------------------------------------------------

PRIOR = {"allreduce_size_2_consec_1_alpha_ms": 1.0,
         "allreduce_size_2_consec_1_beta_mb_per_ms": 1.0,
         "allreduce_size_2_consec_1_alg_ring_lvl_ici_alpha_ms": 2.0,
         "allreduce_size_2_consec_1_alg_ring_lvl_ici_beta_mb_per_ms": 2.0}


def test_refit_single_point_scale_fallback():
    # one production size can't support a regression, but it CAN rescale
    # the prior: r = 1.0 / (1 + 4/1) = 0.2 -> α·r, β/r
    cfg, meta = refit_profile([_pt(mb=4.0, ms=1.0)], prior=PRIOR)
    assert cfg["allreduce_size_2_consec_1_alpha_ms"] == pytest.approx(0.2)
    assert cfg["allreduce_size_2_consec_1_beta_mb_per_ms"] == \
        pytest.approx(5.0)
    assert meta["curves"]["2_1/flat"] == {"points": 1, "method": "scale"}
    assert meta["source"] == "runtime-calibrated"


def test_refit_single_point_without_prior_skips():
    cfg, meta = refit_profile([_pt(mb=4.0, ms=1.0)], prior=None)
    assert cfg == {}
    assert meta["curves"] == {}


def test_refit_scale_ratio_is_clamped():
    # measured 1000x under the prior: the posterior moves hard toward the
    # measurement but a single window may not rescale beyond 20x
    cfg, _ = refit_profile([_pt(mb=4.0, ms=0.005)], prior=PRIOR)
    assert cfg["allreduce_size_2_consec_1_alpha_ms"] == pytest.approx(0.05)


def test_refit_zero_size_variance_falls_back_to_scale():
    # many points, one message size: no spread -> regression refused even
    # above min_points, scale fallback over all of them
    pts = [_pt(mb=4.0, ms=1.0 + 0.01 * i) for i in range(6)]
    cfg, meta = refit_profile(pts, prior=PRIOR)
    assert meta["curves"]["2_1/flat"]["method"] == "scale"
    assert meta["curves"]["2_1/flat"]["points"] == 6
    assert cfg["allreduce_size_2_consec_1_alpha_ms"] < 1.0


def test_refit_negative_slope_falls_back_to_scale():
    # ms DECREASING with size: fit_alpha_beta's degenerate-slope guard
    # (PR 13) rejects the regression; the prior-anchored scale posterior
    # still absorbs the level shift
    pts = [_pt(mb=m, ms=s) for m, s in
           [(1.0, 4.0), (2.0, 3.0), (4.0, 2.0), (8.0, 1.0)]]
    cfg, meta = refit_profile(pts, prior=PRIOR)
    assert meta["curves"]["2_1/flat"]["method"] == "scale"
    assert "allreduce_size_2_consec_1_alpha_ms" in cfg
    # ...and with no prior to rescale, the curve is skipped, not invented
    cfg2, meta2 = refit_profile(pts, prior=None)
    assert cfg2 == {}


def test_refit_regression_recovers_truth_and_drops_outlier():
    alpha, beta = 0.05, 250.0
    pts = [_pt(mb=m, ms=alpha + m / beta)
           for m in (1.0, 2.0, 4.0, 8.0, 16.0, 32.0)]
    pts.append(_pt(mb=32.0, ms=10.0))  # one wild straggler
    cfg, meta = refit_profile(pts, prior=None)
    assert cfg["allreduce_size_2_consec_1_alpha_ms"] == \
        pytest.approx(alpha, rel=1e-3)
    assert cfg["allreduce_size_2_consec_1_beta_mb_per_ms"] == \
        pytest.approx(beta, rel=1e-3)
    assert meta["curves"]["2_1/flat"]["method"] == "regression"
    # the MAD pass dropped the straggler (and at most one truth point the
    # outlier-biased first fit also pushed past the cut)
    assert 5 <= meta["curves"]["2_1/flat"]["points"] <= 6


def test_refit_per_algorithm_curve_lands_in_algos_namespace():
    pts = [_pt(alg="ring_ici", mb=4.0, ms=1.0)]
    cfg, meta = refit_profile(pts, prior=PRIOR)
    # prior ring_ici predicts 2 + 4/2 = 4 -> r = 0.25
    assert cfg == {
        "allreduce_size_2_consec_1_alg_ring_lvl_ici_alpha_ms":
            pytest.approx(0.5),
        "allreduce_size_2_consec_1_alg_ring_lvl_ici_beta_mb_per_ms":
            pytest.approx(8.0)}
    assert meta["curves"] == {"2_1/ring_ici": {"points": 1,
                                               "method": "scale"}}


def test_refit_ignores_garbage_records():
    pts = [_pt(group="abc"), _pt(group="2_1_3"), _pt(mb=-1.0),
           _pt(ms=0.0), "not a dict", {"group": "2_1"}]
    cfg, meta = refit_profile(pts, prior=PRIOR)
    assert cfg == {}
    assert meta["curves"] == {}


def test_profile_roundtrip_with_provenance(tmp_path):
    pts = [_pt(mb=m, ms=0.05 + m / 250.0, t=100.0 + m)
           for m in (1.0, 2.0, 4.0, 8.0)]
    pts += [_pt(alg="ring_ici", mb=4.0, ms=1.0)]
    prof, meta = refit_profile(pts, prior=PRIOR)
    calibrated = dict(prof)
    calibrated[META_KEY] = meta
    full = merge_calibrated_profile(PRIOR, calibrated)
    # calibrated keys override the prior's; untouched prior keys survive
    assert full["allreduce_size_2_consec_1_alpha_ms"] == \
        prof["allreduce_size_2_consec_1_alpha_ms"]
    path = str(tmp_path / "calibrated_profile.json")
    write_calibrated_profile(path, full)
    loaded = json.loads(open(path).read())
    # both parsers read THROUGH the meta key; provenance reads AROUND it
    flat = read_alpha_beta(loaded)
    algos = read_alpha_beta_algos(loaded)
    assert flat["2_1"] == pytest.approx((0.05, 250.0), rel=1e-3)
    assert "ring_ici" in algos["2_1"]
    prov = read_profile_provenance(loaded)
    assert prov["source"] == "runtime-calibrated"
    assert prov["window"] == [101.0, 108.0]
    assert prov["curves"]["2_1/flat"]["method"] == "regression"
    assert read_profile_provenance(PRIOR) == {}  # profiled files: none


# ---------------------------------------------------------------------------
# stored-plan re-pricing + regret sentinel
# ---------------------------------------------------------------------------

LAYERS = [{"tp": 2, "dp": 2, "cp": 1, "sp": 0, "ckpt": 0, "consec": 1}
          for _ in range(2)]
PLAN = {"layers": LAYERS, "pp": 2, "bsz": 8, "chunks": 2}
FLAT = {"2_1": (1.0, 2.0), "2_0": (0.5, 4.0)}
KW = dict(seq_len=8, hidden_size=32, param_mb=8.0, mixed_precision=False)


def test_reprice_stored_plan_hand_math():
    act = 2 * 8 * 32 * 4 / 2**20  # lbsz=2, fp32
    # tp: 6*chunks msgs * 0.5/pp = 3 per layer; dp: (α+4/β)/pp per layer
    want = 2 * 3 * (1.0 + act / 2.0) + 2 * (0.5 + (8.0 / 2) / 4.0) / 2
    got = reprice_stored_plan_ms(PLAN, alpha_beta=FLAT, **KW)
    assert got == pytest.approx(want)
    # a cheaper ici algorithm curve wins the tp min; dcn curves are not
    # candidates for the intra-slice tp collective
    algos = {"2_1": {"ring_ici": (0.25, 2.0), "ring_dcn": (0.0, 1e9)}}
    got2 = reprice_stored_plan_ms(PLAN, alpha_beta=FLAT,
                                  alpha_beta_algos=algos, **KW)
    assert got2 == pytest.approx(want - 2 * 3 * 0.75)


def test_reprice_sp_layer_prices_dp_only():
    plan = {"layers": [{"tp": 2, "dp": 2, "sp": 1, "consec": 1}],
            "pp": 1, "bsz": 8, "chunks": 2}
    # sp folds tp into the dp ring: sdp = 2*2 = 4, consec 1 (tp==1), full
    # param grad at fp32
    got = reprice_stored_plan_ms(plan, alpha_beta={"4_1": (0.5, 4.0)},
                                 **KW)
    assert got == pytest.approx(0.5 + 8.0 / 4.0)


def test_reprice_unpriceable_plan_returns_none():
    assert reprice_stored_plan_ms(PLAN, alpha_beta={}, **KW) is None
    assert reprice_stored_plan_ms(
        {"layers": [{"tp": 1, "dp": 1}], "pp": 1, "bsz": 8, "chunks": 1},
        alpha_beta=FLAT, **KW) is None  # nothing communicates


def test_plan_regret_triggered_and_quiet():
    cal = {"2_1": (0.5, 4.0), "2_0": (0.25, 8.0)}  # everything got faster
    incumbent = dict(PLAN, time_cost_ms=10.0)
    heavy = dict(PLAN, pp=1, time_cost_ms=10.01,
                 strategies=["pp1-tp2-dp2"])
    unpriceable = {"layers": [{"tp": 1, "dp": 1}], "pp": 1, "bsz": 8,
                   "chunks": 2, "time_cost_ms": 1.0}
    res = evaluate_plan_regret(
        incumbent, [unpriceable, heavy], prior=(FLAT, None),
        calibrated=(cal, None), threshold=0.05, **KW)
    # the pp1 runner-up carries 2x the incumbent's comm, so the
    # calibration windfall favors it 2:1 and it overtakes
    assert res["triggered"] is True
    assert res["best_runner_up"] == 1
    assert res["regret_ms"] > 0
    assert res["regret_frac"] > 0.05
    assert res["runner_ups"][0]["adjusted_ms"] is None  # skipped, not faked
    # calibration that matches the prior moves nothing: no regret
    quiet = evaluate_plan_regret(
        incumbent, [heavy], prior=(FLAT, None), calibrated=(FLAT, None),
        threshold=0.05, **KW)
    assert quiet["triggered"] is False
    assert quiet["regret_ms"] == 0.0
    assert quiet["incumbent_ms"] == pytest.approx(10.0)


def test_plan_spec_from_hpc():
    spec = plan_spec_from_hpc(_hpc())
    assert spec == {"layers": LAYERS, "pp": 2, "bsz": 8, "chunks": 2}


# ---------------------------------------------------------------------------
# the glue + the crash-forensics pin
# ---------------------------------------------------------------------------


def test_run_calibration_empty_table_is_harmless(tmp_path):
    reg = MetricsRegistry()
    out = run_calibration({}, None, _model(),
                          calibration_dir=str(tmp_path), registry=reg,
                          world=8, device_kind="cpu")
    assert "error" not in out
    assert out["points_appended"] == 0
    assert out["profile_path"] is None
    assert not os.path.exists(tmp_path / "calibrated_profile.json")


def test_run_calibration_end_to_end_with_recorder(tmp_path):
    reg = MetricsRegistry()
    rec = FlightRecorder(registry=reg, out_dir=str(tmp_path / "flight"))
    table = {"steps": 2, "step_device_ms": 5.0, "rows": [
        {"component": "tp", "measured_ms": 0.6, "predicted_ms": 12.0},
        {"component": "dp", "measured_ms": 0.2, "predicted_ms": 4.0},
    ]}
    prior = {"allreduce_size_2_consec_1_alpha_ms": 2.0,
             "allreduce_size_2_consec_1_beta_mb_per_ms": 50.0,
             "allreduce_size_2_consec_0_alpha_ms": 3.0,
             "allreduce_size_2_consec_0_beta_mb_per_ms": 40.0}
    plan = {"layers": LAYERS, "pp": 2, "bsz": 8, "chunks": 2,
            "predicted_time_cost_ms": 50.0,
            "runner_ups": [dict(PLAN, pp=1, time_cost_ms=50.01,
                                strategies=["pp1-tp2-dp2"])]}
    plan_path = tmp_path / "plan.json"
    plan_path.write_text(json.dumps(plan))
    out = run_calibration(
        table, _hpc(), _model(), calibration_dir=str(tmp_path),
        registry=reg, prior_config=prior, world=8, device_kind="cpu",
        regret_threshold=1e-9, plan_path=str(plan_path),
        mixed_precision=False, recorder=rec, run_id="t0")
    assert "error" not in out
    assert out["points_appended"] == 2  # one tp + one dp point
    assert out["curves_fitted"] == 2
    assert out["drift_score"] == pytest.approx(
        (11.4 + 3.8) / 16.0)
    assert out["regret"]["triggered"] is True
    # the crash dump carries the calibration picture at failure time
    events = [json.loads(l)
              for l in open(tmp_path / "residuals.jsonl") if l.strip()]
    assert len(events) == 2
    snap = rec.snapshot("test")
    assert snap["retained"]["plan_audit"]["data"]["drift_score"] == \
        out["drift_score"]
    assert snap["retained"]["plan_regret"]["data"]["regret_ms"] == \
        out["regret"]["regret_ms"]
    path = rec.dump("test")
    dumped = json.loads(open(path).read())
    assert "plan_regret" in dumped["retained"]


def test_recorder_retain_latest_wins_and_survives_ring_pressure():
    rec = FlightRecorder(capacity=4, registry=MetricsRegistry())
    rec.retain("plan_audit", {"drift_score": 0.5})
    rec.retain("plan_audit", {"drift_score": 0.7})
    for i in range(64):  # far past ring capacity
        rec.note("step", i=i)
    snap = rec.snapshot("test")
    assert len(snap["events"]) == 4
    assert snap["retained"]["plan_audit"]["data"]["drift_score"] == 0.7


def test_check_calibration_pass_is_green(capsys):
    from hetu_galvatron_tpu.cli.check import run_calibration as check_cal

    assert check_cal() == 0
    assert "FAIL" not in capsys.readouterr().out


def test_summarize_renders_calibrated_provenance(tmp_path):
    from hetu_galvatron_tpu.cli.summarize import summarize

    pts = [_pt(mb=m, ms=0.05 + m / 250.0) for m in (1.0, 2.0, 4.0, 8.0)]
    prof, meta = refit_profile(pts, prior=PRIOR)
    full = merge_calibrated_profile(PRIOR, prof)
    full[META_KEY] = meta
    path = str(tmp_path / "calibrated_profile.json")
    write_calibrated_profile(path, full)
    buf = io.StringIO()
    headline = summarize(path, out=buf)
    text = buf.getvalue()
    assert "runtime-calibrated" in text
    assert headline["calibrated_curves"] == 1
