"""Closed-loop trace analysis: Chrome-trace parsing, device-time
attribution (categories / modules / spans / bubbles), XLA program cost
accounting, the predicted-vs-actual plan audit, and the TraceCapture edge
cases (window never triggered, pre-existing trace dir, stop without start,
nested span names surviving into the parsed capture)."""

import glob
import gzip
import io
import json
import os
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
    set_registry,
)
from hetu_galvatron_tpu.observability.sinks import JsonlSink
from hetu_galvatron_tpu.observability.trace_analysis import (
    Attribution,
    analyze_and_audit,
    attribute,
    audit_plan,
    jit_cost_summary,
    latest_profile_dir,
    load_trace,
    maybe_record_jit_cost,
    measured_components,
    op_category,
    predicted_comm_per_step,
)
from hetu_galvatron_tpu.observability.tracing import TraceCapture, span
from hetu_galvatron_tpu.utils.strategy import LayerStrategy

pytestmark = pytest.mark.observability

MB = 1024 * 1024

CFG = ModelArgs(
    hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
    vocab_size=64, max_position_embeddings=16, seq_length=8,
    make_vocab_size_divisible_by=1, ffn_hidden_size=64)


# ---------------------------------------------------------------------------
# synthetic Chrome traces
# ---------------------------------------------------------------------------


def _ev(pid, tid, ts, dur, name, **args):
    e = {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
         "name": name}
    if args:
        e["args"] = args
    return e


def _write_trace(run_dir, events, procs=None, name="t.trace.json.gz"):
    os.makedirs(run_dir, exist_ok=True)
    meta = [{"ph": "M", "name": "process_name", "pid": pid,
             "args": {"name": pname}}
            for pid, pname in (procs or {}).items()]
    path = os.path.join(run_dir, name)
    data = json.dumps({"traceEvents": meta + events}).encode()
    if name.endswith(".gz"):
        with gzip.open(path, "wb") as f:
            f.write(data)
    else:
        with open(path, "wb") as f:
            f.write(data)
    return path


def test_op_category_stems():
    assert op_category("all-reduce.1") == "allreduce"
    assert op_category("all-reduce-start.3") == "allreduce"  # async pair
    assert op_category("all-gather.2") == "allgather"
    assert op_category("reduce-scatter.7") == "reducescatter"
    assert op_category("all-to-all") == "alltoall"
    assert op_category("collective-permute.1") == "permute"
    assert op_category("fusion.12") == "compute"
    assert op_category("dot_general") == "compute"


def test_load_trace_run_discovery_and_torn_files(tmp_path):
    root = str(tmp_path / "trace")
    run = os.path.join(root, "plugins", "profile", "2026_01_01_00_00_00")
    _write_trace(run, [_ev(1, 1, 0, 10, "fusion.1", hlo_op="fusion.1")])
    # a torn gz (crashed run) and a valid-JSON-but-not-a-trace file must
    # both be skipped, not fatal
    with open(os.path.join(run, "torn.trace.json.gz"), "wb") as f:
        f.write(b"\x1f\x8b\x08garbage")
    with open(os.path.join(run, "bare.trace.json"), "w") as f:
        f.write("[1, 2, 3]")
    for probe in (root, run):  # capture root and run dir both accepted
        td = load_trace(probe)
        assert len(td.events) == 1
        assert td.path == run
    with pytest.raises(FileNotFoundError):
        load_trace(str(tmp_path / "empty"))
    assert latest_profile_dir(str(tmp_path / "empty")) is None
    # newest run (lexicographic max) wins
    run2 = os.path.join(root, "plugins", "profile", "2026_02_02_00_00_00")
    _write_trace(run2, [_ev(1, 1, 0, 10, "fusion.9", hlo_op="fusion.9"),
                        _ev(1, 1, 20, 10, "fusion.9", hlo_op="fusion.9")])
    assert len(load_trace(root).events) == 2


def test_attribute_categories_bubble_and_modules():
    """Hand-computed two-track device trace: busy/idle split, per-device
    category averaging, and per-module attribution."""
    events = [
        # track (1,1): 0.4ms compute, 0.2 allreduce, 0.2 idle, 0.2 allgather
        _ev(1, 1, 0, 400, "fusion.1", hlo_op="fusion.1", hlo_module="jit_s"),
        _ev(1, 1, 400, 200, "all-reduce.1", hlo_op="all-reduce.1",
            hlo_module="jit_s"),
        _ev(1, 1, 800, 200, "all-gather.2", hlo_op="all-gather.2",
            hlo_module="jit_s"),
        # track (1,2): 0.6ms compute, 0.1 idle, 0.3 reduce-scatter
        _ev(1, 2, 0, 600, "fusion.2", hlo_op="fusion.2", hlo_module="jit_s"),
        _ev(1, 2, 700, 300, "reduce-scatter.1", hlo_op="reduce-scatter.1",
            hlo_module="jit_s"),
    ]
    attr = attribute(SimpleNamespace(events=events, process_names={},
                                     thread_names={}, path=""))
    assert attr.tracks == 2
    assert attr.wall_ms == pytest.approx(1.0)
    assert attr.device_busy_ms == pytest.approx(1.7)
    assert attr.per_device_busy_ms == pytest.approx(0.85)
    assert attr.bubble_ms == pytest.approx(0.15)
    assert attr.bubble_frac == pytest.approx(0.15)
    assert attr.categories_ms["compute"] == pytest.approx(0.5)
    assert attr.categories_ms["allreduce"] == pytest.approx(0.1)
    assert attr.categories_ms["allgather"] == pytest.approx(0.1)
    assert attr.categories_ms["reducescatter"] == pytest.approx(0.15)
    assert attr.collective_ms == pytest.approx(0.35)
    assert attr.compute_ms == pytest.approx(0.5)
    assert attr.per_module_ms["jit_s"] == pytest.approx(0.85)


def test_attribute_nested_spans_steps_and_layers():
    """Host annotations reconstruct nesting paths by containment, count
    optimizer steps via the step-span markers, and bucket layer spans."""
    events = [
        _ev(9, 1, 0, 1000, "train/step"),
        _ev(9, 1, 100, 200, "pp/fwd_s0"),
        _ev(9, 1, 400, 100, "layer0/fwd"),
        _ev(9, 1, 1000, 1000, "train/step"),
        _ev(9, 1, 1100, 100, "layer1/fwd"),
    ]
    attr = attribute(SimpleNamespace(events=events, process_names={},
                                     thread_names={}, path=""))
    assert attr.steps == 2
    assert attr.host_span_ms["train/step"] == pytest.approx(2.0)
    assert attr.host_span_ms["train/step/pp/fwd_s0"] == pytest.approx(0.2)
    assert attr.host_span_ms["train/step/layer0/fwd"] == pytest.approx(0.1)
    assert attr.per_layer_ms == {0: pytest.approx(0.1),
                                 1: pytest.approx(0.1)}
    assert attr.tracks == 0  # no device events in this trace


def test_attribute_steps_not_inflated_by_device_track_copies():
    """On TPU the step annotation propagates onto every device track;
    steps must be the per-track max, not the all-track sum."""
    events = []
    for pid in (9, 5, 6):  # host thread + two device tracks
        events += [_ev(pid, 1, 0, 900, "train/step"),
                   _ev(pid, 1, 1000, 900, "train/step")]
    attr = attribute(SimpleNamespace(
        events=events,
        process_names={5: "/device:TPU:0", 6: "/device:TPU:1"},
        thread_names={}, path=""))
    assert attr.steps == 2


def test_attribute_device_track_annotation_coverage():
    """On a TPU-style device track (``/device:*`` process), an annotation
    interval attributes the device-op time it covers — the propagated
    TraceAnnotation names."""
    events = [
        _ev(5, 1, 0, 300, "fusion.7"),
        _ev(5, 1, 300, 100, "all-reduce.3"),
        _ev(5, 1, 500, 100, "fusion.8"),
        _ev(5, 1, 0, 350, "train/step"),  # covers fusion.7 + half the AR
    ]
    attr = attribute(SimpleNamespace(
        events=events, process_names={5: "/device:TPU:0"},
        thread_names={}, path=""))
    assert attr.tracks == 1
    assert attr.categories_ms["compute"] == pytest.approx(0.4)
    assert attr.categories_ms["allreduce"] == pytest.approx(0.1)
    assert attr.device_annotation_ms["train/step"] == pytest.approx(0.35)


def test_attribute_two_source_permute_disambiguation():
    """One compiled program mixing tp-ring hops with pp stage rotations
    (the unified 1F1B engine): permutes stamped with named_scope metadata
    (``pp_rotate`` / ``tp_ring`` / ``cp_ring`` in the tf_op path) bill to
    their own sub-category, an unmarked permute covered by a
    device-propagated ``tp/overlap_step`` span rebills to tp, and only the
    remainder stays on the plan-level heuristic."""
    events = [
        # stage rotation: named_scope metadata rides in tf_op
        _ev(5, 1, 0, 100, "collective-permute.1",
            tf_op="pp_rotate/ppermute"),
        # tp ring hop, marker in long_name instead
        _ev(5, 1, 150, 100, "collective-permute.2",
            long_name="jit(step)/tp_ring/ppermute"),
        # cp ring hop
        _ev(5, 1, 300, 50, "collective-permute.5",
            tf_op="cp_ring/ppermute"),
        # unmarked permute fully inside a tp/overlap_step device window
        _ev(5, 1, 400, 100, "collective-permute.3"),
        _ev(5, 1, 380, 140, "tp/overlap_step"),
        # unmarked permute outside every window -> plan heuristic
        _ev(5, 1, 600, 100, "collective-permute.4"),
        _ev(5, 1, 750, 100, "fusion.1"),
    ]
    attr = attribute(SimpleNamespace(
        events=events, process_names={5: "/device:TPU:0"},
        thread_names={}, path=""))
    assert attr.categories_ms["permute_pp"] == pytest.approx(0.1)
    assert attr.categories_ms["permute_tp"] == pytest.approx(0.2)
    assert attr.categories_ms["permute_cp"] == pytest.approx(0.05)
    assert attr.categories_ms["permute"] == pytest.approx(0.1)
    # a pipelined tp plan: ring hops land on tp, rotations + the unmarked
    # remainder on pp — the mis-billing the round-11 heuristic had
    from hetu_galvatron_tpu.utils.strategy import LayerStrategy

    hpc = SimpleNamespace(layers=[LayerStrategy(pp_deg=2, tp_size=2,
                                                dp_size=2)], pp_deg=2)
    m = measured_components(attr, hpc)
    assert m["tp"] == pytest.approx(0.2)
    assert m["cp"] == pytest.approx(0.05)
    assert m["pp"] == pytest.approx(0.1 + 0.1)


def test_attribute_window_rebilling_disabled_under_compiled_pipeline():
    """The tp/overlap_step span wraps the whole train step, so when the
    COMPILED engine ran (its pp stage rotations are in-program ppermutes
    inside the same window) an unmarked permute must NOT be rebilled to tp
    by window coverage — the pp/compiled_step annotation is the evidence
    that disables the pass; only named_scope markers disambiguate there."""
    events = [
        # unmarked permute (a stage rotation whose HLO metadata was
        # stripped) fully inside a step-wide tp/overlap_step window
        _ev(5, 1, 400, 100, "collective-permute.3"),
        _ev(5, 1, 0, 1000, "tp/overlap_step"),
        _ev(5, 1, 0, 1000, "pp/compiled_step"),
        _ev(5, 1, 750, 100, "fusion.1"),
    ]
    attr = attribute(SimpleNamespace(
        events=events, process_names={5: "/device:TPU:0"},
        thread_names={}, path=""))
    # stays a bare permute -> the plan heuristic (pp when pipelined)
    assert attr.categories_ms.get("permute") == pytest.approx(0.1)
    assert "permute_tp" not in attr.categories_ms


# ---------------------------------------------------------------------------
# XLA program cost accounting
# ---------------------------------------------------------------------------


def test_jit_cost_summary_counts_flops():
    fn = jax.jit(lambda a, b: a @ b)
    sds = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    out = jit_cost_summary(fn, (sds, sds))
    # 64^3 multiply-adds = 2*64^3 flops; XLA counts at least the matmul
    assert out.get("flops", 0) >= 2 * 64 ** 3
    # never raises on garbage
    assert jit_cost_summary(object()) == {}


def test_maybe_record_jit_cost_once_per_registry_and_sink_gating(tmp_path):
    fn = jax.jit(lambda a: a * 2.0)
    args = (jnp.ones((8, 8)),)
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    first = maybe_record_jit_cost("prog/a", fn, args, registry=reg)
    assert first and first["flops"] > 0
    # idempotent per (registry, program)
    assert maybe_record_jit_cost("prog/a", fn, args, registry=reg) is None
    # a different registry records independently
    reg2 = MetricsRegistry([JsonlSink(str(tmp_path / "m2.jsonl"))])
    assert maybe_record_jit_cost("prog/a", fn, args, registry=reg2)
    # gauges + one-shot event land in the stream
    assert reg.gauge("cost/flops", program="prog/a").value > 0
    reg.close()
    recs = [json.loads(l) for l in open(path)]
    ev = [r for r in recs if r.get("name") == "program_cost"]
    assert len(ev) == 1 and ev[0]["data"]["program"] == "prog/a"
    # default registry without sinks: pure no-op
    old = get_registry()
    try:
        set_registry(MetricsRegistry())
        assert maybe_record_jit_cost("prog/b", fn, args) is None
        assert not get_registry().metrics()
    finally:
        set_registry(old)


# ---------------------------------------------------------------------------
# predicted communication + component mapping
# ---------------------------------------------------------------------------


def _hpc(layers, *, chunks=1, global_bsz=8, pp_deg=1):
    return SimpleNamespace(layers=layers, chunks=chunks,
                           global_bsz=global_bsz, pp_deg=pp_deg)


def test_predicted_comm_per_step_alpha_beta_pricing():
    """The α-β time predictions follow the cost model's pricing exactly:
    one Megatron-SP message is 0.5*(α + size/β) × 6 msgs/layer/chunk, one
    dp all-reduce is α + grad_mb/β."""
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb

    ab = {"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)}
    layers = [LayerStrategy(tp_size=2, dp_size=2)] * 2
    hpc = _hpc(layers, chunks=1, global_bsz=8)
    out = predicted_comm_per_step(hpc, CFG, alpha_beta=ab,
                                  mixed_precision=True)
    lbsz = 8 // 1 // 2
    act_mb = lbsz * CFG.seq_length * CFG.hidden_size * 2 / MB
    exp_tp = 2 * 6 * 0.5 * (0.05 + act_mb / 100.0)  # 2 layers, consec pair
    assert out["tp"]["predicted_ms"] == pytest.approx(exp_tp)
    grad_mb = layer_param_mb(CFG) / 2 * 0.5
    exp_dp = 2 * (0.07 + grad_mb / 80.0)  # tp>1 leaves dp strided -> "2_0"
    assert out["dp"]["predicted_ms"] == pytest.approx(exp_dp)
    assert out["tp"]["predicted_mb"] > 0 and out["dp"]["predicted_mb"] > 0
    # without fitted pairs: volumes only, no invented times
    vol_only = predicted_comm_per_step(hpc, CFG)
    assert "predicted_ms" not in vol_only["tp"]
    assert "predicted_ms" not in vol_only["dp"]


def test_predicted_comm_checkpoint_and_chunks_scaling():
    ab = {"2_1": (0.0, 100.0), "2_0": (0.0, 100.0)}
    base = predicted_comm_per_step(
        _hpc([LayerStrategy(tp_size=2, dp_size=2)]), CFG, alpha_beta=ab)
    ck = predicted_comm_per_step(
        _hpc([LayerStrategy(tp_size=2, dp_size=2, checkpoint=True)]),
        CFG, alpha_beta=ab)
    # remat replays the forward collectives: 1.5x messages
    assert ck["tp"]["predicted_ms"] == pytest.approx(
        1.5 * base["tp"]["predicted_ms"])


def test_predicted_comm_per_device_pp_normalization():
    """The measured side is a per-device-track average and each device runs
    one stage's layers, so the priced ms divide by pp_deg (volumes stay
    whole-plan)."""
    ab = {"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)}
    flat = predicted_comm_per_step(
        _hpc([LayerStrategy(tp_size=2, dp_size=2)] * 2), CFG, alpha_beta=ab)
    piped = predicted_comm_per_step(
        _hpc([LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)] * 2, pp_deg=2),
        CFG, alpha_beta=ab)
    for comp in ("tp", "dp"):
        assert piped[comp]["predicted_ms"] == pytest.approx(
            flat[comp]["predicted_ms"] / 2)
        assert piped[comp]["predicted_mb"] == pytest.approx(
            flat[comp]["predicted_mb"])


def test_measured_components_plan_disambiguation():
    attr = Attribution(categories_ms={
        "allreduce": 5.0, "allgather": 2.0, "reducescatter": 1.0,
        "alltoall": 3.0, "permute": 4.0})
    # pipelined plan with a dp group: permute->pp, allreduce->dp
    m = measured_components(attr, _hpc([LayerStrategy(
        pp_deg=2, tp_size=2, dp_size=2)], pp_deg=2))
    assert m == {"tp": 3.0, "sp": 3.0, "dp": 5.0, "pp": 4.0}
    # unpipelined cp plan: permute is the ring attention
    m = measured_components(attr, _hpc([LayerStrategy(
        tp_size=2, cp_size=2)]))
    assert m["cp"] == 4.0 and "pp" not in m
    # pure-TP single-replica plan: all-reduces are TP activations, and
    # with no pp/cp the permutes are the ring-overlap rotations
    m = measured_components(attr, _hpc([LayerStrategy(tp_size=8)]))
    assert m["tp"] == 3.0 + 5.0 + 4.0


# ---------------------------------------------------------------------------
# the plan audit
# ---------------------------------------------------------------------------


def _measured_attr(steps=2):
    return Attribution(
        steps=steps, tracks=8, wall_ms=20.0, device_busy_ms=128.0,
        per_device_busy_ms=16.0, bubble_ms=4.0, bubble_frac=0.2,
        categories_ms={"compute": 10.0, "allgather": 2.0,
                       "reducescatter": 1.0, "allreduce": 2.0,
                       "permute": 1.0})


def test_audit_plan_ratios_residuals_gauges_and_event(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    ab = {"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)}
    hpc = _hpc([LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)] * 2,
               chunks=2, pp_deg=2)
    table = audit_plan(_measured_attr(), hpc, CFG, registry=reg,
                       alpha_beta=ab, predicted_layer_s=[0.004, 0.004])
    rows = {r["component"]: r for r in table["rows"]}
    # tp: measured (ag+rs)/steps vs α-β prediction -> ratio + residual
    pred = predicted_comm_per_step(hpc, CFG, alpha_beta=ab)
    assert rows["tp"]["measured_ms"] == pytest.approx(1.5)
    assert rows["tp"]["predicted_ms"] == pytest.approx(
        pred["tp"]["predicted_ms"], abs=1e-4)
    assert rows["tp"]["ratio"] == pytest.approx(
        1.5 / pred["tp"]["predicted_ms"], rel=1e-3)
    assert rows["tp"]["residual_ms"] == pytest.approx(
        1.5 - pred["tp"]["predicted_ms"], abs=1e-3)
    assert rows["dp"]["measured_ms"] == pytest.approx(1.0)
    assert "ratio" in rows["dp"]
    # compute vs the cost model's per-layer per-microbatch seconds,
    # scaled x chunks/pp to the per-device per-step normalization
    # (here 2/2 = 1): 2 layers x 4ms = 8ms
    assert rows["compute"]["measured_ms"] == pytest.approx(5.0)
    assert rows["compute"]["predicted_ms"] == pytest.approx(8.0)
    assert rows["compute"]["ratio"] == pytest.approx(5.0 / 8.0)
    # gradient accumulation without pp: chunks=4 microbatches per step on
    # every device -> the same per-layer seconds predict 4x the ms
    acc = audit_plan(
        _measured_attr(),
        _hpc([LayerStrategy(tp_size=2, dp_size=2)] * 2,
             chunks=4, global_bsz=16),
        CFG, registry=MetricsRegistry(),
        predicted_layer_s=[0.004, 0.004])
    acc_rows = {r["component"]: r for r in acc["rows"]}
    assert acc_rows["compute"]["predicted_ms"] == pytest.approx(32.0)
    # pipeline bubble vs the 1F1B analytical fraction
    assert rows["bubble"]["measured_frac"] == pytest.approx(0.2)
    assert rows["bubble"]["predicted_frac"] == pytest.approx(
        2 * (2 - 1) / (2 + 2 * (2 - 1)))
    assert table["steps"] == 2
    assert table["step_device_ms"] == pytest.approx(8.0)
    # audit/* gauges (component-labelled) + the plan_audit event
    assert reg.gauge("audit/time_ratio", component="tp").value == \
        rows["tp"]["ratio"]
    assert reg.gauge("audit/measured_ms", component="dp").value == \
        rows["dp"]["measured_ms"]
    assert reg.gauge("audit/step_device_ms").value == pytest.approx(8.0)
    reg.close()
    evs = [json.loads(l) for l in open(path)
           if json.loads(l).get("name") == "plan_audit"]
    assert len(evs) == 1 and evs[0]["data"]["rows"] == table["rows"]


def test_audit_plan_volume_only_without_alpha_beta():
    reg = MetricsRegistry()
    hpc = _hpc([LayerStrategy(tp_size=2, dp_size=2)] * 2)
    table = audit_plan(_measured_attr(), hpc, CFG, registry=reg)
    rows = {r["component"]: r for r in table["rows"]}
    assert rows["tp"]["predicted_mb"] > 0
    assert "ratio" not in rows["tp"]  # no fitted pairs -> no invented time
    assert "predicted_frac" not in rows["bubble"]  # pp1 plan


def test_analyze_and_audit_never_raises(tmp_path):
    hpc = _hpc([LayerStrategy(tp_size=2, dp_size=2)])
    assert analyze_and_audit(str(tmp_path / "nope"), hpc, CFG) is None
    # a trace with no events -> None, not a crash
    run = str(tmp_path / "t" / "plugins" / "profile" / "r1")
    _write_trace(run, [])
    assert analyze_and_audit(str(tmp_path / "t"), hpc, CFG) is None
    # garbage hpc on a real trace -> swallowed (post-mortem helper)
    _write_trace(run, [_ev(9, 1, 0, 100, "train/step")])
    assert analyze_and_audit(str(tmp_path / "t"), object(), CFG) is None


# ---------------------------------------------------------------------------
# TraceCapture edge cases
# ---------------------------------------------------------------------------


def test_trace_capture_window_never_triggered(tmp_path):
    d = str(tmp_path / "trace")
    tc = TraceCapture(d, start_iter=100, num_iters=2)
    assert all(not tc.step(it) for it in range(5))
    tc.stop()  # idempotent no-op
    assert not tc.active
    assert latest_profile_dir(d) is None  # nothing was ever captured
    assert not os.path.exists(os.path.join(d, "plugins"))


def test_trace_capture_stop_without_start(tmp_path):
    tc = TraceCapture(str(tmp_path / "t"), start_iter=0, num_iters=1)
    tc.stop()  # never started: must not raise
    tc.stop()
    assert tc._captured == 0
    # disabled capture never starts either
    off = TraceCapture("", enabled=True)
    assert not off.enabled and not off.step(0)


def test_trace_capture_existing_dir_and_nested_spans_in_trace(tmp_path):
    """The full loop on a REAL capture: the trace dir already exists (a
    restarted run reuses it), two iterations are captured, and nested
    span() names survive into the parsed trace as containment paths."""
    d = str(tmp_path / "trace")
    os.makedirs(os.path.join(d, "plugins", "profile"))  # pre-existing
    fn = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((32, 32))
    fn(x).block_until_ready()  # compile outside the window
    tc = TraceCapture(d, start_iter=1, num_iters=2)
    assert not tc.step(0)  # before the window
    for it in (1, 2):
        assert tc.step(it)
        with span("train/step"):
            with span("pp/fwd_s0"):
                fn(x).block_until_ready()
    assert not tc.step(3)  # window closed itself after num_iters
    assert not tc.active
    tc.stop()

    attr = attribute(load_trace(d))
    assert attr.host_span_ms["train/step"] > 0
    assert attr.host_span_ms["train/step/pp/fwd_s0"] > 0  # nesting survived
    assert attr.steps == 2
    # the CPU thunk trace carries device ops (hlo_op args) -> compute time
    assert attr.tracks > 0
    assert attr.compute_ms > 0


def test_runtime_profiler_analyze_trace(tmp_path):
    """RuntimeProfiler.analyze_trace attributes its own flushed capture
    window, and degrades to None when no window was configured/flushed."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.core.profiler.runtime_profiler import (
        RuntimeProfiler,
    )

    args = CoreArgs(model={"hidden_size": 32, "num_hidden_layers": 1,
                           "num_attention_heads": 2, "vocab_size": 64,
                           "seq_length": 8, "max_position_embeddings": 16})
    assert RuntimeProfiler(args).analyze_trace() is None  # no trace_dir
    args.profile.trace_dir = str(tmp_path / "t")
    args.profile.profile_warmup = 0
    args.profile.trace_iters = 1
    prof = RuntimeProfiler(args)
    assert prof.analyze_trace() is None  # configured but never flushed
    fn = jax.jit(lambda a: a * 2)
    prof.time_start(0)
    with span("train/step"):
        fn(jnp.ones((16, 16))).block_until_ready()
    prof.time_end(0)
    prof.stop_trace()
    attr = prof.analyze_trace()
    assert attr is not None and attr.host_span_ms["train/step"] > 0


# ---------------------------------------------------------------------------
# summarize hardening (torn JSONL) — the report-side satellite
# ---------------------------------------------------------------------------


def test_summarize_survives_torn_jsonl(tmp_path, capsys):
    from hetu_galvatron_tpu.cli.summarize import load_records, summarize

    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    reg.counter("train/steps").inc(3)
    reg.gauge("train/tokens_per_sec").set(11.0)
    reg.close()
    with open(path, "a") as f:
        f.write('42\n')                                  # valid JSON, not a record
        f.write('{"kind": "gauge", "name": "train/')     # torn mid-write
    recs = load_records(path)
    assert all(isinstance(r, dict) for r in recs)
    assert "skipped 2 unparseable line(s)" in capsys.readouterr().err
    buf = io.StringIO()
    headline = summarize(path, out=buf)
    assert headline["steps"] == 3
    assert "tokens/sec" in buf.getvalue()


def test_summarize_renders_calibration_table(tmp_path):
    """audit_plan -> JSONL -> summarize renders the plan-audit table and
    surfaces the per-component ratios in the headline dict."""
    from hetu_galvatron_tpu.cli.summarize import summarize

    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    hpc = _hpc([LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)] * 2,
               chunks=2, pp_deg=2)
    audit_plan(_measured_attr(), hpc, CFG, registry=reg,
               alpha_beta={"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)},
               predicted_layer_s=[0.004, 0.004])
    reg.close()
    buf = io.StringIO()
    headline = summarize(path, out=buf)
    text = buf.getvalue()
    assert "plan audit: predicted vs actual" in text
    for comp in ("tp", "dp", "compute", "bubble"):
        assert comp in text
    assert headline["audit_ratio_tp"] > 0
    assert headline["audit_ratio_compute"] == pytest.approx(5.0 / 8.0)
    assert headline["audit_step_device_ms"] == pytest.approx(8.0)


# ---------------------------------------------------------------------------
# per-algorithm / hierarchical-dp audit rows
# ---------------------------------------------------------------------------


def test_predicted_comm_per_algorithm_min_choice():
    """With per-algorithm curves, each priced component carries every
    candidate's ms and predicted_ms = the min — the cost model's own
    choice (min over flat pair + ICI algo curves)."""
    ab = {"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)}
    algos = {"2_1": {"tree_ici": (0.01, 100.0),
                     "ring_ici": (0.2, 400.0),
                     "ring_dcn": (9.9, 1.0)}}  # dcn curve must not price tp
    hpc = _hpc([LayerStrategy(tp_size=2, dp_size=2)])
    out = predicted_comm_per_step(hpc, CFG, alpha_beta=ab,
                                  alpha_beta_algos=algos)
    tp = out["tp"]
    assert set(tp["algorithms"]) == {"flat", "tree_ici", "ring_ici"}
    assert tp["algorithm"] == min(tp["algorithms"],
                                  key=tp["algorithms"].get)
    assert tp["predicted_ms"] == pytest.approx(
        min(tp["algorithms"].values()))
    # without algo data: behavior unchanged (no algorithms key)
    flat_only = predicted_comm_per_step(hpc, CFG, alpha_beta=ab)
    assert "algorithms" not in flat_only["tp"]


def test_predicted_comm_hier_dp_decomposition():
    """A hier_dp plan prices dp as min(flat, hier) and reports the
    rs+ag/cross decomposition, through the cost model's own arithmetic."""
    from hetu_galvatron_tpu.core.cost_model.cost import (
        CostContext,
        hier_dp_reduce_ms,
    )
    from hetu_galvatron_tpu.core.search_engine.strategies import (
        SearchStrategy,
    )
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb

    ab = {"4_1": (0.5, 50.0)}
    algos = {"2_1": {"ring_ici": (0.05, 200.0)},
             "2_0": {"ring_dcn": (0.3, 20.0)}}
    hpc = _hpc([LayerStrategy(tp_size=1, dp_size=4)])
    hpc.hier_dp = True
    out = predicted_comm_per_step(hpc, CFG, alpha_beta=ab,
                                  alpha_beta_algos=algos, dcn_slices=2)
    dp = out["dp"]
    assert {"flat", "hier", "hier_intra", "hier_cross"} <= set(
        dp["algorithms"])
    grad_mb = layer_param_mb(CFG) * 0.5
    want_hier = hier_dp_reduce_ms(
        SearchStrategy(pp=1, tp=1, dp=4),
        CostContext(alpha_beta_algos=algos, hier_dp=True, dcn_slices=2),
        grad_mb)
    assert dp["algorithms"]["hier"] == pytest.approx(want_hier)
    assert dp["predicted_ms"] == pytest.approx(
        min(dp["algorithms"]["flat"], dp["algorithms"]["hier"]))
    # the decomposition entries never compete in the min
    assert dp["algorithm"] in ("flat", "hier")


def test_measured_components_bills_hier_markers_to_dp():
    attr = Attribution(categories_ms={
        "allgather": 2.0, "reducescatter": 1.0, "hier_rs": 3.0,
        "hier_ar": 0.5, "hier_ag": 2.5})
    m = measured_components(attr, _hpc([LayerStrategy(tp_size=2,
                                                      dp_size=4)]))
    # the marked hier collectives are dp; the unmarked ag/rs stay tp
    assert m["dp"] == pytest.approx(6.0)
    assert m["tp"] == pytest.approx(3.0)


def test_audit_plan_emits_per_algorithm_rows(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    ab = {"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)}
    algos = {"2_1": {"tree_ici": (0.01, 100.0),
                     "ring_ici": (0.2, 400.0)}}
    attr = _measured_attr()
    attr.categories_ms.update({"hier_rs": 1.0, "hier_ar": 0.2,
                               "hier_ag": 0.8})
    hpc = _hpc([LayerStrategy(tp_size=2, dp_size=2)] * 2)
    hpc.hier_dp = True
    algos.update({"1_1": {}})
    table = audit_plan(attr, hpc, CFG, registry=reg, alpha_beta=ab,
                       alpha_beta_algos=algos, dcn_slices=1)
    comps = {r["component"]: r for r in table["rows"]}
    # per-algorithm candidate rows ride along, exactly one chosen
    for name in ("tp[flat]", "tp[tree_ici]", "tp[ring_ici]"):
        assert name in comps and "predicted_ms" in comps[name]
    chosen = [r for c, r in comps.items()
              if c.startswith("tp[") and r.get("chosen")]
    assert len(chosen) == 1
    # the hier sub-collectives carry MEASURED ms from their markers even
    # when no hier curves are fitted (dp[...] rows need fitted dcn/ici
    # curves to exist; the dp component row still measures the traffic)
    assert comps["dp"]["measured_ms"] == pytest.approx(
        (2.0 + 1.0 + 0.2 + 0.8) / attr.steps)
    reg.flush()


def test_attribute_bucketed_hier_markers(tmp_path):
    """Bucketed hier scopes (hier_stage_scope ``hier_dp_rs_b{i}``) bill to
    the SAME hier_* categories as the monolithic markers (the base scope
    stays a prefix — substring match) AND surface the per-bucket split in
    ``Attribution.hier_bucket_ms``, which never double-counts against
    categories_ms (it is detail, not a category)."""
    run = str(tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00")
    events = [
        _ev(1, 1, 0, 1000, "reduce-scatter.1", hlo_op="reduce-scatter.1",
            tf_op="hier_dp_rs_b0/psum_scatter"),
        _ev(1, 1, 1000, 2000, "all-reduce.2", hlo_op="all-reduce.2",
            tf_op="hier_dp_ar_b0/psum"),
        _ev(1, 1, 3000, 500, "reduce-scatter.3", hlo_op="reduce-scatter.3",
            tf_op="hier_dp_rs_b1/psum_scatter"),
        _ev(1, 1, 3500, 700, "all-gather.4", hlo_op="all-gather.4",
            tf_op="hier_dp_ag_b1/all_gather"),
        # a monolithic (un-suffixed) marker: base category only, no bucket
        _ev(1, 1, 4200, 300, "all-gather.5", hlo_op="all-gather.5",
            tf_op="hier_dp_ag/all_gather"),
    ]
    _write_trace(run, events, procs={1: "/device:TPU:0"})
    attr = attribute(load_trace(run))
    assert attr.categories_ms["hier_rs"] == pytest.approx(1.5)
    assert attr.categories_ms["hier_ar"] == pytest.approx(2.0)
    assert attr.categories_ms["hier_ag"] == pytest.approx(1.0)
    assert attr.hier_bucket_ms == pytest.approx({
        "hier_rs_b0": 1.0, "hier_ar_b0": 2.0,
        "hier_rs_b1": 0.5, "hier_ag_b1": 0.7})


def test_audit_plan_per_bucket_rows_and_summarize(tmp_path):
    """audit_plan emits measured-only ``dp[hier_rs_b0]``-style rows in
    wavefront order (bucket index, then rs->ar->ag), and summarize
    renders them + headlines the count (audit_hier_bucket_rows)."""
    from hetu_galvatron_tpu.cli.summarize import summarize

    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    ab = {"2_1": (0.05, 100.0), "2_0": (0.07, 80.0)}
    attr = _measured_attr()
    attr.categories_ms.update({"hier_rs": 1.0, "hier_ar": 0.2,
                               "hier_ag": 0.8})
    attr.hier_bucket_ms = {"hier_rs_b1": 0.4, "hier_rs_b0": 0.6,
                           "hier_ar_b0": 0.2, "hier_ag_b1": 0.8}
    hpc = _hpc([LayerStrategy(tp_size=2, dp_size=2)] * 2)
    hpc.hier_dp = True
    table = audit_plan(attr, hpc, CFG, registry=reg, alpha_beta=ab,
                       alpha_beta_algos={"1_1": {}}, dcn_slices=1)
    names = [r["component"] for r in table["rows"]]
    assert [n for n in names if "_b" in n] == [
        "dp[hier_rs_b0]", "dp[hier_ar_b0]",
        "dp[hier_rs_b1]", "dp[hier_ag_b1]"]
    rows = {r["component"]: r for r in table["rows"]}
    assert rows["dp[hier_rs_b0]"]["measured_ms"] == pytest.approx(
        0.6 / attr.steps)
    assert "predicted_ms" not in rows["dp[hier_rs_b0]"]
    reg.close()
    buf = io.StringIO()
    headline = summarize(path, out=buf)
    assert headline["audit_hier_bucket_rows"] == 4
    assert "dp[hier_rs_b0]" in buf.getvalue()


def test_summarize_hardware_renders_algo_columns(tmp_path, capsys):
    from hetu_galvatron_tpu.cli.summarize import summarize_hardware

    cfg = {
        "allreduce_size_4_consec_1": 120.0,
        "allreduce_size_4_consec_1_alpha_ms": 0.2,
        "allreduce_size_4_consec_1_beta_mb_per_ms": 100.0,
        "allreduce_size_4_consec_1_alg_ring_lvl_ici_alpha_ms": 0.3,
        "allreduce_size_4_consec_1_alg_ring_lvl_ici_beta_mb_per_ms": 140.0,
        "allreduce_size_2_consec_0": 80.0,
        "allreduce_size_2_consec_0_alg_ring_lvl_dcn_alpha_ms": 0.9,
        "allreduce_size_2_consec_0_alg_ring_lvl_dcn_beta_mb_per_ms": 30.0,
    }
    import io

    buf = io.StringIO()
    head = summarize_hardware(cfg, "hw.json", out=buf)
    text = buf.getvalue()
    assert "ring_ici" in text and "ring_dcn" in text
    assert "—" in text  # unfitted cells render as em-dash
    assert head["algo_groups"] == 2
    # legacy JSON renders without the algo columns
    buf2 = io.StringIO()
    summarize_hardware({"allreduce_size_4_consec_1": 120.0}, "hw.json",
                       out=buf2)
    assert "ring_ici" not in buf2.getvalue()


def test_predicted_comm_hier_alpha_counted_once_across_layers():
    """The hierarchical schedule runs ONCE per step over the concatenated
    payload: an L-layer plan's dp[hier] prediction must charge the α
    terms once (whole-plan volume through one schedule), not L times —
    matching both the runtime and the summed layer costs."""
    from hetu_galvatron_tpu.core.cost_model.cost import (
        CostContext,
        hier_dp_reduce_ms,
    )
    from hetu_galvatron_tpu.core.search_engine.strategies import (
        SearchStrategy,
    )
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb

    algos = {"2_1": {"ring_ici": (0.05, 200.0)},
             "2_0": {"ring_dcn": (0.3, 20.0)}}
    L = 4
    hpc = _hpc([LayerStrategy(tp_size=1, dp_size=4)] * L)
    hpc.hier_dp = True
    out = predicted_comm_per_step(hpc, CFG, alpha_beta_algos=algos,
                                  dcn_slices=2)
    grad_total = L * layer_param_mb(CFG) * 0.5
    want = hier_dp_reduce_ms(
        SearchStrategy(pp=1, tp=1, dp=4),
        CostContext(alpha_beta_algos=algos, hier_dp=True, dcn_slices=2),
        grad_total)
    assert out["dp"]["algorithms"]["hier"] == pytest.approx(want)
