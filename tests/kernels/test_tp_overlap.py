"""Decomposed ring TP collective matmuls (ops/overlap.py): the overlapped
ag_matmul / matmul_rs / gated pair must match the GSPMD-reference einsum
arithmetic to dtype tolerance, forward AND backward, at tp in {2, 4} on the
8-device virtual mesh, in bf16 and f32 — and per-layer dispatch must fall
back (with a reason) exactly where the path cannot express the plan."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.ops.overlap import (
    layer_overlap_reason,
    make_ag_matmul,
    make_ag_matmul_pair,
    make_layer_matmuls,
    make_matmul_rs,
    plan_overlap_reasons,
)

pytestmark = [pytest.mark.kernels, pytest.mark.tp_overlap,
              pytest.mark.distributed]

TOL = {jnp.float32: dict(rtol=2e-5, atol=2e-5),
       jnp.bfloat16: dict(rtol=2e-2, atol=2e-2)}


def _mesh(cpu_devices, tp):
    arr = np.array(cpu_devices).reshape(8 // tp, tp)
    return Mesh(arr, ("dp", "tp")), ("dp",), ("tp",)


def _rand(key, shape, dtype):
    return jax.random.normal(jax.random.key(key), shape, jnp.float32
                             ).astype(dtype)


@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ag_matmul_fwd_bwd_parity(tp, dtype, cpu_devices):
    mesh, dp, tpa = _mesh(cpu_devices, tp)
    B, S, H, F = 4, 16, 8, 16
    x = _rand(1, (B, S, H), dtype)
    w = _rand(2, (H, F), dtype)
    ag = make_ag_matmul(mesh, dp, tpa)

    ref = lambda x, w: jnp.einsum("bsh,hf->bsf", x, w,
                                  preferred_element_type=jnp.float32)
    with mesh:
        y = jax.jit(ag)(x, w)
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref(x, w)),
                               **TOL[dtype])

    loss = lambda f: lambda x, w: jnp.sum(jnp.sin(f(x, w)))
    with mesh:
        gx, gw = jax.jit(jax.grad(loss(ag), argnums=(0, 1)))(x, w)
    rx, rw = jax.grad(loss(ref), argnums=(0, 1))(x, w)
    assert gx.dtype == dtype and gw.dtype == dtype
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32), **TOL[dtype])
    np.testing.assert_allclose(np.asarray(gw, np.float32),
                               np.asarray(rw, np.float32), **TOL[dtype])


@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_matmul_rs_fwd_bwd_parity(tp, dtype, cpu_devices):
    mesh, dp, tpa = _mesh(cpu_devices, tp)
    B, S, F, H = 4, 16, 16, 8
    h = _rand(3, (B, S, F), dtype)
    w = _rand(4, (F, H), dtype)
    rs = make_matmul_rs(mesh, dp, tpa)

    ref = lambda h, w: jnp.einsum("bsf,fh->bsh", h, w,
                                  preferred_element_type=jnp.float32)
    with mesh:
        y = jax.jit(rs)(h, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref(h, w)),
                               **TOL[dtype])

    loss = lambda f: lambda h, w: jnp.sum(jnp.sin(f(h, w)))
    with mesh:
        gh, gw = jax.jit(jax.grad(loss(rs), argnums=(0, 1)))(h, w)
    rh, rw = jax.grad(loss(ref), argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gh, np.float32),
                               np.asarray(rh, np.float32), **TOL[dtype])
    np.testing.assert_allclose(np.asarray(gw, np.float32),
                               np.asarray(rw, np.float32), **TOL[dtype])


@pytest.mark.parametrize("tp", [2, 4])
def test_gated_pair_matches_fused_split(tp, cpu_devices):
    """fc1_pair(x, wg, wu) == split(fused fc1) halves, fwd + bwd."""
    mesh, dp, tpa = _mesh(cpu_devices, tp)
    B, S, H, F = 4, 16, 8, 16
    dtype = jnp.float32
    x = _rand(5, (B, S, H), dtype)
    w = _rand(6, (H, 2 * F), dtype)
    pair = make_ag_matmul_pair(mesh, dp, tpa)

    def ref(x, w):
        h = jnp.einsum("bsh,hf->bsf", x, w,
                       preferred_element_type=jnp.float32)
        return h[..., :F], h[..., F:]

    with mesh:
        g, u = jax.jit(lambda x, w: pair(x, w[:, :F], w[:, F:]))(x, w)
    rg, ru = ref(x, w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(rg), **TOL[dtype])
    np.testing.assert_allclose(np.asarray(u), np.asarray(ru), **TOL[dtype])

    def loss(f):
        def inner(x, w):
            a, b = f(x, w)
            return jnp.sum(jnp.sin(a) * jnp.cos(b))
        return inner

    with mesh:
        gx, gw = jax.jit(jax.grad(
            loss(lambda x, w: pair(x, w[:, :F], w[:, F:])),
            argnums=(0, 1)))(x, w)
    rx, rw = jax.grad(loss(ref), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), **TOL[dtype])
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), **TOL[dtype])


def test_multi_axis_tp_ring(cpu_devices):
    """tp spread over TWO binary mesh axes (the mesh layer's tp4 = (d1, d2)
    assignment) rings over the flattened axis tuple."""
    arr = np.array(cpu_devices).reshape(2, 2, 2)
    mesh = Mesh(arr, ("d0", "d1", "d2"))
    B, S, H, F = 2, 8, 8, 16
    x = _rand(7, (B, S, H), jnp.float32)
    w = _rand(8, (H, F), jnp.float32)
    ag = make_ag_matmul(mesh, ("d0",), ("d1", "d2"))
    with mesh:
        y = jax.jit(ag)(x, w)
    ref = jnp.einsum("bsh,hf->bsf", x, w,
                     preferred_element_type=jnp.float32)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_layer_matmuls_keys(cpu_devices):
    mesh, dp, tpa = _mesh(cpu_devices, 2)
    mm = make_layer_matmuls(mesh, dp, tpa)
    assert set(mm) == {"qkv", "out", "fc1", "fc2", "fc1_pair"}
    assert mm["qkv"] is mm["fc1"]
    assert mm["out"] is mm["fc2"]


# ---------------------------------------------------------------------------
# fallback reasons
# ---------------------------------------------------------------------------


def _cfg(**kw):
    base = dict(hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
                vocab_size=128, seq_length=16, max_position_embeddings=64,
                hidden_act="swiglu", normalization="rmsnorm",
                position_embedding_type="rope", tie_word_embeddings=False,
                add_bias_linear=False, make_vocab_size_divisible_by=1,
                ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


class _Shim:
    def __init__(self, ulysses=False, cp_axes=()):
        self.ulysses = ulysses
        self.cp_axes = cp_axes


def test_layer_overlap_reasons():
    cfg = _cfg()
    assert layer_overlap_reason(cfg, _Shim(), 2) is None
    assert "tp == 1" in layer_overlap_reason(cfg, _Shim(), 1)
    assert "ulysses" in layer_overlap_reason(cfg, _Shim(ulysses=True), 2)
    assert "cp layer" in layer_overlap_reason(
        cfg, _Shim(cp_axes=("d1",)), 2)
    # tp not dividing the sequence into ring chunks
    assert "divide the sequence" in layer_overlap_reason(
        _cfg(seq_length=6), _Shim(), 4)


def test_plan_overlap_reasons_from_hpc():
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    cfg = _cfg()
    a = CoreArgs(model=cfg.model_dump())
    a.parallel.global_tp_deg = 2
    a.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(a, 8)
    rs = plan_overlap_reasons(cfg, hpc)
    assert [r for _, r in rs] == [None, None]

    a.parallel.global_tp_deg = 4
    a.parallel.use_ulysses = True
    hpc = get_hybrid_parallel_config(a, 8)
    rs = plan_overlap_reasons(cfg, hpc)
    assert all("ulysses" in r for _, r in rs)


def test_spmd_overrides_dispatch_and_fallback(cpu_devices):
    """tp_overlap_overrides: eligible layers get matmul_fns; a non-dividing
    tp reports the reason instead."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.parallel.spmd import (
        layer_shardings,
        tp_overlap_overrides,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    cfg = _cfg()
    a = CoreArgs(model=cfg.model_dump())
    a.parallel.global_tp_deg = 2
    a.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices)
    per_layer, _ = layer_shardings(hpc, mesh)
    ov, fb = tp_overlap_overrides(per_layer, mesh, cfg)
    assert sorted(ov) == [0, 1] and not fb
    assert set(ov[0]["matmul_fns"]) == {"qkv", "out", "fc1", "fc2",
                                        "fc1_pair"}

    bad = _cfg(seq_length=7, max_position_embeddings=8)
    ov, fb = tp_overlap_overrides(per_layer, mesh, bad)
    assert not ov and len(fb) == 2
    assert all("divide the sequence" in r for _, r in fb)
