"""Explicit Ulysses all-to-all attention (ops/ulysses.py, reference
_SeqAllToAll): numerics vs the XLA core, GQA divisibility fallback, and
HLO-level evidence that the lowering emits head-scatter all-to-alls."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.models.modules import xla_sdpa
from hetu_galvatron_tpu.ops.ulysses import make_ulysses_sdpa

pytestmark = [pytest.mark.kernels, pytest.mark.parallel]


def _mesh(cpu_devices, sp=4):
    import numpy as _np

    return Mesh(_np.array(cpu_devices[:sp * 2]).reshape(2, sp), ("dp", "sp"))


def _qkv(B=2, S=16, N=4, D=8, K=None, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    K = K or N
    return (jax.random.normal(ks[0], (B, S, N, D)),
            jax.random.normal(ks[1], (B, S, K, D)),
            jax.random.normal(ks[2], (B, S, K, D)))


def test_ulysses_matches_xla_core(cpu_devices):
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv()
    for causal in (True, False):
        ref = xla_sdpa(q, k, v, causal=causal)
        out = sdpa(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_groups(cpu_devices):
    """kv heads divisible by sp: the a2a path handles GQA."""
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv(N=8, K=4)
    ref = xla_sdpa(q, k, v, causal=True)
    out = sdpa(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_kv_heads_below_sp_replicate(cpu_devices):
    """kv heads < sp degree: kv heads replicate up to sp so the head
    scatter stays whole-headed (reference repeat_interleave,
    attention_impl.py:278-417) — numerics unchanged."""
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv(N=4, K=2)  # K=2 < sp=4, sp % K == 0 -> replicate
    ref = xla_sdpa(q, k, v, causal=True)
    out = sdpa(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_truly_indivisible_falls_back(cpu_devices):
    """Head counts that neither divide nor divide into sp: XLA core
    fallback (GSPMD chooses the collectives)."""
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv(N=6, K=3)  # 3 % 4 != 0 and 4 % 3 != 0
    ref = xla_sdpa(q, k, v, causal=True)
    out = sdpa(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gradients(cpu_devices):
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv()

    def loss(fn):
        return lambda q_, k_, v_: jnp.sum(
            jnp.square(fn(q_, k_, v_, causal=True)))

    gref = jax.grad(loss(xla_sdpa), argnums=(0, 1, 2))(q, k, v)
    g = jax.grad(loss(sdpa), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_lowering_emits_all_to_all(cpu_devices):
    """The round-2 verdict's perf landmine: nobody had verified the Ulysses
    path lowers to head-scatter all-to-alls rather than all-gathers. Compile
    the jitted attention over the mesh and check the collective is there."""
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv()
    shd = NamedSharding(mesh, P("dp", "sp", None, None))
    qs, ks, vs = (jax.device_put(t, shd) for t in (q, k, v))

    f = jax.jit(lambda a, b, c: sdpa(a, b, c, causal=True))
    hlo = f.lower(qs, ks, vs).compile().as_text()
    assert "all-to-all" in hlo, "expected explicit all-to-all collectives"


def test_ulysses_gqa_ratio_unsplittable_falls_back(cpu_devices):
    """N=6, K=2, sp=4: replication would give K=4 which no longer divides
    N — the decision must happen BEFORE mutating k/v so the XLA fallback
    sees the true GQA ratio."""
    mesh = _mesh(cpu_devices)
    sdpa = make_ulysses_sdpa(mesh, ("sp",), dp_axes=("dp",))
    q, k, v = _qkv(N=6, K=2)
    ref = xla_sdpa(q, k, v, causal=True)
    out = sdpa(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
