"""Pallas flash attention vs the dense XLA core (interpret mode on CPU; the
kernel itself compiles with Mosaic on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.models.modules import xla_sdpa
from hetu_galvatron_tpu.ops.pallas.flash_attention import flash_sdpa

pytestmark = pytest.mark.kernels


def _qkv(B=2, S=128, N=4, K=4, D=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (B, S, N, D), dtype)
    k = jax.random.normal(ks[1], (B, S, K, D), dtype)
    v = jax.random.normal(ks[2], (B, S, K, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = xla_sdpa(q, k, v, causal=causal)
    out = flash_sdpa(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_gqa():
    q, k, v = _qkv(N=8, K=2)
    ref = xla_sdpa(q, k, v, causal=True)
    out = flash_sdpa(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_multiple_q_blocks():
    # S=512 with block 256 -> 2 q blocks, causal skips the upper k block
    q, k, v = _qkv(B=1, S=512, N=2, K=2)
    ref = xla_sdpa(q, k, v, causal=True)
    out = flash_sdpa(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_fits_blocks_to_seq():
    # 384 is not a multiple of the 256/512 defaults: the wrapper clamps to
    # the largest lane-aligned divisor (128) instead of raising
    q, k, v = _qkv(B=1, S=384, N=2, K=2)
    out = flash_sdpa(q, k, v, causal=True, interpret=True)
    ref = xla_sdpa(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # an explicitly requested non-divisor block still raises
    with pytest.raises(ValueError, match="must divide"):
        flash_sdpa(q, k, v, interpret=True, block_q=256)


def test_flash_gradients_match():
    """jax.grad must flow through the flash kernel (custom VJP via dense
    recompute) and match the dense-core gradients."""
    q, k, v = _qkv(B=1, S=128, N=2, K=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_gradients_gqa_groups():
    """Fused backward with G=4 query heads per kv head (the grouped dk/dv
    accumulation path)."""
    q, k, v = _qkv(B=1, S=128, N=8, K=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_gradients_noncausal():
    q, k, v = _qkv(B=1, S=64, N=2, K=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=False, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=False) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_segment_ids_match_dense():
    """Packed-document masking inside the kernel (fwd + grads) == dense core
    with the block-diagonal mask."""
    q, k, v = _qkv(B=2, S=128, N=4, K=4)
    seg = jnp.asarray(
        np.concatenate([np.zeros((2, 40), np.int32),
                        np.ones((2, 50), np.int32),
                        np.full((2, 38), 2, np.int32)], axis=1))
    ref = xla_sdpa(q, k, v, causal=True, segment_ids=seg)
    out = flash_sdpa(q, k, v, causal=True, interpret=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        xla_sdpa(a, b, c, causal=True, segment_ids=seg) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda a, b, c: jnp.sum(
        flash_sdpa(a, b, c, causal=True, interpret=True,
                   segment_ids=seg) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_supports_segments_attrs(cpu_devices):
    """apply_attention routes packed docs by this attribute; both the plain
    kernel and the shard_map wrapper must advertise it (ADVICE r3)."""
    from jax.sharding import Mesh
    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    assert getattr(flash_sdpa, "supports_segments", False)
    mesh = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
    sdpa = make_flash_sdpa(mesh, dp_axes=("dp",), tp_axes=("tp",),
                           interpret=True)
    assert getattr(sdpa, "supports_segments", False)


def test_distributed_flash_segment_ids(cpu_devices):
    """segment_ids through the shard_map wrapper (dp-sharded operand)."""
    from jax.sharding import Mesh
    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    mesh = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
    q, k, v = _qkv(B=2, S=128, N=4, K=4)
    seg = jnp.asarray(
        np.concatenate([np.zeros((2, 64), np.int32),
                        np.ones((2, 64), np.int32)], axis=1))
    flash = make_flash_sdpa(mesh, dp_axes=("dp",), tp_axes=("tp",),
                            interpret=True)
    ref = xla_sdpa(q, k, v, causal=True, segment_ids=seg)
    out = jax.jit(lambda a, b, c: flash(a, b, c, causal=True,
                                        segment_ids=seg))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_distributed_flash_matches_dense(cpu_devices):
    """shard_map-wrapped flash (batch over dp, heads over tp) == dense, with
    gradients, on a dp2 x tp2 mesh (interpret mode)."""
    from jax.sharding import Mesh
    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    mesh = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
    q, k, v = _qkv(B=2, S=64, N=4, K=4)
    flash = make_flash_sdpa(mesh, dp_axes=("dp",), tp_axes=("tp",),
                            interpret=True)
    ref = xla_sdpa(q, k, v, causal=True)
    out = jax.jit(lambda a, b, c: flash(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        xla_sdpa(a, b, c, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_out = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
        flash(a, b, c, causal=True) ** 2), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


# ---------------------------------------------------------------------------
# in-kernel attention dropout (counter-based mask over global coordinates;
# the reference's flash-attn dropout variant). keep_mask is pure jnp, so a
# dense reference applying the EXACT same mask verifies fwd + bwd bitwise
# (up to fp tolerance) — stronger than a statistical check.
# ---------------------------------------------------------------------------


def _ref_dropout_attn(q, k, v, seed, rate, causal=True):
    """Dense attention with the kernel's exact dropout mask."""
    import math

    from hetu_galvatron_tpu.ops.pallas.flash_attention import keep_mask

    B, S, N, D = q.shape
    K = k.shape[2]
    G = N // K
    qg = q.reshape(B, S, K, G, D)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k,
                   preferred_element_type=jnp.float32) / math.sqrt(D)
    if causal:
        s = jnp.where(jnp.arange(S)[:, None] >= jnp.arange(S)[None, :], s,
                      jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    bn = (jnp.arange(B)[:, None] * N
          + jnp.arange(N)[None, :])  # flat head index n = kh*G + g
    keep = keep_mask(seed[0], bn[:, :, None, None],
                     jnp.arange(S)[None, None, :, None],
                     jnp.arange(S)[None, None, None, :], rate)
    keep = keep.reshape(B, K, G, S, S)
    p = jnp.where(keep, p / (1.0 - rate), 0.0)
    out = jnp.einsum("bkgst,btkd->bskgd", p, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, S, N, D).astype(q.dtype)


def test_flash_dropout_matches_masked_dense():
    from hetu_galvatron_tpu.ops.pallas.flash_attention import seed_from_key

    q, k, v = _qkv(S=64, D=16)
    rng = jax.random.key(5)
    seed = seed_from_key(rng)
    ref = _ref_dropout_attn(q, k, v, seed, 0.2)
    out = flash_sdpa(q, k, v, causal=True, interpret=True,
                     dropout_rate=0.2, dropout_rng=rng)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_dropout_gradients_match_masked_dense():
    from hetu_galvatron_tpu.ops.pallas.flash_attention import seed_from_key

    q, k, v = _qkv(S=32, N=4, K=2, D=16)  # GQA
    rng = jax.random.key(11)
    seed = seed_from_key(rng)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_dropout_attn(q, k, v, seed, 0.3) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=True, interpret=True,
                                  dropout_rate=0.3, dropout_rng=rng) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_dropout_block_size_invariant():
    """The mask hashes GLOBAL coordinates, so different tilings drop the
    same entries."""
    q, k, v = _qkv(S=64, D=16)
    rng = jax.random.key(3)
    a = flash_sdpa(q, k, v, interpret=True, dropout_rate=0.25,
                   dropout_rng=rng, block_q=16, block_k=32)
    b = flash_sdpa(q, k, v, interpret=True, dropout_rate=0.25,
                   dropout_rng=rng, block_q=32, block_k=16)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


def test_flash_dropout_statistics_and_zero_rate():
    from hetu_galvatron_tpu.ops.pallas.flash_attention import keep_mask

    # empirical keep fraction over a large grid ~ 1 - rate
    bn = jnp.zeros((1,), jnp.int32)
    m = keep_mask(jnp.int32(123), bn, jnp.arange(512)[:, None],
                  jnp.arange(512)[None, :], 0.3)
    frac = float(jnp.mean(m.astype(jnp.float32)))
    assert abs(frac - 0.7) < 0.01, frac
    # rate 0 == no dropout path
    q, k, v = _qkv(S=32, D=16)
    a = flash_sdpa(q, k, v, interpret=True)
    b = flash_sdpa(q, k, v, interpret=True, dropout_rate=0.0,
                   dropout_rng=jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_apply_attention_flash_dropout_dispatch(cpu_devices):
    """modules.apply_attention routes attention_dropout through a
    dropout-capable kernel instead of refusing (ring still refuses)."""
    from jax.sharding import Mesh

    from hetu_galvatron_tpu.core.args_schema import ModelArgs
    from hetu_galvatron_tpu.models import modules as M
    from hetu_galvatron_tpu.ops.ring_attention import make_ring_sdpa

    cfg = ModelArgs(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=64, seq_length=32,
        attention_dropout=0.2, hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", add_bias_linear=False,
        add_qkv_bias=False, make_vocab_size_divisible_by=1)
    p, _ = M.init_attention(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 32, 32), jnp.float32)

    def flash_interp(qq, kk, vv, **kw):
        return flash_sdpa(qq, kk, vv, interpret=True, **kw)

    flash_interp.supports_dropout = True
    out = M.apply_attention(p, x, cfg, sdpa_fn=flash_interp,
                            compute_dtype=jnp.float32,
                            dropout_rng=jax.random.key(2))
    assert np.all(np.isfinite(np.asarray(out)))
    ring = make_ring_sdpa(Mesh(np.array(cpu_devices[:2]), ("c",)), ("c",))
    with pytest.raises(NotImplementedError, match="ring"):
        M.apply_attention(p, x, cfg, sdpa_fn=ring,
                          compute_dtype=jnp.float32,
                          dropout_rng=jax.random.key(2))


def test_distributed_flash_dropout(cpu_devices):
    """make_flash_sdpa dropout under shard_map: runs, differs from the
    no-dropout output, is deterministic per key, and decorrelates masks
    across dp shards (each shard folds its mesh coordinates into the
    seed)."""
    from jax.sharding import Mesh

    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    mesh = Mesh(np.array(cpu_devices[:2]).reshape(2), ("dp",))
    sdpa = make_flash_sdpa(mesh, dp_axes=("dp",), interpret=True)
    assert sdpa.supports_dropout
    q, k, v = _qkv(B=4, S=64, D=16)
    rng = jax.random.key(9)
    base = sdpa(q, k, v, causal=True)
    a = sdpa(q, k, v, causal=True, dropout_rate=0.3, dropout_rng=rng)
    b = sdpa(q, k, v, causal=True, dropout_rate=0.3, dropout_rng=rng)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)
    assert np.abs(np.asarray(a - base)).max() > 1e-3
    # shard decorrelation: rows 0-1 (shard 0) and rows 2-3 (shard 1) see
    # different masks even for identical inputs
    q2 = jnp.concatenate([q[:2], q[:2]], axis=0)
    k2 = jnp.concatenate([k[:2], k[:2]], axis=0)
    v2 = jnp.concatenate([v[:2], v[:2]], axis=0)
    out = sdpa(q2, k2, v2, causal=True, dropout_rate=0.3, dropout_rng=rng)
    assert np.abs(np.asarray(out[:2] - out[2:])).max() > 1e-3
    # and grads flow
    g = jax.grad(lambda qq: jnp.sum(sdpa(qq, k, v, causal=True,
                                         dropout_rate=0.3,
                                         dropout_rng=rng) ** 2))(q)
    assert np.all(np.isfinite(np.asarray(g)))


def test_keep_mask_no_long_sequence_aliasing():
    """ADVICE r5: the old per-element counter qpos*s_total+kpos wrapped
    uint32 once s_total exceeded 2**16, handing distant (qpos, kpos) pairs
    within one head bit-identical dropout masks. The chained finalizer mix
    has no sequence-length bound (and no s_total parameter any more): rows
    that PROVABLY aliased under the old scheme at s_total = 2**17
    (qpos * s_total === 0 mod 2**32) must now differ."""
    from hetu_galvatron_tpu.ops.pallas.flash_attention import keep_mask

    bn = jnp.zeros((1,), jnp.int32)
    kpos = jnp.arange(4096)[None, :]
    rows = []
    # old counters at s_total=2**17: 0*s+k, (2**15)*s+k = 2**32+k = k,
    # (2**16)*s+k = k — all three rows were identical
    for q in (0, 2 ** 15, 2 ** 16):
        rows.append(np.asarray(keep_mask(
            jnp.int32(7), bn, jnp.full((1, 1), q, jnp.int32), kpos, 0.5)))
    assert not np.array_equal(rows[0], rows[1])
    assert not np.array_equal(rows[0], rows[2])
    assert not np.array_equal(rows[1], rows[2])
    # keep fraction stays calibrated at extreme lengths
    for r in rows:
        assert abs(float(r.mean()) - 0.5) < 0.05


def test_keep_mask_tile_invariance_property():
    """The mask depends only on global coordinates: slicing the full-grid
    mask equals computing the mask on the slice's coordinates (the
    property that keeps fwd/bwd kernels tile-size independent)."""
    from hetu_galvatron_tpu.ops.pallas.flash_attention import keep_mask

    S = 128
    bn = jnp.zeros((1,), jnp.int32)
    full = np.asarray(keep_mask(jnp.int32(3), bn,
                                jnp.arange(S)[:, None],
                                jnp.arange(S)[None, :], 0.3))
    tile = np.asarray(keep_mask(jnp.int32(3), bn,
                                (32 + jnp.arange(16))[:, None],
                                (64 + jnp.arange(16))[None, :], 0.3))
    np.testing.assert_array_equal(full[32:48, 64:80], tile)
