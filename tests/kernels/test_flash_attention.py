"""Pallas flash attention vs the dense XLA core (interpret mode on CPU; the
kernel itself compiles with Mosaic on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.models.modules import xla_sdpa
from hetu_galvatron_tpu.ops.pallas.flash_attention import flash_sdpa

pytestmark = pytest.mark.kernels


def _qkv(B=2, S=128, N=4, K=4, D=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (B, S, N, D), dtype)
    k = jax.random.normal(ks[1], (B, S, K, D), dtype)
    v = jax.random.normal(ks[2], (B, S, K, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = xla_sdpa(q, k, v, causal=causal)
    out = flash_sdpa(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_gqa():
    q, k, v = _qkv(N=8, K=2)
    ref = xla_sdpa(q, k, v, causal=True)
    out = flash_sdpa(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_multiple_q_blocks():
    # S=512 with block 256 -> 2 q blocks, causal skips the upper k block
    q, k, v = _qkv(B=1, S=512, N=2, K=2)
    ref = xla_sdpa(q, k, v, causal=True)
    out = flash_sdpa(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_fits_blocks_to_seq():
    # 384 is not a multiple of the 256/512 defaults: the wrapper clamps to
    # the largest lane-aligned divisor (128) instead of raising
    q, k, v = _qkv(B=1, S=384, N=2, K=2)
    out = flash_sdpa(q, k, v, causal=True, interpret=True)
    ref = xla_sdpa(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # an explicitly requested non-divisor block still raises
    with pytest.raises(ValueError, match="must divide"):
        flash_sdpa(q, k, v, interpret=True, block_q=256)


def test_flash_gradients_match():
    """jax.grad must flow through the flash kernel (custom VJP via dense
    recompute) and match the dense-core gradients."""
    q, k, v = _qkv(B=1, S=128, N=2, K=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_gradients_gqa_groups():
    """Fused backward with G=4 query heads per kv head (the grouped dk/dv
    accumulation path)."""
    q, k, v = _qkv(B=1, S=128, N=8, K=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_gradients_noncausal():
    q, k, v = _qkv(B=1, S=64, N=2, K=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_sdpa(q, k, v, causal=False, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=False) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_segment_ids_match_dense():
    """Packed-document masking inside the kernel (fwd + grads) == dense core
    with the block-diagonal mask."""
    q, k, v = _qkv(B=2, S=128, N=4, K=4)
    seg = jnp.asarray(
        np.concatenate([np.zeros((2, 40), np.int32),
                        np.ones((2, 50), np.int32),
                        np.full((2, 38), 2, np.int32)], axis=1))
    ref = xla_sdpa(q, k, v, causal=True, segment_ids=seg)
    out = flash_sdpa(q, k, v, causal=True, interpret=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        xla_sdpa(a, b, c, causal=True, segment_ids=seg) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda a, b, c: jnp.sum(
        flash_sdpa(a, b, c, causal=True, interpret=True,
                   segment_ids=seg) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_flash_supports_segments_attrs(cpu_devices):
    """apply_attention routes packed docs by this attribute; both the plain
    kernel and the shard_map wrapper must advertise it (ADVICE r3)."""
    from jax.sharding import Mesh
    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    assert getattr(flash_sdpa, "supports_segments", False)
    mesh = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
    sdpa = make_flash_sdpa(mesh, dp_axes=("dp",), tp_axes=("tp",),
                           interpret=True)
    assert getattr(sdpa, "supports_segments", False)


def test_distributed_flash_segment_ids(cpu_devices):
    """segment_ids through the shard_map wrapper (dp-sharded operand)."""
    from jax.sharding import Mesh
    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    mesh = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
    q, k, v = _qkv(B=2, S=128, N=4, K=4)
    seg = jnp.asarray(
        np.concatenate([np.zeros((2, 64), np.int32),
                        np.ones((2, 64), np.int32)], axis=1))
    flash = make_flash_sdpa(mesh, dp_axes=("dp",), tp_axes=("tp",),
                            interpret=True)
    ref = xla_sdpa(q, k, v, causal=True, segment_ids=seg)
    out = jax.jit(lambda a, b, c: flash(a, b, c, causal=True,
                                        segment_ids=seg))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_distributed_flash_matches_dense(cpu_devices):
    """shard_map-wrapped flash (batch over dp, heads over tp) == dense, with
    gradients, on a dp2 x tp2 mesh (interpret mode)."""
    from jax.sharding import Mesh
    from hetu_galvatron_tpu.ops.pallas.flash_attention import make_flash_sdpa

    mesh = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
    q, k, v = _qkv(B=2, S=64, N=4, K=4)
    flash = make_flash_sdpa(mesh, dp_axes=("dp",), tp_axes=("tp",),
                            interpret=True)
    ref = xla_sdpa(q, k, v, causal=True)
    out = jax.jit(lambda a, b, c: flash(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        xla_sdpa(a, b, c, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_out = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
        flash(a, b, c, causal=True) ** 2), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)
