"""Ring-attention kernel correctness vs the dense XLA core (the reference has
no cp>1 test — SURVEY §4 flags that gap; this closes it)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.models.modules import xla_sdpa
from hetu_galvatron_tpu.ops.ring_attention import (
    make_ring_sdpa,
    zigzag_layout,
    zigzag_unlayout,
)

pytestmark = [pytest.mark.kernels, pytest.mark.distributed]


def _qkv(B=2, S=32, N=4, K=4, D=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (B, S, N, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, K, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, K, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("cp,kv_heads", [(2, 4), (4, 4), (2, 2), (8, 4)])
def test_ring_matches_dense(cp, kv_heads, cpu_devices):
    import math

    n_axes = int(math.log2(cp))
    mesh = Mesh(np.array(cpu_devices[:cp]).reshape((2,) * n_axes),
                tuple(f"d{i}" for i in range(n_axes)))
    q, k, v = _qkv(K=kv_heads)
    ref = xla_sdpa(q, k, v, causal=True)
    ring = make_ring_sdpa(mesh, tuple(f"d{i}" for i in range(n_axes)))
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_noncausal(cpu_devices):
    mesh = Mesh(np.array(cpu_devices[:2]), ("c",))
    q, k, v = _qkv()
    ref = xla_sdpa(q, k, v, causal=False)
    ring = make_ring_sdpa(mesh, ("c",))
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_dp_and_tp_axes(cpu_devices):
    """cp combined with dp and tp on one mesh (batch + heads sharded too)."""
    mesh = Mesh(np.array(cpu_devices[:8]).reshape(2, 2, 2),
                ("dp", "cp", "tp"))
    q, k, v = _qkv(B=2, S=16, N=4, K=4)
    ref = xla_sdpa(q, k, v, causal=True)
    ring = make_ring_sdpa(mesh, ("cp",), dp_axes=("dp",), tp_axes=("tp",))
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_zigzag_layout_roundtrip():
    x = jnp.arange(2 * 16 * 3).reshape(2, 16, 3)
    for cp in (2, 4):
        z = zigzag_layout(x, cp)
        back = zigzag_unlayout(z, cp)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
        assert not np.array_equal(np.asarray(z), np.asarray(x))


def test_ring_gradients_match(cpu_devices):
    """d(loss)/d(q,k,v) through the ring must match the dense core."""
    mesh = Mesh(np.array(cpu_devices[:2]), ("c",))
    q, k, v = _qkv(S=16)
    ring = make_ring_sdpa(mesh, ("c",))

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("cp", [2, 4])
def test_zigzag_ring_matches_dense(cp, cpu_devices):
    """Zigzag-balanced ring == dense attention (the reference's
    ZigzagRingFlashAttention layout, attention_impl.py:481-905)."""
    import math

    n_axes = int(math.log2(cp))
    mesh = Mesh(np.array(cpu_devices[:cp]).reshape((2,) * n_axes),
                tuple(f"d{i}" for i in range(n_axes)))
    q, k, v = _qkv(S=32)
    ref = xla_sdpa(q, k, v, causal=True)
    ring = make_ring_sdpa(mesh, tuple(f"d{i}" for i in range(n_axes)),
                          zigzag=True)
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_zigzag_balances_unmasked_work():
    """Zigzag positions give every rank the same number of unmasked
    (q, k) pairs, unlike the contiguous layout."""
    from hetu_galvatron_tpu.ops.ring_attention import _positions
    import jax.numpy as jnp

    cp, L = 4, 8  # local length 8 => half-blocks of 4
    total = []
    for r in range(cp):
        qpos = np.asarray(_positions(r, L, cp, True))[:, None]
        work = 0
        for src in range(cp):
            kpos = np.asarray(_positions(src, L, cp, True))[None, :]
            work += int((qpos >= kpos).sum())
        total.append(work)
    assert len(set(total)) == 1, f"unbalanced: {total}"


# ---------------------------------------------------------------------------
# flash-inside-the-ring (use_flash=True, interpret mode on CPU)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cp,zigzag", [(2, False), (2, True),
                                       (4, False), (4, True)])
def test_ring_flash_matches_dense(cp, zigzag, cpu_devices):
    """Flash-kernel-per-ring-step == dense attention, contiguous + zigzag
    (reference flash-in-ring, attention_impl.py:564-905)."""
    import math

    from hetu_galvatron_tpu.ops.ring_attention import ring_flash_blocks_fit

    n_axes = int(math.log2(cp))
    mesh = Mesh(np.array(cpu_devices[:cp]).reshape((2,) * n_axes),
                tuple(f"d{i}" for i in range(n_axes)))
    q, k, v = _qkv(S=64)
    assert ring_flash_blocks_fit(64 // cp, zigzag, 8), (
        "test shapes must take the flash path, not the dense fallback")
    ref = xla_sdpa(q, k, v, causal=True)
    ring = make_ring_sdpa(mesh, tuple(f"d{i}" for i in range(n_axes)),
                          zigzag=zigzag, use_flash=True, interpret=True)
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("cp,zigzag", [(2, False), (2, True),
                                       (4, False), (4, True)])
def test_ring_flash_gradients_match(cp, zigzag, cpu_devices):
    """d(loss)/d(q,k,v) through the flash ring (custom ring-replay VJP) ==
    the dense core's autodiff, contiguous + zigzag. cp=4 exercises the
    multi-hop dk/dv rotation-landing arithmetic (a contribution added at
    step t must survive cp - t further rotations to land home) that cp=2
    cannot distinguish from several mis-routings."""
    import math

    n_axes = int(math.log2(cp))
    mesh = Mesh(np.array(cpu_devices[:cp]).reshape((2,) * n_axes),
                tuple(f"d{i}" for i in range(n_axes)))
    q, k, v = _qkv(S=64, K=2)  # GQA: 4 q heads over 2 kv heads
    ring = make_ring_sdpa(mesh, tuple(f"d{i}" for i in range(n_axes)),
                          zigzag=zigzag, use_flash=True, interpret=True)

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_ring_flash_noncausal(cpu_devices):
    mesh = Mesh(np.array(cpu_devices[:2]), ("c",))
    q, k, v = _qkv(S=32)
    ref = xla_sdpa(q, k, v, causal=False)
    ring = make_ring_sdpa(mesh, ("c",), use_flash=True, interpret=True)
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_with_dp_and_tp_axes(cpu_devices):
    """Flash ring composed with dp/tp sharding on one mesh + grads."""
    mesh = Mesh(np.array(cpu_devices[:8]).reshape(2, 2, 2),
                ("dp", "cp", "tp"))
    q, k, v = _qkv(B=2, S=32, N=4, K=4)
    ring = make_ring_sdpa(mesh, ("cp",), dp_axes=("dp",), tp_axes=("tp",),
                          use_flash=True, interpret=True)
    ref = xla_sdpa(q, k, v, causal=True)
    out = jax.jit(lambda a, b, c: ring(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# packed-document segment masking in the ring (k-side segments rotate with
# their block; reference reset_attention_mask semantics on cp layers)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cp,zigzag", [(2, False), (4, False), (2, True)])
def test_ring_segment_ids_match_dense(cp, zigzag, cpu_devices):
    import math

    n_axes = int(math.log2(cp))
    mesh = Mesh(np.array(cpu_devices[:cp]).reshape((2,) * n_axes),
                tuple(f"d{i}" for i in range(n_axes)))
    q, k, v = _qkv(S=32)
    # three documents of uneven length packed per row
    seg = jnp.asarray(np.stack([np.repeat([0, 1, 2], [10, 14, 8]),
                                np.repeat([0, 1, 2], [4, 20, 8])]))
    ref = xla_sdpa(q, k, v, causal=True, segment_ids=seg)
    ring = make_ring_sdpa(mesh, tuple(f"d{i}" for i in range(n_axes)),
                          zigzag=zigzag)
    assert ring.supports_segments
    out = jax.jit(lambda a, b, c, s: ring(a, b, c, causal=True,
                                          segment_ids=s))(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_segment_gradients_match(cpu_devices):
    mesh = Mesh(np.array(cpu_devices[:2]), ("c",))
    q, k, v = _qkv(S=16, K=2)
    seg = jnp.asarray(np.stack([np.repeat([0, 1], [6, 10]),
                                np.repeat([0, 1], [12, 4])]))
    ring = make_ring_sdpa(mesh, ("c",))

    def loss_ref(q, k, v):
        return jnp.sum(xla_sdpa(q, k, v, causal=True,
                                segment_ids=seg) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v, causal=True, segment_ids=seg) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_ring_flash_falls_back_to_dense_for_segments(cpu_devices):
    """use_flash=True with segment_ids routes through the dense fold (the
    flash-in-ring kernels need equal-length segment operands) and still
    matches the dense core."""
    mesh = Mesh(np.array(cpu_devices[:2]), ("c",))
    q, k, v = _qkv(S=64)
    seg = jnp.asarray(np.stack([np.repeat([0, 1], [20, 44]),
                                np.repeat([0, 1], [40, 24])]))
    ring = make_ring_sdpa(mesh, ("c",), use_flash=True, interpret=True)
    ref = xla_sdpa(q, k, v, causal=True, segment_ids=seg)
    out = ring(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
