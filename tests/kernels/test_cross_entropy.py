"""Pallas fused cross-entropy vs the XLA reference path (interpret mode on
CPU; tools/tpu_flash_check.py exercises the Mosaic compile on hardware)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.models.modules import cross_entropy_loss
from hetu_galvatron_tpu.ops.pallas.cross_entropy import (
    fit_vocab_block,
    fused_ce_nll,
)

pytestmark = pytest.mark.kernels


def _ref_nll(logits, labels, z_loss=0.0):
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - gold
    return nll + z_loss * jnp.square(lse) if z_loss else nll


def _data(B=2, S=64, V=512, seed=0):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(B, S, V) * 3, jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
    return logits, labels


@pytest.mark.parametrize("z_loss", [0.0, 1e-4])
def test_fused_nll_matches_reference(z_loss):
    logits, labels = _data()
    nll = fused_ce_nll(logits, labels, z_loss=z_loss, interpret=True)
    np.testing.assert_allclose(np.asarray(nll),
                               np.asarray(_ref_nll(logits, labels, z_loss)),
                               rtol=1e-5, atol=1e-5)


def test_fused_nll_bf16_multi_tile():
    # several vocab tiles + bf16 inputs (the production dtype)
    logits, labels = _data(B=1, S=128, V=1024)
    logits = logits.astype(jnp.bfloat16)
    nll = fused_ce_nll(logits, labels, interpret=True)
    np.testing.assert_allclose(np.asarray(nll),
                               np.asarray(_ref_nll(logits, labels)),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("z_loss", [0.0, 1e-4])
def test_fused_gradients_match(z_loss):
    logits, labels = _data(B=1, S=32, V=256)

    def loss_fused(x):
        return jnp.mean(fused_ce_nll(x, labels, z_loss=z_loss,
                                     interpret=True))

    def loss_ref(x):
        return jnp.mean(_ref_nll(x, labels, z_loss))

    g_fused = jax.grad(loss_fused)(logits)
    g_ref = jax.grad(loss_ref)(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_untileable_vocab_returns_none():
    logits, labels = _data(V=500)  # 500 % 128 != 0
    assert fused_ce_nll(logits, labels, interpret=True) is None
    assert fit_vocab_block(500) == 0
    assert fit_vocab_block(50304) == 128
    assert fit_vocab_block(32000) == 256


def _mk_sharding(dp=(), tp=(), ulysses=False):
    from hetu_galvatron_tpu.runtime.mesh import LayerSharding

    return LayerSharding(dp_axes=tuple(dp), cp_axes=(), tp_axes=tuple(tp),
                         ulysses=ulysses)


@pytest.mark.distributed
def test_vocab_parallel_ce_matches_single_device(cpu_devices):
    """vtp4 x dp2: fused CE under shard_map (pmax/psum logsumexp merge) ==
    the plain XLA nll, values and gradients."""
    from jax.sharding import Mesh

    from hetu_galvatron_tpu.ops.pallas.cross_entropy import (
        make_vocab_parallel_ce,
    )

    mesh = Mesh(np.array(cpu_devices).reshape(2, 4), ("dp", "tp"))
    logits, labels = _data(B=2, S=64, V=512)
    nll_fn = make_vocab_parallel_ce(mesh, _mk_sharding(dp=("dp",),
                                                       tp=("tp",)),
                                    interpret=True)
    nll = nll_fn(logits, labels)
    np.testing.assert_allclose(np.asarray(nll),
                               np.asarray(_ref_nll(logits, labels)),
                               rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda x: jnp.mean(nll_fn(x, labels)))(logits)
    g_ref = jax.grad(lambda x: jnp.mean(_ref_nll(x, labels)))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.distributed
def test_vocab_parallel_ce_multi_axis_and_vsp(cpu_devices):
    from jax.sharding import Mesh

    from hetu_galvatron_tpu.ops.pallas.cross_entropy import (
        make_vocab_parallel_ce,
    )

    logits, labels = _data(B=2, S=64, V=1024)
    # vocab over two mesh axes: exercises the flattened axis-index offset
    mesh = Mesh(np.array(cpu_devices).reshape(2, 2, 2), ("dp", "t1", "t2"))
    nll_fn = make_vocab_parallel_ce(
        mesh, _mk_sharding(dp=("dp",), tp=("t1", "t2")), interpret=True)
    np.testing.assert_allclose(np.asarray(nll_fn(logits, labels)),
                               np.asarray(_ref_nll(logits, labels)),
                               rtol=1e-5, atol=1e-5)
    # vsp (ulysses): sequence sharded, head replicated — no collective leg
    mesh2 = Mesh(np.array(cpu_devices).reshape(2, 4), ("dp", "tp"))
    nll_fn2 = make_vocab_parallel_ce(
        mesh2, _mk_sharding(dp=("dp",), tp=("tp",), ulysses=True),
        interpret=True)
    np.testing.assert_allclose(np.asarray(nll_fn2(logits, labels)),
                               np.asarray(_ref_nll(logits, labels)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.distributed
def test_spmd_train_step_fused_ce_matches(cpu_devices):
    """End-to-end: the distributed train step with use_fused_ce=True (tp2-
    sharded 512-wide head, so the kernel really runs: V_local=256) produces
    the single-device reference loss."""
    from hetu_galvatron_tpu.core.args_schema import (
        CoreArgs,
        ModelArgs,
        TrainArgs,
    )
    from hetu_galvatron_tpu.models.builder import (
        causal_lm_loss,
        init_causal_lm,
    )
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    cfg = ModelArgs(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=512, max_position_embeddings=64, seq_length=16,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128,
        use_fused_ce=True)
    train = TrainArgs(lr=1e-2, lr_decay_style="constant", lr_warmup_iters=0)
    args = CoreArgs(model=cfg.model_dump(), train=train.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.global_train_batch_size = 8

    params, axes = init_causal_lm(jax.random.key(0), cfg)
    data = np.random.RandomState(0).randint(0, 512, (8, cfg.seq_length + 1))
    batch = jax.tree.map(jnp.asarray, make_batch(data))
    ref = float(causal_lm_loss(params, batch, cfg,
                               compute_dtype=jnp.float32, fused_ce=False))

    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, hpc.pp_deg, devices=cpu_devices)
    tx = make_optimizer(train)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        cfg, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.float32, donate=False)
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    _, _, metrics = step(sp, opt, jax.device_put(batch, batch_shd))
    assert abs(float(metrics["loss"]) - ref) < 2e-5


def test_cross_entropy_loss_fused_flag():
    """The public loss with fused=True (masked mean) == XLA path."""
    logits, labels = _data(B=2, S=64, V=512)
    mask = jnp.asarray(
        np.random.RandomState(1).rand(2, 64) > 0.3, jnp.float32)
    a = cross_entropy_loss(logits, labels, mask)
    b = cross_entropy_loss(logits, labels, mask, fused=True)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-5)
    ga = jax.grad(lambda x: cross_entropy_loss(x, labels, mask))(logits)
    gb = jax.grad(lambda x: cross_entropy_loss(x, labels, mask,
                                               fused=True))(logits)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=1e-5, atol=1e-6)
