"""Pallas fused cross-entropy vs the XLA reference path (interpret mode on
CPU; tools/tpu_flash_check.py exercises the Mosaic compile on hardware)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.models.modules import cross_entropy_loss
from hetu_galvatron_tpu.ops.pallas.cross_entropy import (
    fit_vocab_block,
    fused_ce_nll,
)

pytestmark = pytest.mark.kernels


def _ref_nll(logits, labels, z_loss=0.0):
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - gold
    return nll + z_loss * jnp.square(lse) if z_loss else nll


def _data(B=2, S=64, V=512, seed=0):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(B, S, V) * 3, jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
    return logits, labels


@pytest.mark.parametrize("z_loss", [0.0, 1e-4])
def test_fused_nll_matches_reference(z_loss):
    logits, labels = _data()
    nll = fused_ce_nll(logits, labels, z_loss=z_loss, interpret=True)
    np.testing.assert_allclose(np.asarray(nll),
                               np.asarray(_ref_nll(logits, labels, z_loss)),
                               rtol=1e-5, atol=1e-5)


def test_fused_nll_bf16_multi_tile():
    # several vocab tiles + bf16 inputs (the production dtype)
    logits, labels = _data(B=1, S=128, V=1024)
    logits = logits.astype(jnp.bfloat16)
    nll = fused_ce_nll(logits, labels, interpret=True)
    np.testing.assert_allclose(np.asarray(nll),
                               np.asarray(_ref_nll(logits, labels)),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("z_loss", [0.0, 1e-4])
def test_fused_gradients_match(z_loss):
    logits, labels = _data(B=1, S=32, V=256)

    def loss_fused(x):
        return jnp.mean(fused_ce_nll(x, labels, z_loss=z_loss,
                                     interpret=True))

    def loss_ref(x):
        return jnp.mean(_ref_nll(x, labels, z_loss))

    g_fused = jax.grad(loss_fused)(logits)
    g_ref = jax.grad(loss_ref)(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_untileable_vocab_returns_none():
    logits, labels = _data(V=500)  # 500 % 128 != 0
    assert fused_ce_nll(logits, labels, interpret=True) is None
    assert fit_vocab_block(500) == 0
    assert fit_vocab_block(50304) == 128
    assert fit_vocab_block(32000) == 256


def test_cross_entropy_loss_fused_flag():
    """The public loss with fused=True (masked mean) == XLA path."""
    logits, labels = _data(B=2, S=64, V=512)
    mask = jnp.asarray(
        np.random.RandomState(1).rand(2, 64) > 0.3, jnp.float32)
    a = cross_entropy_loss(logits, labels, mask)
    b = cross_entropy_loss(logits, labels, mask, fused=True)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-5)
    ga = jax.grad(lambda x: cross_entropy_loss(x, labels, mask))(logits)
    gb = jax.grad(lambda x: cross_entropy_loss(x, labels, mask,
                                               fused=True))(logits)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=1e-5, atol=1e-6)
