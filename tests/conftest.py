"""Test harness: force a virtual 8-device CPU platform before JAX initializes.

The reference needs >=8 real GPUs + NCCL for its distributed tier
(tests/conftest.py:81-185 spawns ranked subprocesses). On JAX we instead run
all "distributed" tests in-process on a virtual CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4), so the full parallel
test matrix runs on CI with no accelerator.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon TPU plugin's sitecustomize register() rewrites jax_platforms to
# "axon,cpu" at import, overriding the env var — force it back so tests never
# initialize (or hang on) the tunneled TPU backend.
jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# Fast/slow tiers. ``-m "not slow"`` is the CI-default quick tier (~3 min);
# the full suite (~20 min) runs everything. Tests land here when a
# ``--durations`` profile shows them >=7s on the reference CI shape (the
# parity/trajectory tests dominated by 8-device jit compiles); marking is
# centralized in this hook so test files stay unannotated.
_SLOW_TESTS = {
    # moe / t5 / bert parity
    "test_expert_parallel_matches_single_device",
    "test_moe_pipeline_matches_single_device",
    "test_moe_model_trains",
    "test_moe_mlp_routing_and_aux",
    "test_dropless_grads_flow",
    "test_t5_tp2_matches_single_device",
    "test_t5_pipeline_matches_single_device",
    "test_t5_interleaved_virtual_stages",
    "test_t5_heterogeneous_combined_plan",
    "test_t5_ring_cp_matches_xla",
    "test_t5_spmd_generate_matches_single_device",
    "test_t5_train_dist_cli",
    "test_t5_search_then_train_combined_stack",
    "test_init_structure_and_loss",
    "test_bert_mlm_training_step_tp8",
    "test_bert_mlm_loss_trajectory_matches_hf",
    "test_bidirectional_attention",
    # gpt model correctness / accuracy alignment
    "test_remat_same_loss",
    "test_forward_shapes_and_loss",
    "test_param_count_gpt2_small",
    "test_gpt2_loss_trajectory_matches_hf",
    # hierarchical dp reduction: the engine parity drills compile two full
    # engines each; the single-device zero3 reference adds a third build
    "test_hier_compiled_engine_parity",
    "test_hier_host_engine_parity",
    "test_hier_zero3_matches_single_device_where_flat_drifts",
    # spmd / pipeline parity
    "test_no_involuntary_full_rematerialization",
    "test_strategy_matches_single_device",
    "test_mixed_per_layer_strategies",
    "test_multi_step_trajectory_matches_single_device",
    "test_pipeline_matches_single_device",
    "test_pipeline_tied_embeddings",
    "test_interleaved_virtual_stages_match_single_device",
    "test_interleaved_tied_embeddings",
    "test_uneven_pp_division",
    # kernels repaired in round 10 (the jax.shard_map / CompilerParams pin
    # fixes): they failed at the seed, so the fast tier never counted them
    # — the heavy ones run in the full suite to keep tier-1 inside its
    # budget; cheap smokes (one flash, one ring, one fused-CE) stay fast
    "test_spmd_train_step_fused_ce_matches",
    "test_vocab_parallel_ce_matches_single_device",
    "test_vocab_parallel_ce_multi_axis_and_vsp",
    "test_ring_flash_gradients_match",
    "test_ring_flash_matches_dense",
    "test_ring_flash_with_dp_and_tp_axes",
    "test_ring_flash_noncausal",
    "test_ring_flash_falls_back_to_dense_for_segments",
    "test_ring_segment_gradients_match",
    "test_ring_segment_ids_match_dense",
    "test_flash_dropout_gradients_match_masked_dense",
    "test_flash_dropout_matches_masked_dense",
    "test_flash_segment_ids_match_dense",
    "test_distributed_flash_segment_ids",
    # kernels (8-device shard_map compiles)
    "test_ulysses_gradients",
    "test_ulysses_matches_xla_core",
    "test_ulysses_gqa_groups",
    "test_ulysses_kv_heads_below_sp_replicate",
    "test_ulysses_truly_indivisible_falls_back",
    "test_ring_gradients_match",
    "test_ring_with_dp_and_tp_axes",
    "test_ring_matches_dense",
    "test_zigzag_ring_matches_dense",
    "test_distributed_flash_matches_dense",
    "test_flash_gradients_match",
    "test_flash_gradients_gqa_groups",
    "test_flash_gradients_noncausal",
    # CLI / e2e / profilers / checkpoint
    "test_search_then_train_the_searched_plan",
    "test_train_dist_cli_pipeline_compiled",
    "test_train_dist_cli_compiled_falls_back",
    # compiled-pipeline secondary parity legs (the tier-1 acceptance drill
    # test_compiled_matches_host_engine_three_steps + recompile pinning
    # stay fast-tier)
    "test_compiled_untied_and_uniform_dp",
    "test_compiled_dropout_replays_host_masks",
    "test_compiled_ramp_caches_one_program_per_chunk_count",
    "test_train_dist_rampup_cli",
    "test_train_dist_rampup_pipeline_cli",
    "test_train_dist_cli_pipeline",
    # tp-overlap secondary legs (the acceptance drill
    # test_trajectory_drill_searched_tp2_dp2_plan + recompile pinning stay
    # fast-tier)
    "test_train_dist_cli_tp_overlap",
    "test_tp_overlap_cli_fallback_reasons",
    "test_host_pipeline_engine_tp_overlap_parity",
    "test_train_dist_cli_checkpoint_resume",
    "test_resume_continues_training",
    "test_hf_gpt2_roundtrip_and_forward",
    "test_model_profiler_memory_schema",
    "test_sp_time_profile_feeds_latency_tables",
    "test_hardware_profiler_schemas",
    "test_numpy_fallback_matches_cpp",
    "test_microbatch_accumulation_matches_full_batch",
    "test_microbatch_nonuniform_loss_mask_matches",
    # shared-prefix serving acceptance drill (8-device mesh, two engine
    # warmups x two variants) and secondary prefix/spec legs — the
    # single-device hit-parity, spec-losslessness, and eviction tests
    # stay fast-tier
    "test_shared_prefix_drill_mesh8",
    # sharding-flow heavy leg: compiles the whole fused 1F1B program to
    # walk its partitioned HLO (the jaxpr-level byte census stays fast)
    "test_hlo_walk_full_compiled_step",
    # draft-model serve smoke trains a real draft checkpoint first (the
    # fast tier keeps the draft_model= usage-error path)
    "test_serve_cli_draft_model_smoke",
    "test_serve_bench_ab_legs_importable",
    "test_serve_bench_shared_prefix_trace",
    "test_prefix_engine_defrag_mid_serving",
    "test_suffix_bucket_overshoot_at_table_capacity",
    "test_spec_eos_and_budget_mid_window",
    "test_spec_sampled_lanes_match_plain_engine",
    # elastic topology-change drills (all train real checkpoints): the
    # N -> N/2 SIGTERM-kill resume through the real search, the
    # degree-adapt replay-parity leg, the cross-engine reshard exactness
    # matrix, and the load-test-across-weight-swap drill. Fast tier keeps
    # the reshard layout units, the exit-17 gate, and the quiet-engine
    # swap contract.
    "test_elastic_drill_kill8_resume4_searched",
    "test_elastic_drill_kill4_resume8_scale_up_searched",
    "test_elastic_resume_degree_adapt_replays_exactly",
    "test_reshard_exact_across_engines",
    "test_weight_swap_load_drill",
    "test_swap_invalidates_prefix_cache",
    # chaos matrix: each case spawns real supervised train_dist children
    # through cli/supervise.py and compares bit-exact resumed
    # trajectories against a shared baseline run. Fast tier keeps the
    # in-process crash smoke (test_chaos_crash_smoke_resumes_bit_exact)
    # and the synthetic-children harness smoke.
    "test_chaos_matrix_crash",
    "test_chaos_matrix_preempt",
    "test_chaos_matrix_kill_mid_save",
    "test_chaos_matrix_corrupt_meta",
    "test_chaos_matrix_transient_io",
    "test_chaos_matrix_hung_save",
    "test_chaos_matrix_budget",
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        fn = getattr(item, "function", None)
        if fn is not None and fn.__name__ in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
            matched.add(fn.__name__)
    # self-maintenance: a renamed/deleted test must not silently fall out of
    # the slow tier (only checked on full-collection runs, where every name
    # should resolve)
    stale = _SLOW_TESTS - matched
    if stale and len(items) > len(_SLOW_TESTS):
        import warnings

        warnings.warn(f"_SLOW_TESTS entries no longer collected: "
                      f"{sorted(stale)}", stacklevel=1)


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(scope="session")
def mesh8(cpu_devices):
    """A flat 8-device mesh most parallel tests start from."""
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(cpu_devices).reshape(8), ("devices",))
