"""Test harness: force a virtual 8-device CPU platform before JAX initializes.

The reference needs >=8 real GPUs + NCCL for its distributed tier
(tests/conftest.py:81-185 spawns ranked subprocesses). On JAX we instead run
all "distributed" tests in-process on a virtual CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4), so the full parallel
test matrix runs on CI with no accelerator.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon TPU plugin's sitecustomize register() rewrites jax_platforms to
# "axon,cpu" at import, overriding the env var — force it back so tests never
# initialize (or hang on) the tunneled TPU backend.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(scope="session")
def mesh8(cpu_devices):
    """A flat 8-device mesh most parallel tests start from."""
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(cpu_devices).reshape(8), ("devices",))
