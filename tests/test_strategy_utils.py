"""Strategy spine tests (parity with reference tests for strategy_utils:
dataclass invariants + strategy list <-> JSON round trip)."""

import pytest

from hetu_galvatron_tpu.utils.strategy import (
    DPType,
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    config2strategy,
    form_strategy,
    print_strategies,
    strategy_list2config,
)

pytestmark = pytest.mark.utils


def test_validate_world_size():
    s = LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)
    s.validate(8)
    with pytest.raises(ValueError):
        s.validate(16)
    with pytest.raises(ValueError):
        LayerStrategy(tp_size=3, dp_size=1).validate(3)


def test_sp_cp_exclusive():
    with pytest.raises(ValueError):
        LayerStrategy(tp_size=2, cp_size=2, sp=True, dp_size=1).validate(4)


def test_round_trip():
    layers = [
        LayerStrategy(pp_deg=2, tp_size=2, dp_size=2, dp_type=DPType.ZERO3,
                      checkpoint=True),
        LayerStrategy(pp_deg=2, tp_size=4, dp_size=1, dp_type=DPType.ZERO2),
        LayerStrategy(pp_deg=2, tp_size=1, dp_size=2, cp_size=2, dp_type=DPType.ZERO2),
        LayerStrategy(pp_deg=2, tp_size=2, dp_size=2, sp=True, dp_type=DPType.ZERO2),
    ]
    vocab = EmbeddingLMHeadStrategy(vtp=2, vsp=True, embed_sdp=True)
    cfg = strategy_list2config(
        layers, global_bsz=16, chunks=4, default_dp_type="zero2", vocab=vocab
    )
    assert cfg["pp_deg"] == 2
    assert cfg["tp_sizes_enc"] == "2,4,1,2"
    assert cfg["dp_types_enc"] == "1,0,0,0"
    assert cfg["use_sp"] == "0,0,0,1"
    assert cfg["cp_sizes_enc"] == "1,1,2,1"
    assert cfg["checkpoint"] == "1,0,0,0"
    assert cfg["vtp"] == 2 and cfg["vsp"] == 1 and cfg["embed_sdp"] == 1

    back, vback, extras = config2strategy(cfg, world_size=8)
    assert [s.key() for s in back] == [s.key() for s in layers]
    assert vback == vocab
    assert extras["global_bsz"] == 16 and extras["chunks"] == 4
    assert extras["predicted_layer_compute_ms"] is None  # not embedded


def test_predicted_layer_compute_ms_roundtrip():
    """Searched plans embed the cost model's per-layer compute prediction;
    it survives the interchange round trip, a wrong-length vector raises at
    write time and is dropped (not mis-attributed) at read time."""
    layers = [LayerStrategy(pp_deg=1, tp_size=2, dp_size=2)
              for _ in range(3)]
    pred = [0.25, 0.5, 0.125]
    cfg = strategy_list2config(
        layers, global_bsz=8, chunks=1, predicted_layer_compute_ms=pred)
    assert cfg["predicted_layer_compute_ms"] == pred
    _, _, extras = config2strategy(cfg, world_size=4)
    assert extras["predicted_layer_compute_ms"] == pred

    with pytest.raises(ValueError, match="predicted_layer_compute_ms"):
        strategy_list2config(
            layers, global_bsz=8, chunks=1,
            predicted_layer_compute_ms=[1.0])

    cfg["predicted_layer_compute_ms"] = [1.0, 2.0]  # hand-edited plan drift
    _, _, extras = config2strategy(cfg, world_size=4)
    assert extras["predicted_layer_compute_ms"] is None


def test_reference_format_json_parses():
    # A reference-shaped config (BASELINE.md row: searched llama2-7b 8-dev plan)
    cfg = {
        "pp_deg": 1,
        "tp_sizes_enc": ",".join(["1"] * 32),
        "tp_consecutive_flags": ",".join(["1"] * 32),
        "dp_types_enc": ",".join(["1"] * 32),
        "use_sp": ",".join(["0"] * 32),
        "checkpoint": ",".join(["1"] * 20 + ["0"] * 12),
        "global_bsz": 16,
        "chunks": 1,
        "pp_division": "32",
        "pipeline_type": "pipedream_flush",
        "default_dp_type": "zero2",
        "vtp": 2,
        "vsp": 1,
        "embed_sdp": 1,
    }
    layers, vocab, extras = config2strategy(cfg, world_size=8)
    assert len(layers) == 32
    assert all(s.dp_type == DPType.ZERO3 for s in layers)  # dp_types_enc==1
    assert sum(s.checkpoint for s in layers) == 20
    assert layers[0].dp_size == 8
    assert vocab.vtp == 2 and vocab.vsp
    assert extras["pipeline_type"] == "pipedream_flush"


def test_pretty_print():
    s = LayerStrategy(pp_deg=2, tp_size=2, dp_size=2, dp_type=DPType.ZERO3,
                      checkpoint=True)
    assert "tp2" in form_strategy(s) and "ckpt" in form_strategy(s)
    txt = print_strategies([s, s, s.with_checkpoint(False)])
    assert "*2" in txt


def test_unrepresentable_dp_type_raises():
    # ZERO2 layer under default ddp cannot be carried by the 1-bit encoding
    layers = [LayerStrategy(tp_size=1, dp_size=8, dp_type=DPType.ZERO2)]
    with pytest.raises(ValueError, match="not representable"):
        strategy_list2config(layers, global_bsz=8, chunks=1, default_dp_type="ddp")


def test_default_pp_division_remainder():
    from hetu_galvatron_tpu.utils.strategy import default_pp_division

    assert default_pp_division(30, 4) == [7, 7, 7, 9]
    assert default_pp_division(32, 4) == [8, 8, 8, 8]
    assert default_pp_division(5, 1) == [5]
    layers = [LayerStrategy(pp_deg=4, tp_size=1, dp_size=2) for _ in range(30)]
    cfg = strategy_list2config(layers, global_bsz=8, chunks=1)
    assert sum(int(x) for x in cfg["pp_division"].split(",")) == 30


def test_tp_of_ep_key_roundtrip():
    layers = [LayerStrategy(tp_size=2, dp_size=4, ep_size=4, etp_size=2)]
    cfg = strategy_list2config(layers, global_bsz=8, chunks=1)
    assert "tp_of_ep_sizes_enc" in cfg and "etp_sizes_enc" not in cfg
    back, _, _ = config2strategy(cfg, world_size=8)
    assert back[0].etp_size == 2
    # legacy spelling still readable
    legacy = dict(cfg)
    legacy["etp_sizes_enc"] = legacy.pop("tp_of_ep_sizes_enc")
    back2, _, _ = config2strategy(legacy, world_size=8)
    assert back2[0].etp_size == 2


def test_config2strategy_validates_world_size():
    cfg = {
        "pp_deg": 1,
        "tp_sizes_enc": "16",  # tp 16 > world 8
        "global_bsz": 8,
        "chunks": 1,
    }
    with pytest.raises(ValueError):
        config2strategy(cfg, world_size=8)
