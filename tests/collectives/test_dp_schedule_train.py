"""The synthesized-collective runtime acceptance drills: a searched
pure-dp plan trains with ``dp_schedule`` backends through the real SPMD
step (ops/hier_reduce.py executing collectives/emit.py programs), and

* the bit-parity contract holds END TO END: 3-step trajectories of the
  emitted ring / halving-doubling schedules are bit-identical to the
  hand-built reference backends — losses AND every parameter leaf,
  ``np.array_equal``, zero tolerance;
* the traced step's dp-schedule ppermute counts AND megabytes match the
  plan arithmetic (``plan_collective_counts`` / ``plan_collective_
  bytes``) exactly, for emitted and hand-built backends alike;
* ineligible requests fall back with a reason instead of mis-lowering
  (``analysis/eligibility.py``)."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.analysis.eligibility import (
    dp_schedule_unsupported_reason,
)
from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step, shard_params
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.mesh import build_mesh
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
from hetu_galvatron_tpu.utils.strategy import (
    DPType,
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    strategy_list2config,
)

pytestmark = [pytest.mark.collectives, pytest.mark.distributed]

CFG = ModelArgs(
    hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False, use_flash_attn=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=128,
)
TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _plan_json(tmp_path, dp=8):
    layers = [LayerStrategy(pp_deg=1, tp_size=1, dp_size=dp, cp_size=1,
                            dp_type=DPType.from_name("ddp"))
              for _ in range(CFG.num_hidden_layers)]
    cfg = strategy_list2config(
        layers, global_bsz=16, chunks=2, pipeline_type="pipedream_flush",
        default_dp_type="ddp",
        vocab=EmbeddingLMHeadStrategy(vtp=1),
        pp_division=[CFG.num_hidden_layers])
    path = tmp_path / "galvatron_config_dp_sched.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def _hpc_mesh(tmp_path, cpu_devices, dcn_slices=2):
    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _plan_json(tmp_path)
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices[:8],
                      dcn_slices=dcn_slices)
    return hpc, mesh


def _trajectory(tmp_path, cpu_devices, dp_schedule, n=3):
    hpc, mesh = _hpc_mesh(tmp_path, cpu_devices)
    tx = make_optimizer(TRAIN)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        CFG, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False, hier_dp=True, dcn_slices=2,
        dp_schedule=dp_schedule)
    sp = shard_params(params, pspecs, mesh)
    so = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    data = np.random.RandomState(0).randint(0, 128,
                                            (16, CFG.seq_length + 1))
    b = jax.device_put(jax.tree.map(jnp.asarray, make_batch(data)),
                       batch_shd)
    losses = []
    for _ in range(n):
        sp, so, m = step(sp, so, b)
        losses.append(np.asarray(m["loss"]))
    return sp, losses


@pytest.mark.parametrize("emitted,handbuilt",
                         [("ring", "ring_handbuilt"),
                          ("tree_hd", "tree_handbuilt")])
def test_trajectory_bit_identical_to_handbuilt(tmp_path, cpu_devices,
                                               emitted, handbuilt):
    """The acceptance pin: 3 training steps through the emitted schedule
    vs the hand-built reference body — bit-identical losses and params
    (same hop order, same IEEE add association, so not one ulp apart)."""
    sp_e, l_e = _trajectory(tmp_path, cpu_devices, emitted)
    sp_h, l_h = _trajectory(tmp_path, cpu_devices, handbuilt)
    for a, b in zip(l_e, l_h):
        assert np.array_equal(a, b), (emitted, l_e, l_h)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sp_e),
            jax.tree_util.tree_leaves_with_path(sp_h)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            jax.tree_util.keystr(pa)


@pytest.mark.parametrize("backend", ["ring", "tree_hd", "torus2d",
                                     "hier_rings", "ring_handbuilt",
                                     "tree_handbuilt"])
def test_census_and_flow_exact_per_backend(tmp_path, cpu_devices, backend):
    """Zero-tolerance observability: the traced step's dp_sched ppermute
    COUNT and MEGABYTES equal the plan arithmetic exactly, for every
    backend — the hand-built ones predict through their emitted twin."""
    from hetu_galvatron_tpu.analysis.census import (
        census_spmd_step,
        check_census,
    )
    from hetu_galvatron_tpu.analysis.sharding_flow import (
        check_flow,
        flow_spmd_step,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_bytes,
        plan_collective_counts,
    )

    hpc, mesh = _hpc_mesh(tmp_path, cpu_devices)
    census = census_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                              hier_dp=True, dcn_slices=2,
                              dp_schedule=backend)
    pred = plan_collective_counts(hpc, CFG, tp_overlap=False,
                                  hier_dp=True, hier_cross=2,
                                  dp_schedule=backend)
    assert set(pred) == {"ppermute_dp"} and pred["ppermute_dp"] > 0
    assert check_census(census, pred,
                        program=f"spmd_dp_sched_{backend}") == []

    pf = flow_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                        hier_dp=True, dcn_slices=2, dp_schedule=backend,
                        gather_mb=1e-6)
    pred_mb = plan_collective_bytes(hpc, CFG, tp_overlap=False,
                                    hier_dp=True, hier_cross=2,
                                    dp_schedule=backend)
    assert pred_mb.get("ppermute_dp", 0) > 0
    assert check_flow(pf.flow, pred_mb,
                      program=f"spmd_dp_sched_{backend}") == []


def test_handbuilt_predicts_through_emitted_twin():
    """ring_handbuilt and ring share one count/byte prediction — the
    reference bodies are pinned identical to the emitted programs."""
    from hetu_galvatron_tpu.observability.telemetry import (
        _dp_schedule_from_plan,
    )

    for pair in (("ring", "ring_handbuilt"),
                 ("tree_hd", "tree_handbuilt")):
        a = _dp_schedule_from_plan(pair[0], 8, 2, 0.0)
        b = _dp_schedule_from_plan(pair[1], 8, 2, 0.0)
        assert a.name == b.name and a.n_exchanges == b.n_exchanges


# ---------------------------------------------------------------------------
# eligibility gating
# ---------------------------------------------------------------------------


def test_eligibility_reasons():
    ok = dp_schedule_unsupported_reason
    assert ok("ring", 8) is None
    assert ok("tree_hd", 8) is None
    assert ok("hier_rings", 8, cross=2) is None
    # trees need a power-of-two group
    assert ok("tree_hd", 6) is not None
    # hierarchical rings need a real 2-level split
    assert ok("hier_rings", 8, cross=1) is not None
    # bucketed plans keep the hand-implemented pipelined path: the
    # emitted programs are monolithic
    assert ok("ring", 8, bucket_mb=4.0) is not None
    # unknown family names are rejected, not silently ignored
    assert ok("fancy_new_alg", 8) is not None


def test_unsupported_schedule_raises_in_prediction():
    from hetu_galvatron_tpu.observability.telemetry import (
        _dp_schedule_from_plan,
    )

    with pytest.raises(ValueError, match="dp schedule unsupported"):
        _dp_schedule_from_plan("tree_hd", 6, 1, 0.0)
