"""Emitter lowering parity (collectives/emit.py): the full-manual
shard_map programs emitted from verified schedules must compute the
group sum — and for the ring / halving-doubling families, must match
the canonical hand-built bodies (collectives/reference.py)
BIT-FOR-BIT: same hop order, same add association. This standalone-body
half of the bit-parity contract runs every family; the end-to-end
3-step training trajectory rides tests/collectives/
test_dp_schedule_train.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from hetu_galvatron_tpu.collectives.emit import emit_allreduce_body
from hetu_galvatron_tpu.collectives.reference import (
    handbuilt_allreduce_body,
)
from hetu_galvatron_tpu.collectives.synthesize import (
    SCOPE_PREFIX,
    synthesize_dp_schedule,
    synthesize_space,
)
from hetu_galvatron_tpu.collectives.verify import verify

pytestmark = [pytest.mark.collectives, pytest.mark.distributed]


def _run_body(body, n, cpu_devices, x):
    mesh = Mesh(np.asarray(cpu_devices[:n]), ("dp",))
    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"), check_rep=False))
    return np.asarray(fn(x)).reshape(n, -1)


def _payload(n, local=64):
    return jnp.asarray(np.random.RandomState(7)
                       .standard_normal(n * local), jnp.float32)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("fam,ref", [("ring", "ring"),
                                     ("tree_hd", "tree")])
def test_emitted_matches_handbuilt_bitwise(cpu_devices, n, fam, ref):
    """The bit-parity pin: the emitted program IS the hand-built body,
    hop for hop — byte-equal outputs, not allclose."""
    sched = verify(synthesize_dp_schedule(fam, n, 1))
    x = _payload(n)
    emitted = _run_body(emit_allreduce_body(sched, "dp",
                                            verify_first=False),
                        n, cpu_devices, x)
    hand = _run_body(handbuilt_allreduce_body(ref, n, "dp"),
                     n, cpu_devices, x)
    assert np.array_equal(emitted, hand)


@pytest.mark.parametrize("fam", ["ring", "tree_hd", "tree_bcast",
                                 "torus2d", "hier_rings"])
def test_every_family_computes_the_group_sum(cpu_devices, fam):
    """Every synthesized family is a correct all-reduce: each rank ends
    holding the group sum (per-family association trees differ, so this
    is allclose vs the f64 reference, not bitwise)."""
    n, cross = 8, (2 if fam == "hier_rings" else 1)
    sched = verify(synthesize_dp_schedule(fam, n, cross))
    x = _payload(n)
    out = _run_body(emit_allreduce_body(sched, "dp", verify_first=False),
                    n, cpu_devices, x)
    want = np.asarray(x, np.float64).reshape(n, -1).sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-5)


def test_emitted_scopes_carry_the_census_marker():
    """Every exchange scope starts with the dp_sched marker the census
    and flow passes substring-match on."""
    for name, sched in synthesize_space(8, cross=2).items():
        for st in sched.steps:
            assert st.scope.startswith(SCOPE_PREFIX), (name, st.scope)


def test_emit_refuses_a_broken_schedule():
    """verify_first=True (the default) re-verifies at emit time, so a
    schedule mutated AFTER its verify cannot reach hardware."""
    import dataclasses

    from hetu_galvatron_tpu.collectives.ir import ScheduleError

    sched = synthesize_dp_schedule("ring", 4, 1)
    broken = dataclasses.replace(
        sched, steps=(dataclasses.replace(
            sched.steps[0], xfers=sched.steps[0].xfers[1:]),)
        + sched.steps[1:])
    with pytest.raises(ScheduleError):
        emit_allreduce_body(broken, "dp")


def test_emitted_requires_padding_and_padded_prefix_is_exact(cpu_devices):
    """The emitted body refuses a payload that does not split into the
    schedule's chunks (the runtime pads via ``Schedule.padded_elems``
    first, ops/hier_reduce.py); zero-padding caller-side keeps the
    original prefix exact."""
    n = 4
    sched = verify(synthesize_dp_schedule("ring", n, 1))
    body = emit_allreduce_body(sched, "dp", verify_first=False)
    with pytest.raises(ValueError, match="does not split"):
        body(jnp.zeros(13, jnp.float32))

    local = 13  # not divisible by n_chunks
    padded = sched.padded_elems(local)
    assert padded % sched.n_chunks == 0 and padded >= local
    raw = np.random.RandomState(3).standard_normal((n, local))
    x = jnp.asarray(np.pad(raw, ((0, 0), (0, padded - local)))
                    .reshape(-1), jnp.float32)
    out = _run_body(body, n, cpu_devices, x)
    want = raw.sum(axis=0)
    np.testing.assert_allclose(out[0][:local], want, rtol=1e-5,
                               atol=1e-5)
