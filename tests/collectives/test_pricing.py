"""α-β schedule pricing (collectives/pricing.py): ring-fit inversion
exactness, hop-distance-aware ICI billing, the small/large-payload plan
flip the search keys on, and min-over-curves never inventing a price
for a family missing a link curve."""

import pytest

from hetu_galvatron_tpu.collectives.pricing import (
    invert_ring_fit,
    link_curves_from_algos,
    price_schedule_ms,
    price_space,
)
from hetu_galvatron_tpu.collectives.synthesize import (
    halving_doubling_all_reduce,
    hier_all_reduce,
    ring_all_reduce,
    synthesize_space,
    torus2d_all_reduce,
)

pytestmark = [pytest.mark.collectives]

A_FIT, B_FIT = 0.05, 10.0


def _ici(m):
    return {"ici": invert_ring_fit(A_FIT, B_FIT, m)}


# ---------------------------------------------------------------------------
# ring-fit inversion
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("m", [2, 4, 8, 16])
@pytest.mark.parametrize("mb", [0.001, 1.0, 64.0])
def test_inversion_reproduces_fit_on_ring(m, mb):
    """Pricing the ring schedule with the link curve inverted from its
    own fitted (α, β) must give back α + mb/β — the inversion and the
    pricer are inverses on the schedule shape the fit measured."""
    got = price_schedule_ms(ring_all_reduce(m), mb, _ici(m))
    want = A_FIT + mb / B_FIT
    assert got == pytest.approx(want, rel=1e-9)


def test_inversion_rejects_degenerate_group():
    with pytest.raises(ValueError):
        invert_ring_fit(A_FIT, B_FIT, 1)


# ---------------------------------------------------------------------------
# hop-distance billing (Schedule.topo)
# ---------------------------------------------------------------------------


def test_ring_hops_are_all_distance_one():
    s = ring_all_reduce(8)
    for st in s.steps:
        for x in st.xfers:
            assert s.hop_distance(x.src, x.dst) == 1


def test_halving_doubling_bills_stride_hops():
    """The stride-2^k exchange travels 2^k nearest-neighbour links on
    the 1D torus, so the tree's bandwidth term must grow with payload
    faster than the ring's — hop-distance billing is what keeps the
    ring bandwidth-optimal at bulk."""
    s = halving_doubling_all_reduce(8)
    dists = sorted({s.hop_distance(x.src, x.dst)
                    for st in s.steps for x in st.xfers})
    assert dists == [1, 2, 4]
    ring_bulk = price_schedule_ms(ring_all_reduce(8), 64.0, _ici(8))
    tree_bulk = price_schedule_ms(s, 64.0, _ici(8))
    assert tree_bulk > ring_bulk


def test_torus2d_topo_wraps_both_dims():
    s = torus2d_all_reduce(2, 4)
    assert s.topo == (2, 4)
    # neighbours along each torus dim are one hop, wrap included
    assert s.hop_distance(0, 1) == 1      # same row, col 0 -> 1
    assert s.hop_distance(0, 3) == 1      # col wrap 0 -> 3
    assert s.hop_distance(0, 4) == 1      # row 0 -> 1, same col
    assert s.hop_distance(1, 6) == 2      # row hop + col hop


def test_dcn_is_switch_routed_distance_free():
    """Cross-slice steps bill chunks only — the DCN seam is a switch,
    not a torus, so there is no hop multiplier to pay."""
    s = hier_all_reduce(4, 2)
    curves = {"ici": invert_ring_fit(A_FIT, B_FIT, 2),
              "dcn": invert_ring_fit(0.5, 1.0, 4)}
    assert price_schedule_ms(s, 8.0, curves) > 0


# ---------------------------------------------------------------------------
# the plan flip + space pricing
# ---------------------------------------------------------------------------


def test_space_prices_at_least_four_families():
    prices = price_space(synthesize_space(8), 1.0, _ici(8))
    assert len(prices) >= 4
    assert all(v > 0 for v in prices.values())


def test_plan_flip_tree_wins_only_small_payloads():
    """The pinned regime flip: α-dominated tiny gradients go to a tree
    family, bandwidth-dominated bulk to ring/torus — and never the
    other way around."""
    space = synthesize_space(8)
    tiny = price_space(space, 0.0005, _ici(8))
    bulk = price_space(space, 64.0, _ici(8))
    assert min(tiny, key=tiny.get) in ("tree_hd", "tree_bcast")
    assert min(bulk, key=bulk.get) in ("ring", "torus2d")
    assert min(bulk, key=bulk.get) not in ("tree_hd", "tree_bcast")


def test_missing_curve_drops_family_not_invents_price():
    """min-over-curves never guesses: the 4x2 hierarchical space priced
    with only a dcn curve keeps the (all-dcn-seam) flat ring and drops
    the trees that also need ici."""
    space = synthesize_space(8, cross=2)
    dcn_only = price_space(space, 8.0,
                           {"dcn": invert_ring_fit(0.5, 1.0, 2)})
    assert "ring" in dcn_only
    assert "tree_hd" not in dcn_only and "hier_rings" not in dcn_only
    both = price_space(space, 8.0,
                       {"ici": invert_ring_fit(A_FIT, B_FIT, 4),
                        "dcn": invert_ring_fit(0.5, 1.0, 2)})
    assert set(both) == set(space)


# ---------------------------------------------------------------------------
# curve extraction from the profiled per-algorithm tables
# ---------------------------------------------------------------------------


def test_link_curves_prefer_exact_size_else_nearest():
    algos = {"8_1": {"ring_ici": (0.8, 8.0)},
             "4_1": {"ring_ici": (0.4, 4.0)},
             "2_0": {"ring_dcn": (2.0, 1.0)}}
    curves = link_curves_from_algos(algos, 8, 2)
    assert curves["ici"] == invert_ring_fit(0.8, 8.0, 8)
    assert curves["dcn"] == invert_ring_fit(2.0, 1.0, 2)
    # no size-6 fit: the nearest profiled ring size is inverted instead
    near = link_curves_from_algos(algos, 6, 1)
    assert near["ici"] == invert_ring_fit(0.8, 8.0, 8)


def test_link_curves_empty_for_legacy_profiles():
    """Legacy profiles (no per-algorithm curves) must yield NO link
    curves — which is what keeps every golden search byte-identical:
    no curves, no rankings, no plan-JSON key."""
    assert link_curves_from_algos({}, 8, 1) == {}
    assert link_curves_from_algos({"8_1": {"tree_ici": (1, 1)}}, 8, 1) \
        == {}
