"""The broken-schedule corpus: mutate healthy synthesized schedules along
every axis the static verifier (collectives/verify.py) promises to
police, and pin that each mutation is rejected with a ``ScheduleError``
whose diagnostic NAMES the offending step (or the schedule and the
rank/chunk for the whole-program completeness checks) — never a bare
traceback out of the simulator."""

import dataclasses

import pytest

from hetu_galvatron_tpu.collectives.ir import ScheduleError, Step, Xfer
from hetu_galvatron_tpu.collectives.synthesize import (
    hier_all_reduce,
    ring_all_gather,
    ring_all_reduce,
    synthesize_space,
)
from hetu_galvatron_tpu.collectives.verify import verify

pytestmark = [pytest.mark.collectives]


def _mutate_step(sched, i, **fields):
    steps = list(sched.steps)
    steps[i] = dataclasses.replace(steps[i], **fields)
    return dataclasses.replace(sched, steps=tuple(steps))


def _reject(sched, *needles):
    """The mutation must raise ScheduleError (and ONLY ScheduleError —
    a KeyError/IndexError escaping the simulator is a verifier bug)
    carrying every expected diagnostic fragment."""
    with pytest.raises(ScheduleError) as exc:
        verify(sched)
    msg = str(exc.value)
    for needle in needles:
        assert needle in msg, f"diagnostic {msg!r} lacks {needle!r}"
    return msg


# ---------------------------------------------------------------------------
# the corpus: one mutation per verifier axis
# ---------------------------------------------------------------------------


def test_dropped_transfer_breaks_completeness():
    """1. Drop one transfer: some rank never receives a contribution —
    the final-state check names the starved rank and chunk."""
    s = ring_all_reduce(4)
    step0 = s.steps[0]
    broken = _mutate_step(s, 0, xfers=step0.xfers[1:])
    _reject(broken, "incomplete all_reduce", "missing the contribution")


def test_duplicate_reduction_rejected():
    """2. Replay an add exchange: the same original contribution is
    summed twice — the sum is silently wrong, the verifier is not."""
    s = ring_all_reduce(4)
    dup = s.steps[0]
    steps = (s.steps[0], dataclasses.replace(dup, slot=dup.slot),) \
        + s.steps[1:]
    broken = dataclasses.replace(s, steps=steps)
    _reject(broken, "step 1", "duplicate reduction")


def test_ici_tag_on_cross_slice_transfer_rejected():
    """3. Link-class lie: re-tag a cross-slice exchange ici — the pricer
    would bill the DCN seam at ICI bandwidth."""
    s = hier_all_reduce(2, 2)
    i = next(i for i, st in enumerate(s.steps) if st.link == "dcn")
    broken = _mutate_step(s, i, link="ici")
    _reject(broken, f"step {i}", "crosses slices", "link-class violation")


def test_cyclic_wavefront_rejected():
    """4. Slot order going backwards: a later ppermute waiting on an
    earlier slot is a deadlock on real hardware."""
    s = ring_all_reduce(4)
    broken = _mutate_step(s, 2, slot=0)
    _reject(broken, "step 2", "cyclic/non-monotone", "deadlock")


def test_under_declared_send_budget_rejected():
    """5. Byte undercount: declare fewer per-rank chunk sends than the
    steps actually move — the pricer would underbill the schedule."""
    s = ring_all_reduce(4)
    broken = dataclasses.replace(
        s, declared_sends_per_rank=s.declared_sends_per_rank - 1)
    _reject(broken, "count/byte mismatch", "under-declared")


def test_duplicate_source_rejected():
    """6. Two transfers out of one rank in one exchange: not a partial
    permutation — one lax.ppermute cannot carry both."""
    s = ring_all_reduce(4)
    step0 = s.steps[0]
    broken = _mutate_step(s, 0, xfers=step0.xfers + (step0.xfers[0],))
    _reject(broken, "step 0", "source of two transfers")


def test_duplicate_destination_rejected():
    """7. Two transfers into one rank in one exchange."""
    s = ring_all_reduce(4)
    step0 = s.steps[0]
    clash = dataclasses.replace(step0.xfers[0],
                                dst=step0.xfers[1].dst)
    broken = _mutate_step(s, 0, xfers=(clash,) + step0.xfers[1:])
    _reject(broken, "step 0", "destination of two")


def test_send_of_nothing_rejected():
    """8. A rank sends a chunk slot it holds nothing for: in an
    all-gather only owners start with data, so rewiring the first hop's
    source to a non-owner sends garbage."""
    s = ring_all_gather(4)
    step0 = s.steps[0]
    x0 = step0.xfers[0]
    # rewire x0 to carry a chunk its src does not own at step 0
    wrong = tuple(k for k in range(s.n_chunks)
                  if (s.owner or ())[k] != x0.src)[:1]
    broken = _mutate_step(
        s, 0, xfers=(dataclasses.replace(x0, chunks=wrong),)
        + step0.xfers[1:])
    _reject(broken, "step 0", "holds no contribution")


def test_chunk_out_of_range_rejected():
    """9. A transfer naming a chunk id outside the schedule's space."""
    s = ring_all_reduce(4)
    step0 = s.steps[0]
    broken = _mutate_step(
        s, 0, xfers=(dataclasses.replace(
            step0.xfers[0], chunks=(s.n_chunks,)),) + step0.xfers[1:])
    _reject(broken, "step 0", "out of range")


def test_rank_out_of_range_rejected():
    """10. A transfer to a rank outside the group."""
    s = ring_all_reduce(4)
    step0 = s.steps[0]
    broken = _mutate_step(
        s, 0, xfers=(dataclasses.replace(
            step0.xfers[0], dst=s.n_ranks),) + step0.xfers[1:])
    _reject(broken, "step 0", "out of range")


def test_unknown_link_and_combine_rejected():
    """11. Structural garbage: unknown link class / combine mode."""
    s = ring_all_reduce(4)
    _reject(_mutate_step(s, 0, link="nvlink"), "step 0", "unknown link")
    _reject(_mutate_step(s, 0, combine="max"), "step 0",
            "unknown combine")


def test_truncated_schedule_rejected():
    """12. Chop the tail off: data movement simply stops early."""
    s = ring_all_reduce(4)
    broken = dataclasses.replace(s, steps=s.steps[:-2],
                                 declared_sends_per_rank=None)
    _reject(broken, "incomplete all_reduce")


def test_over_reduction_rejected():
    """13. An extra full ring pass over-reduces every chunk (each
    contribution lands twice) — caught as duplicate reduction at the
    first replayed add."""
    s = ring_all_reduce(2)
    again = tuple(dataclasses.replace(st, slot=st.slot + 100)
                  for st in s.steps if st.combine == "add")
    broken = dataclasses.replace(s, steps=s.steps + again,
                                 declared_sends_per_rank=None)
    _reject(broken, "duplicate reduction")


# ---------------------------------------------------------------------------
# the healthy space stays healthy (the corpus's control group)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,cross", [(2, 1), (4, 1), (6, 1), (8, 1),
                                     (8, 2), (16, 4)])
def test_synthesized_space_verifies(n, cross):
    space = synthesize_space(n, cross=cross)
    assert space, f"empty space for n={n} cross={cross}"
    for name, sched in space.items():
        assert verify(sched) is sched, name


def test_verify_returns_schedule_for_chaining():
    s = ring_all_reduce(8)
    assert verify(s) is s
