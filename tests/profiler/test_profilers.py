"""Profiler tests on the virtual CPU mesh: schema compatibility with the
search engine is the contract (reference tests/profiler/*)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, HardwareProfileArgs
from hetu_galvatron_tpu.core.profiler.hardware_profiler import HardwareProfiler
from hetu_galvatron_tpu.core.profiler.model_profiler import ModelProfiler
from hetu_galvatron_tpu.core.profiler.runtime_profiler import RuntimeProfiler
from hetu_galvatron_tpu.core.search_engine.profiles import (
    parse_memory_config,
    parse_time_config,
    read_allreduce_bandwidth,
    read_p2p_bandwidth,
    remap_collective_latency,
)

pytestmark = [pytest.mark.profiler, pytest.mark.distributed]

TINY = dict(hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
            vocab_size=64, max_position_embeddings=64, seq_length=16,
            make_vocab_size_divisible_by=1)


@pytest.fixture(scope="module")
def hw_args():
    return HardwareProfileArgs(num_nodes=1, num_devices_per_node=8,
                               start_mb=1, end_mb=8, scale=2,
                               warmup_iters=1, profile_iters=2)


def test_hardware_profiler_schemas(hw_args, cpu_devices, tmp_path):
    prof = HardwareProfiler(hw_args, devices=cpu_devices)
    ar = prof.profile_allreduce_bandwidth(message_mb=1)
    assert "allreduce_size_8_consec_1" in ar
    assert "allreduce_size_4_consec_0" in ar
    assert all(v > 0 for v in ar.values())
    # consumable by the search-engine reader
    bw, coe = read_allreduce_bandwidth(ar, 8)
    assert coe["8"] > 0 and coe["1"] == 0

    p2p = prof.profile_p2p_bandwidth(message_mb=1)
    assert set(p2p) == {"pp_size_2", "pp_size_4", "pp_size_8"}
    _, p2p_coe = read_p2p_bandwidth(p2p)
    assert p2p_coe[2] > 0

    ov = prof.profile_overlap_coefficient(message_mb=1)
    assert ov["overlap_coe"] >= 1.0


@pytest.mark.slow
def test_sp_time_profile_feeds_latency_tables(hw_args, cpu_devices):
    args = HardwareProfileArgs(num_nodes=1, num_devices_per_node=4,
                               start_mb=1, end_mb=128, scale=2,
                               warmup_iters=1, profile_iters=1)
    prof = HardwareProfiler(args, devices=cpu_devices[:4])
    sp = prof.profile_sp_time()
    # 8 sizes per group per op -> latency remap fits a line
    tables = remap_collective_latency(sp, "allgather")
    assert 4 in tables and "popt" in tables[4]
    a2a = remap_collective_latency(sp, "all2all")
    assert 2 in a2a
    # the new sub-MB points ride a 'sub_' prefix the legacy remap parsers
    # never see (their MB values would otherwise read as megabytes)
    assert "sub_allreduce_size_4_512KB_time" in sp
    assert all(mb in tables[4] or mb == "popt" for mb in tables[4])
    assert not any(isinstance(k, int) and k > 128 for k in tables[4])


def test_alpha_beta_fit_roundtrips_into_cost_model(cpu_devices):
    """profile_alpha_beta fits (α ms, β MB/ms) per (size, consec) from the
    sub-MB + MB allreduce points; the pairs merge into the bandwidth JSON,
    profiles.read_alpha_beta parses them, and a legacy JSON yields {}."""
    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_alpha_beta,
    )

    args = HardwareProfileArgs(num_nodes=1, num_devices_per_node=4,
                               start_mb=1, end_mb=4, scale=2,
                               warmup_iters=1, profile_iters=1)
    prof = HardwareProfiler(args, devices=cpu_devices[:4])
    sp = prof.profile_sp_time()
    ab = prof.profile_alpha_beta(sp)
    for size, consec in ((4, 1), (2, 1), (2, 0)):
        assert f"allreduce_size_{size}_consec_{consec}_alpha_ms" in ab
        beta = ab[f"allreduce_size_{size}_consec_{consec}_beta_mb_per_ms"]
        assert beta > 0
    # merged with the bandwidth keys, the reader recovers the pairs...
    bw = prof.profile_allreduce_bandwidth(message_mb=1)
    bw.update(ab)
    pairs = read_alpha_beta(bw)
    assert set(pairs) == {"4_1", "2_1", "2_0"}
    assert all(a >= 0 and b > 0 for a, b in pairs.values())
    # ...and the legacy reader still parses the merged JSON untouched
    bw2, coe = read_allreduce_bandwidth(bw, 4)
    assert coe["4"] > 0
    # legacy bandwidth-only JSON -> empty table (golden costs unchanged)
    assert read_alpha_beta(
        {"allreduce_size_4_consec_1": 100.0}) == {}


def test_runtime_profiler_timing_and_log():
    args = CoreArgs.model_validate({"profile": {"profile": 1,
                                                "profile_warmup": 0}})
    prof = RuntimeProfiler(args)
    for it in range(4):
        prof.time_start(it)
        x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
        prof.time_end(it, sync=x)
        line = prof.iteration_log(it, {"loss": 1.0, "grad_norm": 0.5})
    assert prof.filtered_time_ms() > 0
    assert "loss 1.0000" in line


def test_iteration_log_consistent_and_sync_free(capsys):
    """ADVICE r5: the returned string equals the PRINTED line (MoE stats
    included) on printing iterations, and non-printing iterations return
    "" with ZERO device-to-host conversions — never a half-formatted
    line."""

    class NoSync:
        def __float__(self):
            raise AssertionError("device sync on a non-printing iteration")

    moe = {"layer1": {"load_balance_loss": 0.5, "z_loss": 0.25,
                      "tokens_per_expert": np.array([3.0, 1.0])}}
    args = CoreArgs.model_validate({"logging": {"log_interval": 2}})
    prof = RuntimeProfiler(args)
    # off-interval: no formatting at all -> NoSync never converted
    assert prof.iteration_log(
        1, {"loss": NoSync(), "grad_norm": NoSync(), "moe": moe}) == ""
    # non-zero rank: same
    prof_r1 = RuntimeProfiler(args, rank=1)
    assert prof_r1.iteration_log(
        0, {"loss": NoSync(), "grad_norm": NoSync()}) == ""
    capsys.readouterr()
    # printing iteration: the full line — MoE stats included — is BOTH
    # returned and printed
    line = prof.iteration_log(2, {"loss": 1.0, "grad_norm": 0.5,
                                  "moe": moe})
    printed = capsys.readouterr().out.strip()
    assert line == printed
    assert "moe[layer1]" in line and "imb 1.50" in line
    # ...and the converted stats land in the metrics registry
    assert prof.registry.gauge("moe/aux_loss", layer="layer1").value == 0.5
    assert prof.registry.gauge("moe/imbalance", layer="layer1").value == 1.5


def test_runtime_profiler_routes_registry(tmp_path):
    """Iteration timing flows through the observability registry."""
    from hetu_galvatron_tpu.observability.registry import MetricsRegistry

    reg = MetricsRegistry()
    args = CoreArgs.model_validate({"profile": {"profile": 1,
                                                "profile_warmup": 0}})
    prof = RuntimeProfiler(args, registry=reg)
    for it in range(3):
        prof.time_start(it)
        prof.time_end(it)
    h = reg.histogram("profiler/iter_time_ms")
    assert h.count == 3
    assert h.snapshot()["mean"] == pytest.approx(
        float(np.mean(prof.time_samples)), rel=1e-6)


def test_model_profiler_computation_schema(tmp_path):
    args = CoreArgs.model_validate({
        "model": TINY,
        "model_profiler": {"profile_type": "computation",
                           "profile_mode": "static",
                           "profile_batch_size": 2,
                           "profile_seq_length_list": [16],
                           "layernum_min": 1, "layernum_max": 2},
    })
    prof = ModelProfiler(args)
    entries = prof.profile_computation()
    assert "layertype_0_bsz2_seq16" in entries
    assert "layertype_other_bsz2_seq16" in entries
    times, others = parse_time_config(
        entries, mode="static", num_layertype=1, seqlen_list=[16])
    assert len(times) == 1 and len(others) == 1


@pytest.mark.slow
def test_model_profiler_memory_schema(cpu_devices):
    args = CoreArgs.model_validate({
        "model": TINY,
        "model_profiler": {"profile_type": "memory",
                           "profile_batch_size": 2,
                           "profile_seq_length_list": [16],
                           "layernum_min": 1, "layernum_max": 2,
                           "max_tp_deg": 2},
    })
    prof = ModelProfiler(args, devices=cpu_devices)
    mem = prof.profile_memory()
    assert "layertype_0_sp" in mem
    layer = mem["layertype_0_sp"]["16"]
    assert layer["parameter_size"] > 0
    assert 1 in layer["tp_activation_per_bsz_dict"]
    assert "checkpoint" in layer["tp_activation_per_bsz_dict"]
    # consumable by the search-engine reader
    params, acts, off, on = parse_memory_config(
        mem, mode="static", num_layertype=1, seqlen_list=[16],
        sequence_parallel=True)
    assert params[0] > 0 and 1 in acts[0]
    assert "model_states" in off and "first_stage" in on


def test_runtime_profiler_trace_capture(tmp_path):
    """profile.trace_dir captures an XLA trace window (the reference's
    torch.profiler counterpart); stop_trace is idempotent."""
    import glob
    import os

    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.core.profiler.runtime_profiler import (
        RuntimeProfiler,
    )

    args = CoreArgs()
    args.profile.profile = 1
    args.profile.profile_warmup = 1
    args.profile.trace_dir = str(tmp_path / "trace")
    args.profile.trace_iters = 2
    prof = RuntimeProfiler(args)
    x = jnp.ones((8, 8))
    for it in range(5):
        prof.time_start(it)
        y = jax.jit(lambda a: a @ a)(x)
        prof.time_end(it, sync=y)
    prof.stop_trace()
    prof.stop_trace()  # idempotent
    files = glob.glob(str(tmp_path / "trace" / "**" / "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace files written"


def test_alpha_beta_degenerate_fit_falls_back(cpu_devices):
    """Satellite hardening: a noisy fit with a non-positive slope must NOT
    write a garbage β pair — the (size, consec) falls back to the legacy
    single-point bandwidth (absent keys), with a warning."""
    from hetu_galvatron_tpu.core.profiler.hardware_profiler import (
        fit_alpha_beta,
    )
    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_alpha_beta,
    )

    # flat and DECREASING synthetic point sets are both degenerate
    with pytest.warns(UserWarning, match="degenerate slope"):
        assert fit_alpha_beta([0.1, 0.5, 1, 2, 4], [1, 1, 1, 1, 1],
                              label="flat") is None
    with pytest.warns(UserWarning, match="degenerate slope"):
        assert fit_alpha_beta([0.1, 0.5, 1, 2, 4],
                              [5, 4, 3, 2, 1], label="neg") is None
    # a healthy set still fits (α clamped ≥ 0)
    pair = fit_alpha_beta([1, 2, 4], [0.9, 2.1, 3.9], label="ok")
    assert pair is not None and pair[0] >= 0 and pair[1] > 0

    # integration: synthetic sp_times whose size-4 curve is constant ->
    # profile_alpha_beta emits NO 4_1 pair, and the strided/other groups
    # it measures live are unaffected (world 2: no strided variant)
    args = HardwareProfileArgs(num_nodes=1, num_devices_per_node=4,
                               start_mb=1, end_mb=4, sub_mb_floor_kb=256,
                               warmup_iters=0, profile_iters=1)
    prof = HardwareProfiler(args, devices=cpu_devices[:4])
    sp = {}
    for size in (4, 2):
        for kb in (256, 512):
            sp[f"sub_allreduce_size_{size}_{kb}KB_time"] = (
                1.0 if size == 4 else kb / 1024.0)
        for mb in (1, 2, 4):
            sp[f"allreduce_size_{size}_{mb}MB_time"] = (
                1.0 if size == 4 else float(mb))
    with pytest.warns(UserWarning, match="allreduce_size_4_consec_1"):
        ab = prof.profile_alpha_beta(sp)
    assert "allreduce_size_4_consec_1_alpha_ms" not in ab
    assert "allreduce_size_4_consec_1_beta_mb_per_ms" not in ab
    assert "allreduce_size_2_consec_1_alpha_ms" in ab
    # the reader sees only the healthy pairs
    pairs = read_alpha_beta(ab)
    assert "4_1" not in pairs and "2_1" in pairs


def test_alpha_beta_algos_roundtrip(cpu_devices):
    """profile_alpha_beta_algos fits per-(algorithm, level) pairs from
    ring vs halving-doubling shaped schedules; read_alpha_beta_algos
    parses them; the FLAT reader and legacy parsers skip the namespaced
    keys untouched."""
    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_alpha_beta,
        read_alpha_beta_algos,
    )

    args = HardwareProfileArgs(num_nodes=1, num_devices_per_node=4,
                               start_mb=1, end_mb=4, sub_mb_floor_kb=256,
                               warmup_iters=0, profile_iters=1)
    prof = HardwareProfiler(args, devices=cpu_devices[:4])
    algos = prof.profile_alpha_beta_algos()
    # full-world group: ici only; sub-world: ici + the strided dcn proxy
    for key in ("allreduce_size_4_consec_1_alg_ring_lvl_ici_alpha_ms",
                "allreduce_size_4_consec_1_alg_tree_lvl_ici_alpha_ms",
                "allreduce_size_2_consec_0_alg_ring_lvl_dcn_alpha_ms"):
        # CPU timing noise may legitimately drop a degenerate fit; the
        # schema contract is that whatever IS emitted pairs α with β
        if key in algos:
            assert key.replace("_alpha_ms", "_beta_mb_per_ms") in algos
    table = read_alpha_beta_algos(algos)
    for group, curves in table.items():
        for alg_lvl, (a, b) in curves.items():
            assert a >= 0 and b > 0
            alg, lvl = alg_lvl.split("_")
            assert alg in ("ring", "tree") and lvl in ("ici", "dcn")
    # the namespaced keys are INVISIBLE to the flat reader: merging them
    # next to flat pairs does not corrupt the legacy table
    flat = {"allreduce_size_4_consec_1_alpha_ms": 0.5,
            "allreduce_size_4_consec_1_beta_mb_per_ms": 100.0}
    merged = {**flat, **algos}
    assert read_alpha_beta(merged) == read_alpha_beta(flat)
    assert read_alpha_beta_algos(flat) == {}
    # single-process fleet: the dcn level is the strided PROXY and the
    # fitted JSON says so in metadata (a proxy must never silently pass
    # as a fleet measurement); the metadata key is invisible to parsers
    assert algos.get("dcn_level_source") == "proxy-strided"
    assert read_alpha_beta_algos({**algos}) == table


def test_dcn_group_true_multihost_vs_proxy(cpu_devices, recwarn):
    """_dcn_group_devices: with devices spanning processes, the group is
    built round-robin across processes (every hop crosses the seam — a
    true DCN group, tagged 'multihost'); a single-process fleet keeps
    the strided proxy WITH a warning and the 'proxy-strided' tag."""
    from types import SimpleNamespace

    from hetu_galvatron_tpu.core.profiler.hardware_profiler import (
        _dcn_group_devices,
    )

    multi = [SimpleNamespace(id=i, process_index=i // 2) for i in range(8)]
    group, src = _dcn_group_devices(multi, 4, 8)
    assert src == "multihost"
    assert len(group) == 4
    # adjacent group members always sit in DIFFERENT processes
    procs = [d.process_index for d in group]
    assert all(a != b for a, b in zip(procs, procs[1:]))

    import warnings as _w

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        group, src = _dcn_group_devices(list(cpu_devices[:8]), 4, 8)
    assert src == "proxy-strided"
    assert len(group) == 4
    assert any("strided intra-host PROXY" in str(w.message) for w in rec)
