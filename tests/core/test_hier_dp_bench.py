"""The hierarchical-dp A/B microbench must run, produce self-consistent
numbers, and (acceptance) not regress the flat GSPMD path on the virtual
CPU mesh — pooled-median ``hier_dp_vs_flat <= 1.0`` with zero
steady-state recompiles. The full-size acceptance shape rides the slow
tier; the fast smoke only checks the harness is alive."""

import pytest

pytestmark = [pytest.mark.core]


def _bench(**kw):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    import hier_dp_bench as b

    return b.run(**kw)


@pytest.mark.slow
def test_hier_dp_bench_runs_and_is_consistent():
    out = _bench(iters=2, plans=((1, 8),), hidden=64, seq=64, chunks=4,
                 bucket_mb=0.05)
    leg = out["legs"]["tp1dp8"]
    assert leg["flat_step_ms"] > 0 and leg["hier_step_ms"] > 0
    assert out["hier_dp_vs_flat"] > 0
    assert out["hier_dp_recompiles"] == 0
    assert out["platform"] == "cpu"
    assert out["dcn_slices"] == 2
    # the bucketed-vs-monolithic leg rides every run: positive ratio,
    # zero steady-state recompiles, parity gate inside run() (it raises
    # on loss divergence)
    assert out["bucketed"]["hier_dp_bucketed_vs_mono"] > 0
    assert out["bucketed"]["bucket_recompiles"] == 0
    assert out["hier_dp_bucketed_vs_mono"] == \
        out["bucketed"]["hier_dp_bucketed_vs_mono"]


@pytest.mark.slow
def test_hier_dp_bench_acceptance_ratio():
    """ACCEPTANCE: at the committed bench shape the hierarchical path must
    not lose to the flat all-reduce on the CPU mesh (the once-per-step vs
    once-per-microbatch schedule difference dominates; the per-level DCN
    win needs real hardware). Bounded loosely above the committed
    baseline to absorb shared-CI noise — the committed number itself is
    gated by tools/bench_gate.py."""
    out = _bench(iters=6)
    assert out["hier_dp_recompiles"] == 0
    assert out["hier_dp_vs_flat"] <= 1.1, out
    # bucketed acceptance: the pipelined program must not cost more than
    # it hides (<= ~1.0 at the committed shape; loose bound for CI noise
    # — the committed number is gated by tools/bench_gate.py)
    assert out["hier_dp_bucketed_vs_mono"] <= 1.1, out
