"""Indexed dataset: write/read round-trip, C++ vs numpy sample mapping,
document-crossing samples, blending (reference test_dataloader.py tier)."""

import numpy as np
import pytest

from hetu_galvatron_tpu.data.indexed_dataset import (
    BlendedDataset,
    GPTDataset,
    IndexedDataset,
    build_sample_idx,
    indexed_batches,
    write_indexed_dataset,
)

pytestmark = pytest.mark.utils


def _write(tmp_path, name="corpus", n_docs=10, seed=0, vmax=100):
    rng = np.random.RandomState(seed)
    docs = [rng.randint(0, vmax, rng.randint(5, 40)).tolist()
            for _ in range(n_docs)]
    prefix = str(tmp_path / name)
    stats = write_indexed_dataset(prefix, docs)
    return prefix, docs, stats


def test_write_read_roundtrip(tmp_path):
    prefix, docs, stats = _write(tmp_path)
    ds = IndexedDataset(prefix)
    assert len(ds) == len(docs) == stats["documents"]
    assert ds.total_tokens == sum(len(d) for d in docs) == stats["tokens"]
    for i in (0, 3, len(docs) - 1):
        np.testing.assert_array_equal(ds.get_doc(i), np.asarray(docs[i]))


def test_sample_idx_cpp_matches_numpy(tmp_path):
    from hetu_galvatron_tpu.utils import native

    doc_lens = np.array([7, 13, 5, 29, 3, 17], np.int64)
    seq = 8
    n = 6
    cpp = build_sample_idx(doc_lens, seq, n)
    # force the numpy path by poisoning the native-lib cache
    saved = native._CACHE.get("libdataset_helpers.so")
    native._CACHE["libdataset_helpers.so"] = None
    try:
        ref = build_sample_idx(doc_lens, seq, n)
    finally:
        native._CACHE["libdataset_helpers.so"] = saved
    np.testing.assert_array_equal(cpp, ref)


def test_gpt_dataset_crosses_documents(tmp_path):
    prefix, docs, _ = _write(tmp_path)
    flat = np.concatenate([np.asarray(d) for d in docs])
    ds = GPTDataset(IndexedDataset(prefix), seq_length=16, shuffle=False)
    assert len(ds) == (len(flat) - 1) // 16
    for i in range(len(ds)):
        np.testing.assert_array_equal(ds[i], flat[i * 16:i * 16 + 17])


def test_blended_dataset(tmp_path):
    p1, _, _ = _write(tmp_path, "a", seed=1)
    p2, _, _ = _write(tmp_path, "b", seed=2)
    b = BlendedDataset([GPTDataset(IndexedDataset(p1), 8),
                        GPTDataset(IndexedDataset(p2), 8)],
                       weights=[0.5, 0.5])
    assert len(b) > 0
    sample = b[0]
    assert sample.shape == (9,)
    # stateless: same index -> same sample, every time
    np.testing.assert_array_equal(b[0], sample)
    np.testing.assert_array_equal(b[5], b[5])


def test_gpt_dataset_reshuffles_per_epoch(tmp_path):
    prefix, _, _ = _write(tmp_path, n_docs=40)
    ds = GPTDataset(IndexedDataset(prefix), seq_length=8)
    n = len(ds)
    epoch0 = [ds[i].tolist() for i in range(n)]
    epoch1 = [ds[n + i].tolist() for i in range(n)]
    # same multiset of samples, different order
    assert sorted(map(tuple, epoch0)) == sorted(map(tuple, epoch1))
    assert epoch0 != epoch1


def test_indexed_batches_contract(tmp_path):
    prefix, _, _ = _write(tmp_path, n_docs=30)
    it = indexed_batches(prefix, seq_length=8, global_batch_size=4)
    batch = next(it)
    assert batch["tokens"].shape == (4, 8)
    assert batch["labels"].shape == (4, 8)
    np.testing.assert_array_equal(batch["labels"][:, :-1],
                                  batch["tokens"][:, 1:])


def test_corrupt_index_raises(tmp_path):
    path = tmp_path / "bad"
    path.with_suffix(".idx").write_bytes(b"NOTMAGIC" + b"\0" * 16)
    path.with_suffix(".bin").write_bytes(b"")
    with pytest.raises(ValueError, match="bad magic"):
        IndexedDataset(str(path))


def test_preprocess_data_cli(tmp_path, capsys):
    from hetu_galvatron_tpu.cli.preprocess_data import main
    from hetu_galvatron_tpu.data.indexed_dataset import IndexedDataset

    src = tmp_path / "corpus.txt"
    src.write_text("hello world\n" + '{"text": "json doc"}\n' + "third\n")
    prefix = str(tmp_path / "out")
    assert main([str(src), prefix]) == 0
    out = capsys.readouterr().out
    assert "3 documents" in out
    ds = IndexedDataset(prefix)
    assert len(ds) == 3
    # byte tokenizer + eod marker
    doc = ds.get_doc(0)
    assert doc[-1] == 256
    assert bytes(doc[:-1].astype(np.uint8)).decode() == "hello world"
