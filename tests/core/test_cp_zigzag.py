"""Dataloader-side zigzag cp layout (reference get_batch zigzag slice,
utils.py:295): sequences arrive pre-permuted with position_ids riding the
batch, ring layers skip the per-call layout reshard, and training is
numerically identical to the sequence-order path (the loss and grads are
permutation-invariant)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs
from hetu_galvatron_tpu.runtime.dataloader import (
    _zigzag_perm,
    make_batch,
    zigzag_cp_batches,
)

pytestmark = [pytest.mark.core, pytest.mark.distributed]


def _args(**par):
    base = {
        "model": {
            "hidden_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4, "vocab_size": 256,
            "seq_length": 16, "max_position_embeddings": 32,
            "hidden_act": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope", "tie_word_embeddings": False,
            "add_bias_linear": False, "add_qkv_bias": False,
            "make_vocab_size_divisible_by": 1, "ffn_hidden_size": 128,
            "use_flash_attn": False,
        },
        "parallel": {"global_tp_deg": 1, "global_cp_deg": 2, "vocab_tp": 1,
                     "global_train_batch_size": 8, **par},
    }
    return CoreArgs.model_validate(base)


def test_zigzag_perm_matches_kernel_layout():
    from hetu_galvatron_tpu.ops.ring_attention import zigzag_layout

    for S, cp in ((16, 2), (32, 4), (64, 8)):
        perm = _zigzag_perm(S, cp)
        ref = np.asarray(zigzag_layout(jnp.arange(S)[None], cp))[0]
        np.testing.assert_array_equal(perm, ref)
        assert sorted(perm) == list(range(S))  # a true permutation


def test_zigzag_batches_fields_and_positions():
    data = np.arange(2 * 17).reshape(2, 17)
    batch = make_batch(data)
    batch["segment_ids"] = np.tile(np.arange(16), (2, 1))
    out = next(zigzag_cp_batches(iter([batch]), 2))
    perm = _zigzag_perm(16, 2)
    for k in ("tokens", "labels", "loss_mask", "segment_ids"):
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(batch[k])[:, perm])
    # synthesized position_ids are each slot's global position
    np.testing.assert_array_equal(out["position_ids"][0], perm)


def test_cp_zigzag_loss_matches_sequence_order(cpu_devices):
    """One spmd train step on a cp=2 plan: pre-zigzagged data + cp_zigzag
    plan == sequence-order data + plain plan (loss and updated params)."""
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = build_mesh(8, 1, devices=cpu_devices)
    data = np.random.RandomState(0).randint(0, 256, (8, 17))
    batch_plain = make_batch(data)
    batch_zig = next(zigzag_cp_batches(iter([make_batch(data)]), 2))

    results = []
    for par, batch in ((dict(), batch_plain),
                       (dict(cp_zigzag=True), batch_zig)):
        args = _args(**par)
        hpc = get_hybrid_parallel_config(args, 8)
        assert hpc.cp_zigzag == bool(par)
        params, axes = init_causal_lm(jax.random.key(0), args.model)
        tx = make_optimizer(args.train)
        step, pspecs, ospecs, batch_shd = make_spmd_train_step(
            args.model, hpc, mesh, axes, tx, params,
            compute_dtype=jnp.float32, donate=False)
        sp = shard_params(params, pspecs, mesh)
        opt = jax.jit(tx.init, out_shardings=jax.tree.map(
            lambda s: NamedSharding(mesh, s), ospecs,
            is_leaf=lambda x: isinstance(x, PartitionSpec)))(sp)
        b = jax.device_put(jax.tree.map(jnp.asarray, dict(batch)), batch_shd)
        new_sp, _, metrics = step(sp, opt, b)
        results.append((float(metrics["loss"]), jax.device_get(new_sp)))
    (loss_a, sp_a), (loss_b, sp_b) = results
    assert abs(loss_a - loss_b) < 1e-5, (loss_a, loss_b)
    for (pa, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(sp_a),
                               jax.tree_util.tree_leaves_with_path(sp_b)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=1e-5, err_msg=str(pa))


def test_cp_zigzag_validation():
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    # cp=1 => the flag is a no-op, not an error
    args = _args(cp_zigzag=True, global_cp_deg=1)
    assert get_hybrid_parallel_config(args, 8).cp_zigzag is False
    # bert rejects the causal-only data layout
    args = _args(cp_zigzag=True)
    args.model.model_type = "bert"
    with pytest.raises(ValueError, match="causal"):
        get_hybrid_parallel_config(args, 8)


def test_cp_zigzag_e2e_cli_with_packed_docs(tmp_path):
    """Full train run: cp_zigzag + reset flags through the CLI matches the
    sequence-order run's losses exactly."""
    import os

    from hetu_galvatron_tpu.cli.preprocess_data import main as prep_main
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    zoo = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    src = tmp_path / "c.txt"
    src.write_text("".join(f"zigzag doc {i}\n" for i in range(30)))
    prefix = str(tmp_path / "c")
    assert prep_main([str(src), prefix]) == 0
    common = [os.path.join(zoo, "gpt2-small.yaml"),
              "model.hidden_size=32", "model.num_hidden_layers=2",
              "model.num_attention_heads=2", "model.vocab_size=257",
              "model.seq_length=8", "model.max_position_embeddings=16",
              "model.make_vocab_size_divisible_by=1",
              "model.use_flash_attn=false",
              "train.train_iters=2", "parallel.mixed_precision=fp32",
              "parallel.global_train_batch_size=8",
              "parallel.global_cp_deg=2",
              "data.dataset=indexed", f"data.data_path=[{prefix}]",
              "data.reset_position_ids=true",
              "data.reset_attention_mask=true"]
    ref = train(args_from_cli(common, mode="train_dist"))
    zig = train(args_from_cli(common + ["parallel.cp_zigzag=true"],
                              mode="train_dist"))
    np.testing.assert_allclose(zig["losses"], ref["losses"],
                               rtol=1e-5, atol=1e-6)
