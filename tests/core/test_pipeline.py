"""Pipeline-engine correctness on the virtual 8-CPU mesh: pp=2/pp=4 with
GPipe and 1F1B must reproduce the single-device step (the reference's
test_pp.py compares loss trajectories vs HF for both schedules)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss, init_causal_lm
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

pytestmark = [pytest.mark.parallel, pytest.mark.distributed]

CFG = ModelArgs(
    hidden_size=64, num_hidden_layers=4, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=128,
)

TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _batch(bsz=16, seed=0):
    data = np.random.RandomState(seed).randint(
        0, 128, (bsz, CFG.seq_length + 1))
    return make_batch(data)


def _reference_step(params, batch, cfg=CFG, train=TRAIN):
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    import optax

    jb = jax.tree.map(jnp.asarray, batch)
    tx = make_optimizer(train)
    loss_fn = lambda p: causal_lm_loss(p, jb, cfg, compute_dtype=jnp.float32)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(grads, tx.init(params), params)
    return float(loss), optax.apply_updates(params, upd)


def _pipeline_step(cfg, params, axes, batch, cpu_devices, **pkw):
    args = CoreArgs(model=cfg.model_dump(), train=TRAIN.model_dump())
    for k, v in pkw.items():
        setattr(args.parallel, k, v)
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, batch)
    return metrics, eng.merge_params(new_sp)


CASES = [
    dict(pp_deg=2, pipeline_type="gpipe", chunks=2),
    dict(pp_deg=2, pipeline_type="pipedream_flush", chunks=4),
    dict(pp_deg=4, pipeline_type="gpipe", chunks=4),
    dict(pp_deg=4, pipeline_type="pipedream_flush", chunks=2),
    dict(pp_deg=2, pipeline_type="gpipe", chunks=2, global_tp_deg=2),
    dict(pp_deg=2, pipeline_type="pipedream_flush", chunks=2, sdp=1),
]


@pytest.mark.parametrize(
    "pkw", CASES,
    ids=lambda d: ",".join(f"{k}={v}" for k, v in d.items()))
@pytest.mark.slow
def test_pipeline_matches_single_device(pkw, cpu_devices):
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch)
    pkw = dict(pkw, global_train_batch_size=16)
    metrics, new_params = _pipeline_step(CFG, params, axes, batch,
                                         cpu_devices, **pkw)
    assert abs(metrics["loss"] - ref_loss) < 2e-5, \
        f"loss {metrics['loss']} != {ref_loss}"
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


def test_pipeline_tied_embeddings(cpu_devices):
    """GPT-2-style tied wte: grads must sum across first/last stages and the
    two copies must stay in sync after the update."""
    cfg = ModelArgs(
        hidden_size=64, num_hidden_layers=4, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32, seq_length=16,
        tie_word_embeddings=True, make_vocab_size_divisible_by=1)
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch, cfg=cfg)
    args = CoreArgs(model=cfg.model_dump(), train=TRAIN.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.chunks = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, batch)
    assert abs(metrics["loss"] - ref_loss) < 2e-5
    # the two tied copies stay transposed-identical
    wte = np.asarray(jax.device_get(new_sp[0]["embed"]["wte"]))
    whead = np.asarray(jax.device_get(new_sp[-1]["head"]["whead"]))
    np.testing.assert_allclose(wte, whead.T, rtol=1e-6, atol=1e-7)
    merged = eng.merge_params(new_sp)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(merged)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


def test_uneven_pp_division(cpu_devices):
    """5 layers over pp=2 -> [2, 3]; must still match single device."""
    cfg = CFG.model_copy(update={"num_hidden_layers": 5})
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch, cfg=cfg)
    metrics, new_params = _pipeline_step(
        cfg, params, axes, batch, cpu_devices,
        pp_deg=2, chunks=2, global_train_batch_size=8)
    assert abs(metrics["loss"] - ref_loss) < 2e-5
    for a, b in zip(jax.tree.leaves(ref_params),
                    jax.tree.leaves(new_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=3e-4)


def test_engine_builds_jits_lazily(cpu_devices):
    """The engine's stage/step jits are construct-on-first-use: building an
    engine creates none of them, eval-only use never builds backward/update
    programs, and an untied plan never builds the tied-grad transpose."""
    args = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.chunks = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(CFG, hpc, args.train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    assert eng._lazy_jits == {}, "construction built jits eagerly"
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    sp = eng.split_params(params, axes)
    assert eng._lazy_jits == {}
    eng.eval_step(sp, _batch(bsz=8))
    # eval builds only the eval stage programs (and the fwd list they
    # share nothing with): no backward, update, clip or transpose jits
    assert "bwd" not in eng._lazy_jits
    assert "update" not in eng._lazy_jits
    assert "transpose" not in eng._lazy_jits
    so = eng.init_opt(sp, axes)
    eng.train_step(sp, so, _batch(bsz=8))
    # CFG is untied: a full train step still never builds the tied-grad
    # transpose program
    assert "transpose" not in eng._lazy_jits
    assert {"fwd", "bwd", "update", "gnorm", "clip"} <= set(eng._lazy_jits)


@pytest.mark.slow
@pytest.mark.parametrize("pipeline_type", ["gpipe", "pipedream_flush"])
def test_interleaved_virtual_stages_match_single_device(pipeline_type,
                                                        cpu_devices):
    """vpp=2 over pp=2: 4 model chunks round-robin on 2 device groups
    (chunk c on group c % pp) must reproduce the single-device step —
    beyond the reference, which has no interleaved schedule."""
    cfg = CFG.model_copy(update={"num_hidden_layers": 5})
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch, cfg=cfg)
    metrics, new_params = _pipeline_step(
        cfg, params, axes, batch, cpu_devices,
        pp_deg=2, virtual_pp_deg=2, chunks=4, pipeline_type=pipeline_type,
        global_train_batch_size=16)
    assert abs(metrics["loss"] - ref_loss) < 2e-5
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


@pytest.mark.slow
def test_interleaved_tied_embeddings(cpu_devices):
    """Tied wte with vpp=2: embed chunk and head chunk live on DIFFERENT
    physical groups (chunk 0 -> group 0, chunk 3 -> group 1) and the grad
    reconciliation still keeps the copies in sync."""
    cfg = ModelArgs(
        hidden_size=64, num_hidden_layers=4, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32, seq_length=16,
        tie_word_embeddings=True, make_vocab_size_divisible_by=1)
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch, cfg=cfg)
    args = CoreArgs(model=cfg.model_dump(), train=TRAIN.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.virtual_pp_deg = 2
    args.parallel.chunks = 4
    args.parallel.global_train_batch_size = 16
    hpc = get_hybrid_parallel_config(args, 8)
    assert len(hpc.pp_division) == 4
    eng = PipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, batch)
    assert abs(metrics["loss"] - ref_loss) < 2e-5
    wte = np.asarray(jax.device_get(new_sp[0]["embed"]["wte"]))
    whead = np.asarray(jax.device_get(new_sp[-1]["head"]["whead"]))
    np.testing.assert_allclose(wte, whead.T, rtol=1e-6, atol=1e-7)
    merged = eng.merge_params(new_sp)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(merged)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")
