"""Regression: compiled SPMD programs must not trip XLA's "Involuntary full
rematerialization" (spmd_partitioner.cc) — the partitioner's last-resort
replicate-then-reshard. Round 3/4 hit it on the ZeRO-3 embedding lookup in
the cp-ring regime (hidden-sharded gather output vs batch/seq activation
layout); `parallel/spmd.py make_embed_use_constraint` states the
gather-before-use relocation explicitly (reference redistribute.py:345-415).

The warning is C++ stderr from the XLA partitioner, invisible to Python, so
the check compiles the plans in a subprocess and greps its stderr.
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = [pytest.mark.core, pytest.mark.distributed]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_COMPILE_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    MODEL = {{
        "hidden_size": 64, "num_hidden_layers": 2,
        "num_attention_heads": 4, "vocab_size": 256,
        "seq_length": 16, "max_position_embeddings": 32,
        "hidden_act": "swiglu", "normalization": "rmsnorm",
        "position_embedding_type": "rope", "tie_word_embeddings": False,
        "add_bias_linear": False, "add_qkv_bias": False,
        "make_vocab_size_divisible_by": 1, "ffn_hidden_size": 128,
    }}
    # the two regimes that tripped the full-remat warning in r03/r04, plus
    # the heterogeneous zero2/zero3 mix of the searched-plan shape
    PARALLEL = [
        {{"global_tp_deg": 1, "default_dp_type": "zero3", "vocab_tp": 1,
          "global_checkpoint": 1, "global_train_batch_size": 16,
          "global_cp_deg": 2}},
        {{"global_tp_deg": 2, "default_dp_type": "zero3", "vocab_tp": 2,
          "global_checkpoint": 1, "global_train_batch_size": 16}},
    ]
    mesh = build_mesh(8, 1, devices=jax.devices("cpu")[:8])
    for par in PARALLEL:
        args = CoreArgs.model_validate({{"model": MODEL, "parallel": par}})
        hpc = get_hybrid_parallel_config(args, 8)
        params, axes = init_causal_lm(jax.random.key(0), args.model)
        tx = make_optimizer(args.train)
        step, pspecs, ospecs, batch_shd = make_spmd_train_step(
            args.model, hpc, mesh, axes, tx, params,
            compute_dtype=jnp.float32, donate=False)
        shapes = lambda t: jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)
        opt_shape = jax.eval_shape(tx.init, params)
        B, S = hpc.global_bsz, args.model.seq_length
        batch = {{"tokens": jax.ShapeDtypeStruct((B, S), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((B, S), jnp.int32),
                 "loss_mask": jax.ShapeDtypeStruct((B, S), jnp.float32)}}
        step.lower(shapes(params), shapes(opt_shape), batch).compile()
        print("compiled", par.get("global_cp_deg", 1), flush=True)
    print("ALL_COMPILED", flush=True)
""")


def test_no_involuntary_full_rematerialization(tmp_path):
    script = tmp_path / "compile_plans.py"
    script.write_text(_COMPILE_SCRIPT.format(repo=REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the script pins its own platform
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "ALL_COMPILED" in proc.stdout
    assert "Involuntary full rematerialization" not in proc.stderr, (
        "XLA SPMD partitioner fell back to replicate-then-reshard:\n"
        + "\n".join(ln for ln in proc.stderr.splitlines()
                    if "rematerialization" in ln)[:4000])
