"""Packed-sample document masking (reference reset_position_ids /
reset_attention_mask, Megatron get_ltor_masks_and_position_ids): with both
flags on, a document inside a packed sequence must see EXACTLY the logits it
would get alone."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs
from hetu_galvatron_tpu.models.builder import forward_causal_lm, init_causal_lm
from hetu_galvatron_tpu.runtime.dataloader import packed_doc_fields

pytestmark = pytest.mark.model

EOD = 63


def _cfg(**kw):
    base = dict(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32, seq_length=16,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def test_packed_doc_fields_layout():
    tokens = np.array([[5, 6, EOD, 7, 8, 9, EOD, 1]])
    f = packed_doc_fields(tokens, EOD, reset_position_ids=True,
                          reset_attention_mask=True)
    np.testing.assert_array_equal(f["segment_ids"],
                                  [[0, 0, 0, 1, 1, 1, 1, 2]])
    np.testing.assert_array_equal(f["position_ids"],
                                  [[0, 1, 2, 0, 1, 2, 3, 0]])


@pytest.mark.parametrize("pos_type", ["rope", "learned"])
def test_second_document_isolated(pos_type):
    """Logits for the tokens of doc 2 inside a packed sample equal the
    logits of doc 2 run alone (same positions, no cross-doc attention)."""
    cfg = _cfg(position_embedding_type=pos_type)
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    rs = np.random.RandomState(0)
    doc1 = rs.randint(0, 40, 5).tolist() + [EOD]
    doc2 = rs.randint(0, 40, 6).tolist()
    packed = np.asarray([doc1 + doc2], np.int32)  # [1, 12]
    fields = packed_doc_fields(packed, EOD, reset_position_ids=True,
                               reset_attention_mask=True)
    full = forward_causal_lm(
        params, jnp.asarray(packed), cfg, compute_dtype=jnp.float32,
        position_ids=jnp.asarray(fields["position_ids"]),
        segment_ids=jnp.asarray(fields["segment_ids"]))
    alone = forward_causal_lm(params, jnp.asarray([doc2], jnp.int32), cfg,
                              compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(full)[0, len(doc1):],
                               np.asarray(alone)[0], rtol=2e-5, atol=2e-5)
    # and WITHOUT the flags, cross-document leakage makes them differ
    leaky = forward_causal_lm(params, jnp.asarray(packed), cfg,
                              compute_dtype=jnp.float32)
    assert np.abs(np.asarray(leaky)[0, len(doc1):]
                  - np.asarray(alone)[0]).max() > 1e-3


def test_train_e2e_with_packing_flags(tmp_path, capsys):
    """preprocess -> indexed dataset -> train with both reset flags through
    the CLI: the spmd path AND the pipeline engine (pp=2)."""
    import os

    from hetu_galvatron_tpu.cli.preprocess_data import main as prep_main
    from hetu_galvatron_tpu.cli.train_dist import main as train_main

    zoo = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    src = tmp_path / "c.txt"
    src.write_text("".join(f"short doc {i}\n" for i in range(30)))
    prefix = str(tmp_path / "c")
    assert prep_main([str(src), prefix]) == 0
    common = [os.path.join(zoo, "gpt2-small.yaml"),
              "model.hidden_size=32", "model.num_hidden_layers=2",
              "model.num_attention_heads=2", "model.vocab_size=257",
              "model.seq_length=8", "model.max_position_embeddings=16",
              "model.make_vocab_size_divisible_by=1",
              "model.use_flash_attn=false",
              "train.train_iters=2", "parallel.mixed_precision=fp32",
              "parallel.global_train_batch_size=8",
              "data.dataset=indexed", f"data.data_path=[{prefix}]",
              "data.reset_position_ids=true",
              "data.reset_attention_mask=true"]
    assert train_main(common) == 0
    assert "training done" in capsys.readouterr().out
    assert train_main(common + ["parallel.pp_deg=2",
                                "parallel.chunks=2"]) == 0
    assert "training done" in capsys.readouterr().out


@pytest.mark.parametrize("schedule", ["gpipe", "pipedream_flush"])
def test_packed_docs_pp2_matches_pp1(schedule, cpu_devices):
    """Packed position_ids/segment_ids through the pipeline engine: pp=2
    loss and updated params match the single-device step (the reference
    ships these fields via multi-tensor p2p, pipeline.py:1140; here the
    controller places them per stage)."""
    import optax

    from hetu_galvatron_tpu.models.builder import causal_lm_loss
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine
    from hetu_galvatron_tpu.core.args_schema import TrainArgs

    cfg = _cfg(num_hidden_layers=4)
    params, axes = init_causal_lm(jax.random.key(1), cfg)
    rs = np.random.RandomState(3)
    B, S = 8, cfg.seq_length
    tokens = rs.randint(0, 40, (B, S + 1)).astype(np.int32)
    tokens[:, 5] = EOD  # several docs per row
    tokens[:, 11] = EOD
    batch = {"tokens": tokens[:, :-1], "labels": tokens[:, 1:],
             "loss_mask": (tokens[:, 1:] != EOD).astype(np.float32)}
    fields = packed_doc_fields(batch["tokens"], EOD,
                               reset_position_ids=True,
                               reset_attention_mask=True)
    batch.update(fields)

    train = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                      lr_decay_style="constant", lr_warmup_iters=0)
    jb = jax.tree.map(jnp.asarray, batch)
    tx = make_optimizer(train)
    loss_fn = lambda p: causal_lm_loss(p, jb, cfg, compute_dtype=jnp.float32)
    ref_loss, grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(grads, tx.init(params), params)
    ref_params = optax.apply_updates(params, upd)

    args = CoreArgs(model=cfg.model_dump(), train=train.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.chunks = 2
    args.parallel.pipeline_type = schedule
    args.parallel.global_train_batch_size = B
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, batch)
    assert abs(metrics["loss"] - float(ref_loss)) < 2e-5
    new_params = eng.merge_params(new_sp)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=str(pa))
