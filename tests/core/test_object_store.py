"""S3-backed corpus prefixes (reference indexed_dataset.py:506 S3 support):
download-once local caching with an injected client — no boto3 needed for
the tests; the real default client demands boto3 with an actionable error."""

import os

import numpy as np
import pytest

from hetu_galvatron_tpu.data.object_store import (
    is_object_path,
    localize_prefix,
)

pytestmark = pytest.mark.core


class FakeS3:
    """download_file-compatible client backed by a local directory."""

    def __init__(self, root):
        self.root = str(root)
        self.calls = []

    def download_file(self, bucket, key, path):
        self.calls.append((bucket, key))
        src = os.path.join(self.root, bucket, key)
        if not os.path.exists(src):
            raise IOError(f"NoSuchKey: {bucket}/{key}")
        with open(src, "rb") as f, open(path, "wb") as out:
            out.write(f.read())


def _make_remote_corpus(root):
    from hetu_galvatron_tpu.data.indexed_dataset import write_indexed_dataset

    docs = [np.full(20, d, np.int32) for d in range(6)]
    prefix = os.path.join(str(root), "bkt", "corpora", "c")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    write_indexed_dataset(prefix, docs)
    with open(prefix + ".meta.json", "w") as f:
        f.write('{"vocab_size": 64, "eod_id": null}')
    return prefix


def test_localize_downloads_once_and_caches(tmp_path):
    _make_remote_corpus(tmp_path / "remote")
    client = FakeS3(tmp_path / "remote")
    cache = tmp_path / "cache"
    local = localize_prefix("s3://bkt/corpora/c", cache_dir=str(cache),
                            client=client)
    assert os.path.exists(local + ".idx")
    assert os.path.exists(local + ".bin")
    assert os.path.exists(local + ".meta.json")
    n_calls = len(client.calls)
    assert n_calls == 3
    # second call is a pure cache hit
    again = localize_prefix("s3://bkt/corpora/c", cache_dir=str(cache),
                            client=client)
    assert again == local
    assert len(client.calls) == n_calls

    from hetu_galvatron_tpu.data.indexed_dataset import IndexedDataset

    ds = IndexedDataset(local)
    assert len(ds) == 6 and ds.total_tokens == 120


def test_localize_missing_required_and_optional(tmp_path):
    from hetu_galvatron_tpu.data.indexed_dataset import write_indexed_dataset

    # corpus WITHOUT the optional meta sidecar: localization succeeds
    prefix = os.path.join(str(tmp_path), "remote", "bkt", "x")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    write_indexed_dataset(prefix, [np.arange(10, dtype=np.int32)])
    client = FakeS3(tmp_path / "remote")
    local = localize_prefix("s3://bkt/x", cache_dir=str(tmp_path / "c1"),
                            client=client)
    assert os.path.exists(local + ".idx")
    assert not os.path.exists(local + ".meta.json")
    # missing .bin/.idx is a loud FileNotFoundError
    with pytest.raises(FileNotFoundError, match="gone.idx"):
        localize_prefix("s3://bkt/gone", cache_dir=str(tmp_path / "c2"),
                        client=client)
    # no torn temp files left behind
    leftovers = [f for _, _, fs in os.walk(tmp_path / "c2") for f in fs
                 if f.startswith(".dl_")]
    assert not leftovers


def test_is_object_path_and_default_client_error(tmp_path):
    assert is_object_path("s3://b/k")
    assert not is_object_path("/local/prefix")
    with pytest.raises(ValueError, match="malformed"):
        localize_prefix("s3://nokey", client=FakeS3(tmp_path))
    # the default client path demands boto3 with remediation (not bundled)
    with pytest.raises(RuntimeError, match="boto3"):
        localize_prefix("s3://b/k", cache_dir=str(tmp_path / "c"))


def test_data_iterator_localizes_s3_paths(tmp_path, monkeypatch):
    """get_data_iterator transparently localizes s3:// data paths."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.data import object_store
    from hetu_galvatron_tpu.runtime.dataloader import get_data_iterator

    _make_remote_corpus(tmp_path / "remote")
    client = FakeS3(tmp_path / "remote")
    monkeypatch.setattr(object_store, "_default_client", lambda: client)
    monkeypatch.setenv("HGTPU_OBJECT_CACHE", str(tmp_path / "cache"))
    args = CoreArgs.model_validate({
        "model": {"hidden_size": 32, "num_hidden_layers": 1,
                  "num_attention_heads": 2, "vocab_size": 64,
                  "seq_length": 8, "max_position_embeddings": 16,
                  "make_vocab_size_divisible_by": 1},
        "parallel": {"global_train_batch_size": 4},
        "data": {"dataset": "indexed",
                 "data_path": ["s3://bkt/corpora/c"]},
    })
    it = get_data_iterator(args)
    batch = next(it)
    assert batch["tokens"].shape == (4, 8)
    assert client.calls  # it really went through the object store


def test_warm_cache_needs_no_client(tmp_path):
    """A fully-populated cache must localize without touching (or even
    constructing) a client — TPU images without boto3 but with pre-staged
    shards train fine."""
    _make_remote_corpus(tmp_path / "remote")
    client = FakeS3(tmp_path / "remote")
    cache = str(tmp_path / "cache")
    localize_prefix("s3://bkt/corpora/c", cache_dir=cache, client=client)
    # no client at all now: default-client construction would raise on
    # this boto3-less image, so reaching it means the cache was ignored
    local = localize_prefix("s3://bkt/corpora/c", cache_dir=cache)
    assert os.path.exists(local + ".bin")


@pytest.mark.robustness
def test_transient_fetch_retries_with_backoff(tmp_path, monkeypatch):
    """Transient errors (throttling, 5xx) retry through the shared
    backoff policy instead of surfacing immediately; absence (404-class)
    stays non-retryable and fails fast."""
    from hetu_galvatron_tpu.utils import retrying

    sleeps = []
    monkeypatch.setattr(retrying, "_default_sleep", sleeps.append)

    class FlakyS3(FakeS3):
        def __init__(self, root, failures):
            super().__init__(root)
            self.failures = failures

        def download_file(self, bucket, key, path):
            if self.failures > 0:
                self.failures -= 1
                raise IOError("SlowDown: rate exceeded")
            return super().download_file(bucket, key, path)

    _make_remote_corpus(tmp_path / "remote")
    client = FlakyS3(tmp_path / "remote", failures=2)
    local = localize_prefix("s3://bkt/corpora/c",
                            cache_dir=str(tmp_path / "cache"), client=client)
    assert os.path.exists(local + ".idx")
    assert len(sleeps) == 2  # two throttles -> two jittered backoffs
    assert all(s >= 0 for s in sleeps)

    # absence fails fast: exactly one attempt, no sleeps
    sleeps.clear()
    counting = FakeS3(tmp_path / "remote")
    with pytest.raises(FileNotFoundError, match="gone.idx"):
        localize_prefix("s3://bkt/gone", cache_dir=str(tmp_path / "c2"),
                        client=counting)
    assert counting.calls == [("bkt", "gone.idx")]
    assert not sleeps


@pytest.mark.robustness
def test_fetch_retry_budget_exhausts_loudly(tmp_path, monkeypatch):
    from hetu_galvatron_tpu.utils import retrying

    monkeypatch.setattr(retrying, "_default_sleep", lambda s: None)

    class AlwaysThrottled(FakeS3):
        def download_file(self, bucket, key, path):
            self.calls.append((bucket, key))
            raise IOError("SlowDown: rate exceeded")

    _make_remote_corpus(tmp_path / "remote")
    client = AlwaysThrottled(tmp_path / "remote")
    with pytest.raises(FileNotFoundError, match="SlowDown"):
        localize_prefix("s3://bkt/corpora/c",
                        cache_dir=str(tmp_path / "cache"), client=client)
    assert len(client.calls) == 3  # the full (bounded) attempt budget


def test_transient_meta_error_is_loud(tmp_path, monkeypatch):
    """A non-absence failure on the OPTIONAL meta sidecar must raise, not
    silently disable eod masking / vocab checks."""
    from hetu_galvatron_tpu.utils import retrying

    monkeypatch.setattr(retrying, "_default_sleep", lambda s: None)

    class ThrottledS3(FakeS3):
        def download_file(self, bucket, key, path):
            if key.endswith(".meta.json"):
                raise IOError("SlowDown: rate exceeded")
            return super().download_file(bucket, key, path)

    _make_remote_corpus(tmp_path / "remote")
    with pytest.raises(RuntimeError, match="sidecar"):
        localize_prefix("s3://bkt/corpora/c",
                        cache_dir=str(tmp_path / "cache"),
                        client=ThrottledS3(tmp_path / "remote"))


def test_absent_error_classification():
    """ADVICE r5: boto3 ClientErrors classify via the structured error
    code; a transient error whose TEXT contains 'not found' (DNS) must not
    read as object-absent."""
    from hetu_galvatron_tpu.data.object_store import _is_absent_error

    class FakeClientError(Exception):
        def __init__(self, code):
            super().__init__(f"An error occurred ({code})")
            self.response = {"Error": {"Code": code}}

    assert _is_absent_error(FakeClientError("NoSuchKey"))
    assert _is_absent_error(FakeClientError("404"))
    assert _is_absent_error(FakeClientError("NoSuchBucket"))
    assert not _is_absent_error(FakeClientError("SlowDown"))
    assert not _is_absent_error(FakeClientError("AccessDenied"))

    # a botocore exception WITHOUT an absence code is never absence, even
    # when its stringification contains an absence marker (DNS failures)
    class EndpointError(Exception):
        pass

    EndpointError.__module__ = "botocore.exceptions"
    assert not _is_absent_error(
        EndpointError('Could not connect: host not found'))
    # plain injected test clients keep the string heuristic
    assert _is_absent_error(IOError("NoSuchKey: bkt/x.meta.json"))
    assert not _is_absent_error(IOError("SlowDown: rate exceeded"))


def test_absent_meta_negatively_cached(tmp_path):
    """ADVICE r5: a confirmed-absent meta sidecar writes a
    .meta.json.absent marker, so a fully-warmed .idx/.bin cache localizes
    WITHOUT constructing an S3 client (boto3-less TPU images)."""
    from hetu_galvatron_tpu.data.indexed_dataset import write_indexed_dataset

    prefix = os.path.join(str(tmp_path), "remote", "bkt", "x")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    write_indexed_dataset(prefix, [np.arange(10, dtype=np.int32)])
    client = FakeS3(tmp_path / "remote")
    cache = str(tmp_path / "cache")
    local = localize_prefix("s3://bkt/x", cache_dir=cache, client=client)
    assert os.path.exists(local + ".meta.json.absent")
    n_calls = len(client.calls)
    # warm cache: no client passed — default-client construction would
    # raise RuntimeError(boto3) on this image, so success proves the
    # absence marker short-circuits the probe
    again = localize_prefix("s3://bkt/x", cache_dir=cache)
    assert again == local
    assert len(client.calls) == n_calls
    # the marker is purged with the pair on a version-mismatch refetch,
    # so a re-uploaded corpus that GAINED a sidecar is noticed
    with open(local + ".bin", "ab") as f:
        f.write(b"\x00" * 64)
    with open(prefix + ".meta.json", "w") as f:
        f.write('{"vocab_size": 16, "eod_id": null}')
    localize_prefix("s3://bkt/x", cache_dir=cache, client=client)
    assert os.path.exists(local + ".meta.json")
    assert not os.path.exists(local + ".meta.json.absent")


def test_mixed_version_pair_is_refetched(tmp_path):
    """A torn cache (old .idx with a differently-sized .bin) is purged and
    refetched as a unit instead of serving garbage tokens."""
    _make_remote_corpus(tmp_path / "remote")
    client = FakeS3(tmp_path / "remote")
    cache = str(tmp_path / "cache")
    local = localize_prefix("s3://bkt/corpora/c", cache_dir=cache,
                            client=client)
    # corrupt the cached bin (simulates idx from an older corpus version)
    with open(local + ".bin", "ab") as f:
        f.write(b"\x00" * 64)
    local2 = localize_prefix("s3://bkt/corpora/c", cache_dir=cache,
                             client=client)
    from hetu_galvatron_tpu.data.indexed_dataset import IndexedDataset

    ds = IndexedDataset(local2)
    assert ds.total_tokens == 120  # refetched, consistent again
