"""Elastic topology-change resume, end to end through cli/train_dist:
detect (world mismatch at the committed checkpoint) -> re-search (or
degree-adapt) -> HBM budget-gate -> reshard -> replay the exact data
position. The acceptance drill kills an 8-device tp2 x dp2 x pp2 run with
a REAL SIGTERM and resumes it on 4 devices through the offline search."""

import glob
import json
import os

import numpy as np
import pytest

pytestmark = [pytest.mark.distributed, pytest.mark.robustness,
              pytest.mark.elastic]

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")
FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")

TINY = [
    "model.hidden_size=32", "model.num_hidden_layers=4",
    "model.num_attention_heads=2", "model.vocab_size=64",
    "model.seq_length=8", "model.max_position_embeddings=16",
    "model.make_vocab_size_divisible_by=1",
    "train.train_iters=6", "parallel.mixed_precision=fp32",
    "parallel.global_train_batch_size=8",
]

SEARCH_FIXTURES = [
    "search.memory_constraint=36", "search.default_dp_type=zero2",
    "search.pipeline_type=pipedream_flush",
    "search.async_grad_reduce=false", "search.sequence_parallel=true",
    "search.time_profile_mode=sequence",
    "search.memory_profile_mode=sequence",
    "search.max_tp_deg=2", "search.disable_ulysses=1",
    f"search.time_profiling_path={FIXTURES}/computation_profiling_bf16_llama2-7b_all.json",
    f"search.memory_profiling_path={FIXTURES}/memory_profiling_bf16_llama2-7b_all.json",
    f"search.allreduce_bandwidth_config_path={FIXTURES}/allreduce_bandwidth_1nodes_8gpus_per_node.json",
    f"search.p2p_bandwidth_config_path={FIXTURES}/p2p_bandwidth_1nodes_8gpus_per_node.json",
    f"search.overlap_coe_path={FIXTURES}/overlap_coefficient.json",
    f"search.sp_time_path={FIXTURES}/sp_time_1nodes_8gpus_per_node.json",
]


def _args(extra):
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    return args_from_cli([os.path.join(ZOO, "gpt2-small.yaml")] + TINY +
                         extra, mode="train_dist")


def test_elastic_resume_degree_adapt_replays_exactly(tmp_path):
    """2 -> 1 device resume with NO search profiles configured: the
    stored plan's degrees adapt (dp2 -> dp1), the checkpoint reshards,
    and the resumed trajectory is deterministic — a second fresh resume
    from the same committed checkpoint reproduces it step for step (the
    exact data position replayed)."""
    from hetu_galvatron_tpu.cli.train_dist import train

    save = str(tmp_path / "ckpt")
    out2 = train(_args([f"ckpt.save={save}", "ckpt.save_interval=2",
                        "train.train_iters=4", "parallel.num_devices=2"]))
    assert out2["exit_code"] is None and len(out2["losses"]) == 4
    assert os.path.isdir(os.path.join(save, "step_4"))

    resume_extra = [f"ckpt.load={save}", "parallel.num_devices=1",
                    "train.train_iters=6"]
    outA = train(_args(resume_extra))
    assert outA["exit_code"] is None
    assert len(outA["losses"]) == 2  # resumed at 4, ran 4..5
    assert outA["goodput"]["totals"]["reshard"] > 0.0

    outB = train(_args(resume_extra))  # the "fresh run from the same ckpt"
    np.testing.assert_allclose(outA["losses"], outB["losses"],
                               rtol=0, atol=0)
    assert all(np.isfinite(outA["losses"]))


def test_elastic_rejected_plan_exits_17_with_flight_dump(tmp_path):
    """An OOM-rejected elastic target plan is TERMINAL: train() returns
    exit code 17 (failed-result-validation — it reproduces on every
    restart, so the supervisor must not loop) and leaves a parseable
    flight-recorder dump naming the rejection."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.rerun_machine import (
        EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    )

    save = str(tmp_path / "ckpt")
    train(_args([f"ckpt.save={save}", "ckpt.save_interval=1",
                 "train.train_iters=1", "parallel.num_devices=2"]))
    fdir = str(tmp_path / "flight")
    out = train(_args([
        f"ckpt.load={save}", "parallel.num_devices=1",
        # an impossibly small budget: every adapted plan is OOM-rejected
        "search.hbm_budget_gb=0.0001",
        f"observability.flight_dir={fdir}"]))
    assert out["exit_code"] == EXIT_CODE_FAILED_ON_RESULT_VALIDATION
    assert out["losses"] == []
    assert out["flight_dumps"], "no flight dump for the rejected re-plan"
    with open(out["flight_dumps"][0]) as f:
        dump = json.load(f)
    assert dump["reason"] == "elastic_plan_rejected"
    events = [e for e in dump["events"] if e.get("name") == "elastic_replan"]
    assert events and "HBM budget" in events[0]["data"]["reason"]


def test_elastic_reshard_failure_exits_17_not_crash(tmp_path, monkeypatch):
    """A deterministic RESHARD failure (typed ReshardError — MoE opt
    state, shape drift, wrong optimizer) gets the same terminal contract
    as a rejected re-plan: exit 17 + a flight dump, never an exception
    the supervisor would crash-restart-loop on."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime import reshard
    from hetu_galvatron_tpu.runtime.rerun_machine import (
        EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    )

    save = str(tmp_path / "ckpt")
    train(_args([f"ckpt.save={save}", "ckpt.save_interval=1",
                 "train.train_iters=1", "parallel.num_devices=2"]))

    def boom(*a, **k):
        raise reshard.ReshardError("injected reshard failure")

    monkeypatch.setattr(reshard, "resume_elastic", boom)
    fdir = str(tmp_path / "flight")
    out = train(_args([f"ckpt.load={save}", "parallel.num_devices=1",
                       f"observability.flight_dir={fdir}"]))
    assert out["exit_code"] == EXIT_CODE_FAILED_ON_RESULT_VALIDATION
    assert out["losses"] == []  # zero iterations ran on untrusted state
    assert out["flight_dumps"]
    with open(out["flight_dumps"][0]) as f:
        dump = json.load(f)
    assert dump["reason"] == "elastic_reshard_failed"


def test_elastic_drill_kill8_resume4_searched(tmp_path):
    """THE acceptance drill: SIGTERM-kill an 8-device tp2 x dp2 x pp2 run
    mid-training; resume on 4 devices. The supervisor-driven resume
    re-searches a plan for the new topology (the real offline search over
    the profiled fixtures), memory-gates it, reshards the committed
    checkpoint, and its loss trajectory is step-for-step equal to a fresh
    4-device run started from the same committed checkpoint."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.supervisor import (
        EXIT_CODE_CHECKPOINT_AND_EXIT,
    )

    save = str(tmp_path / "ckpt")
    plan8 = ["parallel.pp_deg=2", "parallel.global_tp_deg=2",
             "parallel.chunks=2", "parallel.pipeline_type=pipedream_flush",
             "parallel.vocab_tp=2"]
    out8 = train(_args(plan8 + [
        f"ckpt.save={save}",
        "rerun.inject_kind=preempt", "rerun.inject_at_iter=2"]))
    assert out8["exit_code"] == EXIT_CODE_CHECKPOINT_AND_EXIT
    assert len(out8["losses"]) == 3  # iters 0..2, then the kill
    assert os.path.isdir(os.path.join(save, "step_3"))

    # the restarted attempt sees HALF the world: detect -> re-search ->
    # gate -> reshard -> replay (what run_with_restarts would invoke; the
    # world change itself resets its budget, pinned in test_supervisor)
    resume_extra = plan8 + [f"ckpt.load={save}", "parallel.num_devices=4"
                            ] + SEARCH_FIXTURES
    outA = train(_args(resume_extra))
    assert outA["exit_code"] is None
    assert len(outA["losses"]) == 3  # resumed at 3, finished 3..5
    assert all(np.isfinite(outA["losses"]))
    assert outA["goodput"]["totals"]["reshard"] > 0.0

    # the re-searched plan landed next to the checkpoint root and
    # describes a 4-device world
    plans = glob.glob(os.path.join(save, "elastic_plan_4dev",
                                   "galvatron_config_*.json"))
    assert plans, "elastic re-search wrote no plan"
    plan = json.load(open(plans[0]))
    assert plan["pp_deg"] * int(str(plan["tp_sizes_enc"]).split(",")[0]) \
        <= 4

    # fresh 4-device run from the SAME committed checkpoint: step-for-step
    # equal (exact data position replayed; same searched plan)
    outB = train(_args(resume_extra))
    np.testing.assert_allclose(outA["losses"], outB["losses"],
                               rtol=0, atol=0)


def test_elastic_drill_kill4_resume8_scale_up_searched(tmp_path):
    """Scale-UP drill (ROADMAP elastic follow-on): SIGTERM-kill a
    4-device tp2 x pp2 run mid-training and resume on DOUBLE the world
    (8 devices) through the same detect -> re-search -> gate -> reshard
    -> replay path as the 8 -> 4 drill — N -> 2N rides the same code but
    was unexercised. The re-searched plan must describe an 8-device
    world and the resumed trajectory must be exactly reproducible from
    the committed checkpoint."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.supervisor import (
        EXIT_CODE_CHECKPOINT_AND_EXIT,
    )

    save = str(tmp_path / "ckpt")
    plan4 = ["parallel.pp_deg=2", "parallel.global_tp_deg=2",
             "parallel.chunks=2", "parallel.pipeline_type=pipedream_flush",
             "parallel.vocab_tp=2", "parallel.num_devices=4"]
    out4 = train(_args(plan4 + [
        f"ckpt.save={save}",
        "rerun.inject_kind=preempt", "rerun.inject_at_iter=2"]))
    assert out4["exit_code"] == EXIT_CODE_CHECKPOINT_AND_EXIT
    assert len(out4["losses"]) == 3  # iters 0..2, then the kill
    assert os.path.isdir(os.path.join(save, "step_3"))

    # the restarted attempt sees DOUBLE the world: detect -> re-search ->
    # gate -> reshard -> replay
    resume_extra = [f"ckpt.load={save}", "parallel.pp_deg=2",
                    "parallel.global_tp_deg=2", "parallel.chunks=2",
                    "parallel.pipeline_type=pipedream_flush",
                    "parallel.vocab_tp=2", "parallel.num_devices=8",
                    ] + SEARCH_FIXTURES
    outA = train(_args(resume_extra))
    assert outA["exit_code"] is None
    assert len(outA["losses"]) == 3  # resumed at 3, finished 3..5
    assert all(np.isfinite(outA["losses"]))
    assert outA["goodput"]["totals"]["reshard"] > 0.0

    # the re-searched plan landed next to the checkpoint root and
    # actually uses the grown world (8 devices)
    plans = glob.glob(os.path.join(save, "elastic_plan_8dev",
                                   "galvatron_config_*.json"))
    assert plans, "elastic re-search wrote no scale-up plan"
    plan = json.load(open(plans[0]))
    tp0 = int(str(plan["tp_sizes_enc"]).split(",")[0])
    assert plan["pp_deg"] * tp0 <= 8

    # fresh 8-device run from the SAME committed checkpoint:
    # step-for-step equal (exact data position replayed)
    outB = train(_args(resume_extra))
    np.testing.assert_allclose(outA["losses"], outB["losses"],
                               rtol=0, atol=0)
