"""Goodput + flight-recorder fault drills on the CPU mesh: a SIGTERM
preemption must leave (1) goodput counters that survive the supervisor
restart through the checkpoint train_state payload — nonzero
restart-lost time, goodput < 1 but above a floor — and (2) a parseable
flight-recorder dump from the trapped signal; summarize renders both."""

import io
import json
import os

import pytest

pytestmark = [pytest.mark.distributed, pytest.mark.robustness]

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")

TINY = [
    "model.hidden_size=32", "model.num_hidden_layers=2",
    "model.num_attention_heads=2", "model.vocab_size=64",
    "model.seq_length=8", "model.max_position_embeddings=16",
    "model.make_vocab_size_divisible_by=1",
    "train.train_iters=6", "parallel.mixed_precision=fp32",
    "parallel.global_train_batch_size=8",
]


def _args(extra):
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    return args_from_cli([os.path.join(ZOO, "gpt2-small.yaml")] + TINY +
                         extra, mode="train_dist")


def _supervised_train(args):
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.supervisor import run_with_restarts

    outs = []

    def attempt():
        if args.ckpt.save and not args.ckpt.load:
            args.ckpt.load = args.ckpt.save
        out = train(args)
        outs.append(out)
        return out.get("exit_code") or 0

    rc = run_with_restarts(attempt, max_restarts=3, base_delay=0.0,
                           sleep=lambda s: None, log=lambda m: None)
    return rc, outs


def test_preempt_drill_goodput_survives_restart_and_flight_dump(tmp_path):
    metrics = str(tmp_path / "metrics.jsonl")
    rc, outs = _supervised_train(_args([
        f"ckpt.save={tmp_path / 'ckpt'}",
        "observability.enabled=true",
        f"observability.metrics_path={metrics}",
        "rerun.inject_kind=preempt", "rerun.inject_at_iter=2"]))
    assert rc == 0
    assert len(outs) == 2  # preempted attempt + resumed attempt

    # the preempted attempt's trapped SIGTERM dumped a flight record
    assert len(outs[0]["flight_dumps"]) == 1
    fpath = outs[0]["flight_dumps"][0]
    assert os.path.basename(fpath).startswith("flight_")
    with open(fpath) as f:
        flight = json.load(f)  # parseable (atomic tmp+rename)
    assert flight["kind"] == "flight_recorder"
    assert flight["reason"].startswith("signal:")
    assert any(e["data"].get("ev") == "run_start" for e in flight["events"])

    # goodput survived the restart: the resumed tracker merged the
    # committed totals (attempt 1's productive steps), booked the
    # commit-to-resume wall gap as restart-lost, and counts the restart
    gp = outs[1]["goodput"]
    assert gp["restarts_survived"] == 1
    assert gp["totals"]["restart_lost"] > 0.0
    assert gp["totals"]["productive_step"] > 0.0
    assert gp["totals"]["recompile"] > 0.0
    assert 0.0 < gp["frac"] < 1.0
    # ... and covers BOTH attempts' productive work (attempt 1 trained
    # iters 1..2 after its compile step, attempt 2 iters 4..5), so the
    # merged productive time exceeds what attempt 2 alone accrued
    assert gp["totals"]["productive_step"] > \
        outs[0]["goodput"]["totals"]["productive_step"] / 2

    # goodput/* gauges landed in the metrics stream and summarize
    # renders the partition
    from hetu_galvatron_tpu.cli.summarize import summarize

    buf = io.StringIO()
    headline = summarize(metrics, out=buf)
    text = buf.getvalue()
    assert "-- goodput --" in text
    assert "restart_lost" in text and "goodput" in text
    assert 0.0 < headline["goodput_frac"] < 1.0
    assert headline["goodput/restart_lost_s"] > 0.0

    # the flight dump renders too
    fbuf = io.StringIO()
    fh = summarize(fpath, out=fbuf)
    assert fh["flight_reason"].startswith("signal:")


def test_clean_run_has_full_goodput_and_no_flight_dump(tmp_path):
    """No fault: nothing restart-lost, goodput is the productive share
    (compile time keeps it below 1), and no flight artifact appears."""
    from hetu_galvatron_tpu.cli.train_dist import train

    metrics = str(tmp_path / "metrics.jsonl")
    out = train(_args([
        "observability.enabled=true",
        f"observability.metrics_path={metrics}"]))
    assert out["exit_code"] is None and len(out["losses"]) == 6
    gp = out["goodput"]
    assert gp["totals"]["restart_lost"] == 0.0
    assert gp["restarts_survived"] == 0
    assert gp["totals"]["productive_step"] > 0.0
    assert 0.0 < gp["frac"] <= 1.0
    assert not [f for f in os.listdir(tmp_path)
                if f.startswith("flight_")]


def test_nan_halt_leaves_flight_dump(tmp_path):
    """The rerun machine's resume-to-disambiguate halt (a NaN drill) is
    a forensics event: the run exits 16 AND leaves a dump recording the
    halt."""
    metrics = str(tmp_path / "metrics.jsonl")
    rc, outs = _supervised_train(_args([
        f"ckpt.save={tmp_path / 'ckpt'}",
        "observability.enabled=true",
        f"observability.metrics_path={metrics}",
        "rerun.enable=true", "rerun.mode=validate_results",
        "rerun.inject_kind=nan", "rerun.inject_at_iter=2"]))
    assert rc == 0
    assert outs[0]["exit_code"] == 16
    assert len(outs[0]["flight_dumps"]) == 1
    with open(outs[0]["flight_dumps"][0]) as f:
        flight = json.load(f)
    assert flight["reason"] == "rerun_exit_16"
