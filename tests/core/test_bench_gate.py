"""Perf regression sentinel: an unchanged run must pass the gate, an
artificially regressed leg must fail it with a readable per-leg delta
report, device-mismatched candidates are skipped (not judged), and the
baseline/history plumbing round-trips through the CLI."""

import importlib.util
import io
import json
import os

import pytest

pytestmark = [pytest.mark.core, pytest.mark.observability]

ROOT = os.path.join(os.path.dirname(__file__), "..", "..")
GATE = os.path.abspath(os.path.join(ROOT, "tools", "bench_gate.py"))

spec = importlib.util.spec_from_file_location("bench_gate", GATE)
bench_gate = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench_gate)


PARSED = {"metric": "gpt2_125m_train_mfu", "value": 5.0, "unit": "% MFU",
          "tokens_per_sec": 100.0, "device": "cpu",
          "compiled_vs_host": 0.7, "tp_overlap_vs_gspmd": 0.9}


def _baseline(legs=None, device="cpu"):
    return {"device": device,
            "legs": legs or {"mfu_pct": 5.0, "tokens_per_sec": 100.0,
                             "compiled_vs_host": 0.7}}


def test_extract_legs_maps_and_filters():
    legs = bench_gate.extract_legs(PARSED)
    assert legs == {"mfu_pct": 5.0, "tokens_per_sec": 100.0,
                    "compiled_vs_host": 0.7, "tp_overlap_vs_gspmd": 0.9}
    assert bench_gate.extract_legs(None) == {}
    # non-numeric / non-positive values never become legs
    assert bench_gate.extract_legs({"value": 0, "tokens_per_sec": "n/a"}) \
        == {}


def test_unchanged_run_passes_within_threshold():
    cand = {"device": "cpu", "legs": {"mfu_pct": 4.8,
                                      "tokens_per_sec": 98.0,
                                      "compiled_vs_host": 0.73}}
    rows, ok = bench_gate.compare(_baseline(), cand, threshold=0.10)
    assert ok
    assert all(r["status"] in ("ok", "improved") for r in rows)


def test_regressed_leg_fails_direction_aware():
    # tokens_per_sec DOWN 20% is a regression; compiled_vs_host UP past
    # threshold is a regression (lower is better there)
    cand = {"device": "cpu", "legs": {"mfu_pct": 5.0,
                                      "tokens_per_sec": 80.0,
                                      "compiled_vs_host": 0.9}}
    rows, ok = bench_gate.compare(_baseline(), cand, threshold=0.10)
    assert not ok
    status = {r["leg"]: r["status"] for r in rows}
    assert status["tokens_per_sec"].startswith("REGRESSED")
    assert status["compiled_vs_host"].startswith("REGRESSED")
    assert status["mfu_pct"] == "ok"
    # the inverse moves are improvements, not regressions
    cand = {"device": "cpu", "legs": {"mfu_pct": 5.0,
                                      "tokens_per_sec": 130.0,
                                      "compiled_vs_host": 0.5}}
    rows, ok = bench_gate.compare(_baseline(), cand, threshold=0.10)
    assert ok
    assert {r["status"] for r in rows} == {"ok", "improved"}


def test_missing_leg_is_a_regression_and_new_leg_is_not():
    # VANISHED: the same-device history proves the leg used to be
    # measured — its absence from the candidate is a failure
    cand = {"device": "cpu", "legs": {"mfu_pct": 5.0,
                                      "tokens_per_sec": 100.0}}
    hist = [{"device": "cpu", "value": 5.0, "compiled_vs_host": 0.7}]
    rows, ok = bench_gate.compare(_baseline(), cand, threshold=0.10,
                                  history=hist)
    assert not ok
    assert any(r["status"].startswith("MISSING") for r in rows)
    # PENDING: a baseline leg no same-device run ever produced (a freshly
    # committed entry) must NOT fail the gate — it renders as pending
    # until the first bench round measures it
    rows, ok = bench_gate.compare(_baseline(), cand, threshold=0.10)
    assert ok
    status = {r["leg"]: r["status"] for r in rows}
    assert status["compiled_vs_host"].startswith("pending")
    # a leg only the candidate has is informational, not a failure
    base = _baseline(legs={"mfu_pct": 5.0})
    cand = {"device": "cpu", "legs": {"mfu_pct": 5.0, "flash_speedup": 2.0}}
    rows, ok = bench_gate.compare(base, cand, threshold=0.10)
    assert ok
    assert any(r["status"].startswith("new") for r in rows)


def test_device_mismatch_skips_not_judges():
    """A CPU-fallback bench must neither regress nor green-light a TPU
    baseline."""
    cand = {"device": "cpu", "legs": {"mfu_pct": 0.1,
                                      "tokens_per_sec": 1.0}}
    rows, ok = bench_gate.compare(_baseline(device="TPU v5 lite"), cand,
                                  threshold=0.10)
    assert ok  # skipped, not failed
    assert all(r["status"].startswith("skipped (device mismatch")
               for r in rows)
    # ...but an all-skipped comparison gated NOTHING: the report must say
    # NO VERDICT, not green-light the run as PASS
    buf = io.StringIO()
    bench_gate.render_report(rows, ok, candidate_name="c",
                             baseline_name="b", out=buf)
    text = buf.getvalue()
    assert "NO VERDICT" in text and "PASS" not in text


def test_history_noise_column_filters_by_device():
    hist = [dict(PARSED, tokens_per_sec=v) for v in (90.0, 110.0)]
    hist.append(dict(PARSED, device="tpu", tokens_per_sec=9999.0))
    cand = {"device": "cpu", "legs": {"tokens_per_sec": 100.0}}
    rows, ok = bench_gate.compare(_baseline(legs={"tokens_per_sec": 100.0}),
                                  cand, threshold=0.10, history=hist)
    row = next(r for r in rows if r["leg"] == "tokens_per_sec")
    assert row["history"] == (90.0, 110.0)  # other-device entry excluded


def test_render_report_per_leg_deltas(capsys):
    cand = {"device": "cpu", "legs": {"mfu_pct": 5.0,
                                      "tokens_per_sec": 80.0,
                                      "compiled_vs_host": 0.7}}
    rows, ok = bench_gate.compare(_baseline(), cand, threshold=0.10)
    buf = io.StringIO()
    bench_gate.render_report(rows, ok, candidate_name="BENCH_r06.json",
                             baseline_name="baseline", out=buf)
    text = buf.getvalue()
    assert "BENCH_r06.json vs baseline" in text
    assert "-20.0%" in text           # the per-leg delta
    assert "REGRESSED (>10%)" in text
    assert "FAIL (1 leg(s) regressed)" in text


def test_smoke_self_check():
    assert bench_gate.smoke() == 0


# ---------------------------------------------------------------------------
# CLI end-to-end over a synthetic history directory
# ---------------------------------------------------------------------------


def _hist(tmp_path, n, parsed):
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"n": n, "cmd": "bench.py", "rc": 0,
                                "tail": "", "parsed": parsed}))
    return str(path)


def test_main_end_to_end_pass_fail_and_update(tmp_path, capsys):
    hist_glob = str(tmp_path / "BENCH_r*.json")
    baseline = str(tmp_path / "baseline.json")
    _hist(tmp_path, 1, dict(PARSED, tokens_per_sec=95.0))
    _hist(tmp_path, 2, PARSED)

    # no baseline yet -> rc 2 with a pointer at --update-baseline
    assert bench_gate.main(["--history", hist_glob,
                            "--baseline", baseline]) == 2
    assert "--update-baseline" in capsys.readouterr().err

    # accept the newest entry as the baseline
    assert bench_gate.main(["--history", hist_glob, "--baseline", baseline,
                            "--update-baseline"]) == 0
    saved = json.loads(open(baseline).read())
    assert saved["device"] == "cpu"
    assert saved["legs"]["tokens_per_sec"] == 100.0
    assert saved["created_from"] == "BENCH_r02.json"
    capsys.readouterr()

    # an unchanged newer round passes
    _hist(tmp_path, 3, dict(PARSED, tokens_per_sec=97.0))
    assert bench_gate.main(["--history", hist_glob,
                            "--baseline", baseline]) == 0
    assert "bench gate: PASS" in capsys.readouterr().out

    # an artificially regressed leg fails with the delta report
    _hist(tmp_path, 4, dict(PARSED, tokens_per_sec=60.0))
    assert bench_gate.main(["--history", hist_glob,
                            "--baseline", baseline]) == 1
    out = capsys.readouterr().out
    assert "tokens_per_sec" in out and "REGRESSED" in out
    assert "-40.0%" in out
    # prior rounds show up as the noise-context column
    assert "[95, 100]" in out.replace(",000", "")  # formatting-agnostic

    # explicit --candidate takes precedence over newest-history
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps({"parsed": PARSED}))
    assert bench_gate.main(["--history", hist_glob, "--baseline", baseline,
                            "--candidate", str(cand)]) == 0


def test_main_degrades_on_garbage(tmp_path, capsys):
    hist_glob = str(tmp_path / "BENCH_r*.json")
    baseline = str(tmp_path / "baseline.json")
    # unreadable history entries are skipped; with none left, rc 2
    (tmp_path / "BENCH_r01.json").write_text("{torn")
    assert bench_gate.main(["--history", hist_glob,
                            "--baseline", baseline]) == 2
    # a history entry whose bench never completed (no legs) gates nothing
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "parsed": {"error": "tpu_unavailable", "rc": 1}}))
    assert bench_gate.main(["--history", hist_glob,
                            "--baseline", baseline]) == 0
    assert "nothing to gate" in capsys.readouterr().err


def test_build_bench_candidate_merges_fresh_step_logs(tmp_path, monkeypatch):
    """tpu_measure_all gates the measurements THIS run took: bench.py's
    result line is the base, the pipeline/TP A/B logs contribute their
    ratio legs, and bench.py's own legs win over the standalone benches."""
    spec2 = importlib.util.spec_from_file_location(
        "tpu_measure_all",
        os.path.abspath(os.path.join(ROOT, "tools", "tpu_measure_all.py")))
    tma = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(tma)
    monkeypatch.setattr(tma, "LOG_DIR", str(tmp_path))

    assert tma.build_bench_candidate() is None  # bench never completed

    # realistic bench.py output: it never measures compiled_vs_host itself
    # (that ratio comes from the standalone pipeline A/B)
    bench_line = {k: v for k, v in PARSED.items() if k != "compiled_vs_host"}
    (tmp_path / "bench.log").write_text(
        "some log noise\n" + json.dumps(bench_line) + "\n")
    (tmp_path / "pipeline_ab.log").write_text(
        json.dumps({"compiled_vs_host": 0.66, "recompiles": 0})
        + "\ntrailing noise\n")
    (tmp_path / "tp_overlap.log").write_text(
        json.dumps({"overlap_vs_gspmd": 0.55}) + "\n")
    path = tma.build_bench_candidate()
    parsed = json.load(open(path))["parsed"]
    assert parsed["compiled_vs_host"] == 0.66
    # bench.py already measured its tp_overlap leg: setdefault keeps it
    assert parsed["tp_overlap_vs_gspmd"] == PARSED["tp_overlap_vs_gspmd"]
    # the merged candidate flows through the gate CLI end-to-end
    baseline = tmp_path / "baseline.json"
    assert bench_gate.main(["--baseline", str(baseline),
                            "--candidate", path,
                            "--update-baseline"]) == 0
    assert bench_gate.main(["--history", str(tmp_path / "none_r*.json"),
                            "--baseline", str(baseline),
                            "--candidate", path]) == 0


def test_committed_baseline_matches_gate_schema():
    """The repo's committed baseline must stay loadable and on-schema, or
    the tpu_measure_all wiring silently stops gating."""
    with open(os.path.join(ROOT, "tools", "bench_baseline.json")) as f:
        base = json.load(f)
    assert isinstance(base.get("legs"), dict) and base["legs"]
    assert base.get("device")
    known = {leg for leg, _, _ in bench_gate.LEGS}
    assert set(base["legs"]) <= known
