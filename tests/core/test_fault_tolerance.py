"""Fault-tolerance drills, end to end on the CPU mesh: SIGTERM preemption
mid-run with supervisor auto-resume (the ISSUE's kill drill — the resumed
trajectory must match the uninterrupted one step for step), crash-restart
through run_with_restarts, and crash-mid-save never yielding a selectable
checkpoint."""

import os

import numpy as np
import pytest

pytestmark = [pytest.mark.distributed, pytest.mark.robustness]

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")

TINY = [
    "model.hidden_size=32", "model.num_hidden_layers=2",
    "model.num_attention_heads=2", "model.vocab_size=64",
    "model.seq_length=8", "model.max_position_embeddings=16",
    "model.make_vocab_size_divisible_by=1",
    "train.train_iters=6", "parallel.mixed_precision=fp32",
    "parallel.global_train_batch_size=8",
]


def _args(extra):
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    return args_from_cli([os.path.join(ZOO, "gpt2-small.yaml")] + TINY +
                         extra, mode="train_dist")


def _supervised_train(args):
    """main()'s auto-restart wiring, inlined so the test can inspect every
    attempt's losses."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.supervisor import run_with_restarts

    outs = []

    def attempt():
        if args.ckpt.save and not args.ckpt.load:
            args.ckpt.load = args.ckpt.save
        out = train(args)
        outs.append(out)
        return out.get("exit_code") or 0

    rc = run_with_restarts(attempt, max_restarts=3, base_delay=0.0,
                           sleep=lambda s: None, log=lambda m: None)
    return rc, outs


def test_sigterm_drill_resumes_step_for_step(tmp_path):
    """The kill drill: a run preempted by a REAL SIGTERM at iter 2
    checkpoints at the step boundary, exits restartable (code 18), and the
    supervisor-resumed run reproduces the uninterrupted loss trajectory
    exactly."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.supervisor import (
        EXIT_CODE_CHECKPOINT_AND_EXIT,
    )

    baseline = train(_args([]))["losses"]
    assert len(baseline) == 6

    rc, outs = _supervised_train(_args([
        f"ckpt.save={tmp_path}",
        "rerun.inject_kind=preempt", "rerun.inject_at_iter=2"]))
    assert rc == 0
    assert len(outs) == 2
    assert outs[0]["exit_code"] == EXIT_CODE_CHECKPOINT_AND_EXIT
    assert len(outs[0]["losses"]) == 3  # iters 0..2, then preempted
    assert outs[1]["exit_code"] is None
    assert len(outs[1]["losses"]) == 3  # resumed at 3, finished 3..5
    # the checkpoint carried the full state (data position, step), so the
    # stitched trajectory IS the uninterrupted one
    np.testing.assert_allclose(outs[0]["losses"] + outs[1]["losses"],
                               baseline, rtol=1e-6, atol=1e-7)


def test_crash_drill_restarts_from_last_commit(tmp_path):
    """An injected hard crash at iter 3 loses only the steps since the
    last interval save: the supervisor restarts, resume replays from the
    committed step, and the final trajectory matches."""
    from hetu_galvatron_tpu.runtime.rerun_machine import InjectedCrash  # noqa: F401

    baseline_args = _args(["ckpt.save_interval=0"])
    from hetu_galvatron_tpu.cli.train_dist import train

    baseline = train(baseline_args)["losses"]

    rc, outs = _supervised_train(_args([
        f"ckpt.save={tmp_path}", "ckpt.save_interval=1",
        "rerun.inject_kind=crash", "rerun.inject_at_iter=3"]))
    assert rc == 0
    # the crashed attempt never returns a result dict; only the resumed
    # attempt lands in outs — it re-ran 3..5 from the committed step_3
    # (save_interval=1 committed steps 1..3 before the crash)
    assert len(outs) == 1
    assert len(outs[0]["losses"]) == 3
    np.testing.assert_allclose(outs[0]["losses"], baseline[3:],
                               rtol=1e-6, atol=1e-7)
    assert os.path.isdir(tmp_path / "step_3")


def test_main_auto_restart_cli(tmp_path, capsys):
    """The CLI wiring end to end: supervisor.auto_restart survives a
    preemption drill and reports a completed run."""
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "gpt2-small.yaml")] + TINY + [
        f"ckpt.save={tmp_path}",
        "supervisor.auto_restart=true", "supervisor.backoff_base_s=0.0",
        "rerun.inject_kind=preempt", "rerun.inject_at_iter=1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "training done" in out


def test_nan_drill_drives_rerun_exit_and_restart(tmp_path):
    """A transient NaN drill: the rerun machine classifies it (rerun
    produces a clean loss), requests exit 16 with the PRE-fault state
    saved, and the supervisor's relaunch re-runs the suspect iteration
    clean — completing the run."""
    rc, outs = _supervised_train(_args([
        f"ckpt.save={tmp_path}",
        "rerun.enable=true", "rerun.mode=validate_results",
        "rerun.inject_kind=nan", "rerun.inject_at_iter=2"]))
    assert rc == 0
    assert len(outs) == 2
    assert outs[0]["exit_code"] == 16
    assert outs[0]["rerun"]["transient"] == 1
    # pre-fault checkpoint at step 2: the relaunch re-runs iter 2
    assert len(outs[1]["losses"]) == 4  # iters 2..5
    # resumed run carries the rerun history (full-state resume)
    assert outs[1]["rerun"]["transient"] == 1


def test_crash_mid_save_never_selectable(tmp_path, monkeypatch):
    """Acceptance: a crash during save must never produce a checkpoint
    that latest_checkpoint selects — resume picks the last committed
    step."""
    import jax

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime import checkpoint as ck
    from tests.core.test_checkpoint import TINY as TINY_MODEL

    params, _ = init_causal_lm(jax.random.key(0), TINY_MODEL)
    good = ck.save_checkpoint(str(tmp_path), 2, params)

    real_commit = ck._commit

    def exploding_commit(tmp_dir, final_dir):
        raise RuntimeError("simulated crash between write and commit")

    monkeypatch.setattr(ck, "_commit", exploding_commit)
    with pytest.raises(RuntimeError, match="simulated crash"):
        ck.save_checkpoint(str(tmp_path), 5, params)
    # the partial staging dir exists but is never selected
    assert os.path.isdir(str(tmp_path / "step_5.tmp"))
    assert ck.latest_checkpoint(str(tmp_path)) == good

    # the stale staging dir is garbage-collectable, and after the crash a
    # re-save of the same step succeeds cleanly
    monkeypatch.setattr(ck, "_commit", real_commit)
    removed = ck.gc_checkpoints(str(tmp_path))
    assert str(tmp_path / "step_5.tmp") in removed
    assert not os.path.isdir(str(tmp_path / "step_5.tmp"))
    d5 = ck.save_checkpoint(str(tmp_path), 5, params)
    assert ck.latest_checkpoint(str(tmp_path)) == d5

    # crash mid-OVERWRITE (between _commit's two renames): the previous
    # payload sits under step_5.old — readers roll it back instead of
    # losing the only committed copy of the step
    os.replace(d5, d5 + ".old")
    assert ck.latest_checkpoint(str(tmp_path)) == d5  # recovered
    assert not os.path.isdir(d5 + ".old")
    _, _, step = ck.load_checkpoint(d5, jax.tree.map(lambda x: x, params))
    assert step == 5
