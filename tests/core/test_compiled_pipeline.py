"""Compiled (single-program) 1F1B schedule vs the host-sequenced engine.

The acceptance drill: on the virtual 8-device mesh, a pp2 x dp2 x tp2 plan
with gradient accumulation, global-norm clipping and tied embeddings must
produce the SAME loss trajectory and post-step params as the host engine
over >= 3 steps, compile exactly once for a fixed shape, and perform zero
host->device transfers in steady state apart from the microbatch feed."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.runtime.compiled_pipeline import CompiledPipelineEngine
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

pytestmark = [pytest.mark.pipeline, pytest.mark.parallel,
              pytest.mark.distributed]

# small enough that the fused-program compile fits the tier-1 budget
CFG = ModelArgs(
    hidden_size=32, num_hidden_layers=4, num_attention_heads=2,
    vocab_size=64, max_position_embeddings=32, seq_length=8,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=True,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=64)

TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _hpc(cfg=CFG, train=TRAIN, **pkw):
    args = CoreArgs(model=cfg.model_dump(), train=train.model_dump())
    defaults = dict(pp_deg=2, chunks=4, pipeline_type="pipedream_flush",
                    global_train_batch_size=16, global_tp_deg=2)
    for k, v in {**defaults, **pkw}.items():
        setattr(args.parallel, k, v)
    return args, get_hybrid_parallel_config(args, 8)


def _engines(cpu_devices, cfg=CFG, **pkw):
    from hetu_galvatron_tpu.models.builder import init_causal_lm

    args, hpc = _hpc(cfg=cfg, **pkw)
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    host = PipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                          compute_dtype=jnp.float32)
    comp = CompiledPipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                                  compute_dtype=jnp.float32)
    return host, comp, params, axes, hpc


def _batch(bsz=16, seed=0, cfg=CFG):
    data = np.random.RandomState(seed).randint(
        0, cfg.padded_vocab_size, (bsz, cfg.seq_length + 1))
    return make_batch(data)


def test_compiled_matches_host_engine_three_steps(cpu_devices):
    """The acceptance drill: pp2 x dp2 x tp2 with chunks=4 grad accum,
    clipping and TIED embeddings — identical trajectory and params."""
    host, comp, params, axes, hpc = _engines(cpu_devices)
    hsp = host.split_params(params, axes)
    hso = host.init_opt(hsp, axes)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    for step in range(3):
        batch = _batch(seed=step)
        hsp, hso, hm = host.train_step(hsp, hso, batch)
        csp, cso, cm = comp.train_step(csp, cso, batch)
        assert abs(float(cm["loss"]) - hm["loss"]) < 2e-5, step
        assert abs(float(cm["grad_norm"]) - hm["grad_norm"]) < 1e-4, step
    # post-step params are step-for-step equal (fp32 ulp tolerance only);
    # the compiled tree keeps ONE wte — merge_params drops the host's
    # transposed tied copy too, so the structures line up exactly
    hp, cp = host.merge_params(hsp), comp.merge_params(csp)
    for (path, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(hp),
                                 jax.tree_util.tree_leaves_with_path(cp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"param {jax.tree_util.keystr(path)}")
    # held-out eval under the same plan agrees too
    ev = _batch(seed=99)
    assert abs(comp.eval_step(csp, ev)["loss"]
               - host.eval_step(hsp, ev)["loss"]) < 2e-5


def test_compiled_recompile_pinning_and_steady_state_transfers(cpu_devices):
    """Exactly ONE compilation of the fused step across a multi-step run,
    and zero host->device transfers in the steady loop beyond the
    microbatch feed (pinned with jax.transfer_guard)."""
    _, comp, params, axes, hpc = _engines(cpu_devices)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    batch = _batch()
    csp, cso, _ = comp.train_step(csp, cso, batch)  # the one compile
    assert comp.compile_count() == 1
    dev_batch = comp.put_batch(batch, hpc.chunks)  # the microbatch feed
    for _ in range(3):
        with jax.transfer_guard("disallow"):
            csp, cso, m = comp.train_step(csp, cso, dev_batch)
    jax.block_until_ready(m["loss"])
    assert comp.compile_count() == 1, "steady state recompiled"
    # the per-tick host spans of the host engine collapse into one
    # pp/compiled_step span; the schedule shape is exported as a gauge
    from hetu_galvatron_tpu.observability.registry import get_registry

    gauge = get_registry().gauge("pp/bubble_frac")
    assert gauge.value == pytest.approx(comp.bubble_frac(hpc.chunks))


def test_compiled_untied_and_uniform_dp(cpu_devices):
    """Untied head + pure-dp stages (tp=1): the head grads live only on the
    last lane and the trajectory still matches the host engine."""
    cfg = CFG.model_copy(update={"tie_word_embeddings": False})
    host, comp, params, axes, _ = _engines(cpu_devices, cfg=cfg,
                                           global_tp_deg=1, chunks=2)
    hsp, hso = host.split_params(params, axes), None
    hso = host.init_opt(hsp, axes)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    batch = _batch(cfg=cfg)
    hsp, hso, hm = host.train_step(hsp, hso, batch)
    csp, cso, cm = comp.train_step(csp, cso, batch)
    assert abs(float(cm["loss"]) - hm["loss"]) < 2e-5
    hp, cp = host.merge_params(hsp), comp.merge_params(csp)
    for (path, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(hp),
                                 jax.tree_util.tree_leaves_with_path(cp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"param {jax.tree_util.keystr(path)}")


def test_compiled_dropout_replays_host_masks(cpu_devices):
    """With dropout on, the compiled schedule derives the same
    per-(microbatch, stage) keys as the host engine and produces the
    bit-identical loss — under the PARTITIONABLE threefry rng. (Under the
    default non-partitionable rng, mask bits depend on how XLA shards the
    program, so the host's per-submesh programs and the fused full-mesh
    program draw different — equally valid — masks.)"""
    cfg = CFG.model_copy(update={"hidden_dropout": 0.1,
                                 "attention_dropout": 0.1})
    host, comp, params, axes, _ = _engines(cpu_devices, cfg=cfg, chunks=2)
    hsp = host.split_params(params, axes)
    hso = host.init_opt(hsp, axes)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    batch = dict(_batch(cfg=cfg))
    batch["dropout_rng"] = jax.random.key(7)
    old = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        _, _, hm = host.train_step(hsp, hso, batch)
        _, _, cm = comp.train_step(csp, cso, batch)
        assert abs(float(cm["loss"]) - hm["loss"]) < 1e-6
    finally:
        jax.config.update("jax_threefry_partitionable", old)
    # and a missing key is refused exactly like the host engine
    with pytest.raises(ValueError, match="dropout_rng"):
        comp.train_step(csp, cso, _batch(cfg=cfg))


def test_unsupported_plans_report_reasons():
    """The launcher's fallback gate: every shape the compiled path cannot
    express names its reason (the host engine remains the general path)."""
    _, hpc = _hpc()
    assert CompiledPipelineEngine.unsupported_reason(CFG, hpc) is None

    _, gpipe = _hpc(pipeline_type="gpipe")
    assert "1F1B" in CompiledPipelineEngine.unsupported_reason(CFG, gpipe)

    _, vpp = _hpc(virtual_pp_deg=2, chunks=4)
    assert "virtual" in CompiledPipelineEngine.unsupported_reason(CFG, vpp)

    cfg5 = CFG.model_copy(update={"num_hidden_layers": 5})
    _, uneven = _hpc(cfg=cfg5)
    assert "heterogeneous" in CompiledPipelineEngine.unsupported_reason(
        cfg5, uneven)

    moe = CFG.model_copy(update={"num_experts": 4, "moe_topk": 2})
    _, mhpc = _hpc(cfg=moe)
    assert "MoE" in CompiledPipelineEngine.unsupported_reason(moe, mhpc)

    # cp / zigzag-cp plans are EXPRESSIBLE since the stage axis was
    # de-vmapped (the ring kernel runs inside as a stage-stacked shard_map)
    _, cp = _hpc(global_cp_deg=2, global_tp_deg=1)
    assert CompiledPipelineEngine.unsupported_reason(CFG, cp) is None
    _, zz = _hpc(global_cp_deg=2, global_tp_deg=1, cp_zigzag=True)
    assert CompiledPipelineEngine.unsupported_reason(CFG, zz) is None

    class _Packed:
        reset_position_ids = True
        reset_attention_mask = False

    _, ok = _hpc()
    assert "packed" in CompiledPipelineEngine.unsupported_reason(
        CFG, ok, data=_Packed())

    # constructing an engine for an unsupported plan raises loudly
    with pytest.raises(ValueError, match="unsupported"):
        CompiledPipelineEngine(CFG, gpipe, TRAIN)


def test_bubble_frac_formula():
    _, hpc = _hpc()
    eng = CompiledPipelineEngine.__new__(CompiledPipelineEngine)
    eng.hpc = hpc
    eng.pp = 2
    # lockstep 1F1B: 2(pp-1) idle tick-slots over m + 2(pp-1) ticks
    assert eng.bubble_frac(4) == pytest.approx(2 / 6)
    assert eng.bubble_frac(1) == pytest.approx(2 / 3)
    eng.pp = 4
    assert eng.bubble_frac(8) == pytest.approx(6 / 14)


def test_pp_rotation_is_collective_permute(cpu_devices):
    """mesh.make_pp_rotation: a [pp, ...]-stacked array rotates one stage
    forward/backward (lax.ppermute over the pp axis), identity on the
    intra-stage axes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hetu_galvatron_tpu.runtime.mesh import (
        build_mesh,
        make_pp_rotation,
        stacked_spec,
    )

    mesh = build_mesh(8, 2, devices=cpu_devices)
    spec = stacked_spec(P(("d0",), ("d1",), None))
    x = np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3)
    xd = jax.device_put(x, NamedSharding(mesh, spec))
    fwd = jax.jit(make_pp_rotation(mesh, spec, +1))
    bwd = jax.jit(make_pp_rotation(mesh, spec, -1))
    np.testing.assert_array_equal(np.asarray(fwd(xd)), np.roll(x, 1, axis=0))
    np.testing.assert_array_equal(np.asarray(bwd(xd)), np.roll(x, -1, axis=0))
    # a rotation really lowers to a collective-permute, not a reshard
    txt = fwd.lower(xd).compile().as_text()
    assert "collective-permute" in txt, "rotation did not lower to ppermute"


def _searched_pp2_tp2_dp2_plan(tmp_path):
    """A pp2 x tp2 x dp2 plan in the searched-config interchange format
    (what search_engine.save_results writes): the unified-engine drill runs
    the plan the SEARCH would hand the launcher, not a hand-built hpc."""
    import json

    from hetu_galvatron_tpu.utils.strategy import (
        EmbeddingLMHeadStrategy,
        LayerStrategy,
        strategy_list2config,
    )

    layers = [LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)
              for _ in range(CFG.num_hidden_layers)]
    cfg = strategy_list2config(
        layers, global_bsz=16, chunks=4, pipeline_type="pipedream_flush",
        default_dp_type="ddp", vocab=EmbeddingLMHeadStrategy(vtp=2),
        pp_division=[2, 2])
    path = tmp_path / "galvatron_config_unified_drill.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def test_compiled_kernels_acceptance_drill(tmp_path, cpu_devices):
    """ROUND-12 ACCEPTANCE: a searched tp2 x dp2 x pp2 plan with the
    overlapped-TP ring matmuls AND the Pallas flash kernel (interpret mode
    on the CPU mesh) runs through the COMPILED engine — no host fallback —
    with the bit-identical 3-step trajectory and final params as the host
    engine running the same kernels, exactly one compile, and zero
    steady-state recompiles. This is the composition the de-vmapped stage
    axis exists for: shard_map kernels inside the fused 1F1B program."""
    from hetu_galvatron_tpu.models.builder import init_causal_lm

    args = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    args.parallel.config_mode = "json"
    args.parallel.galvatron_config_path = _searched_pp2_tp2_dp2_plan(
        tmp_path)
    hpc = get_hybrid_parallel_config(args, 8)
    # the searched plan is expressible — no fallback reason
    assert CompiledPipelineEngine.unsupported_reason(CFG, hpc) is None
    kern = dict(tp_overlap=True, use_flash=True, flash_interpret=True)
    host = PipelineEngine(CFG, hpc, args.train, devices=cpu_devices,
                          compute_dtype=jnp.float32, **kern)
    comp = CompiledPipelineEngine(CFG, hpc, args.train, devices=cpu_devices,
                                  compute_dtype=jnp.float32, **kern)
    # the rings really are live inside the compiled program
    assert comp.tp_overlap and comp.overlap_reason is None
    assert comp._matmul_fns and comp._sdpa is not None
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    hsp = host.split_params(params, axes)
    hso = host.init_opt(hsp, axes)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    for step in range(3):
        batch = _batch(seed=step)
        hsp, hso, hm = host.train_step(hsp, hso, batch)
        csp, cso, cm = comp.train_step(csp, cso, batch)
        assert abs(float(cm["loss"]) - hm["loss"]) < 2e-5, step
        assert abs(float(cm["grad_norm"]) - hm["grad_norm"]) < 1e-4, step
    hp, cp = host.merge_params(hsp), comp.merge_params(csp)
    for (path, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(hp),
                                 jax.tree_util.tree_leaves_with_path(cp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"param {jax.tree_util.keystr(path)}")
    # one program, zero steady-state recompiles, no steady host transfers
    assert comp.compile_count() == 1
    dev_batch = comp.put_batch(_batch(seed=9), hpc.chunks)
    with jax.transfer_guard("disallow"):
        csp, cso, m = comp.train_step(csp, cso, dev_batch)
    jax.block_until_ready(m["loss"])
    assert comp.compile_count() == 1, "steady state recompiled"


def test_compiled_cp_plan_matches_host(cpu_devices):
    """cp plans no longer fall back: a cp2 x dp2 x pp2 plan runs the ring
    attention kernel INSIDE the fused program (stage-stacked shard_map)
    with host-engine parity. vocab_cp=2 rides along — the round-11 guard
    rejected `vocab.vcp > 1` too, and the replicated-across-pp vocab rows
    must keep their cp sharding parity now that the guard is gone."""
    host, comp, params, axes, _ = _engines(
        cpu_devices, global_cp_deg=2, global_tp_deg=1, chunks=2,
        global_train_batch_size=8, vocab_cp=2)
    hsp = host.split_params(params, axes)
    hso = host.init_opt(hsp, axes)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    batch = _batch(bsz=8)
    hsp, hso, hm = host.train_step(hsp, hso, batch)
    csp, cso, cm = comp.train_step(csp, cso, batch)
    assert abs(float(cm["loss"]) - hm["loss"]) < 2e-5
    hp, cp = host.merge_params(hsp), comp.merge_params(csp)
    for (path, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(hp),
                                 jax.tree_util.tree_leaves_with_path(cp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"param {jax.tree_util.keystr(path)}")


@pytest.mark.slow
def test_compiled_zigzag_cp_plan_matches_host(cpu_devices):
    """Zigzag-cp composes with the compiled schedule too (the balanced
    causal layout's entry/exit permutes run inside the program)."""
    host, comp, params, axes, _ = _engines(
        cpu_devices, global_cp_deg=2, global_tp_deg=1, chunks=2,
        global_train_batch_size=8, cp_zigzag=True)
    hsp = host.split_params(params, axes)
    hso = host.init_opt(hsp, axes)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    batch = _batch(bsz=8)
    hsp, hso, hm = host.train_step(hsp, hso, batch)
    csp, cso, cm = comp.train_step(csp, cso, batch)
    assert abs(float(cm["loss"]) - hm["loss"]) < 2e-5


def test_compiled_ramp_caches_one_program_per_chunk_count(cpu_devices):
    """A batch-size ramp varies num_microbatches at a fixed micro shape:
    one fused program per distinct count, each compiled once."""
    _, comp, params, axes, _ = _engines(cpu_devices, chunks=2,
                                        global_train_batch_size=8)
    csp = comp.split_params(params, axes)
    cso = comp.init_opt(csp, axes)
    b1 = _batch(bsz=8)
    csp, cso, _ = comp.train_step(csp, cso, b1, num_microbatches=2)
    csp, cso, _ = comp.train_step(csp, cso, _batch(bsz=4),
                                  num_microbatches=1)
    csp, cso, _ = comp.train_step(csp, cso, b1, num_microbatches=2)
    assert sorted(comp._step_jits) == [1, 2]
    assert comp.compile_count() == 2
