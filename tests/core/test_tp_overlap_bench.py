"""The overlapped-TP A/B microbench must run, produce self-consistent
numbers, and (acceptance) not regress the GSPMD path it replaces on the
virtual CPU mesh — pooled-median overlap_vs_gspmd <= 1.0 with zero
steady-state recompiles."""

import pytest

pytestmark = [pytest.mark.core, pytest.mark.tp_overlap]


def _bench(**kw):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    import tp_overlap_bench as b

    return b.run(**kw)


@pytest.mark.slow
def test_tp_overlap_bench_runs_and_is_consistent():
    out = _bench(iters=3, tps=(2,), hidden=64, seq=64)
    leg = out["legs"]["tp2"]
    assert leg["gspmd_step_ms"] > 0 and leg["overlap_step_ms"] > 0
    assert out["overlap_vs_gspmd"] > 0
    assert out["overlap_recompiles"] == 0
    assert out["platform"] == "cpu"


@pytest.mark.slow
@pytest.mark.pipeline
def test_tp_overlap_bench_compiled_mode_measures_inside_the_engine():
    """ROUND-12: --schedule-impl compiled runs the same rings-vs-GSPMD A/B
    INSIDE the compiled 1F1B engine (pp2 plans, the rings as stage-stacked
    shard_maps) — the ratio must hold <= 1.0 there too, with zero
    steady-state recompiles."""
    out = _bench(iters=3, tps=(2,), hidden=64, seq=64,
                 schedule_impl="compiled")
    assert out["schedule_impl"] == "compiled"
    leg = out["legs"]["tp2"]
    assert leg["gspmd_step_ms"] > 0 and leg["overlap_step_ms"] > 0
    assert out["overlap_vs_gspmd"] <= 1.0, out
    assert out["overlap_recompiles"] == 0


@pytest.mark.slow
def test_tp_overlap_does_not_regress_gspmd_on_cpu_mesh():
    """Acceptance: at the default (amortizing) shapes, the interleaved
    pooled-median ratio across tp2 and tp4 stays <= 1.0 and the overlap
    step never retraces in steady state. On CPU no true overlap exists, so
    <= 1.0 here means the ring decomposition's bookkeeping is already paid
    for by the collectives it removes; the on-chip run (--tpu) is where
    the hidden-transfer win lands on top."""
    out = _bench(iters=10)
    assert out["overlap_recompiles"] == 0, out
    assert out["overlap_vs_gspmd"] <= 1.0, out
