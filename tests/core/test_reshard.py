"""Cross-plan checkpoint resharding (runtime/reshard.py): layout
detection, canonicalization from all three engine layouts, structure-
driven re-split onto destination templates, and the EXACTNESS contract —
resharding moves bytes, never values."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.runtime import reshard as R
from hetu_galvatron_tpu.runtime.checkpoint import save_checkpoint
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config

pytestmark = [pytest.mark.distributed, pytest.mark.robustness,
              pytest.mark.elastic]


def _args(pp=2, tp=2, chunks=2, gbsz=8):
    return CoreArgs.model_validate({
        "model": {"hidden_size": 32, "num_hidden_layers": 4,
                  "num_attention_heads": 2, "vocab_size": 64,
                  "seq_length": 8, "max_position_embeddings": 16,
                  "make_vocab_size_divisible_by": 1},
        "parallel": {"pp_deg": pp, "global_tp_deg": tp, "chunks": chunks,
                     "pipeline_type": "pipedream_flush",
                     "mixed_precision": "fp32",
                     "global_train_batch_size": gbsz, "vocab_tp": tp},
    })


def _leaves_equal(a, b):
    la = jax.tree.leaves(jax.tree.map(np.asarray, a))
    lb = jax.tree.leaves(jax.tree.map(np.asarray, b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# -- pure-host layout mechanics (no engines, no jit) ------------------------


def test_detect_layout():
    cfg = _args().model
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    assert R.detect_layout(params) == R.LAYOUT_SPMD
    assert R.detect_layout([{"layers": ()}, {"layers": ()}]) \
        == R.LAYOUT_STAGES
    assert R.detect_layout({"stages": (), "embed": {}}) == R.LAYOUT_STACKED
    with pytest.raises(R.ReshardError):
        R.detect_layout({"nope": 1})
    with pytest.raises(R.ReshardError):
        R.detect_layout([1, 2])


def test_normalize_raw_folds_indexed_dicts():
    """Orbax raw restores surface tuples/lists as '0','1'-keyed dicts;
    canonicalization must see the saved sequence structure."""
    tree = {"layers": {"0": {"w": np.ones(2)}, "1": {"w": np.zeros(2)}}}
    norm = R.canonicalize_params(tree)
    assert isinstance(norm["layers"], tuple) and len(norm["layers"]) == 2
    assert np.array_equal(norm["layers"][1]["w"], np.zeros(2))


def test_canonicalize_stacked_roundtrip():
    """Hand-stack the compiled layout (layer s*lps+j -> row s of
    stages[j]) and canonicalize back — exact, order-preserving."""
    cfg = _args().model
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    pp, lps = 2, 2
    stages = tuple(
        jax.tree.map(lambda *rows: np.stack([np.asarray(r) for r in rows]),
                     *[params["layers"][s * lps + j] for s in range(pp)])
        for j in range(lps))
    stacked = {"stages": stages, "embed": params["embed"],
               "prenorm": params["prenorm"], "head": params["head"]}
    canonical = R.canonicalize_params(stacked)
    _leaves_equal(canonical, params)
    assert len(canonical["layers"]) == 4


def test_canonicalize_stages_drops_tied_whead():
    """The host layout's transposed tied-head copy is derived state: the
    merge drops it (wte is canonical) and the re-split recreates it as
    the transpose — exactly what the engine's symmetric tied-grad
    exchange maintains."""
    cfg = _args().model
    assert cfg.tie_word_embeddings
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    st0 = {"layers": tuple(params["layers"][:2]), "embed": params["embed"]}
    st1 = {"layers": tuple(params["layers"][2:]),
           "prenorm": params["prenorm"],
           "head": {**params["head"],
                    "whead": np.asarray(params["embed"]["wte"]).T}}
    canonical = R.canonicalize_params([st0, st1], tie_word_embeddings=True)
    assert "whead" not in canonical["head"]
    _leaves_equal(canonical, params)

    # re-split recreates whead = wte.T on the head stage
    back = R._split_stages_like(canonical, [st0, st1])
    assert np.array_equal(np.asarray(back[1]["head"]["whead"]),
                          np.asarray(params["embed"]["wte"]).T)
    _leaves_equal(back[0]["layers"], params["layers"][:2])


def test_layer_count_mismatch_is_typed():
    cfg = _args().model
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    short = {**params, "layers": params["layers"][:3]}
    with pytest.raises(R.ReshardError, match="3 decoder layers"):
        R._relayout(short, params)


def test_map_params_like_hits_moment_subtrees():
    """The structure-match walker must transform adam mu/nu (params
    clones) and leave chain scalars (counts) untouched."""
    import optax

    cfg = _args().model
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    tx = optax.chain(optax.scale_by_adam(), optax.scale(1.0))
    opt = tx.init(jax.tree.map(jnp.asarray, params))
    pdef = jax.tree.structure(jax.tree.map(jnp.asarray, params))
    hits = []
    out = R.map_params_like(opt, pdef, lambda t: (hits.append(1) or t))
    assert len(hits) == 2  # mu and nu
    assert len(jax.tree.leaves(out)) == len(jax.tree.leaves(opt))


# -- the exactness contract through real engines + checkpoints --------------


def test_reshard_params_api(cpu_devices):
    """reshard_params: full tree under plan A -> plan B PartitionSpecs
    over a new mesh; values exact, shardings the destination plan's."""
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    args = _args(pp=1, tp=2, chunks=1)
    cfg = args.model
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    src_plan = get_hybrid_parallel_config(args, 4)
    dst_args = _args(pp=1, tp=1, chunks=1)
    dst_plan = get_hybrid_parallel_config(dst_args, 2)
    mesh2 = build_mesh(2, 1, devices=cpu_devices[:2])
    out = R.reshard_params(params, src_plan, dst_plan, mesh2,
                           axes_tree=axes)
    _leaves_equal(out, params)

    bad = get_hybrid_parallel_config(
        _args(pp=1, tp=1, chunks=1).model_copy(
            update={"model": cfg.model_copy(
                update={"num_hidden_layers": 2})}), 2)
    with pytest.raises(R.ReshardError):
        R.reshard_params(params, src_plan, bad, mesh2, axes_tree=axes)


def test_reshard_exact_across_engines(tmp_path, cpu_devices):
    """The full matrix on real checkpoints: a host-pipeline (stages)
    checkpoint reshards onto the 4-device SPMD plan and the compiled
    (stacked) plan; a compiled checkpoint reshards onto the host plan.
    Params AND adam moments are bit-equal to the source in every
    direction, and each destination engine takes a live step on the
    resharded state."""
    from jax.sharding import NamedSharding, PartitionSpec

    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    args = _args()
    cfg = args.model
    hpc8 = get_hybrid_parallel_config(args, 8)
    tx = make_optimizer(args.train)
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    data = np.random.RandomState(0).randint(
        0, cfg.padded_vocab_size, (8, cfg.seq_length + 1))

    # source A: host pipeline, 2 real steps, committed checkpoint
    eng = PipelineEngine(cfg, hpc8, args.train, devices=cpu_devices,
                        compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    for _ in range(2):
        sp, so, _ = eng.train_step(sp, so, make_batch(data))
    truth = eng.merge_params(sp)
    save_checkpoint(str(tmp_path / "host"), 2, sp, so, hpc=hpc8)
    hd = str(tmp_path / "host" / "step_2")

    canonical, copt, step, _ = R.load_checkpoint_canonical(
        hd, tie_word_embeddings=cfg.tie_word_embeddings)
    assert step == 2
    _leaves_equal(canonical, truth)

    # stages -> spmd on HALF the devices (the N -> N/2 shape)
    args4 = _args(pp=1, tp=2, chunks=1)
    hpc4 = get_hybrid_parallel_config(args4, 4)
    mesh4 = build_mesh(4, 1, devices=cpu_devices[:4])
    step_fn, pspecs, ospecs, bshd = make_spmd_train_step(
        cfg, hpc4, mesh4, axes, tx, params, compute_dtype=jnp.float32,
        donate=False)
    sp4 = shard_params(params, pspecs, mesh4)
    so4 = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: NamedSharding(mesh4, s), ospecs,
        is_leaf=lambda x: isinstance(x, PartitionSpec)))(sp4)
    nsp, nso, st = R.resume_elastic(
        hd, sp4, so4, tie_word_embeddings=cfg.tie_word_embeddings)
    assert st == 2
    _leaves_equal(nsp, canonical)
    _leaves_equal(nso, copt)
    b4 = jax.device_put(jax.tree.map(jnp.asarray, make_batch(data)), bshd)
    _, _, m4 = step_fn(nsp, nso, b4)
    assert np.isfinite(float(m4["loss"]))

    # stages -> stacked (compiled engine, same 8-device plan)
    ceng = CompiledPipelineEngine(cfg, hpc8, args.train,
                                  devices=cpu_devices,
                                  compute_dtype=jnp.float32, donate=False)
    csp = ceng.split_params(params, axes)
    cso = ceng.init_opt(csp, axes)
    nsp2, nso2, _ = R.resume_elastic(
        hd, csp, cso, tie_word_embeddings=cfg.tie_word_embeddings)
    _leaves_equal(ceng.merge_params(nsp2), truth)
    csp2, cso2, mc = ceng.train_step(nsp2, nso2, make_batch(data))
    assert np.isfinite(float(mc["loss"]))

    # source B: compiled (stacked) checkpoint -> host (stages) plan
    save_checkpoint(str(tmp_path / "compiled"), 3, csp2, cso2, hpc=hpc8)
    cd = str(tmp_path / "compiled" / "step_3")
    sp_h = eng.split_params(params, axes)
    so_h = eng.init_opt(sp_h, axes)
    nsp3, nso3, _ = R.resume_elastic(
        cd, sp_h, so_h, tie_word_embeddings=cfg.tie_word_embeddings)
    _leaves_equal(eng.merge_params(nsp3), ceng.merge_params(csp2))
    _, _, mh = eng.train_step(nsp3, nso3, make_batch(data))
    assert np.isfinite(float(mh["loss"]))


def test_resume_elastic_rejects_moe_opt_state(tmp_path):
    with pytest.raises(R.ReshardError, match="MoE"):
        R.resume_elastic(str(tmp_path), {}, {}, num_experts=4)
