"""Async snapshot checkpointing (runtime/checkpoint.AsyncCheckpointer +
CheckpointCadence): snapshot/commit roundtrip, the single-writer
supersede rule, the hung-save watchdog, wall-clock cadence, latched
writer errors, and corrupted-checkpoint resume via
load_latest_resilient (truncated meta, missing payload leaf, stray
COMMITTED marker — fall back with a warning, never traceback)."""

import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.runtime.checkpoint import (
    AsyncCheckpointer,
    CheckpointCadence,
    latest_checkpoint,
    load_checkpoint,
    load_latest_resilient,
    save_checkpoint,
    try_read_checkpoint_meta,
)

pytestmark = pytest.mark.robustness


def _tree(scale=1.0):
    return {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4) * scale,
            "b": jnp.ones((4,), dtype=jnp.float32) * scale}


def _target():
    return {"w": jnp.zeros((3, 4), dtype=jnp.float32),
            "b": jnp.zeros((4,), dtype=jnp.float32)}


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- AsyncCheckpointer -------------------------------------------------------


def test_snapshot_drain_commit_roundtrip(tmp_path):
    ac = AsyncCheckpointer(str(tmp_path), log=lambda m: None)
    stall_ms = ac.snapshot(3, _tree(), _tree(2.0),
                           train_state={"consumed_samples": 24})
    assert stall_ms >= 0.0
    assert ac.drain()
    assert ac.last_commit["step"] == 3
    d = latest_checkpoint(str(tmp_path))
    assert d.endswith("step_3")
    p, o, step = load_checkpoint(d, _target(), _target())
    assert step == 3
    _assert_tree_equal(p, _tree())
    _assert_tree_equal(o, _tree(2.0))
    meta, err = try_read_checkpoint_meta(d)
    assert err is None
    assert meta["train_state"]["consumed_samples"] == 24
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    ac.close()


def test_snapshot_isolates_from_later_mutation(tmp_path):
    """The on-step device copy is the donation shield: mutating (or
    donating) the live buffers after snapshot() must not change what
    the background writer commits."""
    ac = AsyncCheckpointer(str(tmp_path), log=lambda m: None)
    live = _tree()
    ac.snapshot(1, live)
    # simulate the next step reusing the buffers
    live["w"] = live["w"] * 100.0
    live["b"] = live["b"] * 100.0
    assert ac.drain()
    p, _, _ = load_checkpoint(latest_checkpoint(str(tmp_path)), _target())
    _assert_tree_equal(p, _tree())
    ac.close()


def test_new_snapshot_supersedes_unstarted_write(tmp_path):
    """Queue depth is ONE: while a write is in flight, the newest
    queued snapshot wins and the middle one is never written — but the
    STARTED write always completes."""
    gate = threading.Event()
    logs = []
    ac = AsyncCheckpointer(str(tmp_path), log=logs.append,
                           hooks={"before_write": lambda step: gate.wait(30)})
    ac.snapshot(1, _tree())  # picked up by the worker, blocks on gate
    deadline = time.monotonic() + 10
    while ac._inflight is None:  # wait for the worker to take it
        assert time.monotonic() < deadline
        time.sleep(0.01)
    ac.snapshot(2, _tree(2.0))  # queued
    ac.snapshot(3, _tree(3.0))  # supersedes step 2
    gate.set()
    assert ac.drain(timeout_s=30)
    steps = sorted(n for n in os.listdir(tmp_path) if n.startswith("step_"))
    assert steps == ["step_1", "step_3"]  # step 2 never hit the disk
    assert any("supersedes" in m for m in logs)
    ac.close()


def test_hung_save_watchdog_and_drain_give_up(tmp_path):
    """A wedged write must not block shutdown: drain() returns False
    after the watchdog deadline instead of hanging, and the hang is
    logged once."""
    gate = threading.Event()
    logs = []
    ac = AsyncCheckpointer(str(tmp_path), save_timeout_s=0.2,
                           log=logs.append,
                           hooks={"before_write": lambda step: gate.wait(60)})
    ac.snapshot(5, _tree())
    t0 = time.monotonic()
    assert ac.drain(timeout_s=0.5) is False
    assert time.monotonic() - t0 < 10.0  # gave up, did not wait the 60s
    time.sleep(0.25)  # age the in-flight write past save_timeout_s
    assert ac.check_watchdog() is True
    assert any("watchdog" in m for m in logs)
    gate.set()  # unwedge so the daemon thread exits cleanly
    ac.drain(timeout_s=30)


def test_writer_error_latches_and_reraises(tmp_path):
    def boom(step):
        raise OSError("disk on fire")

    ac = AsyncCheckpointer(str(tmp_path), log=lambda m: None,
                           hooks={"before_write": boom})
    ac.snapshot(1, _tree())
    with pytest.raises(OSError, match="disk on fire"):
        ac.drain(timeout_s=30)
    # the error is consumed: the next save works
    ac.hooks.pop("before_write")
    ac.snapshot(2, _tree(2.0))
    assert ac.drain(timeout_s=30)
    assert latest_checkpoint(str(tmp_path)).endswith("step_2")
    ac.close()


# -- CheckpointCadence -------------------------------------------------------


class _Ck:
    """A CheckpointArgs stand-in with just the cadence fields."""

    def __init__(self, **kw):
        self.save = kw.get("save")
        self.load = None
        self.save_interval = kw.get("save_interval", 0)
        self.interval_s = kw.get("interval_s", 0.0)
        self.snapshot_async = kw.get("snapshot_async", False)
        self.save_timeout_s = kw.get("save_timeout_s", 120.0)
        self.async_save = False
        self.keep_last = kw.get("keep_last", 0)


def test_cadence_step_interval():
    ck = _Ck(save="/nope", save_interval=3)
    cad = CheckpointCadence(ck, log=lambda m: None)
    assert [cad.due(it) for it in range(6)] == [
        False, False, True, False, False, True]


def test_cadence_wall_clock_bounds_rpo():
    """``ckpt.interval_s`` fires on elapsed wall-clock even when no step
    cadence is configured — the elastic RPO bound when steps slow down."""
    now = [100.0]
    ck = _Ck(save="/nope", interval_s=30.0)
    cad = CheckpointCadence(ck, log=lambda m: None, clock=lambda: now[0])
    assert not cad.due(0)
    now[0] += 29.0
    assert not cad.due(1)
    now[0] += 2.0
    assert cad.due(2)


def test_cadence_save_resets_time_base(tmp_path):
    now = [0.0]
    ck = _Ck(save=str(tmp_path), interval_s=10.0)
    cad = CheckpointCadence(ck, log=lambda m: None, clock=lambda: now[0])
    now[0] = 11.0
    assert cad.due(0)
    cad.save(1, _tree())
    assert not cad.due(1)  # the save re-based the clock
    now[0] = 22.0
    assert cad.due(2)


def test_cadence_no_save_dir_never_due():
    cad = CheckpointCadence(_Ck(save=None, save_interval=1),
                            log=lambda m: None)
    assert not cad.due(0)


def test_cadence_async_books_only_stall(tmp_path):
    """Goodput sees the dispatch stall, not the write: the wall-clock of
    the booked 'checkpoint_save' interval must be far below the actual
    write time (which overlaps training)."""

    class Goodput:
        def __init__(self):
            self.booked = []

        def add(self, name, seconds):
            self.booked.append((name, seconds))

    gp = Goodput()
    ck = _Ck(save=str(tmp_path), save_interval=1, snapshot_async=True)
    cad = CheckpointCadence(ck, goodput=gp, log=lambda m: None)
    assert cad.async_ckptr is not None
    cad.save(1, _tree())
    cad.drain()
    assert [n for n, _ in gp.booked] == ["checkpoint_save"]
    assert gp.booked[0][1] < 5.0  # the stall, not a blocking write
    assert latest_checkpoint(str(tmp_path)).endswith("step_1")


# -- resilient resume --------------------------------------------------------


def _two_commits(root):
    save_checkpoint(root, 1, _tree(), _tree(2.0))
    save_checkpoint(root, 2, _tree(10.0), _tree(20.0))


def test_resilient_falls_back_on_truncated_meta(tmp_path):
    root = str(tmp_path)
    _two_commits(root)
    meta = os.path.join(root, "step_2", "meta.json")
    txt = open(meta).read()
    with open(meta, "w") as f:
        f.write(txt[: len(txt) // 2])  # torn write
    logs = []
    got = load_latest_resilient(root, _target(), _target(),
                                log=logs.append)
    assert got is not None
    p, o, step, ckdir = got
    assert step == 1 and ckdir.endswith("step_1")
    _assert_tree_equal(p, _tree())
    assert any("falling back" in m for m in logs)


def test_resilient_falls_back_on_garbled_meta(tmp_path):
    root = str(tmp_path)
    _two_commits(root)
    with open(os.path.join(root, "step_2", "meta.json"), "w") as f:
        f.write("{this is not json")
    got = load_latest_resilient(root, _target(), _target(),
                                log=lambda m: None)
    assert got is not None and got[2] == 1


def test_resilient_falls_back_on_missing_payload_leaf(tmp_path):
    root = str(tmp_path)
    _two_commits(root)
    shutil.rmtree(os.path.join(root, "step_2", "params"))
    logs = []
    got = load_latest_resilient(root, _target(), _target(),
                                log=logs.append)
    assert got is not None and got[2] == 1
    assert any("falling back" in m for m in logs)


def test_resilient_skips_stray_committed_marker(tmp_path):
    """A COMMITTED marker over a torn payload (a crash between marker
    fsync and payload rename cannot produce this, but operators can) is
    corruption, not a candidate."""
    root = str(tmp_path)
    _two_commits(root)
    stray = os.path.join(root, "step_9")
    os.makedirs(stray)
    with open(os.path.join(stray, "COMMITTED"), "w") as f:
        f.write("committed\n")
    got = load_latest_resilient(root, _target(), _target(),
                                log=lambda m: None)
    assert got is not None and got[2] == 2  # newest REAL commit


def test_resilient_none_when_no_commits(tmp_path):
    assert load_latest_resilient(str(tmp_path), _target()) is None


def test_resilient_raises_when_all_unreadable(tmp_path):
    """Every candidate corrupt -> a loud RuntimeError naming the count,
    never a silent fresh start."""
    root = str(tmp_path)
    _two_commits(root)
    for s in (1, 2):
        with open(os.path.join(root, f"step_{s}", "meta.json"), "w") as f:
            f.write("{nope")
        shutil.rmtree(os.path.join(root, f"step_{s}", "params"))
    with pytest.raises(RuntimeError, match="2 committed checkpoint"):
        load_latest_resilient(root, _target(), log=lambda m: None)


def test_try_read_meta_never_raises(tmp_path):
    d = str(tmp_path / "step_1")
    os.makedirs(d)
    with open(os.path.join(d, "meta.json"), "w") as f:
        f.write("{torn")
    meta, err = try_read_checkpoint_meta(d)
    assert meta == {} and err is not None
    # an ABSENT meta.json is not corruption: {} with no error by contract
    meta, err = try_read_checkpoint_meta(str(tmp_path / "absent"))
    assert meta == {} and err is None
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"step": 1}, f)
    meta, err = try_read_checkpoint_meta(d)
    assert err is None and meta["step"] == 1


def test_checkpoint_reads_absorb_transient_io_faults(tmp_path):
    """The retry seam under checkpoint I/O: a fault injector failing the
    first two read attempts is absorbed by backoff — the read still
    succeeds and resume never sees the flake."""
    from hetu_galvatron_tpu.utils.retrying import set_fault_injector

    root = str(tmp_path)
    save_checkpoint(root, 1, _tree())
    budget = [2]

    def inject(op):
        if "checkpoint" in op and budget[0] > 0:
            budget[0] -= 1
            return OSError("chaos: injected transient I/O error")
        return None

    prev = set_fault_injector(inject)
    try:
        got = load_latest_resilient(root, _target(), log=lambda m: None)
    finally:
        set_fault_injector(prev)
    assert got is not None and got[2] == 1
    assert budget[0] == 0  # the injector actually fired
