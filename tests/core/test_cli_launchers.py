"""CLI launcher smoke tests (reference L7): train_dist / search_dist /
profiler run end-to-end from YAML configs on the virtual CPU mesh."""

import json
import os

import pytest

pytestmark = [pytest.mark.distributed]

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")
FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")

TINY_OVERRIDES = [
    "model.hidden_size=32", "model.num_hidden_layers=2",
    "model.num_attention_heads=2", "model.vocab_size=64",
    "model.seq_length=8", "model.max_position_embeddings=16",
    "model.make_vocab_size_divisible_by=1",
    "train.train_iters=2", "parallel.mixed_precision=fp32",
    "parallel.global_train_batch_size=8",
]


def test_model_zoo_yaml_all_load():
    from hetu_galvatron_tpu.core.arguments import load_config

    for name in os.listdir(ZOO):
        if not name.endswith((".yaml", ".yml")):
            continue
        args = load_config(os.path.join(ZOO, name))
        assert args.model.hidden_size > 0
        assert args.model.hidden_size % args.model.num_attention_heads == 0


def test_train_dist_cli(capsys):
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "gpt2-small.yaml")] + TINY_OVERRIDES)
    assert rc == 0
    assert "training done" in capsys.readouterr().out


def test_train_dist_cli_pipeline(capsys):
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "llama2-7b.yaml")] + TINY_OVERRIDES +
              ["parallel.pp_deg=2", "parallel.chunks=2",
               "parallel.global_tp_deg=2", "model.num_key_value_heads=2",
               "model.ffn_hidden_size=64"])
    assert rc == 0
    assert "training done" in capsys.readouterr().out


def test_train_dist_cli_pipeline_compiled(capsys):
    """pipeline.schedule_impl=compiled routes an eligible 1F1B plan through
    the single-program engine."""
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "llama2-7b.yaml")] + TINY_OVERRIDES +
              ["parallel.pp_deg=2", "parallel.chunks=2",
               "parallel.global_tp_deg=2",
               "parallel.pipeline_type=pipedream_flush",
               "pipeline.schedule_impl=compiled",
               "model.num_key_value_heads=2", "model.ffn_hidden_size=64"])
    res = capsys.readouterr()
    assert rc == 0
    assert "pipeline schedule: compiled" in res.out + res.err
    assert "training done" in res.out


def test_train_dist_cli_compiled_with_tp_overlap(capsys):
    """The unified path at the launcher level: tp_overlap.enable under
    pipeline.schedule_impl=compiled keeps the rings (no feature disable —
    the round-11 behavior) and logs them riding inside the fused program."""
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "llama2-7b.yaml")] + TINY_OVERRIDES +
              ["parallel.pp_deg=2", "parallel.chunks=2",
               "parallel.global_tp_deg=2",
               "parallel.pipeline_type=pipedream_flush",
               "pipeline.schedule_impl=compiled", "tp_overlap.enable=1",
               "model.num_key_value_heads=2", "model.ffn_hidden_size=64"])
    res = capsys.readouterr()
    assert rc == 0
    assert "pipeline schedule: compiled" in res.out + res.err
    assert "overlapped-TP rings inside" in res.out + res.err
    assert "unsupported under" not in res.out + res.err
    assert "training done" in res.out


def test_train_dist_cli_compiled_falls_back(capsys):
    """A plan the compiled schedule cannot express (gpipe) logs its reason
    and trains through the host engine."""
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "llama2-7b.yaml")] + TINY_OVERRIDES +
              ["parallel.pp_deg=2", "parallel.chunks=2",
               "parallel.pipeline_type=gpipe",
               "pipeline.schedule_impl=compiled",
               "model.num_key_value_heads=2", "model.ffn_hidden_size=64"])
    res = capsys.readouterr()
    assert rc == 0
    assert "falling back to the host engine" in res.out + res.err
    assert "training done" in res.out


def test_search_dist_cli(tmp_path, capsys):
    from hetu_galvatron_tpu.cli.search_dist import main

    rc = main([
        os.path.join(ZOO, "llama2-7b.yaml"),
        "model.num_hidden_layers=28", "model.seq_length=8192",
        "model.max_position_embeddings=8192",
        "search.settle_bsz=64", "search.settle_chunks=32",
        "search.memory_constraint=36", "search.default_dp_type=zero2",
        "search.pipeline_type=pipedream_flush",
        "search.async_grad_reduce=false",
        "search.time_profile_mode=sequence",
        "search.memory_profile_mode=sequence",
        f"search.time_profiling_path={FIXTURES}/computation_profiling_bf16_llama2-7b_all.json",
        f"search.memory_profiling_path={FIXTURES}/memory_profiling_bf16_llama2-7b_all.json",
        f"search.allreduce_bandwidth_config_path={FIXTURES}/allreduce_bandwidth_1nodes_8gpus_per_node.json",
        f"search.p2p_bandwidth_config_path={FIXTURES}/p2p_bandwidth_1nodes_8gpus_per_node.json",
        f"search.overlap_coe_path={FIXTURES}/overlap_coefficient.json",
        f"search.sp_time_path={FIXTURES}/sp_time_1nodes_8gpus_per_node.json",
        f"search.output_config_path={tmp_path}",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "max throughput 2.64850914" in out
    files = os.listdir(tmp_path)
    assert len(files) == 1 and files[0].startswith("galvatron_config_")
    # the winner embeds its per-layer compute prediction for the plan audit
    cfg = json.load(open(os.path.join(tmp_path, files[0])))
    pred = cfg["predicted_layer_compute_ms"]
    assert len(pred) == 28 and all(v > 0 for v in pred)


def test_profiler_cli_computation(tmp_path, capsys):
    from hetu_galvatron_tpu.cli.profiler import main

    rc = main([os.path.join(ZOO, "gpt2-small.yaml"),
               "mode=model_profiler"] + TINY_OVERRIDES + [
              "model_profiler.profile_type=computation",
              "model_profiler.layernum_min=1",
              "model_profiler.layernum_max=2",
              "model_profiler.profile_batch_size=2",
              "model_profiler.profile_seq_length_list=[8]",
              f"model_profiler.output_dir={tmp_path}"])
    assert rc == 0
    files = os.listdir(tmp_path)
    assert any(f.startswith("computation_profiling") for f in files)
    cfg = json.load(open(os.path.join(tmp_path, files[0])))
    assert any(k.startswith("layertype_0_") for k in cfg)


def test_train_dist_cli_checkpoint_resume(tmp_path, capsys):
    """Save at an interval, then resume from the checkpoint directory."""
    from hetu_galvatron_tpu.cli.train_dist import main

    common = [os.path.join(ZOO, "gpt2-small.yaml")] + TINY_OVERRIDES + [
        "train.train_iters=4", f"ckpt.save={tmp_path}",
        "ckpt.save_interval=2"]
    assert main(common) == 0
    assert any(d.startswith("step_") for d in os.listdir(tmp_path))
    rc = main(common + [f"ckpt.load={tmp_path}", "train.train_iters=6"])
    assert rc == 0
    # resumed run trains only iters 4..5 (2 iters), not all 6
    out = capsys.readouterr().out
    assert "training done: 2 iters" in out


def test_train_dist_cli_indexed_data(tmp_path):
    import numpy as np
    from hetu_galvatron_tpu.cli.train_dist import main
    from hetu_galvatron_tpu.data.indexed_dataset import write_indexed_dataset

    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "corpus")
    write_indexed_dataset(
        prefix, [rng.randint(0, 64, 50).tolist() for _ in range(40)])
    rc = main([os.path.join(ZOO, "gpt2-small.yaml")] + TINY_OVERRIDES + [
        "data.dataset=indexed", f"data.data_path=[{prefix}]"])
    assert rc == 0


def test_preprocess_then_train_real_data_e2e(tmp_path, capsys):
    """The full real-data path (reference dataloader.py:462-558): raw text
    -> preprocess CLI (tokenize + eod + meta sidecar) -> indexed dataset ->
    train_dist with eod loss-masking."""
    from hetu_galvatron_tpu.cli.preprocess_data import main as prep_main
    from hetu_galvatron_tpu.cli.train_dist import main as train_main

    src = tmp_path / "corpus.txt"
    src.write_text("".join(f"document number {i} with some text\n"
                           for i in range(40)))
    prefix = str(tmp_path / "corpus")
    assert prep_main([str(src), prefix]) == 0
    assert os.path.exists(prefix + ".meta.json")

    # byte tokenizer vocab = 257 (eod 256) -> model vocab must cover it
    rc = train_main([os.path.join(ZOO, "gpt2-small.yaml")] + TINY_OVERRIDES + [
        "model.vocab_size=257",
        "data.dataset=indexed", f"data.data_path=[{prefix}]",
        "data.eod_mask_loss=true"])
    assert rc == 0
    assert "training done: 2 iters" in capsys.readouterr().out


def test_eod_mask_loss_zeroes_eod_positions(tmp_path):
    from hetu_galvatron_tpu.cli.preprocess_data import main as prep_main
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.runtime.dataloader import get_data_iterator

    src = tmp_path / "c.txt"
    src.write_text("".join(f"doc {i}\n" for i in range(30)))
    prefix = str(tmp_path / "c")
    assert prep_main([str(src), prefix]) == 0
    args = args_from_cli(
        [os.path.join(ZOO, "gpt2-small.yaml")] + TINY_OVERRIDES + [
            "model.vocab_size=257",
            "data.dataset=indexed", f"data.data_path=[{prefix}]",
            "data.eod_mask_loss=true"], mode="train_dist")
    b = next(get_data_iterator(args, global_batch_size=4))
    # Megatron semantics: the position whose INPUT is eod is masked (no
    # cross-document prediction); predicting eod itself stays in the loss
    eod = (b["tokens"] == 256)
    assert eod.any(), "short docs should put eod tokens in-batch"
    assert (b["loss_mask"][eod] == 0).all()
    assert (b["loss_mask"][~eod] == 1).all()


def test_checkpoint_convert_cli_roundtrip(tmp_path, capsys):
    """h2g -> g2h through the converter CLI preserves every converted
    tensor (reference tools/checkpoint_convert_{h2g,g2h}.py)."""
    torch = pytest.importorskip("torch")
    from safetensors.numpy import save_file
    from safetensors import safe_open
    from transformers import GPT2Config, GPT2LMHeadModel

    from hetu_galvatron_tpu.cli.checkpoint_convert import main

    hf_cfg = GPT2Config(vocab_size=64, n_positions=16, n_embd=32, n_layer=2,
                        n_head=2, activation_function="gelu_new",
                        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()
    sd_np = {k: v.detach().numpy().copy()
             for k, v in hf.state_dict().items()}
    hf_dir = tmp_path / "hf"
    hf_dir.mkdir()
    save_file(sd_np, str(hf_dir / "model.safetensors"))

    yaml = os.path.join(ZOO, "gpt2-small.yaml")
    ckpt = tmp_path / "ckpt"
    assert main(["h2g", yaml] + TINY_OVERRIDES +
                [f"hf_path={hf_dir}", f"out={ckpt}", "step=3"]) == 0
    assert "step_3" in capsys.readouterr().out

    out_dir = tmp_path / "hf_back"
    assert main(["g2h", yaml] + TINY_OVERRIDES +
                [f"ckpt={ckpt}", f"out={out_dir}"]) == 0
    with safe_open(str(out_dir / "model.safetensors"), framework="np") as f:
        back = {k: f.get_tensor(k) for k in f.keys()}
    # every HF weight except non-weight buffers (causal-mask bias) and the
    # tied lm_head must round-trip — a converter that drops tensors fails
    expected = {k for k in sd_np
                if ".attn.bias" not in k and ".attn.masked_bias" not in k
                and k != "lm_head.weight"}
    assert set(back) == expected, (
        f"missing {expected - set(back)}, extra {set(back) - expected}")
    import numpy as np

    for k, v in back.items():
        np.testing.assert_allclose(v, sd_np[k], atol=1e-6, err_msg=k)


def test_train_dist_cli_with_dropout(capsys):
    """Dropout rides the batch dict through the CLI's spmd step (the rng is
    per-step data and must not be placed under the batch sharding)."""
    from hetu_galvatron_tpu.cli.train_dist import main

    rc = main([os.path.join(ZOO, "gpt2-small.yaml")] + TINY_OVERRIDES + [
        "model.hidden_dropout=0.1", "model.attention_dropout=0.1"])
    assert rc == 0
    assert "training done" in capsys.readouterr().out


def test_generate_cli_smoke_and_ckpt(tmp_path, capsys):
    """Generation CLI: random-init smoke on the multi-device mesh (auto-TP
    submesh) and decoding from a trained framework checkpoint."""
    from hetu_galvatron_tpu.cli.generate import main as gen_main
    from hetu_galvatron_tpu.cli.train_dist import main as train_main

    overrides = [
        os.path.join(ZOO, "gpt2-small.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_attention_heads=4", "model.vocab_size=257",
        "model.max_position_embeddings=64",
        "model.make_vocab_size_divisible_by=1",
    ]
    rc = gen_main(overrides + ["model.seq_length=64", "prompt=hi there",
                               "max_new_tokens=4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("hi there")

    assert train_main(overrides + [
        "model.seq_length=16", "train.train_iters=2",
        "parallel.mixed_precision=fp32",
        "parallel.global_train_batch_size=8",
        f"ckpt.save={tmp_path}", "ckpt.save_interval=2"]) == 0
    capsys.readouterr()  # drain the training log
    rc = gen_main(overrides + ["model.seq_length=64", "prompt=abc",
                               "max_new_tokens=4", f"ckpt={tmp_path}",
                               "temperature=0.5", "top_k=5"])
    assert rc == 0
    assert capsys.readouterr().out.startswith("abc")


def test_serve_cli_smoke(tmp_path, capsys):
    """Serving CLI: JSONL request stream -> per-request token streams on
    the auto-TP submesh, metrics JSONL written and summarizable."""
    from hetu_galvatron_tpu.cli.serve import main as serve_main
    from hetu_galvatron_tpu.cli.summarize import summarize

    reqs = [
        {"prompt": "hello world", "max_new_tokens": 3},
        {"prompt": "abc", "max_new_tokens": 4, "temperature": 0.8,
         "seed": 3},
    ]
    rp = tmp_path / "reqs.jsonl"
    rp.write_text("".join(json.dumps(r) + "\n" for r in reqs))
    mp = tmp_path / "metrics.jsonl"
    rc = serve_main([
        os.path.join(ZOO, "gpt2-small.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_attention_heads=4", "model.vocab_size=257",
        "model.max_position_embeddings=64",
        "model.make_vocab_size_divisible_by=1", "model.seq_length=64",
        "serving.max_batch_size=2", "serving.kv_block_size=8",
        "serving.max_seq_len=32",
        f"requests={rp}", f"metrics={mp}"])
    assert rc == 0
    events = [json.loads(line) for line in
              capsys.readouterr().out.strip().splitlines()]
    done = {e["rid"]: e for e in events if e["event"] == "done"}
    assert done[0]["n_tokens"] == 3 and done[1]["n_tokens"] == 4
    assert all(e["status"] == "done" for e in done.values())
    assert sum(1 for e in events if e["event"] == "token") == 7
    headline = summarize(str(mp), out=__import__("io").StringIO())
    assert headline["serve/requests_completed"] == 2
    assert headline["ttft_p50_ms"] > 0


def test_serve_cli_draft_model_smoke(tmp_path, capsys):
    """Draft-model checkpoint path: serving.spec_draft=model +
    draft_model=<yaml> [+ draft_ckpt=<root>] feeds the engine's
    draft_params/draft_cfg through the CLI. The draft here is trained-0
    steps (a checkpoint written by train_dist), so the smoke only pins
    the plumbing: requests complete, spec decode runs, output budget is
    honored."""
    from hetu_galvatron_tpu.cli.serve import main as serve_main
    from hetu_galvatron_tpu.cli.train_dist import main as train_main

    draft_yaml = tmp_path / "draft.yaml"
    draft_yaml.write_text(
        "model:\n"
        "  model_name: draft-tiny\n"
        "  hidden_size: 32\n"
        "  num_hidden_layers: 1\n"
        "  num_attention_heads: 4\n"
        "  vocab_size: 257\n"
        "  max_position_embeddings: 64\n"
        "  seq_length: 16\n"
        "  make_vocab_size_divisible_by: 1\n")
    # a real draft checkpoint: two train steps of the tiny draft model
    ckdir = tmp_path / "draft_ckpt"
    assert train_main([str(draft_yaml),
                       "train.train_iters=2",
                       "parallel.mixed_precision=fp32",
                       "parallel.global_train_batch_size=8",
                       f"ckpt.save={ckdir}", "ckpt.save_interval=2"]) == 0
    capsys.readouterr()  # drain the training log

    reqs = [{"prompt": "hello world hello", "max_new_tokens": 4}]
    rp = tmp_path / "reqs.jsonl"
    rp.write_text("".join(json.dumps(r) + "\n" for r in reqs))
    rc = serve_main([
        str(draft_yaml),
        "model.vocab_size=257", "model.seq_length=64",
        "serving.max_batch_size=2", "serving.kv_block_size=8",
        "serving.max_seq_len=32",
        "serving.spec_decode=1", "serving.spec_k=2",
        "serving.spec_draft=model",
        f"draft_model={draft_yaml}", f"draft_ckpt={ckdir}",
        f"requests={rp}", f"metrics={tmp_path / 'metrics.jsonl'}"])
    assert rc == 0
    events = [json.loads(line) for line in
              capsys.readouterr().out.strip().splitlines()]
    done = [e for e in events if e["event"] == "done"]
    assert len(done) == 1 and done[0]["status"] == "done"
    assert done[0]["n_tokens"] == 4


def test_serve_cli_draft_model_requires_yaml(tmp_path, capsys):
    """spec_draft=model without draft_model= is an actionable error, not
    a deep engine traceback."""
    from hetu_galvatron_tpu.cli.serve import main as serve_main

    with pytest.raises(ValueError, match="draft_model"):
        serve_main([
            os.path.join(ZOO, "gpt2-small.yaml"),
            "model.hidden_size=32", "model.num_hidden_layers=1",
            "model.num_attention_heads=4", "model.vocab_size=257",
            "model.max_position_embeddings=64",
            "model.make_vocab_size_divisible_by=1", "model.seq_length=64",
            "serving.spec_decode=1", "serving.spec_draft=model",
            "prompt=hi", "max_new_tokens=2"])
