"""Mesh hierarchy helpers: ``dcn_factor_shape`` edge cases (satellite —
the documented raise, pp-first absorption order, dcn_slices=1 no-op) and
the hierarchical sub-mesh view (``hier_submesh`` / ``hier_cross_degree``)
the dp gradient reduction builds on."""

import numpy as np
import pytest

from hetu_galvatron_tpu.runtime.mesh import (
    HIER_HOST_AXIS,
    HIER_SLICE_AXIS,
    build_mesh,
    dcn_factor_shape,
    device_array,
    hier_cross_degree,
    hier_submesh,
)

pytestmark = [pytest.mark.core, pytest.mark.distributed]


def test_dcn_factor_shape_pp_first_absorption():
    """Slices land on pp FIRST, then the outer binary d-axes, in order —
    the 'consecutive ranks on the fast links' locality lifted to pods."""
    # pp2 x d0..d2: 2 slices fully absorbed by pp
    assert dcn_factor_shape((2, 2, 2, 2), 2) == (2, 1, 1, 1)
    # 4 slices: pp takes 2, d0 the rest
    assert dcn_factor_shape((2, 2, 2, 2), 4) == (2, 2, 1, 1)
    # 8 slices: pp, d0, d1
    assert dcn_factor_shape((2, 2, 2, 2), 8) == (2, 2, 2, 1)
    # pp=3 with 6 slices: gcd absorption (3 on pp, 2 on d0)
    assert dcn_factor_shape((3, 2, 2), 6) == (3, 2, 1)


def test_dcn_factor_shape_nonfactoring_raises_documented_message():
    with pytest.raises(ValueError,
                       match="pp \\* outer-dp must absorb the slices"):
        dcn_factor_shape((2, 2, 2), 16)
    # odd slice counts cannot divide the binary axes past pp
    with pytest.raises(ValueError,
                       match="does not factor over the leading mesh axes"):
        dcn_factor_shape((2, 2, 2), 3)


def test_dcn_slices_one_is_byte_identical_mesh(cpu_devices):
    a = device_array(8, 2, cpu_devices[:8], dcn_slices=1)
    b = device_array(8, 2, cpu_devices[:8])
    assert a.shape == b.shape
    assert all(x is y for x, y in zip(a.flat, b.flat))
    m1 = build_mesh(8, 2, devices=cpu_devices[:8], dcn_slices=1)
    m0 = build_mesh(8, 2, devices=cpu_devices[:8])
    assert m1.axis_names == m0.axis_names
    assert m1.devices.tolist() == m0.devices.tolist()


def test_hier_cross_degree_matches_dcn_absorption():
    assert hier_cross_degree(1, 8, 1) == 1
    assert hier_cross_degree(1, 8, 2) == 2
    assert hier_cross_degree(2, 4, 2) == 1   # pp absorbs the slices
    assert hier_cross_degree(2, 2, 4) == 2
    with pytest.raises(ValueError, match="does not factor"):
        hier_cross_degree(1, 2, 8)


def test_hier_submesh_regroups_dp_axes(cpu_devices):
    mesh = build_mesh(8, 1, devices=cpu_devices[:8])  # pp, d0, d1, d2
    h = hier_submesh(mesh, ("d0", "d1"), cross=2)
    assert h.axis_names == ("pp", HIER_SLICE_AXIS, HIER_HOST_AXIS, "d2")
    assert h.shape[HIER_SLICE_AXIS] == 2 and h.shape[HIER_HOST_AXIS] == 2
    # same flat device order: the view coexists with the global mesh
    assert list(h.devices.flat) == list(mesh.devices.flat)
    # degenerate cross=1 keeps the full dp degree on the host axis
    h1 = hier_submesh(mesh, ("d0", "d1"), cross=1)
    assert h1.shape[HIER_HOST_AXIS] == 4

    with pytest.raises(ValueError, match="not a contiguous run"):
        hier_submesh(mesh, ("d0", "d2"), cross=2)
    with pytest.raises(ValueError, match="does not divide"):
        hier_submesh(mesh, ("d0", "d1"), cross=3)


def test_plan_hier_dp_key_roundtrip():
    """The searched plan's hier_dp key survives the interchange format."""
    from hetu_galvatron_tpu.utils.strategy import (
        LayerStrategy,
        config2strategy,
        strategy_list2config,
    )

    layers = [LayerStrategy(pp_deg=1, tp_size=2, dp_size=4)] * 2
    cfg = strategy_list2config(layers, global_bsz=8, chunks=1,
                               hier_dp=True)
    assert cfg["hier_dp"] == 1
    _, _, extras = config2strategy(cfg, world_size=8)
    assert extras["hier_dp"] is True
    cfg2 = strategy_list2config(layers, global_bsz=8, chunks=1)
    assert "hier_dp" not in cfg2
    _, _, extras2 = config2strategy(cfg2, world_size=8)
    assert extras2["hier_dp"] is False
