"""Chaos fault-injection harness (runtime/chaos.py + tools/chaos_drill.py):
plan parsing, cross-process one-shot markers, the retry-seam injector,
a fast in-process crash-resume smoke (tier-1), the drill harness
self-check, and the full cross-process chaos matrix (slow tier — each
case drives real ``cli/supervise.py`` children and asserts bit-exact
resumed trajectories)."""

import os

import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import ChaosArgs
from hetu_galvatron_tpu.runtime.chaos import (
    ChaosCrash,
    ChaosMonkey,
    make_chaos,
    parse_plan,
)

pytestmark = [pytest.mark.robustness, pytest.mark.chaos]


@pytest.fixture(autouse=True)
def _pristine_package_logger():
    """The package logger's StreamHandler is created lazily by the first
    initialize() call and binds THAT moment's sys.stderr. The launcher
    tests create it under their own capsys stream and assert on [INFO]
    lines; the in-process smoke here runs without a capture fixture, so
    a handler it creates would pin the fd-capture tmpfile and blind
    every later capsys assertion. Restore the pre-test handler set."""
    import logging

    lg = logging.getLogger("hetu_galvatron_tpu")
    handlers, level, propagate = list(lg.handlers), lg.level, lg.propagate
    yield
    lg.handlers[:] = handlers
    lg.setLevel(level)
    lg.propagate = propagate

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")

TINY = [
    "model.hidden_size=32", "model.num_hidden_layers=2",
    "model.num_attention_heads=2", "model.vocab_size=64",
    "model.seq_length=8", "model.max_position_embeddings=16",
    "model.make_vocab_size_divisible_by=1",
    "train.train_iters=6", "parallel.mixed_precision=fp32",
    "parallel.global_train_batch_size=8",
]


# -- plan parsing ------------------------------------------------------------


def test_parse_plan_string():
    faults = parse_plan(ChaosArgs(enable=True,
                                  plan="corrupt_meta@4, crash@5, io_error"))
    assert [(f.kind, f.at_iter) for f in faults] == [
        ("corrupt_meta", 4), ("crash", 5), ("io_error", -1)]
    assert [f.index for f in faults] == [0, 1, 2]


def test_parse_plan_single_kind_fallback():
    faults = parse_plan(ChaosArgs(enable=True, kind="sigterm", at_iter=3))
    assert [(f.kind, f.at_iter) for f in faults] == [("sigterm", 3)]
    assert parse_plan(ChaosArgs(enable=True)) == []  # kind="none"


def test_parse_plan_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown kind"):
        parse_plan(ChaosArgs(enable=True, plan="crash@2,meltdown@3"))


def test_parse_plan_plumbs_io_knobs():
    (f,) = parse_plan(ChaosArgs(enable=True, kind="io_error",
                                io_error_count=5, io_error_op="dataset",
                                hang_s=9.0))
    assert f.count == 5 and f.op == "dataset" and f.hang_s == 9.0


def test_make_chaos_gating():
    class A:
        chaos = ChaosArgs()

        class ckpt:
            save = None

    assert make_chaos(A()) is None  # not enabled

    class B(A):
        chaos = ChaosArgs(enable=True)  # enabled but an empty plan

    assert make_chaos(B()) is None


# -- one-shot markers --------------------------------------------------------


def test_marker_one_shot_across_instances(tmp_path):
    """The fired marker is persisted BEFORE the fault fires, so a
    relaunched attempt (a fresh ChaosMonkey over the same state_dir)
    does not re-die at the same step."""
    cfg = ChaosArgs(enable=True, kind="crash", at_iter=2)
    m1 = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    m1.on_step(0)
    m1.on_step(1)
    with pytest.raises(ChaosCrash):
        m1.on_step(2)
    assert os.path.exists(tmp_path / "CHAOS_FIRED_0_crash")
    # the "relaunched" attempt
    m2 = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    assert m2.pending() == []
    for it in range(6):
        m2.on_step(it)  # never raises


def test_unfired_faults_rearm_on_relaunch(tmp_path):
    """A multi-fault plan unfolds across attempts: only the FIRED entry
    is consumed by the relaunch."""
    cfg = ChaosArgs(enable=True, plan="crash@1,crash@4")
    m1 = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    with pytest.raises(ChaosCrash):
        m1.on_step(1)
    m2 = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    assert m2.pending() == ["crash"]
    m2.on_step(3)
    with pytest.raises(ChaosCrash):
        m2.on_step(4)


def test_corrupt_meta_waits_for_a_commit(tmp_path):
    """corrupt_meta stays ARMED until a committed checkpoint exists —
    firing into an empty save dir would test nothing."""
    from hetu_galvatron_tpu.runtime import ckpt_paths

    cfg = ChaosArgs(enable=True, kind="corrupt_meta", at_iter=1)
    m = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    m.on_step(1)
    m.on_step(2)
    assert m.pending() == ["corrupt_meta"]  # nothing to corrupt yet
    d = tmp_path / "step_3"
    os.makedirs(d)
    ckpt_paths.atomic_write_json(str(d / "meta.json"), {"step": 3})
    with open(d / ckpt_paths.COMMIT_MARKER, "w") as f:
        f.write("ok")
    m.on_step(3)
    assert m.pending() == []
    with open(d / "meta.json") as f:
        assert f.read().startswith("{this is not json")


# -- the retry seam ----------------------------------------------------------


def test_io_faults_inject_through_retry_call(tmp_path):
    from hetu_galvatron_tpu.utils.retrying import retry_call

    cfg = ChaosArgs(enable=True, kind="io_error", io_error_count=2,
                    io_error_op="checkpoint")
    m = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    m.install()
    calls = []
    try:
        out = retry_call(lambda: calls.append(1) or "ok", attempts=4,
                         op="checkpoint.read_meta", sleep=lambda s: None)
        assert out == "ok"
        assert len(calls) == 1  # two attempts eaten by injection
        # non-matching ops pass through untouched
        assert retry_call(lambda: "ok", attempts=1, op="dataset.fetch",
                          sleep=lambda s: None) == "ok"
    finally:
        m.uninstall()
    assert m.pending() == []  # exhausted count == fired
    # uninstalled: no injection remains
    assert retry_call(lambda: "ok", attempts=1, op="checkpoint.read_meta",
                      sleep=lambda s: None) == "ok"


def test_io_fault_gated_by_at_iter(tmp_path):
    cfg = ChaosArgs(enable=True, plan="io_error@3")
    m = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    m.on_step(1)
    assert m._io_fault("checkpoint.restore") is None  # not yet armed
    m.on_step(3)
    assert isinstance(m._io_fault("checkpoint.restore"), OSError)


def test_hung_save_hook_gated_by_step(tmp_path):
    """The before_commit hook's step gate: a save of an EARLIER step than
    at_iter must not trip the hang."""
    import time

    cfg = ChaosArgs(enable=True, kind="hung_save", at_iter=4)
    cfg.hang_s = 0.2
    m = ChaosMonkey(cfg, state_dir=str(tmp_path), log=lambda m: None)
    hook = m.save_hooks()["before_commit"]
    t0 = time.monotonic()
    hook(str(tmp_path / "step_2.tmp"))
    assert time.monotonic() - t0 < 0.15  # below at_iter: no stall
    assert m.pending() == ["hung_save"]
    hook(str(tmp_path / "step_4.tmp"))
    assert time.monotonic() - t0 >= 0.2
    assert m.pending() == []


# -- in-process crash smoke (tier-1) -----------------------------------------


@pytest.mark.distributed
def test_chaos_crash_smoke_resumes_bit_exact(tmp_path):
    """The fast chaos leg: a ChaosCrash at step 3 through the REAL
    training loop + in-process restart supervisor; the stitched loss
    trajectory must equal the uninterrupted baseline bit for bit."""
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.runtime.supervisor import run_with_restarts

    def _args(extra):
        return args_from_cli(
            [os.path.join(ZOO, "gpt2-small.yaml")] + TINY + extra,
            mode="train_dist")

    baseline = train(_args([]))["losses"]
    assert len(baseline) == 6

    args = _args([f"ckpt.save={tmp_path}", "ckpt.save_interval=2",
                  "chaos.enable=true", "chaos.plan=crash@3"])
    outs = []

    def attempt():
        if args.ckpt.save and not args.ckpt.load:
            args.ckpt.load = args.ckpt.save
        # ChaosCrash propagates: raised exceptions ARE the in-process
        # supervisor's crash-restart path (returned codes are contracts)
        out = train(args)
        outs.append(out)
        return out.get("exit_code") or 0

    rc = run_with_restarts(attempt, max_restarts=3, base_delay=0.0,
                           sleep=lambda s: None, log=lambda m: None)
    assert rc == 0
    assert len(outs) == 1  # attempt 1 crashed before returning
    assert os.path.exists(tmp_path / "CHAOS_FIRED_0_crash")
    # attempt 2 resumed from step_2 (the commit at iter 1) and replayed
    # steps 2..5: its trajectory must be the baseline tail exactly
    np.testing.assert_array_equal(np.asarray(outs[0]["losses"]),
                                  np.asarray(baseline[2:]))


def test_chaos_drill_harness_smoke(tmp_path):
    """tools/chaos_drill.py --smoke: the supervisor/exit-code/receipt/pin
    machinery with synthetic children (no jax) — also run by
    ``__graft_entry__.dryrun_multichip``."""
    from tools.chaos_drill import smoke

    smoke(str(tmp_path))


# -- the full matrix (slow tier: real supervised train_dist children) --------


@pytest.fixture(scope="session")
def chaos_baseline(tmp_path_factory):
    from tools.chaos_drill import run_baseline

    return run_baseline(str(tmp_path_factory.mktemp("chaos_matrix")))


def _matrix_case(name, tmp_path_factory, baseline):
    from tools.chaos_drill import run_case

    msg = run_case(name, str(tmp_path_factory.mktemp(f"chaos_{name}")),
                   baseline=baseline)
    assert name.split("_")[0] in msg


def test_chaos_matrix_crash(tmp_path_factory, chaos_baseline):
    _matrix_case("crash", tmp_path_factory, chaos_baseline)


def test_chaos_matrix_preempt(tmp_path_factory, chaos_baseline):
    _matrix_case("preempt", tmp_path_factory, chaos_baseline)


def test_chaos_matrix_kill_mid_save(tmp_path_factory, chaos_baseline):
    _matrix_case("kill_mid_save", tmp_path_factory, chaos_baseline)


def test_chaos_matrix_corrupt_meta(tmp_path_factory, chaos_baseline):
    _matrix_case("corrupt_meta", tmp_path_factory, chaos_baseline)


def test_chaos_matrix_transient_io(tmp_path_factory, chaos_baseline):
    _matrix_case("transient_io", tmp_path_factory, chaos_baseline)


def test_chaos_matrix_hung_save(tmp_path_factory, chaos_baseline):
    _matrix_case("hung_save", tmp_path_factory, chaos_baseline)


def test_chaos_matrix_budget(tmp_path_factory, chaos_baseline):
    _matrix_case("budget", tmp_path_factory, chaos_baseline)
