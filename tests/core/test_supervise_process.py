"""Cross-process supervisor (runtime/supervisor.ProcessSupervisor +
cli/supervise.py): exit-code contract against real child PROCESSES
(cheap ``python -c`` children — the full train_dist drills live in
test_chaos.py's slow tier), atomic state persistence, commit receipts
resetting the restart budget, the RESUME_PIN lease lifecycle, signal
forwarding with grace escalation, and the /healthz liveness payload."""

import json
import os
import signal
import sys
import urllib.request

import pytest

from hetu_galvatron_tpu.runtime import ckpt_paths
from hetu_galvatron_tpu.runtime.supervisor import (
    ProcessSupervisor,
    SupervisorState,
)

pytestmark = pytest.mark.robustness


def _await_file(path, timeout_s=20.0):
    import time

    deadline = time.monotonic() + timeout_s
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError(f"child never signalled ready at {path}")
        time.sleep(0.01)


def _sup(argv_fn, **kw):
    kw.setdefault("base_delay", 0.0)
    kw.setdefault("max_delay", 0.0)
    kw.setdefault("poll_interval", 0.02)
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("log", lambda m: None)
    return ProcessSupervisor(argv_fn, **kw)


def _exit_child(code):
    return lambda st: [sys.executable, "-c", f"import sys; sys.exit({code})"]


def _commit(root, step, world=1):
    d = os.path.join(root, f"step_{step}")
    os.makedirs(d, exist_ok=True)
    ckpt_paths.atomic_write_json(
        os.path.join(d, "meta.json"),
        {"iteration": step, "hybrid_parallel_config": {"world_size": world}})
    with open(os.path.join(d, ckpt_paths.COMMIT_MARKER), "w") as f:
        f.write("ok")
    return d


# -- exit-code contract ------------------------------------------------------


def test_clean_exit_stops(tmp_path):
    sup = _sup(_exit_child(0), state_file=str(tmp_path / "s.json"))
    assert sup.run() == 0
    assert sup.state.attempt == 1


def test_code_17_terminal_no_restart(tmp_path):
    sup = _sup(_exit_child(17), state_file=str(tmp_path / "s.json"))
    assert sup.run() == 17
    assert sup.state.attempt == 1  # never relaunched


def test_sigint_130_terminal(tmp_path):
    sup = _sup(_exit_child(130), state_file=str(tmp_path / "s.json"))
    assert sup.run() == 130
    assert sup.state.attempt == 1


def test_usage_error_terminal(tmp_path):
    """Positive codes outside the contract (argparse's 2) are a
    misconfiguration: restarting only burns the budget."""
    sup = _sup(_exit_child(2), state_file=str(tmp_path / "s.json"))
    assert sup.run() == 2
    assert sup.state.attempt == 1


def test_restartable_codes_relaunch_until_budget(tmp_path):
    sup = _sup(_exit_child(18), state_file=str(tmp_path / "s.json"),
               max_restarts=2)
    assert sup.run() == 18  # budget spent, code surfaced
    assert sup.state.attempt == 3


def test_crash_code_1_restarts_when_enabled(tmp_path):
    sup = _sup(_exit_child(1), state_file=str(tmp_path / "s.json"),
               max_restarts=1)
    assert sup.run() == 1
    assert sup.state.attempt == 2


def test_crash_terminal_when_restart_on_error_off(tmp_path):
    sup = _sup(_exit_child(1), state_file=str(tmp_path / "s.json"),
               restart_on_error=False)
    assert sup.run() == 1
    assert sup.state.attempt == 1


def test_signal_death_surfaces_128_plus_signum(tmp_path):
    kill = lambda st: [sys.executable, "-c",
                       "import os, signal; os.kill(os.getpid(), 9)"]
    sup = _sup(kill, state_file=str(tmp_path / "s.json"), max_restarts=1)
    assert sup.run() == 137  # shell convention for SIGKILL
    assert sup.state.attempt == 2  # a signal death IS restartable


# -- progress receipts -------------------------------------------------------


def test_commit_receipt_resets_restart_budget(tmp_path):
    """A child that commits a NEW checkpoint before dying never exhausts
    the budget — the cross-process analogue of run_with_restarts'
    progress_fn."""
    root = str(tmp_path / "ck")
    os.makedirs(root)
    script = (
        "import os, sys\n"
        f"root = {root!r}\n"
        "steps = sorted(int(d[5:]) for d in os.listdir(root)\n"
        "               if d.startswith('step_') and d[5:].isdigit())\n"
        "n = (steps[-1] if steps else 0) + 1\n"
        "if n > 3:\n"
        "    sys.exit(0)\n"
        "d = os.path.join(root, f'step_{n}')\n"
        "os.makedirs(d)\n"
        "import json\n"
        "json.dump({'iteration': n,\n"
        "           'hybrid_parallel_config': {'world_size': 1}},\n"
        "          open(os.path.join(d, 'meta.json'), 'w'))\n"
        "open(os.path.join(d, 'COMMITTED'), 'w').write('ok')\n"
        "sys.exit(18)\n")
    sup = _sup(lambda st: [sys.executable, "-c", script],
               save_dir=root, max_restarts=1)  # budget 1, but 3 preempts
    assert sup.run() == 0
    assert sup.state.attempt == 4
    assert sup.state.last_commit_step == 3


def test_world_change_is_progress_within_budget(tmp_path):
    root = str(tmp_path / "ck")
    os.makedirs(root)
    _commit(root, 1, world=4)
    # run() probes once at init, then once per attempt: world 4 at init
    # and attempt 1, shrinks to 2 at attempt 2
    worlds = iter([4, 4, 2])
    sup = _sup(_exit_child(18), save_dir=root, max_restarts=2,
               max_world_changes=8, world_fn=lambda: next(worlds, 2))
    assert sup.run() == 18
    # attempts: 1 (r0->1), 2 (world change resets, r0->1), 3 (r1->2),
    # 4 (budget spent)
    assert sup.state.attempt == 4
    assert sup.state.world_changes == 1


def test_world_change_budget_bounds_flapping(tmp_path):
    """A fleet that flaps topology every attempt must still terminate:
    past max_world_changes, a change no longer resets the budget."""
    root = str(tmp_path / "ck")
    os.makedirs(root)
    _commit(root, 1)
    w = [0]

    def world():
        w[0] += 1
        return w[0]  # different every probe

    sup = _sup(_exit_child(18), save_dir=root, max_restarts=1,
               max_world_changes=2, world_fn=world)
    assert sup.run() == 18
    assert sup.state.world_changes == 2  # budget pinned at the cap


# -- state persistence -------------------------------------------------------


def test_state_roundtrip_atomic(tmp_path):
    p = str(tmp_path / "s.json")
    st = SupervisorState(attempt=5, restarts=2, world_changes=1,
                        last_exit_code=18, last_commit_step=40,
                        last_commit_wall=123.0, last_world=8, backoff_s=1.5)
    st.save(p)
    st2 = SupervisorState.load(p)
    assert st2 == st
    assert not os.path.exists(p + ".tmp")


def test_state_survives_supervisor_restart(tmp_path):
    """A preempted supervisor resumes with the budgets it had, not a
    fresh allowance."""
    p = str(tmp_path / "s.json")
    sup = _sup(_exit_child(18), state_file=p, max_restarts=2)
    assert sup.run() == 18
    sup2 = _sup(_exit_child(18), state_file=p, max_restarts=2)
    # budget already spent in the previous incarnation: no relaunch
    assert sup2.run() == 18
    assert sup2.state.attempt == sup.state.attempt + 1


def test_corrupt_state_file_degrades_to_fresh(tmp_path):
    p = str(tmp_path / "s.json")
    with open(p, "w") as f:
        f.write("{torn")
    st = SupervisorState.load(p)
    assert st == SupervisorState()


# -- RESUME_PIN lease --------------------------------------------------------


def test_pin_written_before_relaunch_and_cleared_on_success(tmp_path):
    root = str(tmp_path / "ck")
    os.makedirs(root)
    _commit(root, 7)
    seen = []

    def argv(st):
        seen.append(ckpt_paths.read_resume_pin(root))
        return _exit_child(0)(st)

    sup = _sup(argv, save_dir=root)
    assert sup.run() == 0
    assert seen == [os.path.join(root, "step_7")]  # pinned at spawn time
    assert ckpt_paths.read_resume_pin(root) is None  # cleared on success


def test_pin_respected_by_gc(tmp_path):
    """The cross-process half of the GC race fix: retention in ANOTHER
    process must not prune the pinned step dir."""
    from hetu_galvatron_tpu.runtime.checkpoint import gc_checkpoints

    root = str(tmp_path / "ck")
    os.makedirs(root)
    for s in (1, 2, 3):
        _commit(root, s)
    ckpt_paths.write_resume_pin(root, os.path.join(root, "step_1"))
    removed = gc_checkpoints(root, keep_last=1)
    assert os.path.isdir(os.path.join(root, "step_1"))  # pinned survivor
    assert os.path.isdir(os.path.join(root, "step_3"))  # newest survivor
    assert os.path.join(root, "step_2") in removed


def test_expired_pin_reads_absent(tmp_path):
    root = str(tmp_path)
    d = _commit(root, 1)
    ckpt_paths.write_resume_pin(root, d)
    assert ckpt_paths.read_resume_pin(root) == os.path.abspath(d)
    assert ckpt_paths.read_resume_pin(root, ttl_s=0.0) is None


# -- signal forwarding -------------------------------------------------------


def test_sigterm_forwarded_child_exits_loop_terminal(tmp_path):
    """SIGTERM to the supervisor forwards to the child and makes the
    loop terminal — the fleet preempted US; never relaunch."""
    ready = str(tmp_path / "ready")
    script = ("import signal, sys, time\n"
              "signal.signal(signal.SIGTERM, lambda *a: sys.exit(18))\n"
              f"open({ready!r}, 'w').write('up')\n"
              "time.sleep(30)\n")
    sup = _sup(lambda st: [sys.executable, "-c", script],
               state_file=str(tmp_path / "s.json"), term_grace_s=10.0)
    fired = []
    orig_wait = sup._wait

    def wait_and_signal(child):
        if not fired:
            fired.append(1)
            _await_file(ready)  # handler installed before we deliver
            # deliver the stop the way the handler would (tests run on
            # pytest's main thread but the handler itself is thread-safe)
            sup._child = child
            sup._on_signal(signal.SIGTERM, None)
        return orig_wait(child)

    sup._wait = wait_and_signal
    assert sup.run() == 18
    assert sup.state.attempt == 1
    assert not sup.escalated  # the child honored the grace window


def test_grace_escalation_kills_a_wedged_child(tmp_path):
    """A child that ignores SIGTERM is SIGKILL'd after term_grace_s —
    a preempted supervisor must hand back before the fleet's deadline."""
    ready = str(tmp_path / "ready")
    script = ("import signal, time\n"
              "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
              f"open({ready!r}, 'w').write('up')\n"
              "time.sleep(60)\n")
    sup = _sup(lambda st: [sys.executable, "-c", script],
               state_file=str(tmp_path / "s.json"), term_grace_s=0.3)
    fired = []
    orig_wait = sup._wait

    def wait_and_signal(child):
        if not fired:
            fired.append(1)
            _await_file(ready)  # SIG_IGN installed before we deliver
            sup._child = child
            sup._on_signal(signal.SIGTERM, None)
        return orig_wait(child)

    sup._wait = wait_and_signal
    rc = sup.run()
    assert sup.escalated  # the kill timer had to fire
    assert sup.state.attempt == 1  # terminal, not a restart
    assert rc == 18  # surfaced as the preemption code


# -- liveness ----------------------------------------------------------------


def test_health_payload_fields(tmp_path):
    root = str(tmp_path / "ck")
    os.makedirs(root)
    _commit(root, 9)
    sup = _sup(_exit_child(18), save_dir=root, max_restarts=1)
    assert sup.run() == 18
    h = sup.health()
    assert h["supervisor_attempt"] == 2
    assert h["last_child_exit_code"] == 18
    assert h["last_commit_step"] == 9
    assert h["last_commit_age_s"] >= 0.0
    assert h["child_alive"] is False
    json.dumps(h)  # must be wire-serializable for /healthz


def test_healthz_endpoint_serves_supervisor_fields(tmp_path):
    from hetu_galvatron_tpu.observability.prometheus import MetricsHTTPServer

    root = str(tmp_path / "ck")
    os.makedirs(root)
    _commit(root, 3)
    sup = _sup(_exit_child(0), save_dir=root)
    assert sup.run() == 0
    server = MetricsHTTPServer(port=0, health_fn=sup.health)
    port = server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            payload = json.loads(r.read())
    finally:
        server.stop()
    assert payload["status"] == "ok"
    assert payload["supervisor_attempt"] == 1
    assert payload["last_commit_step"] == 3


def test_supervisor_events_emitted(tmp_path):
    """The supervisor timeline (spawn / child_exit / done events) lands
    in the registry's sinks — cli/summarize.py renders it."""
    from hetu_galvatron_tpu.observability.registry import MetricsRegistry
    from hetu_galvatron_tpu.observability.sinks import JsonlSink

    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    sup = _sup(_exit_child(0), state_file=str(tmp_path / "s.json"),
               registry=reg)
    assert sup.run() == 0
    reg.close()
    events = [json.loads(l)["data"]["event"] for l in open(path)
              if json.loads(l).get("name") == "supervisor"]
    assert events[0] == "spawn"
    assert "child_exit" in events
    assert events[-1] == "done"
