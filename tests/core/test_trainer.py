"""Trainer/optimizer/dataloader unit tests: schedules, decay masking,
microbatch-accumulation equivalence, and a short loss-goes-down run."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.runtime.dataloader import (
    RandomTokenDataset,
    get_data_iterator,
    make_batch,
    synthetic_batches,
)
from hetu_galvatron_tpu.runtime.optimizer import (
    global_grad_norm,
    make_lr_schedule,
    make_optimizer,
)
from hetu_galvatron_tpu.runtime.trainer import (
    make_loss_fn,
    make_train_step,
    train_loop,
)

pytestmark = pytest.mark.utils

TINY = ModelArgs(
    hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
    vocab_size=64, max_position_embeddings=32, seq_length=8,
    make_vocab_size_divisible_by=1,
)


def test_lr_schedules():
    for style in ["constant", "linear", "cosine", "inverse-square-root", "WSD"]:
        t = TrainArgs(lr=1e-3, min_lr=1e-5, lr_decay_style=style,
                      lr_warmup_iters=10, train_iters=100,
                      lr_wsd_decay_iters=20)
        sched = make_lr_schedule(t)
        # warmup ramps from 0
        assert float(sched(0)) < 1e-4
        assert abs(float(sched(10)) - 1e-3) < 1e-4
        final = float(sched(99))
        if style != "constant":
            assert final < 1e-3 + 1e-9
        assert final >= 0.0


def test_optimizer_decay_mask_and_step():
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,)),
              "scale": jnp.ones((4,))}
    t = TrainArgs(lr=0.1, weight_decay=0.5, lr_warmup_iters=0,
                  lr_decay_style="constant", clip_grad=0.0)
    tx = make_optimizer(t)
    state = tx.init(params)
    zero_g = jax.tree.map(jnp.zeros_like, params)
    upd, _ = tx.update(zero_g, state, params)
    # zero grads: 2D weight decays, 1D bias/scale must not move
    assert float(jnp.abs(upd["w"]).sum()) > 0
    assert float(jnp.abs(upd["b"]).sum()) == 0
    assert float(jnp.abs(upd["scale"]).sum()) == 0


def test_global_grad_norm():
    g = {"a": jnp.full((2, 2), 3.0), "b": jnp.full((3,), 4.0)}
    expect = np.sqrt(4 * 9 + 3 * 16)
    assert abs(float(global_grad_norm(g)) - expect) < 1e-5


def test_dataset_deterministic_and_batch_shapes():
    ds1 = RandomTokenDataset(64, 8, size=16, seed=7)
    ds2 = RandomTokenDataset(64, 8, size=16, seed=7)
    np.testing.assert_array_equal(ds1[3], ds2[3])
    b = make_batch(np.stack([ds1[0], ds1[1]]))
    assert b["tokens"].shape == (2, 8)
    np.testing.assert_array_equal(b["labels"][:, :-1], b["tokens"][:, 1:])
    it = synthetic_batches(TINY, 4)
    first = next(it)
    assert first["tokens"].shape == (4, 8)
    assert first["tokens"].max() < TINY.padded_vocab_size


@pytest.mark.slow
def test_microbatch_accumulation_matches_full_batch():
    params, _ = init_causal_lm(jax.random.key(0), TINY)
    loss_fn = make_loss_fn(TINY, compute_dtype=jnp.float32)
    t = TrainArgs(lr=1e-2, clip_grad=0.0, weight_decay=0.0,
                  lr_decay_style="constant", lr_warmup_iters=0)
    tx = make_optimizer(t)
    step1 = jax.jit(make_train_step(loss_fn, tx, chunks=1))
    step4 = jax.jit(make_train_step(loss_fn, tx, chunks=4))
    batch = make_batch(
        np.random.RandomState(0).randint(0, 64, (8, 9)).astype(np.int32))
    batch = jax.tree.map(jnp.asarray, batch)
    opt = tx.init(params)
    p1, _, m1 = step1(params, opt, batch)
    p4, _, m4 = step4(params, opt, batch)
    assert abs(float(m1["loss"]) - float(m4["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_train_loop_loss_decreases():
    args = CoreArgs(model=TINY.model_dump())
    args.train.train_iters = 25
    args.train.lr = 1e-2
    args.parallel.mixed_precision = "fp32"
    params, _ = init_causal_lm(jax.random.key(0), args.model)
    # size=8 == batch size: the same batch repeats, so the model can
    # memorize it (uniform random tokens are otherwise irreducible)
    it = synthetic_batches(args.model, 8, size=8)
    _, _, losses = train_loop(args, params, it)
    assert losses[-1] < losses[0] - 0.5
    assert np.isfinite(losses).all()


def test_get_data_iterator_random():
    args = CoreArgs(model=TINY.model_dump())
    b = next(get_data_iterator(args, global_batch_size=4))
    assert b["tokens"].shape == (4, TINY.seq_length)


@pytest.mark.slow
def test_microbatch_nonuniform_loss_mask_matches():
    """chunks>1 must equal chunks=1 even when microbatches carry very
    different numbers of valid tokens (token-weighted accumulation)."""
    params, _ = init_causal_lm(jax.random.key(0), TINY)
    from hetu_galvatron_tpu.runtime.trainer import make_loss_fn
    loss_fn = make_loss_fn(TINY, compute_dtype=jnp.float32)
    t = TrainArgs(lr=1e-2, clip_grad=0.0, weight_decay=0.0,
                  lr_decay_style="constant", lr_warmup_iters=0)
    tx = make_optimizer(t)
    step1 = jax.jit(make_train_step(loss_fn, tx, chunks=1))
    step4 = jax.jit(make_train_step(loss_fn, tx, chunks=4))
    batch = make_batch(
        np.random.RandomState(0).randint(0, 64, (8, 9)).astype(np.int32))
    mask = np.ones((8, 8), np.float32)
    mask[:2] = 0.0          # first microbatch fully masked
    mask[2, 4:] = 0.0       # second partially masked
    batch["loss_mask"] = mask
    batch = jax.tree.map(jnp.asarray, batch)
    opt = tx.init(params)
    p1, _, m1 = step1(params, opt, batch)
    p4, _, m4 = step4(params, opt, batch)
    assert abs(float(m1["loss"]) - float(m4["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
