"""The driver bench must ALWAYS land one parseable JSON line with rc=0
(VERDICT r3 #1): measurement legs run in throwaway subprocesses journaling
results as they arrive; a leg that stops making progress is abandoned (never
killed) and the parent still emits a result."""

import json
import os
import signal
import subprocess
import sys

import pytest

pytestmark = pytest.mark.core

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(tmp_path, extra_env, timeout=600):
    env = dict(os.environ)
    # never let the test process's conftest platform pin leak confusion:
    # bench children set their own platform env
    env.update({
        "BENCH_PLATFORM": "cpu",
        "BENCH_TINY": "1",
        "BENCH_SEQ": "128",
        "BENCH_BSZ": "2",
        "BENCH_ITERS": "1",
        "BENCH_JOURNAL": str(tmp_path / "journal.jsonl"),
        "BENCH_TIMEOUT": "300",
        # the compiled_overlap leg (default-on) runs the dispatch bench's
        # own ~2-minute reference workload — these tests exercise the
        # orchestration lifecycle, not that leg (covered by
        # test_pipeline_dispatch_bench), and it would crowd the 300s
        # watchdog budget
        "BENCH_COMPILED_OVERLAP": "0",
        # likewise the default-on serving A/B legs (covered by
        # tests/serving/test_serve_bench.py)
        "BENCH_SERVE_PREFIX": "0",
        "BENCH_SPEC_DECODE": "0",
        # and the default-on hierarchical-dp A/B leg (covered by
        # tests/core/test_hier_dp_bench.py)
        "BENCH_HIER_DP": "0",
    })
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, BENCH], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=timeout)
    return proc


def _parse_line(proc):
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    return json.loads(lines[0])


def test_bench_cpu_smoke_lands_result(tmp_path):
    proc = _run_bench(tmp_path, {})
    assert proc.returncode == 0, proc.stderr
    out = _parse_line(proc)
    assert out["metric"] == "gpt2_125m_train_mfu"
    assert out["value"] > 0
    assert out["unit"] == "% MFU"
    assert "vs_baseline" in out
    # the journal recorded the full leg lifecycle
    journal = [json.loads(ln) for ln in
               (tmp_path / "journal.jsonl").read_text().splitlines()]
    statuses = [ln["status"] for ln in journal]
    assert statuses[0] == "start"
    assert "compiled" in statuses
    assert statuses[-1] == "ok"


def _abandoned_pids(proc):
    return [int(p) for line in proc.stderr.splitlines()
            for w in [line.split("pid ")]
            if len(w) > 1
            for p in [w[1].split()[0].rstrip(")")] if p.isdigit()]


def test_bench_wedged_cpu_leg_terminated(tmp_path):
    """A hung CPU leg is abandoned via the progress-stall path (it journals
    'start' before hanging, so the stage is never 'spawn') and, since a CPU
    child cannot hold the TPU tunnel, it is terminated rather than leaked."""
    proc = _run_bench(tmp_path, {
        "BENCH_FAKE_WEDGE": "1",
        "BENCH_FAKE_WEDGE_SECS": "120",
        "BENCH_PROGRESS_TIMEOUT": "15",
    })
    assert proc.returncode == 0, proc.stderr
    out = _parse_line(proc)
    assert out["value"] == 0.0
    assert "error" in out
    assert "abandoned" in proc.stderr
    # the stall detector (not a past-deadline bug) must be what fired: the
    # child journals 'start' (and usually 'device') before the fake wedge
    assert "stage 'spawn'" not in proc.stderr, proc.stderr
    assert "terminated" in proc.stderr
    pids = _abandoned_pids(proc)
    assert pids, f"no abandoned pid reported in: {proc.stderr!r}"
    for pid in pids:  # cleanup if terminate lost the race; must not linger
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def test_bench_wedged_leg_abandoned_never_kill(tmp_path):
    """With BENCH_NEVER_KILL (the TPU-leg policy, forced on for the test),
    the hung child is left running — abandoned, never signalled."""
    proc = _run_bench(tmp_path, {
        "BENCH_FAKE_WEDGE": "1",
        "BENCH_FAKE_WEDGE_SECS": "120",
        "BENCH_PROGRESS_TIMEOUT": "15",
        "BENCH_NEVER_KILL": "1",
    })
    assert proc.returncode == 0, proc.stderr
    out = _parse_line(proc)
    assert out["value"] == 0.0
    assert "abandoned" in proc.stderr
    assert "left running" in proc.stderr
    pids = _abandoned_pids(proc)
    assert pids, f"no abandoned pid reported in: {proc.stderr!r}"
    for pid in pids:
        try:
            os.kill(pid, 0)  # still running
        except ProcessLookupError:
            pytest.fail(f"abandoned child {pid} is gone — was it killed?")
        os.kill(pid, signal.SIGKILL)  # cleanup (cpu child: safe in test)
