"""The driver bench must ALWAYS land one parseable JSON line with rc=0
(VERDICT r3 #1): measurement legs run in throwaway subprocesses journaling
results as they arrive; a leg that stops making progress is abandoned (never
killed) and the parent still emits a result."""

import json
import os
import signal
import subprocess
import sys

import pytest

pytestmark = pytest.mark.core

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(tmp_path, extra_env, timeout=600):
    env = dict(os.environ)
    # never let the test process's conftest platform pin leak confusion:
    # bench children set their own platform env
    env.update({
        "BENCH_PLATFORM": "cpu",
        "BENCH_TINY": "1",
        "BENCH_SEQ": "128",
        "BENCH_BSZ": "2",
        "BENCH_ITERS": "1",
        "BENCH_JOURNAL": str(tmp_path / "journal.jsonl"),
        "BENCH_TIMEOUT": "300",
    })
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, BENCH], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=timeout)
    return proc


def _parse_line(proc):
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    return json.loads(lines[0])


def test_bench_cpu_smoke_lands_result(tmp_path):
    proc = _run_bench(tmp_path, {})
    assert proc.returncode == 0, proc.stderr
    out = _parse_line(proc)
    assert out["metric"] == "gpt2_125m_train_mfu"
    assert out["value"] > 0
    assert out["unit"] == "% MFU"
    assert "vs_baseline" in out
    # the journal recorded the full leg lifecycle
    journal = [json.loads(ln) for ln in
               (tmp_path / "journal.jsonl").read_text().splitlines()]
    statuses = [ln["status"] for ln in journal]
    assert statuses[0] == "start"
    assert "compiled" in statuses
    assert statuses[-1] == "ok"


def test_bench_wedged_leg_abandoned_not_killed(tmp_path):
    """A leg that hangs is abandoned: the parent emits a zero result with an
    error annotation, rc stays 0, and the child is left running (never
    signalled)."""
    proc = _run_bench(tmp_path, {
        "BENCH_FAKE_WEDGE": "1",
        "BENCH_FAKE_WEDGE_SECS": "60",
        "BENCH_PROGRESS_TIMEOUT": "5",
    })
    assert proc.returncode == 0, proc.stderr
    out = _parse_line(proc)
    assert out["value"] == 0.0
    assert "error" in out
    assert "abandoned" in proc.stderr
    # the abandoned child must still be alive (it was not killed); reap it
    # here so the test doesn't leak a sleeper
    pids = [int(p) for line in proc.stderr.splitlines()
            for w in [line.split("pid ")]
            if len(w) > 1
            for p in [w[1].split()[0].rstrip(")")] if p.isdigit()]
    assert pids, f"no abandoned pid reported in: {proc.stderr!r}"
    for pid in pids:
        try:
            os.kill(pid, 0)  # still running
        except ProcessLookupError:
            pytest.fail(f"abandoned child {pid} is gone — was it killed?")
        os.kill(pid, signal.SIGKILL)  # cleanup (cpu child: safe in test)
