"""Batch-size ramp calculator + rebatcher + launcher integration
(reference num_microbatches_calculator.py semantics)."""

import numpy as np
import pytest

from hetu_galvatron_tpu.runtime.microbatches import (
    MicroBatchCalculator,
    Rebatcher,
)

pytestmark = pytest.mark.core


def test_constant_calculator():
    c = MicroBatchCalculator(global_batch_size=16, micro_batch_size=2,
                             dp_size=2)
    assert c.get() == 4  # 16 / (2*2)
    assert c.get_current_global_batch_size() == 16
    assert not c.is_ramping
    assert not c.update(1000)  # never changes


def test_ramp_schedule_matches_reference_semantics():
    # start 4 -> 16 by 4 over 24 samples: 3 increments, 8 samples each
    c = MicroBatchCalculator(global_batch_size=16, micro_batch_size=2,
                             dp_size=1, rampup_batch_size=[4, 4, 24])
    assert c.get_current_global_batch_size() == 4
    assert c.get() == 2
    c.update(7)   # still inside the first 8-sample window
    assert c.get_current_global_batch_size() == 4
    c.update(8)
    assert c.get_current_global_batch_size() == 8
    c.update(16)
    assert c.get_current_global_batch_size() == 12
    c.update(25)  # past ramp_samples -> target
    assert c.get_current_global_batch_size() == 16
    assert c.get() == 8


def test_ramp_full_schedule():
    c = MicroBatchCalculator(global_batch_size=8, micro_batch_size=2,
                             dp_size=1, rampup_batch_size=[2, 2, 12])
    # 3 increments over 12 samples -> 4-sample windows
    assert c.schedule(30) == [2, 2, 4, 6, 8, 8]


def test_indivisible_ramp_step():
    with pytest.raises(ValueError):
        MicroBatchCalculator(global_batch_size=16, micro_batch_size=3,
                             dp_size=1, rampup_batch_size=[4, 4, 8])
    # decrease_batch_size_if_needed rounds down instead
    c = MicroBatchCalculator(global_batch_size=18, micro_batch_size=4,
                             dp_size=1, rampup_batch_size=[6, 6, 8],
                             decrease_batch_size_if_needed=True)
    assert c.get_current_running_global_batch_size() == 4  # 6 -> round to 4
    c.update(100)
    assert c.get_current_running_global_batch_size() == 16  # 18 -> 16


def test_rebatcher_preserves_sample_order():
    def stream():
        i = 0
        while True:
            yield {"tokens": np.arange(i, i + 8)}
            i += 8

    rb = Rebatcher(stream())
    got = []
    for n in (2, 2, 4, 6, 8):
        b = rb.next_batch(n)
        assert len(b["tokens"]) == n
        got.extend(b["tokens"].tolist())
    assert got == list(range(22))


@pytest.mark.slow
def test_train_dist_rampup_cli(capsys):
    import os

    from hetu_galvatron_tpu.cli.train_dist import main

    ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    rc = main([os.path.join(ZOO, "gpt2-small.yaml"),
               "model.hidden_size=32", "model.num_hidden_layers=2",
               "model.num_attention_heads=2", "model.vocab_size=64",
               "model.seq_length=16", "model.max_position_embeddings=16",
               "model.make_vocab_size_divisible_by=1",
               "model.ffn_hidden_size=64",
               "train.train_iters=6", "parallel.mixed_precision=fp32",
               "parallel.global_train_batch_size=8", "parallel.chunks=4",
               "parallel.global_tp_deg=4",
               "train.rampup_batch_size=[2,2,12]"])
    cap = capsys.readouterr()
    log = cap.out + cap.err
    assert rc == 0
    assert "batch-size ramp" in log
    assert "ramping global batch size" in log
    assert "training done: 6 iters" in cap.out


@pytest.mark.distributed
@pytest.mark.slow
def test_train_dist_rampup_pipeline_cli(capsys):
    import os

    from hetu_galvatron_tpu.cli.train_dist import main

    ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    rc = main([os.path.join(ZOO, "gpt2-small.yaml"),
               "model.hidden_size=32", "model.num_hidden_layers=4",
               "model.num_attention_heads=2", "model.vocab_size=64",
               "model.seq_length=16", "model.max_position_embeddings=16",
               "model.make_vocab_size_divisible_by=1",
               "model.ffn_hidden_size=64", "model.tie_word_embeddings=false",
               "train.train_iters=5", "parallel.mixed_precision=fp32",
               "parallel.global_train_batch_size=16", "parallel.chunks=4",
               "parallel.pp_deg=2",
               "train.rampup_batch_size=[4,4,16]"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "training done: 5 iters" in out


def test_ramp_samples_zero_jumps_to_target():
    c = MicroBatchCalculator(global_batch_size=16, micro_batch_size=2,
                             dp_size=1, rampup_batch_size=[4, 4, 0])
    assert c.get_current_global_batch_size() == 16
    assert c.get() == 8
