"""Rerun state machine: NaN/spike detection, transient-vs-persistent
attribution, replayable iterator, error injection (reference
rerun_state_machine.py behaviors)."""

import math

import pytest

from hetu_galvatron_tpu.core.args_schema import RerunArgs
from hetu_galvatron_tpu.runtime.rerun_machine import (
    EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
    RerunDataIterator,
    RerunDiagnostic,
    RerunStateMachine,
)

pytestmark = pytest.mark.utils


def _machine(**kw):
    return RerunStateMachine(RerunArgs(enable=True, mode="validate_results",
                                       **kw))


def test_disabled_passthrough():
    m = RerunStateMachine(RerunArgs(enable=False))
    assert m.validate_result(float("nan"), 0) == RerunDiagnostic.CORRECT
    assert m.exit_code_requested() is None


def test_nan_transient_vs_persistent():
    m = _machine()
    # transient: the rerun produces a clean value
    d = m.validate_result(float("nan"), 0, rerun_fn=lambda: 1.0)
    assert d == RerunDiagnostic.TRANSIENT_ERROR
    assert m.exit_code_requested() == EXIT_CODE_RESUME_TO_DISAMBIGUATE
    # persistent: the rerun reproduces the NaN
    m2 = _machine()
    d = m2.validate_result(float("nan"), 0, rerun_fn=lambda: float("nan"))
    assert d == RerunDiagnostic.PERSISTENT_ERROR
    assert m2.exit_code_requested() == EXIT_CODE_FAILED_ON_RESULT_VALIDATION


def test_spike_detection():
    m = _machine(spike_factor=5.0)
    for it in range(5):
        assert m.validate_result(2.0, it) == RerunDiagnostic.CORRECT
    d = m.validate_result(50.0, 5, rerun_fn=lambda: 50.0)
    assert d == RerunDiagnostic.PERSISTENT_ERROR
    assert m.report()["persistent"] == 1


def test_normal_values_update_ema():
    m = _machine(spike_factor=10.0)
    for it in range(10):
        assert m.validate_result(3.0 - it * 0.1, it) == RerunDiagnostic.CORRECT
    assert not m.records


def test_data_iterator_replay():
    it = RerunDataIterator(iter(range(10)))
    assert next(it) == 0 and next(it) == 1
    it.rewind()
    assert next(it) == 0 and next(it) == 1
    it.advance()
    assert next(it) == 2


def test_error_injection_drill():
    m = RerunStateMachine(RerunArgs(
        enable=True, mode="validate_results", error_injection_rate=1.0,
        error_injection_type="transient_error"))
    d = m.validate_result(1.0, 0, rerun_fn=lambda: 1.0)
    assert d == RerunDiagnostic.TRANSIENT_ERROR  # injected once, gone on rerun

    m2 = RerunStateMachine(RerunArgs(
        enable=True, mode="validate_results", error_injection_rate=1.0,
        error_injection_type="persistent_error"))
    d = m2.validate_result(1.0, 0, rerun_fn=lambda: 1.0)
    assert d == RerunDiagnostic.PERSISTENT_ERROR


def test_rerun_replays_same_data():
    it = RerunDataIterator(iter(range(100)))
    m = _machine()
    batch = next(it)

    def rerun():
        b = next(it)
        assert b == batch  # identical data replayed
        return 1.0

    d = m.validate_result(float("nan"), 0, rerun_fn=rerun, data_iterator=it)
    assert d == RerunDiagnostic.TRANSIENT_ERROR


def test_report_determinism_stats_mode():
    """report_stats mode (reference REPORT_DETERMINISM_STATS): every step
    re-runs once, relative differences are recorded, and no exit code is
    ever requested — execution continues."""
    from hetu_galvatron_tpu.core.args_schema import RerunArgs
    from hetu_galvatron_tpu.runtime.rerun_machine import RerunStateMachine

    args = RerunArgs(enable=True, mode="report_stats")
    m = RerunStateMachine(args)
    # deterministic step: rerun reproduces exactly
    for it in range(3):
        d = m.validate_result(1.0 + it, it, rerun_fn=lambda i=it: 1.0 + i)
    rep = m.report()
    assert rep["determinism"]["checked"] == 3
    assert rep["determinism"]["mismatches"] == 0
    assert m.exit_code_requested() is None

    # nondeterministic step: mismatch captured with its relative magnitude
    m2 = RerunStateMachine(args)
    m2.validate_result(1.0, 0, rerun_fn=lambda: 1.001)
    rep2 = m2.report()
    assert rep2["determinism"]["mismatches"] == 1
    assert abs(rep2["determinism"]["max_rel_diff"] - 1e-3) < 1e-6
    assert m2.exit_code_requested() is None  # never exits in stats mode
    assert rep2["checked_iterations"] == 1  # mismatch recorded for the log


def test_report_stats_nan_handling():
    """A deterministic NaN re-run is not a mismatch; a one-sided NaN is,
    without poisoning the running mean."""
    from hetu_galvatron_tpu.core.args_schema import RerunArgs
    from hetu_galvatron_tpu.runtime.rerun_machine import RerunStateMachine

    m = RerunStateMachine(RerunArgs(enable=True, mode="report_stats"))
    m.validate_result(float("nan"), 0, rerun_fn=lambda: float("nan"))
    rep = m.report()
    assert rep["checked_iterations"] == 0  # deterministic nan != mismatch
    assert rep["determinism"]["mismatches"] == 0

    m2 = RerunStateMachine(RerunArgs(enable=True, mode="report_stats"))
    m2.validate_result(1.0, 0, rerun_fn=lambda: float("nan"))
    m2.validate_result(2.0, 1, rerun_fn=lambda: 2.0)
    rep2 = m2.report()
    d = rep2["determinism"]
    assert d["mismatches"] == 1 and d["nonfinite"] == 1
    assert d["mean_rel_diff"] == 0.0  # finite mean unpoisoned


def test_state_dict_roundtrip():
    """Full-state resume: records, spike EMA, and injector memory survive
    a serialize/deserialize cycle (the checkpoint's train_state payload)."""
    m = _machine(spike_factor=5.0)
    for it in range(4):
        m.validate_result(2.0, it)
    m.validate_result(float("nan"), 4, rerun_fn=lambda: 2.0)  # transient
    sd = m.state_dict()
    import json

    # must survive a STRICT json round-trip: meta.json is read by external
    # tooling too, and bare NaN tokens are spec-invalid
    sd = json.loads(json.dumps(sd, allow_nan=False))
    m2 = _machine(spike_factor=5.0)
    m2.load_state_dict(sd)
    assert m2._ema == pytest.approx(m._ema)
    assert len(m2.records) == 1
    r = m2.records[0]
    assert r.diagnostic == RerunDiagnostic.TRANSIENT_ERROR
    assert r.iteration == 4 and math.isnan(r.value)
    # restored EMA keeps spike detection warm: a 10x value still trips
    d = m2.validate_result(50.0, 5, rerun_fn=lambda: 50.0)
    assert d == RerunDiagnostic.PERSISTENT_ERROR


def test_data_iterator_tracks_position():
    """batches_consumed counts COMMITTED batches only — rewound replays
    do not double-count (the data position the checkpoint carries)."""
    it = RerunDataIterator(iter(range(100)))
    next(it)
    it.advance()
    next(it)
    it.rewind()
    next(it)  # replay of the same batch
    it.advance()
    assert it.batches_consumed == 2


def test_state_transitions_emit_counters():
    """Fault-detection state transitions increment observability counters
    (rerun/*) so dashboards see attribution without parsing logs."""
    from hetu_galvatron_tpu.observability.registry import MetricsRegistry

    reg = MetricsRegistry()
    m = RerunStateMachine(RerunArgs(enable=True, mode="validate_results"),
                          registry=reg)
    m.validate_result(1.0, 0, rerun_fn=lambda: 1.0)        # clean
    m.validate_result(float("nan"), 1, rerun_fn=lambda: 1.0)  # transient
    m.validate_result(float("nan"), 2,
                      rerun_fn=lambda: float("nan"))       # persistent
    assert reg.counter("rerun/validated").value == 3
    assert reg.counter("rerun/suspect").value == 2
    assert reg.counter("rerun/rerun_in_place").value == 2
    assert reg.counter("rerun/transient_error").value == 1
    assert reg.counter("rerun/persistent_error").value == 1
    assert reg.counter(
        "rerun/exit_requested",
        code=EXIT_CODE_RESUME_TO_DISAMBIGUATE).value == 1
    assert reg.counter(
        "rerun/exit_requested",
        code=EXIT_CODE_FAILED_ON_RESULT_VALIDATION).value == 1

    # report_stats mode: determinism mismatches count too
    reg2 = MetricsRegistry()
    m2 = RerunStateMachine(RerunArgs(enable=True, mode="report_stats"),
                           registry=reg2)
    m2.validate_result(1.0, 0, rerun_fn=lambda: 1.0)
    m2.validate_result(1.0, 1, rerun_fn=lambda: 1.5)
    assert reg2.counter("rerun/determinism_mismatch").value == 1
    assert reg2.counter("rerun/rerun_in_place").value == 2
