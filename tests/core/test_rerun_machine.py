"""Rerun state machine: NaN/spike detection, transient-vs-persistent
attribution, replayable iterator, error injection (reference
rerun_state_machine.py behaviors)."""

import math

import pytest

from hetu_galvatron_tpu.core.args_schema import RerunArgs
from hetu_galvatron_tpu.runtime.rerun_machine import (
    EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
    RerunDataIterator,
    RerunDiagnostic,
    RerunStateMachine,
)

pytestmark = pytest.mark.utils


def _machine(**kw):
    return RerunStateMachine(RerunArgs(enable=True, mode="validate_results",
                                       **kw))


def test_disabled_passthrough():
    m = RerunStateMachine(RerunArgs(enable=False))
    assert m.validate_result(float("nan"), 0) == RerunDiagnostic.CORRECT
    assert m.exit_code_requested() is None


def test_nan_transient_vs_persistent():
    m = _machine()
    # transient: the rerun produces a clean value
    d = m.validate_result(float("nan"), 0, rerun_fn=lambda: 1.0)
    assert d == RerunDiagnostic.TRANSIENT_ERROR
    assert m.exit_code_requested() == EXIT_CODE_RESUME_TO_DISAMBIGUATE
    # persistent: the rerun reproduces the NaN
    m2 = _machine()
    d = m2.validate_result(float("nan"), 0, rerun_fn=lambda: float("nan"))
    assert d == RerunDiagnostic.PERSISTENT_ERROR
    assert m2.exit_code_requested() == EXIT_CODE_FAILED_ON_RESULT_VALIDATION


def test_spike_detection():
    m = _machine(spike_factor=5.0)
    for it in range(5):
        assert m.validate_result(2.0, it) == RerunDiagnostic.CORRECT
    d = m.validate_result(50.0, 5, rerun_fn=lambda: 50.0)
    assert d == RerunDiagnostic.PERSISTENT_ERROR
    assert m.report()["persistent"] == 1


def test_normal_values_update_ema():
    m = _machine(spike_factor=10.0)
    for it in range(10):
        assert m.validate_result(3.0 - it * 0.1, it) == RerunDiagnostic.CORRECT
    assert not m.records


def test_data_iterator_replay():
    it = RerunDataIterator(iter(range(10)))
    assert next(it) == 0 and next(it) == 1
    it.rewind()
    assert next(it) == 0 and next(it) == 1
    it.advance()
    assert next(it) == 2


def test_error_injection_drill():
    m = RerunStateMachine(RerunArgs(
        enable=True, mode="validate_results", error_injection_rate=1.0,
        error_injection_type="transient_error"))
    d = m.validate_result(1.0, 0, rerun_fn=lambda: 1.0)
    assert d == RerunDiagnostic.TRANSIENT_ERROR  # injected once, gone on rerun

    m2 = RerunStateMachine(RerunArgs(
        enable=True, mode="validate_results", error_injection_rate=1.0,
        error_injection_type="persistent_error"))
    d = m2.validate_result(1.0, 0, rerun_fn=lambda: 1.0)
    assert d == RerunDiagnostic.PERSISTENT_ERROR


def test_rerun_replays_same_data():
    it = RerunDataIterator(iter(range(100)))
    m = _machine()
    batch = next(it)

    def rerun():
        b = next(it)
        assert b == batch  # identical data replayed
        return 1.0

    d = m.validate_result(float("nan"), 0, rerun_fn=rerun, data_iterator=it)
    assert d == RerunDiagnostic.TRANSIENT_ERROR
