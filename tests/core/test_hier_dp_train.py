"""Hierarchical dp gradient-reduction drills on the virtual 8-device mesh.

The acceptance drill: a searched-format tp2 x dp4 plan trains 3 steps with
the hierarchical reduce-scatter/all-reduce/all-gather path vs the flat
GSPMD all-reduce — trajectories equal within a tight tolerance (the two
differ ONLY by cross-dp reduction reassociation: per-device contractions
are identical, the lane sums just associate host-first), zero steady-state
recompiles, and the traced step's explicit collective counts AND bytes
match ``plan_collective_counts/bytes`` exactly.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step, shard_params
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.mesh import build_mesh
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
from hetu_galvatron_tpu.utils.strategy import (
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    strategy_list2config,
)

pytestmark = [pytest.mark.core, pytest.mark.distributed]

CFG = ModelArgs(
    hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False, use_flash_attn=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=128,
)
TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _searched_plan_json(tmp_path, tp=2, dp=4, cp=1, dp_type="ddp", gbsz=8,
                        chunks=2, vtp=None):
    layers = [LayerStrategy(pp_deg=1, tp_size=tp, dp_size=dp, cp_size=cp,
                            dp_type=__import__(
                                "hetu_galvatron_tpu.utils.strategy",
                                fromlist=["DPType"]).DPType.from_name(
                                    dp_type))
              for _ in range(CFG.num_hidden_layers)]
    cfg = strategy_list2config(
        layers, global_bsz=gbsz, chunks=chunks,
        pipeline_type="pipedream_flush", default_dp_type=dp_type,
        vocab=EmbeddingLMHeadStrategy(vtp=tp if vtp is None else vtp),
        pp_division=[CFG.num_hidden_layers])
    path = tmp_path / "galvatron_config_hier.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def _steps(tmp_path, cpu_devices, hier_dp, *, n=3, dp_type="ddp",
           chunks=2, dcn_slices=2, hier_bucket_mb=0.0, tp=2, dp=4, cp=1,
           vtp=None):
    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _searched_plan_json(
        tmp_path, tp=tp, dp=dp, cp=cp, dp_type=dp_type, chunks=chunks,
        vtp=vtp)
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices[:8], dcn_slices=dcn_slices)
    tx = make_optimizer(TRAIN)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        CFG, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False, hier_dp=hier_dp, dcn_slices=dcn_slices,
        hier_bucket_mb=hier_bucket_mb)
    sp = shard_params(params, pspecs, mesh)
    so = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    data = np.random.RandomState(0).randint(0, 128, (8, CFG.seq_length + 1))
    b = jax.device_put(jax.tree.map(jnp.asarray, make_batch(data)),
                       batch_shd)
    losses = []
    for _ in range(n):
        sp, so, m = step(sp, so, b)
        losses.append(float(m["loss"]))
    return step, sp, so, b, losses


@pytest.mark.parametrize("dp_type,chunks", [("ddp", 2), ("zero2", 2),
                                            ("zero3", 2)])
def test_hier_vs_flat_trajectory(tmp_path, cpu_devices, dp_type, chunks):
    """3-step trajectories equal within reassociation tolerance, params
    included, under ddp AND the ZeRO flavours.

    zero3 now runs at chunks=2: the flat path's microbatch-scan sharding
    bug (the chunk axis absorbing the outer dp mesh axis, which made the
    partitioner's ZeRO-3 gradient program numerically wrong) is FIXED by
    the scanned-microbatch pin in ``make_spmd_train_step``, so the flat
    side is a valid reference everywhere — see
    ``test_hier_zero3_matches_single_device_where_flat_drifts``."""
    _, sp0, _, _, l0 = _steps(tmp_path, cpu_devices, False, dp_type=dp_type,
                              chunks=chunks)
    _, sp1, _, _, l1 = _steps(tmp_path, cpu_devices, True, dp_type=dp_type,
                              chunks=chunks)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sp0),
            jax.tree_util.tree_leaves_with_path(sp1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=jax.tree_util.keystr(pa))


@pytest.mark.parametrize("dp_type", ["ddp", "zero2", "zero3"])
def test_hier_bucketed_matches_monolithic_trajectory(tmp_path, cpu_devices,
                                                     dp_type):
    """THE bucketed acceptance drill: the software-pipelined schedule
    (hier_bucket_mb small enough for several buckets on the tiny payload)
    is BIT-consistent with the monolithic hier path on the tp2 x dp4 plan
    — every element rides the same rs->ar->ag association, a bucket is
    just a contiguous slice — under ddp and both ZeRO flavours."""
    _, sp0, _, _, l0 = _steps(tmp_path, cpu_devices, True, dp_type=dp_type)
    _, sp1, _, _, l1 = _steps(tmp_path, cpu_devices, True, dp_type=dp_type,
                              hier_bucket_mb=0.02)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sp0),
            jax.tree_util.tree_leaves_with_path(sp1)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=jax.tree_util.keystr(pa))


def test_hier_bucketed_zero_steady_state_recompiles(tmp_path, cpu_devices):
    step, sp, so, b, _ = _steps(tmp_path, cpu_devices, True,
                                hier_bucket_mb=0.02)
    n0 = step._cache_size()
    assert n0 == 1
    for _ in range(2):
        sp, so, _ = step(sp, so, b)
    assert step._cache_size() == n0


def test_hier_cp_plan_takes_hier_path(tmp_path, cpu_devices):
    """cp-bearing sdp plan (tp1 x cp2 x dp4) through the hierarchical
    path: eligibility no longer kicks it flat (the lane vmap covers the
    dp axes; the in-lane cp partial sums stay a GSPMD reduction and the
    ring kernel swaps for the GSPMD attention core), and the 3-step
    trajectory + params match the flat path within reassociation/
    association tolerance."""
    from hetu_galvatron_tpu.analysis.eligibility import plan_hier_dp_reason

    _, sp0, _, _, l0 = _steps(tmp_path, cpu_devices, False, tp=1, cp=2,
                              dp=4, vtp=1)
    _, sp1, _, _, l1 = _steps(tmp_path, cpu_devices, True, tp=1, cp=2,
                              dp=4, vtp=1)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sp0),
            jax.tree_util.tree_leaves_with_path(sp1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=jax.tree_util.keystr(pa))


def test_hier_cp_plan_eligible_and_zigzag_not(tmp_path):
    """The plan-level predicate: cp/ulysses sdp groups are eligible;
    zigzag-cp keeps the shared reason (its pre-permuted data layout is
    only correct under the ring kernel)."""
    from hetu_galvatron_tpu.analysis.eligibility import (
        HIER_ZIGZAG_REASON,
        hier_dp_unsupported_reason,
    )

    assert hier_dp_unsupported_reason(dp=4, cp=2) is None
    assert hier_dp_unsupported_reason(dp=4, ulysses=True, tp=2) is None
    assert hier_dp_unsupported_reason(dp=4, cp=2, cp_zigzag=True) == \
        HIER_ZIGZAG_REASON


def test_hier_cp_census_counts_and_bytes_exact(tmp_path, cpu_devices):
    """The cp-bearing lane program's explicit collectives are EXACTLY the
    hier rs/ar/ag (the cp partial-sum reduction is partition-time GSPMD,
    invisible to the jaxpr; the ring kernel is swapped out), counts and
    padded bytes pinned to the plan arithmetic."""
    from hetu_galvatron_tpu.analysis.census import (
        census_spmd_step,
        check_census,
    )
    from hetu_galvatron_tpu.analysis.sharding_flow import (
        check_flow,
        flow_spmd_step,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_bytes,
        plan_collective_counts,
    )

    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _searched_plan_json(
        tmp_path, tp=1, cp=2, dp=4, vtp=1)
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices[:8], dcn_slices=2)

    census = census_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                              hier_dp=True, dcn_slices=2)
    pred = plan_collective_counts(hpc, CFG, tp_overlap=False, hier_dp=True)
    assert pred == {"reduce_scatter": 1, "all_reduce": 1, "all_gather": 1}
    assert check_census(census, pred, program="spmd_hier_cp") == []

    pf = flow_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                        hier_dp=True, dcn_slices=2, gather_mb=1e-6)
    pred_mb = plan_collective_bytes(hpc, CFG, tp_overlap=False,
                                    hier_dp=True, hier_cross=2)
    assert check_flow(pf.flow, pred_mb, program="spmd_hier_cp") == []


@pytest.mark.parametrize("bucket_mb", [0.02, 0.01])
def test_hier_bucketed_census_counts_and_bytes_exact(tmp_path, cpu_devices,
                                                     bucket_mb):
    """Bucketed acceptance: the traced pipelined step contains EXACTLY
    3 x buckets collectives with exactly the per-bucket padded payload
    megabytes the shared hier_bucket_layout arithmetic promises — pinned
    at two different bucket counts (zero tolerance)."""
    from hetu_galvatron_tpu.analysis.census import (
        census_spmd_step,
        check_census,
    )
    from hetu_galvatron_tpu.analysis.sharding_flow import (
        check_flow,
        flow_spmd_step,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_bytes,
        plan_collective_counts,
    )

    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _searched_plan_json(tmp_path)
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices[:8], dcn_slices=2)

    census = census_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                              hier_dp=True, dcn_slices=2,
                              hier_bucket_mb=bucket_mb)
    pred = plan_collective_counts(hpc, CFG, tp_overlap=False, hier_dp=True,
                                  hier_bucket_mb=bucket_mb, hier_cross=2)
    n = pred["reduce_scatter"]
    assert n > 1 and pred == {"reduce_scatter": n, "all_reduce": n,
                              "all_gather": n}
    assert check_census(census, pred,
                        program=f"spmd_hier_b{bucket_mb}") == []

    pf = flow_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                        hier_dp=True, dcn_slices=2,
                        hier_bucket_mb=bucket_mb, gather_mb=1e-6)
    pred_mb = plan_collective_bytes(hpc, CFG, tp_overlap=False,
                                    hier_dp=True, hier_cross=2,
                                    hier_bucket_mb=bucket_mb)
    assert check_flow(pf.flow, pred_mb,
                      program=f"spmd_hier_b{bucket_mb}") == []
    # the per-bucket gather-backs stay marker-exempt (the bucketed scopes
    # keep the hier_dp_ag prefix)
    assert all("hier_dp_ag" not in p for p in pf.reshard_problems)


def test_hier_zero3_matches_single_device_where_flat_drifts(
        tmp_path, cpu_devices):
    """embed-ZeRO-3 + vtp2 + chunks=2 vs an UNSHARDED single-device run:
    BOTH paths now match it tightly. The hier lane path always did (its
    lane_batch pins the per-lane layout); the FLAT path's scanned
    microbatches used to arrive batch-sharded over only the inner dp
    axes — the reshape absorbed the outer dp axis into the chunk dim —
    and the partitioner's ZeRO-3 gradient program for that layout was
    numerically WRONG (the ROADMAP BUG: wte rows off at grad magnitude).
    ``make_spmd_train_step`` now pins the scanned stack to the plan's
    batch sharding, so the per-microbatch embed-grad reduce-scatter
    materializes in the correct layout: the bug is FIXED on the GSPMD
    path, not masked by comparing hier-to-flat."""
    from hetu_galvatron_tpu.models.builder import causal_lm_loss
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer as _mo
    from hetu_galvatron_tpu.runtime.trainer import make_train_step

    _, sp1, _, _, l1 = _steps(tmp_path, cpu_devices, True, dp_type="zero3",
                              chunks=2)
    _, sp0, _, _, l0 = _steps(tmp_path, cpu_devices, False, dp_type="zero3",
                              chunks=2)
    # single-device reference with the same optimizer + chunking
    tx = _mo(TRAIN)
    params, _ = init_causal_lm(jax.random.key(0), CFG)
    loss_fn = lambda p, b: causal_lm_loss(p, b, CFG,
                                          compute_dtype=jnp.float32)
    step = jax.jit(make_train_step(loss_fn, tx, chunks=2))
    so = tx.init(params)
    data = np.random.RandomState(0).randint(0, 128, (8, CFG.seq_length + 1))
    b = jax.tree.map(jnp.asarray, make_batch(data))
    ref = []
    for _ in range(3):
        params, so, m = step(params, so, b)
        ref.append(float(m["loss"]))
    np.testing.assert_allclose(ref, l1, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ref, l0, rtol=1e-5, atol=1e-5)
    # the flat path's PARAMS (wte included) match the reference too —
    # the strong form of "bug fixed": ~40% of wte rows used to deviate at
    # GRAD magnitude (~6e-2); the tolerance here is 3 orders below that,
    # absorbing only the 3-step adam amplification of f32 reassociation
    for (pa, a), (_, r) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(sp0)),
            jax.tree_util.tree_leaves_with_path(params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=1e-4,
            err_msg=jax.tree_util.keystr(pa))


def test_hier_zero_steady_state_recompiles(tmp_path, cpu_devices):
    step, sp, so, b, _ = _steps(tmp_path, cpu_devices, True)
    n0 = step._cache_size()
    assert n0 == 1
    for _ in range(2):
        sp, so, _ = step(sp, so, b)
    assert step._cache_size() == n0


def test_hier_census_counts_and_bytes_exact(tmp_path, cpu_devices):
    """The traced hierarchical step contains EXACTLY the collectives the
    plan arithmetic promises — one reduce-scatter, one cross-slice
    all-reduce, one all-gather — and moves exactly the predicted padded
    payload megabytes (zero tolerance, the sharding-flow contract)."""
    from hetu_galvatron_tpu.analysis.census import (
        census_spmd_step,
        check_census,
    )
    from hetu_galvatron_tpu.analysis.sharding_flow import (
        check_flow,
        flow_spmd_step,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_bytes,
        plan_collective_counts,
    )

    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _searched_plan_json(tmp_path)
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices[:8], dcn_slices=2)

    census = census_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                              hier_dp=True, dcn_slices=2)
    pred_counts = plan_collective_counts(hpc, CFG, tp_overlap=False,
                                         hier_dp=True)
    assert pred_counts == {"reduce_scatter": 1, "all_reduce": 1,
                           "all_gather": 1}
    assert check_census(census, pred_counts, program="spmd_hier") == []

    pf = flow_spmd_step(CFG, hpc, TRAIN, mesh, tp_overlap=False,
                        hier_dp=True, dcn_slices=2, gather_mb=1e-6)
    pred_mb = plan_collective_bytes(hpc, CFG, tp_overlap=False,
                                    hier_dp=True, hier_cross=2)
    assert check_flow(pf.flow, pred_mb, program="spmd_hier") == []
    # the deliberate hier gather-back is marker-exempt from the reshard
    # lint even at a microscopic threshold
    assert all("hier_dp_ag" not in p for p in pf.reshard_problems)
    assert not any("all-gathers" in p and "materialized" in p
                   for p in pf.reshard_problems), pf.reshard_problems


def _pp2_plan(dp=2, tp=2, gbsz=8, chunks=4):
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        HybridParallelConfig,
    )
    from hetu_galvatron_tpu.utils.strategy import DPType

    layers = [LayerStrategy(pp_deg=2, tp_size=tp, dp_size=dp)
              for _ in range(CFG.num_hidden_layers)]
    return HybridParallelConfig(
        layers=layers, vocab=EmbeddingLMHeadStrategy(vtp=tp), pp_deg=2,
        pp_division=[1, 1], chunks=chunks, global_bsz=gbsz,
        pipeline_type="pipedream_flush", default_dp_type=DPType.DDP,
        world_size=8)


def _engine_steps(cpu_devices, engine_cls, hier_dp, *, n=3, dcn=4):
    hpc = _pp2_plan()
    eng = engine_cls(CFG, hpc, TRAIN, devices=cpu_devices[:8],
                     compute_dtype=jnp.float32, dcn_slices=dcn,
                     hier_dp=hier_dp,
                     **({"donate": False} if "Compiled" in engine_cls.__name__
                        else {}))
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    data = np.random.RandomState(0).randint(0, 128, (8, CFG.seq_length + 1))
    b = make_batch(data)
    losses = []
    for _ in range(n):
        sp, so, m = eng.train_step(sp, so, b)
        losses.append(float(m["loss"]))
    return eng, sp, losses


def test_hier_compiled_engine_parity(cpu_devices):
    """Compiled 1F1B: hier vs flat 3-step trajectories + merged params
    within reassociation tolerance, exactly one compile."""
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )

    e0, sp0, l0 = _engine_steps(cpu_devices, CompiledPipelineEngine, False)
    e1, sp1, l1 = _engine_steps(cpu_devices, CompiledPipelineEngine, True)
    assert e1.compile_count() == 1
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    m0, m1 = e0.merge_params(sp0), e1.merge_params(sp1)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(m0),
            jax.tree_util.tree_leaves_with_path(m1)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5,
                                   err_msg=jax.tree_util.keystr(pa))


def test_hier_host_engine_parity(cpu_devices):
    """Host 1F1B: hier vs flat 3-step trajectories + merged params."""
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    e0, sp0, l0 = _engine_steps(cpu_devices, PipelineEngine, False)
    e1, sp1, l1 = _engine_steps(cpu_devices, PipelineEngine, True)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    m0, m1 = e0.merge_params(sp0), e1.merge_params(sp1)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(m0),
            jax.tree_util.tree_leaves_with_path(m1)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5,
                                   err_msg=jax.tree_util.keystr(pa))


def test_hier_compiled_census_counts_and_bytes(cpu_devices):
    """The compiled hier step contains 2T marked stage rotations plus
    exactly the three hier collectives, bytes exact."""
    from hetu_galvatron_tpu.analysis.census import census_jaxpr, check_census
    from hetu_galvatron_tpu.analysis.sharding_flow import (
        check_flow,
        flow_jaxpr,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        MB,
        plan_collective_bytes,
        plan_collective_counts,
    )
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )

    hpc = _pp2_plan()
    eng = CompiledPipelineEngine(CFG, hpc, TRAIN, devices=cpu_devices[:8],
                                 compute_dtype=jnp.float32, dcn_slices=4,
                                 hier_dp=True, donate=False)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    data = np.random.RandomState(0).randint(0, 128, (8, CFG.seq_length + 1))
    jaxpr = eng.step_jaxpr(sp, so, make_batch(data))
    census = census_jaxpr(jaxpr)
    pred = plan_collective_counts(hpc, CFG, tp_overlap=False, hier_dp=True)
    assert check_census(census, pred, program="compiled_hier") == []

    shapes = [tuple(l.shape) for l in jax.tree_util.tree_leaves(sp)]
    local, padded = eng._hier.payload_elems(shapes)
    intra = eng._hier.intra
    pred_mb = plan_collective_bytes(hpc, CFG, tp_overlap=False)
    pred_mb["reduce_scatter"] = padded * 4 / MB
    pred_mb["all_reduce"] = padded // intra * 4 / MB
    pred_mb["all_gather"] = padded // intra * 4 / MB
    assert check_flow(flow_jaxpr(jaxpr), pred_mb,
                      program="compiled_hier") == []


def test_train_dist_cli_hier_dp(tmp_path, cpu_devices, capfd, caplog):
    """Launcher wiring end to end: parallel.hier_dp trains with the
    hierarchical path (the slice x host split logged), and an ineligible
    config logs the shared fallback reason and keeps training flat."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    base = [
        "model.hidden_size=64", "model.num_hidden_layers=2",
        "model.num_attention_heads=4", "model.vocab_size=128",
        "model.seq_length=16", "model.max_position_embeddings=64",
        "model.hidden_act=swiglu", "model.normalization=rmsnorm",
        "model.position_embedding_type=rope",
        "model.tie_word_embeddings=false", "model.add_bias_linear=false",
        "model.make_vocab_size_divisible_by=1",
        "model.ffn_hidden_size=128", "model.use_flash_attn=false",
        "parallel.global_tp_deg=2", "parallel.global_train_batch_size=8",
        "parallel.num_devices=8", "parallel.dcn_slices=2",
        "parallel.hier_dp=true", "train.train_iters=2",
    ]
    import logging

    with caplog.at_level(logging.INFO):
        out = train(args_from_cli(base, mode="train_dist"))
    assert len(out["losses"]) == 2 and all(np.isfinite(out["losses"]))
    cap = capfd.readouterr()
    logged = cap.out + cap.err + caplog.text
    assert "hierarchical gradient reduction on" in logged
    assert "2 slice x 2 host" in logged
    caplog.clear()

    # ineligible: tp_overlap rings cannot nest under the lane vmap —
    # the launcher logs the shared reason and falls back to flat
    with caplog.at_level(logging.INFO):
        out = train(args_from_cli(base + ["tp_overlap.enable=true"],
                                  mode="train_dist"))
    assert len(out["losses"]) == 2 and all(np.isfinite(out["losses"]))
    cap = capfd.readouterr()
    logged = cap.out + cap.err + caplog.text
    assert "falling back to the flat GSPMD gradient all-reduce" in logged
    assert "cannot nest" in logged
    caplog.clear()

    # bucketed: hier_bucket_mb pipelines the schedule — logged, trains
    with caplog.at_level(logging.INFO):
        out = train(args_from_cli(base + ["parallel.hier_bucket_mb=0.05"],
                                  mode="train_dist"))
    assert len(out["losses"]) == 2 and all(np.isfinite(out["losses"]))
    cap = capfd.readouterr()
    logged = cap.out + cap.err + caplog.text
    assert "0.05 MB buckets, pipelined" in logged


def test_train_dist_cli_hier_dp_cp_plan_no_fallback(tmp_path, cpu_devices,
                                                    capfd, caplog):
    """The cp-bearing sdp plan takes the hierarchical path end to end
    through the launcher: NO flat-fallback line, the slice x host split
    logged, finite losses (acceptance: cp plans stop paying flat
    per-microbatch all-reduces)."""
    import logging

    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    base = [
        "model.hidden_size=64", "model.num_hidden_layers=2",
        "model.num_attention_heads=4", "model.vocab_size=128",
        "model.seq_length=16", "model.max_position_embeddings=64",
        "model.hidden_act=swiglu", "model.normalization=rmsnorm",
        "model.position_embedding_type=rope",
        "model.tie_word_embeddings=false", "model.add_bias_linear=false",
        "model.make_vocab_size_divisible_by=1",
        "model.ffn_hidden_size=128", "model.use_flash_attn=false",
        "parallel.global_cp_deg=2", "parallel.global_train_batch_size=8",
        "parallel.num_devices=8", "parallel.dcn_slices=2",
        "parallel.hier_dp=true", "train.train_iters=2",
    ]
    with caplog.at_level(logging.INFO):
        out = train(args_from_cli(base, mode="train_dist"))
    assert len(out["losses"]) == 2 and all(np.isfinite(out["losses"]))
    cap = capfd.readouterr()
    logged = cap.out + cap.err + caplog.text
    assert "hierarchical gradient reduction on" in logged
    assert "falling back to the flat GSPMD gradient" not in logged


def test_hier_ineligible_plans_raise_with_reason(tmp_path, cpu_devices):
    """tp_overlap rings cannot nest under the lane vmap; dropout diverges."""
    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _searched_plan_json(tmp_path)
    hpc = get_hybrid_parallel_config(a, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices[:8])
    tx = make_optimizer(TRAIN)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="cannot nest"):
        make_spmd_train_step(CFG, hpc, mesh, axes, tx, params,
                             compute_dtype=jnp.float32, hier_dp=True,
                             tp_overlap=True)
    drop = CFG.model_copy(update={"hidden_dropout": 0.1})
    with pytest.raises(ValueError, match="dropout"):
        make_spmd_train_step(drop, hpc, mesh, axes, tx, params,
                             compute_dtype=jnp.float32, hier_dp=True)
