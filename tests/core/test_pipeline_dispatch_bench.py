"""The dispatch-overhead microbench (VERDICT r4 weak #5: bound the
host-sequenced PipelineEngine's scheduling cost) must run and produce
self-consistent numbers, including the compiled-schedule A/B leg."""

import pytest

pytestmark = [pytest.mark.core, pytest.mark.pipeline]


def _bench(**kw):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    import pipeline_dispatch_bench as b

    return b.run(**kw)


@pytest.mark.slow
def test_dispatch_bench_runs_and_is_consistent():
    out = _bench(pp=2, chunks=2, iters=5)
    assert out["dispatch_us"] > 0
    assert out["step_ms"] > 0 and out["serial_fwd_bwd_ms"] > 0
    # the full step includes the serial legs plus clip/update/transfers;
    # it cannot be (much) cheaper than the legs alone
    assert out["step_overhead_ratio"] > 0.8
    # per-(stage, microbatch) dispatch cost must be a small fraction of a
    # leg's wall time even on this tiny model, else the schedule could
    # never stay ahead of real devices
    legs = 2 * out["pp"] * out["chunks"]  # fwd + bwd per stage per mb
    assert out["dispatch_us"] * legs / 1e3 < out["step_ms"]
    # A/B leg is present and sane
    assert out["compiled_step_ms"] > 0
    assert out["compiled_vs_host"] > 0
    assert out["compiled_recompiles"] == 0


@pytest.mark.slow
def test_compiled_does_not_regress_host_bound():
    """Acceptance: on the virtual CPU mesh (the dispatch-bound regime the
    host schedule is worst at), the compiled single-program 1F1B must at
    minimum not regress the host engine it replaces — compiled_vs_host
    <= 1.0 on the pp2 x chunks4 reference workload. Interleaved medians in
    the bench keep this robust to shared-host load spikes."""
    out = _bench(pp=2, chunks=4, iters=20)
    assert out["compiled_recompiles"] == 0, "steady state recompiled"
    assert out["compiled_vs_host"] <= 1.0, out


@pytest.mark.slow
def test_kernels_leg_unified_path_holds_the_dispatch_win():
    """ROUND-12 ACCEPTANCE: with the shard_map kernels live on BOTH
    engines (ring tp matmuls + flash interpret, tp2 x dp2 x pp2), the
    compiled program keeps compiled_vs_host <= 1.0 on the CPU mesh with
    zero steady-state recompiles. chunks=16 amortizes the lockstep bubble
    (on the shared-host mesh every bubble tick costs real compute, so the
    ratio is bounded below by ~1 + 2(pp-1)/m — see the bench docstring)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    import pipeline_dispatch_bench as b

    out = b.run_kernels(iters=10)
    assert "skipped" not in out, out
    assert out["compiled_recompiles"] == 0, "steady state recompiled"
    assert out["compiled_overlap_vs_host"] == out["compiled_vs_host"]
    assert out["compiled_vs_host"] <= 1.0, out
