"""Parallelism correctness on the virtual 8-CPU mesh: every strategy must
reproduce the single-device loss and gradients bit-for-tolerance (the
reference's tier-2 tests compare loss trajectories vs HF across tp/sp/fsdp/
hybrid configs — tests/core/test_tp.py, test_fsdp.py, test_hybrid.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss, init_causal_lm
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.mesh import build_mesh, lower_strategy
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
from hetu_galvatron_tpu.parallel.spmd import (
    make_spmd_train_step,
    layer_shardings,
    param_specs,
    shard_params,
)
from hetu_galvatron_tpu.utils.strategy import DPType, LayerStrategy

pytestmark = [pytest.mark.parallel, pytest.mark.distributed]

# 4 heads / 4 kv heads / hidden 64 shard cleanly up to tp=4; dp up to 8
CFG = ModelArgs(
    hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=128,
)

TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _args(**parallel):
    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    for k, v in parallel.items():
        setattr(a.parallel, k, v)
    return a


def _batch(bsz=8, seed=0):
    data = np.random.RandomState(seed).randint(
        0, 128, (bsz, CFG.seq_length + 1))
    return jax.tree.map(jnp.asarray, make_batch(data))


def _reference_step(params, batch):
    """Single-device fp32 train step used as ground truth."""
    tx = make_optimizer(TRAIN)
    loss_fn = lambda p: causal_lm_loss(p, batch, CFG, compute_dtype=jnp.float32)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    import optax
    upd, _ = tx.update(grads, tx.init(params), params)
    return loss, optax.apply_updates(params, upd)


def _spmd_step(args, params, axes, batch, cpu_devices):
    world = 8
    hpc = get_hybrid_parallel_config(args, world)
    mesh = build_mesh(world, hpc.pp_deg, devices=cpu_devices)
    tx = make_optimizer(TRAIN)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        CFG, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.float32, donate=False)
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(
        tx.init,
        out_shardings=jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
            is_leaf=lambda x: isinstance(
                x, jax.sharding.PartitionSpec)))(sp)
    b = jax.device_put(batch, batch_shd)
    new_p, new_o, metrics = step(sp, opt, b)
    return metrics["loss"], new_p


STRATEGIES = [
    dict(global_tp_deg=8, global_train_batch_size=8),               # pure TP
    dict(default_dp_type="ddp", global_train_batch_size=8),          # pure DP
    dict(sdp=1, global_train_batch_size=8),                          # ZeRO-3
    dict(default_dp_type="zero2", global_train_batch_size=8),        # ZeRO-2
    dict(global_tp_deg=2, default_dp_type="zero3",
         global_train_batch_size=8),                                 # tp2 x dp4
    dict(global_tp_deg=4, global_train_batch_size=8),                # tp4 x dp2
    dict(global_tp_deg=4, use_ulysses=True,
         global_train_batch_size=8),                                 # ulysses
    dict(global_cp_deg=2, global_train_batch_size=8),                # cp2 x dp4
    dict(global_tp_deg=2, global_checkpoint=1,
         global_train_batch_size=8),                                 # remat
    dict(global_tp_deg=2, vocab_tp=4, global_train_batch_size=8),    # vtp!=tp
    dict(global_tp_deg=2, chunks=2, global_train_batch_size=8),      # microbatch
]


@pytest.mark.parametrize("pkw", STRATEGIES,
                         ids=lambda d: ",".join(f"{k}={v}" for k, v in d.items()
                                                if k != "global_train_batch_size"))
def test_strategy_matches_single_device(pkw, cpu_devices):
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch)
    loss, new_params = _spmd_step(_args(**pkw), params, axes, batch,
                                  cpu_devices)
    assert abs(float(loss) - float(ref_loss)) < 2e-5, \
        f"loss {float(loss)} != ref {float(ref_loss)}"
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


def test_mixed_per_layer_strategies(cpu_devices):
    """Layer 0 tp=4/dp=2, layer 1 tp=2/dp=4(zero3) — the framework's whole
    point (reference test_hybrid.py + redistribution test_redistributed.py)."""
    import json, tempfile
    from hetu_galvatron_tpu.utils.strategy import (
        EmbeddingLMHeadStrategy, strategy_list2config)

    layers = [
        LayerStrategy(pp_deg=1, tp_size=4, dp_size=2, dp_type=DPType.DDP),
        LayerStrategy(pp_deg=1, tp_size=2, dp_size=4, dp_type=DPType.ZERO3),
    ]
    cfg = strategy_list2config(
        layers, global_bsz=8, chunks=1,
        vocab=EmbeddingLMHeadStrategy(vtp=2))
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(cfg, f)
        path = f.name
    args = _args(config_mode="json", galvatron_config_path=path)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    batch = _batch()
    ref_loss, ref_params = _reference_step(params, batch)
    loss, new_params = _spmd_step(args, params, axes, batch, cpu_devices)
    assert abs(float(loss) - float(ref_loss)) < 2e-5
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(new_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=3e-4)


def test_zero3_actually_shards_params(cpu_devices):
    """ZeRO-3 must leave each chip with 1/dp of the 2D params (memory is the
    point of the strategy, reference parallel.py:122)."""
    args = _args(sdp=1)
    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices)
    per_layer, vocab = layer_shardings(hpc, mesh)
    pspecs = param_specs(
        {"embed": {"wte": ("vocab", "embed")},
         "layers": tuple({"attn": {"wqkv": ("embed", "qkv")}}
                         for _ in range(2)),
         "prenorm": {"scale": ("embed",)},
         "head": {"whead": ("embed", "vocab")}},
        per_layer, vocab)
    # decoder wqkv: embed axis sharded over all 3 dp axes
    wqkv_spec = pspecs["layers"][0]["attn"]["wqkv"]
    assert wqkv_spec[0] == ("d0", "d1", "d2")
    # 1D norm scale stays replicated (too small to shard)
    assert pspecs["prenorm"]["scale"] == jax.sharding.PartitionSpec(None)


def test_tp_shards_heads_and_mlp(cpu_devices):
    mesh = build_mesh(8, 1, devices=cpu_devices)
    sh = lower_strategy(
        LayerStrategy(pp_deg=1, tp_size=4, dp_size=2), mesh)
    assert sh.tp_axes == ("d1", "d2")
    assert sh.dp_axes == ("d0",)
    spec = sh.param_spec(("embed", "qkv"))
    assert spec == jax.sharding.PartitionSpec(None, ("d1", "d2"))
    # non-consecutive: tp outermost
    sh2 = lower_strategy(
        LayerStrategy(pp_deg=1, tp_size=4, dp_size=2, tp_consecutive=False),
        mesh)
    assert sh2.tp_axes == ("d0", "d1")


def test_zero2_shards_optimizer_moments_only(cpu_devices):
    mesh = build_mesh(8, 1, devices=cpu_devices)
    sh = lower_strategy(
        LayerStrategy(pp_deg=1, tp_size=1, dp_size=8,
                      dp_type=DPType.ZERO2), mesh)
    P = jax.sharding.PartitionSpec
    assert sh.param_spec(("embed", "mlp")) == P(None, None)  # replicated
    assert sh.opt_spec(("embed", "mlp")) == P(("d0", "d1", "d2"), None)


def test_pp3_mesh_allowed(cpu_devices):
    """pp need not be a power of two; only the per-stage world does."""
    mesh = build_mesh(6, 3, devices=cpu_devices[:6])
    assert dict(mesh.shape) == {"pp": 3, "d0": 2}


def test_multi_step_trajectory_matches_single_device(cpu_devices):
    """5 optimizer steps under tp2 x dp4(zero3): the loss trajectory and the
    threaded optimizer state must track the single-device run (reference
    tier-2 loss-trajectory comparisons)."""
    import optax

    params, axes = init_causal_lm(jax.random.key(0), CFG)
    args = _args(global_tp_deg=2, default_dp_type="zero3",
                 global_train_batch_size=8)
    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, hpc.pp_deg, devices=cpu_devices)
    tx = make_optimizer(TRAIN)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        CFG, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.float32, donate=False)
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)

    ref_p = params
    ref_o = tx.init(params)
    ref_loss_fn = lambda p, b: causal_lm_loss(p, b, CFG,
                                              compute_dtype=jnp.float32)

    for it in range(5):
        batch = _batch(seed=it)
        loss, grads = jax.value_and_grad(ref_loss_fn)(ref_p, batch)
        upd, ref_o = tx.update(grads, ref_o, ref_p)
        ref_p = optax.apply_updates(ref_p, upd)
        sp, opt, metrics = step(sp, opt, jax.device_put(batch, batch_shd))
        assert abs(float(metrics["loss"]) - float(loss)) < 5e-5, \
            f"iter {it}: {float(metrics['loss'])} vs {float(loss)}"
    for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_dcn_factor_shape():
    """dcn slices factor over pp first, then the outer binary d-axes, so
    tp/cp-bearing inner axes never cross DCN (reference locality,
    comm_groups.py:96-100, lifted to pod level)."""
    from hetu_galvatron_tpu.runtime.mesh import dcn_factor_shape

    assert dcn_factor_shape((1, 2, 2, 2), 2) == (1, 2, 1, 1)
    assert dcn_factor_shape((2, 2, 2, 2), 2) == (2, 1, 1, 1)
    assert dcn_factor_shape((2, 2, 2, 2), 4) == (2, 2, 1, 1)
    assert dcn_factor_shape((6, 2, 2), 4) == (2, 2, 1)  # pp 6 = 2 dcn x 3 ici
    with pytest.raises(ValueError, match="does not factor"):
        dcn_factor_shape((1, 2, 2), 8)


def test_build_mesh_dcn_single_process_fallback(cpu_devices):
    """Virtual CPU devices carry no pod topology: dcn_slices falls back to
    enumeration order (leading axes are outermost either way) and the mesh
    still lowers strategies normally."""
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    mesh = build_mesh(8, 1, devices=cpu_devices, dcn_slices=2)
    assert mesh.axis_names == ("pp", "d0", "d1", "d2")
    assert mesh.shape["pp"] == 1
    s = lower_strategy(
        LayerStrategy(pp_deg=1, tp_size=2, dp_size=4), mesh)
    assert s.tp_axes and s.dp_axes


def test_initialize_distributed_noop_single_process(monkeypatch):
    """num_processes<=1 and no COORDINATOR_ADDRESS => no coordination
    service; initialize() keeps working single-process."""
    from hetu_galvatron_tpu.runtime.initialize import initialize_distributed

    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    args = _args()
    assert initialize_distributed(args) is False
