"""Train/valid/test splits + the eval loop (reference
get_train_valid_test_data_iterators, runtime/dataloader.py:462, and the
split matrix in blended_megatron_dataset_builder.py:39): held-out documents
never leak into training samples, and validation loss is computed under the
distributed plan."""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.core

ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")


def test_split_doc_ranges_partition():
    from hetu_galvatron_tpu.data.indexed_dataset import split_doc_ranges

    for n in (1, 7, 100, 1000):
        ranges = split_doc_ranges(n, "969,30,1")
        assert ranges[0][0] == 0 and ranges[-1][1] == n
        for (a, b), (c, d) in zip(ranges, ranges[1:]):
            assert b == c  # contiguous, disjoint
    # zero ratio -> empty range
    tr, va, te = split_doc_ranges(100, "1,0,0")
    assert tr == (0, 100) and va[0] == va[1] and te[0] == te[1]
    with pytest.raises(ValueError):
        split_doc_ranges(10, "1,2")


def test_doc_range_isolated_tokens(tmp_path):
    """Samples drawn from the valid split contain ONLY tokens from its
    document range (no leakage across the split boundary)."""
    from hetu_galvatron_tpu.data.indexed_dataset import (
        GPTDataset,
        IndexedDataset,
        split_doc_ranges,
        write_indexed_dataset,
    )

    # 10 docs; doc d is 40 copies of token d -> membership is readable
    docs = [np.full(40, d, np.int32) for d in range(10)]
    prefix = str(tmp_path / "corpus")
    write_indexed_dataset(prefix, docs)
    idx = IndexedDataset(prefix)
    ranges = split_doc_ranges(len(idx), "8,1,1")
    assert ranges == [(0, 8), (8, 9), (9, 10)]
    valid = GPTDataset(idx, seq_length=16, shuffle=False,
                       doc_range=ranges[1])
    assert len(valid) >= 1
    for i in range(len(valid)):
        assert set(np.unique(valid[i])) <= {8}, "token from another split"
    train = GPTDataset(idx, seq_length=16, shuffle=False,
                       doc_range=ranges[0])
    seen = set()
    for i in range(len(train)):
        seen |= set(np.unique(train[i]).tolist())
    assert seen <= set(range(8))


def _train(extra, tmp_path):
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    src = tmp_path / "c.txt"
    src.write_text("".join(f"held out document number {i}\n"
                           for i in range(60)))
    prefix = str(tmp_path / "c")
    from hetu_galvatron_tpu.cli.preprocess_data import main as prep_main

    assert prep_main([str(src), prefix]) == 0
    argv = [os.path.join(ZOO, "gpt2-small.yaml"),
            "model.hidden_size=32", "model.num_hidden_layers=2",
            "model.num_attention_heads=2", "model.vocab_size=257",
            "model.seq_length=8", "model.max_position_embeddings=16",
            # default vocab padding (128) keeps 257 -> 384 divisible by vtp
            "model.use_flash_attn=false",
            "train.train_iters=2", "parallel.mixed_precision=fp32",
            "parallel.global_train_batch_size=8",
            "data.dataset=indexed", f"data.data_path=[{prefix}]",
            "data.split=8,1,1",
            "train.eval_interval=1", "train.eval_iters=2"] + extra
    return train(args_from_cli(argv, mode="train_dist"))


def test_eval_loop_spmd_plan(tmp_path):
    """Validation + test loss on held-out splits under a tp2 x dp plan."""
    out = _train(["parallel.global_tp_deg=2", "parallel.vocab_tp=2"],
                 tmp_path)
    assert len(out["val_losses"]) == 2  # eval_interval=1, 2 iters
    for v in out["val_losses"]:
        assert np.isfinite(v["loss"]) and v["loss"] > 0
    assert out["test_loss"] is not None and np.isfinite(out["test_loss"])


def test_eval_loop_pipeline_plan(tmp_path):
    """Same contract through the pipeline engine (pp=2)."""
    out = _train(["parallel.pp_deg=2", "parallel.chunks=2"], tmp_path)
    assert len(out["val_losses"]) == 2
    for v in out["val_losses"]:
        assert np.isfinite(v["loss"]) and v["loss"] > 0
    assert out["test_loss"] is not None and np.isfinite(out["test_loss"])
