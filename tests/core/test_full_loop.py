"""The whole product, end to end: profiled JSONs -> search engine -> searched
galvatron_config JSON -> training runtime executes the heterogeneous plan.
This is the reference's headline workflow (README "System Architecture":
Profiler -> Search Engine -> Runtime)."""

import glob
import json
import os

import pytest

pytestmark = [pytest.mark.distributed, pytest.mark.slow]

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")
ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                   "hetu_galvatron_tpu", "models", "configs")


def test_search_then_train_the_searched_plan(tmp_path, capsys):
    from hetu_galvatron_tpu.cli.search_dist import main as search_main
    from hetu_galvatron_tpu.cli.train_dist import main as train_main

    # 1) search (profiled fixtures, 8 devices, 36 GB) -> plan JSON
    rc = search_main([
        os.path.join(ZOO, "llama2-7b.yaml"),
        "model.num_hidden_layers=28", "model.seq_length=8192",
        "model.max_position_embeddings=8192",
        "search.settle_bsz=64", "search.settle_chunks=32",
        "search.memory_constraint=36", "search.default_dp_type=zero2",
        "search.pipeline_type=pipedream_flush",
        "search.async_grad_reduce=false",
        "search.time_profile_mode=sequence",
        "search.memory_profile_mode=sequence",
        f"search.time_profiling_path={FIXTURES}/computation_profiling_bf16_llama2-7b_all.json",
        f"search.memory_profiling_path={FIXTURES}/memory_profiling_bf16_llama2-7b_all.json",
        f"search.allreduce_bandwidth_config_path={FIXTURES}/allreduce_bandwidth_1nodes_8gpus_per_node.json",
        f"search.p2p_bandwidth_config_path={FIXTURES}/p2p_bandwidth_1nodes_8gpus_per_node.json",
        f"search.overlap_coe_path={FIXTURES}/overlap_coefficient.json",
        f"search.sp_time_path={FIXTURES}/sp_time_1nodes_8gpus_per_node.json",
        f"search.output_config_path={tmp_path}",
    ])
    assert rc == 0
    plan = glob.glob(os.path.join(str(tmp_path), "galvatron_config_*.json"))[0]
    cfg = json.load(open(plan))
    # the searched plan is heterogeneous: remat on some layers, not others
    assert "1" in cfg["checkpoint"] and "0" in cfg["checkpoint"]

    # 2) train a 28-layer (tiny-dim) model under the searched plan
    rc = train_main([
        os.path.join(ZOO, "llama2-7b.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=28",
        "model.num_attention_heads=4", "model.num_key_value_heads=4",
        "model.ffn_hidden_size=64", "model.vocab_size=64",
        "model.seq_length=8", "model.max_position_embeddings=16",
        "model.make_vocab_size_divisible_by=1",
        "parallel.mixed_precision=fp32", "train.train_iters=1",
        "parallel.config_mode=json",
        f"parallel.galvatron_config_path={plan}",
    ])
    assert rc == 0
    assert "training done: 1 iters" in capsys.readouterr().out


def test_t5_search_then_train_combined_stack(tmp_path, capsys):
    """Encoder-decoder end to end: the search runs over TWO layertypes
    (encoder, decoder), the plan records num_encoder_layers and spans the
    combined stack, and the runtime executes it — pp is searchable for t5
    now that the pipeline engine stage-slices both stacks."""
    from hetu_galvatron_tpu.cli.search_dist import main as search_main
    from hetu_galvatron_tpu.cli.train_dist import main as train_main

    # the llama fixtures profile one layertype; clone it as layertype_1 so
    # the t5 search sees per-layertype rows for encoder AND decoder
    comp = json.load(open(os.path.join(
        FIXTURES, "computation_profiling_bf16_llama2-7b_all.json")))
    comp.update({k.replace("layertype_0_", "layertype_1_"): v
                 for k, v in comp.items() if k.startswith("layertype_0_")})
    mem = json.load(open(os.path.join(
        FIXTURES, "memory_profiling_bf16_llama2-7b_all.json")))
    mem.update({k.replace("layertype_0_", "layertype_1_"): v
                for k, v in mem.items() if k.startswith("layertype_0_")})
    comp_path, mem_path = tmp_path / "comp.json", tmp_path / "mem.json"
    comp_path.write_text(json.dumps(comp))
    mem_path.write_text(json.dumps(mem))

    rc = search_main([
        os.path.join(ZOO, "t5-3b.yaml"),
        "model.num_hidden_layers=2", "model.num_encoder_layers=2",
        "model.seq_length=8192", "model.max_position_embeddings=8192",
        "search.settle_bsz=16", "search.settle_chunks=4",
        "search.max_pp_deg=2", "search.memory_constraint=36",
        "search.default_dp_type=zero2",
        "search.pipeline_type=pipedream_flush",
        "search.async_grad_reduce=false",
        "search.time_profile_mode=sequence",
        "search.memory_profile_mode=sequence",
        f"search.time_profiling_path={comp_path}",
        f"search.memory_profiling_path={mem_path}",
        f"search.allreduce_bandwidth_config_path={FIXTURES}/allreduce_bandwidth_1nodes_8gpus_per_node.json",
        f"search.p2p_bandwidth_config_path={FIXTURES}/p2p_bandwidth_1nodes_8gpus_per_node.json",
        f"search.overlap_coe_path={FIXTURES}/overlap_coefficient.json",
        f"search.sp_time_path={FIXTURES}/sp_time_1nodes_8gpus_per_node.json",
        f"search.output_config_path={tmp_path}",
    ])
    assert rc == 0
    plan = glob.glob(os.path.join(str(tmp_path), "galvatron_config_t5*.json"))[0]
    cfg = json.load(open(plan))
    assert cfg["num_encoder_layers"] == 2
    assert len(cfg["tp_sizes_enc"].split(",")) == 4  # enc 2 + dec 2

    rc = train_main([
        os.path.join(ZOO, "t5-3b.yaml"),
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_encoder_layers=2", "model.num_attention_heads=2",
        "model.ffn_hidden_size=64", "model.vocab_size=64",
        "model.seq_length=16", "model.max_position_embeddings=16",
        "model.make_vocab_size_divisible_by=1",
        "parallel.mixed_precision=fp32", "train.train_iters=1",
        "parallel.config_mode=json",
        f"parallel.galvatron_config_path={plan}",
    ])
    assert rc == 0
    assert "training done: 1 iters" in capsys.readouterr().out
