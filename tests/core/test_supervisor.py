"""Preemption-aware supervisor (runtime/supervisor.py): signal trapping,
restart policy honoring the rerun machine's 16/17 exit-code contract, and
the at-step-k fault drill harness."""

import signal

import pytest

from hetu_galvatron_tpu.core.args_schema import RerunArgs
from hetu_galvatron_tpu.observability.registry import MetricsRegistry
from hetu_galvatron_tpu.runtime.rerun_machine import (
    EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
    FaultDrill,
    InjectedCrash,
)
from hetu_galvatron_tpu.runtime.supervisor import (
    EXIT_CODE_CHECKPOINT_AND_EXIT,
    RESTARTABLE_EXIT_CODES,
    PreemptionGuard,
    run_with_restarts,
)

pytestmark = [pytest.mark.core, pytest.mark.robustness]


# -- PreemptionGuard --------------------------------------------------------


def test_guard_catches_real_sigterm_and_restores_handlers():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert not g.requested()
        signal.raise_signal(signal.SIGTERM)  # a REAL signal, not a flag poke
        assert g.requested()
    assert signal.getsignal(signal.SIGTERM) is before


def test_guard_second_signal_escalates_to_previous_handler():
    """A hung step never reaches the boundary check, so the SECOND signal
    of the same kind must escalate (restore the previous handler and
    re-deliver) instead of being swallowed — a stuck run stays killable
    without SIGKILL."""
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        with PreemptionGuard() as g:
            signal.raise_signal(signal.SIGTERM)
            assert g.requested() and not hits  # first: absorbed, flagged
            signal.raise_signal(signal.SIGTERM)
            assert hits == [signal.SIGTERM]  # second: escalated
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_guard_counts_signals():
    """The handler itself is async-signal-safe (flag only — a registry
    counter there could deadlock on the registry lock the interrupted
    thread holds); the signal is counted when the main loop polls
    requested(), exactly once."""
    reg = MetricsRegistry()
    with PreemptionGuard(registry=reg) as g:
        signal.raise_signal(signal.SIGTERM)
        assert reg.counter("supervisor/preemption_signals",
                           sig="SIGTERM").value == 0  # not in the handler
        assert g.requested()
        assert g.requested()  # idempotent count
    assert reg.counter("supervisor/preemption_signals",
                       sig="SIGTERM").value == 1


def test_guard_maps_sigint_to_nonrestartable_exit():
    """Ctrl-C is a deliberate stop: it checkpoints like a preemption but
    must NOT be auto-restarted (the fleet's SIGTERM is)."""
    from hetu_galvatron_tpu.runtime.supervisor import EXIT_CODE_INTERRUPTED

    with PreemptionGuard() as g:
        signal.raise_signal(signal.SIGINT)
        assert g.requested()
        assert g.exit_code() == EXIT_CODE_INTERRUPTED
    assert EXIT_CODE_INTERRUPTED not in RESTARTABLE_EXIT_CODES
    with PreemptionGuard() as g:
        signal.raise_signal(signal.SIGTERM)
        assert g.exit_code() == EXIT_CODE_CHECKPOINT_AND_EXIT
    # a drill request (no signal) reads as preemption
    g = PreemptionGuard(enabled=False)
    with g:
        g.request()
        assert g.exit_code() == EXIT_CODE_CHECKPOINT_AND_EXIT


def test_guard_disabled_installs_nothing():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(enabled=False) as g:
        assert signal.getsignal(signal.SIGTERM) is before
        g.request()  # drills can still set the flag programmatically
        assert g.requested()


def test_guard_rearms_per_entry():
    g = PreemptionGuard(enabled=False)
    with g:
        g.request()
        assert g.requested()
    with g:
        assert not g.requested()  # a fresh loop starts clean


# -- run_with_restarts ------------------------------------------------------


def _supervised(codes, **kw):
    """Run a scripted sequence of exit codes, recording sleeps."""
    sleeps = []
    seq = list(codes)

    def attempt():
        c = seq.pop(0)
        if isinstance(c, Exception):
            raise c
        return c

    rc = run_with_restarts(attempt, sleep=sleeps.append,
                           log=lambda m: None, registry=MetricsRegistry(),
                           **kw)
    return rc, sleeps, seq


def test_restarts_on_resume_to_disambiguate_then_succeeds():
    rc, sleeps, left = _supervised(
        [EXIT_CODE_RESUME_TO_DISAMBIGUATE, EXIT_CODE_RESUME_TO_DISAMBIGUATE, 0],
        max_restarts=3, base_delay=1.0)
    assert rc == 0 and not left
    assert len(sleeps) == 2
    # jittered exponential: each delay within its attempt's envelope
    assert 0 <= sleeps[0] <= 1.0 and 0 <= sleeps[1] <= 2.0


def test_restarts_on_preemption_code():
    rc, sleeps, _ = _supervised([EXIT_CODE_CHECKPOINT_AND_EXIT, 0],
                                max_restarts=2)
    assert rc == 0 and len(sleeps) == 1


def test_failed_validation_is_terminal():
    """Exit 17 = persistent fault: restarting would reproduce it, so the
    supervisor surfaces it immediately (the reference's contract)."""
    rc, sleeps, left = _supervised(
        [EXIT_CODE_FAILED_ON_RESULT_VALIDATION, 0], max_restarts=3)
    assert rc == EXIT_CODE_FAILED_ON_RESULT_VALIDATION
    assert not sleeps and left == [0]  # never restarted


def test_unknown_code_is_terminal():
    rc, sleeps, _ = _supervised([5, 0], max_restarts=3)
    assert rc == 5 and not sleeps


def test_restart_budget_is_bounded():
    rc, sleeps, _ = _supervised(
        [EXIT_CODE_CHECKPOINT_AND_EXIT] * 5, max_restarts=2)
    assert rc == EXIT_CODE_CHECKPOINT_AND_EXIT
    assert len(sleeps) == 2  # exactly max_restarts backoffs, then give up


def test_restart_budget_resets_on_progress():
    """The budget bounds crash LOOPS, not total preemptions: attempts
    that committed a new checkpoint reset the counter, so a preemptible
    fleet can preempt a healthy run more than max_restarts times."""
    seq = [EXIT_CODE_CHECKPOINT_AND_EXIT] * 6 + [0]
    steps = iter(range(100))

    rc = run_with_restarts(
        lambda: seq.pop(0), max_restarts=2,
        progress_fn=lambda: next(steps),  # every attempt advanced
        sleep=lambda s: None, log=lambda m: None,
        registry=MetricsRegistry())
    assert rc == 0 and not seq  # survived 6 preemptions on a budget of 2

    # without progress the same sequence exhausts the budget
    rc2, sleeps, _ = _supervised(
        [EXIT_CODE_CHECKPOINT_AND_EXIT] * 6 + [0], max_restarts=2,
        progress_fn=lambda: "step_0")  # checkpoint never advances
    assert rc2 == EXIT_CODE_CHECKPOINT_AND_EXIT and len(sleeps) == 2


def test_world_change_resets_restart_budget():
    """A topology-change restart is PROGRESS (the attempt will re-search
    and reshard, not repeat the fault): when world_fn's value differs
    between attempts the budget resets exactly as a committed checkpoint
    would reset it — while a same-world exit loop still exhausts it."""
    reg = MetricsRegistry()
    seq = [EXIT_CODE_CHECKPOINT_AND_EXIT] * 6 + [0]
    # the fleet shrinks every other attempt: 8 -> 8 -> 4 -> 4 -> 2 -> 2
    worlds = iter([8, 4, 4, 2, 2, 1, 1, 1])

    rc = run_with_restarts(
        lambda: seq.pop(0), max_restarts=2,
        world_fn=lambda: next(worlds),
        sleep=lambda s: None, log=lambda m: None, registry=reg)
    assert rc == 0 and not seq  # survived 6 exits on a budget of 2
    assert reg.counter("supervisor/world_changes").value >= 2

    # a STATIC world with the same exit sequence exhausts the budget
    seq2 = [EXIT_CODE_CHECKPOINT_AND_EXIT] * 6 + [0]
    rc2 = run_with_restarts(
        lambda: seq2.pop(0), max_restarts=2,
        world_fn=lambda: 8,
        sleep=lambda s: None, log=lambda m: None,
        registry=MetricsRegistry())
    assert rc2 == EXIT_CODE_CHECKPOINT_AND_EXIT


def test_reshard_failure_code_17_is_terminal_not_a_restart_loop():
    """An OOM-rejected elastic target plan exits 17 (failed result
    validation — it reproduces on every restart): the supervisor must
    surface it immediately, even when the world just changed."""
    calls = []

    def attempt():
        calls.append(1)
        return EXIT_CODE_FAILED_ON_RESULT_VALIDATION

    worlds = iter([8, 4, 4, 4])
    rc = run_with_restarts(
        attempt, max_restarts=5, world_fn=lambda: next(worlds),
        sleep=lambda s: None, log=lambda m: None,
        registry=MetricsRegistry())
    assert rc == EXIT_CODE_FAILED_ON_RESULT_VALIDATION
    assert len(calls) == 1  # no restart loop


def test_crash_restarts_when_enabled():
    rc, sleeps, _ = _supervised([InjectedCrash("boom"), 0],
                                max_restarts=2, restart_on_error=True)
    assert rc == 0 and len(sleeps) == 1


def test_crash_reraises_when_disabled():
    with pytest.raises(InjectedCrash):
        _supervised([InjectedCrash("boom"), 0],
                    max_restarts=2, restart_on_error=False)


def test_crash_budget_exhaustion_reraises():
    with pytest.raises(InjectedCrash, match="third"):
        _supervised([InjectedCrash("a"), InjectedCrash("b"),
                     InjectedCrash("third")],
                    max_restarts=2, restart_on_error=True)


def test_restarts_counted_in_registry():
    reg = MetricsRegistry()
    seq = [EXIT_CODE_CHECKPOINT_AND_EXIT, 0]
    run_with_restarts(lambda: seq.pop(0), sleep=lambda s: None,
                      log=lambda m: None, registry=reg)
    assert reg.counter("supervisor/restarts",
                       code=EXIT_CODE_CHECKPOINT_AND_EXIT).value == 1


# -- FaultDrill -------------------------------------------------------------


def _drill(**kw):
    reg = MetricsRegistry()
    return FaultDrill(RerunArgs(**kw), registry=reg), reg


def test_drill_nan_fires_once_at_step_k():
    d, reg = _drill(inject_kind="nan", inject_at_iter=2)
    import math

    assert d.apply(1.0, 0) == 1.0
    assert d.apply(1.0, 1) == 1.0
    assert math.isnan(d.apply(1.0, 2))
    assert d.apply(1.0, 2) == 1.0  # one-shot: re-running step 2 is clean
    assert reg.counter("faults/injected", kind="nan").value == 1


def test_drill_spike_scales_loss():
    d, _ = _drill(inject_kind="spike", inject_at_iter=0,
                  inject_spike_scale=50.0)
    assert d.apply(2.0, 0) == pytest.approx(101.0)


def test_drill_crash_raises():
    d, reg = _drill(inject_kind="crash", inject_at_iter=1)
    d.apply(1.0, 0)
    with pytest.raises(InjectedCrash, match="iteration 1"):
        d.apply(1.0, 1)
    assert reg.counter("faults/injected", kind="crash").value == 1


def test_drill_preempt_delivers_real_sigterm():
    d, _ = _drill(inject_kind="preempt", inject_at_iter=0)
    with PreemptionGuard() as g:
        assert d.apply(1.0, 0) == 1.0  # loss untouched; the signal fires
        assert g.requested()


def test_drill_disarms_on_resumed_runs():
    d, reg = _drill(inject_kind="nan", inject_at_iter=3)
    d.arm(start_iter=3)  # resumed past/at the drill point: train clean
    assert d.apply(1.0, 3) == 1.0
    assert reg.counter("faults/injected", kind="nan").value == 0


def test_drill_none_is_identity():
    d, _ = _drill()
    assert d.apply(float("inf"), 0) == float("inf")
