"""Overlapped-TP training parity + hygiene on the virtual 8-device mesh:
the acceptance drill (3-step train trajectory, overlap on vs off, under a
searched-format tp2 x dp2 plan JSON), steady-state recompile pinning with a
transfer guard, and the launcher-level fallback/telemetry wiring."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import init_causal_lm
from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step, shard_params
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.mesh import build_mesh
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
from hetu_galvatron_tpu.utils.strategy import (
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    strategy_list2config,
)

pytestmark = [pytest.mark.core, pytest.mark.tp_overlap,
              pytest.mark.distributed]

CFG = ModelArgs(
    hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=128,
)
TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _searched_plan_json(tmp_path, tp=2, dp=2):
    """A tp x dp plan in the searched-config interchange format (the JSON
    the search engine's save_results writes)."""
    layers = [LayerStrategy(pp_deg=1, tp_size=tp, dp_size=dp)
              for _ in range(CFG.num_hidden_layers)]
    cfg = strategy_list2config(
        layers, global_bsz=8, chunks=1, pipeline_type="pipedream_flush",
        default_dp_type="ddp", vocab=EmbeddingLMHeadStrategy(vtp=tp),
        pp_division=[CFG.num_hidden_layers])
    path = tmp_path / "galvatron_config_drill.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def _steps(tmp_path, cpu_devices, tp_overlap, world=4, n=3):
    a = CoreArgs(model=CFG.model_dump(), train=TRAIN.model_dump())
    a.parallel.config_mode = "json"
    a.parallel.galvatron_config_path = _searched_plan_json(tmp_path)
    hpc = get_hybrid_parallel_config(a, world)
    mesh = build_mesh(world, 1, devices=cpu_devices[:world])
    tx = make_optimizer(TRAIN)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        CFG, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False, tp_overlap=tp_overlap)
    sp = shard_params(params, pspecs, mesh)
    so = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    data = np.random.RandomState(0).randint(0, 128, (8, CFG.seq_length + 1))
    b = jax.device_put(jax.tree.map(jnp.asarray, make_batch(data)),
                       batch_shd)
    losses = []
    for _ in range(n):
        sp, so, m = step(sp, so, b)
        losses.append(float(m["loss"]))
    return step, sp, so, b, losses


def test_trajectory_drill_searched_tp2_dp2_plan(tmp_path, cpu_devices):
    """Acceptance: 3-step train trajectory, overlap on vs off, under a
    searched tp2 x dp2 plan — identical to tolerance, params included."""
    _, sp0, _, _, l0 = _steps(tmp_path, cpu_devices, tp_overlap=False)
    _, sp1, _, _, l1 = _steps(tmp_path, cpu_devices, tp_overlap=True)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sp0),
            jax.tree_util.tree_leaves_with_path(sp1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


def test_overlap_step_recompile_pinning_and_no_transfers(
        tmp_path, cpu_devices):
    """The overlapped step compiles exactly once and its steady state moves
    no host data (pinned with jax.transfer_guard)."""
    step, sp, so, b, _ = _steps(tmp_path, cpu_devices, tp_overlap=True, n=1)
    assert step._cache_size() == 1
    for _ in range(2):
        with jax.transfer_guard("disallow"):
            sp, so, m = step(sp, so, b)
    jax.block_until_ready(m["loss"])
    assert step._cache_size() == 1, "steady state recompiled"


def test_train_dist_cli_tp_overlap(tmp_path, cpu_devices):
    """Launcher wiring end to end: tp_overlap.enable trains, logs the
    overlapped-layer count, emits the tp/comm_hidden_frac gauge and the
    tp/overlap_step span into the metrics stream, and summarize renders
    the hidden fraction."""
    from hetu_galvatron_tpu.cli.summarize import summarize
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    metrics = tmp_path / "metrics.jsonl"
    args = args_from_cli([
        "model.hidden_size=64", "model.num_hidden_layers=2",
        "model.num_attention_heads=4", "model.vocab_size=128",
        "model.seq_length=16", "model.max_position_embeddings=64",
        "model.hidden_act=swiglu", "model.normalization=rmsnorm",
        "model.position_embedding_type=rope",
        "model.tie_word_embeddings=false", "model.add_bias_linear=false",
        "model.make_vocab_size_divisible_by=1",
        "model.ffn_hidden_size=128", "model.use_flash_attn=false",
        "parallel.global_tp_deg=2", "parallel.global_train_batch_size=8",
        "parallel.num_devices=8",
        "tp_overlap.enable=true", "train.train_iters=2",
        "observability.enabled=true",
        f"observability.metrics_path={metrics}",
    ], mode="train_dist")
    out = train(args)
    assert len(out["losses"]) == 2
    assert all(np.isfinite(out["losses"]))
    records = [json.loads(ln) for ln in
               metrics.read_text().splitlines() if ln.strip()]
    gauges = {r["name"]: r for r in records if r.get("kind") == "gauge"}
    assert "tp/comm_hidden_frac" in gauges
    # every layer of the uniform tp2 plan is overlap-expressible, so the
    # whole TP volume is on the overlapped path
    assert gauges["tp/comm_hidden_frac"]["value"] == pytest.approx(1.0)
    spans = {json.loads(lb)["path"] for (lb,) in
             [(json.dumps(r.get("labels") or {}),) for r in records
              if r.get("kind") == "histogram" and r.get("name") == "span_ms"]}
    assert "tp/overlap_step" in spans
    import io

    buf = io.StringIO()
    head = summarize(str(metrics), out=buf)
    assert head.get("tp_comm_hidden_frac") == pytest.approx(1.0)
    assert "TP comm overlapped" in buf.getvalue()


def test_tp_overlap_cli_fallback_reasons(tmp_path):
    """tp_overlap.enable with tp == 1 logs the reason and runs the GSPMD
    path (no crash, no gauge)."""
    from hetu_galvatron_tpu.cli.train_dist import train
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    metrics = tmp_path / "m.jsonl"
    args = args_from_cli([
        "model.hidden_size=32", "model.num_hidden_layers=2",
        "model.num_attention_heads=2", "model.vocab_size=64",
        "model.seq_length=8", "model.max_position_embeddings=16",
        "model.make_vocab_size_divisible_by=1",
        "model.use_flash_attn=false",
        "parallel.global_train_batch_size=4", "parallel.num_devices=2",
        "tp_overlap.enable=true", "train.train_iters=1",
        "observability.enabled=true",
        f"observability.metrics_path={metrics}",
    ], mode="train_dist")
    out = train(args)
    assert len(out["losses"]) == 1
    records = [json.loads(ln) for ln in
               metrics.read_text().splitlines() if ln.strip()]
    assert not any(r.get("name") == "tp/comm_hidden_frac" for r in records)


def test_host_pipeline_engine_tp_overlap_parity(cpu_devices):
    """pp2 x tp2 x dp2 through the host PipelineEngine: the overlapped
    stage programs reproduce the GSPMD stage programs' 2-step trajectory."""
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    cfg = CFG.model_copy(update={"num_hidden_layers": 4})
    a = CoreArgs(model=cfg.model_dump(), train=TRAIN.model_dump())
    a.parallel.pp_deg = 2
    a.parallel.global_tp_deg = 2
    a.parallel.chunks = 2
    a.parallel.pipeline_type = "pipedream_flush"
    a.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(a, 8)
    data = np.random.RandomState(0).randint(0, 128, (8, cfg.seq_length + 1))
    batch = make_batch(data)

    def run(tp_overlap):
        eng = PipelineEngine(cfg, hpc, TRAIN, devices=cpu_devices,
                             compute_dtype=jnp.float32,
                             tp_overlap=tp_overlap)
        params, axes = init_causal_lm(jax.random.key(0), cfg)
        sp = eng.split_params(params, axes)
        so = eng.init_opt(sp, axes)
        losses = []
        for _ in range(2):
            sp, so, m = eng.train_step(sp, so, batch)
            losses.append(float(m["loss"]))
        return losses, sp

    l0, sp0 = run(False)
    l1, sp1 = run(True)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-5)
    for s0, s1 in zip(sp0, sp1):
        for (pa, x), (_, y) in zip(
                jax.tree_util.tree_leaves_with_path(s0),
                jax.tree_util.tree_leaves_with_path(s1)):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=5e-4, atol=3e-4,
                err_msg=f"stage param {jax.tree_util.keystr(pa)}")
