"""Checkpoint save/restore + HF interchange (reference
test_checkpoint_convert.py + distributed ckpt round-trips)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import forward_causal_lm, init_causal_lm
from hetu_galvatron_tpu.runtime.checkpoint import (
    hf_to_params,
    latest_checkpoint,
    load_checkpoint,
    params_to_hf,
    save_checkpoint,
)
from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

pytestmark = pytest.mark.model

TINY = ModelArgs(
    hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
    vocab_size=64, max_position_embeddings=16, seq_length=8,
    make_vocab_size_divisible_by=1)


def test_save_load_roundtrip(tmp_path):
    params, _ = init_causal_lm(jax.random.key(0), TINY)
    tx = make_optimizer(TrainArgs())
    opt = tx.init(params)
    d = save_checkpoint(str(tmp_path), 7, params, opt)
    assert latest_checkpoint(str(tmp_path)) == d
    target_p = jax.tree.map(lambda x: jnp.zeros_like(x), params)
    target_o = jax.tree.map(lambda x: jnp.zeros_like(x), opt)
    p2, o2, step = load_checkpoint(d, target_p, target_o)
    assert step == 7
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert o2 is not None
    for a, b in zip(jax.tree.leaves(opt), jax.tree.leaves(o2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_checkpoint_picks_max(tmp_path):
    params, _ = init_causal_lm(jax.random.key(0), TINY)
    save_checkpoint(str(tmp_path), 2, params)
    save_checkpoint(str(tmp_path), 10, params)
    assert latest_checkpoint(str(tmp_path)).endswith("step_10")
    assert latest_checkpoint(str(tmp_path / "nope")) is None


@pytest.mark.robustness
def test_latest_checkpoint_skips_stray_entries(tmp_path):
    """Non-integer step suffixes (orbax temp dirs, step_5.partial) and
    uncommitted dirs must be skipped, not crash resume with ValueError."""
    import os

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    d = save_checkpoint(str(tmp_path), 3, params)
    (tmp_path / "step_x").mkdir()
    (tmp_path / "step_5.partial").mkdir()
    (tmp_path / "step_7.orbax-checkpoint-tmp-123").mkdir()
    (tmp_path / "step_9.tmp").mkdir()  # crashed mid-save staging dir
    # an uncommitted final-named dir (no marker, no meta.json)
    (tmp_path / "step_99").mkdir()
    (tmp_path / "step_4").write_text("a file, not a dir")
    assert latest_checkpoint(str(tmp_path)) == d
    # stray entries we did not create survive GC; our staging dir and the
    # uncommitted partial do not
    from hetu_galvatron_tpu.runtime.checkpoint import gc_checkpoints

    gc_checkpoints(str(tmp_path))
    assert os.path.isdir(tmp_path / "step_x")
    assert os.path.isdir(tmp_path / "step_5.partial")
    assert not os.path.isdir(tmp_path / "step_9.tmp")
    assert not os.path.isdir(tmp_path / "step_99")
    assert latest_checkpoint(str(tmp_path)) == d


@pytest.mark.robustness
def test_keep_last_retention(tmp_path):
    import os

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    for s in (1, 2, 3):
        save_checkpoint(str(tmp_path), s, params)
    save_checkpoint(str(tmp_path), 4, params, keep_last=2)
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert kept == ["step_3", "step_4"]
    assert latest_checkpoint(str(tmp_path)).endswith("step_4")
    # the async commit path enforces the same bound (its own just-committed
    # dir must count toward keep_last, not read as in-flight)
    from hetu_galvatron_tpu.runtime.checkpoint import wait_for_checkpoints

    save_checkpoint(str(tmp_path), 5, params, async_save=True, keep_last=2)
    wait_for_checkpoints()
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert kept == ["step_4", "step_5"]


@pytest.mark.robustness
def test_async_save_commits_only_after_wait(tmp_path):
    """An async save is invisible to latest_checkpoint until
    wait_for_checkpoints commits it through the same marker/rename
    protocol."""
    import os

    from hetu_galvatron_tpu.runtime.checkpoint import wait_for_checkpoints

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    d1 = save_checkpoint(str(tmp_path), 1, params)
    d2 = save_checkpoint(str(tmp_path), 2, params, async_save=True)
    # not committed yet: the staging dir exists, the final name does not
    assert os.path.isdir(d2 + ".tmp")
    assert not os.path.isdir(d2)
    assert latest_checkpoint(str(tmp_path)) == d1
    wait_for_checkpoints()
    assert latest_checkpoint(str(tmp_path)) == d2
    assert os.path.exists(os.path.join(d2, "meta.json"))
    # idempotent when drained
    wait_for_checkpoints()


@pytest.mark.robustness
def test_wait_for_checkpoints_drains_despite_failure(tmp_path):
    """A failing commit mid-drain must not abandon the remaining async
    saves unawaited: everything drains, the first error re-raises."""
    from hetu_galvatron_tpu.runtime import checkpoint as ck

    class FakeCkptr:
        def __init__(self, log, name, fail=False):
            self.log, self.name, self.fail = log, name, fail

        def wait_until_finished(self):
            self.log.append(self.name)
            if self.fail:
                raise IOError(f"flaky wait: {self.name}")

    log = []
    for i, fail in enumerate([False, True, False]):
        d = tmp_path / f"step_{i + 1}"
        d.mkdir()
        ck._PENDING.append(ck._PendingSave(
            [FakeCkptr(log, f"c{i}", fail)], str(d) + ".tmp", str(d),
            str(tmp_path)))
    # give the non-failing entries real staging dirs so their commit works
    (tmp_path / "step_1.tmp").mkdir()
    (tmp_path / "step_3.tmp").mkdir()
    with pytest.raises(IOError, match="flaky wait: c1"):
        ck.wait_for_checkpoints()
    assert log == ["c0", "c1", "c2"]  # every save awaited, none dropped
    assert not ck._PENDING


@pytest.mark.robustness
def test_train_state_rides_meta(tmp_path):
    from hetu_galvatron_tpu.runtime.checkpoint import read_checkpoint_meta

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    ts = {"step": 4, "seed": 7, "batches_consumed": 4,
          "rerun": {"records": [], "ema": 2.5}}
    d = save_checkpoint(str(tmp_path), 4, params, train_state=ts)
    meta = read_checkpoint_meta(d)
    assert meta["step"] == 4
    assert meta["train_state"] == ts
    assert read_checkpoint_meta(str(tmp_path / "nowhere")) == {}


def test_plan_mismatch_raises(tmp_path):
    params, _ = init_causal_lm(jax.random.key(0), TINY)
    args = CoreArgs(model=TINY.model_dump())
    args.parallel.global_tp_deg = 2
    hpc = get_hybrid_parallel_config(args, 8)
    d = save_checkpoint(str(tmp_path), 1, params, hpc=hpc)
    args2 = CoreArgs(model=TINY.model_dump())
    args2.parallel.global_tp_deg = 1
    hpc2 = get_hybrid_parallel_config(args2, 8)
    with pytest.raises(ValueError, match="plan mismatch"):
        load_checkpoint(d, params, hpc=hpc2, strict_plan=True)
    # non-strict restore reshards instead
    p2, _, _ = load_checkpoint(d, params, hpc=hpc2)
    assert p2 is not None


def test_hf_gpt2_roundtrip_and_forward():
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(vocab_size=64, n_positions=16, n_embd=32, n_layer=2,
                        n_head=2, activation_function="gelu_new",
                        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), TINY)
    tokens_np = np.random.RandomState(0).randint(0, 64, (2, 8))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), TINY,
                             compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)
    # g2h inverse gives back identical tensors
    sd = params_to_hf(params, TINY)
    for k, v in sd.items():
        np.testing.assert_allclose(v, np.asarray(hf.state_dict()[k]),
                                   atol=1e-6, err_msg=k)


def test_hf_llama_roundtrip():
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = ModelArgs(
        model_type="llama", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, num_key_value_heads=2, ffn_hidden_size=48,
        vocab_size=64, max_position_embeddings=16, seq_length=8,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1)
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=16, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    sd = params_to_hf(params, cfg)
    ref_sd = hf.state_dict()
    for k, v in sd.items():
        np.testing.assert_allclose(v, np.asarray(ref_sd[k]), atol=1e-6,
                                   err_msg=k)


def test_resume_continues_training(tmp_path):
    """Save mid-run, restore, and verify the next step's loss matches an
    uninterrupted run exactly."""
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    tx = make_optimizer(TrainArgs(lr=1e-2, lr_decay_style="constant"))
    step = jax.jit(make_train_step(make_loss_fn(TINY,
                                                compute_dtype=jnp.float32),
                                   tx))
    batch = jax.tree.map(jnp.asarray, make_batch(
        np.random.RandomState(0).randint(0, 64, (4, 9))))
    opt = tx.init(params)
    p1, o1, _ = step(params, opt, batch)
    d = save_checkpoint(str(tmp_path), 1, p1, o1)
    p2, o2, _ = step(p1, o1, batch)  # uninterrupted second step

    rp, ro, _ = load_checkpoint(d, jax.tree.map(jnp.zeros_like, p1),
                                jax.tree.map(jnp.zeros_like, o1))
    rp2, ro2, m = step(rp, ro, batch)
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(rp2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_hf_mixtral_roundtrip():
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    cfg = ModelArgs(
        model_type="moe", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, num_key_value_heads=2, ffn_hidden_size=48,
        moe_ffn_hidden_size=48, vocab_size=64, max_position_embeddings=16,
        seq_length=8, hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, num_experts=4, moe_topk=2)
    hf_cfg = MixtralConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=16, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = MixtralForCausalLM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    assert "moe" in params["layers"][0]
    assert params["layers"][0]["moe"]["win"].shape == (4, 32, 96)
    sd = params_to_hf(params, cfg)
    ref_sd = hf.state_dict()
    for k, v in sd.items():
        np.testing.assert_allclose(v, np.asarray(ref_sd[k]), atol=1e-6,
                                   err_msg=k)
    # imported params run a finite forward through our MoE stack
    import jax, jax.numpy as jnp
    from hetu_galvatron_tpu.models.builder import causal_lm_loss
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
    loss = causal_lm_loss(params, {"tokens": tokens, "labels": tokens}, cfg,
                          compute_dtype=jnp.float32)
    assert np.isfinite(float(loss))


def test_hf_bert_roundtrip_and_forward():
    """BERT h2g: HF BertForMaskedLM logits must match our post-norm encoder
    exactly (embeddings LN + post-LN blocks + MLM transform head); g2h is the
    tensor-exact inverse (token-type folded into wpe, exported as zeros)."""
    torch = pytest.importorskip("torch")
    from transformers import BertConfig, BertForMaskedLM

    cfg = ModelArgs(
        model_type="bert", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, ffn_hidden_size=64, vocab_size=64,
        max_position_embeddings=16, seq_length=8, hidden_act="gelu_exact",
        tie_word_embeddings=True, make_vocab_size_divisible_by=1,
        layernorm_epsilon=1e-12)
    hf_cfg = BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=16, hidden_act="gelu",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = BertForMaskedLM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    tokens_np = np.random.RandomState(0).randint(0, 64, (2, 8))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), cfg,
                             compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)
    sd = params_to_hf(params, cfg)
    ref_sd = {k: np.asarray(v) for k, v in hf.state_dict().items()}
    for k, v in sd.items():
        if k == "bert.embeddings.position_embeddings.weight":
            # import folds token_type[0] into wpe; export keeps the fold
            np.testing.assert_allclose(
                v, ref_sd[k]
                + ref_sd["bert.embeddings.token_type_embeddings.weight"][0],
                atol=1e-6, err_msg=k)
        elif k == "bert.embeddings.token_type_embeddings.weight":
            np.testing.assert_allclose(v, 0.0)
        else:
            np.testing.assert_allclose(v, ref_sd[k], atol=1e-6, err_msg=k)
    # and re-importing the export reproduces the same forward
    params2 = hf_to_params(sd, cfg)
    ours2 = forward_causal_lm(params2, jnp.asarray(tokens_np), cfg,
                              compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours2), np.asarray(ours), atol=1e-6)


@pytest.mark.robustness
@pytest.mark.elastic
def test_world_mismatch_raises_typed_error(tmp_path):
    """A topology-changed resume must fail AT LOAD with both worlds named
    (not as a shape error deep in device_put) — the exact condition the
    elastic resume path catches to trigger re-search + reshard."""
    from hetu_galvatron_tpu.runtime.checkpoint import WorldSizeMismatchError

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    args = CoreArgs.model_validate({"model": TINY.model_dump()})
    hpc = get_hybrid_parallel_config(args, 2)
    save_checkpoint(str(tmp_path), 3, params, hpc=hpc)
    d = latest_checkpoint(str(tmp_path))

    # same world: loads fine with the check armed
    p2, _, step = load_checkpoint(d, params, expected_world=2)
    assert step == 3

    with pytest.raises(WorldSizeMismatchError) as ei:
        load_checkpoint(d, params, expected_world=1)
    err = ei.value
    assert err.stored_world == 2 and err.live_world == 1
    assert "2-device" in str(err) and "1 devices" in str(err)
    assert "reshard" in str(err)  # actionable: names the remedy

    # legacy checkpoints (no plan fingerprint) stay loadable
    save_checkpoint(str(tmp_path / "legacy"), 1, params)
    d2 = latest_checkpoint(str(tmp_path / "legacy"))
    load_checkpoint(d2, params, expected_world=1)


@pytest.mark.robustness
@pytest.mark.elastic
def test_gc_never_reaps_live_resume_selection(tmp_path):
    """keep_last pruning racing a concurrent resume must never delete the
    step latest_checkpoint() just selected — the selection is held out of
    the prune set until the next selection releases it."""
    import os

    from hetu_galvatron_tpu.runtime.checkpoint import gc_checkpoints

    params, _ = init_causal_lm(jax.random.key(0), TINY)
    for s in (1, 2, 3):
        save_checkpoint(str(tmp_path), s, params)
    sel = latest_checkpoint(str(tmp_path))
    assert sel.endswith("step_3")

    # a newer save commits and prunes aggressively while the resume is
    # between its latest_checkpoint() and the shard/meta reads
    save_checkpoint(str(tmp_path), 4, params, keep_last=1)
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert kept == ["step_3", "step_4"]  # selection survived; 1/2 pruned
    load_checkpoint(sel, params)  # the resume still completes

    # the NEXT selection releases the old protection
    assert latest_checkpoint(str(tmp_path)).endswith("step_4")
    gc_checkpoints(str(tmp_path), keep_last=1)
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert kept == ["step_4"]


def test_hf_t5_roundtrip():
    """T5 h2g/g2h: every projection/norm tensor round-trips exactly (position
    scheme intentionally differs — models/encdec.py is RoPE/learned by
    design, so no logit parity leg here)."""
    torch = pytest.importorskip("torch")
    from transformers import T5Config, T5ForConditionalGeneration

    cfg = ModelArgs(
        model_type="t5", hidden_size=32, num_hidden_layers=2,
        num_encoder_layers=3, num_attention_heads=2, ffn_hidden_size=48,
        vocab_size=64, max_position_embeddings=16, seq_length=8,
        hidden_act="geglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1)
    hf_cfg = T5Config(
        vocab_size=64, d_model=32, d_kv=16, d_ff=48, num_layers=3,
        num_decoder_layers=2, num_heads=2, feed_forward_proj="gated-gelu",
        tie_word_embeddings=False, dropout_rate=0.0)
    torch.manual_seed(0)
    hf = T5ForConditionalGeneration(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    assert len(params["enc_layers"]) == 3 and len(params["layers"]) == 2
    sd = params_to_hf(params, cfg)
    ref_sd = {k: np.asarray(v) for k, v in hf.state_dict().items()}
    assert len(sd) > 40
    for k, v in sd.items():
        np.testing.assert_allclose(v, ref_sd[k], atol=1e-6, err_msg=k)
