"""Encoder (BERT) family: bidirectional attention + MLM batches, and the
BASELINE milestone-2 configuration (pure TP=8) on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import (
    causal_lm_loss,
    forward_causal_lm,
    init_causal_lm,
)
from hetu_galvatron_tpu.runtime.dataloader import make_mlm_batch

pytestmark = [pytest.mark.model, pytest.mark.parallel]

BERT = ModelArgs(
    model_type="bert", hidden_size=32, num_hidden_layers=2,
    num_attention_heads=2, vocab_size=64, max_position_embeddings=16,
    seq_length=8, make_vocab_size_divisible_by=1, tie_word_embeddings=True)


def test_bidirectional_attention():
    """In an encoder, changing a late token changes early positions too."""
    params, _ = init_causal_lm(jax.random.key(0), BERT)
    t1 = jax.random.randint(jax.random.key(1), (1, 8), 0, 64)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 64)
    l1 = forward_causal_lm(params, t1, BERT, compute_dtype=jnp.float32)
    l2 = forward_causal_lm(params, t2, BERT, compute_dtype=jnp.float32)
    assert not np.allclose(np.asarray(l1[:, 0]), np.asarray(l2[:, 0]))


def test_mlm_batch_semantics():
    rng = np.random.RandomState(0)
    samples = rng.randint(0, 63, (64, 32))
    b = make_mlm_batch(samples, 64, np.random.RandomState(1))
    sel = b["loss_mask"].astype(bool)
    frac = sel.mean()
    assert 0.10 < frac < 0.20
    # labels always the original tokens
    np.testing.assert_array_equal(b["labels"], samples)
    # unselected positions unchanged
    np.testing.assert_array_equal(b["tokens"][~sel], samples[~sel])
    # most selected positions became the mask token (id 63)
    masked = (b["tokens"][sel] == 63).mean()
    assert masked > 0.6


def test_bert_mlm_training_step_tp8(cpu_devices):
    """Milestone 2 shape: pure TP=8 MLM step matches single device."""
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step, shard_params)
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    train = TrainArgs(lr=1e-3, lr_decay_style="constant", lr_warmup_iters=0)
    params, axes = init_causal_lm(jax.random.key(0), BERT)
    rng = np.random.RandomState(0)
    batch = jax.tree.map(jnp.asarray, make_mlm_batch(
        rng.randint(0, 63, (8, 8)), 64, np.random.RandomState(1)))
    loss_fn = lambda p: causal_lm_loss(p, batch, BERT,
                                       compute_dtype=jnp.float32)
    ref_loss = float(loss_fn(params))

    args = CoreArgs(model=BERT.model_dump(), train=train.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.vocab_tp = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices)
    tx = make_optimizer(train)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        BERT, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False)
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    _, _, metrics = step(sp, opt, jax.device_put(batch, batch_shd))
    assert abs(float(metrics["loss"]) - ref_loss) < 2e-5
