"""Model-core correctness: shapes/grads + logit parity vs HuggingFace torch
baselines (the reference's tier-2 strategy, tests/models/test_model_correctness.py:
loss trajectories vs GPT2LMHeadModel / LlamaForCausalLM — here we compare
logits directly, which is stronger)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models.builder import (
    build_causal_lm_arch,
    causal_lm_loss,
    forward_causal_lm,
    init_causal_lm,
    param_count,
)

pytestmark = pytest.mark.model

TINY_GPT = ModelArgs(
    model_type="gpt", hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, vocab_size=128, max_position_embeddings=32,
    seq_length=16, hidden_act="gelu", normalization="layernorm",
    position_embedding_type="learned", make_vocab_size_divisible_by=1,
)

TINY_LLAMA = ModelArgs(
    model_type="llama", hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, ffn_hidden_size=176,
    vocab_size=128, max_position_embeddings=32, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False, make_vocab_size_divisible_by=1,
)


def test_arch_list():
    arch = build_causal_lm_arch(TINY_GPT)
    assert arch[0] == "embed" and arch[-2:] == ["prenorm", "head"]
    assert arch.count("decoder") == 2


def test_forward_shapes_and_loss():
    params, axes = init_causal_lm(jax.random.key(0), TINY_GPT)
    assert jax.tree.structure(params) == jax.tree.structure(
        axes, is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(s, str) for s in x))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = forward_causal_lm(params, tokens, TINY_GPT)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    batch = {"tokens": tokens, "labels": tokens}
    loss = causal_lm_loss(params, batch, TINY_GPT)
    assert np.isfinite(float(loss))
    grads = jax.grad(lambda p: causal_lm_loss(p, batch, TINY_GPT))(params)
    leaves = jax.tree.leaves(grads)
    assert all(np.all(np.isfinite(g)) for g in leaves)
    # loss at init is ~ log(V)
    assert abs(float(loss) - np.log(128)) < 1.0


def test_remat_same_loss():
    params, _ = init_causal_lm(jax.random.key(0), TINY_LLAMA)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    batch = {"tokens": tokens, "labels": tokens}
    l0 = causal_lm_loss(params, batch, TINY_LLAMA, compute_dtype=jnp.float32)
    l1 = causal_lm_loss(params, batch, TINY_LLAMA, compute_dtype=jnp.float32,
                        remat_flags=[True, True])
    assert abs(float(l0) - float(l1)) < 1e-6
    g0 = jax.grad(lambda p: causal_lm_loss(p, batch, TINY_LLAMA,
                                           compute_dtype=jnp.float32))(params)
    g1 = jax.grad(lambda p: causal_lm_loss(p, batch, TINY_LLAMA,
                                           compute_dtype=jnp.float32,
                                           remat_flags=[True, True]))(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_causal_masking():
    """Changing a future token must not change past logits."""
    params, _ = init_causal_lm(jax.random.key(0), TINY_LLAMA)
    t1 = jax.random.randint(jax.random.key(1), (1, 16), 0, 128)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 128)
    l1 = forward_causal_lm(params, t1, TINY_LLAMA, compute_dtype=jnp.float32)
    l2 = forward_causal_lm(params, t2, TINY_LLAMA, compute_dtype=jnp.float32)
    np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], atol=1e-6)
    assert not np.allclose(l1[:, -1], l2[:, -1])


# ---------------------------------------------------------------------------
# HF parity
# ---------------------------------------------------------------------------


def _t2j(t):
    return jnp.asarray(t.detach().numpy())


def test_gpt2_logit_parity_vs_hf():
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=128, n_positions=32, n_embd=64, n_layer=2, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()

    params, _ = init_causal_lm(jax.random.key(0), TINY_GPT)
    sd = hf.state_dict()
    layers = []
    for i in range(2):
        pre = f"transformer.h.{i}."
        layers.append({
            "ln1": {"scale": _t2j(sd[pre + "ln_1.weight"]),
                    "bias": _t2j(sd[pre + "ln_1.bias"])},
            "attn": {"wqkv": _t2j(sd[pre + "attn.c_attn.weight"]),
                     "bqkv": _t2j(sd[pre + "attn.c_attn.bias"]),
                     "wo": _t2j(sd[pre + "attn.c_proj.weight"]),
                     "bo": _t2j(sd[pre + "attn.c_proj.bias"])},
            "ln2": {"scale": _t2j(sd[pre + "ln_2.weight"]),
                    "bias": _t2j(sd[pre + "ln_2.bias"])},
            "mlp": {"win": _t2j(sd[pre + "mlp.c_fc.weight"]),
                    "bin": _t2j(sd[pre + "mlp.c_fc.bias"]),
                    "wout": _t2j(sd[pre + "mlp.c_proj.weight"]),
                    "bout": _t2j(sd[pre + "mlp.c_proj.bias"])},
        })
    params = {
        "embed": {"wte": _t2j(sd["transformer.wte.weight"]),
                  "wpe": _t2j(sd["transformer.wpe.weight"])},
        "layers": tuple(layers),
        "prenorm": {"scale": _t2j(sd["transformer.ln_f.weight"]),
                    "bias": _t2j(sd["transformer.ln_f.bias"])},
        "head": {},
    }
    tokens_np = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), TINY_GPT,
                             compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_llama_logit_parity_vs_hf():
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg).eval()
    sd = hf.state_dict()

    def lin(name):  # torch Linear stores [out, in]
        return _t2j(sd[name]).T

    layers = []
    for i in range(2):
        pre = f"model.layers.{i}."
        wqkv = jnp.concatenate(
            [lin(pre + "self_attn.q_proj.weight"),
             lin(pre + "self_attn.k_proj.weight"),
             lin(pre + "self_attn.v_proj.weight")], axis=1)
        win = jnp.concatenate(
            [lin(pre + "mlp.gate_proj.weight"),
             lin(pre + "mlp.up_proj.weight")], axis=1)
        layers.append({
            "ln1": {"scale": _t2j(sd[pre + "input_layernorm.weight"])},
            "attn": {"wqkv": wqkv, "wo": lin(pre + "self_attn.o_proj.weight")},
            "ln2": {"scale": _t2j(sd[pre + "post_attention_layernorm.weight"])},
            "mlp": {"win": win, "wout": lin(pre + "mlp.down_proj.weight")},
        })
    params = {
        "embed": {"wte": _t2j(sd["model.embed_tokens.weight"])},
        "layers": tuple(layers),
        "prenorm": {"scale": _t2j(sd["model.norm.weight"])},
        "head": {"whead": lin("lm_head.weight")},
    }
    tokens_np = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), TINY_LLAMA,
                             compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_param_count_gpt2_small():
    cfg = ModelArgs(model_name="gpt2-small")  # defaults are gpt2-small
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    n = param_count(params)
    # 124M-class (padded vocab 50304)
    assert 1.2e8 < n < 1.3e8


def test_llama3_rope_scaling_parity_vs_hf():
    """llama-3.1-style rope_scaling (llama3 recipe) + linear scaling: the
    scaled inv-freq table matches transformers' ROPE_INIT_FUNCTIONS and the
    full model matches HF logits (BASELINE milestone 5 model family)."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from hetu_galvatron_tpu.models.modules import _scale_inv_freq
    from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params

    sc = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
          "high_freq_factor": 4.0, "original_max_position_embeddings": 8192}
    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=16384, rms_norm_eps=1e-5,
        rope_theta=500000.0, rope_scaling=dict(sc),
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
    )
    inv_ref, _ = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, "cpu")
    base = 1.0 / (500000.0 ** (np.arange(0, 16, 2, dtype=np.float64) / 16.0))
    ours = _scale_inv_freq(jnp.asarray(base, jnp.float32), sc)
    np.testing.assert_allclose(np.asarray(ours), inv_ref.numpy(), rtol=1e-6)
    lin = _scale_inv_freq(jnp.asarray(base, jnp.float32),
                          {"rope_type": "linear", "factor": 4.0})
    np.testing.assert_allclose(np.asarray(lin), base / 4.0, rtol=1e-6)

    cfg = TINY_LLAMA.model_copy(update={
        "rope_theta": 500000.0, "rope_scaling": sc,
        "max_position_embeddings": 16384})
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    tokens_np = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours_logits = forward_causal_lm(params, jnp.asarray(tokens_np), cfg,
                                    compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours_logits), ref,
                               rtol=2e-4, atol=2e-4)


def test_gemma_logit_parity_vs_hf():
    """Gemma family numerics: zero-centered RMSNorm (x * (1+w)), sqrt(H)
    embedding scaling, gated-gelu MLP, decoupled head_dim — logits must
    match HF GemmaForCausalLM through the config adapter + converter."""
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig, GemmaForCausalLM

    from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        populate_model_args_from_hf,
    )

    hf_cfg = GemmaConfig(
        vocab_size=128, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=32, rms_norm_eps=1e-6,
        rope_theta=10000.0, attention_dropout=0.0,
        hidden_act="gelu_pytorch_tanh", hidden_activation="gelu_pytorch_tanh",
    )
    cfg = populate_model_args_from_hf(hf_cfg)
    cfg = cfg.model_copy(update={"seq_length": 16,
                                 "make_vocab_size_divisible_by": 1})
    assert cfg.norm_zero_centered and cfg.scale_embeddings
    assert cfg.head_dim == 16 and cfg.hidden_act == "geglu"
    assert cfg.tie_word_embeddings

    torch.manual_seed(0)
    hf = GemmaForCausalLM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    tokens_np = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), cfg,
                             compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=5e-4, atol=5e-4)


def test_remat_policy_parity():
    """remat policies change memory/recompute, never numerics: loss and
    grads identical across full / dots / dots_no_batch and no-remat."""
    from hetu_galvatron_tpu.models.builder import causal_lm_loss

    base = TINY_LLAMA
    params, _ = init_causal_lm(jax.random.key(0), base)
    tokens = np.random.RandomState(0).randint(0, 128, (2, 17))
    batch = {"tokens": jnp.asarray(tokens[:, :-1]),
             "labels": jnp.asarray(tokens[:, 1:])}
    flags = [True] * base.num_hidden_layers

    def loss_grads(cfg, remat_flags):
        l, g = jax.value_and_grad(lambda p: causal_lm_loss(
            p, batch, cfg, compute_dtype=jnp.float32,
            remat_flags=remat_flags))(params)
        return float(l), g

    l_ref, g_ref = loss_grads(base, None)
    for policy in ("full", "dots", "dots_no_batch"):
        cfg = base.model_copy(update={"remat_policy": policy})
        l, g = loss_grads(cfg, flags)
        assert l == pytest.approx(l_ref, rel=1e-6), policy
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6, err_msg=policy)


@pytest.mark.parametrize("family", ["qwen2", "mistral"])
def test_qwen2_mistral_logit_parity_vs_hf(family):
    """Qwen2 (qkv bias, no mlp bias) and Mistral (bias-free GQA) through the
    config adapter + converter match HF logits."""
    torch = pytest.importorskip("torch")

    from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        populate_model_args_from_hf,
    )

    if family == "qwen2":
        from transformers import Qwen2Config as Cfg, Qwen2ForCausalLM as LM
    else:
        from transformers import MistralConfig as Cfg, MistralForCausalLM as LM

    hf_cfg = Cfg(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    cfg = populate_model_args_from_hf(hf_cfg)
    cfg = cfg.model_copy(update={"seq_length": 16,
                                 "make_vocab_size_divisible_by": 1})
    assert cfg.add_qkv_bias == (family == "qwen2")
    assert not cfg.add_bias_linear

    torch.manual_seed(0)
    hf = LM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    tokens_np = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), cfg,
                             compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


# ---------------------------------------------------------------------------
# multimodal rope (qwen2-vl mrope; reference rotary_pos_embedding.py)
# ---------------------------------------------------------------------------


def test_mrope_identical_rows_equal_standard_rope():
    """With temporal == height == width positions (text-only), mrope IS
    standard rope — exact equality of the tables and of forward logits."""
    from hetu_galvatron_tpu.models import modules as M

    S, D = 16, 16
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (3, 2, S))
    cos_m, sin_m = M.mrope_cos_sin(pos, D, 10000.0, sections=(2, 3, 3))
    cos, sin = M.rope_cos_sin(S, D, 10000.0)
    np.testing.assert_allclose(np.asarray(cos_m[0]), np.asarray(cos),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(sin_m[1]), np.asarray(sin),
                               atol=1e-6)

    cfg = ModelArgs(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=32, seq_length=S,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1)
    mcfg = cfg.model_copy(update={"mrope_section": [2, 3, 3]})
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, S)))
    base = forward_causal_lm(params, toks, cfg, compute_dtype=jnp.float32)
    out = forward_causal_lm(params, toks, mcfg, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=1e-5, atol=1e-5)


def test_mrope_sections_draw_from_their_axis():
    """Frequency section j rotates by position row j: changing only the
    height row changes only its section's columns."""
    from hetu_galvatron_tpu.models import modules as M

    S, D = 8, 16
    base = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (3, 1, S))
    shifted = base.at[1].add(5)  # move the height positions only
    c0, s0 = M.mrope_cos_sin(base, D, 10000.0, sections=(2, 3, 3))
    c1, s1 = M.mrope_cos_sin(shifted, D, 10000.0, sections=(2, 3, 3))
    diff = np.abs(np.asarray(c1 - c0)).max(axis=(0, 1))  # per freq dim
    assert np.all(diff[2:5] > 1e-3), diff  # height section moved
    assert np.allclose(diff[:2], 0) and np.allclose(diff[5:], 0), diff


def test_mrope_batch_position_ids_and_validation():
    from hetu_galvatron_tpu.models import modules as M

    with pytest.raises(ValueError, match="sum"):
        M.mrope_cos_sin(jnp.zeros((3, 1, 4), jnp.int32), 16, 1e4,
                        sections=(2, 2, 2))
    with pytest.raises(ValueError, match="3, B, S"):
        M.mrope_cos_sin(jnp.zeros((1, 4), jnp.int32), 16, 1e4,
                        sections=(2, 3, 3))
    # explicit [3,B,S] ids through the forward (multimodal-shaped batch)
    cfg = ModelArgs(
        hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=32, seq_length=8,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, mrope_section=[2, 3, 3])
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, 64, (1, 8)))
    # non-uniform per-axis positions: rope attention is shift-invariant,
    # so constant offsets would leave logits unchanged — stretch the
    # height/width grids instead (vision-patch geometry)
    mpos = jnp.stack([jnp.arange(8), jnp.arange(8) * 2,
                      jnp.arange(8) * 3]).astype(jnp.int32)[:, None, :]
    out = forward_causal_lm(params, toks, cfg, compute_dtype=jnp.float32,
                            mrope_position_ids=mpos)
    plain = forward_causal_lm(params, toks, cfg, compute_dtype=jnp.float32)
    assert np.all(np.isfinite(np.asarray(out)))
    assert np.abs(np.asarray(out - plain)).max() > 1e-5


def test_hf_adapter_detects_mrope():
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        populate_model_args_from_hf,
    )

    cfg = populate_model_args_from_hf({
        "model_type": "qwen2", "hidden_size": 64, "num_hidden_layers": 2,
        "num_attention_heads": 4, "vocab_size": 128,
        "max_position_embeddings": 64,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
    })
    assert cfg.mrope_section == [2, 3, 3]
    assert cfg.rope_scaling is None  # "mrope" is not a frequency scaling
