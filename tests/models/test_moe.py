"""MoE correctness: routing math, aux losses, dense/MoE alternation, and
expert-parallel parity on the 8-CPU mesh (reference test_ep.py /
test_moe_correctness.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss, init_causal_lm
from hetu_galvatron_tpu.models.moe import (
    apply_moe_mlp,
    is_moe_layer,
    moe_capacity,
)
from hetu_galvatron_tpu.runtime.dataloader import make_batch

pytestmark = [pytest.mark.model, pytest.mark.parallel]

MOE_CFG = ModelArgs(
    model_type="moe", hidden_size=32, num_hidden_layers=2,
    num_attention_heads=2, vocab_size=64, max_position_embeddings=32,
    seq_length=16, hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=48,
    num_experts=4, moe_topk=2, moe_aux_loss_coeff=1e-2,
    moe_z_loss_coeff=1e-3)


def test_is_moe_layer_alternation():
    cfg = MOE_CFG.model_copy(update={"moe_layer_freq": 2,
                                     "num_hidden_layers": 4})
    assert [is_moe_layer(cfg, i) for i in range(4)] == [
        False, True, False, True]
    dense = ModelArgs(num_experts=0)
    assert not is_moe_layer(dense, 0)


def test_moe_mlp_routing_and_aux():
    from hetu_galvatron_tpu.models.moe import init_moe_mlp

    p, axes = init_moe_mlp(jax.random.key(0), MOE_CFG)
    assert axes["win"] == ("expert", "embed", "mlp")
    x = jax.random.normal(jax.random.key(1), (2, 16, 32))
    y, aux = apply_moe_mlp(p, x, MOE_CFG, compute_dtype=jnp.float32)
    assert y.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0
    # perfectly balanced router would give aux = coeff * E * E * (1/E)^2
    assert float(aux) < 1.0


def test_moe_capacity():
    assert moe_capacity(MOE_CFG, tokens=32) == int(
        np.ceil(32 * 2 / 4 * 1.25))


def test_moe_model_trains():
    params, axes = init_causal_lm(jax.random.key(0), MOE_CFG)
    assert "moe" in params["layers"][0]  # freq=1: every layer MoE
    batch = jax.tree.map(jnp.asarray, make_batch(
        np.random.RandomState(0).randint(0, 64, (4, 17))))
    loss_fn = lambda p: causal_lm_loss(p, batch, MOE_CFG,
                                       compute_dtype=jnp.float32)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # router + expert weights all get gradients
    g = grads["layers"][0]["moe"]
    assert float(jnp.abs(g["router"]).sum()) > 0
    assert float(jnp.abs(g["win"]).sum()) > 0


def test_expert_parallel_matches_single_device(cpu_devices):
    """ep=2 x dp=4 sharded step == single-device step (the dispatch math is
    identical; ep only distributes experts)."""
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step, shard_params)
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    import optax

    train = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.0,
                      lr_decay_style="constant", lr_warmup_iters=0)
    params, axes = init_causal_lm(jax.random.key(0), MOE_CFG)
    batch = jax.tree.map(jnp.asarray, make_batch(
        np.random.RandomState(0).randint(0, 64, (8, 17))))

    tx = make_optimizer(train)
    loss_fn = lambda p: causal_lm_loss(p, batch, MOE_CFG,
                                       compute_dtype=jnp.float32)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(ref_grads, tx.init(params), params)
    ref_params = optax.apply_updates(params, upd)

    args = CoreArgs(model=MOE_CFG.model_dump(), train=train.model_dump())
    args.parallel.global_ep_deg = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    assert hpc.layers[0].ep_size == 2
    mesh = build_mesh(8, 1, devices=cpu_devices)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        MOE_CFG, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.float32, donate=False)
    # expert weights sharded over the ep axis
    assert pspecs["layers"][0]["moe"]["win"][0] in ("d0", ("d0",))
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    b = jax.device_put(batch, batch_shd)
    new_p, _, metrics = step(sp, opt, b)
    assert abs(float(metrics["loss"]) - float(ref_loss)) < 2e-5
    for (pa, a), (_, b2) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b2), rtol=5e-4, atol=3e-4,
            err_msg=jax.tree_util.keystr(pa))


def test_moe_pipeline_matches_single_device(cpu_devices):
    """pp=2 x ep=2 MoE pipeline == single device (aux losses flow across
    stage boundaries with correct gradients)."""
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine
    import optax

    train = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.0,
                      lr_decay_style="constant", lr_warmup_iters=0)
    cfg = MOE_CFG.model_copy(update={"num_hidden_layers": 4})
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    raw = make_batch(np.random.RandomState(0).randint(0, 64, (8, 17)))
    batch = jax.tree.map(jnp.asarray, raw)

    # MoE aux losses and capacity are computed per microbatch, so the
    # single-device reference must microbatch identically (chunks=2)
    from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

    tx = make_optimizer(train)
    ref_step = jax.jit(make_train_step(
        make_loss_fn(cfg, compute_dtype=jnp.float32), tx, chunks=2))
    ref_params, _, ref_metrics = ref_step(params, tx.init(params), batch)
    ref_loss = ref_metrics["loss"]

    args = CoreArgs(model=cfg.model_dump(), train=train.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.chunks = 2
    args.parallel.global_ep_deg = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(cfg, hpc, train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, raw)
    assert abs(metrics["loss"] - float(ref_loss)) < 2e-5
    merged = eng.merge_params(new_sp)
    for (pa, a), (_, b2) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(merged)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b2), rtol=5e-4, atol=3e-4,
            err_msg=jax.tree_util.keystr(pa))
