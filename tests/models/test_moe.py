"""MoE correctness: routing math, aux losses, dense/MoE alternation, and
expert-parallel parity on the 8-CPU mesh (reference test_ep.py /
test_moe_correctness.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss, init_causal_lm
from hetu_galvatron_tpu.models.moe import (
    apply_moe_mlp,
    is_moe_layer,
    moe_capacity,
)
from hetu_galvatron_tpu.runtime.dataloader import make_batch

pytestmark = [pytest.mark.model, pytest.mark.parallel]

MOE_CFG = ModelArgs(
    model_type="moe", hidden_size=32, num_hidden_layers=2,
    num_attention_heads=2, vocab_size=64, max_position_embeddings=32,
    seq_length=16, hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=48,
    num_experts=4, moe_topk=2, moe_aux_loss_coeff=1e-2,
    moe_z_loss_coeff=1e-3)


def test_is_moe_layer_alternation():
    cfg = MOE_CFG.model_copy(update={"moe_layer_freq": 2,
                                     "num_hidden_layers": 4})
    assert [is_moe_layer(cfg, i) for i in range(4)] == [
        False, True, False, True]
    dense = ModelArgs(num_experts=0)
    assert not is_moe_layer(dense, 0)


def test_moe_mlp_routing_and_aux():
    from hetu_galvatron_tpu.models.moe import init_moe_mlp

    p, axes = init_moe_mlp(jax.random.key(0), MOE_CFG)
    assert axes["win"] == ("expert", "embed", "mlp")
    x = jax.random.normal(jax.random.key(1), (2, 16, 32))
    y, aux, stats = apply_moe_mlp(p, x, MOE_CFG, compute_dtype=jnp.float32)
    assert y.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0
    # perfectly balanced router would give aux = coeff * E * E * (1/E)^2
    assert float(aux) < 1.0


def test_moe_capacity():
    assert moe_capacity(MOE_CFG, tokens=32) == int(
        np.ceil(32 * 2 / 4 * 1.25))


@pytest.mark.slow
def test_moe_model_trains():
    params, axes = init_causal_lm(jax.random.key(0), MOE_CFG)
    assert "moe" in params["layers"][0]  # freq=1: every layer MoE
    batch = jax.tree.map(jnp.asarray, make_batch(
        np.random.RandomState(0).randint(0, 64, (4, 17))))
    loss_fn = lambda p: causal_lm_loss(p, batch, MOE_CFG,
                                       compute_dtype=jnp.float32)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # router + expert weights all get gradients
    g = grads["layers"][0]["moe"]
    assert float(jnp.abs(g["router"]).sum()) > 0
    assert float(jnp.abs(g["win"]).sum()) > 0


@pytest.mark.slow
def test_expert_parallel_matches_single_device(cpu_devices):
    """ep=2 x dp=4 sharded step == single-device step (the dispatch math is
    identical; ep only distributes experts)."""
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step, shard_params)
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    import optax

    train = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.0,
                      lr_decay_style="constant", lr_warmup_iters=0)
    params, axes = init_causal_lm(jax.random.key(0), MOE_CFG)
    batch = jax.tree.map(jnp.asarray, make_batch(
        np.random.RandomState(0).randint(0, 64, (8, 17))))

    tx = make_optimizer(train)
    loss_fn = lambda p: causal_lm_loss(p, batch, MOE_CFG,
                                       compute_dtype=jnp.float32)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(ref_grads, tx.init(params), params)
    ref_params = optax.apply_updates(params, upd)

    args = CoreArgs(model=MOE_CFG.model_dump(), train=train.model_dump())
    args.parallel.global_ep_deg = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    assert hpc.layers[0].ep_size == 2
    mesh = build_mesh(8, 1, devices=cpu_devices)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        MOE_CFG, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.float32, donate=False)
    # expert weights sharded over the ep axis
    assert pspecs["layers"][0]["moe"]["win"][0] in ("d0", ("d0",))
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    b = jax.device_put(batch, batch_shd)
    new_p, _, metrics = step(sp, opt, b)
    assert abs(float(metrics["loss"]) - float(ref_loss)) < 2e-5
    for (pa, a), (_, b2) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b2), rtol=5e-4, atol=3e-4,
            err_msg=jax.tree_util.keystr(pa))


@pytest.mark.slow
def test_moe_pipeline_matches_single_device(cpu_devices):
    """pp=2 x ep=2 MoE pipeline == single device (aux losses flow across
    stage boundaries with correct gradients)."""
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine
    import optax

    train = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.0,
                      lr_decay_style="constant", lr_warmup_iters=0)
    cfg = MOE_CFG.model_copy(update={"num_hidden_layers": 4})
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    raw = make_batch(np.random.RandomState(0).randint(0, 64, (8, 17)))
    batch = jax.tree.map(jnp.asarray, raw)

    # MoE aux losses and capacity are computed per microbatch, so the
    # single-device reference must microbatch identically (chunks=2)
    from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

    tx = make_optimizer(train)
    ref_step = jax.jit(make_train_step(
        make_loss_fn(cfg, compute_dtype=jnp.float32), tx, chunks=2))
    ref_params, _, ref_metrics = ref_step(params, tx.init(params), batch)
    ref_loss = ref_metrics["loss"]

    args = CoreArgs(model=cfg.model_dump(), train=train.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.chunks = 2
    args.parallel.global_ep_deg = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    eng = PipelineEngine(cfg, hpc, train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, raw)
    assert abs(metrics["loss"] - float(ref_loss)) < 2e-5
    merged = eng.merge_params(new_sp)
    for (pa, a), (_, b2) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(merged)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b2), rtol=5e-4, atol=3e-4,
            err_msg=jax.tree_util.keystr(pa))


# ---------------------------------------------------------------------------
# dispatchers + router variants (round 3)
# ---------------------------------------------------------------------------


def _moe_params(cfg, seed=0):
    from hetu_galvatron_tpu.models.moe import init_moe_mlp

    return init_moe_mlp(jax.random.key(seed), cfg)[0]


def test_dropless_matches_uncapped_capacity():
    """With capacity high enough that nothing drops, the GShard einsum path
    and the ragged-dot dropless path are the same function."""
    cfg = MOE_CFG
    p = _moe_params(cfg)
    x = jax.random.normal(jax.random.key(1), (2, 16, 32))
    y_cap, aux_cap, _ = apply_moe_mlp(p, x, cfg, compute_dtype=jnp.float32,
                                   capacity_factor=100.0)
    y_dl, aux_dl, _ = apply_moe_mlp(
        p, x, cfg.model_copy(update={"moe_dispatcher": "dropless"}),
        compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(y_dl), np.asarray(y_cap),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_dl), float(aux_cap), rtol=1e-6)


def test_capacity_overflow_drops_and_renormalizes():
    """Force overflow (tiny capacity): output stays finite, differs from the
    dropless result, and each surviving token keeps a unit combine weight
    (outputs bounded by the expert-output scale)."""
    cfg = MOE_CFG.model_copy(update={"moe_capacity_factor": 0.25})
    p = _moe_params(cfg)
    x = jax.random.normal(jax.random.key(2), (2, 16, 32))
    y_cap, _, _ = apply_moe_mlp(p, x, cfg, compute_dtype=jnp.float32)
    y_dl, _, _ = apply_moe_mlp(
        p, x, cfg.model_copy(update={"moe_dispatcher": "dropless"}),
        compute_dtype=jnp.float32)
    assert np.all(np.isfinite(np.asarray(y_cap)))
    assert not np.allclose(np.asarray(y_cap), np.asarray(y_dl))
    # dropped-token outputs are zero or renormalized, never amplified
    assert np.abs(np.asarray(y_cap)).max() <= \
        np.abs(np.asarray(y_dl)).max() * 4 + 1.0


def test_dropless_grads_flow():
    cfg = MOE_CFG.model_copy(update={"moe_dispatcher": "dropless"})
    p = _moe_params(cfg)
    x = jax.random.normal(jax.random.key(3), (2, 8, 32))

    def loss(p_):
        y, aux, _ = apply_moe_mlp(p_, x, cfg, compute_dtype=jnp.float32)
        return jnp.sum(jnp.square(y)) + aux

    g = jax.grad(loss)(p)
    for path, leaf in jax.tree_util.tree_leaves_with_path(g):
        assert np.all(np.isfinite(leaf)), path
    # router gets gradient through the combine weights
    assert np.abs(np.asarray(g["router"])).sum() > 0


def test_sinkhorn_router():
    from hetu_galvatron_tpu.models.moe import route_tokens, sinkhorn

    cfg = MOE_CFG.model_copy(update={"moe_router_type": "sinkhorn",
                                     "moe_aux_loss_coeff": 0.0,
                                     "moe_z_loss_coeff": 0.0})
    p = _moe_params(cfg)
    xt = jax.random.normal(jax.random.key(4), (64, 32))
    idx, w, aux, _ = route_tokens(p, xt, cfg, compute_dtype=jnp.float32)
    assert idx.shape == (64, 2) and w.shape == (64, 2)
    assert float(aux) == 0.0
    # sinkhorn normalization balances the assignment matrix
    norm = np.asarray(sinkhorn(jax.random.normal(jax.random.key(5),
                                                 (64, 4))))
    np.testing.assert_allclose(norm.sum(axis=1), 1.0 / 64, rtol=1e-3)
    np.testing.assert_allclose(norm.sum(axis=0), 1.0 / 4, rtol=1e-3)
    # aux loss is rejected (reference router.py:158)
    bad = cfg.model_copy(update={"moe_aux_loss_coeff": 1e-2})
    with pytest.raises(ValueError):
        route_tokens(p, xt, bad, compute_dtype=jnp.float32)
    # end-to-end through the layer
    y, _, _ = apply_moe_mlp(p, xt[None], cfg, compute_dtype=jnp.float32)
    assert np.all(np.isfinite(np.asarray(y)))


def test_expert_bias_steers_selection():
    from hetu_galvatron_tpu.models.moe import route_tokens, update_expert_bias

    cfg = MOE_CFG.model_copy(update={"moe_router_enable_expert_bias": True,
                                     "moe_topk": 1})
    p = _moe_params(cfg)
    assert "expert_bias" in p
    xt = jax.random.normal(jax.random.key(6), (128, 32))
    idx0, w0, _, _ = route_tokens(p, xt, cfg, compute_dtype=jnp.float32)
    # bias expert 3 way up: every token must now select it...
    p2 = dict(p, expert_bias=jnp.array([-10., -10., -10., 10.]))
    idx1, w1, _, _ = route_tokens(p2, xt, cfg, compute_dtype=jnp.float32)
    assert np.all(np.asarray(idx1) == 3)
    # ...but combine weights still come from the unbiased probs
    sel_same = np.asarray(idx0) == 3
    np.testing.assert_allclose(np.asarray(w1)[sel_same],
                               np.asarray(w0)[sel_same])
    # the maintenance step pushes the overloaded expert's bias down
    counts = jnp.array([0., 0., 0., 128.])
    b = update_expert_bias(p2["expert_bias"], counts, update_rate=0.1)
    assert float(b[3]) < float(p2["expert_bias"][3])
    assert float(b[0]) > float(p2["expert_bias"][0])


@pytest.mark.slow
def test_mixtral_hf_logit_parity():
    """Converted HF Mixtral checkpoint + dropless dispatch must reproduce HF
    logits (the round-2 verdict's missing Mixtral parity evidence)."""
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    from hetu_galvatron_tpu.models.builder import forward_causal_lm
    from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params

    cfg = ModelArgs(
        model_type="moe", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, num_key_value_heads=2, ffn_hidden_size=48,
        moe_ffn_hidden_size=48, vocab_size=64, max_position_embeddings=32,
        seq_length=16, hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1,
        num_experts=4, moe_topk=2, moe_aux_loss_coeff=0.0,
        moe_dispatcher="dropless")
    hf_cfg = MixtralConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=32, tie_word_embeddings=False,
        attention_dropout=0.0, router_aux_loss_coef=0.0)
    torch.manual_seed(0)
    hf = MixtralForCausalLM(hf_cfg).eval()
    params = hf_to_params(hf.state_dict(), cfg)
    tokens_np = np.random.RandomState(0).randint(0, 64, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens_np)).logits.numpy()
    ours = forward_causal_lm(params, jnp.asarray(tokens_np), cfg,
                             compute_dtype=jnp.float32)
    # tolerance: a token sitting exactly on the top-k boundary can route
    # differently between torch and XLA fp32 softmax; everything else is
    # bit-close
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=1e-3)


def test_expert_bias_updates_during_training():
    """The expert-bias flag must be live end to end: the router emits the
    maintenance signal through the gradient and the optimizer's SGD(1)
    partition applies it — bias moves after a step, model weights still
    train under Adam (round-3 review finding: the flag was a silent no-op)."""
    import optax

    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    cfg = MOE_CFG.model_copy(update={"moe_router_enable_expert_bias": True,
                                     "moe_aux_loss_coeff": 0.0,
                                     "moe_z_loss_coeff": 0.0})
    params, _ = init_causal_lm(jax.random.key(7), cfg)
    tx = make_optimizer(TrainArgs(lr=1e-3, clip_grad=0.0,
                                  lr_decay_style="constant"))
    tok = np.random.RandomState(7).randint(0, 64, (4, 17))
    batch = make_batch(tok)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}

    loss_fn = lambda p: causal_lm_loss(p, batch, cfg,
                                       compute_dtype=jnp.float32)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(grads, tx.init(params), params)
    new_params = optax.apply_updates(params, upd)

    for i, lp in enumerate(params["layers"]):
        b0 = np.asarray(lp["moe"]["expert_bias"])
        b1 = np.asarray(new_params["layers"][i]["moe"]["expert_bias"])
        assert not np.allclose(b0, b1), f"layer {i} expert_bias did not move"
        # the SGD(1) partition applies the raw ±update_rate signal
        deltas = np.abs(b1 - b0)
        rate = cfg.moe_expert_bias_update_rate
        assert np.all(np.isclose(deltas, 0.0, atol=1e-9)
                      | np.isclose(deltas, rate, rtol=1e-4))
        # and the bias-maintenance term added zero to the loss value
    w0 = np.asarray(params["layers"][0]["attn"]["wqkv"])
    w1 = np.asarray(new_params["layers"][0]["attn"]["wqkv"])
    assert not np.allclose(w0, w1), "model weights must still train"
    assert np.isfinite(float(loss))


def test_per_layer_aux_tracker_in_train_metrics(cpu_devices):
    """Per-layer aux/z-loss + tokens-per-expert ride the train-step metrics
    (reference aux-losses tracker, moe_utils.py:547-644), spmd path with
    microbatching."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.dataloader import make_batch
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    from jax.sharding import NamedSharding, PartitionSpec

    args = CoreArgs.model_validate({
        "model": {
            "model_type": "moe", "hidden_size": 32, "num_hidden_layers": 4,
            "num_attention_heads": 2, "vocab_size": 64, "seq_length": 8,
            "max_position_embeddings": 16, "num_experts": 4,
            "moe_layer_freq": 2, "moe_aux_loss_coeff": 1e-2,
            "moe_z_loss_coeff": 1e-3, "hidden_act": "swiglu",
            "normalization": "rmsnorm", "position_embedding_type": "rope",
            "tie_word_embeddings": False, "add_bias_linear": False,
            "add_qkv_bias": False, "make_vocab_size_divisible_by": 1,
            "ffn_hidden_size": 64,
        },
        "parallel": {"global_tp_deg": 2, "default_dp_type": "zero3",
                     "vocab_tp": 1, "global_train_batch_size": 8,
                     "chunks": 2, "global_ep_deg": 2},
    })
    mesh = build_mesh(8, 1, devices=cpu_devices)
    hpc = get_hybrid_parallel_config(args, 8)
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    tx = make_optimizer(args.train)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        args.model, hpc, mesh, axes, tx, params,
        compute_dtype=jnp.float32, donate=False)
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, PartitionSpec)))(sp)
    data = np.random.RandomState(0).randint(
        0, args.model.padded_vocab_size, (8, 9))
    batch = jax.device_put(jax.tree.map(jnp.asarray, make_batch(data)),
                           batch_shd)
    _, _, metrics = step(sp, opt, batch)
    moe = metrics["moe"]
    # layers 1 and 3 are MoE (freq 2); 0 and 2 dense
    assert set(moe) == {"layer1", "layer3"}, set(moe)
    total_tokens = 8 * 8 * args.model.moe_topk
    for st in moe.values():
        assert float(st["load_balance_loss"]) > 0
        assert float(st["z_loss"]) > 0
        tpe = np.asarray(st["tokens_per_expert"])
        assert tpe.shape == (4,)
        assert int(tpe.sum()) == total_tokens, (tpe, total_tokens)
    # the iteration log renders the tracker
    from hetu_galvatron_tpu.core.profiler.runtime_profiler import (
        RuntimeProfiler,
    )

    prof = RuntimeProfiler(args, world_size=8, rank=0)
    line = prof.iteration_log(0, metrics)
    assert "moe[layer1]" in line and "imb" in line
