"""KV-cache generation vs naive full-forward decoding (the cache path must
reproduce the exact greedy chain the training forward implies)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models.builder import forward_causal_lm, init_causal_lm
from hetu_galvatron_tpu.models.generate import generate

pytestmark = pytest.mark.model


def _cfg(**kw):
    base = dict(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64, seq_length=32,
        hidden_act="swiglu", normalization="rmsnorm",
        position_embedding_type="rope", tie_word_embeddings=False,
        add_bias_linear=False, add_qkv_bias=False,
        make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def _naive_greedy(params, tokens, cfg, n, dtype=jnp.float32):
    """Re-run the FULL training forward on the growing sequence each step."""
    for _ in range(n):
        logits = forward_causal_lm(params, tokens, cfg, compute_dtype=dtype)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(tokens.dtype)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


@pytest.mark.parametrize("kw", [
    dict(),  # llama-style: rope + rmsnorm + swiglu, MHA
    dict(num_attention_heads=4, num_key_value_heads=2),  # GQA
    dict(position_embedding_type="learned", normalization="layernorm",
         hidden_act="gelu", add_bias_linear=True, add_qkv_bias=True,
         tie_word_embeddings=True),  # gpt2-style
    dict(scale_embeddings=True, norm_zero_centered=True,
         num_key_value_heads=1, head_dim_override=24),  # gemma numerics
], ids=["llama", "gqa", "gpt2", "gemma"])
def test_cached_greedy_matches_naive(kw):
    cfg = _cfg(**kw)
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 8)), jnp.int32)
    want = _naive_greedy(params, prompt, cfg, 12)
    got = generate(params, prompt, cfg, 12, compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_jits_and_eos_masks():
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (2, 4)), jnp.int32)
    fn = jax.jit(lambda p, t: generate(p, t, cfg, 8, eos_id=5,
                                       compute_dtype=jnp.float32))
    out = np.asarray(fn(params, prompt))
    assert out.shape == (2, 12)
    # after the first eos, every later token must be eos
    for row in out:
        hits = np.where(row[4:] == 5)[0]
        if hits.size:
            assert (row[4 + hits[0]:] == 5).all()


def test_generate_sampling_shapes_and_topk():
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(2), cfg)
    prompt = jnp.zeros((3, 2), jnp.int32)
    out = generate(params, prompt, cfg, 5, temperature=0.8, top_k=10,
                   key=jax.random.key(3), compute_dtype=jnp.float32)
    assert out.shape == (3, 7)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < 128).all()


def test_generate_never_samples_vocab_padding():
    """padded_vocab_size > vocab_size: the padding columns hold untrained
    head weights and must be masked out of both argmax and sampling."""
    cfg = _cfg(vocab_size=100, make_vocab_size_divisible_by=128)
    assert cfg.padded_vocab_size == 128
    params, _ = init_causal_lm(jax.random.key(4), cfg)
    prompt = jnp.zeros((4, 2), jnp.int32)
    for temp in (0.0, 1.5):
        out = np.asarray(generate(params, prompt, cfg, 6, temperature=temp,
                                  key=jax.random.key(5),
                                  compute_dtype=jnp.float32))
        assert (out < 100).all(), out.max()


@pytest.mark.parametrize("max_len", [12, 40])
@pytest.mark.parametrize("kv_heads", [None, 2, 1], ids=["mha", "gqa2", "mqa"])
def test_prefill_decode_logit_parity_vs_full_forward(max_len, kv_heads):
    """The KV-cache decode chain reproduces the full-sequence forward's
    next-token logits at every position, for varying cache max_len and
    GQA head counts."""
    from hetu_galvatron_tpu.models.generate import decode_step, prefill

    cfg = _cfg(num_key_value_heads=kv_heads)
    params, _ = init_causal_lm(jax.random.key(7), cfg)
    rng = np.random.RandomState(7)
    S0, n_steps = 4, 6
    assert S0 + n_steps <= max_len
    seq = jnp.asarray(rng.randint(0, 128, (2, S0 + n_steps)), jnp.int32)

    cache, logits = prefill(params, seq[:, :S0], cfg, max_len,
                            compute_dtype=jnp.float32)
    rope = None
    if cfg.position_embedding_type == "rope":
        from hetu_galvatron_tpu.models import modules as M

        rope = M.rope_cos_sin(max_len, cfg.head_dim, cfg.rope_theta,
                              scaling=cfg.rope_scaling)
    for t in range(n_steps):
        full = forward_causal_lm(params, seq[:, :S0 + t], cfg,
                                 compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1]),
                                   rtol=2e-5, atol=2e-5)
        cache, logits = decode_step(params, cache, seq[:, S0 + t],
                                    jnp.int32(S0 + t), cfg, rope_full=rope,
                                    compute_dtype=jnp.float32)


@pytest.mark.parametrize("kw", [
    dict(),  # rope + rmsnorm
    dict(position_embedding_type="learned", normalization="layernorm",
         hidden_act="gelu", add_bias_linear=True, add_qkv_bias=True),
    dict(num_attention_heads=4, num_key_value_heads=2),  # GQA
], ids=["rope", "learned", "gqa"])
def test_generate_ragged_left_padded_batch(kw):
    """Batched ragged prompts (LEFT-padded, ``prompt_lens``): every row
    decodes exactly as it would alone — pad prefix masked from attention,
    positions starting at the first real token."""
    cfg = _cfg(**kw)
    params, _ = init_causal_lm(jax.random.key(8), cfg)
    rng = np.random.RandomState(8)
    lens = [2, 9, 5]
    S0 = max(lens)
    padded = np.zeros((len(lens), S0), np.int32)
    rows = []
    for i, n in enumerate(lens):
        rows.append(rng.randint(0, 128, (n,)))
        padded[i, S0 - n:] = rows[-1]
    out = np.asarray(generate(
        params, jnp.asarray(padded), cfg, 8,
        prompt_lens=jnp.asarray(lens, jnp.int32),
        compute_dtype=jnp.float32))
    for i, row in enumerate(rows):
        want = np.asarray(generate(params, jnp.asarray(row[None], jnp.int32),
                                   cfg, 8, compute_dtype=jnp.float32))
        np.testing.assert_array_equal(out[i, S0:], want[0, len(row):])


def test_generate_pad_id_masks_retired_rows():
    """After a row's EOS the output carries pad_id (not live samples, not
    eos repetition), so batched output is deterministic regardless of
    neighbors — the contract the serving engine's retirement trims
    against."""
    cfg = _cfg()
    params, _ = init_causal_lm(jax.random.key(1), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (1, 5)), jnp.int32)
    # find an eos that actually fires mid-stream on the free-running chain
    free = np.asarray(generate(params, prompt, cfg, 8,
                               compute_dtype=jnp.float32))[0, 5:]
    eos = int(free[2])
    out = np.asarray(generate(params, prompt, cfg, 8, eos_id=eos, pad_id=0,
                              compute_dtype=jnp.float32))[0, 5:]
    stop = np.where(out == eos)[0][0]
    assert (out[stop + 1:] == 0).all(), out
    # the tokens up to (and incl.) eos match the free-running chain
    np.testing.assert_array_equal(out[:stop + 1], free[:stop + 1])
    # a retired row's padding must not disturb a live neighbor: decode the
    # pair (one stops early, one runs free) and check the live row
    pair = jnp.concatenate([prompt, prompt], axis=0)
    both = np.asarray(generate(params, pair, cfg, 8, eos_id=eos, pad_id=0,
                               compute_dtype=jnp.float32))
    np.testing.assert_array_equal(both[0, 5:], out)
    np.testing.assert_array_equal(both[1, 5:], out)


def test_generate_rejects_unsupported():
    cfg = _cfg(model_type="bert", position_embedding_type="learned",
               normalization="layernorm", hidden_act="gelu")
    params, _ = init_causal_lm(jax.random.key(0), _cfg())
    with pytest.raises(NotImplementedError):
        generate(params, jnp.zeros((1, 2), jnp.int32), cfg, 2)


@pytest.mark.distributed
def test_spmd_generate_matches_single_device():
    """Distributed decode (tp2 x dp2 GSPMD, sharded KV cache) reproduces the
    single-device greedy chain exactly."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_generate,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    cfg = _cfg()
    args = CoreArgs(model=cfg.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.vocab_tp = 2
    args.parallel.global_train_batch_size = 4
    hpc = get_hybrid_parallel_config(args, 4)
    mesh = build_mesh(4, 1, devices=jax.devices("cpu")[:4])
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (4, 8)), jnp.int32)
    want = generate(params, prompt, cfg, 10, compute_dtype=jnp.float32)

    gen, pspecs, batch_shd = make_spmd_generate(
        cfg, hpc, mesh, axes, 10, compute_dtype=jnp.float32)
    sp = shard_params(params, pspecs, mesh)
    got = gen(sp, jax.device_put(prompt, batch_shd), jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
