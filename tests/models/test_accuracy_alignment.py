"""Accuracy alignment: multi-step loss trajectories vs a HuggingFace torch
baseline trained from identical weights on identical batches (the reference's
tier-2 method, tests/models/test_model_correctness.py + the
scripts/accuracy_alignment harness)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

pytestmark = [pytest.mark.model, pytest.mark.slow]

CFG = ModelArgs(
    hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
    vocab_size=64, max_position_embeddings=16, seq_length=8,
    make_vocab_size_divisible_by=1)

STEPS = 5
LR = 1e-3


def test_gpt2_loss_trajectory_matches_hf():
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=2, n_head=2,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg)
    params = hf_to_params(hf.state_dict(), CFG)

    train = TrainArgs(lr=LR, weight_decay=0.01, adam_beta1=0.9,
                      adam_beta2=0.95, adam_eps=1e-8, clip_grad=0.0,
                      lr_decay_style="constant", lr_warmup_iters=0)
    tx = make_optimizer(train)
    step = jax.jit(make_train_step(
        make_loss_fn(CFG, compute_dtype=jnp.float32), tx))

    # torch AdamW with decoupled weight decay on >=2D params only, matching
    # our optimizer's decay mask
    decay, no_decay = [], []
    for name, p in hf.named_parameters():
        (decay if p.ndim >= 2 else no_decay).append(p)
    opt = torch.optim.AdamW(
        [{"params": decay, "weight_decay": 0.01},
         {"params": no_decay, "weight_decay": 0.0}],
        lr=LR, betas=(0.9, 0.95), eps=1e-8)

    rng = np.random.RandomState(0)
    opt_state = tx.init(params)
    ours, theirs = [], []
    for it in range(STEPS):
        tokens = rng.randint(0, 64, (4, 9))
        batch = {"tokens": jnp.asarray(tokens[:, :-1]),
                 "labels": jnp.asarray(tokens[:, 1:])}
        params, opt_state, metrics = step(params, opt_state, batch)
        ours.append(float(metrics["loss"]))

        t = torch.tensor(tokens[:, :-1])
        lbl = torch.tensor(tokens[:, 1:])
        out = hf(t)
        loss = torch.nn.functional.cross_entropy(
            out.logits.reshape(-1, 64), lbl.reshape(-1))
        opt.zero_grad()
        loss.backward()
        opt.step()
        theirs.append(float(loss))

    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3,
                               err_msg=f"ours={ours} hf={theirs}")


def test_bert_mlm_loss_trajectory_matches_hf():
    """Tier-2 alignment for the encoder family: 5 AdamW steps of MLM from an
    HF BertForMaskedLM init must track HF exactly (post-norm blocks,
    embedding LN, MLM transform head — runtime/checkpoint.py bert h2g)."""
    torch = pytest.importorskip("torch")
    from transformers import BertConfig, BertForMaskedLM

    cfg = ModelArgs(
        model_type="bert", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, ffn_hidden_size=64, vocab_size=64,
        max_position_embeddings=16, seq_length=8, hidden_act="gelu_exact",
        tie_word_embeddings=True, make_vocab_size_divisible_by=1,
        layernorm_epsilon=1e-12)
    hf_cfg = BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=16, hidden_act="gelu",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = BertForMaskedLM(hf_cfg)
    params = hf_to_params(hf.state_dict(), cfg)

    train = TrainArgs(lr=LR, weight_decay=0.01, adam_beta1=0.9,
                      adam_beta2=0.95, adam_eps=1e-8, clip_grad=0.0,
                      lr_decay_style="constant", lr_warmup_iters=0)
    tx = make_optimizer(train)
    step = jax.jit(make_train_step(
        make_loss_fn(cfg, compute_dtype=jnp.float32), tx))

    decay, no_decay = [], []
    for name, p in hf.named_parameters():
        (decay if p.ndim >= 2 else no_decay).append(p)
    opt = torch.optim.AdamW(
        [{"params": decay, "weight_decay": 0.01},
         {"params": no_decay, "weight_decay": 0.0}],
        lr=LR, betas=(0.9, 0.95), eps=1e-8)

    rng = np.random.RandomState(0)
    opt_state = tx.init(params)
    ours, theirs = [], []
    for it in range(STEPS):
        orig = rng.randint(0, 64, (4, 8))
        tokens = orig.copy()
        mask = rng.rand(4, 8) < 0.2
        tokens[mask] = 63  # mask token
        batch = {"tokens": jnp.asarray(tokens),
                 "labels": jnp.asarray(orig),
                 "loss_mask": jnp.asarray(mask.astype(np.float32))}
        params, opt_state, metrics = step(params, opt_state, batch)
        ours.append(float(metrics["loss"]))

        t = torch.tensor(tokens)
        lbl = torch.tensor(orig.copy())
        lbl[~torch.tensor(mask)] = -100  # HF ignores unmasked positions
        out = hf(t, labels=lbl)
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        theirs.append(float(out.loss))

    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3,
                               err_msg=f"ours={ours} hf={theirs}")
