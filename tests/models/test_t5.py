"""Encoder-decoder (T5 family): cross-attention semantics, seq2seq batches,
and distributed parity (BASELINE milestone 4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss, init_causal_lm
from hetu_galvatron_tpu.models.encdec import encdec_loss, forward_encdec

pytestmark = [pytest.mark.model, pytest.mark.parallel]

T5 = ModelArgs(
    model_type="t5", hidden_size=32, num_hidden_layers=2,
    num_encoder_layers=3, num_attention_heads=2, vocab_size=64,
    max_position_embeddings=32, seq_length=16, hidden_act="gelu",
    normalization="rmsnorm", position_embedding_type="rope",
    tie_word_embeddings=True, add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1)


def _batch(bsz=4, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "enc_tokens": jnp.asarray(rng.randint(0, 64, (bsz, 8))),
        "tokens": jnp.asarray(rng.randint(0, 64, (bsz, 6))),
        "labels": jnp.asarray(rng.randint(0, 64, (bsz, 6))),
    }


def test_init_structure_and_loss():
    params, axes = init_causal_lm(jax.random.key(0), T5)
    assert len(params["enc_layers"]) == 3
    assert len(params["layers"]) == 2
    assert "cross" in params["layers"][0]
    assert axes["layers"][0]["cross"]["wq"] == ("embed", "qkv")
    loss = causal_lm_loss(params, _batch(), T5, compute_dtype=jnp.float32)
    assert np.isfinite(float(loss))
    grads = jax.grad(lambda p: encdec_loss(p, _batch(), T5,
                                           compute_dtype=jnp.float32))(params)
    assert all(np.all(np.isfinite(g)) for g in jax.tree.leaves(grads))


def test_decoder_causal_encoder_bidirectional():
    params, _ = init_causal_lm(jax.random.key(0), T5)
    b = _batch(bsz=1)
    base = forward_encdec(params, b["enc_tokens"], b["tokens"], T5,
                          compute_dtype=jnp.float32)
    # future decoder token must not change earlier decoder logits
    d2 = b["tokens"].at[0, -1].set((b["tokens"][0, -1] + 1) % 64)
    out2 = forward_encdec(params, b["enc_tokens"], d2, T5,
                          compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(base[:, :-1]),
                               np.asarray(out2[:, :-1]), atol=1e-6)
    # but any encoder token change reaches every decoder position
    e2 = b["enc_tokens"].at[0, -1].set((b["enc_tokens"][0, -1] + 1) % 64)
    out3 = forward_encdec(params, e2, b["tokens"], T5,
                          compute_dtype=jnp.float32)
    assert not np.allclose(np.asarray(base[:, 0]), np.asarray(out3[:, 0]))


def test_seq2seq_batches():
    from hetu_galvatron_tpu.runtime.dataloader import get_data_iterator

    args = CoreArgs(model=T5.model_dump())
    it = get_data_iterator(args, global_batch_size=4)
    b = next(it)
    assert set(b) == {"enc_tokens", "tokens", "labels", "loss_mask"}
    assert b["enc_tokens"].shape == (4, 8)
    assert b["tokens"].shape[1] == b["labels"].shape[1]


@pytest.mark.slow
def test_t5_tp2_matches_single_device(cpu_devices):
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step, shard_params)
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
    import optax

    train = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                      lr_decay_style="constant", lr_warmup_iters=0)
    params, axes = init_causal_lm(jax.random.key(0), T5)
    batch = _batch(bsz=8)

    tx = make_optimizer(train)
    loss_fn = lambda p: encdec_loss(p, batch, T5, compute_dtype=jnp.float32)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(ref_grads, tx.init(params), params)
    ref_params = optax.apply_updates(params, upd)

    args = CoreArgs(model=T5.model_dump(), train=train.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.vocab_tp = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    mesh = build_mesh(8, 1, devices=cpu_devices)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        T5, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False)
    assert "enc_layers" in pspecs
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    new_p, _, metrics = step(sp, opt, jax.device_put(batch, batch_shd))
    assert abs(float(metrics["loss"]) - float(ref_loss)) < 2e-5
    for (pa, a), (_, b2) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b2), rtol=5e-4, atol=3e-4,
            err_msg=jax.tree_util.keystr(pa))


def test_t5_train_dist_cli(capsys):
    import os
    from hetu_galvatron_tpu.cli.train_dist import main

    ZOO = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    rc = main([os.path.join(ZOO, "t5-3b.yaml"),
               "model.hidden_size=32", "model.num_hidden_layers=2",
               "model.num_encoder_layers=2", "model.num_attention_heads=2",
               "model.vocab_size=64", "model.seq_length=16",
               "model.max_position_embeddings=16",
               "model.make_vocab_size_divisible_by=1",
               "model.ffn_hidden_size=64",
               "train.train_iters=2", "parallel.mixed_precision=fp32",
               "parallel.global_train_batch_size=8",
               "parallel.global_tp_deg=2"])
    assert rc == 0
    assert "training done" in capsys.readouterr().out


def test_cross_attention_biases_honored():
    """add_qkv_bias/add_bias_linear apply to cross-attention too."""
    cfg = T5.model_copy(update={"add_qkv_bias": True,
                                "add_bias_linear": True})
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    cross = params["layers"][0]["cross"]
    assert "bq" in cross and "bkv" in cross and "bo" in cross
    loss = causal_lm_loss(params, _batch(), cfg, compute_dtype=jnp.float32)
    assert np.isfinite(float(loss))


def test_num_encoder_layers_zero_is_zero():
    cfg = T5.model_copy(update={"num_encoder_layers": 0})
    params, _ = init_causal_lm(jax.random.key(0), cfg)
    assert len(params["enc_layers"]) == 0


# ---------------------------------------------------------------------------
# pipeline parallelism over the combined enc+dec stack (BASELINE milestone 4)
# ---------------------------------------------------------------------------

TRAIN = TrainArgs(lr=1e-2, clip_grad=1.0, weight_decay=0.01,
                  lr_decay_style="constant", lr_warmup_iters=0)


def _ref_step(cfg, params, batch):
    import optax

    from hetu_galvatron_tpu.models.encdec import encdec_loss
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    tx = make_optimizer(TRAIN)
    loss_fn = lambda p: encdec_loss(p, batch, cfg, compute_dtype=jnp.float32)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd, _ = tx.update(grads, tx.init(params), params)
    return float(loss), optax.apply_updates(params, upd)


def _t5_pipeline_step(cfg, params, axes, batch, cpu_devices, **pkw):
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    args = CoreArgs(model=cfg.model_dump(), train=TRAIN.model_dump())
    for k, v in pkw.items():
        setattr(args.parallel, k, v)
    hpc = get_hybrid_parallel_config(args, 8)
    assert hpc.num_encoder_layers == 3
    assert sum(hpc.pp_division) == 5  # combined enc(3) + dec(2)
    eng = PipelineEngine(cfg, hpc, args.train, devices=cpu_devices,
                         compute_dtype=jnp.float32)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    new_sp, _, metrics = eng.train_step(sp, so, batch)
    return metrics, eng.merge_params(new_sp)


T5_PP_CASES = [
    dict(pp_deg=2, pipeline_type="gpipe", chunks=2),
    dict(pp_deg=2, pipeline_type="pipedream_flush", chunks=4),
    # pp=4 over 5 combined layers -> [1,1,1,2]: encoder-only stages with the
    # decoder-stream passthrough, and the enc->dec boundary mid-pipeline
    dict(pp_deg=4, pipeline_type="pipedream_flush", chunks=4),
    dict(pp_deg=2, pipeline_type="gpipe", chunks=2, global_tp_deg=2),
]


@pytest.mark.distributed
@pytest.mark.parametrize(
    "pkw", T5_PP_CASES,
    ids=lambda d: ",".join(f"{k}={v}" for k, v in d.items()))
@pytest.mark.slow
def test_t5_pipeline_matches_single_device(pkw, cpu_devices):
    """pp>1 over the combined enc+dec stack must reproduce the single-device
    step (the reference pipelines any arch via PipeSequential,
    pipeline.py:1592; this engine stage-slices the (a, b) activation pair)."""
    params, axes = init_causal_lm(jax.random.key(0), T5)
    rng = np.random.RandomState(0)
    batch = {
        "enc_tokens": rng.randint(0, 64, (16, 8)),
        "tokens": rng.randint(0, 64, (16, 6)),
        "labels": rng.randint(0, 64, (16, 6)),
    }
    jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
    ref_loss, ref_params = _ref_step(T5, params, jbatch)
    pkw = dict(pkw, global_train_batch_size=16)
    metrics, new_params = _t5_pipeline_step(T5, params, axes, batch,
                                            cpu_devices, **pkw)
    assert abs(metrics["loss"] - ref_loss) < 2e-5, \
        f"loss {metrics['loss']} != {ref_loss}"
    # tied embedding: enc-token AND dec-token wte grads + transposed head
    # copy must all have reconciled across stages
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


@pytest.mark.distributed
@pytest.mark.slow
def test_t5_heterogeneous_combined_plan(cpu_devices, tmp_path):
    """A searched-style JSON plan over the COMBINED stack: per-layer encoder
    strategies differ from decoder strategies (tp2 encoder, dp zero3 decoder)
    and pp_division splits mid-encoder."""
    import json

    plan = {
        "pp_deg": 2,
        "tp_sizes_enc": "2,2,1,1,1",    # enc 3 layers then dec 2 layers
        "tp_consecutive_flags": "1,1,1,1,1",
        "dp_types_enc": "0,0,0,1,1",
        "use_sp": "0,0,0,0,0",
        "cp_sizes_enc": "1,1,1,1,1",
        "checkpoint": "0,1,0,0,1",
        "global_bsz": 8,
        "chunks": 2,
        "pp_division": "2,3",
        "pipeline_type": "pipedream_flush",
        "default_dp_type": "ddp",
        "vtp": 1, "vsp": 0, "vcp": 1, "embed_sdp": 0,
        "num_encoder_layers": 3,
    }
    path = tmp_path / "t5_plan.json"
    path.write_text(json.dumps(plan))
    params, axes = init_causal_lm(jax.random.key(1), T5)
    rng = np.random.RandomState(1)
    batch = {
        "enc_tokens": rng.randint(0, 64, (8, 8)),
        "tokens": rng.randint(0, 64, (8, 6)),
        "labels": rng.randint(0, 64, (8, 6)),
    }
    jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
    ref_loss, ref_params = _ref_step(T5, params, jbatch)
    metrics, new_params = _t5_pipeline_step(
        T5, params, axes, batch, cpu_devices,
        galvatron_config_path=str(path))
    assert abs(metrics["loss"] - ref_loss) < 2e-5
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


def test_t5_flash_attention_overrides():
    """Flash kernels (interpret mode) in BOTH t5 stacks: encoder non-causal,
    decoder causal self-attention + non-causal cross-attention — must match
    the XLA core. Equal enc/dec lengths so cross-attention tiles."""
    from functools import partial as fpartial

    from hetu_galvatron_tpu.ops.pallas.flash_attention import flash_sdpa

    cfg = T5.model_copy(update={"num_encoder_layers": 2})
    params, _ = init_causal_lm(jax.random.key(2), cfg)
    rng = np.random.RandomState(2)
    batch = {
        "enc_tokens": jnp.asarray(rng.randint(0, 64, (2, 16))),
        "tokens": jnp.asarray(rng.randint(0, 64, (2, 16))),
        "labels": jnp.asarray(rng.randint(0, 64, (2, 16))),
    }
    base = causal_lm_loss(params, batch, cfg, compute_dtype=jnp.float32)
    flash = fpartial(flash_sdpa, interpret=True)
    over = {i: {"sdpa_fn": flash} for i in range(2)}
    out = causal_lm_loss(params, batch, cfg, compute_dtype=jnp.float32,
                         layer_overrides=over, enc_layer_overrides=over)
    np.testing.assert_allclose(float(out), float(base), rtol=2e-5, atol=2e-5)


@pytest.mark.distributed
@pytest.mark.slow
def test_t5_ring_cp_matches_xla(cpu_devices):
    """cp=2 on every combined layer: encoder runs non-causal ring, decoder
    self-attention runs causal ring, cross-attention falls back to the XLA
    core (unequal q/kv lengths) — loss must match the single-device step."""
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step, shard_params)
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config)
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    from hetu_galvatron_tpu.models.encdec import encdec_loss

    cfg = T5.model_copy(update={"num_encoder_layers": 2})
    params, axes = init_causal_lm(jax.random.key(3), cfg)
    rng = np.random.RandomState(3)
    batch = {
        "enc_tokens": jnp.asarray(rng.randint(0, 64, (8, 8))),
        "tokens": jnp.asarray(rng.randint(0, 64, (8, 8))),
        "labels": jnp.asarray(rng.randint(0, 64, (8, 8))),
    }
    ref_loss = float(encdec_loss(params, batch, cfg,
                                 compute_dtype=jnp.float32))
    args = CoreArgs(model=cfg.model_dump(), train=TRAIN.model_dump())
    args.parallel.global_cp_deg = 2
    args.parallel.global_train_batch_size = 8
    hpc = get_hybrid_parallel_config(args, 8)
    assert hpc.num_encoder_layers == 2
    mesh = build_mesh(8, 1, devices=cpu_devices)
    tx = make_optimizer(TRAIN)
    step, pspecs, ospecs, batch_shd = make_spmd_train_step(
        cfg, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False)
    sp = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init, out_shardings=jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)))(sp)
    _, _, metrics = step(sp, opt, jax.device_put(batch, batch_shd))
    assert abs(float(metrics["loss"]) - ref_loss) < 2e-5


@pytest.mark.distributed
@pytest.mark.slow
def test_t5_interleaved_virtual_stages(cpu_devices):
    """vpp=2 x pp=2 over the combined enc+dec stack: 4 chunks round-robin
    on 2 device groups, enc->dec boundary inside a chunk."""
    params, axes = init_causal_lm(jax.random.key(0), T5)
    rng = np.random.RandomState(5)
    batch = {
        "enc_tokens": rng.randint(0, 64, (16, 8)),
        "tokens": rng.randint(0, 64, (16, 6)),
        "labels": rng.randint(0, 64, (16, 6)),
    }
    jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
    ref_loss, ref_params = _ref_step(T5, params, jbatch)
    metrics, new_params = _t5_pipeline_step(
        T5, params, axes, batch, cpu_devices,
        pp_deg=2, virtual_pp_deg=2, chunks=4,
        pipeline_type="pipedream_flush", global_train_batch_size=16)
    assert abs(metrics["loss"] - ref_loss) < 2e-5
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref_params),
            jax.tree_util.tree_leaves_with_path(new_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=3e-4,
            err_msg=f"param {jax.tree_util.keystr(pa)}")


# ---------------------------------------------------------------------------
# encoder-decoder decode (encoder once + cached cross k/v + cached causal
# self-attention). This runtime is position-scheme agnostic (no T5 relative
# bias — encdec.py docstring), so the decode contract is incremental ==
# full teacher-forced forward, not HF bit-parity.
# ---------------------------------------------------------------------------


def test_t5_greedy_decode_matches_teacher_forced_forward():
    """Greedy generate_encdec token t+1 must equal the argmax of the full
    (uncached) forward_encdec over the prefix — the KV/cross caches change
    nothing."""
    from hetu_galvatron_tpu.models.generate import generate_encdec

    params, _ = init_causal_lm(jax.random.key(7), T5)
    rng = np.random.RandomState(1)
    enc = jnp.asarray(rng.randint(0, 64, (2, 8)))
    n_new = 6
    out = jax.jit(lambda p, t: generate_encdec(
        p, t, T5, n_new, compute_dtype=jnp.float32))(params, enc)
    assert out.shape == (2, 1 + n_new)
    assert np.all(np.asarray(out[:, 0]) == 0)  # decoder start token
    for t in range(n_new):
        logits = forward_encdec(params, enc, out[:, :t + 1], T5,
                                compute_dtype=jnp.float32)
        nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
        np.testing.assert_array_equal(np.asarray(out[:, t + 1]), nxt,
                                      err_msg=f"step {t}")


def test_t5_decode_eos_masking_and_sampling_shapes():
    from hetu_galvatron_tpu.models.generate import generate_encdec

    params, _ = init_causal_lm(jax.random.key(3), T5)
    enc = jnp.asarray(np.random.RandomState(2).randint(0, 64, (3, 8)))
    out = generate_encdec(params, enc, T5, 5, temperature=0.7, top_k=10,
                          eos_id=9, key=jax.random.key(0),
                          compute_dtype=jnp.float32)
    assert out.shape == (3, 6)
    arr = np.asarray(out)
    # once eos appears, everything after stays eos
    for row in arr:
        hits = np.where(row[1:] == 9)[0]
        if len(hits):
            assert np.all(row[1 + hits[0]:] == 9)


def test_t5_generate_cli_smoke(capsys):
    """CLI routes t5 configs through generate_encdec (random weights)."""
    import os

    from hetu_galvatron_tpu.cli.generate import main as gen_main

    zoo = os.path.join(os.path.dirname(__file__), "..", "..",
                       "hetu_galvatron_tpu", "models", "configs")
    rc = gen_main([os.path.join(zoo, "t5-3b.yaml"),
                   "model.hidden_size=32", "model.num_hidden_layers=2",
                   "model.num_encoder_layers=2",
                   "model.num_attention_heads=2", "model.vocab_size=300",
                   "model.seq_length=16", "model.max_position_embeddings=32",
                   "model.make_vocab_size_divisible_by=1",
                   "prompt=translate this", "max_new_tokens=4"])
    assert rc == 0
    assert capsys.readouterr().out.strip() != ""


@pytest.mark.distributed
def test_t5_spmd_generate_matches_single_device(cpu_devices):
    """make_spmd_generate routes t5 configs through generate_encdec under
    the plan's GSPMD shardings; tp2 x dp2 greedy decode == single-device."""
    from hetu_galvatron_tpu.models.generate import generate_encdec
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_generate,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    params, axes = init_causal_lm(jax.random.key(2), T5)
    args = CoreArgs(model=T5.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.vocab_tp = 2
    args.parallel.global_train_batch_size = 4
    mesh = build_mesh(4, 1, devices=cpu_devices[:4])
    hpc = get_hybrid_parallel_config(args, 4)
    enc = jnp.asarray(np.random.RandomState(8).randint(0, 64, (4, 8)))
    # fp32 on both sides: bf16 + resharded reduction order could flip an
    # argmax tie and cascade through the greedy decode (same convention as
    # the causal spmd-generate parity test)
    ref = generate_encdec(params, enc, T5, 5, compute_dtype=jnp.float32)
    fn, pspecs, batch_shd = make_spmd_generate(
        T5, hpc, mesh, axes, 5, compute_dtype=jnp.float32)
    sp = shard_params(params, pspecs, mesh)
    out = fn(sp, jax.device_put(enc, batch_shd), jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_t5_cross_attention_dropout_with_capable_kernel():
    """Cross-attention dropout routes through dropout-capable kernels
    (flash) instead of refusing; incapable kernels still refuse."""
    from hetu_galvatron_tpu.models.encdec import apply_cross_attention
    from hetu_galvatron_tpu.models.modules import xla_sdpa

    cfg = T5.model_copy(update={"attention_dropout": 0.2})
    from hetu_galvatron_tpu.models.encdec import init_cross_attention

    p, _ = init_cross_attention(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 6, 32), jnp.float32)
    mem = jax.random.normal(jax.random.key(2), (2, 8, 32), jnp.float32)

    def capable(q, k, v, **kw):
        kw.pop("dropout_rate", None)
        kw.pop("dropout_rng", None)
        return xla_sdpa(q, k, v, **kw)

    capable.supports_dropout = True
    out = apply_cross_attention(p, x, mem, cfg, sdpa_fn=capable,
                                compute_dtype=jnp.float32,
                                dropout_rng=jax.random.key(3))
    assert np.all(np.isfinite(np.asarray(out)))

    def incapable(q, k, v, **kw):
        return xla_sdpa(q, k, v, **kw)

    with pytest.raises(NotImplementedError, match="dropout-capable"):
        apply_cross_attention(p, x, mem, cfg, sdpa_fn=incapable,
                              compute_dtype=jnp.float32,
                              dropout_rng=jax.random.key(3))
